"""Reliable device synchronization.

On the tunnelled TPU backend in this image, ``jax.block_until_ready``
can return before execution or transfer actually completes (measured:
sub-millisecond "completion" of second-long programs). The only
trustworthy barrier is a host fetch of a value that *depends* on the
arrays in question. ``hard_sync`` builds that dependency explicitly: a
trivial jitted reduction consumes one element of every leaf and the
scalar result is fetched. Used where timing scope matters (the bench
methodology keeps the one-time dataset upload outside the timed
window, BASELINE.md) — correctness paths never rely on
block_until_ready ordering.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def _probe(leaves):
    total = jnp.float32(0)
    for x in leaves:
        first = jax.lax.slice(x.reshape(-1), (0,), (1,))
        total = total + jnp.sum(first.astype(jnp.float32))
    return total


_probe_jit = jax.jit(_probe)


def hard_sync(tree) -> None:
    """Block until every array leaf of ``tree`` is resident and its
    producing computation/transfer has finished."""
    leaves = [x for x in jax.tree.leaves(tree)
              if isinstance(x, jax.Array)]
    if not leaves:
        return
    float(np.asarray(_probe_jit(leaves)))
