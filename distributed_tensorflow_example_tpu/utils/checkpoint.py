"""Checkpoint / resume.

Reference parity: the reference configures **no** checkpointing — its
``Supervisor`` is built without a logdir so the default saver is
inactive, and no ``tf.train.Saver`` exists (/root/reference/example.py:
132-134; SURVEY.md §5). Its only restart resilience is the parameters
surviving on the parameter server across worker restarts.

SPMD removes that implicit resilience (a lost process kills the step),
so this module supplies the explicit recovery story (SURVEY.md §5):
the chief saves the full train-state pytree + step + epoch every
``--checkpoint_every`` steps and at exit; ``--resume`` restores and
continues. Format: a single ``.npz`` holding each leaf under its
tree-path name — readable anywhere numpy is.
"""

from __future__ import annotations

import os
import re
from typing import Any, Tuple

import jax
import numpy as np


def _flatten(tree: Any):
    leaves_with_paths = jax.tree_util.tree_flatten_with_path(tree)[0]
    out = {}
    for path, leaf in leaves_with_paths:
        key = "/".join(str(getattr(p, "key", getattr(p, "idx", p))) for p in path)
        out[key] = np.asarray(leaf)
    return out


def save_checkpoint(ckpt_dir: str, state: Any, step: int, epoch: int,
                    extras: dict | None = None) -> str:
    """Atomic save: write tmp, rename. Returns the checkpoint path.
    ``extras``: scalar driver-side counters (e.g. the early-stopping
    best/patience state) stored as ``__x_<key>__`` entries so --resume
    replays exactly what an uninterrupted run would do."""
    os.makedirs(ckpt_dir, exist_ok=True)
    path = os.path.join(ckpt_dir, f"ckpt-{step:08d}.npz")
    tmp = path + ".tmp.npz"
    payload = _flatten(state)
    payload["__step__"] = np.asarray(step, np.int64)
    payload["__epoch__"] = np.asarray(epoch, np.int64)
    for k, v in (extras or {}).items():
        payload[f"__x_{k}__"] = np.asarray(v)
    with open(tmp, "wb") as f:
        np.savez(f, **payload)
    os.replace(tmp, path)
    return path


def load_extras(path: str) -> dict:
    """The ``extras`` scalars a checkpoint carries (empty for
    checkpoints written before the field existed)."""
    out = {}
    with np.load(path) as z:
        for k in z.files:
            m = re.fullmatch(r"__x_(.+)__", k)
            if m:
                out[m.group(1)] = z[k].item()
    return out


def _list_checkpoints(ckpt_dir: str) -> list[tuple[int, str]]:
    """(step, filename) for every completed checkpoint, step-sorted —
    the one filename-format scan prune and resume share (atomic-rename
    temp files never match)."""
    if not os.path.isdir(ckpt_dir):
        return []
    found = []
    for name in os.listdir(ckpt_dir):
        m = re.fullmatch(r"ckpt-(\d+)\.npz", name)
        if m:
            found.append((int(m.group(1)), name))
    return sorted(found)


def prune_checkpoints(ckpt_dir: str, keep: int) -> list[str]:
    """Delete all but the ``keep`` highest-step checkpoints (0 = keep
    everything). Returns the deleted paths."""
    if keep <= 0:
        return []
    deleted = []
    for _, name in _list_checkpoints(ckpt_dir)[:-keep]:
        path = os.path.join(ckpt_dir, name)
        os.remove(path)
        deleted.append(path)
    return deleted


def latest_checkpoint(ckpt_dir: str) -> str | None:
    found = _list_checkpoints(ckpt_dir)
    return os.path.join(ckpt_dir, found[-1][1]) if found else None


def restore_checkpoint(path: str, state_template: Any) -> Tuple[Any, int, int]:
    """Restore into the template's tree structure; returns (state, step, epoch).

    Leaves are matched by tree path, so the checkpoint survives
    refactors that keep param names stable (W1/b1/..., SURVEY.md §5).
    """
    with np.load(path) as z:
        data = {k: z[k] for k in z.files}
    step = int(data.pop("__step__"))
    epoch = int(data.pop("__epoch__"))
    leaves_with_paths, treedef = jax.tree_util.tree_flatten_with_path(state_template)
    new_leaves = []
    for path_, leaf in leaves_with_paths:
        key = "/".join(str(getattr(p, "key", getattr(p, "idx", p))) for p in path_)
        if key not in data:
            raise KeyError(f"checkpoint {path} missing leaf {key!r}")
        arr = data[key]
        want = tuple(np.shape(leaf))
        if arr.shape != want and arr.size == np.size(leaf) \
                and key.endswith("qkv"):
            # migration: transformer qkv leaves changed layout from
            # (d, 3d)/(3d,) to (d, 3, d)/(3, d) when Megatron TP
            # landed; the flat row-major order is identical (q|k|v
            # column blocks), so old checkpoints restore by reshape
            arr = arr.reshape(want)
        if arr.shape != want:
            raise ValueError(
                f"checkpoint leaf {key!r} shape {arr.shape} != expected {want}"
            )
        new_leaves.append(arr.astype(np.asarray(leaf).dtype))
    state = jax.tree_util.tree_unflatten(treedef, new_leaves)
    return state, step, epoch
