"""Checkpoint / resume.

Reference parity: the reference configures **no** checkpointing — its
``Supervisor`` is built without a logdir so the default saver is
inactive, and no ``tf.train.Saver`` exists (/root/reference/example.py:
132-134; SURVEY.md §5). Its only restart resilience is the parameters
surviving on the parameter server across worker restarts.

SPMD removes that implicit resilience (a lost process kills the step),
so this module supplies the explicit recovery story (SURVEY.md §5):
the chief saves the full train-state pytree + step + epoch every
``--checkpoint_every`` steps and at exit; ``--resume`` restores and
continues. Format: a single ``.npz`` holding each leaf under its
tree-path name — readable anywhere numpy is.

Two on-disk formats:

- **Portable single file** ``ckpt-N.npz`` (default): the full
  unsharded tree, written by the chief. In multi-process runs this
  costs a ``process_allgather`` of the whole state onto every host —
  fine at MNIST scale, the wrong shape once params outgrow a host.
- **Sharded directory** ``ckpt-N.shards/`` (``--sharded_checkpoints``):
  every process writes ONLY its addressable replica-0 device shards to
  ``proc-NNNNN.npz`` (each entry = the shard's data plus its global
  index), the chief writes ``manifest.json`` naming the expected shard
  files — no cross-process gather anywhere. A checkpoint is complete
  iff the manifest AND every file it names exist (all writes are
  atomic tmp+rename), so a SIGKILL mid-save leaves an ignorable
  partial directory, never a corrupt resumable one. Restore
  reassembles full leaves host-side from the shard indices — which
  makes the on-disk format topology-agnostic: a run saved at one
  (dp, mp, ...) resumes at another, because reassembly recovers the
  logical arrays and placement re-shards them. With
  ``--async_checkpoints`` the device->host fetches stay synchronous
  but the file writes move to a background thread
  (``wait_for_pending_saves`` joins it).
"""

from __future__ import annotations

import json
import os
import re
import shutil
import threading
from typing import Any, Tuple

import jax
import numpy as np

# ONE bf16-family bit-container codec for every checkpoint format:
# shared with the resilience snapshot store (resilience/codec.py has
# the rationale — ml_dtypes leaves register as numpy kind 'V' and
# np.savez cannot round-trip them)
from ..resilience.codec import bit_container_dtype as _bit_dtype
from ..resilience.codec import decode_array as _decode_leaf
from ..resilience.codec import encode_array as _encode_leaf


def _tree_key(path) -> str:
    """The one tree-path -> key-string rule every reader/writer shares."""
    return "/".join(str(getattr(p, "key", getattr(p, "idx", p)))
                    for p in path)


def _flatten_with_keys(tree: Any):
    return [(_tree_key(path), leaf)
            for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]]


def _flatten(tree: Any):
    return {k: np.asarray(v) for k, v in _flatten_with_keys(tree)}


def save_checkpoint(ckpt_dir: str, state: Any, step: int, epoch: int,
                    extras: dict | None = None) -> str:
    """Atomic save: write tmp, rename. Returns the checkpoint path.
    ``extras``: scalar driver-side counters (e.g. the early-stopping
    best/patience state) stored as ``__x_<key>__`` entries so --resume
    replays exactly what an uninterrupted run would do."""
    os.makedirs(ckpt_dir, exist_ok=True)
    path = os.path.join(ckpt_dir, f"ckpt-{step:08d}.npz")
    tmp = path + ".tmp.npz"
    payload = {}
    for k, v in _flatten(state).items():
        enc, name = _encode_leaf(v)
        payload[k] = enc
        if name:   # bf16-family leaf: record the dtype to view back
            payload[f"__dt_{k}__"] = np.asarray(name)
    payload["__step__"] = np.asarray(step, np.int64)
    payload["__epoch__"] = np.asarray(epoch, np.int64)
    for k, v in (extras or {}).items():
        payload[f"__x_{k}__"] = np.asarray(v)
    with open(tmp, "wb") as f:
        np.savez(f, **payload)
    os.replace(tmp, path)
    return path


def load_extras(path: str) -> dict:
    """The ``extras`` scalars a checkpoint carries (empty for
    checkpoints written before the field existed). Works on both
    formats."""
    if os.path.isdir(path):
        with open(os.path.join(path, "manifest.json")) as f:
            return dict(json.load(f).get("extras", {}))
    out = {}
    with np.load(path) as z:
        for k in z.files:
            m = re.fullmatch(r"__x_(.+)__", k)
            if m:
                out[m.group(1)] = z[k].item()
    return out


def _sharded_complete(path: str) -> bool:
    """A sharded checkpoint dir is complete iff its manifest exists and
    names only files that exist."""
    man = os.path.join(path, "manifest.json")
    if not os.path.isfile(man):
        return False
    try:
        with open(man) as f:
            manifest = json.load(f)
        return all(os.path.isfile(os.path.join(path, name))
                   for name in manifest["files"])
    except (OSError, ValueError, KeyError):
        return False


def _list_checkpoints(ckpt_dir: str) -> list[tuple[int, str]]:
    """(step, filename) for every completed checkpoint (single-file or
    complete sharded dir), step-sorted — the one filename-format scan
    prune and resume share (atomic-rename temp files never match;
    incomplete sharded dirs — killed mid-save — never list)."""
    if not os.path.isdir(ckpt_dir):
        return []
    found = []
    for name in os.listdir(ckpt_dir):
        m = re.fullmatch(r"ckpt-(\d+)\.npz", name)
        if m:
            found.append((int(m.group(1)), name))
            continue
        m = re.fullmatch(r"ckpt-(\d+)\.shards", name)
        if m and _sharded_complete(os.path.join(ckpt_dir, name)):
            found.append((int(m.group(1)), name))
    return sorted(found)


def prune_checkpoints(ckpt_dir: str, keep: int) -> list[str]:
    """Delete all but the ``keep`` highest-step COMPLETE checkpoints
    (0 = keep everything). Returns the deleted paths.

    An in-flight sharded checkpoint (its peers' shard files still
    landing) is invisible to the scan and deliberately does NOT count
    toward ``keep``: deleting a durable checkpoint before its
    replacement is durable would silently drop the configured
    redundancy, so the disk transiently holds keep+1 entries until the
    next save's prune — over-retention is the safe direction."""
    if keep <= 0:
        return []
    deleted = []
    for _, name in _list_checkpoints(ckpt_dir)[:-keep]:
        path = os.path.join(ckpt_dir, name)
        if os.path.isdir(path):
            shutil.rmtree(path, ignore_errors=True)
        else:
            os.remove(path)
        deleted.append(path)
    return deleted


def latest_checkpoint(ckpt_dir: str) -> str | None:
    found = _list_checkpoints(ckpt_dir)
    return os.path.join(ckpt_dir, found[-1][1]) if found else None


# ---------------------------------------------------------------------------
# Sharded format (see module docstring)
# ---------------------------------------------------------------------------

_PENDING_SAVES: list[threading.Thread] = []


def wait_for_pending_saves() -> None:
    """Join any background checkpoint writers (--async_checkpoints).
    Called before starting the next save and at run exit, so at most
    one write is ever in flight and the process never exits with a
    half-written shard file pending. A writer that FAILED re-raises
    here — a checkpoint that silently failed to write must not look
    like a durable one."""
    while _PENDING_SAVES:
        t = _PENDING_SAVES.pop()
        t.join()
        err = getattr(t, "error", None)
        if err is not None:
            raise RuntimeError(
                f"background checkpoint write failed: {err!r}") from err


def _local_shards(leaf):
    """[(index_bounds, host_array)] for this process's replica-0 device
    shards of ``leaf`` (host/numpy leaves: one full shard on the chief
    only — they are replicated by construction). index_bounds is an
    int array [[start, stop] per dim] resolved against the global
    shape; the device->host copy happens HERE (synchronously), so an
    async writer thread touches only host memory."""
    if isinstance(leaf, jax.Array) and hasattr(leaf, "addressable_shards"):
        out = []
        for sh in leaf.addressable_shards:
            if sh.replica_id != 0:
                continue  # another device holds the identical copy
            bounds = np.asarray(
                [[0 if sl.start is None else sl.start,
                  dim if sl.stop is None else sl.stop]
                 for sl, dim in zip(sh.index, leaf.shape)], np.int64)
            if bounds.size == 0:  # scalar leaf
                bounds = np.zeros((0, 2), np.int64)
            out.append((bounds, np.asarray(sh.data)))
        return out
    if jax.process_index() != 0:
        return []
    a = np.asarray(leaf)
    bounds = np.asarray([[0, d] for d in a.shape], np.int64)
    if bounds.size == 0:
        bounds = np.zeros((0, 2), np.int64)
    return [(bounds, a)]


def save_checkpoint_sharded(ckpt_dir: str, state: Any, step: int,
                            epoch: int, extras: dict | None = None,
                            async_: bool = False,
                            on_complete=None) -> str:
    """Every process calls this; no cross-process collective runs.
    Each process writes its shard file atomically; the chief also
    writes the manifest (naming every expected shard file, so the
    checkpoint only becomes visible to ``latest_checkpoint`` once all
    processes have finished). ``on_complete`` (e.g. retention pruning)
    runs after this process's write lands — in the writer thread under
    ``async_``, so pruning never counts a checkpoint that is still
    invisible. Returns the checkpoint directory.

    Multi-process runs REQUIRE ``ckpt_dir`` on a filesystem shared by
    every process (NFS/GCS-fuse/...): there is deliberately no
    cross-process barrier, so the chief's manifest can land before
    peer shard files — harmless on a shared FS (`_sharded_complete`
    keeps the checkpoint invisible until every named file exists), but
    on per-host local disks the format would yield permanently
    incomplete checkpoints. Retention: the possibly-still-landing
    checkpoint is deliberately NOT counted by ``prune_checkpoints``
    (see its docstring) — the disk transiently holds keep+1 entries
    rather than ever deleting a durable checkpoint early."""
    wait_for_pending_saves()
    path = os.path.join(ckpt_dir, f"ckpt-{step:08d}.shards")
    os.makedirs(path, exist_ok=True)
    proc = jax.process_index()
    nprocs = jax.process_count()

    import jax.numpy as jnp

    payload = {}
    leaves = _flatten_with_keys(state)
    shapes = {}
    for key, leaf in leaves:
        shapes[key] = (list(np.shape(leaf)),
                       np.dtype(jnp.result_type(leaf)).name)
        for j, (bounds, data) in enumerate(_local_shards(leaf)):
            # bf16-family shards bit-encode (savez round-trip); the
            # manifest's recorded leaf dtype drives the view-back
            payload[f"{key}§{j}"], _ = _encode_leaf(data)
            payload[f"{key}§{j}§idx"] = bounds

    fname = f"proc-{proc:05d}.npz"

    def write():
        tmp = os.path.join(path, fname + f".tmp{os.getpid()}.npz")
        with open(tmp, "wb") as f:
            np.savez(f, **payload)
        os.replace(tmp, os.path.join(path, fname))
        if proc == 0:
            manifest = {
                "step": int(step), "epoch": int(epoch),
                "extras": {k: float(v) for k, v in (extras or {}).items()},
                "nprocs": int(nprocs),
                "files": [f"proc-{i:05d}.npz" for i in range(nprocs)],
                "leaves": {k: {"shape": s, "dtype": d}
                           for k, (s, d) in shapes.items()},
            }
            mtmp = os.path.join(path, f"manifest.tmp{os.getpid()}.json")
            with open(mtmp, "w") as f:
                json.dump(manifest, f)
            os.replace(mtmp, os.path.join(path, "manifest.json"))
        if on_complete is not None:
            on_complete()

    if async_:
        def guarded():
            try:
                write()
            except BaseException as e:  # surfaced by wait_for_pending
                t.error = e

        t = threading.Thread(target=guarded, daemon=False,
                             name=f"ckpt-writer-{step}")
        t.error = None
        t.start()
        _PENDING_SAVES.append(t)
    else:
        write()
    return path


def restore_sharded_arrays(path: str) -> Tuple[dict, int, int]:
    """Reassemble a sharded checkpoint into full host arrays:
    ({tree-path key: np.ndarray}, step, epoch). Topology-agnostic —
    shard indices recorded at save time place each piece regardless of
    how many processes/devices wrote them."""
    with open(os.path.join(path, "manifest.json")) as f:
        manifest = json.load(f)
    data = {k: np.zeros(tuple(v["shape"]), np.dtype(v["dtype"]))
            for k, v in manifest["leaves"].items()}
    # positional coverage from the shard bounds (no per-element mask —
    # a multi-GB state must not pay +25% host memory to restore):
    # shards must tile the leaf exactly, i.e. pairwise-disjoint boxes
    # whose sizes sum to the leaf size — overlapping shards from a
    # hypothetical buggy writer can then never mask a gap. Near-linear
    # for the dim-0-sharded layouts the writers emit (the dim-0 sweep
    # prunes the pair loop); quadratic only in degenerate worst cases
    boxes: dict[str, list] = {k: [] for k in data}
    for name in manifest["files"]:
        with np.load(os.path.join(path, name)) as z:
            for entry in z.files:
                if entry.endswith("§idx"):
                    continue
                key, _j = entry.rsplit("§", 1)
                bounds = z[entry + "§idx"]
                idx = tuple(slice(int(a), int(b)) for a, b in bounds)
                val = z[entry]
                if _bit_dtype(data[key].dtype) is not None:
                    val = _decode_leaf(val, data[key].dtype.name)
                data[key][idx] = val
                boxes[key].append(np.asarray(bounds, np.int64))

    def _covers(bs, shape) -> bool:
        if any(len(b) != len(shape) for b in bs):
            return False                     # rank-mismatched writer
        total = sum(int(np.prod(b[:, 1] - b[:, 0])) if b.size else 1
                    for b in bs)
        if total != int(np.prod(shape, dtype=np.int64)):
            return False
        if not shape:                        # scalar: exactly one box
            return len(bs) == 1
        bs = sorted(bs, key=lambda b: int(b[0, 0]))
        for i, a in enumerate(bs):           # pairwise disjoint
            for b in bs[i + 1:]:
                if b[0, 0] >= a[0, 1]:
                    break                    # sorted: no later overlap
                if all((a[d, 1] > b[d, 0]) and (b[d, 1] > a[d, 0])
                       for d in range(len(a))):
                    return False
        return True

    missing = [k for k, bs in boxes.items()
               if not _covers(bs, data[k].shape)]
    if missing:
        raise ValueError(
            f"sharded checkpoint {path} does not cover leaves "
            f"{missing[:5]} — saved by an incompatible writer?")
    return data, int(manifest["step"]), int(manifest["epoch"])


def _rebuild(data: dict, template: Any, validate: bool,
             ckpt_path: str = "<data>"):
    """Key-matched unflatten of ``data`` into the template's tree
    structure. ``validate=False`` skips shape checks — the
    sharded-FSDP resume path, where the saved flat layout's shapes
    (old dp/mp) legitimately differ from the new run's template."""
    leaves_with_paths, treedef = jax.tree_util.tree_flatten_with_path(
        template)
    new_leaves = []
    for path_, leaf in leaves_with_paths:
        key = _tree_key(path_)
        if key not in data:
            raise KeyError(f"checkpoint {ckpt_path} missing leaf {key!r}")
        arr = data[key]
        want = tuple(np.shape(leaf))
        if validate:
            if arr.shape != want and arr.size == np.size(leaf) \
                    and key.endswith("qkv"):
                # migration: transformer qkv leaves changed layout from
                # (d, 3d)/(3d,) to (d, 3, d)/(3, d) when Megatron TP
                # landed; the flat row-major order is identical (q|k|v
                # column blocks), so old checkpoints restore by reshape
                arr = arr.reshape(want)
            if arr.shape != want:
                raise ValueError(
                    f"checkpoint leaf {key!r} shape {arr.shape} != "
                    f"expected {want}")
        new_leaves.append(arr.astype(np.asarray(leaf).dtype))
    return jax.tree_util.tree_unflatten(treedef, new_leaves)


def rebuild_tree(data: dict, template: Any):
    """Key-matched unflatten WITHOUT shape validation (see _rebuild)."""
    return _rebuild(data, template, validate=False)


def rebuild_tree_validated(data: dict, template: Any,
                           ckpt_path: str = "<data>"):
    """Key-matched unflatten WITH shape validation — the resilience
    auto-resume path (full logical leaves restored from the snapshot
    store, resilience/manifest.py) shares the one rebuild
    implementation with the classic formats."""
    return _rebuild(data, template, validate=True, ckpt_path=ckpt_path)


def restore_checkpoint(path: str, state_template: Any) -> Tuple[Any, int, int]:
    """Restore into the template's tree structure; returns (state, step, epoch).

    Leaves are matched by tree path, so the checkpoint survives
    refactors that keep param names stable (W1/b1/..., SURVEY.md §5).
    Dispatches on the on-disk format: a ``.shards`` directory is
    reassembled to full leaves first (restore_sharded_arrays), so both
    formats restore into the same template — and a sharded checkpoint
    written at one process/device topology restores at any other.
    """
    if os.path.isdir(path):
        data, step, epoch = restore_sharded_arrays(path)
    else:
        with np.load(path) as z:
            data = {k: z[k] for k in z.files}
        step = int(data.pop("__step__"))
        epoch = int(data.pop("__epoch__"))
        # bit-encoded leaves (bf16-family, _encode_leaf): view back
        for dk in [k for k in data if k.startswith("__dt_")]:
            name = str(data.pop(dk))
            data[dk[len("__dt_"):-2]] = _decode_leaf(
                data[dk[len("__dt_"):-2]], name)
    state = _rebuild(data, state_template, validate=True, ckpt_path=path)
    return state, step, epoch
