from .summary import SummaryWriter, read_event_file
from .checkpoint import save_checkpoint, restore_checkpoint, latest_checkpoint

__all__ = [
    "SummaryWriter",
    "read_event_file",
    "save_checkpoint",
    "restore_checkpoint",
    "latest_checkpoint",
]
