"""Compiled-graph observability: HLO/StableHLO text dumps.

Reference parity: the reference writes its TF graph into the
TensorBoard event log (``FileWriter(logs_path, graph=...)``,
/root/reference/example.py:146) so the operator can inspect what will
execute. The TPU-native analog of "the graph" is the XLA program:
``--profile`` dumps, next to the profiler trace,

- ``<name>.stablehlo.txt`` — the portable StableHLO module as traced
  (the artifact to diff across JAX versions), and
- ``<name>.hlo.txt`` — the optimized HLO the TPU actually runs (post
  XLA fusion/layout; the artifact to read for performance work).

Dumping lowers/compiles through the persistent compilation cache, so
the subsequent real execution of the same program is a cache hit, not
a second compile.
"""

from __future__ import annotations

import os
from typing import Sequence


def dump_graph(jitted, args: Sequence, logs_path: str, name: str) -> list[str]:
    """Write StableHLO + optimized-HLO text for ``jitted(*args)`` into
    ``logs_path``; returns the paths written. Never raises — graph
    observability must not take down training (errors are reported to
    stdout and the run continues)."""
    written: list[str] = []
    try:
        lowered = jitted.lower(*args)
        os.makedirs(logs_path, exist_ok=True)
        p = os.path.join(logs_path, f"{name}.stablehlo.txt")
        with open(p, "w") as f:
            f.write(lowered.as_text())
        written.append(p)
        compiled = lowered.compile()
        p = os.path.join(logs_path, f"{name}.hlo.txt")
        with open(p, "w") as f:
            f.write(compiled.as_text())
        written.append(p)
    except Exception as e:  # pragma: no cover - backend-dependent
        print(f"NOTE: HLO dump for {name!r} failed: {e}")
    return written
