"""TensorBoard event-file writer (no TensorFlow dependency).

Reference parity: the reference creates scalar summaries for ``cost``
and ``accuracy`` (/root/reference/example.py:124-125), merges them
(example.py:128), writes a ``FileWriter(logs_path, graph=...)`` on every
machine (example.py:145-146) and appends the merged summary every step
(example.py:163). The files are TFRecord-framed ``Event`` protobufs
written by TF's C++ RecordWriter.

This module re-implements that capability from scratch:

- the ``Event``/``Summary`` protobuf subset is hand-encoded (wire
  format: varint/64-bit/length-delimited fields) — no protobuf runtime;
- TFRecord framing (little-endian length, masked CRC32C of the length,
  payload, masked CRC32C of the payload) uses the native C++ CRC32C
  from ``distributed_tensorflow_example_tpu.native`` (the role TF's C++
  RecordWriter played);
- files are named ``events.out.tfevents.<ts>.<host>`` and open with a
  ``file_version: "brain.Event:2"`` event, exactly what TensorBoard
  expects.

``read_event_file`` parses the format back (used by tests to round-trip
and by parity checks).
"""

from __future__ import annotations

import os
import socket
import struct
import time
from typing import Iterator, Tuple

from ..native import masked_crc32c

# --- minimal protobuf wire-format encoders -------------------------------


def _varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _key(field: int, wire: int) -> bytes:
    return _varint((field << 3) | wire)


def _double_field(field: int, value: float) -> bytes:
    return _key(field, 1) + struct.pack("<d", value)


def _float_field(field: int, value: float) -> bytes:
    return _key(field, 5) + struct.pack("<f", value)


def _int64_field(field: int, value: int) -> bytes:
    return _key(field, 0) + _varint(value & 0xFFFFFFFFFFFFFFFF)


def _bytes_field(field: int, value: bytes) -> bytes:
    return _key(field, 2) + _varint(len(value)) + value


def _packed_doubles_field(field: int, values) -> bytes:
    """Packed repeated double (wire type 2, consecutive LE doubles)."""
    payload = b"".join(struct.pack("<d", float(v)) for v in values)
    return _key(field, 2) + _varint(len(payload)) + payload


# --- Event / Summary messages (tensorflow/core/util/event.proto) ---------


def encode_scalar_summary(values: dict[str, float]) -> bytes:
    """Summary{ repeated Value{ tag=1, simple_value=2 } value=1 }."""
    out = b""
    for tag, val in values.items():
        value_msg = _bytes_field(1, tag.encode()) + _float_field(2, float(val))
        out += _bytes_field(1, value_msg)
    return out


def encode_histogram_proto(values) -> bytes:
    """HistogramProto{ min=1, max=2, num=3, sum=4, sum_squares=5,
    repeated bucket_limit=6 [packed], repeated bucket=7 [packed] }
    (tensorflow/core/framework/summary.proto).

    Buckets are 30 equal-width bins over [min, max] (right edges in
    ``bucket_limit``), degenerating to one bin when all values are
    equal — TensorBoard renders arbitrary edges, and equal-width bins
    keep the encoder dependency-free. Counts always sum to
    ``len(values)`` (pinned by tests/test_summary.py).

    Non-finite values must not kill the run that is recording them —
    a diverging loss producing an inf grad norm is exactly what the
    histogram exists to show. They are clamped into the finite
    values' range (landing in the edge buckets; NaN counts high);
    an all-non-finite tensor collapses to one bucket at 0."""
    import numpy as np

    v = np.asarray(values, dtype=np.float64).ravel()
    if v.size == 0:
        raise ValueError("cannot encode an empty histogram")
    finite = v[np.isfinite(v)]
    if finite.size == 0:
        lo = hi = 0.0
        vb = np.zeros_like(v)
    else:
        lo, hi = float(finite.min()), float(finite.max())
        vb = np.clip(np.nan_to_num(v, nan=hi, posinf=hi, neginf=lo),
                     lo, hi)
    msg = _double_field(1, lo) + _double_field(2, hi)
    msg += _double_field(3, float(v.size))
    msg += _double_field(4, float(vb.sum()))
    msg += _double_field(5, float(np.square(vb).sum()))
    if hi > lo:
        counts, edges = np.histogram(vb, bins=30, range=(lo, hi))
        limits = edges[1:]
    else:
        counts, limits = np.array([v.size]), np.array([hi])
    msg += _packed_doubles_field(6, limits)
    msg += _packed_doubles_field(7, counts)
    return msg


def encode_histogram_summary(histos: dict) -> bytes:
    """Summary{ repeated Value{ tag=1, histo=5 } } from {tag: array}."""
    out = b""
    for tag, vals in histos.items():
        value_msg = _bytes_field(1, tag.encode()) + _bytes_field(
            5, encode_histogram_proto(vals))
        out += _bytes_field(1, value_msg)
    return out


def encode_node_def(name: str, op: str, inputs: tuple[str, ...] = ()) -> bytes:
    """NodeDef{ name=1, op=2, repeated input=3 } (node_def.proto)."""
    msg = _bytes_field(1, name.encode()) + _bytes_field(2, op.encode())
    for inp in inputs:
        msg += _bytes_field(3, inp.encode())
    return msg


def encode_graph_def(nodes) -> bytes:
    """GraphDef{ repeated node=1, versions=4{producer=1} } from
    (name, op, inputs) triples (graph.proto)."""
    out = b"".join(_bytes_field(1, encode_node_def(*n)) for n in nodes)
    out += _bytes_field(4, _int64_field(1, 27))  # VersionDef.producer
    return out


def mlp_graph_nodes(input_size: int, hidden_sizes, num_classes: int,
                    activation: str, optimizer: str = "sgd"):
    """The training graph as (name, op, inputs) triples, mirroring the
    reference's graph build (/root/reference/example.py:60-129: x/y_
    placeholders, W/b variables, MatMul+Add+activation per layer,
    Softmax output, cross_entropy, accuracy, the optimizer's apply op
    and global_step) so the TensorBoard Graphs tab shows the same
    structure the reference's ``FileWriter(logs_path, graph=...)``
    (example.py:146) published."""
    act_op = {"sigmoid": "Sigmoid", "relu": "Relu", "tanh": "Tanh",
              "gelu": "Gelu"}.get(activation, activation.capitalize())
    opt_op = {"sgd": "ApplyGradientDescent", "momentum": "ApplyMomentum",
              "adam": "ApplyAdam"}.get(optimizer, "ApplyGradientDescent")
    nodes = [
        ("x", "Placeholder", ()),
        ("y_", "Placeholder", ()),
        ("global_step", "VariableV2", ()),
    ]
    sizes = (input_size, *tuple(hidden_sizes), num_classes)
    prev = "x"
    n_layers = len(sizes) - 1
    for i in range(n_layers):
        w, b = f"W{i + 1}", f"b{i + 1}"
        nodes += [(w, "VariableV2", ()), (b, "VariableV2", ())]
        mm, z = f"layer{i + 1}/MatMul", f"z{i + 2}"
        nodes += [(mm, "MatMul", (prev, w)), (z, "Add", (mm, b))]
        if i < n_layers - 1:
            a = f"a{i + 2}"
            nodes.append((a, act_op, (z,)))
            prev = a
        else:
            nodes.append(("y", "Softmax", (z,)))
    nodes += [
        ("cross_entropy", "Mean", ("y", "y_")),
        ("accuracy", "Mean", ("y", "y_")),
        ("train", opt_op, ("cross_entropy", "global_step")),
    ]
    return nodes


def transformer_graph_nodes(num_blocks: int):
    """Graph triples for the transformer family (models/transformer.py)
    — coarse block-level structure for the TB Graphs tab (tensor dims
    are not part of this skeleton, only the op topology)."""
    nodes = [
        ("x", "Placeholder", ()),
        ("y_", "Placeholder", ()),
        ("global_step", "VariableV2", ()),
        ("embed/MatMul", "MatMul", ("x",)),
        ("embed/pos_add", "Add", ("embed/MatMul",)),
    ]
    prev = "embed/pos_add"
    for i in range(num_blocks):
        blk = f"block{i}"
        nodes += [
            (f"{blk}/ln1", "LayerNorm", (prev,)),
            (f"{blk}/attention", "MultiHeadAttention", (f"{blk}/ln1",)),
            (f"{blk}/residual1", "Add", (prev, f"{blk}/attention")),
            (f"{blk}/ln2", "LayerNorm", (f"{blk}/residual1",)),
            (f"{blk}/ffn", "MatMul", (f"{blk}/ln2",)),
            (f"{blk}/residual2", "Add", (f"{blk}/residual1", f"{blk}/ffn")),
        ]
        prev = f"{blk}/residual2"
    nodes += [
        ("lnf", "LayerNorm", (prev,)),
        ("pool", "Mean", ("lnf",)),
        ("y", "Softmax", ("pool",)),
        ("cross_entropy", "Mean", ("y", "y_")),
        ("accuracy", "Mean", ("y", "y_")),
        ("train", "ApplyGradientDescent", ("cross_entropy", "global_step")),
    ]
    return nodes


def encode_event(
    wall_time: float,
    step: int | None = None,
    file_version: str | None = None,
    scalars: dict[str, float] | None = None,
    graph_def: bytes | None = None,
    histograms: dict | None = None,
) -> bytes:
    """Event{ wall_time=1(double), step=2(int64), file_version=3,
    graph_def=4(bytes), summary=5 }."""
    msg = _double_field(1, wall_time)
    if step is not None:
        msg += _int64_field(2, step)
    if file_version is not None:
        msg += _bytes_field(3, file_version.encode())
    if graph_def is not None:
        msg += _bytes_field(4, graph_def)
    summary = b""
    if scalars:
        summary += encode_scalar_summary(scalars)
    if histograms:
        summary += encode_histogram_summary(histograms)
    if summary:
        msg += _bytes_field(5, summary)
    return msg


def tfrecord_frame(data: bytes) -> bytes:
    header = struct.pack("<Q", len(data))
    return (
        header
        + struct.pack("<I", masked_crc32c(header))
        + data
        + struct.pack("<I", masked_crc32c(data))
    )


class SummaryWriter:
    """Drop-in for the reference's FileWriter + add_summary usage
    (example.py:146, 163), TensorBoard-compatible."""

    def __init__(self, logdir: str, filename_suffix: str = ""):
        os.makedirs(logdir, exist_ok=True)
        fname = "events.out.tfevents.%010d.%s%s" % (
            int(time.time()),
            socket.gethostname(),
            filename_suffix,
        )
        self.path = os.path.join(logdir, fname)
        self._f = open(self.path, "ab")
        self._write_event(encode_event(time.time(), file_version="brain.Event:2"))

    def _write_event(self, event: bytes) -> None:
        self._f.write(tfrecord_frame(event))

    def add_scalars(self, step: int, values: dict[str, float]) -> None:
        """``writer.add_summary(summary, step)`` equivalent (example.py:163)."""
        self._write_event(encode_event(time.time(), step=step, scalars=values))

    def add_histograms(self, step: int, values: dict) -> None:
        """Write histogram summaries (e.g. grad/param norms) — the
        capability the reference's merged scalar summary never had;
        TensorBoard's Histograms tab reads these."""
        self._write_event(encode_event(time.time(), step=step,
                                       histograms=values))

    def add_graph(self, nodes) -> None:
        """``FileWriter(logdir, graph=...)`` equivalent (example.py:146):
        write the graph record TensorBoard's Graphs tab reads. ``nodes``
        is a list of (name, op, inputs) triples (see mlp_graph_nodes)."""
        self._write_event(encode_event(
            time.time(), graph_def=encode_graph_def(nodes)))

    def flush(self) -> None:
        self._f.flush()

    def close(self) -> None:
        self._f.flush()
        self._f.close()


# --- reader (tests / tooling) --------------------------------------------


def _read_varint(buf: bytes, pos: int) -> Tuple[int, int]:
    result = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _parse_fields(buf: bytes) -> Iterator[Tuple[int, int, bytes | int | float]]:
    pos = 0
    while pos < len(buf):
        key, pos = _read_varint(buf, pos)
        field, wire = key >> 3, key & 7
        if wire == 0:
            val, pos = _read_varint(buf, pos)
        elif wire == 1:
            (val,) = struct.unpack_from("<d", buf, pos)
            pos += 8
        elif wire == 5:
            (val,) = struct.unpack_from("<f", buf, pos)
            pos += 4
        elif wire == 2:
            ln, pos = _read_varint(buf, pos)
            val = buf[pos : pos + ln]
            pos += ln
        else:
            raise ValueError(f"unsupported wire type {wire}")
        yield field, wire, val


def _parse_histogram(buf: bytes) -> dict:
    """Decode a HistogramProto (see encode_histogram_proto)."""
    histo = {"min": None, "max": None, "num": None, "sum": None,
             "sum_squares": None, "bucket_limit": [], "bucket": []}
    names = {1: "min", 2: "max", 3: "num", 4: "sum", 5: "sum_squares"}
    for hfield, _hw, hval in _parse_fields(buf):
        if hfield in names:
            histo[names[hfield]] = hval
        elif hfield in (6, 7):
            key = "bucket_limit" if hfield == 6 else "bucket"
            vals = [struct.unpack_from("<d", hval, off)[0]
                    for off in range(0, len(hval), 8)]
            histo[key].extend(vals)
    return histo


def read_event_file(path: str):
    """Parse a tfevents file into [{wall_time, step, file_version,
    scalars, histograms, graph_nodes}]."""
    events = []
    with open(path, "rb") as f:
        data = f.read()
    pos = 0
    while pos < len(data):
        (length,) = struct.unpack_from("<Q", data, pos)
        header = data[pos : pos + 8]
        (len_crc,) = struct.unpack_from("<I", data, pos + 8)
        if len_crc != masked_crc32c(header):
            raise ValueError("length CRC mismatch")
        payload = data[pos + 12 : pos + 12 + length]
        (data_crc,) = struct.unpack_from("<I", data, pos + 12 + length)
        if data_crc != masked_crc32c(payload):
            raise ValueError("payload CRC mismatch")
        pos += 12 + length + 4

        ev = {"wall_time": None, "step": None, "file_version": None,
              "scalars": {}, "histograms": {}, "graph_nodes": None}
        for field, _wire, val in _parse_fields(payload):
            if field == 1:
                ev["wall_time"] = val
            elif field == 2:
                ev["step"] = val
            elif field == 3:
                ev["file_version"] = val.decode()
            elif field == 4:
                nodes = []
                for gfield, _gw, gval in _parse_fields(val):
                    if gfield == 1:  # NodeDef
                        name, op, inputs = None, None, []
                        for nfield, _nw, nval in _parse_fields(gval):
                            if nfield == 1:
                                name = nval.decode()
                            elif nfield == 2:
                                op = nval.decode()
                            elif nfield == 3:
                                inputs.append(nval.decode())
                        nodes.append(
                            {"name": name, "op": op, "inputs": inputs})
                ev["graph_nodes"] = nodes
            elif field == 5:
                for sfield, _w, sval in _parse_fields(val):
                    if sfield == 1:
                        tag, simple, histo = None, None, None
                        for vfield, _w2, vval in _parse_fields(sval):
                            if vfield == 1:
                                tag = vval.decode()
                            elif vfield == 2:
                                simple = vval
                            elif vfield == 5:
                                histo = _parse_histogram(vval)
                        if tag is not None and histo is not None:
                            ev["histograms"][tag] = histo
                        elif tag is not None:
                            ev["scalars"][tag] = simple
        events.append(ev)
    return events
