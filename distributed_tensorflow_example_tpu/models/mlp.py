"""MLP model family.

Reference parity: the reference builds, under a ps-placement scope, a
2-layer sigmoid MLP with seed-1 standard-normal weights and zero biases
(/root/reference/example.py:74-90):

    W1 ~ N(0,1) [784,100]; b1 = 0 [100]      (example.py:76, 81)
    W2 ~ N(0,1) [100,10];  b2 = 0 [10]       (example.py:77, 82)
    z2 = x@W1 + b1; a2 = sigmoid(z2)         (example.py:87-88)
    z3 = a2@W2 + b2; y = softmax(z3)         (example.py:89-90)

TPU-native design (SURVEY.md L3): a pure-function pytree model —
``init(key, spec)`` returns the parameter pytree, ``apply(spec, params,
x)`` returns *logits* (z3). Softmax is deliberately NOT applied in the
forward: the loss works on logits in log-sum-exp form (the reference's
``log(softmax)`` is numerically unstable, SURVEY.md §2 quirks), and the
accuracy argmax is softmax-invariant. ``--naive_ce`` reproduces the
reference arithmetic from the same logits for parity runs.

BASELINE.json config 4 ("deeper MLP, 2 hidden, ReLU") is the same code
with ``hidden_sizes=(h1, h2), activation='relu'`` — depth, widths and
activation are spec fields, not new code.

Sharding (SURVEY.md L2): parameters carry no placement here; the
parallel layer assigns ``NamedSharding``s — replicated for pure DP, or
Megatron-style split over the hidden axis when ``model_parallel > 1``
(W1 column-sharded, W2 row-sharded; see parallel/step.py).
"""

from __future__ import annotations

import dataclasses
from typing import Dict

import jax
import jax.numpy as jnp

Params = Dict[str, jnp.ndarray]

_ACTIVATIONS = {
    "sigmoid": jax.nn.sigmoid,
    "relu": jax.nn.relu,
    "tanh": jnp.tanh,
    "gelu": jax.nn.gelu,
}


@dataclasses.dataclass(frozen=True)
class MLPSpec:
    input_size: int = 784
    hidden_sizes: tuple[int, ...] = (100,)
    num_classes: int = 10
    activation: str = "sigmoid"
    param_dtype: jnp.dtype = jnp.float32
    compute_dtype: jnp.dtype = jnp.float32

    @property
    def layer_sizes(self) -> tuple[int, ...]:
        return (self.input_size, *self.hidden_sizes, self.num_classes)

    @property
    def num_layers(self) -> int:
        return len(self.hidden_sizes) + 1


def init(key: jax.Array, spec: MLPSpec) -> Params:
    """Seeded init: W ~ N(0,1), b = 0, matching example.py:74-82.

    The reference seeds the graph with ``tf.set_random_seed(1)``
    (example.py:74); callers pass ``jax.random.PRNGKey(seed)``. Standard
    normal (stddev 1) init is unusual by modern standards but is the
    reference's exact choice (``tf.random_normal`` defaults).
    """
    sizes = spec.layer_sizes
    params: Params = {}
    keys = jax.random.split(key, spec.num_layers)
    for i in range(spec.num_layers):
        params[f"W{i + 1}"] = jax.random.normal(
            keys[i], (sizes[i], sizes[i + 1]), dtype=spec.param_dtype
        )
        params[f"b{i + 1}"] = jnp.zeros((sizes[i + 1],), dtype=spec.param_dtype)
    return params


def apply(
    spec: MLPSpec,
    params: Params,
    x: jnp.ndarray,
    styles: tuple[str, ...] | None = None,
    model_axis: str | None = None,
) -> jnp.ndarray:
    """Forward pass to logits (example.py:87-89; softmax left to the loss).

    Matmuls take ``compute_dtype`` inputs (bfloat16 hits the MXU's
    native input width) with float32 accumulation
    (``preferred_element_type``); bias add and activation run in f32,
    rounded to ``compute_dtype`` at each layer edge. For float32 this is
    the plain forward; for bfloat16 it keeps the MXU's f32 accumulator
    precision through the elementwise tail. The fused Pallas kernel
    (ops.pallas_fused) computes this layer-for-layer identically. The
    whole chain fuses into one XLA computation.

    ``styles`` (from parallel.mesh.layer_styles) makes the same code
    tensor-parallel inside shard_map: a 'row'-split layer's partial
    matmul is psum'd over ``model_axis`` before the bias. With the
    default (None / all-'rep') this is the plain replicated forward.
    """
    act = _ACTIVATIONS[spec.activation]
    cdt = spec.compute_dtype
    h = x.astype(cdt)
    L = spec.num_layers
    for i in range(1, L + 1):
        w = params[f"W{i}"].astype(cdt)
        b = params[f"b{i}"].astype(jnp.float32)
        acc = jnp.dot(h.astype(cdt), w, preferred_element_type=jnp.float32)
        if styles is not None and styles[i - 1] == "row":
            acc = jax.lax.psum(acc, model_axis)
        h = acc + b
        if i < L:
            h = act(h).astype(cdt)
    return h.astype(jnp.float32)


def num_params(spec: MLPSpec) -> int:
    sizes = spec.layer_sizes
    return sum(sizes[i] * sizes[i + 1] + sizes[i + 1] for i in range(spec.num_layers))
