"""Transformer model family (beyond-reference capability).

The reference's only model is the 2-layer sigmoid MLP
(/root/reference/example.py:74-90); SURVEY.md §5 records attention and
long context as absent upstream. This module supplies the model family
that WIRES the framework's long-context primitives
(ops/flash_attention.py, ops/ring_attention.py) into the actual
training pipeline: a pre-LN encoder classifier whose attention backend
is selectable per spec — XLA dense for short sequences, the flash
Pallas kernels for tile-aligned long ones — running through the same
driver, SPMD step, fast scan paths, checkpointing, summaries and eval
as the MLP (`--model=transformer`).

TPU-native design notes:
- images (or any flat feature vector) are viewed as a sequence:
  ``[B, input_size] -> [B, seq_len, input_size/seq_len]`` tokens, so
  the MNIST pipeline feeds it unchanged;
- matmuls take ``compute_dtype`` inputs with f32 accumulation
  (``preferred_element_type``), exactly like models/mlp.py — bfloat16
  puts them on the MXU's native input width;
- layer norms and softmax statistics stay in f32;
- the whole forward is one XLA computation; with
  ``attention='flash'`` the score matrix is never materialized
  (O(S·blk) memory; ragged lengths fall back to exact dense inside
  ops/flash_attention).

Params are a flat ``{name: array}`` dict like the MLP's — checkpoint
and FSDP-flattening friendly. PartitionSpec tree = replicated P() for
every leaf under pure data parallelism; Megatron-style tensor
parallelism (``--model_parallel``, ``model_axis``) shards attention
heads and the FFN hidden dim: ``Wqkv`` is laid out ``[d, 3, d]`` so a
last-dim PartitionSpec gives every shard whole heads' q/k/v columns
(heads are contiguous Dh-column blocks of d), ``Wo``/``W2`` row-split
with one psum each per block, ``W1`` column-split — two psums per
block total, the textbook Megatron count.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np

Params = Dict[str, jnp.ndarray]

from ..parallel import pp_schedule  # pure-Python tick tables (no jax)
from .mlp import _ACTIVATIONS  # one activation table for every family


def _hop_start(x, stage_axis: str, perm):
    """Issue a stage-hop collective NOW, under the ``pp_comm`` trace
    scope (obs/buckets.NAMED_SCOPES) so profiler captures name the
    transfer.  The async split is structural: the ppermute depends
    only on ``x``, so once issued here — BEFORE the other direction's
    compute in program order — XLA's latency-hiding scheduler is free
    to run the transfer underneath it; ``_hop_join`` pins the matching
    wait AFTER that compute, so the overlap window spans it."""
    with jax.named_scope("pp_comm"):
        return jax.lax.ppermute(x, stage_axis, perm)


def _hop_join(msg, anchor):
    """Join an in-flight stage hop: barrier the received message
    against ``anchor`` (the compute the transfer should hide under),
    so no consumer of the message can be scheduled before the anchor
    completes — the ``done`` half of the start/done pair.  Returns
    (message, anchor) re-tied."""
    return jax.lax.optimization_barrier((msg, anchor))


def _chunk_select(stacked, c, sidx, stage_span, kc):
    """Select virtual chunk ``c``'s block params from a stage's
    ``[v, kc, ...]``-stacked leaves, plus the chunk's global block
    offset for the dropout/MoE salts: this stage's stacked slice
    starts at ``sidx * stage_span`` and chunk ``c`` occupies positions
    ``base .. base + kc - 1`` (chunk-major is the stacking order
    ``_pipeline_block_order`` fixed at conversion time).  The ONE copy
    of the convention, shared by ``apply_pipeline`` (the jax.grad
    schedules) and the fused 1f1b family — the two schedules'
    dropout/MoE parity depends on it."""
    bp_c = {k: jax.lax.dynamic_index_in_dim(a, c, 0, keepdims=False)
            for k, a in stacked.items()}
    return bp_c, sidx * stage_span + c * kc


@dataclasses.dataclass(frozen=True)
class TransformerSpec:
    input_size: int = 784
    num_classes: int = 10
    seq_len: int = 28              # tokens; input_size must divide evenly
    d_model: int = 128
    n_heads: int = 4
    num_blocks: int = 2
    d_ff: int = 256
    activation: str = "gelu"
    objective: str = "classify"    # classify (reference-style labels)
                                   # | lm (autoregressive next-token
                                   # prediction over discretized
                                   # inputs, image-GPT style — causal,
                                   # one token per input scalar)
    vocab_size: int = 256          # lm only: discretization levels
    attention: str = "dense"       # dense | flash (ops/flash_attention)
    sp_impl: str = "ring"          # sequence-parallel layout: ring
                                   # (ppermute k/v orbit) | ulysses
                                   # (head<->seq all_to_all)
    causal: bool = False
    num_experts: int = 0           # 0 = dense FFN; >0 = mixture-of-
                                   # experts FFN (Switch/GShard style)
    moe_topk: int = 1              # experts per token: 1 = Switch
                                   # (gate = raw top prob), >1 = GShard
                                   # (gates renormalized among the
                                   # selected experts)
    aux_loss_weight: float = 0.0   # > 0 adds the Switch load-balance
                                   # loss E*sum_e(f_e*P_e) per MoE
                                   # block to the training objective
                                   # (reported cost stays plain CE)
    dropout_rate: float = 0.0      # training-only dropout on the
                                   # embedded input and each block's
                                   # attention/FFN outputs (inverted
                                   # scaling; eval never drops)
    moe_dispatch: str = "dense"    # dense (every expert on every token,
                                   # one-hot select — exact) | alltoall
                                   # (capacity-limited token dispatch,
                                   # Switch/GShard style)
    capacity_factor: float = 1.25  # alltoall only: per-expert buffer =
                                   # ceil(cf * tokens * k / E); overflow
                                   # tokens are dropped (residual path
                                   # carries them)
    fused_ln: bool = False         # LayerNorms (block ln1/ln2, final
                                   # lnf, decode) run the fused Pallas
                                   # kernel (ops/pallas_fused.
                                   # fused_layer_norm[_residual]) with
                                   # its Pallas backward; ln2 also
                                   # fuses the attention residual add
    grouped_moe: bool = False      # sparse-dispatch expert FFN runs
                                   # the fused grouped Pallas kernel
                                   # (ops/pallas_fused.
                                   # moe_grouped_matmul) instead of
                                   # two batched XLA einsums
    fp8_ffn: bool = False          # FFN matmuls (dense W1/W2 and the
                                   # sparse grouped expert kernel)
                                   # run on fp8-e4m3-rounded operands
                                   # with pow2 scales (ops/
                                   # pallas_fused.fp8_dense_ffn /
                                   # fp8_grouped_matmul; bf16/f32
                                   # master weights, straight-through
                                   # gradients)
    param_dtype: jnp.dtype = jnp.float32
    compute_dtype: jnp.dtype = jnp.float32

    @property
    def d_feature(self) -> int:
        if self.objective == "lm":
            # one token per input scalar; values embed via W_emb lookup
            if self.seq_len != self.input_size:
                raise ValueError(
                    f"objective='lm' tokenizes every input scalar: "
                    f"seq_len ({self.seq_len}) must equal input_size "
                    f"({self.input_size})")
            return 1
        if self.input_size % self.seq_len:
            raise ValueError(
                f"input_size={self.input_size} not divisible by "
                f"seq_len={self.seq_len}")
        return self.input_size // self.seq_len

    @property
    def d_head(self) -> int:
        if self.d_model % self.n_heads:
            raise ValueError(
                f"d_model={self.d_model} not divisible by "
                f"n_heads={self.n_heads}")
        return self.d_model // self.n_heads


def init(key: jax.Array, spec: TransformerSpec) -> Params:
    """Seeded init: scaled-normal weights (1/sqrt(fan_in)), 0.02-normal
    positional embeddings, zero biases, unit layer-norm gains. Unlike
    the MLP's reference-mandated N(0,1) (example.py:76-82), this family
    is beyond-reference, so it uses the init that actually trains a
    transformer. Structure comes from ``param_shapes`` — the one source
    of truth shared with ``param_pspecs``/``num_params``."""
    shapes = param_shapes(spec)
    pd = spec.param_dtype
    random_names = [n for n in shapes if "W" in n or n == "pos"]
    keys = dict(zip(random_names, jax.random.split(key, len(random_names))))
    p: Params = {}
    for name, shape in shapes.items():
        if name in ("pos", "W_emb"):
            p[name] = (0.02 * jax.random.normal(
                keys[name], shape, dtype=jnp.float32)).astype(pd)
        elif "W" in name:
            # expert weights are [E, fan_in, fan_out] and Wqkv is
            # [d, 3, d]: scale by the actual fan-in in either layout
            fan_in = (shape[-2] if name.endswith(("We1", "We2"))
                      else shape[0])
            p[name] = (jax.random.normal(keys[name], shape, jnp.float32)
                       / jnp.sqrt(jnp.float32(fan_in))).astype(pd)
        elif name.endswith("_g"):
            p[name] = jnp.ones(shape, pd)
        else:
            p[name] = jnp.zeros(shape, pd)
    return p


def param_shapes(spec: TransformerSpec) -> Dict[str, tuple[int, ...]]:
    """Analytic {name: shape} map — the single source of truth for the
    parameter tree's structure (init, pspecs and num_params derive from
    it without materializing weights)."""
    d, ff, f = spec.d_model, spec.d_ff, spec.d_feature
    if spec.objective == "lm":
        # vocab embedding in, per-position vocab head out
        shapes: Dict[str, tuple[int, ...]] = {
            "W_emb": (spec.vocab_size, d), "pos": (spec.seq_len, d),
            "lnf_g": (d,), "lnf_b": (d,),
            "W_head": (d, spec.vocab_size), "b_head": (spec.vocab_size,),
        }
    else:
        shapes = {
            "W_in": (f, d), "b_in": (d,), "pos": (spec.seq_len, d),
            "lnf_g": (d,), "lnf_b": (d,),
            "W_head": (d, spec.num_classes),
            "b_head": (spec.num_classes,),
        }
    for i in range(spec.num_blocks):
        shapes.update({
            f"L{i}_ln1_g": (d,), f"L{i}_ln1_b": (d,),
            f"L{i}_Wqkv": (d, 3, d), f"L{i}_bqkv": (3, d),
            f"L{i}_Wo": (d, d), f"L{i}_bo": (d,),
            f"L{i}_ln2_g": (d,), f"L{i}_ln2_b": (d,),
        })
        if spec.num_experts:
            e = spec.num_experts
            shapes.update({
                f"L{i}_Wr": (d, e),                 # router
                f"L{i}_We1": (e, d, ff), f"L{i}_be1": (e, ff),
                f"L{i}_We2": (e, ff, d), f"L{i}_be2": (e, d),
            })
        else:
            shapes.update({
                f"L{i}_W1": (d, ff), f"L{i}_b1": (ff,),
                f"L{i}_W2": (ff, d), f"L{i}_b2": (d,),
            })
    return shapes


_EXPERT_LEAVES = ("_We1", "_be1", "_We2", "_be2")


def _tp_leaf_specs(model_axis: str):
    """Per-block-leaf Megatron PartitionSpecs (unprefixed leaf name ->
    spec); leaves not listed replicate. Shared by the flat and the
    pipeline-stacked layouts."""
    from jax.sharding import PartitionSpec as P

    return {
        "Wqkv": P(None, None, model_axis), "bqkv": P(None, model_axis),
        "Wo": P(model_axis, None), "bo": P(),
        "W1": P(None, model_axis), "b1": P(model_axis),
        "W2": P(model_axis, None), "b2": P(),
    }


def check_tp(spec: TransformerSpec, model_parallel: int) -> None:
    """Validate a Megatron TP degree against the spec's dims. With a
    MoE FFN only the attention side TP-shards (experts shard over the
    expert axis instead), so d_ff divisibility applies to the dense
    FFN alone."""
    if model_parallel <= 1:
        return
    if spec.n_heads % model_parallel:
        raise ValueError(
            f"n_heads={spec.n_heads} must divide evenly over "
            f"model_parallel={model_parallel}")
    if not spec.num_experts and spec.d_ff % model_parallel:
        raise ValueError(
            f"d_ff={spec.d_ff} must divide evenly over "
            f"model_parallel={model_parallel}")


def param_pspecs(spec: TransformerSpec, expert_axis: str | None = None,
                 model_axis: str | None = None,
                 ) -> Dict[str, "jax.sharding.PartitionSpec"]:
    """Replicated P() for every leaf, with two sharded flavors:

    - ``expert_axis`` (expert parallelism): the per-expert weight
      stacks shard their leading E dim (the router stays replicated —
      every shard needs the full gate distribution);
    - ``model_axis`` (Megatron tensor parallelism): per-block attention
      and FFN weights shard the head/hidden dim — ``Wqkv [d,3,d]``
      last-dim (whole heads per shard), ``Wo [d,d]`` first-dim
      (row-split + psum), ``W1 [d,ff]`` last-dim, ``W2 [ff,d]``
      first-dim (row-split + psum); the token-wise leaves (LN, embed,
      pos, head) replicate. Optimizer state follows via state_pspecs.
    """
    from jax.sharding import PartitionSpec as P

    tp_specs = _tp_leaf_specs(model_axis)
    out = {}
    for name, shape in param_shapes(spec).items():
        if expert_axis and any(name.endswith(s) for s in _EXPERT_LEAVES):
            out[name] = P(expert_axis, *([None] * (len(shape) - 1)))
        elif model_axis and name.startswith("L"):
            leaf = name.split("_", 1)[1]
            out[name] = tp_specs.get(leaf, P())
        else:
            out[name] = P()
    return out


def _layer_norm(x, g, b):
    """Reference LayerNorm (f32 statistics and output; rank-agnostic —
    rank-2 [N, D] and rank-3 [B, S, D] both normalize axis -1). The
    oracle the fused Pallas kernel is tested against."""
    x = x.astype(jnp.float32)
    mu = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x - mu), axis=-1, keepdims=True)
    return (x - mu) * jax.lax.rsqrt(var + 1e-6) * g.astype(jnp.float32) \
        + b.astype(jnp.float32)


def _ln(spec: TransformerSpec, x, g, b):
    """The model's LayerNorm dispatch: the fused Pallas kernel
    (forward AND backward, interpret mode on CPU) under
    ``spec.fused_ln``, the XLA reference otherwise. Every transformer
    LN call site (block ln1/ln2, final lnf, the pipeline/1f1b heads
    and the rank-2 decode sites) routes through here, wrapped in the
    ``ln`` trace scope so profiler timelines name the op."""
    with jax.named_scope("ln"):
        if spec.fused_ln:
            from ..ops.pallas_fused import fused_layer_norm

            return fused_layer_norm(x, g, b)
        return _layer_norm(x, g, b)


def _ln_residual(spec: TransformerSpec, h, branch, g, b):
    """Residual add + the LayerNorm that consumes it:
    ``s = h + branch; return (LN(s), s)``. Under ``spec.fused_ln`` the
    add rides inside the Pallas kernel (one HBM pass); the reference
    path computes the identical math with XLA ops."""
    with jax.named_scope("ln"):
        if spec.fused_ln:
            from ..ops.pallas_fused import fused_layer_norm_residual

            return fused_layer_norm_residual(h, branch, g, b)
        s = h + branch
        return _layer_norm(s, g, b), s


def _attend(spec: TransformerSpec, q, k, v, seq_axis: str | None):
    """[B, S(local), H, Dh] in/out via the selected backend.

    With ``seq_axis`` set (sequence-parallel training inside shard_map)
    attention runs in the layout ``spec.sp_impl`` selects: the RING —
    k/v blocks travel between shards via ppermute while each block
    pair is computed locally (``ring_flash_attention`` uses the Pallas
    kernels where the local block is tile-aligned, the exact XLA ring
    otherwise) — or ULYSSES — two all_to_alls re-shard seq<->heads so
    each shard runs ordinary full-sequence attention on H/n heads
    (ops/ulysses_attention)."""
    if seq_axis is not None:
        if spec.sp_impl == "ulysses":
            from ..ops.ulysses_attention import ulysses_attention

            return ulysses_attention(q, k, v, seq_axis, causal=spec.causal,
                                     use_flash=spec.attention == "flash")
        if spec.sp_impl != "ring":
            raise ValueError(
                f"unknown sp_impl {spec.sp_impl!r}: expected 'ring' or "
                f"'ulysses'")
        from ..ops.ring_attention import ring_attention, ring_flash_attention

        ring = (ring_flash_attention if spec.attention == "flash"
                else ring_attention)
        return ring(q, k, v, seq_axis, causal=spec.causal)
    if spec.attention == "flash":
        from ..ops.flash_attention import flash_attention

        return flash_attention(q, k, v, spec.causal)
    from ..ops.ring_attention import attention

    return attention(q, k, v, causal=spec.causal)


def _load_balance_loss(spec: TransformerSpec, probs, top1_idx, axes=()):
    """Switch Transformer's load-balance auxiliary loss for one MoE
    block: ``E * sum_e f_e * P_e`` where ``f_e`` is the fraction of
    tokens whose FIRST routing choice is expert e (non-differentiable
    counts) and ``P_e`` the mean router probability mass on e
    (differentiable) — minimized (value 1) by a uniform router, its
    gradient pushes probability off overloaded experts. ``probs`` is
    [..., E] over any leading token dims.

    ``axes``: mesh axes the TOKENS are sharded over inside shard_map
    (data, seq, and — sparse dispatch — expert). f and P are pmean'd
    over them BEFORE combining, so every shard adds the
    global-batch aux value and N-shard training matches the
    single-device objective exactly (mean of per-shard products would
    not)."""
    e = spec.num_experts
    flat = probs.reshape(-1, e)
    f = jnp.mean(jax.nn.one_hot(top1_idx.reshape(-1), e,
                                dtype=jnp.float32), axis=0)
    p = jnp.mean(flat, axis=0)
    if axes:
        f = jax.lax.pmean(f, axes)
        p = jax.lax.pmean(p, axes)
    return e * jnp.sum(f * p)


def _balance_stats(spec: TransformerSpec, probs, top1_idx):
    """Raw per-block load-balance statistics ``[2, E]`` = (f, P): the
    top-1 routing fraction and the mean router probability, as LOCAL
    token means with no pmean — the pipeline path accumulates these
    across microbatch ticks and combines once at the end
    (_load_balance_loss is the combine-now form the flat path uses;
    both are means over equal token populations, so
    mean-over-microbatches-then-pmean equals the flat global mean
    exactly)."""
    e = spec.num_experts
    f = jnp.mean(jax.nn.one_hot(top1_idx.reshape(-1), e,
                                dtype=jnp.float32), axis=0)
    p = jnp.mean(probs.reshape(-1, e), axis=0)
    return jnp.stack([f, p])


def _route_topk(spec: TransformerSpec, probs):
    """(gates [..., k], idx [..., k]) — the router's top-k choices.
    Top-1 keeps the raw winning probability as the gate (Switch
    Transformer); k > 1 renormalizes the gates among the selected
    experts (the GShard top-2 convention). Differentiable through the
    gate values (the selection itself is a hard argmax, as in both
    papers)."""
    gates, idx = jax.lax.top_k(probs, spec.moe_topk)
    if spec.moe_topk > 1:
        gates = gates / jnp.sum(gates, axis=-1, keepdims=True)
    return gates, idx


def _moe_ffn(spec: TransformerSpec, bp: Params, a, act, cdt,
             expert_axis: str | None, aux_axes=(),
             aux_stats: bool = False):
    """Top-k mixture-of-experts FFN for one block (dense dispatch).
    ``bp`` holds the block's UNPREFIXED leaves (Wr, We1, be1, We2,
    be2) — the same view _block_forward passes for attention, so the
    flat forward, the KV-cached decode and the pipeline's scan-carried
    stacked leaves all feed the identical body.

    Exact "dense dispatch": every (local) expert runs on every token
    and the router's gate-weighted selection combines — no capacity
    factor, no dropped tokens, fully differentiable through the gate
    probabilities. Under expert parallelism (``expert_axis``) each
    shard holds E/n experts' weights and computes ONLY those (1/n of
    the expert FLOPs and memory); the selection weights are sliced by
    the shard's expert offset and the partial outputs combine with one
    psum. (``_moe_ffn_sparse`` is the capacity-limited all-to-all
    realization of the same math, selected by
    ``moe_dispatch='alltoall'``; this dense form trades its
    compute/bandwidth savings for exactness.)
    """
    with jax.named_scope("moe_dispatch"):
        gate_logits = jnp.dot(
            a.astype(cdt), bp["Wr"].astype(cdt),
            preferred_element_type=jnp.float32)           # [B, S, E]
        probs = jax.nn.softmax(gate_logits, axis=-1)
        gates, idx = _route_topk(spec, probs)             # [B, S, k]
        # gate-weighted selection: sum of k weighted one-hots
        sel = jnp.sum(
            jax.nn.one_hot(idx, spec.num_experts, dtype=jnp.float32)
            * gates[..., None], axis=-2)                  # [B, S, E]
    we1, be1 = bp["We1"], bp["be1"]
    we2, be2 = bp["We2"], bp["be2"]
    if expert_axis is not None:
        off = jax.lax.axis_index(expert_axis) * we1.shape[0]
        sel = jax.lax.dynamic_slice_in_dim(sel, off, we1.shape[0],
                                           axis=2)
    with jax.named_scope("moe_expert"):
        h1 = jnp.einsum("bsd,edf->bsef", a.astype(cdt), we1.astype(cdt),
                        preferred_element_type=jnp.float32) \
            + be1.astype(jnp.float32)
        h1 = act(h1).astype(cdt)
        h2 = jnp.einsum("bsef,efd->bsed", h1, we2.astype(cdt),
                        preferred_element_type=jnp.float32) \
            + be2.astype(jnp.float32)
    with jax.named_scope("moe_dispatch"):
        out = jnp.einsum("bsed,bse->bsd", h2, sel)
    if expert_axis is not None:
        out = jax.lax.psum(out, expert_axis)
    aux = (_balance_stats(spec, probs, idx[..., 0]) if aux_stats
           else _load_balance_loss(spec, probs, idx[..., 0], aux_axes))
    return out, aux


def _sparse_route(spec: TransformerSpec, x, wr, cdt):
    """Router + slotting + scatter: the DISPATCH half of the sparse
    MoE FFN, split out so the bench can time it against the expert
    matmul (the moe_wide dispatch-vs-expert breakdown).

    ``x`` [T, d] -> ``(buf [E, C, d], slot [k*T], gates [T, k],
    keep [k*T], probs [T, E], idx [T, k])`` with capacity
    ``C = ceil(capacity_factor * T * k / E)``.

    Each of a token's k routing choices goes to one expert buffer
    (position assigned by a stable argsort over the routing choices —
    O(kT·log(kT)), E-independent; tokens past capacity are dropped —
    their FFN contribution is zero and the residual stream carries
    them, exactly Switch Transformer's overflow semantics)."""
    import math

    t, d = x.shape
    e = spec.num_experts
    k = spec.moe_topk
    cap = max(1, math.ceil(spec.capacity_factor * t * k / e))
    gate_logits = jnp.dot(
        x.astype(cdt), wr.astype(cdt),
        preferred_element_type=jnp.float32)                 # [T, E]
    probs = jax.nn.softmax(gate_logits, axis=-1)
    gates, idx = _route_topk(spec, probs)                   # [T, k]
    # each (token, choice) pair is its own dispatch unit, flattened
    # RANK-major ([k, T]): every token's FIRST choice claims buffer
    # space before any token's second choice — the GShard priority
    # rule (under overflow a high-gate first choice must never lose
    # its slot to an earlier token's low-gate runner-up)
    flat_e = idx.T.reshape(k * t)
    # position of each unit within its expert's buffer (0-based,
    # arrival order = rank then token), by STABLE argsort instead of a
    # [k*T, E] one-hot cumsum (VERDICT r4 next #6: that was O(k·T·E)
    # work/memory, linear in E — this is O(kT·log kT), E-independent):
    # sorting groups units by expert while the stable tie-break keeps
    # them in priority (index) order, so a unit's buffer position is
    # its sorted rank minus its expert group's first sorted rank
    # (found by searchsorted on the sorted keys). Routing then runs
    # via scatter/gather on a flat [E*C] slot index — NOT the
    # [T, E, C] one-hot dispatch tensor (cf*T^2 — it OOMs the moment a
    # big eval batch walks through; overflow and out-slot both land in
    # a trash row past the buffer)
    order = jnp.argsort(flat_e, stable=True)                # [k*T]
    sorted_e = flat_e[order]
    group_start = jnp.searchsorted(sorted_e, sorted_e, side="left")
    pos_sorted = jnp.arange(k * t, dtype=jnp.int32) - group_start
    pos = jnp.zeros((k * t,), jnp.int32).at[order].set(pos_sorted)
    keep = pos < cap
    slot = jnp.where(keep, flat_e * cap + pos, e * cap)
    xk = jnp.broadcast_to(x[None].astype(jnp.float32),
                          (k, t, d)).reshape(k * t, d)
    buf = jnp.zeros((e * cap + 1, d), jnp.float32)
    buf = buf.at[slot].add(xk)[:-1].reshape(e, cap, d)
    return buf, slot, gates, keep, probs, idx


def _grouped_expert_ffn(spec: TransformerSpec, buf, we1, be1, we2, be2,
                        act, cdt):
    """The grouped per-expert two-matmul FFN ``[El, C, d] -> [El, C,
    d]`` (f32 out) — the EXPERT half of the sparse MoE block. Under
    ``spec.grouped_moe`` it runs the fused Pallas kernel
    (ops/pallas_fused.moe_grouped_matmul: one kernel loops (expert,
    capacity-tile) grid cells, weights and the [tile, ff] hidden
    resident in VMEM); otherwise two batched XLA einsums with the
    [El, C, ff] hidden round-tripping HBM between them.  Under
    ``spec.fp8_ffn`` the SAME fused kernel consumes fp8-e4m3-rounded
    operands with per-expert pow2 scales (ops/pallas_fused.
    fp8_grouped_matmul) — exact fp8-MXU numerics, straight-through
    gradients to the master weights."""
    if spec.fp8_ffn:
        from ..ops.pallas_fused import fp8_grouped_matmul

        return fp8_grouped_matmul(spec.activation, cdt, buf,
                                  we1, be1, we2, be2)
    if spec.grouped_moe:
        from ..ops.pallas_fused import moe_grouped_matmul

        return moe_grouped_matmul(spec.activation, cdt, buf,
                                  we1, be1, we2, be2)
    h1 = act(jnp.einsum("ecd,edf->ecf", buf.astype(cdt), we1.astype(cdt),
                        preferred_element_type=jnp.float32)
             + be1[:, None].astype(jnp.float32)).astype(cdt)
    return jnp.einsum("ecf,efd->ecd", h1, we2.astype(cdt),
                      preferred_element_type=jnp.float32) \
        + be2[:, None].astype(jnp.float32)


def _sparse_combine(h2, slot, gates, keep):
    """Gather each (token, choice)'s processed row from its slot
    (trash row = 0 for dropped units), gate-weight, and sum over the k
    choices — the return half of the dispatch. ``h2`` is any
    [E*C, d]-reshapeable expert output; returns [T, d]."""
    t, k = gates.shape
    d = h2.shape[-1]
    h2_flat = jnp.concatenate(
        [h2.reshape(-1, d), jnp.zeros((1, d), h2.dtype)])
    picked = h2_flat[slot].reshape(k, t, d)
    w = gates.T * keep.astype(jnp.float32).reshape(k, t)
    return jnp.sum(picked * w[..., None], axis=0)


def _moe_ffn_sparse(spec: TransformerSpec, bp: Params, a, act,
                    cdt, expert_axis: str | None, aux_axes=(),
                    aux_stats: bool = False):
    """Capacity-limited token dispatch for the top-k MoE FFN — the
    sparse (Switch/GShard-style) realization of the same math as
    ``_moe_ffn``'s dense dispatch, composed from ``_sparse_route`` ->
    ``_grouped_expert_ffn`` -> ``_sparse_combine`` (each timed
    separately by the moe_wide bench breakdown and scoped
    ``moe_dispatch``/``moe_expert`` in profiler traces).

    Under expert parallelism the ``[E, C, d]`` buffers are exchanged
    with ONE ``all_to_all`` each way over the 'expert' axis, so every
    shard runs only its E/n experts on the tokens routed to them from
    all data positions: compute AND bandwidth scale with
    ``capacity_factor``, not with E — the sparse optimization the
    dense dispatch trades for exactness. With ample capacity
    (``cf >= E``) nothing drops and the result equals dense dispatch
    bit-for-near (fp order aside).
    """
    b, s, d = a.shape
    t = b * s
    e = spec.num_experts
    x = a.reshape(t, d)
    with jax.named_scope("moe_dispatch"):
        buf, slot, gates, keep, probs, idx = _sparse_route(
            spec, x, bp["Wr"], cdt)
    cap = buf.shape[1]

    we1, be1 = bp["We1"], bp["be1"]                         # [El, d, ff]
    we2, be2 = bp["We2"], bp["be2"]
    el = we1.shape[0]
    if expert_axis is not None and el != e:
        ep = e // el
        # [ep, El, C, d]: send expert-group j to the shard owning it;
        # receive every data shard's buffer for MY experts,
        # concatenated along the capacity axis
        buf = jax.lax.all_to_all(buf.reshape(ep, el, cap, d), expert_axis,
                                 split_axis=0, concat_axis=2, tiled=True)
        buf = buf.reshape(el, ep * cap, d)
    with jax.named_scope("moe_expert"):
        h2 = _grouped_expert_ffn(spec, buf, we1, be1, we2, be2, act,
                                 cdt)                       # [El, ep*C, d]
    if expert_axis is not None and el != e:
        # reverse exchange: hand each shard back its tokens' outputs
        h2 = jax.lax.all_to_all(h2.reshape(el, ep, cap, d), expert_axis,
                                split_axis=1, concat_axis=0, tiled=True)
    with jax.named_scope("moe_dispatch"):
        out = _sparse_combine(h2, slot, gates, keep)
    aux = (_balance_stats(spec, probs, idx[:, 0]) if aux_stats
           else _load_balance_loss(spec, probs, idx[:, 0], aux_axes))
    return out.reshape(b, s, d), aux


def tokenize(spec: TransformerSpec, x: jnp.ndarray) -> jnp.ndarray:
    """Discretize float inputs in [0, 1] to int tokens ([B, S] from
    [B, S] or [B, S, 1]) — the lm objective's vocabulary (image-GPT
    style: one token per input scalar)."""
    v = spec.vocab_size
    flat = x.reshape(x.shape[0], -1)
    return jnp.clip(jnp.round(flat * (v - 1)), 0, v - 1).astype(jnp.int32)


def _mm(params_or_bp, a, w_name, b_name, cdt):
    acc = jnp.dot(a.astype(cdt), params_or_bp[w_name].astype(cdt),
                  preferred_element_type=jnp.float32)
    return acc + params_or_bp[b_name].astype(jnp.float32)


def _dropout(h, spec: TransformerSpec, rng, salt: int):
    """Inverted dropout: keep-mask / keep_prob, only when a training
    rng is provided (eval passes None and never drops). ``salt``
    decorrelates the sites within one forward."""
    if rng is None or not spec.dropout_rate:
        return h
    keep = 1.0 - spec.dropout_rate
    mask = jax.random.bernoulli(jax.random.fold_in(rng, salt), keep,
                                h.shape)
    return jnp.where(mask, h / keep, 0.0).astype(h.dtype)


def _row_psum(x, w, b, cdt, model_axis):
    """Row-split projection: local [.., k_local] @ [k_local, n], psum'd
    over ``model_axis`` (the partial-sum combine of Megatron's row
    parallelism), bias added once after the reduction."""
    acc = jnp.dot(x, w.astype(cdt), preferred_element_type=jnp.float32)
    if model_axis is not None:
        acc = jax.lax.psum(acc, model_axis)
    return acc + b.astype(jnp.float32)


def _block_forward(spec: TransformerSpec, bp: Params, h, act, cdt,
                   seq_axis: str | None = None,
                   expert_axis: str | None = None, moe_block: int = 0,
                   model_axis: str | None = None, aux_axes=(),
                   dropout_rng=None, aux_stats: bool = False,
                   kv_out: list | None = None):
    """One encoder block on ``h`` [B, S(local), D]. ``bp`` holds the
    block's leaves under their UNPREFIXED names (ln1_g, Wqkv, ...) so
    the same body serves the regular forward (dict views of L{i}_*)
    and the pipelined forward (lax.scan over stacked stages). Returns
    ``(h, aux)`` — aux is the block's MoE load-balance loss (0.0 for
    the dense FFN).  ``kv_out``: a list to append this block's
    ``(k, v)`` [B, S, Hl, Dh] to — the serving prefill captures the
    training forward's exact keys/values into the paged cache this
    way, so prefill and decode cannot drift.

    Under tensor parallelism (``model_axis``) the leaves arrive as
    their Megatron shards: Wqkv/bqkv hold this shard's heads (dl =
    d/mp trailing columns), Wo its matching rows, W1/b1 the hidden
    slice, W2 its rows — attention and the FFN inner product run on
    1/mp of the width with ONE psum after each row-split matmul."""
    b, s, d = h.shape
    a = _ln(spec, h, bp["ln1_g"], bp["ln1_b"])
    # [B, S, 3, dl]: t indexes q/k/v, e the (local) head columns
    qkv = jnp.einsum("bsd,dte->bste", a.astype(cdt),
                     bp["Wqkv"].astype(cdt),
                     preferred_element_type=jnp.float32) \
        + bp["bqkv"].astype(jnp.float32)
    q, k, v = (qkv[:, :, t].astype(cdt) for t in range(3))
    local_heads = bp["Wqkv"].shape[-1] // spec.d_head
    shape = (b, s, local_heads, spec.d_head)
    if kv_out is not None:
        kv_out.append((k.reshape(shape), v.reshape(shape)))
    att = _attend(spec, q.reshape(shape), k.reshape(shape),
                  v.reshape(shape), seq_axis)
    branch = _dropout(
        _row_psum(att.reshape(b, s, -1).astype(cdt), bp["Wo"],
                  bp["bo"], cdt, model_axis),
        spec, dropout_rng, 2 * moe_block)
    # the attention residual add fuses into ln2 (one kernel pass under
    # --fused_ln); the pre-normalized activations flow to _ffn_block
    # so it skips its own LN
    a2, h = _ln_residual(spec, h, branch, bp["ln2_g"], bp["ln2_b"])
    return _ffn_block(spec, bp, h, act, cdt, model_axis,
                      moe_block, expert_axis, aux_axes, dropout_rng,
                      aux_stats, a=a2)


def _ffn_block(spec: TransformerSpec, bp: Params, h, act, cdt,
               model_axis=None,
               moe_block: int = 0, expert_axis=None, aux_axes=(),
               dropout_rng=None, aux_stats: bool = False, a=None):
    """The LN2 + FFN (dense or MoE) residual half of a block — shared
    by the training forward and the KV-cached decode step so the two
    cannot drift. ``h`` [B, S, D] -> (h, aux). ``a``: pre-computed
    ln2 output (_block_forward fuses the attention residual add into
    it); None computes it here (the decode path)."""
    if a is None:
        a = _ln(spec, h, bp["ln2_g"], bp["ln2_b"])
    aux = (jnp.zeros((2, spec.num_experts), jnp.float32) if aux_stats
           else jnp.float32(0.0))
    if spec.num_experts:
        if spec.moe_dispatch == "alltoall":
            moe = _moe_ffn_sparse
        elif spec.moe_dispatch == "dense":
            moe = _moe_ffn
        else:
            raise ValueError(
                f"unknown moe_dispatch {spec.moe_dispatch!r}: expected "
                f"'dense' or 'alltoall'")
        ffn, aux = moe(spec, bp, a, act, cdt, expert_axis, aux_axes,
                       aux_stats)
        h = h + _dropout(ffn, spec, dropout_rng, 2 * moe_block + 1)
    elif spec.fp8_ffn:
        # fp8-rounded operands through the fused grouped kernel
        # (ops/pallas_fused.fp8_dense_ffn); the per-tensor pow2 scales
        # cover the FULL d/d_ff contraction, which tensor parallelism
        # would row-split — config.validate_quant_config rejects the
        # combination, and this guard keeps direct callers honest
        if model_axis is not None:
            raise ValueError("fp8_ffn does not compose with tensor "
                             "parallelism (the row-split FFN shards "
                             "the contraction its scales cover)")
        from ..ops.pallas_fused import fp8_dense_ffn

        bsz, s, d = a.shape
        ffn = fp8_dense_ffn(spec.activation, cdt, a.reshape(bsz * s, d),
                            bp["W1"], bp["b1"], bp["W2"],
                            bp["b2"]).reshape(bsz, s, -1)
        h = h + _dropout(ffn, spec, dropout_rng, 2 * moe_block + 1)
    else:
        a = act(_mm(bp, a, "W1", "b1", cdt)).astype(cdt)
        h = h + _dropout(
            _row_psum(a, bp["W2"], bp["b2"], cdt, model_axis),
            spec, dropout_rng, 2 * moe_block + 1)
    return h, aux


def apply(spec: TransformerSpec, params: Params, x: jnp.ndarray,
          seq_axis: str | None = None,
          expert_axis: str | None = None,
          model_axis: str | None = None,
          with_aux: bool = False, aux_axes=(),
          dropout_rng=None) -> jnp.ndarray:
    """Forward to logits. ``x``: [B, input_size] (viewed as seq_len
    tokens) or already [B, S, F].

    ``seq_axis`` enables sequence parallelism inside shard_map: ``x``
    arrives as this shard's contiguous block of the token axis
    ([B, input_size/n]); positional embeddings are sliced by the
    shard's global offset, attention runs over the ppermute ring, the
    token-wise blocks (LN/FFN/residuals) need no communication, and
    the mean-pool is completed with a pmean across shards — after
    which the logits are sequence-invariant on every shard.

    ``model_axis`` enables Megatron tensor parallelism inside
    shard_map: the per-block attention/FFN leaves arrive width-sharded
    (param_pspecs with model_axis), each shard computes its heads and
    hidden slice, and the two row-split projections psum — activations
    stay full-width and replicated across the model axis, so the
    embed/LN/head plumbing is untouched.
    """
    cdt = spec.compute_dtype
    b = x.shape[0]
    s, f, d = spec.seq_len, spec.d_feature, spec.d_model
    if seq_axis is not None:
        n_shards = jax.lax.psum(1, seq_axis)
        s = spec.seq_len // n_shards

    pos = params["pos"].astype(jnp.float32)
    if seq_axis is not None:
        # this shard's slice of the global positional table
        off = jax.lax.axis_index(seq_axis) * s
        pos = jax.lax.dynamic_slice_in_dim(pos, off, s, axis=0)
    if spec.objective == "lm":
        # vocab-embedding lookup of the discretized tokens
        tokens = tokenize(spec, x)                        # [B, s]
        h = params["W_emb"].astype(jnp.float32)[tokens] + pos[None]
    else:
        h = x.reshape(b, s, f).astype(cdt)
        h = _mm(params, h, "W_in", "b_in", cdt) + pos[None]
    act = _ACTIVATIONS[spec.activation]
    h = _dropout(h, spec, dropout_rng, 0x9999)   # embedding dropout
    aux = jnp.float32(0.0)
    for i in range(spec.num_blocks):
        bp = {k[len(f"L{i}_"):]: v for k, v in params.items()
              if k.startswith(f"L{i}_")}
        h, aux_i = _block_forward(spec, bp, h, act, cdt, seq_axis,
                                  expert_axis, moe_block=i,
                                  model_axis=model_axis,
                                  aux_axes=aux_axes,
                                  dropout_rng=dropout_rng)
        aux = aux + aux_i
    h = _ln(spec, h, params["lnf_g"], params["lnf_b"])
    if spec.objective == "lm":
        # per-position vocab logits [B, s(local), V] — no pooling; the
        # next-token loss (parallel/step._lm_loss_and_acc) consumes
        # the full sequence
        logits = _mm(params, h, "W_head", "b_head",
                     cdt).astype(jnp.float32)
    else:
        pooled = jnp.mean(h, axis=1)                      # [B, D]
        if seq_axis is not None:
            # complete the global token mean; logits become
            # seq-invariant
            pooled = jax.lax.pmean(pooled, seq_axis)
        logits = _mm(params, pooled, "W_head", "b_head",
                     cdt).astype(jnp.float32)
    if with_aux:
        # per-block mean of the MoE load-balance loss
        return logits, aux / spec.num_blocks
    return logits


_BLOCK_LEAVES = ("ln1_g", "ln1_b", "Wqkv", "bqkv", "Wo", "bo",
                 "ln2_g", "ln2_b", "W1", "b1", "W2", "b2")
_BLOCK_LEAVES_MOE = ("ln1_g", "ln1_b", "Wqkv", "bqkv", "Wo", "bo",
                     "ln2_g", "ln2_b", "Wr", "We1", "be1", "We2", "be2")


def _block_leaf_names(spec: TransformerSpec) -> tuple:
    """The per-block leaf set the pipeline stacks — dense FFN or MoE."""
    return _BLOCK_LEAVES_MOE if spec.num_experts else _BLOCK_LEAVES


def _pipeline_block_order(num_blocks: int, n_stages: int,
                          virtual: int) -> list:
    """Stacked-position -> logical-block map. virtual == 1: identity
    (each stage's contiguous shard = its contiguous blocks, any stage
    count dividing num_blocks). virtual > 1 (Megatron interleaved
    stages): stage ``s`` executes chunks ``c*p + s`` (each chunk =
    num_blocks/(p*v) consecutive logical blocks), so stacked position
    ``s*K + c*k + i`` must hold logical block ``(c*p + s)*k + i`` —
    the contiguous per-stage shard then contains stage s's v chunks in
    execution order."""
    if virtual <= 1:
        return list(range(num_blocks))
    k = num_blocks // (n_stages * virtual)
    order = []
    for s in range(n_stages):
        for c in range(virtual):
            j0 = (c * n_stages + s) * k
            order.extend(range(j0, j0 + k))
    return order


def pipeline_stack_params(spec: TransformerSpec, params: Params,
                          n_stages: int = 1, virtual: int = 1) -> Params:
    """Regroup the flat ``L{i}_*`` block leaves into stacked
    ``blk_*`` arrays with a leading ``[num_blocks, ...]`` dim — the
    layout pipeline parallelism shards ``P('stage')`` on (each stage
    holds its contiguous num_blocks/n_stages slice). Embed/head/final-
    LN leaves stay replicated under their own names. With
    ``virtual > 1`` the stacking order is the interleaved permutation
    (_pipeline_block_order), so checkpoints of interleaved runs are
    restorable only at the same (n_stages, virtual). MoE blocks (r4)
    stack their router/expert leaves the same way."""
    out = {k: v for k, v in params.items() if not k.startswith("L")}
    order = _pipeline_block_order(spec.num_blocks, n_stages, virtual)
    for leaf in _block_leaf_names(spec):
        out[f"blk_{leaf}"] = jnp.stack(
            [params[f"L{j}_{leaf}"] for j in order])
    return out


def pipeline_unstack_params(spec: TransformerSpec, stacked: Params,
                            n_stages: int = 1, virtual: int = 1) -> Params:
    """Inverse of pipeline_stack_params (same (n_stages, virtual)).
    Note checkpoints of PP runs store the STACKED layout — with
    virtual == 1 stage-count-agnostic (any stage count dividing
    num_blocks restores it), with virtual > 1 pinned to the run's
    (n_stages, virtual) — and NOT interchangeable with the flat non-PP
    layout; this inverse serves tests, sampling and conversions."""
    out = {k: v for k, v in stacked.items() if not k.startswith("blk_")}
    order = _pipeline_block_order(spec.num_blocks, n_stages, virtual)
    for leaf in _block_leaf_names(spec):
        for pos, j in enumerate(order):
            out[f"L{j}_{leaf}"] = stacked[f"blk_{leaf}"][pos]
    return out


def pipeline_train_state(spec: TransformerSpec, optimizer, state,
                         n_stages: int = 1, virtual: int = 1):
    """Re-layout a freshly created TrainState for pipeline parallelism:
    stacked block params with optimizer slots initialized on the
    stacked layout — the one place the PP state shape is defined."""
    from ..train.state import TrainState

    stacked = pipeline_stack_params(spec, state.params, n_stages, virtual)
    return TrainState(step=state.step, params=stacked,
                      opt_state=optimizer.init(stacked))


def pipeline_param_pspecs(spec: TransformerSpec, stage_axis: str,
                          model_axis: str | None = None,
                          expert_axis: str | None = None,
                          ) -> Dict[str, "jax.sharding.PartitionSpec"]:
    """Specs for the stacked layout: blk_* shard their block dim over
    ``stage_axis``, with the per-leaf INNER spec taken from the
    canonical flat-layout param_pspecs — so PPxTP shards the Megatron
    head/hidden dims and (r4) PPxEP shards the stacked expert leaves'
    E dim over the expert axis; everything else replicated."""
    from jax.sharding import PartitionSpec as P

    base = param_pspecs(spec, expert_axis=expert_axis,
                        model_axis=model_axis)
    shapes = param_shapes(spec)
    out = {}
    for name in shapes:
        if name.startswith("L0_"):
            leaf = name[len("L0_"):]
            inner = tuple(base[name]) or (None,) * len(shapes[name])
            out[f"blk_{leaf}"] = P(stage_axis, *inner)
        elif not name.startswith("L"):
            out[name] = P()
    return out


def apply_pipeline(spec: TransformerSpec, params: Params, x: jnp.ndarray,
                   stage_axis: str, n_stages: int,
                   num_microbatches: int,
                   model_axis: str | None = None,
                   virtual: int = 1,
                   head_fn=None, head_width: int | None = None,
                   seq_axis: str | None = None,
                   expert_axis: str | None = None,
                   with_aux: bool = False, aux_axes=(),
                   dropout_rng=None,
                   slot_remat: bool = False) -> jnp.ndarray:
    """Pipeline-parallel forward inside shard_map: GPipe microbatch
    schedule at ``virtual == 1``, Megatron interleaved virtual stages
    at ``virtual > 1``.

    ``params`` is the stacked layout (pipeline_stack_params with the
    same (n_stages, virtual)) with the block dim sharded over
    ``stage_axis``: each stage holds ``virtual`` chunks of
    num_blocks/(n_stages*virtual) consecutive logical blocks (stage s
    owns chunks ``c*p + s``), applied per-tick by a lax.scan over the
    chunk's blocks. The local batch splits into ``num_microbatches``;
    at tick t stage s runs work-slot ``ts = t - s`` — chunk
    ``c = (ts//p) % v``, microbatch ``m = (ts//(p*v))*p + ts%p`` — and
    hands its activations to stage s+1 mod p with a single ppermute
    (the wrap hop carries chunk c's output of the last stage into
    chunk c+1 on stage 0 exactly one tick later, so one uniform
    schedule covers both modes; at v=1 it degenerates to GPipe's
    ``m = t - s``). Ticks = v*M + p - 1 of 1/v the per-stage work:
    relative bubble = (p-1)/(v*M + p - 1), the interleaved schedule's
    v-fold bubble shrink over GPipe at the price of v times the
    ppermute traffic.

    Stage 0 embeds microbatches entering chunk 0 (classify W_in or the
    lm vocab-embedding lookup); the LAST stage of the LAST chunk runs
    ``head_fn(params, h_out [mb, S, D], m) -> [mb, head_width]``
    (default: pooled classify logits, head_width = num_classes — the
    lm path passes its loss-statistics head from parallel/step so the
    per-position [mb, S, V] logits are reduced to per-example numbers
    ON the last stage instead of psum-broadcasting a vocab-wide
    tensor). Collected values are psum-shared so every stage returns
    an identical [B, head_width] array. The backward pass is jax.grad
    through this forward: shard_map transposes each ppermute into the
    reverse hop, which IS the reverse pipeline schedule.

    ``seq_axis`` (r4): PP x SP — ``x`` arrives with its token axis
    sharded over the inner seq axis; every pipeline chunk runs
    ring/Ulysses attention across the seq shards (via _block_forward's
    seq_axis plumbing), positional embeddings slice by the shard's
    global offset, the stage-hop ppermutes carry [mb, S/n_seq, D]
    blocks, and the classify pool completes with a seq pmean.

    ``with_aux`` (r5): returns ``(out, aux)`` with aux the per-block
    MEAN MoE load-balance loss, exactly the flat forward's objective:
    each live tick accumulates its chunk's raw (f, P) router
    statistics (_balance_stats) into a [v, K, 2, E] buffer; after the
    tick loop the microbatch means are pmean'd over ``aux_axes`` (the
    token-sharding axes) and combined E*sum(f*P) per block, summed
    over this stage's blocks and psum'd over ``stage_axis`` — f and P
    are token means over equal microbatches, so
    mean-over-microbatches == the flat full-batch mean exactly, and
    the value is identical on every shard.
    """
    cdt = spec.compute_dtype
    b = x.shape[0]
    s, d = spec.seq_len, spec.d_model
    if seq_axis is not None:
        # psum(1, axis) of a mesh axis is a compile-time constant, so
        # the local length is static and usable in reshape shapes
        s = s // jax.lax.psum(1, seq_axis)
    p, v, m_cnt = n_stages, virtual, num_microbatches
    if b % m_cnt:
        raise ValueError(
            f"local batch {b} must divide into microbatches={m_cnt}")
    if v < 1:
        raise ValueError(f"virtual={v} must be >= 1")
    if v > 1 and p < 2:
        # the chunk wrap hop is a ppermute, gated on p > 1: with one
        # stage, chunks beyond the first would silently consume stale
        # zero activations (the driver validates this; library callers
        # must hit the same wall)
        raise ValueError(
            f"virtual={v} needs n_stages >= 2 (nothing to interleave "
            f"on one stage)")
    if v > 1 and m_cnt % p:
        raise ValueError(
            f"interleaved stages need microbatches ({m_cnt}) divisible "
            f"by n_stages ({p})")
    if spec.num_blocks % (p * v):
        raise ValueError(
            f"num_blocks={spec.num_blocks} must divide over "
            f"n_stages*virtual={p * v}")
    mb = b // m_cnt
    sidx = jax.lax.axis_index(stage_axis)
    act = _ACTIVATIONS[spec.activation]
    pos = params["pos"].astype(jnp.float32)
    if seq_axis is not None:
        # this seq shard's slice of the global positional table
        off = jax.lax.axis_index(seq_axis) * s
        pos = jax.lax.dynamic_slice_in_dim(pos, off, s, axis=0)

    if spec.objective == "lm":
        micro_t = tokenize(spec, x).reshape(m_cnt, mb, s)

        def embed(m):
            tok = jax.lax.dynamic_index_in_dim(micro_t, m, 0,
                                               keepdims=False)
            return params["W_emb"].astype(jnp.float32)[tok] + pos[None]
    else:
        micro = x.reshape(m_cnt, mb, s, spec.d_feature)

        def embed(m):
            x_t = jax.lax.dynamic_index_in_dim(
                micro, m, 0, keepdims=False).astype(cdt)
            return _mm(params, x_t, "W_in", "b_in", cdt) + pos[None]

    custom_head = head_fn is not None
    if not custom_head:
        head_width = spec.num_classes

        def head_fn(params_, h, m):
            hl = _ln(spec, h, params_["lnf_g"], params_["lnf_b"])
            pooled = jnp.mean(hl, axis=1)
            if seq_axis is not None:
                # complete the global token mean across seq shards
                pooled = jax.lax.pmean(pooled, seq_axis)
            return _mm(params_, pooled, "W_head", "b_head", cdt)
    elif head_width is None:
        raise ValueError("custom head_fn needs an explicit head_width")

    # local block leaves [K, ...] -> [v, K/v, ...]: chunk-major is the
    # stacking order _pipeline_block_order fixed at conversion time
    local_v = {k[len("blk_"):]: a.reshape(v, a.shape[0] // v,
                                          *a.shape[1:])
               for k, a in params.items() if k.startswith("blk_")}

    want_aux = bool(with_aux and spec.num_experts)
    kc = spec.num_blocks // (p * v)   # blocks per chunk

    def run_chunk(lv, c, h, rng_m):
        bp_c, base = _chunk_select(lv, c, sidx,
                                   spec.num_blocks // p, kc)

        def body(h_, bp_i):
            bp, i = bp_i
            h2_, aux_b = _block_forward(spec, bp, h_, act, cdt,
                                        seq_axis=seq_axis,
                                        expert_axis=expert_axis,
                                        moe_block=base + i,
                                        model_axis=model_axis,
                                        aux_stats=want_aux,
                                        dropout_rng=rng_m)
            return h2_, (aux_b if want_aux else None)

        h_, aux_c = jax.lax.scan(body, h, (bp_c, jnp.arange(kc)))
        return h_, aux_c   # aux_c: [K/v, 2, E] raw stats, or None

    # per-SLOT rematerialization (VERDICT r4 next #4, the
    # schedule-aware-freeing half): checkpointing each (tick, chunk)
    # slot means jax.grad's backward saves only every slot's INPUT
    # [mb, S, D] — M live input buffers per stage — and recomputes the
    # intra-slot residuals (attention stats, FFN hiddens: the ~10x
    # bigger set) one slot at a time in the reverse schedule. A
    # whole-forward jax.checkpoint cannot do this: its backward
    # re-runs the entire tick loop and then holds every recomputed
    # residual at once.
    chunk_fn = jax.checkpoint(run_chunk) if slot_remat else run_chunk

    # full-circle ppermute only when the wrap hop is live (v > 1)
    perm = ([(j, (j + 1) % p) for j in range(p)] if v > 1
            else [(j, j + 1) for j in range(p - 1)])
    recv = jnp.zeros((mb, s, d), jnp.float32)
    # Collection strategy by head kind: the cheap default classify
    # head runs per tick into a tiny [M, mb, C] buffer; a CUSTOM head
    # (the lm loss statistics, with an [mb, S, V] vocab projection
    # inside) collects the last stage's final-chunk activations
    # ([M, mb, S, D]) and runs ONCE per microbatch after the tick
    # loop, so the expensive head is never computed for a dead or
    # masked slot (a per-tick lax.cond can't express the skip: its
    # branches' manual-axes types differ under shard_map).
    # Memory note: the custom-head buffer is [M, mb, S, D] f32 = the
    # full local batch's final-chunk activations ON EVERY stage, though
    # non-last stages only ever write zeros — O(B*S*D) f32 per device
    # of dead memory on p-1 of p stages, accepted at current scales
    # (a last-stage-only collect needs shape-varying buffers shard_map
    # cannot express).
    if custom_head:
        collected = jnp.zeros((m_cnt, mb, s, d), jnp.float32)
    else:
        collected = jnp.zeros((m_cnt, mb, head_width), jnp.float32)
    aux_buf = (jnp.zeros((v, kc, 2, spec.num_experts), jnp.float32)
               if want_aux else None)
    total = v * m_cnt
    ticks = total + p - 1
    for t in range(ticks):
        ts = t - sidx
        live = jnp.logical_and(ts >= 0, ts < total)
        tsc = jnp.clip(ts, 0, total - 1)
        g, r = tsc // p, tsc % p
        c = (g % v).astype(jnp.int32)
        m = ((g // v) * p + r).astype(jnp.int32)
        # per-microbatch dropout stream (distinct masks per microbatch,
        # block salts distinct per stacked position)
        rng_m = (jax.random.fold_in(dropout_rng, m)
                 if dropout_rng is not None else None)
        # stage 0 ingests microbatch m into chunk 0; every other
        # (stage, chunk) consumes the ppermuted activations (dead
        # slots compute on stale values and are discarded by `live`)
        h_in = jnp.where(
            jnp.logical_and(jnp.equal(sidx, 0), jnp.equal(c, 0)),
            _dropout(embed(m), spec, rng_m, 0x9999), recv)
        h_out, aux_c = chunk_fn(local_v, c, h_in, rng_m)
        if want_aux:
            # accumulate this live slot's chunk stats (dead slots
            # computed on stale values: masked to zero)
            prev_a = jax.lax.dynamic_index_in_dim(aux_buf, c, 0,
                                                  keepdims=False)
            aux_buf = jax.lax.dynamic_update_index_in_dim(
                aux_buf, prev_a + jnp.where(live, 1.0, 0.0) * aux_c,
                c, 0)
        live_head = jnp.logical_and(live, jnp.logical_and(
            jnp.equal(sidx, p - 1), jnp.equal(c, v - 1)))
        val = (h_out if custom_head
               else head_fn(params, h_out, m).astype(jnp.float32))
        prev = jax.lax.dynamic_index_in_dim(collected, m, 0,
                                            keepdims=False)
        collected = jax.lax.dynamic_update_index_in_dim(
            collected, jnp.where(live_head, val, prev), m, 0)
        if p > 1 and t < ticks - 1:
            recv = _hop_start(h_out, stage_axis, perm)

    if custom_head:
        def head_m(_, h_and_m):
            h_m, m_i = h_and_m
            return None, head_fn(params, h_m, m_i).astype(jnp.float32)

        _, vals = jax.lax.scan(head_m, None,
                               (collected, jnp.arange(m_cnt)))
        # non-last stages ran the head on garbage zeros: mask them
        vals = jnp.where(jnp.equal(sidx, p - 1), vals, 0.0)
    else:
        vals = collected   # live_head already zeroed other stages
    out = jax.lax.psum(vals, stage_axis)
    out = out.reshape(b, head_width).astype(jnp.float32)
    if not with_aux:
        return out
    aux = jnp.float32(0.0)
    if want_aux:
        stats = aux_buf / m_cnt              # microbatch means
        f, pr = stats[:, :, 0], stats[:, :, 1]
        if aux_axes:
            f = jax.lax.pmean(f, aux_axes)
            pr = jax.lax.pmean(pr, aux_axes)
        local = spec.num_experts * jnp.sum(f * pr)
        aux = jax.lax.psum(local, stage_axis) / spec.num_blocks
    return out, aux


def pipeline_value_and_grad_1f1b(
        spec: TransformerSpec, params: Params, x: jnp.ndarray,
        stage_axis: str, n_stages: int, num_microbatches: int,
        loss_of, head_fn=None, head_width: int | None = None,
        model_axis: str | None = None, dropout_rng=None,
        batch_axes: tuple = (), virtual: int = 1):
    """1F1B pipeline schedule family (VERDICT r4 next #4; interleaved
    refinement r8): fused forward AND backward ticks so live
    microbatch activations cap at O(p·v) input buffers — M-independent
    — instead of ``jax.grad`` through the GPipe forward holding all M
    microbatches' residuals; at ``virtual > 1`` each stage round-robins
    ``v`` chunks of ``num_blocks/(p·v)`` consecutive blocks (Megatron
    interleaved stages), shrinking the pipeline bubble ~v-fold.

    The schedule is NOT derived here: the pure-Python tick table
    (parallel/pp_schedule.interleaved_1f1b_table — stage, tick,
    microbatch, fwd/bwd, virtual-chunk) is the one derivation, and
    this loop consumes it literally: each tick gathers its per-stage
    (live, chunk, microbatch, stash-slot, head) row — compile-time
    constants indexed by the traced stage id — and emits a forward
    sub-slot and/or a backward sub-slot ONLY when the table says some
    stage is live in that direction.  Warmup ticks are therefore
    forward-only and drain ticks backward-only (the specialization
    that makes the interleaved bubble shrink real in a lockstep SPMD
    program: a dead fused tick would still cost fwd+bwd compute), and
    the golden tests check schedule correctness against the same table
    with no mesh at all.  At v == 1 the table degenerates to the
    classic fused 1F1B (fwd ``m + s``, bwd ``m + 2(p-1) - s``,
    ``m + 2(p-1)`` ticks).

    Each live forward sub-slot stashes only its INPUT ``[mb, S, D]``
    (``pp_schedule.stash_cap`` = min(vM, 2pv-1) buffers, slot =
    fwd-unit % cap — reuse-safety is a checked table invariant); the
    backward sub-slot re-runs its slot under ``jax.vjp``
    (rematerialization: intra-slot residuals exist only inside that
    slot's backward).

    Stage hops are ASYNC start/done pairs: the activation hop
    ``s -> s+1`` (full-circle when v > 1 — the wrap carries the last
    stage's chunk-c output into chunk c+1 on stage 0 one tick later)
    is ISSUED right after the forward sub-slot and JOINED (consumed)
    only after the same tick's backward compute, and the gradient hop
    ``s+1 -> s`` issues after the backward and joins after the next
    tick's forward — each transfer's dependency window spans the
    opposite direction's compute (``_hop_start``/``_hop_join``,
    ``pp_comm`` trace scope), the same overlap discipline the input
    pipeline v2 applied to H2D.  Dead slots compute on placeholder
    indices; their loss/stat writes are masked and their vjp
    cotangents zeroed (vjp is linear in cotangents, so dead grads are
    exactly zero).

    ``loss_of(vals [mb, W], m) -> scalar`` is the per-microbatch loss
    contribution, normalized by the CALLER so the sum over microbatches
    equals the flat objective (classify: CE(mb)/M; lm:
    nll_sum/(B·(S-1))). ``head_fn`` as apply_pipeline (default: pooled
    classify logits). Gradients flow from sum_m loss_of on the last
    stage of the last chunk through the whole schedule.

    Returns ``((loss, stats [B, W]), grads)`` with grads summed over
    ``batch_axes`` (matching what shard_map's transpose produces for
    the jax.grad paths) and non-block leaves psum'd over
    ``stage_axis`` (each stage contributes its embed/head slice;
    blk_* leaves stay per-stage local).

    Composition scope: DP x PP x TP (any virtual). Sequence/expert
    sharding and the MoE balance loss keep the GPipe/interleaved
    jax.grad schedules (their gradient replication rides shard_map's
    transpose; this function manages replication manually). Dropout
    composes: the per-microbatch fold_in rng is recomputed
    bit-identically in the backward sub-slot.
    """
    cdt = spec.compute_dtype
    b = x.shape[0]
    s, d = spec.seq_len, spec.d_model
    p, v, m_cnt = n_stages, virtual, num_microbatches
    if b % m_cnt:
        raise ValueError(
            f"local batch {b} must divide into microbatches={m_cnt}")
    if spec.num_blocks % (p * v):
        raise ValueError(
            f"num_blocks={spec.num_blocks} must divide over "
            f"n_stages*virtual={p * v}")
    # the table's own validation covers v>=1, p>=2, and (v>1) m%p==0
    table = pp_schedule.interleaved_1f1b_table(p, v, m_cnt)
    mb = b // m_cnt
    sidx = jax.lax.axis_index(stage_axis)
    act = _ACTIVATIONS[spec.activation]
    kc = spec.num_blocks // (p * v)   # blocks per virtual chunk
    is0 = jnp.equal(sidx, 0)

    if spec.objective == "lm":
        micro_t = tokenize(spec, x).reshape(m_cnt, mb, s)

        def embed(prm, m):
            tok = jax.lax.dynamic_index_in_dim(micro_t, m, 0,
                                               keepdims=False)
            return (prm["W_emb"].astype(jnp.float32)[tok]
                    + prm["pos"].astype(jnp.float32)[None])
    else:
        micro = x.reshape(m_cnt, mb, s, spec.d_feature)

        def embed(prm, m):
            x_t = jax.lax.dynamic_index_in_dim(
                micro, m, 0, keepdims=False).astype(cdt)
            return (_mm(prm, x_t, "W_in", "b_in", cdt)
                    + prm["pos"].astype(jnp.float32)[None])

    if head_fn is None:
        head_width = spec.num_classes

        def head_fn(prm, h, m):
            hl = _ln(spec, h, prm["lnf_g"], prm["lnf_b"])
            return _mm(prm, jnp.mean(hl, axis=1), "W_head", "b_head", cdt)
    elif head_width is None:
        raise ValueError("custom head_fn needs an explicit head_width")

    def slot(prm, h_in, c, m, rng_m, take_head):
        """One (stage, chunk, microbatch) unit: embed-or-consume, the
        chunk's blocks, head + masked loss — uniform across stages so
        jax.vjp of it is the slot's exact backward (collective
        transposes included).  ``c`` (this stage's virtual chunk) and
        ``take_head`` (this unit bears the loss: last stage, last
        chunk, live) arrive as traced scalars gathered from the tick
        table's per-stage row."""
        local = {k[len("blk_"):]: a.reshape(v, kc, *a.shape[1:])
                 for k, a in prm.items() if k.startswith("blk_")}
        bp_c, base = _chunk_select(local, c, sidx,
                                   spec.num_blocks // p, kc)
        enters = jnp.logical_and(is0, jnp.equal(c, 0))
        h0 = jnp.where(enters,
                       _dropout(embed(prm, m), spec, rng_m, 0x9999),
                       h_in)

        def body(h_, bp_i):
            bp, i = bp_i
            h2_, _ = _block_forward(spec, bp, h_, act, cdt,
                                    expert_axis=None,
                                    moe_block=base + i,
                                    model_axis=model_axis,
                                    dropout_rng=rng_m)
            return h2_, None

        h1, _ = jax.lax.scan(body, h0, (bp_c, jnp.arange(kc)))
        vals = head_fn(prm, h1, m).astype(jnp.float32)
        lc = jnp.where(take_head, loss_of(vals, m), 0.0)
        return h1, lc, vals

    def rng_for(m):
        return (jax.random.fold_in(dropout_rng, m)
                if dropout_rng is not None else None)

    # Lift params to VARYING over the stage and batch axes before the
    # per-slot vjps: the pvary-aware AD otherwise inserts a psum over
    # every unvaried axis inside EVERY backward sub-slot's vjp
    # (grads w.r.t. an unvarying input must come back unvarying) — M
    # full-tree collectives per step. Varying params make each slot's
    # dprm a purely LOCAL contribution; the single psum at the end
    # restores the jax.grad replication semantics. Axes a leaf already
    # varies over (blk_* over 'stage'; TP-sharded dims over 'model')
    # are left as-is — their grads stay local, exactly as in the
    # jax.grad schedules.
    from ..ops.ring_attention import pvary_axes

    lift_axes = (stage_axis,) + tuple(batch_axes)

    def lift(a):
        try:
            have = set(jax.typeof(a).vma)
        except (AttributeError, TypeError):
            return a
        missing = tuple(ax for ax in lift_axes if ax not in have)
        return pvary_axes(a, missing) if missing else a

    params = jax.tree.map(lift, params)

    from ..ops.ring_attention import _lift_varying

    cap = table.stash_cap
    stash = jnp.zeros((cap, mb, s, d), jnp.float32)
    collected = jnp.zeros((m_cnt, mb, head_width), jnp.float32)
    g_acc = jax.tree.map(lambda a: jnp.zeros(a.shape, jnp.float32),
                         params)
    recv_f = jnp.zeros((mb, s, d), jnp.float32)
    recv_b = jnp.zeros((mb, s, d), jnp.float32)
    loss_sum = jnp.float32(0.0)
    # full-circle hops when the chunk wrap is live (v > 1): the wrap
    # edge carries stage p-1's chunk-c output into chunk c+1 on stage
    # 0 (fwd) and the matching gradient back (bwd)
    if v > 1:
        perm_f = [(j, (j + 1) % p) for j in range(p)]
        perm_b = [((j + 1) % p, j) for j in range(p)]
    else:
        perm_f = [(j, j + 1) for j in range(p - 1)]
        perm_b = [(j + 1, j) for j in range(p - 1)]

    def row_const(row, attr):
        """One tick row's per-stage schedule constants, gathered by the
        traced stage id — the kernel's literal read of the table."""
        vals_ = [getattr(e, attr) for e in row]
        if attr == "live":
            return jnp.asarray(np.asarray(vals_, np.bool_))[sidx]
        if attr == "head":
            return jnp.asarray(np.asarray(
                [e.head and e.live for e in row], np.bool_))[sidx]
        return jnp.asarray(np.asarray(vals_, np.int32))[sidx]

    for t in range(table.ticks):
        frow, brow = table.fwd[t], table.bwd[t]
        send_f = (frow is not None and t + 1 < table.ticks
                  and table.fwd[t + 1] is not None)
        send_b = (brow is not None and t + 1 < table.ticks
                  and table.bwd[t + 1] is not None)
        msg_f = None
        h1 = None
        if frow is not None:
            # ---- forward sub-slot: this tick's table row
            live_f = row_const(frow, "live")
            cf = row_const(frow, "chunk")
            mfc = row_const(frow, "microbatch")
            head_f = row_const(frow, "head")
            h1, _lc, vals = slot(params, recv_f, cf, mfc, rng_for(mfc),
                                 head_f)
            # ---- activation hop START: issued before the backward
            # sub-slot's compute so the transfer overlaps it
            if send_f:
                msg_f = _hop_start(h1, stage_axis, perm_f)
            # stash this slot's INPUT for its backward sub-slot (slot
            # reuse is a checked table invariant: a rewrite lands
            # strictly after the evicted unit's backward read)
            slot_i = row_const(frow, "unit") % cap
            prev_sl = jax.lax.dynamic_index_in_dim(stash, slot_i, 0,
                                                   keepdims=False)
            stash = jax.lax.dynamic_update_index_in_dim(
                stash, jnp.where(live_f, recv_f, prev_sl), slot_i, 0)
            prev_c = jax.lax.dynamic_index_in_dim(collected, mfc, 0,
                                                  keepdims=False)
            collected = jax.lax.dynamic_update_index_in_dim(
                collected, jnp.where(head_f, vals, prev_c), mfc, 0)
        if brow is not None:
            # ---- backward sub-slot: this tick's table row
            live_b = row_const(brow, "live")
            cb = row_const(brow, "chunk")
            mbc = row_const(brow, "microbatch")
            head_b = row_const(brow, "head")
            rng_b = rng_for(mbc)
            h_saved = jax.lax.dynamic_index_in_dim(
                stash, row_const(brow, "unit") % cap, 0, keepdims=False)
            # pin this backward's forward-recompute to its tick: the
            # recompute depends only on the stash (available early), so
            # without an explicit dependency on the PREVIOUS backward's
            # output XLA's scheduler hoists every recompute to the
            # start of the program — re-inflating live memory to O(M),
            # the exact thing the schedule exists to prevent (measured:
            # 478 MB vs 294 MB gpipe at M=8 before this barrier). The
            # same barrier is the gradient hop's JOIN: tying recv_b to
            # this tick's forward output pins the wait after the
            # forward compute the transfer was hiding under.
            if h1 is not None:
                (h_saved, recv_b, h1) = jax.lax.optimization_barrier(
                    (h_saved, recv_b, h1))
            else:
                h_saved, recv_b = jax.lax.optimization_barrier(
                    (h_saved, recv_b))
            (_h1b, lb, _v), vjp_fn = jax.vjp(
                lambda prm, h: slot(prm, h, cb, mbc, rng_b, head_b),
                params, h_saved)
            live_bf = jnp.where(live_b, 1.0, 0.0)
            # h_out cotangent: the upstream grad (zero on the loss-
            # bearing head unit — its h1 feeds nothing); loss
            # cotangent: 1 on live slots. vjp is linear in cotangents,
            # so dead slots add exact zeros. Each cotangent must carry
            # its primal output's varying-manual-axes type
            # (_lift_varying) — vjp rejects vma mismatches.
            g_ct = _lift_varying(
                jnp.where(head_b, 0.0, recv_b) * live_bf, _h1b)
            dprm, dh = vjp_fn((g_ct, _lift_varying(live_bf * 1.0, lb),
                               _lift_varying(jnp.zeros_like(_v), _v)))
            g_acc = jax.tree.map(jnp.add, g_acc, dprm)
            loss_sum = loss_sum + jnp.where(live_b, lb, 0.0)
            # ---- gradient hop START: issued before the next tick's
            # forward compute, which its transfer overlaps
            if send_b:
                recv_b = _hop_start(dh, stage_axis, perm_b)
            # ---- activation hop JOIN: consumers of the in-flight
            # forward message wait for this tick's backward compute —
            # the transfer window spans it
            if msg_f is not None:
                msg_f, _ = _hop_join(msg_f, dh)
        if msg_f is not None:
            recv_f = msg_f

    # grad replication: blk_* leaves are per-stage local; every other
    # leaf (embed/head/pos/final-LN) got real contributions only from
    # the stages that use it (zeros elsewhere) — psum makes them
    # stage-replicated, exactly what shard_map's transpose produces
    # for the jax.grad schedules. batch_axes: manual vjp never crossed
    # the data axes, so sum the per-shard grads explicitly (the
    # jax.grad paths get this from the transpose of the replicated
    # params' broadcast).
    def fix(k, g):
        if not k.startswith("blk_"):
            g = jax.lax.psum(g, stage_axis)
        if batch_axes:
            g = jax.lax.psum(g, batch_axes)
        return g

    g_acc = {k: fix(k, g) for k, g in g_acc.items()}
    stats = jax.lax.psum(collected, stage_axis).reshape(b, head_width)
    loss = jax.lax.psum(loss_sum, stage_axis)
    return (loss, stats), g_acc


def init_decode_cache(spec: TransformerSpec, batch: int,
                      heads: int | None = None) -> Params:
    """Per-block KV cache for autoregressive decoding:
    ``{k{i}/v{i}: [B, S, H, Dh]}`` preallocated at the full sequence
    length (static shapes — the decode loop writes position ``pos``
    with a dynamic-index update). ``heads``: the LOCAL head count
    under tensor-parallel decode (each shard caches only its heads)."""
    shape = (batch, spec.seq_len, heads or spec.n_heads, spec.d_head)
    cache: Params = {}
    for i in range(spec.num_blocks):
        # compute dtype: the cache holds the same rounded k/v values
        # the training forward feeds its attention
        cache[f"k{i}"] = jnp.zeros(shape, spec.compute_dtype)
        cache[f"v{i}"] = jnp.zeros(shape, spec.compute_dtype)
    return cache


class _DenseKV:
    """KV adapter for the contiguous ``[B, S, H, Dh]`` per-block cache
    (scalar decode position): writes position ``pos`` with ONE
    dynamic-index update per leaf and returns the updated views for
    attention.  Updated leaves replace the originals in ``self.cache``
    in place of a rebuilt dict — the only copies left are the XLA
    buffer updates themselves, which alias when the caller donates
    (``decode_step_fn``) or carries the cache through a scan
    (``generate``)."""

    def __init__(self, spec: TransformerSpec, cache: Params, pos):
        self.cache = cache
        self.pos = pos
        # mask over cache positions: attend to <= pos only
        self.valid = (jnp.arange(spec.seq_len) <= pos)[None, None]

    def update(self, i: int, kk, vv):
        ck = jax.lax.dynamic_update_index_in_dim(
            self.cache[f"k{i}"], kk, self.pos, axis=1)
        cv = jax.lax.dynamic_update_index_in_dim(
            self.cache[f"v{i}"], vv, self.pos, axis=1)
        self.cache[f"k{i}"], self.cache[f"v{i}"] = ck, cv
        return ck, cv, self.valid


def _decode_forward(spec: TransformerSpec, params: Params, token, pos,
                    kv, model_axis: str | None = None):
    """The ONE KV-cached decode forward, shared by the contiguous
    ``decode_step`` and the paged ``serving.kv_cache.paged_decode_step``
    (their greedy bit-parity is a tested invariant — the cache LAYOUT
    is the adapter's business, the math lives here exactly once).

    ``token`` [B]; ``pos`` is a scalar (contiguous, every row at the
    same position) or [B] (paged, ragged per-sequence positions) —
    the embedding lookup broadcasts either way.  ``kv`` is the cache
    adapter: ``update(i, kk, vv) -> (keys, values, mask)`` writes
    block i's new row(s) and returns the attention operands
    ([B, S_kv, Hl, Dh] views plus a mask broadcastable to
    [B, Hl, S_kv])."""
    if spec.objective != "lm":
        raise ValueError("decode serves the lm objective only")
    # host-side numpy params would reject traced indices (token/pos)
    params = {k: jnp.asarray(v) for k, v in params.items()}
    # decode routes MoE with the exact dense dispatch: training's
    # capacity pool spans the whole [B, S] token population, which a
    # per-position step cannot reproduce — inference computes the
    # no-drop routing instead (== training wherever nothing dropped)
    if spec.moe_dispatch != "dense":
        spec = dataclasses.replace(spec, moe_dispatch="dense")
    cdt = spec.compute_dtype
    b = token.shape[0]
    dh = spec.d_head
    h = (params["W_emb"].astype(jnp.float32)[token]
         + params["pos"].astype(jnp.float32)[pos])        # [B, D]
    act = _ACTIVATIONS[spec.activation]
    for i in range(spec.num_blocks):
        bp = {k[len(f"L{i}_"):]: v for k, v in params.items()
              if k.startswith(f"L{i}_")}
        hn = bp["Wqkv"].shape[-1] // dh       # LOCAL heads under TP
        # rank-2 direct: _ln (fused kernel AND the reference) both
        # normalize axis -1, so the old [:, None]...[:, 0] reshape
        # dance is gone (ISSUE 6 satellite)
        a = _ln(spec, h, bp["ln1_g"], bp["ln1_b"])
        qkv = jnp.einsum("bd,dte->bte", a.astype(cdt),
                         bp["Wqkv"].astype(cdt),
                         preferred_element_type=jnp.float32) \
            + bp["bqkv"].astype(jnp.float32)              # [B, 3, Dl]
        # round q/k/v to the compute dtype exactly where the training
        # forward does (qkv.astype(cdt) before attention) — cache
        # stores the rounded values so bf16 runs match training
        q, kk, vv = (qkv[:, t].astype(cdt).reshape(b, hn, dh)
                     for t in range(3))
        ck, cv, valid = kv.update(i, kk, vv)
        # mirror ops/ring_attention.attention exactly: the score
        # einsum runs in the inputs' dtype and is cast AFTER (bf16
        # rounding included), masked with the same NEG_INF
        from ..ops.ring_attention import NEG_INF

        scores = jnp.einsum("bhe,bshe->bhs", q, ck).astype(jnp.float32) \
            / jnp.sqrt(jnp.float32(dh))                   # [B, Hl, S]
        scores = jnp.where(valid, scores, NEG_INF)
        probs = jax.nn.softmax(scores, axis=-1)
        att = jnp.einsum("bhs,bshe->bhe", probs.astype(cv.dtype),
                         cv).reshape(b, hn * dh)
        h = h + _row_psum(att.astype(cdt), bp["Wo"], bp["bo"], cdt,
                          model_axis)
        h, _aux = _ffn_block(spec, bp, h[:, None], act, cdt,
                             model_axis=model_axis, moe_block=i)
        h = h[:, 0]
    hf = _ln(spec, h, params["lnf_g"], params["lnf_b"])
    logits = _mm(params, hf, "W_head", "b_head", cdt).astype(jnp.float32)
    return logits


def decode_step(spec: TransformerSpec, params: Params, cache: Params,
                token: jnp.ndarray, pos, model_axis: str | None = None):
    """One KV-cached decode step for the lm objective: embed ``token``
    [B] at position ``pos``, run every block attending to the cached
    keys/values up to and including ``pos``, and return
    (vocab logits [B, V], updated cache). O(S) per step instead of the
    O(S^2) full re-forward; exactly the training forward's math
    (verified by the greedy-vs-teacher-forcing test).

    ``model_axis`` (inside shard_map): Megatron TP decode — ``Wqkv``
    arrives with this shard's head columns, the per-head attention and
    its KV cache stay shard-local, and the two row-split projections
    (Wo, W2) psum, exactly like the training forward.

    Per-step cache copies: called standalone under a plain jit, every
    step materializes a fresh cache output.  Use ``decode_step_fn``
    (donated cache buffers) for step-at-a-time decoding loops —
    ``generate``'s scan already aliases the cache as its carry."""
    kv = _DenseKV(spec, dict(cache), pos)
    logits = _decode_forward(spec, params, token, pos, kv,
                             model_axis=model_axis)
    return logits, kv.cache


@functools.lru_cache(maxsize=8)
def decode_step_fn(spec: TransformerSpec, model_axis: str | None = None,
                   donate: bool | None = None):
    """Compiled ``(params, cache, token, pos) -> (logits, cache)``
    step with the cache buffers DONATED (in-place XLA updates), so a
    step-at-a-time decode loop — the serving engine's shape, where a
    scan over positions cannot exist — stops paying a full cache copy
    per emitted token.  ``donate=None`` resolves by backend (the CPU
    runtime implements no donation and would warn per call); the
    tokens are bit-identical either way, donation only changes buffer
    lifetime."""
    if donate is None:
        donate = jax.default_backend() != "cpu"

    def step(params, cache, token, pos):
        return decode_step(spec, params, cache, token, pos,
                           model_axis=model_axis)

    return jax.jit(step, donate_argnums=(1,) if donate else ())


def generate(spec: TransformerSpec, params: Params, prompt: jnp.ndarray,
             rng: jax.Array = None, temperature: float = 1.0,
             model_axis: str | None = None):
    """Autoregressively complete ``prompt`` [B, P] int tokens to the
    full ``spec.seq_len`` with KV-cached decoding (one lax.scan over
    positions, prompt positions teacher-forced). ``rng=None`` decodes
    greedily; otherwise samples at ``temperature``. Returns
    [B, seq_len] int tokens. With ``model_axis`` (inside shard_map)
    decoding runs tensor-parallel on the mesh — see generate_sharded
    for the jit-able wrapper."""
    b, p = prompt.shape
    s = spec.seq_len
    local_heads = (jnp.shape(params["L0_Wqkv"])[-1] // spec.d_head
                   if model_axis is not None else spec.n_heads)
    cache = init_decode_cache(spec, b, heads=local_heads)
    # the zeros-init cache must carry every manual axis the decode
    # will vary it over, or the scan carry types mismatch after the
    # first (genuinely varying) update: the prompt's axes (data-
    # sharded decode, generate_dp) plus the model axis (TP decode —
    # each shard caches only its heads)
    from ..ops.ring_attention import _lift_varying, pvary_axes

    cache = jax.tree.map(lambda a: _lift_varying(a, prompt), cache)
    if model_axis is not None:
        cache = jax.tree.map(
            lambda a: pvary_axes(a, (model_axis,)), cache)
    tokens0 = jnp.concatenate(
        [prompt, jnp.zeros((b, s - p), prompt.dtype)], axis=1)

    def step(carry, pos):
        tokens, cache, key = carry
        tok = jax.lax.dynamic_index_in_dim(tokens, pos, axis=1,
                                           keepdims=False)   # [B]
        logits, cache = decode_step(spec, params, cache, tok, pos,
                                    model_axis=model_axis)
        if rng is None or temperature <= 0:
            # greedy (temperature 0 requests argmax, not a div-by-zero)
            nxt = jnp.argmax(logits, -1).astype(tokens.dtype)
        else:
            key, sub = jax.random.split(key)
            nxt = jax.random.categorical(
                sub, logits / jnp.float32(temperature), -1
            ).astype(tokens.dtype)
        # write position pos+1 (pos stops at s-2) unless it is still
        # inside the prompt (teacher forcing)
        cur = jax.lax.dynamic_index_in_dim(tokens, pos + 1, axis=1,
                                           keepdims=False)
        val = jnp.where(pos + 1 >= p, nxt, cur)
        tokens = jax.lax.dynamic_update_index_in_dim(
            tokens, val, pos + 1, axis=1)
        return (tokens, cache, key), None

    key0 = rng if rng is not None else jax.random.PRNGKey(0)
    (tokens, _, _), _ = jax.lax.scan(
        step, (tokens0, cache, key0), jnp.arange(s - 1))
    return tokens


@functools.lru_cache(maxsize=8)
def _gen_sharded_fn(spec, mesh, model_axis: str, temperature: float,
                    sampled: bool):
    """Compiled TP-decode program, LRU-bounded so long-lived processes
    sweeping meshes/specs/temperatures cannot accumulate dead
    executables and their device handles."""
    from jax.sharding import PartitionSpec as P

    pspecs = param_pspecs(spec, model_axis=model_axis)

    def run(p, t, k):
        return generate(spec, p, t, rng=(k if sampled else None),
                        temperature=temperature, model_axis=model_axis)

    return jax.jit(jax.shard_map(run, mesh=mesh,
                                 in_specs=(pspecs, P(), P()),
                                 out_specs=P()))


def generate_sharded(spec: TransformerSpec, params: Params,
                     prompt: jnp.ndarray, mesh, model_axis: str,
                     rng: jax.Array = None, temperature: float = 1.0):
    """``generate`` running tensor-parallel ON the mesh (VERDICT r3
    next #8): params stay in their Megatron placement (one shard's
    heads/hidden per device — never gathered to the host), each shard
    decodes its heads with a shard-local KV cache, and the row-split
    psums make the logits — and therefore the sampled tokens, every
    shard drawing with the same key — identical everywhere. The prompt
    and returned [B, seq_len] tokens are replicated. The jitted
    program is memoized (rng rides as a traced argument), so periodic
    sampling never re-compiles."""
    sampled = rng is not None
    fn = _gen_sharded_fn(spec, mesh, model_axis, float(temperature),
                         sampled)
    return fn(params, prompt,
              rng if sampled else jax.random.PRNGKey(0))


@functools.lru_cache(maxsize=8)
def _gen_dp_fn(spec, mesh, data_axis: str, model_axis: str | None,
               temperature: float, sampled: bool):
    """Compiled DP(xTP)-decode program (LRU-bounded like
    _gen_sharded_fn): the prompt batch shards over ``data_axis``, each
    shard KV-decodes its slice — with ``model_axis`` the heads also
    split Megatron-style within each data shard. Per-shard sampling
    keys fold in the data coordinate so shards draw independent
    tokens."""
    from jax.sharding import PartitionSpec as P

    pspecs = param_pspecs(spec, model_axis=model_axis)
    if model_axis is None:
        pspecs = {k: P() for k in pspecs}

    def run(p, t, k):
        if sampled:
            k = jax.random.fold_in(k, jax.lax.axis_index(data_axis))
        return generate(spec, p, t, rng=(k if sampled else None),
                        temperature=temperature, model_axis=model_axis)

    return jax.jit(jax.shard_map(run, mesh=mesh,
                                 in_specs=(pspecs, P(data_axis), P()),
                                 out_specs=P(data_axis)))


def generate_dp(spec: TransformerSpec, params: Params,
                prompts: jnp.ndarray, mesh, data_axis: str = "data",
                model_axis: str | None = None, rng: jax.Array = None,
                temperature: float = 1.0):
    """Batched decode ON the mesh (VERDICT r4 next #8): prompts shard
    over ``data_axis`` (padded up to a multiple of its size), so
    ``--sample_after`` scales decode throughput with the data axis in
    EVERY mode instead of falling back to a chief-host numpy decode.
    ``params`` are the FLAT layout, replicated (PP/FSDP callers
    unstack/gather first — on device); with ``model_axis`` the
    per-shard decode is additionally Megatron tensor-parallel. Works
    single- and multi-process: the prompt array is assembled with
    make_array_from_callback from the (identical) host copy.

    Returns ``(tokens, n)`` — SYMMETRIC across process counts (r5
    ADVICE: the old contract sliced ``[:n]`` single-process but
    returned the padded global array multi-process, so callers written
    against one topology silently broke on the other): ``tokens`` is
    ALWAYS the padded, data-sharded global array and ``n`` the valid
    row count. ``dp_samples_host`` materializes the first ``n`` rows
    on every host (allgather only when multi-process)."""
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    n = int(prompts.shape[0])
    dp = mesh.shape[data_axis]
    pad = (-n) % dp
    pr = np.asarray(prompts)
    if pad:
        pr = np.concatenate([pr, np.tile(pr[:1], (pad, 1))], axis=0)
    sharding = NamedSharding(mesh, P(data_axis))
    pr_g = jax.make_array_from_callback(
        pr.shape, sharding, lambda idx: pr[idx])
    prm = jax.device_put(
        params, jax.tree.map(
            lambda _: NamedSharding(mesh, P()), params)
    ) if model_axis is None else params
    fn = _gen_dp_fn(spec, mesh, data_axis, model_axis,
                    float(temperature), rng is not None)
    out = fn(prm, pr_g, rng if rng is not None else jax.random.PRNGKey(0))
    # cross-shard slicing is not addressable multi-process, so the
    # padded global array + count is the one contract every topology
    # shares; dp_samples_host does the (allgather +) [:n] slice
    return out, n


def dp_samples_host(tokens, n: int):
    """Materialize ``generate_dp``'s padded output as the first ``n``
    rows on every host: one ``process_allgather`` when the shards span
    processes (single-process arrays are fully addressable and fetch
    directly), then the ``[:n]`` slice dropping the pad rows."""
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        tokens = multihost_utils.process_allgather(tokens, tiled=True)
    return np.asarray(tokens)[:int(n)]


def num_params(spec: TransformerSpec) -> int:
    import math

    return sum(math.prod(s) for s in param_shapes(spec).values())


def flops_per_step(spec: TransformerSpec, batch: int) -> float:
    """Analytic fwd+bwd matmul+attention FLOPs per training step (fwd
    2*MACs, bwd 4*MACs; attention 4*B*H*S^2*Dh fwd, x3 for fwd+bwd),
    for bench MFU accounting."""
    d, ff, f, s = spec.d_model, spec.d_ff, spec.d_feature, spec.seq_len
    if spec.num_experts and spec.moe_dispatch == "alltoall":
        # sparse dispatch computes ~capacity_factor * k tokens' worth
        # of expert FFN per token (plus the router)
        ffn = spec.capacity_factor * spec.moe_topk * (d * ff + ff * d) \
            + d * spec.num_experts
    elif spec.num_experts:
        # dense-dispatch MoE computes every expert (plus the router);
        # under EP each device computes 1/n of this
        ffn = spec.num_experts * (d * ff + ff * d) + d * spec.num_experts
    else:
        ffn = d * ff + ff * d
    macs_tok = f * d + spec.num_blocks * (3 * d * d + d * d + ffn)
    head = (s * d * spec.vocab_size if spec.objective == "lm"
            else d * spec.num_classes)
    macs = batch * (s * macs_tok + head)
    attn = 4.0 * batch * spec.n_heads * s * s * spec.d_head \
        * spec.num_blocks * (0.5 if spec.causal else 1.0)
    # 3.5x forward for fwd+bwd attention — the same accounting as
    # bench._attn_flops (backward ~2.5x forward on top of the forward)
    return 6.0 * macs + 3.5 * attn
