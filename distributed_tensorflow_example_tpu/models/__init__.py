from .mlp import MLPSpec, init, apply, num_params

__all__ = ["MLPSpec", "init", "apply", "num_params"]
