"""TPU-native distributed training framework.

A brand-new JAX/XLA/pjit framework with the capabilities of
``springle/distributed-tensorflow-example`` (reference: a TF 1.2
parameter-server MNIST example, /root/reference/example.py) rebuilt
TPU-first:

- the parameter server's per-step param-pull / grad-push over gRPC
  (reference example.py:55-57, 111) becomes a single ``lax.psum``
  allreduce over the ICI data-parallel mesh, compiled into the step;
- both the live async path (example.py:101, 111) and the commented
  ``SyncReplicasOptimizer`` path (example.py:102-110) map to the same
  synchronous SPMD step (see SURVEY.md §7), with an optional local-SGD
  mode (``--sync_period > 1``) reproducing async staleness semantics
  TPU-natively;
- the ``--job_name/--task_index`` CLI (example.py:30-32) is preserved
  and maps to ``jax.distributed`` process identity.

Layout:
    config      flag system (reference example.py:29-44 equivalents)
    cluster     process bootstrap / chief helpers (example.py:34-38, 132-138)
    data        MNIST pipeline (example.py:46-48, 157)
    models      MLP model zoo (example.py:74-90)
    ops         losses, metrics, Pallas kernels (example.py:92-96, 118-121)
    parallel    mesh, shardings, SPMD train step (example.py:54-57, 98-116)
    train       optimizers, train state, driver loop (example.py:132-181)
    utils       TensorBoard event writer, checkpointing, timers
                (example.py:123-128, 145-146, 163)
"""

__version__ = "0.1.0"
