"""Cross-entropy losses.

Reference parity: the reference computes
``mean(-sum(y_ * log(softmax(z3)), axis=1))``
(/root/reference/example.py:92-96 over the softmax from :90) — the
numerically *unstable* form: ``log(softmax)`` with no clamping NaNs when
any softmax output underflows to 0 (SURVEY.md §2 quirks).

``stable_cross_entropy`` is the default: the same quantity computed from
logits in log-sum-exp form, safe for all logit magnitudes.
``naive_cross_entropy`` reproduces the reference arithmetic exactly
(softmax then log) behind the ``--naive_ce`` flag for parity runs.
Both take logits so the forward pass is shared.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def stable_cross_entropy(logits: jnp.ndarray, labels_onehot: jnp.ndarray) -> jnp.ndarray:
    """mean over batch of -sum(y_ * log_softmax(logits)) — stable form."""
    log_probs = jax.nn.log_softmax(logits, axis=-1)
    return -jnp.mean(jnp.sum(labels_onehot * log_probs, axis=-1))


def naive_cross_entropy(logits: jnp.ndarray, labels_onehot: jnp.ndarray) -> jnp.ndarray:
    """The reference's exact arithmetic (example.py:95-96): log(softmax(z)).

    Kept for parity experiments; NaNs for large logits, by design.
    """
    probs = jax.nn.softmax(logits, axis=-1)
    return -jnp.mean(jnp.sum(labels_onehot * jnp.log(probs), axis=-1))


def cross_entropy(
    logits: jnp.ndarray, labels_onehot: jnp.ndarray, naive: bool = False,
    label_smoothing: float = 0.0,
) -> jnp.ndarray:
    """CE with optional label smoothing: targets become
    ``y*(1-eps) + eps/K`` (uniform mass on the off classes) — the
    standard regularizer the reference era predates. Smoothing
    composes with either arithmetic form (it only transforms the
    targets)."""
    if label_smoothing:
        k = labels_onehot.shape[-1]
        labels_onehot = (labels_onehot * (1.0 - label_smoothing)
                         + label_smoothing / k)
    if naive:
        return naive_cross_entropy(logits, labels_onehot)
    return stable_cross_entropy(logits, labels_onehot)
