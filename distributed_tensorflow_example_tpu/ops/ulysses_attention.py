"""Ulysses-style all-to-all sequence parallelism.

The second of the two canonical sequence/context-parallel layouts
(absent from the reference, which has no sequence axis at all —
/root/reference/example.py:69's inputs are flat ``[B, 784]``;
SURVEY.md §5 "Long-context"):

- **ring** (ops/ring_attention.py): k/v blocks orbit the shards via
  ppermute; each shard keeps its token block. Communication is
  neighbor-only (ICI-friendly) and overlaps compute, but attention
  runs blockwise with online-softmax merging.
- **ulysses** (this module): two ``all_to_all`` collectives re-shard
  the tensors from sequence-sharded ``[B, S/n, H, Dh]`` to
  head-sharded ``[B, S, H/n, Dh]`` and back. Between them every shard
  sees the FULL sequence for its subset of heads, so attention runs
  as one ordinary (dense or flash-kernel) call — no blockwise
  merging, exact softmax by construction.

Trade-off (the reason both exist, as in DeepSpeed-Ulysses vs Ring
Attention): ulysses moves activations twice through an all-to-all
(bisection bandwidth, head-count-limited parallelism ``n <= H``) but
composes directly with the single-chip flash kernels at full sequence
length; the ring's degree is bounded by tokens, not heads, and its
traffic is neighbor-only, but it needs the stats-merging machinery.

Both are selected per-run by ``--sp_impl {ring,ulysses}`` on the same
``('data','seq')`` mesh — the layout contract (contiguous token
blocks per shard) is identical, so switching is a flag, not a
re-shard.

Differentiability: ``lax.all_to_all`` is its own transpose (the
reverse exchange), so ``jax.grad`` through this function yields the
all-to-all of the local attention gradients — no custom VJP needed.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def ulysses_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                      axis_name: str, causal: bool = False,
                      use_flash: bool = False) -> jnp.ndarray:
    """Sequence-parallel attention via head<->sequence all-to-all.

    Args:
      q, k, v: ``[B, S_local, H, Dh]`` — this shard's contiguous token
        block, all heads (the same layout the ring variant takes).
      axis_name: the mesh axis the sequence is sharded over.
      causal: standard causal mask (applied on the full local
        sequence — no global-offset bookkeeping needed, unlike the
        ring's blockwise masking).
      use_flash: run the single-chip flash-attention Pallas kernels on
        the gathered sequence (ops/flash_attention); otherwise the
        exact XLA dense path.

    Returns: ``[B, S_local, H, Dh]`` — sequence-sharded again.
    """
    n = jax.lax.psum(1, axis_name)
    heads = q.shape[2]
    if heads % n:
        raise ValueError(
            f"ulysses sequence parallelism needs n_heads ({heads}) "
            f"divisible by the sequence-axis size ({n})")
    if n == 1:
        qg, kg, vg = q, k, v
    else:
        # [3, B, S/n, H, Dh] -> [3, B, S, H/n, Dh]: scatter heads,
        # gather seq — q/k/v stacked so the exchange is ONE collective
        # launch per direction instead of three
        qkv = jax.lax.all_to_all(jnp.stack((q, k, v)), axis_name,
                                 split_axis=3, concat_axis=2, tiled=True)
        qg, kg, vg = qkv[0], qkv[1], qkv[2]
    if use_flash:
        from .flash_attention import flash_attention

        out = flash_attention(qg, kg, vg, causal)
    else:
        from .ring_attention import attention

        out = attention(qg, kg, vg, causal=causal)
    if n == 1:
        return out
    # [B, S, H/n, Dh] -> [B, S/n, H, Dh]: scatter seq, gather heads
    return jax.lax.all_to_all(out, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)
