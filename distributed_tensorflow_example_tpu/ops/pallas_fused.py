"""Fused MLP forward as a Pallas TPU kernel.

Reference parity: the reference's forward is four ops dispatched by the
TF graph executor — matmul, sigmoid, matmul, (softmax)
(/root/reference/example.py:87-90), each a separate C++ Eigen kernel
with HBM round-trips between them on CPU.

TPU-native design: one Pallas kernel computes the whole forward chain
per batch tile — weights and the tile's activations stay in VMEM, the
matmuls hit the MXU, the activation function runs on the VPU between
them with no HBM round-trip. For the reference's 784-100-10 MLP, stock
XLA already fuses this well (SURVEY.md §2b); the kernel exists to (a)
own the capability the task calls for, (b) cut dispatch to a single
fused op for wider/deeper spec variants where XLA's fusion boundaries
start to matter.

Training support: gradients flow via ``jax.custom_vjp`` — the forward
runs the Pallas kernel (saving the layer activations as residuals), the
backward is plain XLA (matmuls on the MXU either way). Enabled with
``--pallas``; only the pure data-parallel path uses it (TP shards the
hidden dim, which this kernel does not partition).

On non-TPU backends the kernel runs in Pallas interpret mode so tests
exercise the same code path on the 8-virtual-device CPU mesh.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ..models import mlp

_BATCH_TILE = 128

# Activations whose derivative is expressible from the saved activation
# output (the residuals the kernel writes); gelu needs the
# pre-activation, so its --pallas requests fall back to the XLA forward
# (parallel/step.py gates on this set).
SUPPORTED_ACTIVATIONS = ("sigmoid", "tanh", "relu")


def _interpret() -> bool:
    return jax.default_backend() == "cpu"


def _act(name: str, z):
    return mlp._ACTIVATIONS[name](z)


def _layer(h, w, b, activation: str, compute_dtype, last: bool):
    """One MLP layer, shared by the Pallas kernel body and the XLA
    fallback (and matching models.mlp.apply layer-for-layer): matmul
    takes ``compute_dtype`` inputs (bfloat16 is the MXU's native input
    width), accumulation/bias/activation run in f32 (Mosaic also rejects
    f32 scalar constants inside bf16 elementwise ops), and the result is
    rounded to ``compute_dtype`` at the layer edge."""
    acc = jnp.dot(
        h.astype(compute_dtype), w, preferred_element_type=jnp.float32
    ) + b  # bias arrives f32 (never rounded through compute_dtype), as in mlp.apply
    if last:
        return acc  # logits stay f32, as in models.mlp.apply
    return _act(activation, acc).astype(compute_dtype)


def _make_kernel(num_layers: int, activation: str, compute_dtype):
    """Kernel over one batch tile: x_ref, W1,b1,...,WL,bL -> logits and
    per-hidden-layer activations (residuals for the VJP)."""

    def kernel(x_ref, *refs):
        param_refs = refs[: 2 * num_layers]
        out_refs = refs[2 * num_layers :]  # logits_ref, h1_ref, ..., h{L-1}_ref
        h = x_ref[:]
        for i in range(num_layers):
            w = param_refs[2 * i][:]
            b = param_refs[2 * i + 1][:]
            h = _layer(h, w, b, activation, compute_dtype, i == num_layers - 1)
            out_refs[(1 + i) if i < num_layers - 1 else 0][:] = h

    return kernel


@functools.partial(jax.jit, static_argnums=0)
def _forward_pallas(spec: mlp.MLPSpec, params, x):
    """Run the fused kernel; returns (logits, (h1, ..., h_{L-1})).

    Inputs/params are cast to ``spec.compute_dtype`` (as the XLA forward
    in models.mlp.apply does); matmul accumulation stays float32."""
    L = spec.num_layers
    cdt = spec.compute_dtype
    n = x.shape[0]
    n_pad = max(_BATCH_TILE, ((n + _BATCH_TILE - 1) // _BATCH_TILE) * _BATCH_TILE)
    xp = jnp.pad(x.astype(cdt), ((0, n_pad - n), (0, 0)))

    flat_params = []
    for i in range(1, L + 1):
        flat_params.append(params[f"W{i}"].astype(cdt))
        flat_params.append(params[f"b{i}"].astype(jnp.float32).reshape(1, -1))

    grid = (n_pad // _BATCH_TILE,)
    sizes = spec.layer_sizes
    in_specs = [
        pl.BlockSpec((_BATCH_TILE, sizes[0]), lambda i: (i, 0)),
    ]
    for i in range(1, L + 1):
        in_specs.append(pl.BlockSpec((sizes[i - 1], sizes[i]), lambda i_: (0, 0)))
        in_specs.append(pl.BlockSpec((1, sizes[i]), lambda i_: (0, 0)))

    # Under shard_map's varying-axis checking, outputs must declare how
    # they vary across mesh axes: like the batch input (vma of x). The
    # kernel's inputs must also agree, so lift the (data-replicated)
    # params to the batch's vma; the custom-VJP backward reduces the
    # cotangents back down (_match_vma).
    try:
        vma = jax.typeof(xp).vma
    except (AttributeError, TypeError):
        vma = None
    if vma:

        def lift(p):
            # Lift only the axes a param is still invariant over:
            # replicated DP params need the full vma, while FSDP hands
            # in all-gathered params that are already varying.
            try:
                have = set(jax.typeof(p).vma)
            except (AttributeError, TypeError):
                have = set()
            missing = tuple(sorted(set(vma) - have))
            if not missing:
                return p
            from .ring_attention import pvary_axes

            return pvary_axes(p, missing)

        flat_params = [lift(p) for p in flat_params]
    _sds = (
        (lambda shape, dt: jax.ShapeDtypeStruct(shape, dt, vma=vma))
        if vma
        else (lambda shape, dt: jax.ShapeDtypeStruct(shape, dt))
    )
    # logits in f32 (the accumulator dtype, as mlp.apply returns them);
    # hidden residuals in compute_dtype
    out_shapes = [_sds((n_pad, sizes[L]), jnp.float32)]
    out_specs = [pl.BlockSpec((_BATCH_TILE, sizes[L]), lambda i: (i, 0))]
    for i in range(1, L):
        out_shapes.append(_sds((n_pad, sizes[i]), cdt))
        out_specs.append(pl.BlockSpec((_BATCH_TILE, sizes[i]), lambda i: (i, 0)))

    if _interpret() and vma:
        # The HLO interpreter drops vma from its internal loop carries,
        # so it cannot run under shard_map's varying-axis checking. On
        # CPU inside shard_map, compute the identical math with XLA ops
        # — the custom-VJP path (incl. the _match_vma psum reinsertion)
        # is still exercised; the kernel itself is covered by the
        # non-shard_map interpret tests and by real-TPU runs.
        h = xp
        outs = [None]
        for i in range(L):
            h = _layer(
                h, flat_params[2 * i], flat_params[2 * i + 1],
                spec.activation, cdt, i == L - 1,
            )
            if i < L - 1:
                outs.append(h)
            else:
                outs[0] = h
    elif _interpret():
        # Interpret mode (CPU tests), outside shard_map: gridless
        # full-array call (the interpreter pads oddly with grids).
        outs = pl.pallas_call(
            _make_kernel(L, spec.activation, cdt),
            out_shape=out_shapes,
            interpret=True,
        )(xp, *flat_params)
    else:
        outs = pl.pallas_call(
            _make_kernel(L, spec.activation, cdt),
            grid=grid,
            in_specs=in_specs,
            out_specs=out_specs,
            out_shape=out_shapes,
        )(xp, *flat_params)
    logits = outs[0][:n].astype(jnp.float32)
    hiddens = tuple(o[:n] for o in outs[1:])
    return logits, hiddens


def _act_grad(name: str, h):
    """d(act)/dz expressed in terms of the activation output h (the
    residual we saved): sigmoid' = h(1-h), tanh' = 1-h^2, relu' = h>0.
    gelu has no closed form in h — it is excluded by
    SUPPORTED_ACTIVATIONS and routed to the XLA forward instead."""
    if name == "sigmoid":
        return h * (1.0 - h)
    if name == "tanh":
        return 1.0 - h * h
    if name == "relu":
        return (h > 0).astype(h.dtype)
    raise NotImplementedError(name)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def mlp_forward(spec: mlp.MLPSpec, params, x):
    """Drop-in for models.mlp.apply on the data-parallel path."""
    logits, _ = _forward_pallas(spec, params, x)
    return logits


def _fwd(spec, params, x):
    logits, hiddens = _forward_pallas(spec, params, x)
    return logits, (params, x, hiddens)


def _match_vma(val, like):
    """Reduce a cotangent onto its primal's varying-axis set — the psum
    shard_map's automatic transpose would have inserted (a custom_vjp
    opts out of that machinery, so we reproduce it): a param replicated
    across 'data' gets its per-shard cotangents summed over 'data'."""
    try:
        cur = jax.typeof(val).vma
        want = jax.typeof(like).vma
    except (AttributeError, TypeError):
        return val
    extra = tuple(sorted(cur - want))
    return jax.lax.psum(val, extra) if extra else val


def _bwd(spec, res, g):
    """Backward in the same mixed precision as the forward: matmul
    inputs in ``compute_dtype`` (bf16 keeps the MXU at native rate —
    the backward is 2/3 of the step FLOPs), accumulation and the
    elementwise delta chain in float32."""
    params, x, hiddens = res
    L = spec.num_layers
    cdt = spec.compute_dtype
    mm = lambda a, b: jnp.dot(
        a.astype(cdt), b.astype(cdt), preferred_element_type=jnp.float32
    )
    acts = (x,) + hiddens  # inputs to layers 1..L
    dW = {}
    db = {}
    delta = g.astype(jnp.float32)  # dL/dz_L (chain stays f32 for precision)
    for i in range(L, 0, -1):
        dW[f"W{i}"] = mm(acts[i - 1].T, delta)
        db[f"b{i}"] = jnp.sum(delta, axis=0)
        if i > 1:
            da = mm(delta, params[f"W{i}"].T)
            delta = da * _act_grad(spec.activation, hiddens[i - 2]).astype(
                jnp.float32
            )
    dparams = {
        k: _match_vma(v, params[k]).astype(params[k].dtype)
        for k, v in {**dW, **db}.items()
    }
    dx = mm(delta, params["W1"].T).astype(x.dtype)
    return dparams, dx


mlp_forward.defvjp(_fwd, _bwd)
