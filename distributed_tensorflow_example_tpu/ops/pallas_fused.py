"""Fused Pallas TPU kernels: MLP forward, LayerNorm(+residual), and
the grouped MoE expert matmul.

Reference parity: the reference's forward is four ops dispatched by the
TF graph executor — matmul, sigmoid, matmul, (softmax)
(/root/reference/example.py:87-90), each a separate C++ Eigen kernel
with HBM round-trips between them on CPU.

TPU-native design: one Pallas kernel computes the whole forward chain
per batch tile — weights and the tile's activations stay in VMEM, the
matmuls hit the MXU, the activation function runs on the VPU between
them with no HBM round-trip. For the reference's 784-100-10 MLP, stock
XLA already fuses this well (SURVEY.md §2b); the kernel exists to (a)
own the capability the task calls for, (b) cut dispatch to a single
fused op for wider/deeper spec variants where XLA's fusion boundaries
start to matter.

Training support: gradients flow via ``jax.custom_vjp`` — the forward
runs the Pallas kernel (saving the layer activations as residuals), the
backward is plain XLA (matmuls on the MXU either way). Enabled with
``--pallas``; only the pure data-parallel path uses it (TP shards the
hidden dim, which this kernel does not partition).

On non-TPU backends the kernel runs in Pallas interpret mode so tests
exercise the same code path on the 8-virtual-device CPU mesh.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ..models import mlp

_BATCH_TILE = 128

# Activations whose derivative is expressible from the saved activation
# output (the residuals the kernel writes); gelu needs the
# pre-activation, so its --pallas requests fall back to the XLA forward
# (parallel/step.py gates on this set).
SUPPORTED_ACTIVATIONS = ("sigmoid", "tanh", "relu")


def _interpret() -> bool:
    return jax.default_backend() == "cpu"


def _act(name: str, z):
    return mlp._ACTIVATIONS[name](z)


def _layer(h, w, b, activation: str, compute_dtype, last: bool):
    """One MLP layer, shared by the Pallas kernel body and the XLA
    fallback (and matching models.mlp.apply layer-for-layer): matmul
    takes ``compute_dtype`` inputs (bfloat16 is the MXU's native input
    width), accumulation/bias/activation run in f32 (Mosaic also rejects
    f32 scalar constants inside bf16 elementwise ops), and the result is
    rounded to ``compute_dtype`` at the layer edge."""
    acc = jnp.dot(
        h.astype(compute_dtype), w, preferred_element_type=jnp.float32
    ) + b  # bias arrives f32 (never rounded through compute_dtype), as in mlp.apply
    if last:
        return acc  # logits stay f32, as in models.mlp.apply
    return _act(activation, acc).astype(compute_dtype)


def _make_kernel(num_layers: int, activation: str, compute_dtype):
    """Kernel over one batch tile: x_ref, W1,b1,...,WL,bL -> logits and
    per-hidden-layer activations (residuals for the VJP)."""

    def kernel(x_ref, *refs):
        param_refs = refs[: 2 * num_layers]
        out_refs = refs[2 * num_layers :]  # logits_ref, h1_ref, ..., h{L-1}_ref
        h = x_ref[:]
        for i in range(num_layers):
            w = param_refs[2 * i][:]
            b = param_refs[2 * i + 1][:]
            h = _layer(h, w, b, activation, compute_dtype, i == num_layers - 1)
            out_refs[(1 + i) if i < num_layers - 1 else 0][:] = h

    return kernel


@functools.partial(jax.jit, static_argnums=0)
def _forward_pallas(spec: mlp.MLPSpec, params, x):
    """Run the fused kernel; returns (logits, (h1, ..., h_{L-1})).

    Inputs/params are cast to ``spec.compute_dtype`` (as the XLA forward
    in models.mlp.apply does); matmul accumulation stays float32."""
    L = spec.num_layers
    cdt = spec.compute_dtype
    n = x.shape[0]
    n_pad = max(_BATCH_TILE, ((n + _BATCH_TILE - 1) // _BATCH_TILE) * _BATCH_TILE)
    xp = jnp.pad(x.astype(cdt), ((0, n_pad - n), (0, 0)))

    flat_params = []
    for i in range(1, L + 1):
        flat_params.append(params[f"W{i}"].astype(cdt))
        flat_params.append(params[f"b{i}"].astype(jnp.float32).reshape(1, -1))

    grid = (n_pad // _BATCH_TILE,)
    sizes = spec.layer_sizes
    in_specs = [
        pl.BlockSpec((_BATCH_TILE, sizes[0]), lambda i: (i, 0)),
    ]
    for i in range(1, L + 1):
        in_specs.append(pl.BlockSpec((sizes[i - 1], sizes[i]), lambda i_: (0, 0)))
        in_specs.append(pl.BlockSpec((1, sizes[i]), lambda i_: (0, 0)))

    # Under shard_map's varying-axis checking, outputs must declare how
    # they vary across mesh axes: like the batch input (vma of x). The
    # kernel's inputs must also agree, so lift the (data-replicated)
    # params to the batch's vma (lifting only the axes a param is
    # still invariant over: FSDP hands in all-gathered params that are
    # already varying); the custom-VJP backward reduces the cotangents
    # back down (_match_vma).
    vma = _vma_of(xp)
    if vma:
        flat_params = [_lift_to(p, vma) for p in flat_params]
    _sds = (
        (lambda shape, dt: jax.ShapeDtypeStruct(shape, dt, vma=vma))
        if vma
        else (lambda shape, dt: jax.ShapeDtypeStruct(shape, dt))
    )
    # logits in f32 (the accumulator dtype, as mlp.apply returns them);
    # hidden residuals in compute_dtype
    out_shapes = [_sds((n_pad, sizes[L]), jnp.float32)]
    out_specs = [pl.BlockSpec((_BATCH_TILE, sizes[L]), lambda i: (i, 0))]
    for i in range(1, L):
        out_shapes.append(_sds((n_pad, sizes[i]), cdt))
        out_specs.append(pl.BlockSpec((_BATCH_TILE, sizes[i]), lambda i: (i, 0)))

    if _interpret() and vma:
        # The HLO interpreter drops vma from its internal loop carries,
        # so it cannot run under shard_map's varying-axis checking. On
        # CPU inside shard_map, compute the identical math with XLA ops
        # — the custom-VJP path (incl. the _match_vma psum reinsertion)
        # is still exercised; the kernel itself is covered by the
        # non-shard_map interpret tests and by real-TPU runs.
        h = xp
        outs = [None]
        for i in range(L):
            h = _layer(
                h, flat_params[2 * i], flat_params[2 * i + 1],
                spec.activation, cdt, i == L - 1,
            )
            if i < L - 1:
                outs.append(h)
            else:
                outs[0] = h
    elif _interpret():
        # Interpret mode (CPU tests), outside shard_map: gridless
        # full-array call (the interpreter pads oddly with grids).
        outs = pl.pallas_call(
            _make_kernel(L, spec.activation, cdt),
            out_shape=out_shapes,
            interpret=True,
        )(xp, *flat_params)
    else:
        outs = pl.pallas_call(
            _make_kernel(L, spec.activation, cdt),
            grid=grid,
            in_specs=in_specs,
            out_specs=out_specs,
            out_shape=out_shapes,
        )(xp, *flat_params)
    logits = outs[0][:n].astype(jnp.float32)
    hiddens = tuple(o[:n] for o in outs[1:])
    return logits, hiddens


def _act_grad(name: str, h):
    """d(act)/dz expressed in terms of the activation output h (the
    residual we saved): sigmoid' = h(1-h), tanh' = 1-h^2, relu' = h>0.
    gelu has no closed form in h — it is excluded by
    SUPPORTED_ACTIVATIONS and routed to the XLA forward instead."""
    if name == "sigmoid":
        return h * (1.0 - h)
    if name == "tanh":
        return 1.0 - h * h
    if name == "relu":
        return (h > 0).astype(h.dtype)
    raise NotImplementedError(name)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def mlp_forward(spec: mlp.MLPSpec, params, x):
    """Drop-in for models.mlp.apply on the data-parallel path."""
    logits, _ = _forward_pallas(spec, params, x)
    return logits


def _fwd(spec, params, x):
    logits, hiddens = _forward_pallas(spec, params, x)
    return logits, (params, x, hiddens)


def _match_vma(val, like):
    """Reduce a cotangent onto its primal's varying-axis set — the psum
    shard_map's automatic transpose would have inserted (a custom_vjp
    opts out of that machinery, so we reproduce it): a param replicated
    across 'data' gets its per-shard cotangents summed over 'data'."""
    try:
        cur = jax.typeof(val).vma
        want = jax.typeof(like).vma
    except (AttributeError, TypeError):
        return val
    extra_axes = tuple(sorted(cur - want))
    return jax.lax.psum(val, extra_axes) if extra_axes else val


def _bwd(spec, res, g):
    """Backward in the same mixed precision as the forward: matmul
    inputs in ``compute_dtype`` (bf16 keeps the MXU at native rate —
    the backward is 2/3 of the step FLOPs), accumulation and the
    elementwise delta chain in float32."""
    params, x, hiddens = res
    L = spec.num_layers
    cdt = spec.compute_dtype
    mm = lambda a, b: jnp.dot(
        a.astype(cdt), b.astype(cdt), preferred_element_type=jnp.float32
    )
    acts = (x,) + hiddens  # inputs to layers 1..L
    dW = {}
    db = {}
    delta = g.astype(jnp.float32)  # dL/dz_L (chain stays f32 for precision)
    for i in range(L, 0, -1):
        dW[f"W{i}"] = mm(acts[i - 1].T, delta)
        db[f"b{i}"] = jnp.sum(delta, axis=0)
        if i > 1:
            da = mm(delta, params[f"W{i}"].T)
            delta = da * _act_grad(spec.activation, hiddens[i - 2]).astype(
                jnp.float32
            )
    dparams = {
        k: _match_vma(v, params[k]).astype(params[k].dtype)
        for k, v in {**dW, **db}.items()
    }
    dx = mm(delta, params["W1"].T).astype(x.dtype)
    return dparams, dx


mlp_forward.defvjp(_fwd, _bwd)


# ---------------------------------------------------------------------------
# Fused LayerNorm (+ residual add) — forward AND backward as Pallas
# kernels (VERDICT r5: the f32 LayerNorms are the first suspect for the
# transformer_wide MFU gap; ISSUE 6 tentpole (a))
# ---------------------------------------------------------------------------

_LN_EPS = 1e-6      # matches models/transformer._layer_norm exactly
_LN_TILE = 128      # rows per grid step (any rank-2/3 input is
                    # canonicalized to [rows, d] and row-padded)


def _ln_rows(x32, g32, b32):
    """The reference LayerNorm math on f32 rows — the ONE formula the
    Pallas kernels, the XLA fallback and the oracle share (identical
    op sequence to transformer._layer_norm)."""
    mu = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x32 - mu), axis=-1, keepdims=True)
    return (x32 - mu) * jax.lax.rsqrt(var + _LN_EPS) * g32 + b32


def _ln_bwd_rows(dy32, x32, g32):
    """Closed-form LayerNorm backward on f32 rows: with
    xh = (x - mu) * rstd and w = dy * g,
    dx = rstd * (w - mean(w) - xh * mean(w * xh)),
    dg = sum_rows dy * xh, db = sum_rows dy. Shared by the Pallas
    backward kernel and the XLA fallback."""
    mu = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x32 - mu), axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(var + _LN_EPS)
    xh = (x32 - mu) * rstd
    w = dy32 * g32
    dx = rstd * (w - jnp.mean(w, axis=-1, keepdims=True)
                 - xh * jnp.mean(w * xh, axis=-1, keepdims=True))
    return dx, xh


def _ln_fwd_kernel(x_ref, g_ref, b_ref, y_ref):
    y_ref[:] = _ln_rows(x_ref[:].astype(jnp.float32),
                        g_ref[:].astype(jnp.float32),
                        b_ref[:].astype(jnp.float32))


def _ln_res_fwd_kernel(x_ref, r_ref, g_ref, b_ref, y_ref, s_ref):
    # statistics run on the ROUNDED sum (s as emitted), so the kernel
    # agrees with the unfused `s = x + r; LN(s)` composition, with the
    # CPU-shard_map fallback, and with the VJP's recompute-from-s —
    # including sub-f32 result dtypes (a no-op for the model's f32
    # residual stream)
    s = (x_ref[:].astype(jnp.float32)
         + r_ref[:].astype(jnp.float32)).astype(s_ref.dtype)
    s_ref[:] = s
    y_ref[:] = _ln_rows(s.astype(jnp.float32),
                        g_ref[:].astype(jnp.float32),
                        b_ref[:].astype(jnp.float32))


def _ln_bwd_kernel(dy_ref, x_ref, g_ref, dx_ref, dg_ref, db_ref):
    """One row tile's dx plus its dg/db partials, accumulated across
    the (sequentially executed) grid into the single [1, d] blocks —
    the first grid step zero-initializes them. Zero-padded rows are
    exact no-ops: dy = 0 there, so w, dx and both partial sums vanish."""
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        dg_ref[:] = jnp.zeros_like(dg_ref)
        db_ref[:] = jnp.zeros_like(db_ref)

    dy = dy_ref[:].astype(jnp.float32)
    dx, xh = _ln_bwd_rows(dy, x_ref[:].astype(jnp.float32),
                          g_ref[:].astype(jnp.float32))
    dx_ref[:] = dx
    dg_ref[:] += jnp.sum(dy * xh, axis=0, keepdims=True)
    db_ref[:] += jnp.sum(dy, axis=0, keepdims=True)


def _vma_of(x):
    """The varying-manual-axes set of ``x`` under shard_map's typing
    (None on jax versions without it) — shared by every kernel in this
    module."""
    try:
        return jax.typeof(x).vma
    except (AttributeError, TypeError):
        return None


def _lift_to(p, vma):
    """Lift a (replicated) param to the activations' varying-axis set —
    the shard_map typing requirement the MLP kernel documents above.
    Lifts only the axes ``p`` is still invariant over."""
    try:
        have = set(jax.typeof(p).vma)
    except (AttributeError, TypeError):
        return p
    missing = tuple(sorted(set(vma) - have))
    if not missing:
        return p
    from .ring_attention import pvary_axes

    return pvary_axes(p, missing)


def _ln_pad_rows(a2, n_pad):
    n = a2.shape[0]
    return a2 if n == n_pad else jnp.pad(a2, ((0, n_pad - n), (0, 0)))


def _ln_run_fwd(x, g, b, residual=None):
    """Canonicalize to [rows, d], run the forward kernel, restore the
    input rank. Returns ``(y, s)`` — y always f32 (as the reference
    returns), s the residual sum (None without ``residual``)."""
    shape = x.shape
    d = shape[-1]
    x2 = x.reshape(-1, d)
    vma = _vma_of(x2)
    if vma:
        g, b = _lift_to(g, vma), _lift_to(b, vma)
    if _interpret() and vma:
        # CPU inside shard_map: the HLO interpreter drops vma from its
        # loop carries — compute the identical math with XLA ops (the
        # custom-VJP path incl. _match_vma still exercises; the kernel
        # itself is covered by the non-shard_map interpret tests).
        g32, b32 = g.astype(jnp.float32), b.astype(jnp.float32)
        if residual is None:
            return _ln_rows(x.astype(jnp.float32), g32, b32), None
        s = x + residual
        return _ln_rows(s.astype(jnp.float32), g32, b32), s
    n = x2.shape[0]
    n_pad = max(_LN_TILE, ((n + _LN_TILE - 1) // _LN_TILE) * _LN_TILE)
    xp = _ln_pad_rows(x2, n_pad)
    g2 = g.reshape(1, d)
    b2 = b.reshape(1, d)
    grid = (n_pad // _LN_TILE,)
    row_spec = pl.BlockSpec((_LN_TILE, d), lambda i: (i, 0))
    vec_spec = pl.BlockSpec((1, d), lambda i: (0, 0))
    _sds = ((lambda sh, dt: jax.ShapeDtypeStruct(sh, dt, vma=vma)) if vma
            else (lambda sh, dt: jax.ShapeDtypeStruct(sh, dt)))
    if residual is None:
        y = pl.pallas_call(
            _ln_fwd_kernel, grid=grid,
            in_specs=[row_spec, vec_spec, vec_spec],
            out_specs=row_spec,
            out_shape=_sds((n_pad, d), jnp.float32),
            interpret=_interpret(),
        )(xp, g2, b2)
        return y[:n].reshape(shape).astype(jnp.float32), None
    r2 = residual.reshape(-1, d)
    s_dtype = jnp.result_type(x.dtype, residual.dtype)
    y, s = pl.pallas_call(
        _ln_res_fwd_kernel, grid=grid,
        in_specs=[row_spec, row_spec, vec_spec, vec_spec],
        out_specs=[row_spec, row_spec],
        out_shape=[_sds((n_pad, d), jnp.float32),
                   _sds((n_pad, d), s_dtype)],
        interpret=_interpret(),
    )(xp, _ln_pad_rows(r2, n_pad), g2, b2)
    return (y[:n].reshape(shape).astype(jnp.float32),
            s[:n].reshape(shape))


def _ln_run_bwd(dy, x, g):
    """-> (dx f32 [x.shape], dg f32 [d], db f32 [d]); the statistics
    are recomputed from the saved normalization input (x, or the
    residual sum s) — cheaper than stashing an extra [rows, d] xhat."""
    shape = x.shape
    d = shape[-1]
    dy2 = dy.reshape(-1, d)
    x2 = x.reshape(-1, d)
    vma = _vma_of(dy2) or _vma_of(x2)
    if vma:
        g = _lift_to(g, vma)
    if _interpret() and vma:
        dy32 = dy.astype(jnp.float32)
        dx, xh = _ln_bwd_rows(dy32, x.astype(jnp.float32),
                              g.astype(jnp.float32))
        red = tuple(range(dy.ndim - 1))
        return dx, jnp.sum(dy32 * xh, red), jnp.sum(dy32, red)
    n = x2.shape[0]
    n_pad = max(_LN_TILE, ((n + _LN_TILE - 1) // _LN_TILE) * _LN_TILE)
    grid = (n_pad // _LN_TILE,)
    row_spec = pl.BlockSpec((_LN_TILE, d), lambda i: (i, 0))
    vec_spec = pl.BlockSpec((1, d), lambda i: (0, 0))
    _sds = ((lambda sh, dt: jax.ShapeDtypeStruct(sh, dt, vma=vma)) if vma
            else (lambda sh, dt: jax.ShapeDtypeStruct(sh, dt)))
    dx, dg, db = pl.pallas_call(
        _ln_bwd_kernel, grid=grid,
        in_specs=[row_spec, row_spec, vec_spec],
        out_specs=[row_spec, vec_spec, vec_spec],
        out_shape=[_sds((n_pad, d), jnp.float32),
                   _sds((1, d), jnp.float32),
                   _sds((1, d), jnp.float32)],
        interpret=_interpret(),
    )(_ln_pad_rows(dy2, n_pad), _ln_pad_rows(x2, n_pad), g.reshape(1, d))
    return dx[:n].reshape(shape), dg[0], db[0]


@jax.custom_vjp
def fused_layer_norm(x, g, b):
    """Drop-in for models/transformer._layer_norm (rank-2 [N, d] or
    rank-3 [B, S, d]; f32 statistics and output) as ONE Pallas kernel:
    the mean/variance/normalize/scale chain runs on the VPU with the
    row tile resident in VMEM instead of five XLA elementwise passes
    over HBM. Backward is a second Pallas kernel (dx + accumulated
    dg/db) via this custom VJP. Interpret mode on CPU."""
    y, _ = _ln_run_fwd(x, g, b)
    return y


def _fused_ln_fwd(x, g, b):
    y, _ = _ln_run_fwd(x, g, b)
    return y, (x, g, b)


def _fused_ln_bwd(res, dy):
    x, g, b = res
    dx, dg, db = _ln_run_bwd(dy, x, g)
    return (dx.astype(x.dtype),
            _match_vma(dg, g).astype(g.dtype),
            _match_vma(db, b).astype(b.dtype))


fused_layer_norm.defvjp(_fused_ln_fwd, _fused_ln_bwd)


@jax.custom_vjp
def fused_layer_norm_residual(x, r, g, b):
    """Residual-add fused into the LayerNorm that consumes it:
    ``s = x + r; y = LN(s)`` in one kernel pass — the summed stream
    never round-trips HBM between the add and the statistics. Returns
    ``(y, s)``: callers keep ``s`` as the new residual stream. The VJP
    routes both cotangents (dy through the LN backward kernel, ds
    directly) to the identical dx == dr."""
    y, s = _ln_run_fwd(x, g, b, residual=r)
    return y, s


def _fused_ln_res_fwd(x, r, g, b):
    y, s = _ln_run_fwd(x, g, b, residual=r)
    # zero-size dtype carriers: custom_vjp residuals must be JAX values
    return (y, s), (s, g, b, jnp.zeros((0,), x.dtype),
                    jnp.zeros((0,), r.dtype))


def _fused_ln_res_bwd(res, cts):
    s, g, b, x_proto, r_proto = res
    dy, ds = cts
    dx, dg, db = _ln_run_bwd(dy, s, g)
    d_sum = dx + ds.astype(jnp.float32)
    return (d_sum.astype(x_proto.dtype), d_sum.astype(r_proto.dtype),
            _match_vma(dg, g).astype(g.dtype),
            _match_vma(db, b).astype(b.dtype))


fused_layer_norm_residual.defvjp(_fused_ln_res_fwd, _fused_ln_res_bwd)


# ---------------------------------------------------------------------------
# Grouped MoE expert matmul (ragged-dot style) — ISSUE 6 tentpole (b):
# the sparse dispatch packs each expert's tokens into its capacity
# buffer [E, C, d]; this kernel runs BOTH expert matmuls fused per
# (expert, capacity-tile) grid cell, the [C_t, ff] hidden staying in
# VMEM instead of materializing the [E, C, ff] tensor in HBM between
# two batched einsums.
# ---------------------------------------------------------------------------

_MOE_CAP_TILE = 128   # capacity rows per grid step


def _moe_kernel(activation: str, with_z1: bool):
    def kernel(x_ref, w1_ref, b1_ref, w2_ref, b2_ref, out_ref,
               *z1_refs):
        # mixed precision exactly as the XLA grouped einsums: matmul
        # inputs arrive pre-cast to compute_dtype, accumulation/bias/
        # activation in f32, hidden rounded to compute_dtype between
        # the two matmuls
        z1 = jnp.dot(x_ref[:], w1_ref[:],
                     preferred_element_type=jnp.float32) + b1_ref[:]
        h1 = _act(activation, z1).astype(x_ref.dtype)
        out_ref[:] = jnp.dot(h1, w2_ref[:],
                             preferred_element_type=jnp.float32) + b2_ref[:]
        if with_z1:
            # the VJP's residual; primal-only calls skip this output
            # entirely so the hidden truly never touches HBM
            z1_refs[0][:] = z1

    return kernel


def _moe_grouped_forward(activation, cdt, buf, we1, be1, we2, be2,
                         want_z1: bool):
    """(h2 [E, C, d] f32, z1 [E, C, ff] f32 or None): the fused grouped
    expert FFN plus — only when ``want_z1`` (the VJP forward rule) —
    the pre-activation residual (gelu has no derivative in the
    activation OUTPUT, so the saved residual is the pre-activation —
    one [E, C, ff] f32 buffer, the same thing XLA autodiff stashes for
    the reference einsum path). Primal-only calls (eval, decode, the
    bench component timing) skip the z1 output entirely, so the hidden
    genuinely never round-trips HBM."""
    e, c, d = buf.shape
    ff = we1.shape[-1]
    vma = _vma_of(buf)
    if vma:
        we1, be1 = _lift_to(we1, vma), _lift_to(be1, vma)
        we2, be2 = _lift_to(we2, vma), _lift_to(be2, vma)
    act = mlp._ACTIVATIONS[activation]
    if _interpret() and vma:
        # CPU inside shard_map (see _ln_run_fwd): identical math, XLA
        # ops (an unused z1 dead-code-eliminates there)
        z1 = jnp.einsum("ecd,edf->ecf", buf.astype(cdt), we1.astype(cdt),
                        preferred_element_type=jnp.float32) \
            + be1[:, None].astype(jnp.float32)
        h1 = act(z1).astype(cdt)
        h2 = jnp.einsum("ecf,efd->ecd", h1, we2.astype(cdt),
                        preferred_element_type=jnp.float32) \
            + be2[:, None].astype(jnp.float32)
        return h2, (z1 if want_z1 else None)
    c_pad = max(_MOE_CAP_TILE,
                ((c + _MOE_CAP_TILE - 1) // _MOE_CAP_TILE) * _MOE_CAP_TILE)
    xp = buf.astype(cdt)
    if c_pad != c:
        xp = jnp.pad(xp, ((0, 0), (0, c_pad - c), (0, 0)))
    grid = (e, c_pad // _MOE_CAP_TILE)
    _sds = ((lambda sh, dt: jax.ShapeDtypeStruct(sh, dt, vma=vma)) if vma
            else (lambda sh, dt: jax.ShapeDtypeStruct(sh, dt)))
    out_specs = [
        pl.BlockSpec((None, _MOE_CAP_TILE, d), lambda i, j: (i, j, 0)),
    ]
    out_shape = [_sds((e, c_pad, d), jnp.float32)]
    if want_z1:
        out_specs.append(
            pl.BlockSpec((None, _MOE_CAP_TILE, ff), lambda i, j: (i, j, 0)))
        out_shape.append(_sds((e, c_pad, ff), jnp.float32))
    outs = pl.pallas_call(
        _moe_kernel(activation, want_z1),
        grid=grid,
        in_specs=[
            pl.BlockSpec((None, _MOE_CAP_TILE, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((None, d, ff), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((None, 1, ff), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((None, ff, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((None, 1, d), lambda i, j: (i, 0, 0)),
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=_interpret(),
    )(xp, we1.astype(cdt), be1.astype(jnp.float32).reshape(e, 1, ff),
      we2.astype(cdt), be2.astype(jnp.float32).reshape(e, 1, d))
    if want_z1:
        return outs[0][:, :c], outs[1][:, :c]
    return outs[0][:, :c], None


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1))
def moe_grouped_matmul(activation: str, cdt, buf, we1, be1, we2, be2):
    """Fused grouped expert FFN ``[E, C, d] -> [E, C, d]`` (f32 out,
    like the XLA einsum path it replaces in
    models/transformer._grouped_expert_ffn): one Pallas kernel loops
    (expert, capacity-tile) grid cells with the expert's weight pair
    resident in VMEM. VMEM budget: ~2·d·ff·sizeof(cdt) for the weights
    plus the [tile, ff] hidden — d=1024, ff=2048 bf16 fits with room;
    larger d_ff needs an ff-tiling extension. Backward is XLA einsums
    in the same mixed precision (matmul inputs cdt, f32 accumulation),
    with the activation derivative taken exactly via jax.vjp on the
    saved pre-activation. Interpret mode on CPU."""
    h2, _ = _moe_grouped_forward(activation, cdt, buf, we1, be1, we2,
                                 be2, want_z1=False)
    return h2


def _moe_grouped_fwd(activation, cdt, buf, we1, be1, we2, be2):
    h2, z1 = _moe_grouped_forward(activation, cdt, buf, we1, be1, we2,
                                  be2, want_z1=True)
    return h2, (buf, we1, be1, we2, be2, z1)


def _moe_grouped_bwd(activation, cdt, res, g):
    buf, we1, be1, we2, be2, z1 = res
    act = mlp._ACTIVATIONS[activation]
    mm = lambda sub, a, b_: jnp.einsum(
        sub, a.astype(cdt), b_.astype(cdt),
        preferred_element_type=jnp.float32)
    h1 = act(z1).astype(cdt)
    dwe2 = mm("ecf,ecd->efd", h1, g)
    dbe2 = jnp.sum(g.astype(jnp.float32), axis=1)
    dh1 = mm("ecd,efd->ecf", g, we2)
    _, act_vjp = jax.vjp(act, z1)
    (dz1,) = act_vjp(dh1)
    dwe1 = mm("ecd,ecf->edf", buf, dz1)
    dbe1 = jnp.sum(dz1, axis=1)
    dbuf = mm("ecf,edf->ecd", dz1, we1)
    out = (dbuf, dwe1, dbe1, dwe2, dbe2)
    prim = (buf, we1, be1, we2, be2)
    return tuple(_match_vma(dv, p).astype(p.dtype)
                 for dv, p in zip(out, prim))


moe_grouped_matmul.defvjp(_moe_grouped_fwd, _moe_grouped_bwd)


# ---------------------------------------------------------------------------
# fp8 FFN matmuls (ISSUE 11 tentpole (b)): the dense and grouped-MoE
# expert FFNs on fp8-e4m3-rounded operands.
#
# The fp8 path REUSES the fused grouped kernel above: operands are
# rounded onto the scaled fp8 grid first (ops/quant.fp8_round —
# power-of-two per-expert scales, so the scaled-back values are exact
# in bf16/f32), then flow through the identical Pallas kernel /
# interpret-mode / shard_map-vma fallbacks.  With pow2 scales this is
# bit-what-an-fp8-MXU computes — (q_x·s_x)@(q_w·s_w) == s_x·s_w·
# (q_x@q_w) with f32 accumulation — without a second kernel body to
# keep in sync.  The inter-matmul hidden stays in the compute dtype:
# inside the fused kernel it never leaves VMEM, so quantizing it
# would spend precision on bandwidth that is not being moved (the
# HBM-resident operands are where fp8 pays).
#
# Gradients are straight-through to the bf16/f32 MASTER weights: the
# backward is the grouped kernel's XLA-einsum backward evaluated on
# the saved QUANTIZED residuals (what real fp8 training differentiates
# — the rounded operands the forward actually used), with d(round)/dx
# treated as identity.  Scales are just-in-time per call; the
# delayed-scaling amax-history helpers (ops/quant.amax_history_*) are
# oracle-tested and available to callers that thread aux state, and a
# length-1 history degenerates to exactly this scaling.
# ---------------------------------------------------------------------------


def _fp8_operands(buf, we1, we2):
    """Round the three matmul operands onto their per-expert fp8
    grids (axis (1, 2) = everything but the leading expert dim)."""
    from .quant import fp8_round

    with jax.named_scope("quant"):
        return (fp8_round(buf, axis=(1, 2)), fp8_round(we1, axis=(1, 2)),
                fp8_round(we2, axis=(1, 2)))


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1))
def fp8_grouped_matmul(activation: str, cdt, buf, we1, be1, we2, be2):
    """``moe_grouped_matmul`` on fp8-e4m3-rounded operands: the fused
    grouped expert FFN ``[E, C, d] -> [E, C, d]`` (f32 out) with
    ``buf``/``we1``/``we2`` rounded per expert onto pow2-scaled fp8
    grids before the two fused matmuls (biases and accumulation stay
    f32 — the e4m3 recipe).  Selected by ``TransformerSpec.fp8_ffn``
    for the sparse-dispatch expert FFN; drop-in for the bf16 kernel,
    within the oracle-tested error bounds (tests/test_pallas.py)."""
    bq, w1q, w2q = _fp8_operands(buf, we1, we2)
    h2, _ = _moe_grouped_forward(activation, cdt, bq, w1q, be1, w2q,
                                 be2, want_z1=False)
    return h2


def _fp8_grouped_fwd(activation, cdt, buf, we1, be1, we2, be2):
    bq, w1q, w2q = _fp8_operands(buf, we1, we2)
    h2, z1 = _moe_grouped_forward(activation, cdt, bq, w1q, be1, w2q,
                                  be2, want_z1=True)
    # residuals are the QUANTIZED operands: the backward differentiates
    # the computation the forward ran; the quantizer itself is
    # straight-through (cotangents land on the master weights as-is)
    return h2, (bq, w1q, be1, w2q, be2, z1)


def _fp8_grouped_bwd(activation, cdt, res, g):
    return _moe_grouped_bwd(activation, cdt, res, g)


fp8_grouped_matmul.defvjp(_fp8_grouped_fwd, _fp8_grouped_bwd)


def fp8_dense_ffn(activation: str, cdt, x2, w1, b1, w2, b2):
    """The DENSE FFN (``act(x @ W1 + b1) @ W2 + b2``) on fp8-rounded
    operands: ``x2`` [T, d] -> [T, d] f32, routed through
    ``fp8_grouped_matmul`` as a single-expert group (E=1) so the
    dense and MoE fp8 paths share one kernel, one VJP and one oracle
    suite.  Selected by ``TransformerSpec.fp8_ffn`` at every dense
    FFN site (training forward and the KV-cached decode)."""
    out = fp8_grouped_matmul(activation, cdt, x2[None], w1[None],
                             b1[None], w2[None], b2[None])
    return out[0]
