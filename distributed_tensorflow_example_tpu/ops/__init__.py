from .losses import cross_entropy, stable_cross_entropy, naive_cross_entropy
from .metrics import accuracy

__all__ = ["cross_entropy", "stable_cross_entropy", "naive_cross_entropy", "accuracy"]
