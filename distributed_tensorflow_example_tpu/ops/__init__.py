from .losses import cross_entropy, stable_cross_entropy, naive_cross_entropy
from .metrics import accuracy

# NOTE: ring_attention / pallas_fused are imported as submodules
# (pkg.ops.ring_attention.ring_attention) — re-exporting the
# ring_attention *function* here would shadow its module name.
__all__ = ["cross_entropy", "stable_cross_entropy", "naive_cross_entropy", "accuracy"]
