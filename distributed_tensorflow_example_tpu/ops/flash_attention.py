"""Blockwise (flash-style) attention as Pallas TPU kernels.

The intra-chip complement to ops/ring_attention.py: the ring splits the
sequence ACROSS chips (ppermute neighbor exchange); this kernel makes
the per-chip block computation memory-lean by never materializing the
``[S, S]`` score matrix. The grid is ``(batch*heads, q_tiles, k_tiles)``
with the k-tile dimension innermost and sequential: each program
combines one (q tile, k tile) pair into VMEM scratch accumulators
(running max ``m``, normalizer ``l``, un-normalized output ``acc``)
via the same online-softmax recurrence the ring uses, initializing at
``j == 0`` and writing the normalized output at ``j == nk-1``. Peak
memory is O(blk·D) per program — sequence length is bounded by HBM
only (tested to S=16384 where dense scores would need 17 GB).

Beyond-reference capability (the reference has no attention at all,
/root/reference/example.py:84-90; SURVEY.md §5).

Throughput design (tuned on a v5e chip, measured by in-program
dispatch chains so tunnel round-trips cancel):
- **Tile size**: 2048x1024 rectangular q/k tiles (``_pick_tiles``) —
  the dominant lever. The kernel is bounded by per-grid-step overhead
  and VPU softmax passes, both of which amortize with tile area
  (256-tiles ran ~11 TF/s, 1024-tiles ~41 TF/s f32 / ~55-85 TF/s
  bf16 on ``[4,4096,8,64]`` causal; the bundled production kernel
  measures ~48 TF/s bf16 at its best block size on the same chip and
  method; the d=64 head-dim caps the MXU at ~98 TF/s of the 197 bf16
  peak — d=128 drives the full contraction width at 110-156 TF/s).
  blk_q doubles blk_k when S divides (r5): the 2:1 tile amortizes
  every k/v fetch over twice the q rows (+13% in-window) at
  [2048, 1024] f32 score/p intermediates (8 MB, inside the VMEM
  cap). blk_k shrinks to keep dividing the padded sequence, capped
  at 512 when D > 128 — and for D > 128 the doubled blk_q is bounded
  by the same 512 cap (square tiles; the wide head already scales
  the backward's VMEM working set).
- **Causal fetch elimination** (r5): dead (above-diagonal) grid
  steps clamp their fetch indices to the causal frontier
  (``_causal_frontier``) — the Pallas pipeline elides repeated-index
  copies, so skipped steps cost grid overhead, not HBM traffic.
- **exp2 scores**: q is pre-scaled ONCE by ``log2(e)/sqrt(d)``
  (O(S·D)), so the kernel's scores live in the log2 domain and every
  transcendental is a raw ``exp2`` — the per-tile O(blk²) scale
  multiply and the exp→exp2 argument conversion both disappear. All
  saved/returned softmax statistics are converted back to the natural
  domain at the tile boundary (O(blk) per tile), so the ring's
  ``_merge_partials`` and every downstream consumer are unchanged.
- **Causal tile classes**: strictly-below-diagonal tiles run a
  mask-free body (no iota/compare/select passes); only
  diagonal-crossing tiles mask; above-diagonal tiles are skipped
  outright with ``pl.when``. The fully-masked-row guard the XLA
  paths need is omitted in the kernels because every row's running
  max is finite BEFORE any fully-masked rows appear: under causal
  masking every row sees k position 0, so the ascending k stream's
  j=0 tile (always computed — interior or crossing, never skipped)
  contributes a real score to every row; fully-masked rows in later
  crossing tiles (which DO occur under the 2:1 rectangular tiles —
  e.g. q tile i's rows below 2048i+1024 against k tile 2i+1) then
  hold NEG_INF entries that underflow via ``exp2(NEG_INF - m)`` to
  exactly 0.0 against that finite max. This ordering argument is
  load-bearing: a k stream that skips or reorders tile 0 would
  evaluate ``exp2(NEG_INF - NEG_INF) = 1`` and corrupt l/acc.

Training: ``flash_attention`` carries a ``jax.custom_vjp`` whose
backward is ALSO tiled Pallas (``_make_dq_kernel`` /
``_make_dkv_kernel``): the forward saves only (o, m, l) — O(S)
residuals — and each backward tile recomputes its probabilities from
the saved softmax statistics (``_bwd_tile``, shared by both kernels),
applies the softmax VJP ``ds = p * (dp - rowsum(do*o))``, and
accumulates dq (streaming k tiles past each q tile) and dk/dv
(streaming q tiles past each k tile) in VMEM scratch. Forward AND
backward are O(S·blk) — long-context training memory is bounded by
HBM, not by an [S, S] score tensor. The backward kernels consume the
same pre-scaled-q / exp2 form (constant factors fold into the
finalize writes: dq scales by 1/sqrt(d), dk by 1/log2(e)).

Ragged shapes (S not a multiple of the 256 alignment) by direction:
non-causal ragged runs exact dense XLA in BOTH directions (padded keys
would corrupt real rows); causal ragged keeps the O(S·blk) kernels in
BOTH directions — the VJP pads q/k/v/do to the tile multiple, where
the global-position causal mask zeroes padded keys for every real row
and zero-padded ``do`` rows contribute nothing to dk/dv, then slices
the gradients back. Cross-length q/k (``k.shape[1] != q.shape[1]``)
always delegates to the dense path, which supports it non-causally and
rejects it causally.

On non-TPU backends the kernels run in Pallas interpret mode, so the
CPU test suite exercises the same code paths bit-for-bit.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .ring_attention import NEG_INF, attention as dense_attention

_BLK = 256   # sequence ALIGNMENT: pad unit and minimum tile length
             # (ring_attention gates its kernel path on S % _BLK)
_BLK_PREF = 1024   # preferred tile length (VPU/grid overhead amortizer)
_LOG2E = float(np.log2(np.e))
_LN2 = float(np.log(2.0))


def _interpret() -> bool:
    return jax.default_backend() == "cpu"


def _pick_tiles(s: int, d: int) -> tuple[int, int]:
    """(blk_q, blk_k) for a padded length ``s`` (s % _BLK == 0):
    blk_k is the largest power-of-two tile in [_BLK, _BLK_PREF]
    dividing s (capped at 512 when D > 128 to keep the backward
    kernels' [blk_q, blk_k] intermediates inside scoped VMEM);
    blk_q doubles it when s allows — a 2:1 rectangular tile amortizes
    every k/v fetch over twice the q rows (measured +13% on
    [4,4096,8,64] bf16 causal) at 2x the [blk_q, blk_k] score/p VMEM
    (8 MB f32 at 2048x1024, well inside the 100 MB cap). For D > 128
    the doubled blk_q is ALSO bounded by the 512 cap (square tiles):
    wide heads already multiply the backward kernels' [blk_q, blk_k]
    intermediates and the q/do fetch buffers by D/128 — doubling q on
    top would run twice the scoped-VMEM budget the cap protects
    (ADVICE r5 #1; tests/test_flash_attention.py pins the geometry)."""
    cap = _BLK_PREF if d <= 128 else 512
    blk = _BLK
    while blk * 2 <= cap and s % (blk * 2) == 0:
        blk *= 2
    blk_q = blk * 2 if s % (blk * 2) == 0 else blk
    if d > 128:
        blk_q = min(blk_q, cap)
    return blk_q, blk


def _compiler_params():
    # bh and the q-tile grid dims are independent programs; only the
    # k-tile dim carries the scratch recurrence. The raised VMEM cap
    # covers the backward kernels' three [blk, blk] f32 intermediates
    # at the 1024 tile (p/dp/ds = 12 MB + operand tiles).
    return pltpu.CompilerParams(
        dimension_semantics=("parallel", "parallel", "arbitrary"),
        vmem_limit_bytes=100 * 1024 * 1024,
    )


def _prescale(q):
    """Fold softmax scale AND the exp->exp2 conversion into q once:
    scores computed from the returned q are (q·kᵀ)/sqrt(d)·log2(e) —
    natural-domain scores in log2 units."""
    c = _LOG2E / np.sqrt(q.shape[-1])
    return (q.astype(jnp.float32) * c).astype(q.dtype)


def _causal_frontier(i, blk_q: int, blk_k: int):
    """Last k tile visible to q tile ``i`` under the causal mask —
    the tile holding q row ``(i+1)*blk_q - 1``'s diagonal. Must stay
    consistent with ``_causal_tile_classes``' visibility predicate:
    the fetch-elision clamps (forward kv, backward k/v and q/do) are
    only safe while every live (computed) step fetches its true
    tile."""
    return ((i + 1) * blk_q - 1) // blk_k


def _causal_first_q(j, blk_q: int, blk_k: int):
    """First q tile that sees k tile ``j`` (the dkv kernel's stream
    start) — sibling of ``_causal_frontier``."""
    return (j * blk_k) // blk_q


def _causal_tile_classes(iq, blk_q, j, blk_k):
    """(interior, crossing) predicates for a (q tile, k tile) pair
    under the global-position causal mask. Interior tiles are fully
    visible (no mask work); crossing tiles straddle the diagonal
    (masked); everything else is fully masked (skipped)."""
    interior = (j + 1) * blk_k - 1 <= iq * blk_q
    visible = j * blk_k <= iq * blk_q + blk_q - 1
    return interior, jnp.logical_and(visible, jnp.logical_not(interior))


def _make_kernel(blk_q: int, blk_k: int, causal: bool, compute_dtype,
                 return_stats: bool = False):
    def kernel(q_ref, k_ref, v_ref, o_ref, *rest):
        if return_stats:
            m_out, l_out, m_scr, l_scr, acc_scr = rest
        else:
            m_scr, l_scr, acc_scr = rest
        iq = pl.program_id(1)
        j = pl.program_id(2)
        nk = pl.num_programs(2)

        @pl.when(j == 0)
        def _init():
            m_scr[...] = jnp.full_like(m_scr[...], NEG_INF)
            l_scr[...] = jnp.zeros_like(l_scr[...])
            acc_scr[...] = jnp.zeros_like(acc_scr[...])

        def _compute(masked: bool):
            q = q_ref[0].astype(compute_dtype)     # [blk_q, d], prescaled
            k = k_ref[0].astype(compute_dtype)
            v = v_ref[0].astype(compute_dtype)
            s = _tile_scores(q, k, iq, j, blk_q, blk_k, masked)
            m = m_scr[...]
            m_blk = jnp.max(s, axis=-1, keepdims=True)
            m_new = jnp.maximum(m, m_blk)
            # log2-domain online softmax: masked entries are NEG_INF
            # and exp2(NEG_INF - finite) == 0.0 exactly; every row's
            # m is finite by the time a fully-masked row can appear
            # (the j=0 tile always computes and every row sees k
            # position 0 — module docstring's ordering argument)
            p = jnp.exp2(s - m_new)
            alpha = jnp.exp2(m - m_new)
            m_scr[...] = m_new
            l_scr[...] = l_scr[...] * alpha + jnp.sum(
                p, axis=-1, keepdims=True)
            acc_scr[...] = acc_scr[...] * alpha + jax.lax.dot_general(
                p.astype(compute_dtype), v, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )

        if causal:
            interior, crossing = _causal_tile_classes(iq, blk_q, j, blk_k)
            pl.when(interior)(lambda: _compute(False))
            pl.when(crossing)(lambda: _compute(True))
        else:
            _compute(False)

        @pl.when(j == nk - 1)
        def _finalize():
            if return_stats:
                # raw partials for cross-block merging (ring SP): the
                # un-normalized accumulator plus its (m, l) statistics —
                # m converted to the NATURAL log domain so downstream
                # consumers (_merge_partials, the backward) are
                # exp2-agnostic
                o_ref[0] = acc_scr[...].astype(o_ref.dtype)
                m_out[0] = m_scr[...] * _LN2
                l_out[0] = l_scr[...]
            else:
                o_ref[0] = (
                    acc_scr[...] / jnp.maximum(l_scr[...], 1e-30)
                ).astype(o_ref.dtype)

    return kernel


def _tile_scores(q, k, q_tile, k_tile, blk_q: int, blk_k: int,
                 masked: bool):
    """log2-domain scores q·kᵀ for one tile pair (q arrives pre-scaled
    by log2(e)/sqrt(d)) with the global-position causal mask when
    ``masked`` — shared by the forward and both backward kernels."""
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    if masked:
        q_pos = q_tile * blk_q + jax.lax.broadcasted_iota(
            jnp.int32, (blk_q, blk_k), 0)
        k_pos = k_tile * blk_k + jax.lax.broadcasted_iota(
            jnp.int32, (blk_q, blk_k), 1)
        s = jnp.where(k_pos <= q_pos, s, NEG_INF)
    return s


def _bwd_tile(q2, k, v, do, m, l, dlt, q_tile, k_tile, blk_q: int,
              blk_k: int, masked: bool):
    """Shared backward tile math: recompute this tile's normalized
    probabilities from the saved (m, l) stats — ``q2`` is pre-scaled so
    scores are log2-domain and ``m`` (natural) converts with one O(blk)
    multiply — and apply the softmax VJP. Returns (p, ds)."""
    s = _tile_scores(q2, k, q_tile, k_tile, blk_q, blk_k, masked)
    p = jnp.exp2(s - m * _LOG2E) / jnp.maximum(l, 1e-30)
    dp = jax.lax.dot_general(                     # do @ v^T
        do, v, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    ds = p * (dp - dlt)
    return p, ds


def _make_dq_kernel(blk_q: int, blk_k: int, causal: bool, compute_dtype,
                    scale: float):
    """dq accumulation: grid (bh, iq, jk), jk innermost sequential.
    The softmax scale folds into the single finalize write."""

    def kernel(q_ref, k_ref, v_ref, do_ref, m_ref, l_ref, dlt_ref,
               dq_ref, dq_scr):
        iq = pl.program_id(1)
        j = pl.program_id(2)
        nk = pl.num_programs(2)

        @pl.when(j == 0)
        def _init():
            dq_scr[...] = jnp.zeros_like(dq_scr[...])

        def _compute(masked: bool):
            k = k_ref[0].astype(compute_dtype)
            _, ds = _bwd_tile(
                q_ref[0].astype(compute_dtype), k,
                v_ref[0].astype(compute_dtype),
                do_ref[0].astype(compute_dtype),
                m_ref[0], l_ref[0], dlt_ref[0], iq, j, blk_q, blk_k,
                masked,
            )
            dq_scr[...] += jax.lax.dot_general(   # ds @ k
                ds.astype(compute_dtype), k, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )

        if causal:  # skip k tiles past the causal frontier
            interior, crossing = _causal_tile_classes(iq, blk_q, j, blk_k)
            pl.when(interior)(lambda: _compute(False))
            pl.when(crossing)(lambda: _compute(True))
        else:
            _compute(False)

        @pl.when(j == nk - 1)
        def _finalize():
            dq_ref[0] = (dq_scr[...] * scale).astype(dq_ref.dtype)

    return kernel


def _make_dkv_kernel(blk_q: int, blk_k: int, causal: bool, compute_dtype):
    """dk/dv accumulation: grid (bh, jk, iq), iq innermost sequential
    (each program owns one k tile and streams q tiles through it). The
    pre-scaled q folds log2(e)·scale into dk; the finalize write
    divides the log2(e) back out, leaving the wanted ds·scale @ q."""

    def kernel(q_ref, k_ref, v_ref, do_ref, m_ref, l_ref, dlt_ref,
               dk_ref, dv_ref, dk_scr, dv_scr):
        j = pl.program_id(1)
        i = pl.program_id(2)
        nq = pl.num_programs(2)

        @pl.when(i == 0)
        def _init():
            dk_scr[...] = jnp.zeros_like(dk_scr[...])
            dv_scr[...] = jnp.zeros_like(dv_scr[...])

        def _compute(masked: bool):
            q2 = q_ref[0].astype(compute_dtype)
            do = do_ref[0].astype(compute_dtype)
            p, ds = _bwd_tile(
                q2, k_ref[0].astype(compute_dtype),
                v_ref[0].astype(compute_dtype), do,
                m_ref[0], l_ref[0], dlt_ref[0], i, j, blk_q, blk_k,
                masked,
            )
            dv_scr[...] += jax.lax.dot_general(   # p^T @ do
                p.astype(compute_dtype), do, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            dk_scr[...] += jax.lax.dot_general(   # ds^T @ q2
                ds.astype(compute_dtype), q2, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )

        if causal:  # q tiles before this k tile see none of its keys
            interior, crossing = _causal_tile_classes(i, blk_q, j, blk_k)
            pl.when(interior)(lambda: _compute(False))
            pl.when(crossing)(lambda: _compute(True))
        else:
            _compute(False)

        @pl.when(i == nq - 1)
        def _finalize():
            dk_ref[0] = (dk_scr[...] * (1.0 / _LOG2E)).astype(dk_ref.dtype)
            dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)

    return kernel


def _flash_call(qf, kf, vf, causal: bool, blk: int, return_stats: bool):
    """Shared forward launcher on pre-flattened [BH, S, D] arrays with
    S % blk == 0 (``blk`` is the alignment; actual tiles come from
    _pick_tiles). return_stats=False -> normalized output [BH, S, D];
    True -> (acc f32, m, l) raw partials (natural-domain m)."""
    bh, s, d = qf.shape
    qf = _prescale(qf)
    blk_q, blk_k = _pick_tiles(s, d)
    try:
        vma = jax.typeof(qf).vma
    except (AttributeError, TypeError):
        vma = None

    def sds(shape, dt):
        if vma:
            return jax.ShapeDtypeStruct(shape, dt, vma=vma)
        return jax.ShapeDtypeStruct(shape, dt)

    tile_q = pl.BlockSpec((1, blk_q, d), lambda b, i, j: (b, i, 0))
    # causal: clamp the k/v fetch index to the causal frontier — dead
    # (above-diagonal) grid steps then request the SAME tile as the
    # row's last live step, and the Pallas pipeline elides the
    # refetch (it re-issues a copy only when the block index
    # changes), so skipped steps cost grid overhead, not HBM traffic.
    # The frontier for q tile i is _causal_frontier (blk_q-vs-blk_k
    # general). Safe because the tile-class predicates use the
    # UNCLAMPED program id: dead steps compute nothing and the
    # j == nk-1 finalize only reads scratch.
    kv_idx = ((lambda b, i, j:
               (b, jnp.minimum(j, _causal_frontier(i, blk_q, blk_k)), 0))
              if causal else (lambda b, i, j: (b, j, 0)))
    kv_spec = pl.BlockSpec((1, blk_k, d), kv_idx)
    tile_1 = pl.BlockSpec((1, blk_q, 1), lambda b, i, j: (b, i, 0))
    if return_stats:
        out_specs = [tile_q, tile_1, tile_1]
        out_shape = [sds((bh, s, d), jnp.float32),
                     sds((bh, s, 1), jnp.float32),
                     sds((bh, s, 1), jnp.float32)]
    else:
        out_specs = tile_q
        out_shape = sds((bh, s, d), qf.dtype)
    return pl.pallas_call(
        _make_kernel(blk_q, blk_k, causal, qf.dtype, return_stats),
        grid=(bh, s // blk_q, s // blk_k),
        in_specs=[tile_q, kv_spec, kv_spec],
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[
            pltpu.VMEM((blk_q, 1), jnp.float32),   # running max m (log2)
            pltpu.VMEM((blk_q, 1), jnp.float32),   # normalizer l
            pltpu.VMEM((blk_q, d), jnp.float32),   # un-normalized output
        ],
        compiler_params=_compiler_params(),
        interpret=_interpret(),
    )(qf, kf, vf)


@functools.partial(jax.jit, static_argnums=(3, 4))
def _flash_forward(q, k, v, causal: bool, blk: int):
    """[B, S, H, D] -> [B, S, H, D] via the tiled kernel."""
    b, s, h, d = q.shape
    s_pad = max(blk, ((s + blk - 1) // blk) * blk)

    def prep(x):
        x = jnp.pad(x, ((0, 0), (0, s_pad - s), (0, 0), (0, 0)))
        return x.transpose(0, 2, 1, 3).reshape(b * h, s_pad, d)

    if k.shape[1] != s or (s_pad != s and not causal):
        # Two dense-fallback cases: (1) cross-length q/k — the kernel's
        # tiling assumes square [S, S] score geometry, and
        # dense_attention handles unequal lengths (causal cross-length
        # is rejected there with a clear error rather than a confusing
        # reshape failure here); (2) non-causal ragged S — padded q
        # rows are sliced off, and under causal masking padded KEYS sit
        # strictly in every real row's future, but non-causal ragged
        # shapes would let padded keys contribute.
        return dense_attention(q, k, v, causal=causal)

    out = _flash_call(prep(q), prep(k), prep(v), causal, blk,
                      return_stats=False)
    return out.reshape(b, h, s_pad, d).transpose(0, 2, 1, 3)[:, :s]


@functools.partial(jax.jit, static_argnums=(3, 4))
def _flash_stats(q, k, v, causal: bool, blk: int):
    """Raw softmax partials for cross-block merging (the ring SP
    composition, ring_attention.ring_flash_attention) and for the
    backward's O(S) residuals: returns (acc [B,S,H,D] un-normalized
    f32, m [B,S,H,1] natural-log domain, l [B,S,H,1]). Requires
    S % blk == 0 (callers fall back to XLA paths otherwise)."""
    b, s, h, d = q.shape
    if s % blk or k.shape[1] != s:
        raise ValueError(f"_flash_stats needs S % {blk} == 0, got {s}")

    def prep(x):
        return x.transpose(0, 2, 1, 3).reshape(b * h, s, d)

    acc, m, l = _flash_call(prep(q), prep(k), prep(v), causal, blk,
                            return_stats=True)

    def un(x):
        return x.reshape(b, h, s, -1).transpose(0, 2, 1, 3)

    return un(acc), un(m), un(l)


def _flash_backward_flat(qf, kf, vf, dof, mf, lf, dlt, causal: bool,
                         blk: int, compute_dtype):
    """Pallas backward on pre-flattened [BH, S, ...] operands with a
    precomputed ``dlt`` (rowsum(do*o)); returns FLAT f32 (dq, dk, dv)
    so callers that accumulate across blocks (the ring VJP) never
    quantize partials to the input dtype."""
    bh, s, d = qf.shape
    scale = 1.0 / np.sqrt(d)
    qf = _prescale(qf)
    blk_q, blk_k = _pick_tiles(s, d)
    try:
        vma = jax.typeof(qf).vma
    except (AttributeError, TypeError):
        vma = None

    def sds():
        if vma:
            return jax.ShapeDtypeStruct((bh, s, d), jnp.float32, vma=vma)
        return jax.ShapeDtypeStruct((bh, s, d), jnp.float32)

    tq = lambda: pl.BlockSpec((1, blk_q, d), lambda b_h, a, b_: (b_h, a, 0))
    tq_b = lambda: pl.BlockSpec((1, blk_q, d), lambda b_h, a, b_: (b_h, b_, 0))
    tk = lambda: pl.BlockSpec((1, blk_k, d), lambda b_h, a, b_: (b_h, a, 0))
    tk_b = lambda: pl.BlockSpec((1, blk_k, d), lambda b_h, a, b_: (b_h, b_, 0))
    t1 = lambda: pl.BlockSpec((1, blk_q, 1), lambda b_h, a, b_: (b_h, a, 0))
    t1_b = lambda: pl.BlockSpec((1, blk_q, 1), lambda b_h, a, b_: (b_h, b_, 0))
    scr = lambda blk, w: pltpu.VMEM((blk, w), jnp.float32)
    if causal:
        # clamp dead-step fetches to the causal frontier (see
        # _flash_call; blk_q-vs-blk_k general): dq streams k tiles
        # j <= _causal_frontier(iq) past each q tile, dkv streams q
        # tiles i >= _causal_first_q(jk) past each k tile — the
        # Pallas pipeline elides the repeated-index refetch either way
        kfront = lambda a: _causal_frontier(a, blk_q, blk_k)
        qfirst = lambda a: _causal_first_q(a, blk_q, blk_k)
        tk_b = lambda: pl.BlockSpec(
            (1, blk_k, d),
            lambda b_h, a, b_: (b_h, jnp.minimum(b_, kfront(a)), 0))
        tq_b = lambda: pl.BlockSpec(
            (1, blk_q, d),
            lambda b_h, a, b_: (b_h, jnp.maximum(b_, qfirst(a)), 0))
        t1_b = lambda: pl.BlockSpec(
            (1, blk_q, 1),
            lambda b_h, a, b_: (b_h, jnp.maximum(b_, qfirst(a)), 0))

    dq = pl.pallas_call(
        _make_dq_kernel(blk_q, blk_k, causal, compute_dtype, scale),
        grid=(bh, s // blk_q, s // blk_k),
        # q/do/m/l/dlt indexed by the q-tile (2nd grid dim); k/v by
        # the inner jk dim
        in_specs=[tq(), tk_b(), tk_b(), tq(), t1(), t1(), t1()],
        out_specs=tq(),
        out_shape=sds(),
        scratch_shapes=[scr(blk_q, d)],
        compiler_params=_compiler_params(),
        interpret=_interpret(),
    )(qf, kf, vf, dof, mf, lf, dlt)

    dk, dv = pl.pallas_call(
        _make_dkv_kernel(blk_q, blk_k, causal, compute_dtype),
        grid=(bh, s // blk_k, s // blk_q),
        # k/v indexed by the k-tile (2nd grid dim); q/do/m/l/dlt by
        # the inner iq dim
        in_specs=[tq_b(), tk(), tk(), tq_b(), t1_b(), t1_b(), t1_b()],
        out_specs=[tk(), tk()],
        out_shape=[sds(), sds()],
        scratch_shapes=[scr(blk_k, d), scr(blk_k, d)],
        compiler_params=_compiler_params(),
        interpret=_interpret(),
    )(qf, kf, vf, dof, mf, lf, dlt)
    return dq, dk, dv


def _xla_stats(q, k, v, causal: bool):
    """XLA reference implementation of ``_flash_stats``' contract
    ([B, L, H, D] in; (acc, m, l) raw softmax partials out). Injected
    where the Pallas kernel cannot run — interpret mode inside
    shard_map on CPU meshes (the driver's ring-gradient dryrun) — so
    the ring machinery is exercised against identical block semantics."""
    scale = 1.0 / np.sqrt(q.shape[-1])
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        lq, lk = s.shape[-2], s.shape[-1]
        s = jnp.where(jnp.tril(jnp.ones((lq, lk), bool)), s, NEG_INF)
    m = jnp.max(s, axis=-1)
    p = jnp.exp(s - m[..., None])
    p = jnp.where(s <= NEG_INF / 2, 0.0, p)
    l = jnp.sum(p, axis=-1)
    acc = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    tr = lambda x: jnp.transpose(x, (0, 2, 1))[..., None]
    return acc, tr(m), tr(l)


def _xla_backward_flat(qf, kf, vf, dof, mf, lf, dlt, causal: bool,
                       blk: int, compute_dtype):
    """XLA reference implementation of ``_flash_backward_flat``'s
    contract (flat [BH, L, ...] operands, saved (m, l) stats, f32
    partials out) — the injectable sibling of ``_xla_stats`` for the
    backward ring."""
    scale = 1.0 / np.sqrt(qf.shape[-1])
    s = jnp.einsum("nqd,nkd->nqk", qf, kf).astype(jnp.float32) * scale
    if causal:
        lq, lk = s.shape[-2], s.shape[-1]
        s = jnp.where(jnp.tril(jnp.ones((lq, lk), bool)), s, NEG_INF)
    p = jnp.exp(s - mf) / jnp.maximum(lf, 1e-30)   # mf/lf: [N, L, 1]
    p = jnp.where(s <= NEG_INF / 2, 0.0, p)
    dp = jnp.einsum("nqd,nkd->nqk", dof, vf).astype(jnp.float32)
    ds = p * (dp - dlt)                            # dlt: [N, L, 1]
    dq = jnp.einsum("nqk,nkd->nqd", ds, kf.astype(jnp.float32)) * scale
    dk = jnp.einsum("nqk,nqd->nkd", ds, qf.astype(jnp.float32)) * scale
    dv = jnp.einsum("nqk,nqd->nkd", p, dof.astype(jnp.float32))
    return dq, dk, dv


@functools.partial(jax.jit, static_argnums=(7, 8))
def _flash_backward(q, k, v, o, m, l, do, causal: bool, blk: int):
    """O(S·blk) backward: (dq, dk, dv) from the forward residuals.
    Layouts as _flash_stats ([B, S, H, ...])."""
    b, s, h, d = q.shape

    def prep(x):
        return x.transpose(0, 2, 1, 3).reshape(b * h, s, x.shape[-1])

    qf, kf, vf, dof, mf, lf = map(prep, (q, k, v, do, m, l))
    # delta_i = rowsum(do * o): the only O(S) precomputation
    dlt = prep(jnp.sum(
        do.astype(jnp.float32) * o.astype(jnp.float32),
        axis=-1, keepdims=True,
    ))
    dq, dk, dv = _flash_backward_flat(
        qf, kf, vf, dof, mf, lf, dlt, causal, blk, q.dtype)

    def un(x, dt):
        return x.reshape(b, h, s, d).transpose(0, 2, 1, 3).astype(dt)

    return un(dq, q.dtype), un(dk, k.dtype), un(dv, v.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def flash_attention(q, k, v, causal: bool = False):
    """Tiled attention on the MXU; O(S·blk) memory forward AND backward
    (the backward kernels recompute tile probabilities from the saved
    softmax statistics)."""
    return _flash_forward(q, k, v, causal, _BLK)


def _fwd(q, k, v, causal):
    s = q.shape[1]
    if k.shape[1] != s or (s % _BLK and not causal):
        # cross-length or non-causal ragged: dense in both directions —
        # see the module docstring's ragged-shapes paragraph
        return flash_attention(q, k, v, causal), (q, k, v, None, None, None)
    if s % _BLK:
        # causal ragged: pad to the tile multiple and keep the PADDED
        # residuals, so the backward stays on the O(S·blk) kernels.
        # Exactness: the global-position causal mask zeroes every
        # padded-key column of every real row (k_pos > q_pos), and the
        # backward pads ``do`` with zeros so padded q rows contribute
        # nothing to dk/dv (ds = p·(dp - dlt) with dp = dlt = 0).
        s_pad = -(-s // _BLK) * _BLK
        q, k, v = (jnp.pad(x, ((0, 0), (0, s_pad - s), (0, 0), (0, 0)))
                   for x in (q, k, v))
    acc, m, l = _flash_stats(q, k, v, causal, _BLK)
    o = (acc / jnp.maximum(l, 1e-30)).astype(q.dtype)
    return o[:, :s], (q, k, v, o, m, l)


def _bwd(causal, res, g):
    q, k, v, o, m, l = res
    if o is None:
        # dense recompute in XLA (cross-length / non-causal ragged only)
        _, vjp = jax.vjp(
            lambda q_, k_, v_: dense_attention(q_, k_, v_, causal), q, k, v)
        return vjp(g)
    s, s_pad = g.shape[1], q.shape[1]
    if s_pad != s:
        g = jnp.pad(g, ((0, 0), (0, s_pad - s), (0, 0), (0, 0)))
    dq, dk, dv = _flash_backward(q, k, v, o, m, l, g, causal, _BLK)
    return dq[:, :s], dk[:, :s], dv[:, :s]


flash_attention.defvjp(_fwd, _bwd)
