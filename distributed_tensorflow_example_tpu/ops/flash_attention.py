"""Blockwise (flash-style) attention as Pallas TPU kernels.

The intra-chip complement to ops/ring_attention.py: the ring splits the
sequence ACROSS chips (ppermute neighbor exchange); this kernel makes
the per-chip block computation memory-lean by never materializing the
``[S, S]`` score matrix. The grid is ``(batch*heads, q_tiles, k_tiles)``
with the k-tile dimension innermost and sequential: each program
combines one (q tile, k tile) pair into VMEM scratch accumulators
(running max ``m``, normalizer ``l``, un-normalized output ``acc``)
via the same online-softmax recurrence the ring uses, initializing at
``j == 0`` and writing the normalized output at ``j == nk-1``. Peak
memory is O(blk·D) per program — sequence length is bounded by HBM
only (tested to S=16384 where dense scores would need 17 GB).

Beyond-reference capability (the reference has no attention at all,
/root/reference/example.py:84-90; SURVEY.md §5).

Causal masking is by global position. Fully-masked (future) k tiles
are skipped outright with ``pl.when`` (their online update would be an
arithmetic no-op — ``m_blk = NEG_INF`` leaves every accumulator
unchanged — so skipping is purely a ~2x MXU saving, not a correctness
requirement); the backward kernels skip their off-frontier tiles the
same way.

Training: ``flash_attention`` carries a ``jax.custom_vjp`` whose
backward is ALSO tiled Pallas (``_make_dq_kernel`` /
``_make_dkv_kernel``): the forward saves only (o, m, l) — O(S)
residuals — and each backward tile recomputes its probabilities from
the saved softmax statistics (``_bwd_tile``, shared by both kernels),
applies the softmax VJP ``ds = p * (dp - rowsum(do*o))``, and
accumulates dq (streaming k tiles past each q tile) and dk/dv
(streaming q tiles past each k tile) in VMEM scratch. Forward AND
backward are O(S·blk) — long-context training memory is bounded by
HBM, not by an [S, S] score tensor.

Ragged shapes (S not a multiple of the 256 tile) by direction:
non-causal ragged runs exact dense XLA in BOTH directions (padded keys
would corrupt real rows); causal ragged keeps the O(S·blk) kernels in
BOTH directions — the VJP pads q/k/v/do to the tile multiple, where
the global-position causal mask zeroes padded keys for every real row
and zero-padded ``do`` rows contribute nothing to dk/dv, then slices
the gradients back. Cross-length q/k (``k.shape[1] != q.shape[1]``)
always delegates to the dense path, which supports it non-causally and
rejects it causally.

On non-TPU backends the kernels run in Pallas interpret mode, so the
CPU test suite exercises the same code paths bit-for-bit.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .ring_attention import NEG_INF, attention as dense_attention

_BLK = 256  # q and k tile length (sequence is padded to a multiple)


def _interpret() -> bool:
    return jax.default_backend() == "cpu"


def _make_kernel(blk: int, causal: bool, compute_dtype,
                 return_stats: bool = False):
    def kernel(q_ref, k_ref, v_ref, o_ref, *rest):
        if return_stats:
            m_out, l_out, m_scr, l_scr, acc_scr = rest
        else:
            m_scr, l_scr, acc_scr = rest
        iq = pl.program_id(1)
        j = pl.program_id(2)
        nk = pl.num_programs(2)

        @pl.when(j == 0)
        def _init():
            m_scr[...] = jnp.full_like(m_scr[...], NEG_INF)
            l_scr[...] = jnp.zeros_like(l_scr[...])
            acc_scr[...] = jnp.zeros_like(acc_scr[...])

        # under causal masking, k tiles past the q tile's frontier are
        # arithmetic no-ops — skip their matmuls outright (`causal` is
        # Python-static: non-causal kernels get no conditional at all)
        def _compute():
            q = q_ref[0].astype(compute_dtype)     # [blk, d]
            k = k_ref[0].astype(compute_dtype)
            v = v_ref[0].astype(compute_dtype)
            s = _tile_scores(q, k, iq, j, blk, causal)
            m = m_scr[...]
            m_blk = jnp.max(s, axis=-1, keepdims=True)
            m_new = jnp.maximum(m, m_blk)
            p = jnp.exp(s - m_new)
            # fully-masked rows: exp(NEG_INF - NEG_INF) would be 1
            p = jnp.where(s <= NEG_INF / 2, 0.0, p)
            alpha = jnp.exp(m - m_new)
            m_scr[...] = m_new
            l_scr[...] = l_scr[...] * alpha + jnp.sum(
                p, axis=-1, keepdims=True)
            acc_scr[...] = acc_scr[...] * alpha + jax.lax.dot_general(
                p.astype(compute_dtype), v, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )

        if causal:
            pl.when(j <= iq)(_compute)
        else:
            _compute()

        @pl.when(j == nk - 1)
        def _finalize():
            if return_stats:
                # raw partials for cross-block merging (ring SP): the
                # un-normalized accumulator plus its (m, l) statistics
                o_ref[0] = acc_scr[...].astype(o_ref.dtype)
                m_out[0] = m_scr[...]
                l_out[0] = l_scr[...]
            else:
                o_ref[0] = (
                    acc_scr[...] / jnp.maximum(l_scr[...], 1e-30)
                ).astype(o_ref.dtype)

    return kernel


def _tile_scores(q, k, q_tile, k_tile, blk: int, causal: bool):
    """Scaled q·kᵀ for one tile pair with the global-position causal
    mask — shared by the forward and both backward kernels."""
    scale = 1.0 / np.sqrt(q.shape[-1])
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale
    if causal:
        q_pos = q_tile * blk + jax.lax.broadcasted_iota(
            jnp.int32, (blk, blk), 0)
        k_pos = k_tile * blk + jax.lax.broadcasted_iota(
            jnp.int32, (blk, blk), 1)
        s = jnp.where(k_pos <= q_pos, s, NEG_INF)
    return s


def _bwd_tile(q, k, v, do, m, l, dlt, q_tile, k_tile, blk: int,
              causal: bool):
    """Shared backward tile math: recompute this tile's normalized
    probabilities from the saved (m, l) stats and apply the softmax VJP.
    Returns (p, ds, scale)."""
    s = _tile_scores(q, k, q_tile, k_tile, blk, causal)
    p = jnp.exp(s - m) / jnp.maximum(l, 1e-30)
    p = jnp.where(s <= NEG_INF / 2, 0.0, p)
    dp = jax.lax.dot_general(                     # do @ v^T
        do, v, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    ds = p * (dp - dlt)
    return p, ds, 1.0 / np.sqrt(q.shape[-1])


def _make_dq_kernel(blk: int, causal: bool, compute_dtype):
    """dq accumulation: grid (bh, iq, jk), jk innermost sequential."""

    def kernel(q_ref, k_ref, v_ref, do_ref, m_ref, l_ref, dlt_ref,
               dq_ref, dq_scr):
        iq = pl.program_id(1)
        j = pl.program_id(2)
        nk = pl.num_programs(2)

        @pl.when(j == 0)
        def _init():
            dq_scr[...] = jnp.zeros_like(dq_scr[...])

        def _compute():
            k = k_ref[0].astype(compute_dtype)
            _, ds, scale = _bwd_tile(
                q_ref[0].astype(compute_dtype), k,
                v_ref[0].astype(compute_dtype),
                do_ref[0].astype(compute_dtype),
                m_ref[0], l_ref[0], dlt_ref[0], iq, j, blk, causal,
            )
            dq_scr[...] += jax.lax.dot_general(   # ds @ k
                ds.astype(compute_dtype), k, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * scale

        if causal:  # skip k tiles past the causal frontier
            pl.when(j <= iq)(_compute)
        else:
            _compute()

        @pl.when(j == nk - 1)
        def _finalize():
            dq_ref[0] = dq_scr[...].astype(dq_ref.dtype)

    return kernel


def _make_dkv_kernel(blk: int, causal: bool, compute_dtype):
    """dk/dv accumulation: grid (bh, jk, iq), iq innermost sequential
    (each program owns one k tile and streams q tiles through it)."""

    def kernel(q_ref, k_ref, v_ref, do_ref, m_ref, l_ref, dlt_ref,
               dk_ref, dv_ref, dk_scr, dv_scr):
        j = pl.program_id(1)
        i = pl.program_id(2)
        nq = pl.num_programs(2)

        @pl.when(i == 0)
        def _init():
            dk_scr[...] = jnp.zeros_like(dk_scr[...])
            dv_scr[...] = jnp.zeros_like(dv_scr[...])

        def _compute():
            q = q_ref[0].astype(compute_dtype)
            do = do_ref[0].astype(compute_dtype)
            p, ds, scale = _bwd_tile(
                q, k_ref[0].astype(compute_dtype),
                v_ref[0].astype(compute_dtype), do,
                m_ref[0], l_ref[0], dlt_ref[0], i, j, blk, causal,
            )
            dv_scr[...] += jax.lax.dot_general(   # p^T @ do
                p.astype(compute_dtype), do, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            dk_scr[...] += jax.lax.dot_general(   # ds^T @ q
                ds.astype(compute_dtype), q, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * scale

        if causal:  # q tiles before this k tile see none of its keys
            pl.when(i >= j)(_compute)
        else:
            _compute()

        @pl.when(i == nq - 1)
        def _finalize():
            dk_ref[0] = dk_scr[...].astype(dk_ref.dtype)
            dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)

    return kernel


def _flash_call(qf, kf, vf, causal: bool, blk: int, return_stats: bool):
    """Shared forward launcher on pre-flattened [BH, S, D] arrays with
    S % blk == 0. return_stats=False -> normalized output [BH, S, D];
    True -> (acc f32, m, l) raw partials."""
    bh, s, d = qf.shape
    try:
        vma = jax.typeof(qf).vma
    except (AttributeError, TypeError):
        vma = None

    def sds(shape, dt):
        if vma:
            return jax.ShapeDtypeStruct(shape, dt, vma=vma)
        return jax.ShapeDtypeStruct(shape, dt)

    nt = s // blk
    tile_d = pl.BlockSpec((1, blk, d), lambda b, i, j: (b, i, 0))
    kv_spec = pl.BlockSpec((1, blk, d), lambda b, i, j: (b, j, 0))
    tile_1 = pl.BlockSpec((1, blk, 1), lambda b, i, j: (b, i, 0))
    if return_stats:
        out_specs = [tile_d, tile_1, tile_1]
        out_shape = [sds((bh, s, d), jnp.float32),
                     sds((bh, s, 1), jnp.float32),
                     sds((bh, s, 1), jnp.float32)]
    else:
        out_specs = tile_d
        out_shape = sds((bh, s, d), qf.dtype)
    return pl.pallas_call(
        _make_kernel(blk, causal, qf.dtype, return_stats),
        grid=(bh, nt, nt),
        in_specs=[tile_d, kv_spec, kv_spec],
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[
            pltpu.VMEM((blk, 1), jnp.float32),   # running max m
            pltpu.VMEM((blk, 1), jnp.float32),   # normalizer l
            pltpu.VMEM((blk, d), jnp.float32),   # un-normalized output
        ],
        interpret=_interpret(),
    )(qf, kf, vf)


@functools.partial(jax.jit, static_argnums=(3, 4))
def _flash_forward(q, k, v, causal: bool, blk: int):
    """[B, S, H, D] -> [B, S, H, D] via the tiled kernel."""
    b, s, h, d = q.shape
    s_pad = max(blk, ((s + blk - 1) // blk) * blk)

    def prep(x):
        x = jnp.pad(x, ((0, 0), (0, s_pad - s), (0, 0), (0, 0)))
        return x.transpose(0, 2, 1, 3).reshape(b * h, s_pad, d)

    if k.shape[1] != s or (s_pad != s and not causal):
        # Two dense-fallback cases: (1) cross-length q/k — the kernel's
        # tiling assumes square [S, S] score geometry, and
        # dense_attention handles unequal lengths (causal cross-length
        # is rejected there with a clear error rather than a confusing
        # reshape failure here); (2) non-causal ragged S — padded q
        # rows are sliced off, and under causal masking padded KEYS sit
        # strictly in every real row's future, but non-causal ragged
        # shapes would let padded keys contribute.
        return dense_attention(q, k, v, causal=causal)

    out = _flash_call(prep(q), prep(k), prep(v), causal, blk,
                      return_stats=False)
    return out.reshape(b, h, s_pad, d).transpose(0, 2, 1, 3)[:, :s]


@functools.partial(jax.jit, static_argnums=(3, 4))
def _flash_stats(q, k, v, causal: bool, blk: int):
    """Raw softmax partials for cross-block merging (the ring SP
    composition, ring_attention.ring_flash_attention) and for the
    backward's O(S) residuals: returns (acc [B,S,H,D] un-normalized
    f32, m [B,S,H,1], l [B,S,H,1]). Requires S % blk == 0 (callers
    fall back to XLA paths otherwise)."""
    b, s, h, d = q.shape
    if s % blk or k.shape[1] != s:
        raise ValueError(f"_flash_stats needs S % {blk} == 0, got {s}")

    def prep(x):
        return x.transpose(0, 2, 1, 3).reshape(b * h, s, d)

    acc, m, l = _flash_call(prep(q), prep(k), prep(v), causal, blk,
                            return_stats=True)

    def un(x):
        return x.reshape(b, h, s, -1).transpose(0, 2, 1, 3)

    return un(acc), un(m), un(l)


def _flash_backward_flat(qf, kf, vf, dof, mf, lf, dlt, causal: bool,
                         blk: int, compute_dtype):
    """Pallas backward on pre-flattened [BH, S, ...] operands with a
    precomputed ``dlt`` (rowsum(do*o)); returns FLAT f32 (dq, dk, dv)
    so callers that accumulate across blocks (the ring VJP) never
    quantize partials to the input dtype."""
    bh, s, d = qf.shape
    try:
        vma = jax.typeof(qf).vma
    except (AttributeError, TypeError):
        vma = None

    def sds():
        if vma:
            return jax.ShapeDtypeStruct((bh, s, d), jnp.float32, vma=vma)
        return jax.ShapeDtypeStruct((bh, s, d), jnp.float32)

    nt = s // blk
    tile_d = lambda: pl.BlockSpec((1, blk, d), lambda b_h, a, b_: (b_h, a, 0))
    tile_d_b = lambda: pl.BlockSpec((1, blk, d), lambda b_h, a, b_: (b_h, b_, 0))
    tile_1 = lambda: pl.BlockSpec((1, blk, 1), lambda b_h, a, b_: (b_h, a, 0))
    tile_1_b = lambda: pl.BlockSpec((1, blk, 1), lambda b_h, a, b_: (b_h, b_, 0))
    scr = lambda w: pltpu.VMEM((blk, w), jnp.float32)

    dq = pl.pallas_call(
        _make_dq_kernel(blk, causal, compute_dtype),
        grid=(bh, nt, nt),
        # q/do/m/l/dlt indexed by the q-tile (2nd grid dim); k/v by
        # the inner jk dim
        in_specs=[tile_d(), tile_d_b(), tile_d_b(), tile_d(),
                  tile_1(), tile_1(), tile_1()],
        out_specs=tile_d(),
        out_shape=sds(),
        scratch_shapes=[scr(d)],
        interpret=_interpret(),
    )(qf, kf, vf, dof, mf, lf, dlt)

    dk, dv = pl.pallas_call(
        _make_dkv_kernel(blk, causal, compute_dtype),
        grid=(bh, nt, nt),
        # k/v indexed by the k-tile (2nd grid dim); q/do/m/l/dlt by
        # the inner iq dim
        in_specs=[tile_d_b(), tile_d(), tile_d(), tile_d_b(),
                  tile_1_b(), tile_1_b(), tile_1_b()],
        out_specs=[tile_d(), tile_d()],
        out_shape=[sds(), sds()],
        scratch_shapes=[scr(d), scr(d)],
        interpret=_interpret(),
    )(qf, kf, vf, dof, mf, lf, dlt)
    return dq, dk, dv


def _xla_stats(q, k, v, causal: bool):
    """XLA reference implementation of ``_flash_stats``' contract
    ([B, L, H, D] in; (acc, m, l) raw softmax partials out). Injected
    where the Pallas kernel cannot run — interpret mode inside
    shard_map on CPU meshes (the driver's ring-gradient dryrun) — so
    the ring machinery is exercised against identical block semantics."""
    scale = 1.0 / np.sqrt(q.shape[-1])
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        lq, lk = s.shape[-2], s.shape[-1]
        s = jnp.where(jnp.tril(jnp.ones((lq, lk), bool)), s, NEG_INF)
    m = jnp.max(s, axis=-1)
    p = jnp.exp(s - m[..., None])
    p = jnp.where(s <= NEG_INF / 2, 0.0, p)
    l = jnp.sum(p, axis=-1)
    acc = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    tr = lambda x: jnp.transpose(x, (0, 2, 1))[..., None]
    return acc, tr(m), tr(l)


def _xla_backward_flat(qf, kf, vf, dof, mf, lf, dlt, causal: bool,
                       blk: int, compute_dtype):
    """XLA reference implementation of ``_flash_backward_flat``'s
    contract (flat [BH, L, ...] operands, saved (m, l) stats, f32
    partials out) — the injectable sibling of ``_xla_stats`` for the
    backward ring."""
    scale = 1.0 / np.sqrt(qf.shape[-1])
    s = jnp.einsum("nqd,nkd->nqk", qf, kf).astype(jnp.float32) * scale
    if causal:
        lq, lk = s.shape[-2], s.shape[-1]
        s = jnp.where(jnp.tril(jnp.ones((lq, lk), bool)), s, NEG_INF)
    p = jnp.exp(s - mf) / jnp.maximum(lf, 1e-30)   # mf/lf: [N, L, 1]
    p = jnp.where(s <= NEG_INF / 2, 0.0, p)
    dp = jnp.einsum("nqd,nkd->nqk", dof, vf).astype(jnp.float32)
    ds = p * (dp - dlt)                            # dlt: [N, L, 1]
    dq = jnp.einsum("nqk,nkd->nqd", ds, kf.astype(jnp.float32)) * scale
    dk = jnp.einsum("nqk,nqd->nkd", ds, qf.astype(jnp.float32)) * scale
    dv = jnp.einsum("nqk,nqd->nkd", p, dof.astype(jnp.float32))
    return dq, dk, dv


@functools.partial(jax.jit, static_argnums=(7, 8))
def _flash_backward(q, k, v, o, m, l, do, causal: bool, blk: int):
    """O(S·blk) backward: (dq, dk, dv) from the forward residuals.
    Layouts as _flash_stats ([B, S, H, ...])."""
    b, s, h, d = q.shape

    def prep(x):
        return x.transpose(0, 2, 1, 3).reshape(b * h, s, x.shape[-1])

    qf, kf, vf, dof, mf, lf = map(prep, (q, k, v, do, m, l))
    # delta_i = rowsum(do * o): the only O(S) precomputation
    dlt = prep(jnp.sum(
        do.astype(jnp.float32) * o.astype(jnp.float32),
        axis=-1, keepdims=True,
    ))
    dq, dk, dv = _flash_backward_flat(
        qf, kf, vf, dof, mf, lf, dlt, causal, blk, q.dtype)

    def un(x, dt):
        return x.reshape(b, h, s, d).transpose(0, 2, 1, 3).astype(dt)

    return un(dq, q.dtype), un(dk, k.dtype), un(dv, v.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def flash_attention(q, k, v, causal: bool = False):
    """Tiled attention on the MXU; O(S·blk) memory forward AND backward
    (the backward kernels recompute tile probabilities from the saved
    softmax statistics)."""
    return _flash_forward(q, k, v, causal, _BLK)


def _fwd(q, k, v, causal):
    s = q.shape[1]
    if k.shape[1] != s or (s % _BLK and not causal):
        # cross-length or non-causal ragged: dense in both directions —
        # see the module docstring's ragged-shapes paragraph
        return flash_attention(q, k, v, causal), (q, k, v, None, None, None)
    if s % _BLK:
        # causal ragged: pad to the tile multiple and keep the PADDED
        # residuals, so the backward stays on the O(S·blk) kernels.
        # Exactness: the global-position causal mask zeroes every
        # padded-key column of every real row (k_pos > q_pos), and the
        # backward pads ``do`` with zeros so padded q rows contribute
        # nothing to dk/dv (ds = p·(dp - dlt) with dp = dlt = 0).
        s_pad = -(-s // _BLK) * _BLK
        q, k, v = (jnp.pad(x, ((0, 0), (0, s_pad - s), (0, 0), (0, 0)))
                   for x in (q, k, v))
    acc, m, l = _flash_stats(q, k, v, causal, _BLK)
    o = (acc / jnp.maximum(l, 1e-30)).astype(q.dtype)
    return o[:, :s], (q, k, v, o, m, l)


def _bwd(causal, res, g):
    q, k, v, o, m, l = res
    if o is None:
        # dense recompute in XLA (cross-length / non-causal ragged only)
        _, vjp = jax.vjp(
            lambda q_, k_, v_: dense_attention(q_, k_, v_, causal), q, k, v)
        return vjp(g)
    s, s_pad = g.shape[1], q.shape[1]
    if s_pad != s:
        g = jnp.pad(g, ((0, 0), (0, s_pad - s), (0, 0), (0, 0)))
    dq, dk, dv = _flash_backward(q, k, v, o, m, l, g, causal, _BLK)
    return dq[:, :s], dk[:, :s], dv[:, :s]


flash_attention.defvjp(_fwd, _bwd)
