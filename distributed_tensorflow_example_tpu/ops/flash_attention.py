"""Blockwise (flash-style) attention as a Pallas TPU kernel.

The intra-chip complement to ops/ring_attention.py: the ring splits the
sequence ACROSS chips (ppermute neighbor exchange); this kernel makes
the per-chip block computation memory-lean by never materializing the
``[S, S]`` score matrix. The grid is ``(batch*heads, q_tiles, k_tiles)``
with the k-tile dimension innermost and sequential: each program
combines one (q tile, k tile) pair into VMEM scratch accumulators
(running max ``m``, normalizer ``l``, un-normalized output ``acc``)
via the same online-softmax recurrence the ring uses, initializing at
``j == 0`` and writing the normalized output at ``j == nk-1``. Peak
memory is O(blk·D) per program — sequence length is bounded by HBM
only (tested to S=16384 where dense scores would need 17 GB).

Beyond-reference capability (the reference has no attention at all,
/root/reference/example.py:84-90; SURVEY.md §5).

Causal masking is by global position. Fully-masked (future) k tiles
reduce to arithmetic no-ops (``m_blk = NEG_INF`` leaves every
accumulator unchanged), so correctness needs no per-tile control flow;
the wasted half of the causal grid is accepted for simplicity.

Training: ``flash_attention`` carries a ``jax.custom_vjp`` whose
backward recomputes the dense probabilities in plain XLA from the
saved (q, k, v) — the same kernel-forward/XLA-backward split as
ops/pallas_fused.py. The O(S·blk) memory win therefore applies to the
forward/inference path; a backward in O(S) would need its own kernel
and is out of scope here (documented, not hidden).

On non-TPU backends the kernel runs in Pallas interpret mode, so the
CPU test suite exercises the same code path bit-for-bit.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .ring_attention import NEG_INF, attention as dense_attention

_BLK = 256  # q and k tile length (sequence is padded to a multiple)


def _interpret() -> bool:
    return jax.default_backend() == "cpu"


def _make_kernel(blk: int, causal: bool, compute_dtype,
                 return_stats: bool = False):
    def kernel(q_ref, k_ref, v_ref, o_ref, *rest):
        if return_stats:
            m_out, l_out, m_scr, l_scr, acc_scr = rest
        else:
            m_scr, l_scr, acc_scr = rest
        iq = pl.program_id(1)
        j = pl.program_id(2)
        nk = pl.num_programs(2)

        @pl.when(j == 0)
        def _init():
            m_scr[...] = jnp.full_like(m_scr[...], NEG_INF)
            l_scr[...] = jnp.zeros_like(l_scr[...])
            acc_scr[...] = jnp.zeros_like(acc_scr[...])

        q = q_ref[0].astype(compute_dtype)         # [blk, d]
        k = k_ref[0].astype(compute_dtype)
        v = v_ref[0].astype(compute_dtype)
        scale = 1.0 / np.sqrt(q.shape[-1])
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale                                   # [blk, blk]
        if causal:
            q_pos = iq * blk + jax.lax.broadcasted_iota(
                jnp.int32, (blk, blk), 0)
            k_pos = j * blk + jax.lax.broadcasted_iota(
                jnp.int32, (blk, blk), 1)
            s = jnp.where(k_pos <= q_pos, s, NEG_INF)
        m = m_scr[...]
        m_blk = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m, m_blk)
        p = jnp.exp(s - m_new)
        # fully-masked rows: exp(NEG_INF - NEG_INF) would be 1
        p = jnp.where(s <= NEG_INF / 2, 0.0, p)
        alpha = jnp.exp(m - m_new)
        m_scr[...] = m_new
        l_scr[...] = l_scr[...] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[...] = acc_scr[...] * alpha + jax.lax.dot_general(
            p.astype(compute_dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

        @pl.when(j == nk - 1)
        def _finalize():
            if return_stats:
                # raw partials for cross-block merging (ring SP): the
                # un-normalized accumulator plus its (m, l) statistics
                o_ref[0] = acc_scr[...].astype(o_ref.dtype)
                m_out[0] = m_scr[...]
                l_out[0] = l_scr[...]
            else:
                o_ref[0] = (
                    acc_scr[...] / jnp.maximum(l_scr[...], 1e-30)
                ).astype(o_ref.dtype)

    return kernel


@functools.partial(jax.jit, static_argnums=(3, 4))
def _flash_forward(q, k, v, causal: bool, blk: int):
    """[B, S, H, D] -> [B, S, H, D] via the tiled kernel."""
    b, s, h, d = q.shape
    s_pad = max(blk, ((s + blk - 1) // blk) * blk)

    def prep(x):
        x = jnp.pad(x, ((0, 0), (0, s_pad - s), (0, 0), (0, 0)))
        return x.transpose(0, 2, 1, 3).reshape(b * h, s_pad, d)

    if s_pad != s and not causal:
        # padded q rows are sliced off, and under causal masking padded
        # KEYS sit strictly in every real row's future — but non-causal
        # ragged shapes would let padded keys contribute, so they take
        # the exact dense path instead
        return dense_attention(q, k, v, causal=False)

    qf, kf, vf = prep(q), prep(k), prep(v)
    nq = s_pad // blk
    grid = (b * h, nq, nq)
    out = pl.pallas_call(
        _make_kernel(blk, causal, q.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, blk, d), lambda bh, i, j: (bh, i, 0)),
            pl.BlockSpec((1, blk, d), lambda bh, i, j: (bh, j, 0)),
            pl.BlockSpec((1, blk, d), lambda bh, i, j: (bh, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, blk, d), lambda bh, i, j: (bh, i, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, s_pad, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((blk, 1), jnp.float32),   # running max m
            pltpu.VMEM((blk, 1), jnp.float32),   # normalizer l
            pltpu.VMEM((blk, d), jnp.float32),   # un-normalized output
        ],
        interpret=_interpret(),
    )(qf, kf, vf)
    return out.reshape(b, h, s_pad, d).transpose(0, 2, 1, 3)[:, :s]


@functools.partial(jax.jit, static_argnums=(3, 4))
def _flash_stats(q, k, v, causal: bool, blk: int):
    """Raw softmax partials for cross-block merging (the ring SP
    composition, ring_attention.ring_flash_attention): returns
    (acc [B,S,H,D] un-normalized f32, m [B,S,H,1], l [B,S,H,1]).
    Requires S % blk == 0 (callers fall back to XLA blocks otherwise).
    """
    b, s, h, d = q.shape
    if s % blk or k.shape[1] != s:
        raise ValueError(f"_flash_stats needs S % {blk} == 0, got {s}")

    def prep(x):
        return x.transpose(0, 2, 1, 3).reshape(b * h, s, d)

    qf, kf, vf = prep(q), prep(k), prep(v)
    try:
        vma = jax.typeof(qf).vma
    except (AttributeError, TypeError):
        vma = None
    _sds = (
        (lambda shape: jax.ShapeDtypeStruct(shape, jnp.float32, vma=vma))
        if vma else (lambda shape: jax.ShapeDtypeStruct(shape, jnp.float32))
    )
    nq = s // blk
    grid = (b * h, nq, nq)
    acc, m, l = pl.pallas_call(
        _make_kernel(blk, causal, q.dtype, return_stats=True),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, blk, d), lambda bh, i, j: (bh, i, 0)),
            pl.BlockSpec((1, blk, d), lambda bh, i, j: (bh, j, 0)),
            pl.BlockSpec((1, blk, d), lambda bh, i, j: (bh, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, blk, d), lambda bh, i, j: (bh, i, 0)),
            pl.BlockSpec((1, blk, 1), lambda bh, i, j: (bh, i, 0)),
            pl.BlockSpec((1, blk, 1), lambda bh, i, j: (bh, i, 0)),
        ],
        out_shape=[
            _sds((b * h, s, d)), _sds((b * h, s, 1)), _sds((b * h, s, 1)),
        ],
        scratch_shapes=[
            pltpu.VMEM((blk, 1), jnp.float32),
            pltpu.VMEM((blk, 1), jnp.float32),
            pltpu.VMEM((blk, d), jnp.float32),
        ],
        interpret=_interpret(),
    )(qf, kf, vf)

    def un(x):
        return x.reshape(b, h, s, -1).transpose(0, 2, 1, 3)

    return un(acc), un(m), un(l)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def flash_attention(q, k, v, causal: bool = False):
    """Tiled attention forward on the MXU; O(S·blk) forward memory."""
    return _flash_forward(q, k, v, causal, _BLK)


def _fwd(q, k, v, causal):
    return flash_attention(q, k, v, causal), (q, k, v)


def _bwd(causal, res, g):
    # dense recompute in XLA (documented O(S^2) backward)
    q, k, v = res
    _, vjp = jax.vjp(lambda q_, k_, v_: dense_attention(q_, k_, v_, causal),
                     q, k, v)
    return vjp(g)


flash_attention.defvjp(_fwd, _bwd)
