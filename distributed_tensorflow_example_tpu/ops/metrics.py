"""Metrics.

Reference parity: ``mean(cast(equal(argmax(y,1), argmax(y_,1)), float))``
(/root/reference/example.py:118-121). Computed from logits — argmax is
softmax-invariant, so this matches the reference's accuracy over
softmax outputs exactly.
"""

from __future__ import annotations

import jax.numpy as jnp


def accuracy(logits: jnp.ndarray, labels_onehot: jnp.ndarray) -> jnp.ndarray:
    correct = jnp.argmax(logits, axis=-1) == jnp.argmax(labels_onehot, axis=-1)
    return jnp.mean(correct.astype(jnp.float32))
