"""Low-precision representation with per-tile scales — the ONE
quantization core the stack shares (ISSUE 11).

Three memory-bandwidth walls, one technique: the decode roofline
streams the KV cache through HBM every step (int8 pages halve
``decode_kv_bytes_per_step``), the dense/MoE FFN matmuls stream
weights and activations (fp8 operands halve them again past the bf16
MXU rows), and the multi-site outer sync moves a full f32
pseudo-gradient across the slow DCN axis per round (int8 +
error-feedback compression is another ~4x on the gated
``local_sgd_comm_bytes_per_token``).  Each consumer quantizes with
THIS module's helpers so the formats, the scale conventions and the
numerics are defined exactly once and oracle-tested once
(tests/test_quant.py pins every function against a numpy reference).

Conventions:

- **int8 is symmetric per-axis**: ``scale = amax / 127`` over the
  reduced axes (kept as size-1 dims so it broadcasts back),
  ``q = clip(round(x / scale), -127, 127)``.  No zero-point — the
  KV rows and pseudo-gradients this repo quantizes are zero-centered,
  and symmetric scales make dequantize a single multiply.
- **fp8 is e4m3 with power-of-two scales**: a pow2 scale only shifts
  the exponent, so the scaled-back values sit EXACTLY on an fp8 grid
  that bf16/f32 represent losslessly (3-bit mantissa <= bf16's 8) —
  the fused Pallas kernels (ops/pallas_fused.py) consume the rounded
  operands unchanged and compute bit-what-an-fp8-MXU-matmul-computes:
  ``(q_x * s_x) @ (q_w * s_w) == s_x * s_w * (q_x @ q_w)`` with f32
  accumulation.
- **delayed scaling** keeps a rolling amax history per tensor and
  derives the scale from the history max (the Transformer-Engine
  recipe); a length-1 history degenerates to just-in-time (current)
  scaling, which is what the ``--fp8_ffn`` model switch uses (the
  history-threading API is here for callers that carry aux state).
- **error feedback** makes the compressed outer sync unbiased over
  time: the residual ``(delta + ef) - dequantized`` is carried to the
  next round, so quantization error never accumulates
  (parallel/local_sgd.py stores it per-site in the opt-state).

Everything here is plain jnp (elementwise + reductions): it runs on
every backend, inside shard_map, and under the Pallas interpret-mode
fallbacks unchanged.
"""

from __future__ import annotations

import jax.numpy as jnp

# symmetric int8: q in [-127, 127] (no -128 — symmetric range keeps
# dequantize a single multiply and the format sign-stable)
INT8_MAX = 127.0

# largest finite float8_e4m3fn magnitude (the OCP e4m3 format jax
# ships; casts SATURATE to nan above it, hence the explicit scaling)
FP8_E4M3_MAX = 448.0


def _amax(x, axis):
    """max |x| over ``axis`` (None = all), keepdims so the result
    broadcasts back over ``x``."""
    return jnp.max(jnp.abs(x.astype(jnp.float32)), axis=axis,
                   keepdims=True)


def int8_scale(amax):
    """Symmetric int8 scale for a tensor (tile) whose largest
    magnitude is ``amax``: ``amax / 127``, floored to 1.0 where the
    tile is all-zero (q is then exactly 0 regardless of scale)."""
    return jnp.where(amax > 0.0, amax / INT8_MAX, 1.0)


def quantize_int8(x, axis=None):
    """Symmetric per-axis int8 quantization: returns ``(q int8,
    scale f32)``; ``axis`` = the axis/axes the scale REDUCES over
    (None = one per-tensor scale), kept as size-1 dims so
    ``q * scale`` broadcasts.  Round-to-nearest-even (jnp.round ==
    np.round), clipped to the symmetric [-127, 127] range."""
    scale = int8_scale(_amax(x, axis))
    q = jnp.clip(jnp.round(x.astype(jnp.float32) / scale),
                 -INT8_MAX, INT8_MAX).astype(jnp.int8)
    return q, scale


def dequantize_int8(q, scale, dtype=jnp.float32):
    """``q * scale`` in f32, cast to ``dtype`` — scale must broadcast
    (quantize_int8 keeps its reduced dims)."""
    return (q.astype(jnp.float32) * scale).astype(dtype)


def int8_roundtrip(x, axis=None):
    """``dequantize(quantize(x))`` — the values an int8 wire carries,
    in f32.  Worst-case per-element error is scale/2 = amax/254 (the
    bound tests/test_quant.py pins)."""
    q, scale = quantize_int8(x, axis)
    return dequantize_int8(q, scale)


def ef_compress_int8(x, ef, axis=None):
    """One error-feedback compression step: add the carried residual,
    quantize the sum, return ``(dequantized, new_residual)``.  The
    residual makes the compressor unbiased over time — the sum of
    transmitted values tracks the sum of inputs to within one
    quantization step, however many rounds run (EF-SGD; the numpy
    oracle in tests/test_quant.py pins the telescoping identity)."""
    c = x.astype(jnp.float32) + ef.astype(jnp.float32)
    dq = int8_roundtrip(c, axis)
    return dq, c - dq


# ---------------------------------------------------------------------------
# fp8 (e4m3) with power-of-two scales + delayed scaling
# ---------------------------------------------------------------------------


def pow2_scale(amax, fmt_max=FP8_E4M3_MAX):
    """The smallest power-of-two ``s`` with ``amax / s <= fmt_max``
    (1.0 for an all-zero tile).  A pow2 scale only shifts the
    exponent: ``x / s`` and ``q * s`` are EXACT in any binary float
    format, so fp8-grid values scaled back remain exactly
    representable in bf16/f32 — the property the fused kernels'
    operand-rounding emulation rests on."""
    amax = jnp.asarray(amax, jnp.float32)
    e = jnp.ceil(jnp.log2(jnp.where(amax > 0.0, amax, fmt_max)
                          / fmt_max))
    # ldexp(1, e) with an INTEGER exponent: exactly 2^e (jnp.exp2
    # lowers through exp(x*ln2) on some backends and misses the exact
    # power of two by an ulp — enough to break the exactness the
    # fp8-grid emulation depends on)
    s = jnp.ldexp(jnp.ones_like(amax), e.astype(jnp.int32))
    return jnp.where(amax > 0.0, s, 1.0)


def fp8_round(x, axis=None, scale=None):
    """Round ``x`` onto the float8_e4m3 grid: scale down by the pow2
    per-``axis`` scale (or the caller's ``scale`` — delayed-scaling
    callers pass scale_from_history), cast to f8e4m3 and back, scale
    up.  Returns values in ``x.dtype`` sitting exactly on the scaled
    fp8 grid — feed them to any matmul and the result is what an
    fp8-input MXU computes with f32 accumulation."""
    if scale is None:
        scale = pow2_scale(_amax(x, axis))
    x32 = x.astype(jnp.float32) / scale
    # the pow2 ceiling guarantees |x32| <= 448 already; the clip is a
    # belt against caller-provided (stale delayed) scales — e4m3
    # saturates to nan, not to the max finite value
    x32 = jnp.clip(x32, -FP8_E4M3_MAX, FP8_E4M3_MAX)
    q = x32.astype(jnp.float8_e4m3fn).astype(jnp.float32)
    return (q * scale).astype(x.dtype)


def amax_history_init(length: int):
    """A fresh rolling amax history (all zero — the first update
    fills slot 0)."""
    if length < 1:
        raise ValueError(f"amax history length {length} must be >= 1")
    return jnp.zeros((int(length),), jnp.float32)


def amax_history_update(hist, x):
    """Record ``max |x|`` into the history's newest slot, evicting the
    oldest (roll-and-write; O(length))."""
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)))
    return jnp.roll(hist, 1).at[0].set(amax)


def scale_from_history(hist, fmt_max=FP8_E4M3_MAX):
    """The delayed-scaling scale: pow2 over the HISTORY max — stale by
    up to ``length`` steps, which is the recipe's point (no
    same-step amax sync); a length-1 history is just-in-time
    scaling."""
    return pow2_scale(jnp.max(hist), fmt_max)


__all__ = [
    "INT8_MAX", "FP8_E4M3_MAX",
    "int8_scale", "quantize_int8", "dequantize_int8", "int8_roundtrip",
    "ef_compress_int8",
    "pow2_scale", "fp8_round",
    "amax_history_init", "amax_history_update", "scale_from_history",
]
