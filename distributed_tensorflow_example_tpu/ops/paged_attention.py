"""Paged KV-cache primitives for the decode engine (vLLM-style).

The serving decode path (serving/kv_cache.py) stores each block's
keys/values in fixed-size **pages** — ``[num_pages, page_size, H, Dh]``
pool arrays — and addresses a sequence's cache through a per-sequence
**block table**: row ``b`` lists, in logical order, the page ids that
hold sequence ``b``'s positions (logical position ``j`` lives at page
``table[b, j // page_size]``, row ``j % page_size``).  Ragged
sequences then pack one decode batch with zero padding waste beyond
the last partial page, and a finished sequence's pages return to the
pool immediately (PagedAttention's central idea, reproduced
TPU-natively with XLA scatter/gather — the layout is Pallas-ready:
a fused kernel would consume the same pool + table operands).

This module holds the three primitives the adapter composes; the
attention math itself stays in models/transformer.py's shared decode
forward so the paged and contiguous paths cannot drift (bit-parity is
a tested invariant, tests/test_serving.py).
"""

from __future__ import annotations

import jax.numpy as jnp


def scatter_kv_rows(pool: jnp.ndarray, page_ids: jnp.ndarray,
                    rows: jnp.ndarray, vals: jnp.ndarray) -> jnp.ndarray:
    """Write one cache row per sequence into the page pool.

    ``pool`` [num_pages, page_size, H, Dh]; ``page_ids``/``rows`` [B]
    int32 (each sequence's target page and row within it); ``vals``
    [B, H, Dh].  Distinct sequences own distinct pages (the allocator
    guarantees it), so the scatter indices never collide — except on
    the reserved scratch page dead slots write to, whose content is
    never read (their validity mask is empty)."""
    return pool.at[page_ids, rows].set(vals)


def scatter_prefill_rows(pool: jnp.ndarray, page_ids: jnp.ndarray,
                         rows: jnp.ndarray,
                         vals: jnp.ndarray) -> jnp.ndarray:
    """Write a whole prompt's rows at once: ``page_ids``/``rows``
    [B, P] address each of the P prefilled positions, ``vals``
    [B, P, H, Dh] holds the per-position k or v.  Padded positions
    (>= the sequence's true length) are routed to rows the decode
    either overwrites before reading (rows above the current position
    are masked until written) or to the scratch page."""
    b, p = page_ids.shape
    return pool.at[page_ids.reshape(b * p), rows.reshape(b * p)].set(
        vals.reshape((b * p,) + vals.shape[2:]))


def gather_kv(pool: jnp.ndarray, block_table: jnp.ndarray) -> jnp.ndarray:
    """Materialize the batch's logical KV view from the pool:
    ``block_table`` [B, W] page ids -> [B, W*page_size, H, Dh], where
    index ``j`` along the gathered axis IS logical position ``j``
    (pages are listed in order).  W is the *bucketed* live width —
    the gather touches only the blocks the batch can actually
    address, not the full max sequence length.  Generic over the
    trailing dims: the int8 pools' [num_pages, page_size, H] scale
    planes gather through the same table to [B, W*page_size, H]."""
    b, w = block_table.shape
    ps = pool.shape[1]
    return pool[block_table].reshape((b, w * ps) + pool.shape[2:])


def length_mask(kv_width: int, pos: jnp.ndarray) -> jnp.ndarray:
    """Validity over gathered positions: ``[B, kv_width]`` True where
    logical position ``j`` is readable for sequence ``b`` at decode
    position ``pos[b]`` (attend to ``<= pos``, exactly the contiguous
    decode's mask)."""
    return jnp.arange(kv_width)[None, :] <= pos[:, None]


def page_row_index(pos: jnp.ndarray, block_table: jnp.ndarray,
                   page_size: int) -> tuple[jnp.ndarray, jnp.ndarray]:
    """(page_ids [B], rows [B]) addressing position ``pos[b]`` of each
    sequence through its block-table row."""
    page_slot = pos // page_size
    page_ids = jnp.take_along_axis(
        block_table, page_slot[:, None], axis=1)[:, 0]
    return page_ids, pos % page_size


def prefill_page_rows(lengths_width: int, block_table: jnp.ndarray,
                      page_size: int) -> tuple[jnp.ndarray, jnp.ndarray]:
    """(page_ids [B, P], rows [B, P]) addressing positions
    ``0 .. lengths_width-1`` of every sequence — the prefill scatter's
    index plan (P = the bucketed prompt width)."""
    j = jnp.arange(lengths_width)
    pages = jnp.take_along_axis(
        block_table, jnp.broadcast_to(j[None, :] // page_size,
                                      (block_table.shape[0],
                                       lengths_width)), axis=1)
    rows = jnp.broadcast_to((j % page_size)[None, :],
                            (block_table.shape[0], lengths_width))
    return pages, rows


__all__ = ["scatter_kv_rows", "scatter_prefill_rows", "gather_kv",
           "length_mask", "page_row_index", "prefill_page_rows"]
