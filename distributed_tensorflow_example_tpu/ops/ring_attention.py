"""Sequence-parallel ring attention — the long-context primitive.

Beyond-reference capability: the reference has no sequence dimension at
all (inputs are flat ``[B, 784]`` images, /root/reference/example.py:69;
SURVEY.md §5 records long-context/SP as absent). This module supplies
the TPU-native sequence-parallel building block anyway, because it is
the canonical "context longer than one chip's HBM" answer for the mesh
this framework is built around — the same way tensor parallelism was
added as a config-level capability despite being absent upstream.

Design (blockwise ring attention):
- q, k, v are sharded along the sequence axis of a named mesh: each of
  the ``n`` shards holds a contiguous ``[B, S/n, H, D]`` block.
- Each shard keeps its q block resident and consumes one k/v block per
  ring step, combining blocks with the **online-softmax** recurrence
  (running row-max ``m``, normalizer ``l``, and un-normalized output
  accumulator ``o`` — numerically identical to one full softmax).
- After each step the k/v block moves to the next shard with
  ``lax.ppermute`` over the ring — on real hardware this is a
  neighbor-to-neighbor ICI transfer that XLA overlaps with the block's
  matmuls; total traffic per shard is exactly one pass of K and V, the
  same bytes a single all-gather would move, but with peak memory
  O(S/n) instead of O(S).
- Causal masking is by *global* position: block offsets are recovered
  from the ring step index, so the sharded result matches the
  single-device lower-triangular mask exactly.

``attention`` is the plain single-device reference implementation the
ring version is tested against (tests/test_ring_attention.py: bitwise-
close equivalence on an 8-virtual-device mesh, causal and full,
including gradients through the ring).

Precision note: the recurrence itself is exact (a reassociation of the
full softmax, accumulated in f32). On TPU the *matmuls* run at the
backend's default precision — bf16 inputs for f32 operands, the
standard choice for attention — so ring and dense outputs differ by
bf16 reassociation noise (~6e-3 measured at [2,128,4,64]); under
``jax.default_matmul_precision("highest")`` they agree to ~2e-7.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

NEG_INF = -1e30  # large-negative instead of -inf: keeps exp() and the
                 # running-max recurrence NaN-free for fully-masked rows


def pvary_axes(x, axes):
    """Declare ``x`` varying over mesh ``axes`` — the one
    pcast-with-pvary-fallback compatibility shim (jax renamed
    pvary -> pcast(..., to='varying'); older releases lack pcast).
    Shared by every site that lifts an axis-invariant value into a
    varying carry/branch type."""
    if hasattr(jax.lax, "pcast"):
        return jax.lax.pcast(x, axes, to="varying")
    return jax.lax.pvary(x, axes)  # older JAX


def attention(q, k, v, causal: bool = False):
    """Plain softmax attention, single device. [B, S, H, D] layout.

    The oracle for the ring version; also usable directly for short
    sequences. Unequal q/k lengths are supported non-causally; under
    ``causal=True`` they are rejected (a top-left-aligned tril would
    silently assume q position i aligns with k position i, which is
    not the conventional bottom-right alignment).
    """
    scale = 1.0 / np.sqrt(q.shape[-1])
    # [B, H, Sq, Sk]
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        sq, sk = scores.shape[-2], scores.shape[-1]
        if sq != sk:
            raise ValueError(
                f"causal attention requires equal q/k lengths, got "
                f"sq={sq}, sk={sk}"
            )
        mask = jnp.tril(jnp.ones((sq, sk), bool))
        scores = jnp.where(mask, scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs.astype(v.dtype), v)


def _block(q, k, v, m, l, o, q_off, k_off, causal: bool):
    """One online-softmax accumulation step for a (q block, kv block)
    pair. q: [B, Lq, H, D]; k, v: [B, Lk, H, D]; m, l: [B, H, Lq];
    o: [B, Lq, H, D] (un-normalized). Offsets are global sequence
    positions of the blocks' first rows (for the causal mask)."""
    scale = 1.0 / np.sqrt(q.shape[-1])
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        lq, lk = scores.shape[-2], scores.shape[-1]
        q_pos = q_off + jnp.arange(lq)
        k_pos = k_off + jnp.arange(lk)
        mask = k_pos[None, :] <= q_pos[:, None]
        scores = jnp.where(mask, scores, NEG_INF)
    m_blk = jnp.max(scores, axis=-1)                # [B, H, Lq]
    m_new = jnp.maximum(m, m_blk)
    # rescale previous accumulators to the new max
    alpha = jnp.exp(m - m_new)                      # [B, H, Lq]
    p = jnp.exp(scores - m_new[..., None])          # [B, H, Lq, Lk]
    # a fully-masked row still has m_new == NEG_INF, making
    # exp(NEG_INF - NEG_INF) == 1 for every masked key — zero those
    # weights explicitly so masked keys never contribute
    p = jnp.where(scores <= NEG_INF / 2, 0.0, p)
    l_new = l * alpha + jnp.sum(p, axis=-1)
    o_new = (
        o * jnp.transpose(alpha, (0, 2, 1))[..., None]
        + jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    )
    return m_new, l_new, o_new


def _lift_varying(x, ref):
    """Declare an axis-invariant constant varying over every manual
    axis ``ref`` is varying over — ring loop carries start as invariant
    zeros but are rebound to q-dependent (varying) values, and the
    carry types must match. Matching REF (rather than just the ring
    axis) matters under multi-axis meshes: in ('data','seq') SP+DP
    training q is varying over both axes, so the carries must be too.
    Idempotent for axes already varying."""
    try:
        want = set(jax.typeof(ref).vma)
        have = set(jax.typeof(x).vma)
    except (AttributeError, TypeError):
        return x
    missing = tuple(sorted(want - have))
    if not missing:
        return x
    return pvary_axes(x, missing)


def _rotate_unless_last(kv, t, n, axis_name: str):
    """Pass k/v to the next ring neighbor, skipping the redundant final
    rotation. Rotation happens AFTER a step consumes its block."""
    perm = [(j, (j + 1) % n) for j in range(n)]
    return jax.lax.cond(
        t < n - 1,
        lambda kv_: jax.tree.map(
            functools.partial(jax.lax.ppermute, axis_name=axis_name,
                              perm=perm), kv_),
        lambda kv_: kv_,
        kv,
    )


def ring_attention(q, k, v, axis_name: str, causal: bool = False):
    """Sequence-parallel attention inside shard_map.

    q, k, v: this shard's sequence block ``[B, S/n, H, D]`` (sequence
    sharded over ``axis_name``; blocks are contiguous, shard i holding
    positions ``[i*S/n, (i+1)*S/n)``). Returns this shard's output
    block. Exact (not approximate): identical math to full softmax via
    the online recurrence.
    """
    n = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    b, lq, h, d = q.shape
    lk = k.shape[1]

    m = _lift_varying(jnp.full((b, h, lq), NEG_INF, jnp.float32), q)
    l = _lift_varying(jnp.zeros((b, h, lq), jnp.float32), q)
    o = _lift_varying(jnp.zeros((b, lq, h, d), jnp.float32), q)
    q_off = idx * lq

    # ring: at step t this shard holds the block that started on shard
    # (idx - t) mod n
    def step(t, carry):
        k_t, v_t, m_, l_, o_ = carry
        k_off = ((idx - t) % n) * lk
        m_, l_, o_ = _block(q, k_t, v_t, m_, l_, o_, q_off, k_off, causal)
        k_t, v_t = _rotate_unless_last((k_t, v_t), t, n, axis_name)
        return k_t, v_t, m_, l_, o_

    _, _, m, l, o = jax.lax.fori_loop(0, n, step, (k, v, m, l, o))
    # normalize; a fully-masked row has l == 0 and o == 0 (masked
    # weights are zeroed in _block), so the guard makes it 0/1e-30 = 0
    l = jnp.maximum(l, 1e-30)
    out = o / jnp.transpose(l, (0, 2, 1))[..., None]
    return out.astype(q.dtype)


def _merge_partials(m, l, o, m_b, l_b, acc_b):
    """Combine two un-normalized softmax partials by their (max,
    normalizer) statistics — the cross-block analog of _block's online
    update. Layout [B, L, H, 1] for m/l, [B, L, H, D] for o/acc."""
    m_new = jnp.maximum(m, m_b)
    a = jnp.exp(m - m_new)
    b = jnp.exp(m_b - m_new)
    return m_new, l * a + l_b * b, o * a + acc_b * b


def ring_flash_attention(q, k, v, axis_name: str, causal: bool = False,
                         stats_fn=None):
    """Ring SP composed with the intra-chip flash kernel: the ring
    moves k/v blocks between chips (ppermute) while each block pair is
    computed by ops/flash_attention's tiled Pallas kernel returning raw
    (acc, m, l) partials, merged across ring steps by _merge_partials.
    This is the full long-context stack: O(S/n) HBM per chip from the
    ring AND no [L, L] score materialization within a chip.

    Under causal masking each kv block is classified once per step —
    strictly-past blocks run the unmasked kernel, the diagonal block
    runs the causal kernel (local positions align), and future blocks
    are skipped outright (no kernel launch, no wasted MXU work —
    unlike single-chip flash where masked tiles still execute).

    On CPU backends (and local blocks not divisible by the 256 tile)
    this delegates to ``ring_attention`` — identical math, XLA blocks,
    differentiable by autodiff.

    ``stats_fn(q, k, v, causal) -> (acc, m, l)`` overrides the block
    backend (tests inject an XLA implementation so the ring/branch/
    merge machinery is exercised on the CPU mesh, where interpret-mode
    Pallas cannot run inside shard_map); the stats_fn path is
    forward-only.

    Training: the kernel path is differentiable — its custom VJP
    (``_rf_bwd``) runs a second ring pass in which each k/v block
    travels WITH its gradient accumulators, every shard adding its
    block-pair contribution via the flash backward kernels
    (O(L·blk) per pair, no [L, L] scores).
    """
    from . import flash_attention as fa

    lq = q.shape[1]
    if stats_fn is not None:
        return _ring_flash_impl(q, k, v, axis_name, causal, stats_fn)[0]
    if fa._interpret() or lq % fa._BLK or k.shape[1] != lq:
        return ring_attention(q, k, v, axis_name, causal)
    return _ring_flash_diff(q, k, v, axis_name, causal)


def _ring_flash_impl(q, k, v, axis_name: str, causal: bool, stats_fn):
    """The forward ring loop; returns (o, m, l) — the normalized output
    plus its softmax statistics (the custom VJP's residuals)."""
    lq = q.shape[1]
    n = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    b, _, h, d = q.shape

    m = _lift_varying(jnp.full((b, lq, h, 1), NEG_INF, jnp.float32), q)
    l = _lift_varying(jnp.zeros((b, lq, h, 1), jnp.float32), q)
    o = _lift_varying(jnp.zeros((b, lq, h, d), jnp.float32), q)

    def step(t, carry):
        k_t, v_t, m_, l_, o_ = carry
        rel = (idx - t) % n  # which block of the sequence we hold now

        def merge_with(block_causal):
            def go(args):
                m0, l0, o0 = args
                acc_b, m_b, l_b = stats_fn(q, k_t, v_t, block_causal)
                return _merge_partials(m0, l0, o0, m_b, l_b, acc_b)

            return go

        if causal:
            # 0: future block (skip), 1: diagonal (causal kernel),
            # 2: past block (unmasked kernel)
            branch = jnp.where(rel > idx, 0, jnp.where(rel == idx, 1, 2))
            m_, l_, o_ = jax.lax.switch(
                branch,
                [lambda args: args, merge_with(True), merge_with(False)],
                (m_, l_, o_),
            )
        else:
            m_, l_, o_ = merge_with(False)((m_, l_, o_))
        k_t, v_t = _rotate_unless_last((k_t, v_t), t, n, axis_name)
        return k_t, v_t, m_, l_, o_

    _, _, m, l, o = jax.lax.fori_loop(0, n, step, (k, v, m, l, o))
    return (o / jnp.maximum(l, 1e-30)).astype(q.dtype), m, l


def _rotate_always(tree, axis_name: str, n):
    """One ring rotation of every leaf (the backward pass rotates all n
    steps so traveling accumulators arrive back home)."""
    perm = [(j, (j + 1) % n) for j in range(n)]
    return jax.tree.map(
        functools.partial(jax.lax.ppermute, axis_name=axis_name, perm=perm),
        tree,
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _ring_flash_diff(q, k, v, axis_name: str, causal: bool):
    from . import flash_attention as fa

    stats_fn = lambda q_, k_, v_, c: fa._flash_stats(q_, k_, v_, c, fa._BLK)
    return _ring_flash_impl(q, k, v, axis_name, causal, stats_fn)[0]


def _rf_fwd(q, k, v, axis_name, causal):
    from . import flash_attention as fa

    stats_fn = lambda q_, k_, v_, c: fa._flash_stats(q_, k_, v_, c, fa._BLK)
    o, m, l = _ring_flash_impl(q, k, v, axis_name, causal, stats_fn)
    return o, (q, k, v, o, m, l)


def _rf_bwd(axis_name, causal, res, do):
    """The backward ring: k/v blocks travel the ring again, this time
    carrying their dk/dv accumulators; each shard adds its (q block x
    visiting block) contribution with the flash backward kernels and
    accumulates dq locally. The accumulators rotate on every step (n
    rotations bring them home); the k/v blocks skip the final, dead
    rotation. All ring traffic and accumulation run in the kernels'
    flat [BH, L, ...] layout with the loop-invariant prologue (layout
    transposes and the dlt = rowsum(do*o) reduction) hoisted out of
    the loop, and partials stay f32 end to end."""
    from . import flash_attention as fa

    q, k, v, o, m, l = res
    b, lq, h, d = q.shape
    n = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)

    def prep(x):
        return x.transpose(0, 2, 1, 3).reshape(b * h, lq, x.shape[-1])

    qf, dof, mf, lf = map(prep, (q, do, m, l))
    dlt = prep(jnp.sum(
        do.astype(jnp.float32) * o.astype(jnp.float32),
        axis=-1, keepdims=True,
    ))
    kf, vf = prep(k), prep(v)
    zeros = lambda: _lift_varying(
        jnp.zeros((b * h, lq, d), jnp.float32), qf)
    dq0, dk0, dv0 = zeros(), zeros(), zeros()

    def step(t, carry):
        k_t, v_t, dk_t, dv_t, dq_ = carry
        rel = (idx - t) % n

        def contrib(block_causal):
            def go(args):
                dk0_, dv0_, dq0_ = args
                dqp, dkp, dvp = fa._flash_backward_flat(
                    qf, k_t, v_t, dof, mf, lf, dlt, block_causal,
                    fa._BLK, q.dtype,
                )
                return dk0_ + dkp, dv0_ + dvp, dq0_ + dqp

            return go

        if causal:
            branch = jnp.where(rel > idx, 0, jnp.where(rel == idx, 1, 2))
            dk_t, dv_t, dq_ = jax.lax.switch(
                branch,
                [lambda args: args, contrib(True), contrib(False)],
                (dk_t, dv_t, dq_),
            )
        else:
            dk_t, dv_t, dq_ = contrib(False)((dk_t, dv_t, dq_))
        k_t, v_t = _rotate_unless_last((k_t, v_t), t, n, axis_name)
        dk_t, dv_t = _rotate_always((dk_t, dv_t), axis_name, n)
        return k_t, v_t, dk_t, dv_t, dq_

    _, _, dk, dv, dq = jax.lax.fori_loop(
        0, n, step, (kf, vf, dk0, dv0, dq0))

    def un(x, dt):
        return x.reshape(b, h, lq, d).transpose(0, 2, 1, 3).astype(dt)

    return un(dq, q.dtype), un(dk, k.dtype), un(dv, v.dtype)


_ring_flash_diff.defvjp(_rf_fwd, _rf_bwd)
