"""Cluster / runtime bootstrap.

Reference parity: the reference builds a ``tf.train.ClusterSpec`` over
hardcoded ``host:2222`` endpoints and starts an in-process gRPC
``tf.train.Server`` per task (/root/reference/example.py:22-38); the
parameter-server role then blocks in ``server.join()`` (example.py:50-51)
while workers wait for a ready session via ``tf.train.Supervisor``
(example.py:132-138).

TPU-native design (SURVEY.md L1): there is no role split — SPMD makes
every process a worker. ``jax.distributed.initialize`` provides the
coordination service (the coordinator address plays the spirit of the
ps endpoint), and chief-ness is simply ``jax.process_index() == 0``,
replacing ``Supervisor(is_chief=...)``. A startup barrier replaces
``prepare_or_wait_for_session``; parameter broadcast is unnecessary
because every process runs the identical seeded init (deterministic and
barrier-free, SURVEY.md §3.2).
"""

from __future__ import annotations

import jax

from .config import Config


def bootstrap(cfg: Config) -> None:
    """Initialize the distributed runtime from flags.

    Maps the reference CLI onto ``jax.distributed``:
      - ``--coordinator_address`` ≈ the ps endpoint ``pc-01:2222``
        (example.py:23) — but serves only bootstrap, never tensors;
      - ``--task_index`` ≈ the reference's task index (example.py:31-32),
        reused as the process id;
      - ``--job_name=ps`` is absorbed: the ps role is eliminated
        (SURVEY.md §7). We print the explanation once for operators
        porting run scripts from the reference.
    """
    if cfg.job_name == "ps":
        print(
            "NOTE: --job_name=ps maps to a no-op under SPMD: parameters are "
            "device-resident and gradient exchange is a compiled psum "
            "allreduce, so there is no parameter-server role. This process "
            "will participate as a regular worker."
        )
    if cfg.coordinator_address and cfg.num_processes > 1:
        jax.distributed.initialize(
            coordinator_address=cfg.coordinator_address,
            num_processes=cfg.num_processes,
            process_id=cfg.task_index,
        )


def enable_compilation_cache(cfg: Config) -> None:
    """Persistent XLA compile cache (the analog of the reference reusing
    its built graph across sess.run calls — here across *processes*).

    First compile of the fused training program costs tens of seconds
    through a remote-compile path; warm runs load the serialized
    executable in ~ms. "auto" keeps the cache next to the repo so bench
    and CLI runs share it.
    """
    path = cfg.compilation_cache
    if not path:
        return
    if path == "auto":
        import os

        path = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                            ".jax_cache")
    jax.config.update("jax_compilation_cache_dir", path)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)


def is_chief() -> bool:
    """Replaces ``Supervisor(is_chief=(task_index == 0))`` (example.py:132)."""
    return jax.process_index() == 0


def shutdown() -> None:
    """Replaces ``sv.stop()`` (example.py:181)."""
    if jax.process_count() > 1:
        jax.distributed.shutdown()
