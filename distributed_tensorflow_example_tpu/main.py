"""CLI entry point.

Reference parity: ``python example.py --job_name={ps,worker}
--task_index=N`` (/root/reference/example.py:6-11, 29-32). Same flags
here — ``python -m distributed_tensorflow_example_tpu.main
--job_name=worker --task_index=0`` — plus every formerly-hardcoded
constant as a flag (config.py). Under SPMD there is no ps role
(SURVEY.md §7): ``--job_name=ps`` participates as a worker after
printing the mapping explanation.
"""

from __future__ import annotations

import os
import sys

from .config import parse_config
from .train.loop import run


def main(argv=None) -> int:
    # Operator platform override (e.g. DTX_PLATFORM=cpu for local runs /
    # multi-process localhost smoke tests). Needed as a config update,
    # not an env var: this image's TPU plugin pins jax_platforms via
    # jax.config at interpreter start, which wins over JAX_PLATFORMS.
    platform = os.environ.get("DTX_PLATFORM")
    if platform:
        import jax

        jax.config.update("jax_platforms", platform)
    cfg = parse_config(argv)
    # Operator telemetry override: DTX_METRICS=1 enables the --metrics
    # JSONL stream (obs/) without editing the command line — the knob a
    # driver/orchestrator flips fleet-wide when diagnosing stragglers.
    # Gated on the VALUE: a templated DTX_METRICS=0 must stay off.
    if (os.environ.get("DTX_METRICS", "").strip().lower()
            in ("1", "true", "yes", "on") and not cfg.metrics):
        cfg = cfg.replace(metrics=True)
    run(cfg)
    return 0


if __name__ == "__main__":
    sys.exit(main())
