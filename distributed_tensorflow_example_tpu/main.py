"""CLI entry point.

Reference parity: ``python example.py --job_name={ps,worker}
--task_index=N`` (/root/reference/example.py:6-11, 29-32). Same flags
here — ``python -m distributed_tensorflow_example_tpu.main
--job_name=worker --task_index=0`` — plus every formerly-hardcoded
constant as a flag (config.py). Under SPMD there is no ps role
(SURVEY.md §7): ``--job_name=ps`` participates as a worker after
printing the mapping explanation.
"""

from __future__ import annotations

import os
import sys

from .config import parse_config
from .train.loop import run


def main(argv=None) -> int:
    # Operator platform override (e.g. DTX_PLATFORM=cpu for local runs /
    # multi-process localhost smoke tests). Needed as a config update,
    # not an env var: this image's TPU plugin pins jax_platforms via
    # jax.config at interpreter start, which wins over JAX_PLATFORMS.
    platform = os.environ.get("DTX_PLATFORM")
    if platform:
        import jax

        jax.config.update("jax_platforms", platform)
    cfg = parse_config(argv)
    # Operator env overrides — the knobs a driver/orchestrator flips
    # fleet-wide without editing the command line: DTX_METRICS=1
    # enables the --metrics JSONL stream (straggler diagnosis),
    # DTX_FLIGHT=1 the crash flight recorder (obs/flight.py post-
    # mortem dumps). Gated on the VALUE: a templated DTX_X=0 stays off.
    def env_flag(name: str) -> bool:
        return (os.environ.get(name, "").strip().lower()
                in ("1", "true", "yes", "on"))

    for env_name, field in (("DTX_METRICS", "metrics"),
                            ("DTX_FLIGHT", "flight")):
        if env_flag(env_name) and not getattr(cfg, field):
            cfg = cfg.replace(**{field: True})
    # DTX_STATUS_PORT=P: the live /status + Prometheus endpoint
    # (obs/serve.py), fleet-enabled the same way
    port = os.environ.get("DTX_STATUS_PORT", "").strip()
    if port.isdigit() and int(port) and not cfg.status_port:
        cfg = cfg.replace(status_port=int(port))
    run(cfg)
    return 0


if __name__ == "__main__":
    sys.exit(main())
