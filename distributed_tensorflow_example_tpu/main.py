"""CLI entry point.

Reference parity: ``python example.py --job_name={ps,worker}
--task_index=N`` (/root/reference/example.py:6-11, 29-32). Same flags
here — ``python -m distributed_tensorflow_example_tpu.main
--job_name=worker --task_index=0`` — plus every formerly-hardcoded
constant as a flag (config.py). Under SPMD there is no ps role
(SURVEY.md §7): ``--job_name=ps`` participates as a worker after
printing the mapping explanation.
"""

from __future__ import annotations

import sys

from .config import parse_config
from .train.loop import run


def main(argv=None) -> int:
    cfg = parse_config(argv)
    run(cfg)
    return 0


if __name__ == "__main__":
    sys.exit(main())
