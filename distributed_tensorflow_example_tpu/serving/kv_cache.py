"""Paged/block KV cache + the paged decode/prefill programs.

The contiguous decode cache (models/transformer.init_decode_cache)
preallocates ``[B, seq_len, H, Dh]`` per block per sequence — a
4K-context request that generates 30 tokens still owns 4K rows, and a
batch must be torn down and re-padded whenever membership changes.
This module reproduces vLLM's PagedAttention layout TPU-natively:

- the pool: per block ``k{i}/v{i}`` arrays ``[num_pages, page_size,
  H, Dh]`` — the ONLY cache allocation, made once;
- the block table: ``[B, W]`` int32 page ids per sequence, W bucketed
  to the live maximum (logical position ``j`` of row ``b`` lives at
  page ``table[b, j // page_size]``, row ``j % page_size``);
- page 0 is the SCRATCH page: dead batch slots and padded prefill
  rows write there, nothing ever reads it (the allocator hands out
  pages 1..num_pages-1).

``paged_decode_step`` runs models/transformer.py's shared
``_decode_forward`` — the identical math as the contiguous
``decode_step``, only the cache adapter differs — so greedy decode is
token-identical across layouts and page sizes (tests/test_serving.py
pins it, including ragged positions and a TP-sharded cache).
``prefill_into_pages`` runs the existing batched training forward
(``_block_forward`` with ``kv_out`` capture) over the whole prompt at
once and scatters the rows into the pages: prompts cost one program,
not P sequential steps.  ``sample_tokens`` folds greedy/temperature
sampling into the same compiled program so logits never round-trip
to the host.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from ..models import transformer as tfm
from ..models.mlp import _ACTIVATIONS
from ..ops import paged_attention as pa
from ..ops import quant as quant_lib

# valid --kv_quant values ("" = the compute-dtype pool)
KV_QUANTS = ("", "int8")


def local_heads(spec: tfm.TransformerSpec, params) -> int:
    """The cache's head count: the LOCAL heads this shard's ``Wqkv``
    columns hold (== spec.n_heads outside tensor parallelism)."""
    return int(jnp.shape(params["L0_Wqkv"])[-1]) // spec.d_head


def init_paged_cache(spec: tfm.TransformerSpec, num_pages: int,
                     page_size: int, heads: int | None = None,
                     quant: str = ""):
    """The page pool: ``{k{i}/v{i}: [num_pages, page_size, H, Dh]}``
    in the compute dtype (the cache stores the same rounded k/v the
    training attention consumes — the contiguous cache's convention).

    ``quant='int8'`` (ISSUE 11 leg a) stores the pools as int8 with a
    per-row/per-head f32 scale PLANE per pool
    (``k{i}_s``/``v{i}_s`` [num_pages, page_size, H]): every cached
    row is quantized symmetrically over its Dh lane
    (ops/quant.quantize_int8), halving the KV bytes a decode step
    streams (obs/flops.decode_kv_bytes_per_step at kv_dtype_bytes=1)
    for a 4/Dh scale overhead.  The adapter dequantizes the gathered
    view back to the compute dtype, so the attention math in
    ``transformer._decode_forward`` is untouched."""
    if quant not in KV_QUANTS:
        raise ValueError(f"kv quant {quant!r}: expected one of "
                         f"{list(KV_QUANTS)}")
    shape = (num_pages, page_size, heads or spec.n_heads, spec.d_head)
    cache = {}
    for i in range(spec.num_blocks):
        if quant == "int8":
            cache[f"k{i}"] = jnp.zeros(shape, jnp.int8)
            cache[f"v{i}"] = jnp.zeros(shape, jnp.int8)
            cache[f"k{i}_s"] = jnp.zeros(shape[:3], jnp.float32)
            cache[f"v{i}_s"] = jnp.zeros(shape[:3], jnp.float32)
        else:
            cache[f"k{i}"] = jnp.zeros(shape, spec.compute_dtype)
            cache[f"v{i}"] = jnp.zeros(shape, spec.compute_dtype)
    return cache


@dataclasses.dataclass
class PagedKV:
    """Cache adapter for ``transformer._decode_forward``: writes each
    block's new row through the block table and returns the gathered
    page view + ragged-length mask for attention.  ``pos`` is [B]
    (per-sequence positions — THE ragged-batch difference from the
    contiguous adapter's scalar).

    An int8 pool (``k{i}_s`` scale planes present) quantizes each new
    row per head on the way in and dequantizes the gathered view back
    to ``dequant_dtype`` on the way out — the attention math never
    sees the wire format."""

    page_size: int
    cache: dict
    block_table: jnp.ndarray      # [B, W] int32
    pos: jnp.ndarray              # [B] int32
    dequant_dtype: jnp.dtype = jnp.float32

    def __post_init__(self):
        self._page_ids, self._rows = pa.page_row_index(
            self.pos, self.block_table, self.page_size)
        kvw = self.block_table.shape[1] * self.page_size
        # [B, 1, S_kv], broadcast over heads in the score mask
        self.valid = pa.length_mask(kvw, self.pos)[:, None, :]
        self.quantized = "k0_s" in self.cache

    def _put(self, name: str, vals):
        """Scatter one row per sequence into pool ``name`` (values +
        scale plane when quantized); returns the gathered, dequantized
        [B, S_kv, H, Dh] view."""
        if not self.quantized:
            pool = pa.scatter_kv_rows(self.cache[name], self._page_ids,
                                      self._rows, vals)
            self.cache[name] = pool
            return pa.gather_kv(pool, self.block_table)
        with jax.named_scope("quant"):
            q, s = quant_lib.quantize_int8(vals, axis=-1)   # [B,H,(1)]
        pool = pa.scatter_kv_rows(self.cache[name], self._page_ids,
                                  self._rows, q)
        splane = pa.scatter_kv_rows(self.cache[f"{name}_s"],
                                    self._page_ids, self._rows,
                                    s[..., 0])
        self.cache[name], self.cache[f"{name}_s"] = pool, splane
        cq = pa.gather_kv(pool, self.block_table)           # int8
        cs = pa.gather_kv(splane, self.block_table)         # [B,S,H]
        with jax.named_scope("quant"):
            return quant_lib.dequantize_int8(cq, cs[..., None],
                                             self.dequant_dtype)

    def update(self, i: int, kk, vv):
        # gather AFTER the write: position pos attends to itself,
        # exactly like the contiguous dynamic-update-then-attend
        ck = self._put(f"k{i}", kk)
        cv = self._put(f"v{i}", vv)
        return ck, cv, self.valid


def paged_decode_step(spec: tfm.TransformerSpec, params, cache,
                      block_table, token, pos,
                      model_axis: str | None = None):
    """One decode step over the paged cache: ``token``/``pos`` [B]
    (ragged per-sequence positions), gathers keys/values over the
    block-table's live pages only, returns (logits [B, V], cache).
    The math is ``transformer._decode_forward`` — shared with the
    contiguous ``decode_step``, so the layouts cannot drift."""
    kv = PagedKV(page_size=_page_size(cache),
                 cache=dict(cache), block_table=block_table, pos=pos,
                 dequant_dtype=spec.compute_dtype)
    logits = tfm._decode_forward(spec, params, token, pos, kv,
                                 model_axis=model_axis)
    return logits, kv.cache


def _page_size(cache) -> int:
    return int(jnp.shape(cache["k0"])[1])


def prefill_into_pages(spec: tfm.TransformerSpec, params, cache,
                       block_table, tokens, lengths,
                       model_axis: str | None = None):
    """Prefill whole prompts with ONE batched forward: run the
    training forward over ``tokens`` [B, P] (P = the bucketed prompt
    width; rows past ``lengths[b]`` are pad), capture every block's
    k/v via ``_block_forward(kv_out=...)``, scatter rows
    ``0..lengths[b]-1`` into the pages, and return
    (last-position logits [B, V], cache) — the logits at position
    ``lengths[b]-1``, i.e. the first generated token's distribution.

    Exactness: causal attention means pad rows never influence live
    positions; pad k/v rows scatter into rows the decode overwrites
    before any mask exposes them (or into the scratch page).  MoE
    routes dense like the decode path (the shared convention)."""
    if spec.objective != "lm":
        raise ValueError("prefill serves the lm objective only")
    if not spec.causal:
        raise ValueError("prefill requires a causal spec (lm decode)")
    params = {k: jnp.asarray(v) for k, v in params.items()}
    # dense dispatch + dense attention: the decode path's conventions
    # (exact MoE routing; ragged prompt widths are never tile-aligned,
    # and the dense score math is what decode_step mirrors)
    if spec.moe_dispatch != "dense" or spec.attention != "dense":
        spec = dataclasses.replace(spec, moe_dispatch="dense",
                                   attention="dense")
    cdt = spec.compute_dtype
    b, p = tokens.shape
    page_size = _page_size(cache)
    h = (params["W_emb"].astype(jnp.float32)[tokens]
         + params["pos"].astype(jnp.float32)[None, :p])   # [B, P, D]
    act = _ACTIVATIONS[spec.activation]
    page_ids, rows = pa.prefill_page_rows(p, block_table, page_size)
    cache = dict(cache)
    quantized = "k0_s" in cache

    def put(name, vals):
        """[B, P, Hl, Dh] rows into pool ``name`` (+ the scale plane
        when the pool is int8 — same per-row/per-head convention as
        the decode adapter, so prefill and decode cannot drift)."""
        if not quantized:
            cache[name] = pa.scatter_prefill_rows(cache[name],
                                                  page_ids, rows, vals)
            return
        with jax.named_scope("quant"):
            q, s = quant_lib.quantize_int8(vals, axis=-1)
        cache[name] = pa.scatter_prefill_rows(cache[name], page_ids,
                                              rows, q)
        cache[f"{name}_s"] = pa.scatter_prefill_rows(
            cache[f"{name}_s"], page_ids, rows, s[..., 0])

    for i in range(spec.num_blocks):
        bp = {k[len(f"L{i}_"):]: v for k, v in params.items()
              if k.startswith(f"L{i}_")}
        kv_out: list = []
        h, _aux = tfm._block_forward(spec, bp, h, act, cdt,
                                     model_axis=model_axis, moe_block=i,
                                     kv_out=kv_out)
        (kk, vv), = kv_out                                # [B, P, Hl, Dh]
        put(f"k{i}", kk)
        put(f"v{i}", vv)
    # head only at each prompt's LAST position: gather [B, D] then the
    # rank-2 final LN + vocab projection (the decode sites' shape)
    last = jnp.take_along_axis(
        h, (lengths - 1)[:, None, None].astype(jnp.int32), axis=1)[:, 0]
    hf = tfm._ln(spec, last, params["lnf_g"], params["lnf_b"])
    logits = tfm._mm(params, hf, "W_head", "b_head",
                     cdt).astype(jnp.float32)
    return logits, cache


def sample_tokens(logits, rng, temperature):
    """Fused sampling: greedy argmax where ``temperature[b] <= 0``,
    categorical at ``logits / temperature[b]`` otherwise — ONE
    program for the whole ragged batch, selected per sequence, so the
    [B, V] logits never leave the device.  ``temperature`` [B] f32;
    ``rng`` a single key (categorical draws independently per row)."""
    greedy = jnp.argmax(logits, axis=-1)
    safe = jnp.where(temperature > 0, temperature, 1.0)
    sampled = jax.random.categorical(
        rng, logits / safe[:, None].astype(jnp.float32), axis=-1)
    return jnp.where(temperature > 0, sampled, greedy).astype(jnp.int32)
