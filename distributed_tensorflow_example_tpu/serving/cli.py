"""``dtx-serve`` — the serving front door.

Builds the transformer spec from the SAME config.py flag surface as
training (one vocabulary of ``--d_model``/``--num_blocks``/... for
both halves of the system), loads params from a training checkpoint
(``--checkpoint_dir``, the utils/checkpoint .npz format) or falls
back to a seeded init (demo mode), starts the continuous-batching
``DecodeEngine``, and serves:

- ``POST /generate`` — ``{"prompt": [ints], "max_new_tokens": N,
  "temperature": t}`` -> completion + latency (obs/serve.py);
- ``GET /status`` / ``/metrics`` — the run-status surface plus the
  ``dtx_generate_*`` serving gauges.

Engine knobs: ``--decode_page_size`` (tokens per KV page),
``--decode_pages`` (pool size; 0 sizes for ``--decode_max_batch``
worst-case sequences), ``--decode_max_batch`` (concurrent decode
slots = the largest batch bucket), ``--serve_port``.

Observability knobs: ``--trace_spans`` records every request's
lifecycle to ``<logs_path>/spans.<proc>.jsonl`` (obs/spans.py) and
lights up ``/trace?rid=N``, ``/slo``, ``/fleet`` and the
``dtx_slo_*``/``dtx_fleet_*`` gauges; ``--span_rotate_mb`` /
``--span_keep`` bound the span stream's disk (size-based rotation,
readers stitch segments); ``--slo`` overrides the SLO specs those
evaluate (obs/slo.py DSL, e.g. ``ttft_p99_ms<=250,error_rate<=0.01``).
``POST /generate`` accepts and returns a W3C ``traceparent`` header —
the request's spans carry the caller's trace id (obs/serve.py).

Fleet knobs: ``--replicas N`` (N > 1) runs N in-process engines
behind the serving/router front door (least-loaded placement over
health scores, per-replica circuit breakers via ``--breaker``,
cross-engine failover bounded by ``--fleet_retries``); per-replica
span streams land under ``<logs>/replica<i>`` and the router's
route/failover narration under ``<logs>/router`` so ``dtx-obs
fleet`` joins the whole story.  SIGTERM drains: stop admitting,
finish in-flight, typed-shed the queue.

Replay mode: ``--replay workload.json`` (a ``dtx-obs capture``
WORKLOAD) feeds the recorded request schedule back through the
engine — or the ``--replicas N`` fleet — at the recorded arrival
offsets (``--replay_speed`` compresses time) and prints the replay
report instead of serving HTTP; every span the run writes carries
``replay_of: <workload_id>`` (serving/replay.py).
"""

from __future__ import annotations

import re
import sys
import time
from typing import Optional, Sequence


def _params_from_checkpoint(path: str, expect: dict):
    """Pull the flat transformer params out of a training checkpoint:
    state leaves are saved under tree-path keys, so match each
    expected param name against the flattened key tails (shape-checked
    — optimizer slots share names with neither params nor each
    other's tails)."""
    import glob
    import os

    import numpy as np

    if os.path.isdir(path):
        cands = sorted(glob.glob(os.path.join(path, "ckpt-*.npz")))
        if not cands:
            raise FileNotFoundError(f"no ckpt-*.npz under {path}")
        path = cands[-1]
    from ..utils.checkpoint import _decode_leaf

    out = {}
    with np.load(path) as z:
        dts = {m.group(1): str(z[k][()])
               for k in z.files
               for m in [re.fullmatch(r"__dt_(.+)__", k)] if m}
        # optimizer slots share every param's name and shape under
        # their own subtree: visit the params/ paths first so the
        # weights win, slots only ever fill a gap (older formats)
        ordered = sorted((k for k in z.files if not k.startswith("__")),
                         key=lambda k: (0 if "params" in k else 1, k))
        for k in ordered:
            tail = k.split("/")[-1]
            if tail in expect and tuple(z[k].shape) == expect[tail] \
                    and tail not in out:
                a = z[k]
                if k in dts:
                    a = _decode_leaf(a, dts[k])
                out[tail] = a
    missing = sorted(set(expect) - set(out))
    if missing:
        raise ValueError(f"{path}: checkpoint lacks params {missing} "
                         f"(wrong model flags for this checkpoint?)")
    return out, path


def _spec_from_cfg(cfg):
    """The lm-transformer slice of train/loop.make_spec, inlined so
    dtx-serve never imports the training stack (the loop pulls the
    mesh/step machinery, which serving does not need)."""
    import jax.numpy as jnp

    from ..models.transformer import TransformerSpec

    return TransformerSpec(
        input_size=cfg.input_size, num_classes=cfg.num_classes,
        objective="lm", vocab_size=cfg.vocab_size,
        seq_len=cfg.input_size,      # lm tokenizes every input scalar
        d_model=cfg.d_model, n_heads=cfg.n_heads,
        num_blocks=cfg.num_blocks, d_ff=cfg.d_ff,
        activation=(cfg.activation if cfg.activation != "sigmoid"
                    else "gelu"),
        attention="flash" if cfg.pallas else cfg.attention,
        sp_impl=cfg.sp_impl, causal=True,
        num_experts=cfg.num_experts, moe_topk=cfg.moe_topk,
        moe_dispatch=cfg.moe_dispatch,
        capacity_factor=cfg.capacity_factor,
        aux_loss_weight=cfg.moe_aux_weight,
        fused_ln=cfg.fused_ln, grouped_moe=cfg.grouped_moe,
        fp8_ffn=cfg.fp8_ffn,
        param_dtype=jnp.dtype(cfg.param_dtype),
        compute_dtype=jnp.dtype(cfg.compute_dtype),
    )


def _main_fleet(cfg, spec, params, slos, brownout) -> int:
    """``--replicas N`` (N > 1): the fleet mode.  N in-process
    ``DecodeEngine`` replicas — each with its own span stream under
    ``<logs>/replica<i>`` so ``dtx-obs fleet`` federates them as
    sources — behind the serving/router least-loaded health-scored
    front door.  The router's route/failover narration lands in
    ``<logs>/router``; SIGTERM drains (stop admitting, finish
    in-flight, typed-shed the queue)."""
    import os

    from .engine import DecodeEngine
    from .health import parse_breaker
    from .router import Router, RouterServer

    breaker = parse_breaker(cfg.breaker or "on")
    recorders = []

    def _recorder(sub):
        if not cfg.trace_spans:
            return None
        from ..obs.spans import SpanRecorder

        rec = SpanRecorder(
            os.path.join(cfg.logs_path, sub),
            rotate_bytes=int(cfg.span_rotate_mb * 1024 * 1024),
            keep=cfg.span_keep)
        recorders.append(rec)
        return rec

    narrator = None
    if cfg.engine_retries > 0:
        from ..resilience.restart import RestartNarrator

        narrator = RestartNarrator(cfg.logs_path)
    engines = []
    for i in range(cfg.replicas):
        engines.append(DecodeEngine(
            spec, params, page_size=cfg.decode_page_size,
            num_pages=cfg.decode_pages,
            max_batch=cfg.decode_max_batch,
            seed=cfg.seed, kv_quant=cfg.kv_quant,
            recorder=_recorder(f"replica{i}"),
            max_queue=cfg.max_queue, deadline_ms=cfg.deadline_ms,
            engine_retries=cfg.engine_retries, brownout=brownout,
            slos=slos, restart_narrator=narrator))
        engines[-1].start()
    router = Router(engines, fleet_retries=cfg.fleet_retries,
                    breaker=breaker, recorder=_recorder("router"))
    server = RouterServer(router)
    server.install_sigterm()
    port = server.start(cfg.serve_port)
    if port is None:
        for e in engines:
            e.stop()
        for rec in recorders:
            rec.close()
        return 2
    print(f"dtx-serve: fleet of {cfg.replicas} replicas behind "
          f"POST /generate on :{port} "
          f"(fleet_retries={cfg.fleet_retries} "
          f"breaker=failures:{breaker.failures}"
          + (f" engine_retries={cfg.engine_retries}"
             if cfg.engine_retries else "")
          + (f" spans -> {cfg.logs_path}/replica<i>"
             if cfg.trace_spans else "") + ")")
    try:
        import time

        while not router.draining:
            time.sleep(0.5)
        # SIGTERM drained the router (queue typed-shed); let the
        # in-flight decodes retire before tearing the engines down
        while any(e.stats().get("inflight", 0) for e in engines):
            time.sleep(0.1)
        print("dtx-serve: fleet drained, exiting")
    except KeyboardInterrupt:
        router.drain()
    finally:
        server.close()
        for e in engines:
            e.stop()
        for rec in recorders:
            rec.close()
    return 0


def _main_replay(cfg, spec, params, slos, brownout) -> int:
    """``--replay workload.json``: instead of serving HTTP, feed the
    captured WORKLOAD (dtx-obs capture) back through the engine — or
    the ``--replicas N`` router fleet — at the recorded (or
    ``--replay_speed``-scaled) arrival offsets and print the replay
    report (serving/replay.py).  With ``--trace_spans`` every emitted
    row carries ``replay_of: <workload_id>``, so ``dtx-obs tail
    --workload`` isolates this run's waterfalls.  Exit 0 when every
    request reached a typed terminal, 1 when any wedged."""
    import json
    import os

    from ..obs.workload import load_workload
    from . import replay as replay_lib
    from .engine import DecodeEngine

    try:
        doc = load_workload(cfg.replay)
    except (OSError, ValueError, KeyError) as e:
        print(f"dtx-serve: --replay: {e}", file=sys.stderr)
        return 2
    wid = doc["workload_id"]
    recorders = []

    def _recorder(sub=""):
        if not cfg.trace_spans:
            return None
        rec = replay_lib.replay_recorder(
            os.path.join(cfg.logs_path, sub) if sub else cfg.logs_path,
            wid,
            rotate_bytes=int(cfg.span_rotate_mb * 1024 * 1024),
            keep=cfg.span_keep)
        recorders.append(rec)
        return rec

    engines = []
    for i in range(cfg.replicas):
        engines.append(DecodeEngine(
            spec, params, page_size=cfg.decode_page_size,
            num_pages=cfg.decode_pages,
            max_batch=cfg.decode_max_batch,
            seed=cfg.seed, kv_quant=cfg.kv_quant,
            recorder=_recorder(f"replica{i}" if cfg.replicas > 1
                               else ""),
            max_queue=cfg.max_queue, deadline_ms=cfg.deadline_ms,
            engine_retries=cfg.engine_retries, brownout=brownout,
            slos=slos))
        engines[-1].start()
    if cfg.replicas > 1:
        from .health import parse_breaker
        from .router import Router

        target = Router(engines, fleet_retries=cfg.fleet_retries,
                        breaker=parse_breaker(cfg.breaker or "on"),
                        recorder=_recorder("router"))
    else:
        target = engines[0]
    print(f"dtx-serve: replaying {wid} ({doc['n_requests']} requests "
          f"over {doc['duration_s']:g}s recorded) at "
          f"x{cfg.replay_speed:g}"
          + (f" across {cfg.replicas} replicas"
             if cfg.replicas > 1 else ""), file=sys.stderr)
    try:
        report = replay_lib.replay_engine(
            target, doc, vocab_size=cfg.vocab_size,
            speed=cfg.replay_speed, seed=cfg.seed)
    finally:
        # let each engine reach its final tick boundary before stop()
        # so the last retire span lands (the result() that unblocked
        # the replay returns one plan_tick before the retire row)
        deadline = time.monotonic() + 10.0
        for e in engines:
            while time.monotonic() < deadline:
                if not e.sched.live and not e.sched.waiting:
                    time.sleep(0.05)
                    break
                time.sleep(0.02)
            e.stop()
        for rec in recorders:
            rec.close()
    print(json.dumps(report, indent=1, sort_keys=True))
    return 0 if not report["terminals"].get("wedged") else 1


def main(argv: Optional[Sequence[str]] = None) -> int:
    from .. import config as config_lib

    cfg = config_lib.parse_config(argv)
    if cfg.serve_port <= 0 and not cfg.replay:
        print("dtx-serve: --serve_port is required (> 0)",
              file=sys.stderr)
        return 2
    if cfg.model != "transformer" or cfg.objective != "lm":
        print("dtx-serve: decoding needs --model=transformer "
              "--objective=lm", file=sys.stderr)
        return 2
    try:
        config_lib.validate_quant_config(cfg)
        config_lib.validate_serving_config(cfg)
    except ValueError as e:
        print(f"dtx-serve: {e}", file=sys.stderr)
        return 2
    from ..obs import slo as slo_lib
    from .admission import parse_brownout

    try:
        slos = slo_lib.parse_specs(cfg.slo)
        brownout = parse_brownout(cfg.brownout)
    except ValueError as e:
        print(f"dtx-serve: {e}", file=sys.stderr)
        return 2

    import jax

    from ..models import transformer as tfm
    from .engine import DecodeEngine

    spec = _spec_from_cfg(cfg)
    if cfg.checkpoint_dir:
        params, path = _params_from_checkpoint(
            cfg.checkpoint_dir, tfm.param_shapes(spec))
        # stderr so --replay's stdout is exactly the report JSON
        print(f"dtx-serve: params restored from {path}",
              file=sys.stderr)
        params = {k: jax.numpy.asarray(v) for k, v in params.items()}
    else:
        print("dtx-serve: no --checkpoint_dir — serving a seeded "
              "random init (demo mode)", file=sys.stderr)
        params = tfm.init(jax.random.PRNGKey(cfg.seed), spec)

    if cfg.replay:
        return _main_replay(cfg, spec, params, slos, brownout)

    if cfg.replicas > 1:
        return _main_fleet(cfg, spec, params, slos, brownout)

    recorder = None
    if cfg.trace_spans:
        from ..obs.spans import SpanRecorder

        recorder = SpanRecorder(
            cfg.logs_path,
            rotate_bytes=int(cfg.span_rotate_mb * 1024 * 1024),
            keep=cfg.span_keep)
        print(f"dtx-serve: request spans -> {recorder.path}"
              + (f" (rotate at {cfg.span_rotate_mb:g} MB, keep "
                 f"{cfg.span_keep})" if cfg.span_rotate_mb > 0
                 else ""))
    narrator = None
    if cfg.engine_retries > 0:
        # supervised restarts land on the SAME restarts.jsonl
        # timeline the training supervisor writes — dtx-obs report
        # folds serving loop deaths and training preemptions alike
        from ..resilience.restart import RestartNarrator

        narrator = RestartNarrator(cfg.logs_path)
        print(f"dtx-serve: engine supervision armed "
              f"(engine_retries={cfg.engine_retries}; restarts -> "
              f"{narrator.path})")
    engine = DecodeEngine(
        spec, params, page_size=cfg.decode_page_size,
        num_pages=cfg.decode_pages, max_batch=cfg.decode_max_batch,
        seed=cfg.seed, kv_quant=cfg.kv_quant, recorder=recorder,
        max_queue=cfg.max_queue, deadline_ms=cfg.deadline_ms,
        engine_retries=cfg.engine_retries, brownout=brownout,
        slos=slos, restart_narrator=narrator)
    engine.start()

    from ..obs.serve import StatusServer

    server = StatusServer(cfg.logs_path, engine=engine, slos=slos,
                          cache_ttl_s=cfg.status_cache_s)
    port = server.start(cfg.serve_port)
    if port is None:
        engine.stop()
        if recorder is not None:
            recorder.close()
        return 2
    print(f"dtx-serve: POST /generate on :{port} "
          f"(page_size={engine.page_size} pages={engine.num_pages} "
          f"max_batch={engine.sched.max_batch} "
          f"max_len={engine.max_len}"
          + (f" kv_quant={engine.kv_quant}" if engine.kv_quant else "")
          + (f" deadline_ms={engine.deadline_ms:g}"
             if engine.deadline_ms else "")
          + (f" max_queue={engine.max_queue}"
             if engine.max_queue else "")
          + (" brownout=on" if engine.brownout is not None else "")
          + ")")
    try:
        import time

        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        pass
    finally:
        server.close()
        engine.stop()
        if recorder is not None:
            recorder.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
