"""Deterministic fault injection for the serving stack — pure Python.

The failure paths PR 15 adds (deadline expiry, load shedding, engine
supervision) are worthless untested, and real faults don't show up on
demand.  ``FaultPlan`` is the chaos switchboard: a frozen, seedable
description of WHICH faults fire WHEN, injected into the pure
scheduler (``BlockAllocator`` page-allocation failures) and the
``DecodeEngine`` loop (crash / stall / delay at chosen ticks).  The
same plan drives the tick simulation and the real engine, so the
chaos acceptance suite asserts closed-form counters against the
scheduler and then replays the identical plan through compiled
programs.

Clocks (both deterministic):

- **allocation calls** — ``BlockAllocator.alloc`` numbers its calls
  0, 1, 2, ...; ``alloc_fail_calls`` makes those calls return None
  (exactly what pool exhaustion looks like to admission — the
  all-or-nothing contract is preserved, nothing is partially
  granted);
- **tick boundaries** — the scheduler's planned-tick index;
  ``crash_at_ticks`` raises ``InjectedFault`` out of the engine's
  ``step()`` at that boundary (the loop-death path supervision must
  survive), ``stall_at_ticks`` sleeps ``stall_s`` before executing it
  (how a tick outlives a request deadline), ``delay_s`` sleeps before
  EVERY tick (uniform slowdown).

Disabled is the default and is bitwise-invisible: ``FaultPlan()`` (or
``faults=None`` anywhere one is accepted) injects nothing, and the
only added work on the hot path is an attribute check — greedy decode
through the engine is token-identical with the plumbing present
(pinned in tests/test_serving_faults.py).
"""

from __future__ import annotations

import dataclasses
import random
from typing import Optional, Tuple


class InjectedFault(RuntimeError):
    """Raised by an armed FaultPlan at a crash tick — a distinct type
    so tests (and the supervision narration) can tell an injected
    death from an organic one."""


@dataclasses.dataclass(frozen=True)
class FaultPlan:
    """One deterministic chaos schedule.  All fields default to
    "never": a default-constructed plan is disabled
    (``active`` False) and injects nothing."""

    # BlockAllocator.alloc call indices (0-based) that fail
    alloc_fail_calls: Tuple[int, ...] = ()
    # tick boundaries where the engine's step() raises InjectedFault
    crash_at_ticks: Tuple[int, ...] = ()
    # tick boundaries stalled by stall_s before executing
    stall_at_ticks: Tuple[int, ...] = ()
    stall_s: float = 0.0
    # uniform pre-tick delay (every tick), seconds
    delay_s: float = 0.0

    def __post_init__(self):
        if self.stall_s < 0 or self.delay_s < 0:
            raise ValueError("stall_s and delay_s must be >= 0")
        if self.stall_at_ticks and self.stall_s == 0.0:
            raise ValueError("stall_at_ticks without stall_s is a "
                             "no-op; set stall_s > 0")

    @property
    def active(self) -> bool:
        return bool(self.alloc_fail_calls or self.crash_at_ticks
                    or self.stall_at_ticks or self.delay_s)

    # ---- the injection predicates (each clocked as documented) ----
    def fail_alloc(self, call_index: int) -> bool:
        return call_index in self.alloc_fail_calls

    def crash(self, tick: int) -> bool:
        return tick in self.crash_at_ticks

    def stall(self, tick: int) -> float:
        return self.stall_s if tick in self.stall_at_ticks else 0.0

    def describe(self) -> str:
        if not self.active:
            return "disabled"
        parts = []
        if self.alloc_fail_calls:
            parts.append(f"alloc_fail@calls{sorted(self.alloc_fail_calls)}")
        if self.crash_at_ticks:
            parts.append(f"crash@ticks{sorted(self.crash_at_ticks)}")
        if self.stall_at_ticks:
            parts.append(f"stall{self.stall_s}s@ticks"
                         f"{sorted(self.stall_at_ticks)}")
        if self.delay_s:
            parts.append(f"delay{self.delay_s}s/tick")
        return " ".join(parts)

    @classmethod
    def sample(cls, seed: int, horizon: int,
               alloc_fails: int = 0, crashes: int = 0,
               stalls: int = 0, stall_s: float = 0.0,
               delay_s: float = 0.0) -> "FaultPlan":
        """A seeded random plan over ``horizon`` ticks/calls — the
        same (seed, shape) always yields the same plan (random.Random,
        no global state), so a chaos sweep is reproducible from its
        seed alone."""
        if horizon < 1:
            raise ValueError(f"horizon={horizon} must be >= 1")
        rng = random.Random(seed)

        def pick(n: int) -> Tuple[int, ...]:
            n = min(n, horizon)
            return tuple(sorted(rng.sample(range(horizon), n)))

        return cls(alloc_fail_calls=pick(alloc_fails),
                   crash_at_ticks=pick(crashes),
                   stall_at_ticks=pick(stalls),
                   stall_s=float(stall_s), delay_s=float(delay_s))


@dataclasses.dataclass(frozen=True)
class DegradedSimResult:
    """Closed-form accounting for one degraded replay: every
    submitted request lands in exactly one terminal bucket (the
    terminates-typed invariant, counted) — ``completed`` + ``shed`` +
    ``timed_out`` == requests submitted."""

    completed: int
    shed: int
    timed_out: int
    ticks: int
    completed_frac: float
    terminals: dict    # rid -> "result" | "shed" | "timeout"


def simulate_degraded(scheduler, requests, max_queue: int = 0) -> DegradedSimResult:
    """Replay ``requests`` (``(rid, prompt_len, max_new_tokens,
    arrival[, deadline])`` — deadline in ticks, absolute) through a
    scheduler under admission control: arrivals are fed at their tick,
    a full queue (``max_queue`` > 0 waiting slots) sheds on arrival,
    and the scheduler's own deadline machinery retires expirations.
    Pure Python — the deterministic half of ``bench_serving_degraded``
    and the closed-form oracle the chaos tests pin engine counters
    against."""
    pending = sorted(
        ((tuple(r) + (None,) * (5 - len(r))) for r in requests),
        key=lambda r: (r[3] or 0.0, r[0]))
    total = len(pending)
    terminals = {}
    t = 0.0
    guard = 0
    while pending or not scheduler.idle:
        # feed arrivals due by now; shed on a full waiting queue
        while pending and (pending[0][3] or 0.0) <= t:
            rid, p, n, arrival, deadline = pending.pop(0)
            if max_queue and len(scheduler.waiting) >= max_queue:
                terminals[rid] = "shed"
                continue
            scheduler.submit(rid, p, n, arrival=arrival or 0.0,
                             deadline=deadline)
        plan = scheduler.plan_tick(now=t)
        for rid, _reason in scheduler.take_expired():
            terminals[rid] = "timeout"
        t += 1.0
        if plan is None:
            if not pending and scheduler.idle:
                break
            guard += 1
            if guard > 10_000_000:
                raise RuntimeError("degraded simulation did not "
                                   "converge")
            continue
        for rid in plan.prefills:
            scheduler.record_prefill(rid, now=t)
        scheduler.record_decode(
            [r for r in plan.decodes
             if not scheduler._seq(r).done], now=t)
        guard += 1
        if guard > 10_000_000:
            raise RuntimeError("degraded simulation did not converge")
    for rid in scheduler.finished:
        terminals.setdefault(rid, "result")
    completed = sum(1 for v in terminals.values() if v == "result")
    shed = sum(1 for v in terminals.values() if v == "shed")
    timed_out = sum(1 for v in terminals.values() if v == "timeout")
    if completed + shed + timed_out != total:
        raise AssertionError(
            f"terminates-typed invariant violated in simulation: "
            f"{completed}+{shed}+{timed_out} != {total} requests")
    return DegradedSimResult(
        completed=completed, shed=shed, timed_out=timed_out,
        ticks=scheduler.ticks,
        completed_frac=round(completed / max(1, total), 6),
        terminals=terminals)
