"""Admission control and graceful degradation — pure Python.

PR 8's scheduler admits whatever fits; overload just grows the
waiting queue without bound (memory) and stretches every latency SLO
(queueing delay).  This module holds the two policies that turn
overload into *typed*, bounded behavior:

- **load shedding** (``ShedError``): the engine bounds its pending
  queue (``--max_queue``); a submit past the bound raises this typed
  rejection, which ``POST /generate`` maps to ``503`` with a
  ``Retry-After`` hint — the client-visible contract that the server
  is overloaded rather than broken;
- **brownout** (``BrownoutPolicy``): when KV page-pool occupancy or
  the fast-window SLO burn rate crosses its threshold, new admissions
  are degraded instead of refused — their ``max_new_tokens`` is
  clamped (shorter answers, fewer reserved pages) and admission width
  per tick is capped, so the backlog drains.  Hysteresis
  (``occupancy_lo``) keeps the policy from flapping at the threshold.

Both are pure decision tables: the engine feeds them observations and
applies their verdicts, so tier-1 pins the transitions closed-form
without jax.  ``parse_brownout`` is the ``--brownout`` flag DSL.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional


def retry_after_hint(p50_ms: Optional[float]) -> float:
    """The Retry-After hint a shed carries: the p50 request latency in
    SECONDS when one is known (about one queue slot's drain time),
    floored at 1s.  The ONE place the heuristic lives — the engine's
    shed path, the /generate 503 header and the fleet router all
    consume it (drifting copies were how PR 15 and PR 16 ended up
    disagreeing on the hint by a rounding mode)."""
    return round(max(1.0, (p50_ms or 0.0) / 1e3), 3)


def retry_after_header(retry_after_s: float) -> int:
    """The HTTP ``Retry-After`` header value for a hint in seconds:
    integer-seconds CEILING, floored at 1.  Ceil, not round — a 1.4s
    hint rounded down to 1 invites the client back 0.4s before the
    queue slot it is waiting on has drained, which re-sheds the retry
    under steady load."""
    return max(1, int(math.ceil(float(retry_after_s))))


class ShedError(RuntimeError):
    """A request refused by admission control (bounded queue).  The
    HTTP front door maps this to 503 + ``Retry-After: retry_after_s``;
    carrying the hint on the exception keeps obs/serve.py free of
    engine internals."""

    def __init__(self, msg: str, retry_after_s: float = 1.0,
                 rid: Optional[int] = None):
        super().__init__(msg)
        self.retry_after_s = float(retry_after_s)
        self.rid = rid


@dataclasses.dataclass(frozen=True)
class BrownoutPolicy:
    """Graceful-degradation thresholds.  Activation is OR-triggered:
    page-pool occupancy >= ``occupancy_hi`` OR fast-window SLO burn
    rate >= ``burn_hi``; deactivation requires occupancy back under
    ``occupancy_lo`` AND burn under ``burn_hi`` (hysteresis — a
    policy that flaps at the threshold degrades every other
    request)."""

    occupancy_hi: float = 0.90
    occupancy_lo: float = 0.75
    burn_hi: float = 2.0
    clamp_new_tokens: int = 8   # max_new_tokens cap for NEW admissions
    admit_per_tick: int = 1     # admission width cap while active

    def __post_init__(self):
        if not 0.0 < self.occupancy_hi <= 1.0:
            raise ValueError(
                f"occupancy_hi={self.occupancy_hi} must be in (0, 1]")
        if not 0.0 <= self.occupancy_lo <= self.occupancy_hi:
            raise ValueError(
                f"occupancy_lo={self.occupancy_lo} must be in "
                f"[0, occupancy_hi]")
        if self.burn_hi <= 0:
            raise ValueError(f"burn_hi={self.burn_hi} must be > 0")
        if self.clamp_new_tokens < 1:
            raise ValueError(
                f"clamp_new_tokens={self.clamp_new_tokens} must be "
                f">= 1")
        if self.admit_per_tick < 1:
            raise ValueError(
                f"admit_per_tick={self.admit_per_tick} must be >= 1")

    def update(self, active: bool, occupancy: float,
               burn_rate: Optional[float]) -> bool:
        """One hysteresis transition: the next ``active`` state given
        the current observations (``burn_rate`` None = no SLO data
        yet — only occupancy decides)."""
        burning = burn_rate is not None and burn_rate >= self.burn_hi
        if active:
            return occupancy >= self.occupancy_lo or burning
        return occupancy >= self.occupancy_hi or burning


def parse_brownout(text: str) -> Optional[BrownoutPolicy]:
    """Parse the ``--brownout`` DSL: empty = disabled (None); ``on``
    = the documented defaults; otherwise comma-separated ``key=value``
    over occ / occ_lo / burn / clamp / admit (e.g.
    ``occ=0.85,clamp=4,admit=1``).  Raises ValueError on an unknown
    key or a malformed value, naming the offending part."""
    text = (text or "").strip()
    if not text:
        return None
    if text == "on":
        return BrownoutPolicy()
    kw = {}
    names = {"occ": ("occupancy_hi", float),
             "occ_lo": ("occupancy_lo", float),
             "burn": ("burn_hi", float),
             "clamp": ("clamp_new_tokens", int),
             "admit": ("admit_per_tick", int)}
    for part in text.split(","):
        part = part.strip()
        if not part:
            continue
        key, sep, val = part.partition("=")
        key = key.strip()
        if not sep or key not in names:
            raise ValueError(
                f"bad --brownout part {part!r} (want key=value with "
                f"key one of {sorted(names)}, or 'on', or empty)")
        field, typ = names[key]
        try:
            kw[field] = typ(val)
        except ValueError:
            raise ValueError(f"bad --brownout value in {part!r}")
    # occupancy_lo defaults relative to a lowered occ: if only occ was
    # given and it undercuts the default lo, scale lo down with it
    # (constructing first would trip the lo<=hi validation)
    if "occupancy_hi" in kw and "occupancy_lo" not in kw \
            and kw["occupancy_hi"] < BrownoutPolicy.occupancy_lo:
        kw["occupancy_lo"] = round(kw["occupancy_hi"] * 5 / 6, 6)
    return BrownoutPolicy(**kw)
