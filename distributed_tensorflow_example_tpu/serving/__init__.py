"""Serving package: paged KV cache + continuous-batching decode.

Re-exports resolve lazily (PEP 562, the parallel/ package's
convention): importing the package does NOT pull in jax, so the
pure-Python members (``scheduler`` — the continuous-batching tick
planner the tier-1 tests and the bench's analytic half consume) stay
importable on environments whose jax predates the repo's API.
Touching a jax-backed name (``DecodeEngine``, the kv_cache module)
imports its home module with the usual error surface.
"""

_EXPORTS = {
    "BlockAllocator": "scheduler",
    "ContinuousScheduler": "scheduler",
    "StaticBatchScheduler": "scheduler",
    "TickPlan": "scheduler",
    "simulate": "scheduler",
    "shape_buckets": "scheduler",
    "DecodeEngine": "engine",
    "init_paged_cache": "kv_cache",
    "paged_decode_step": "kv_cache",
    "prefill_into_pages": "kv_cache",
    "sample_tokens": "kv_cache",
    # fail-open serving (PR 15) — all pure Python like the scheduler
    "FaultPlan": "faults",
    "InjectedFault": "faults",
    "simulate_degraded": "faults",
    "BrownoutPolicy": "admission",
    "ShedError": "admission",
    "parse_brownout": "admission",
    "retry_after_hint": "admission",
    "retry_after_header": "admission",
    # fleet serving (PR 18) — the routing decision layer stays pure
    # Python like the scheduler; RouterServer is stdlib http.server
    "Router": "router",
    "RouterServer": "router",
    "BreakerPolicy": "health",
    "CircuitBreaker": "health",
    "HealthMonitor": "health",
    "health_score": "health",
    "parse_breaker": "health",
}

__all__ = list(_EXPORTS)


def __getattr__(name):
    if name in _EXPORTS:
        import importlib

        mod = importlib.import_module(f".{_EXPORTS[name]}", __name__)
        return getattr(mod, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
