"""DecodeEngine: continuous-batching inference over the paged cache.

The execution half of the serving stack: the pure-Python scheduler
(serving/scheduler.py) decides membership and shapes, this engine
executes each ``TickPlan`` with compiled programs drawn from a FINITE
shape set (the no-recompile invariant):

- one **prefill** program per bucketed prompt width — the batched
  training forward captured into the request's pages, emitting the
  first generated token;
- one **decode** program per (batch bucket, block-table width) pair —
  the shared ragged decode step with sampling FUSED into the program
  (greedy argmax / temperature categorical selected per sequence on
  device), so per-token logits never round-trip to the host;
- the paged cache buffers are DONATED to each call (off-CPU), so a
  step updates the pool in place instead of copying every page per
  emitted token — the contiguous path's scan-carry aliasing,
  reproduced for the step-at-a-time serving shape.

Phases are annotated with the ``prefill`` / ``decode`` / ``sampling``
trace scopes (obs/buckets.NAMED_SCOPES), so profiler captures
attribute device time to the serving phases the same way training
traces name ``ln``/``moe_*``/``pp_comm``.

Thread model: ``submit()`` may be called from any thread (the
``/generate`` HTTP handlers); ``step()`` — or the ``start()``-ed
background loop — executes ticks under the engine lock.  Completion
is signaled per request via an Event; ``stats()`` exposes the
request-latency percentiles the Prometheus endpoint exports.
"""

from __future__ import annotations

import collections
import math
import sys
import threading
import time
import traceback
from typing import Dict, List, Optional, Tuple

import numpy as np

from . import scheduler as sched_lib
from .scheduler import SCRATCH_PAGE

# rolling window for the latency percentiles stats() reports (the
# Prometheus gauges are point-in-time reads; an all-history scan would
# grow every scrape O(N log N) under the engine lock)
STATS_WINDOW = 2048
# completed requests retained for result() pickup before the oldest
# are evicted — bounds a long-running dtx-serve's memory under
# fire-and-forget clients
RETAIN_FINISHED = 4096


def _percentile(vals: List[float], q: float) -> Optional[float]:
    # np.percentile (linear interpolation) — the SAME definition the
    # gated bench_serving row uses, so the dtx_generate_* gauges and
    # serving_p99_ms agree on identical data
    if not vals:
        return None
    return float(np.percentile(vals, q * 100.0))


class _Result:
    __slots__ = ("event", "prompt", "tokens", "arrival_t", "first_t",
                 "finish_t", "error")

    def __init__(self, prompt, arrival_t: float):
        self.event = threading.Event()
        self.prompt = prompt
        self.tokens: List[int] = []
        self.arrival_t = arrival_t
        self.first_t: Optional[float] = None
        self.finish_t: Optional[float] = None
        self.error: Optional[str] = None


class DecodeEngine:
    """Continuous-batching decode over a paged KV cache.

    ``num_pages=0`` sizes the pool for ``max_batch`` concurrent
    worst-case (``max_len``) sequences plus the scratch page;
    ``max_len`` (prompt + generated) defaults to — and may never
    exceed — ``spec.seq_len`` (the positional table's reach).
    ``donate=None`` resolves by backend (CPU implements no buffer
    donation and warns per call)."""

    def __init__(self, spec, params, page_size: int = 16,
                 num_pages: int = 0, max_batch: int = 8,
                 max_len: int = 0, donate: Optional[bool] = None,
                 seed: int = 0, kv_quant: str = "", recorder=None):
        import jax

        from . import kv_cache as kvc

        if spec.objective != "lm":
            raise ValueError("the decode engine serves the lm "
                             "objective only")
        self.spec = spec
        self.params = params
        self.page_size = int(page_size)
        self.kv_quant = str(kv_quant or "")
        self.max_len = int(max_len) or spec.seq_len
        if self.max_len > spec.seq_len:
            raise ValueError(
                f"max_len={self.max_len} exceeds the positional "
                f"table's seq_len={spec.seq_len}")
        pages_per_seq = max(1, math.ceil((self.max_len - 1)
                                         / self.page_size))
        self.num_pages = int(num_pages) or 1 + max_batch * pages_per_seq
        # ONE span recorder (obs/spans.SpanRecorder or None) threads
        # both layers: the scheduler narrates admission decisions, the
        # engine adds the execution milestones (prefill / first_token /
        # error).  Host-side appends only — greedy outputs are
        # token-identical with tracing on or off.
        self.recorder = recorder
        self.sched = sched_lib.ContinuousScheduler(
            self.num_pages, self.page_size, max_batch,
            recorder=recorder)
        self.prompt_buckets = sched_lib.shape_buckets(
            max(1, self.max_len - 1))
        self._heads = kvc.local_heads(spec, params)
        self.cache = kvc.init_paged_cache(
            spec, self.num_pages, self.page_size, heads=self._heads,
            quant=self.kv_quant)
        self._kvc = kvc
        self._jax = jax
        if donate is None:
            donate = jax.default_backend() != "cpu"
        self._donate = (1,) if donate else ()
        self._decode_fns: Dict[Tuple[int, int], object] = {}
        self._prefill_fns: Dict[int, object] = {}
        self._base_key = jax.random.PRNGKey(seed)
        self._lock = threading.RLock()
        self._results: Dict[int, _Result] = {}
        self._temps: Dict[int, float] = {}
        self._last_tok: Dict[int, int] = {}
        self._finished_order: collections.deque = collections.deque()
        self._lat_ms: collections.deque = collections.deque(
            maxlen=STATS_WINDOW)
        self._ttft_ms: collections.deque = collections.deque(
            maxlen=STATS_WINDOW)
        self._completed = 0
        self._failure: Optional[str] = None
        self._next_rid = 0
        self._tick = 0
        self._prefills = 0
        self._tokens_out = 0
        self._started_t: Optional[float] = None
        self._busy_s = 0.0
        self.shapes_used: set = set()
        self._thread: Optional[threading.Thread] = None
        self._running = False
        self._work = threading.Condition()

    # ---- request surface ----
    def submit(self, prompt, max_new_tokens: int,
               temperature: float = 0.0) -> int:
        """Queue a request (``prompt``: iterable of int token ids);
        returns its rid.  Thread-safe; the background loop (or the
        next ``step()``) picks it up."""
        prompt = [int(t) for t in prompt]
        if not prompt:
            raise ValueError("empty prompt")
        if any(not 0 <= t < self.spec.vocab_size for t in prompt):
            raise ValueError("prompt token outside the vocabulary")
        if len(prompt) + int(max_new_tokens) > self.max_len:
            raise ValueError(
                f"prompt ({len(prompt)}) + max_new_tokens "
                f"({max_new_tokens}) exceeds max_len={self.max_len}")
        now = time.monotonic()
        with self._lock:
            if self._failure is not None:
                raise RuntimeError(
                    f"decode engine failed: {self._failure}")
            rid = self._next_rid
            # the scheduler may reject (page need > pool): allocate the
            # rid only on acceptance so requests_total counts accepted
            # requests, not attempts
            self.sched.submit(rid, len(prompt), int(max_new_tokens),
                              arrival=now)
            self._next_rid += 1
            self._results[rid] = _Result(prompt, now)
            self._temps[rid] = float(temperature)
        with self._work:
            self._work.notify()
        return rid

    def result(self, rid: int, timeout: Optional[float] = None):
        """Block until rid completes; returns
        ``{"rid", "prompt", "tokens", "latency_ms", "ttft_ms"}``,
        ``{"rid", "error"}`` if the engine loop died mid-request, or
        None on timeout.  Results stay retrievable until the engine
        has finished ``RETAIN_FINISHED`` newer requests (KeyError
        after eviction — bounded memory for fire-and-forget
        clients)."""
        res = self._results[rid]
        if not res.event.wait(timeout):
            return None
        if res.error is not None:
            return {"rid": rid, "error": res.error}
        return {
            "rid": rid,
            "prompt": list(res.prompt),
            "tokens": list(res.tokens),
            "latency_ms": round((res.finish_t - res.arrival_t) * 1e3, 3),
            "ttft_ms": round((res.first_t - res.arrival_t) * 1e3, 3),
        }

    # ---- execution ----
    def step(self) -> bool:
        """Execute one scheduler tick (admissions' prefills + the
        shared decode step).  Returns False when there was nothing to
        do."""
        with self._lock:
            t0 = time.monotonic()
            if self._started_t is None:
                self._started_t = t0
            plan = self.sched.plan_tick(now=t0)
            # the engine keeps its own counters; the scheduler's
            # finished map is the simulate() surface and would grow
            # per request forever in a long-running server
            self.sched.finished.clear()
            if plan is None:
                return False
            for rid in plan.prefills:
                self._run_prefill(rid)
            decodes = [r for r in plan.decodes
                       if not self.sched._seq(r).done]
            if decodes:
                self._run_decode(decodes, plan)
            self._busy_s += time.monotonic() - t0
            return True

    def run_until_idle(self) -> int:
        """Drive ticks until every submitted request completed;
        returns the number of executed ticks (the bench's measured
        loop)."""
        n = 0
        while True:
            if not self.step():
                with self._lock:
                    if self.sched.idle:
                        return n
                time.sleep(0.001)
                continue
            n += 1

    def _run_prefill(self, rid: int) -> None:
        jnp = self._jax.numpy
        seq = self.sched._seq(rid)
        res = self._results[rid]
        p = len(res.prompt)
        pb = sched_lib.bucket_for(p, self.prompt_buckets)
        wp = max(1, math.ceil(pb / self.page_size))
        self.shapes_used.add(("prefill", pb, wp))
        if self.recorder is not None:
            self.recorder.emit("prefill", rid=rid, bucket=pb,
                           pages_width=wp)
        bt = np.full((1, wp), SCRATCH_PAGE, np.int32)
        own = seq.pages[:wp]
        bt[0, :len(own)] = own
        toks = np.zeros((1, pb), np.int32)
        toks[0, :p] = res.prompt
        fn = self._prefill_fn(pb, wp)
        # even/odd split keeps prefill and decode key domains disjoint
        key = self._jax.random.fold_in(self._base_key, 2 * rid)
        nxt, self.cache = fn(
            self.params, self.cache, jnp.asarray(bt),
            jnp.asarray(toks), jnp.asarray([p], jnp.int32), key,
            jnp.asarray([self._temps[rid]], jnp.float32))
        tok = int(np.asarray(nxt)[0])
        now = time.monotonic()
        res.tokens.append(tok)
        res.first_t = now
        self._last_tok[rid] = tok
        self._prefills += 1
        self._tokens_out += 1
        if self.recorder is not None:
            self.recorder.emit("first_token", rid=rid, ttft_ms=round(
                (now - res.arrival_t) * 1e3, 3))
        self.sched.record_prefill(rid, now=now)
        if seq.done:
            self._finish(rid, now)

    def _run_decode(self, rids: List[int], plan) -> None:
        jnp = self._jax.numpy
        b, w = plan.batch_bucket, plan.kv_pages
        self.shapes_used.add(("decode", b, w))
        bt = np.full((b, w), SCRATCH_PAGE, np.int32)
        tok = np.zeros((b,), np.int32)
        pos = np.zeros((b,), np.int32)
        temp = np.zeros((b,), np.float32)
        for i, rid in enumerate(rids):
            seq = self.sched._seq(rid)
            own = seq.pages[:w]
            bt[i, :len(own)] = own
            tok[i] = self._last_tok[rid]
            pos[i] = seq.length - 1
            temp[i] = self._temps[rid]
        fn = self._decode_fn(b, w)
        self._tick += 1
        key = self._jax.random.fold_in(self._base_key,
                                       2 * self._tick + 1)
        nxt, self.cache = fn(
            self.params, self.cache, jnp.asarray(bt),
            jnp.asarray(tok), jnp.asarray(pos), key,
            jnp.asarray(temp))
        out = np.asarray(nxt)
        now = time.monotonic()
        for i, rid in enumerate(rids):
            t = int(out[i])
            self._results[rid].tokens.append(t)
            self._last_tok[rid] = t
            self._tokens_out += 1
        self.sched.record_decode(rids, now=now)
        for rid in rids:
            if self.sched._seq(rid).done:
                self._finish(rid, now)

    def _finish(self, rid: int, now: float) -> None:
        res = self._results[rid]
        res.finish_t = now
        self._completed += 1
        self._lat_ms.append((now - res.arrival_t) * 1e3)
        if res.first_t is not None:
            self._ttft_ms.append((res.first_t - res.arrival_t) * 1e3)
        # per-rid decode state is dead once the sequence finished;
        # the result itself stays for pickup under a bounded retention
        self._temps.pop(rid, None)
        self._last_tok.pop(rid, None)
        self._finished_order.append(rid)
        while len(self._finished_order) > RETAIN_FINISHED:
            self._results.pop(self._finished_order.popleft(), None)
        res.event.set()

    # ---- compiled-program caches (one per shape bucket) ----
    def _prefill_fn(self, pb: int, wp: int):
        fn = self._prefill_fns.get(pb)
        if fn is None:
            jax, kvc, spec = self._jax, self._kvc, self.spec

            def prefill(params, cache, bt, toks, lengths, key, temp):
                with jax.named_scope("prefill"):
                    logits, cache = kvc.prefill_into_pages(
                        spec, params, cache, bt, toks, lengths)
                with jax.named_scope("sampling"):
                    nxt = kvc.sample_tokens(logits, key, temp)
                return nxt, cache

            fn = jax.jit(prefill, donate_argnums=self._donate)
            self._prefill_fns[pb] = fn
        return fn

    def _decode_fn(self, b: int, w: int):
        fn = self._decode_fns.get((b, w))
        if fn is None:
            jax, kvc, spec = self._jax, self._kvc, self.spec

            def decode(params, cache, bt, tok, pos, key, temp):
                with jax.named_scope("decode"):
                    logits, cache = kvc.paged_decode_step(
                        spec, params, cache, bt, tok, pos)
                with jax.named_scope("sampling"):
                    nxt = kvc.sample_tokens(logits, key, temp)
                return nxt, cache

            fn = jax.jit(decode, donate_argnums=self._donate)
            self._decode_fns[(b, w)] = fn
        return fn

    # ---- background loop (the HTTP front door's worker) ----
    def start(self) -> None:
        with self._work:
            if self._running:
                return
            self._running = True
        self._thread = threading.Thread(target=self._loop,
                                        name="dtx-decode-engine",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        with self._work:
            self._running = False
            self._work.notify()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None

    def _loop(self) -> None:
        while True:
            with self._work:
                if not self._running:
                    return
            try:
                did = self.step()
            except Exception as e:   # noqa: BLE001 — the one thread
                # every request depends on must not die silently
                self._fail(e)
                return
            if not did:
                with self._work:
                    if self._running:
                        self._work.wait(timeout=0.02)

    def _fail(self, e: BaseException) -> None:
        """A tick raised: record the failure, refuse new submits, and
        fail every pending request NOW — blocked ``result()`` /
        ``/generate`` callers get an error immediately instead of
        hanging until their timeout against a dead worker."""
        msg = f"{type(e).__name__}: {e}"
        sys.stderr.write(f"dtx-serve: decode engine loop died: {msg}\n"
                         f"{traceback.format_exc()}")
        with self._lock:
            self._failure = msg
            for rid, res in self._results.items():
                if res.finish_t is None and res.error is None:
                    res.error = msg
                    if self.recorder is not None:
                        # no retire will follow: mark the lifecycle
                        # failed so reconstruction doesn't read these
                        # as silently dropped requests
                        self.recorder.emit("error", rid=rid, reason=msg)
                    res.event.set()
        with self._work:
            self._running = False

    # ---- observability ----
    def stats(self) -> dict:
        """Point-in-time serving counters + request-latency
        percentiles (the obs/schema.SERVING_STATS contract; the
        Prometheus ``dtx_generate_*`` gauges read these).  Percentiles
        cover the last ``STATS_WINDOW`` completions — a rolling
        window, so scrape cost stays O(window) under the engine lock
        however long the server has been up."""
        with self._lock:
            lats = list(self._lat_ms)
            ttfts = list(self._ttft_ms)
            wall = (time.monotonic() - self._started_t
                    if self._started_t is not None else 0.0)
            toks = self._tokens_out
            occ = self.sched.alloc.in_use / self.sched.alloc.usable
            return {
                "requests_total": self._next_rid,
                "completed_total": self._completed,
                "inflight": len(self.sched.live),
                "queued": len(self.sched.waiting),
                "latency_p50_ms": _percentile(lats, 0.50),
                "latency_p99_ms": _percentile(lats, 0.99),
                "ttft_p50_ms": _percentile(ttfts, 0.50),
                "ttft_p99_ms": _percentile(ttfts, 0.99),
                "tokens_generated_total": toks,
                "tokens_per_sec": (toks / wall if wall > 0 and toks
                                   else None),
                "page_occupancy_frac": round(occ, 6),
                "decode_ticks_total": self._tick,
                "prefills_total": self._prefills,
            }
