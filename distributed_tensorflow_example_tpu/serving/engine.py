"""DecodeEngine: continuous-batching inference over the paged cache.

The execution half of the serving stack: the pure-Python scheduler
(serving/scheduler.py) decides membership and shapes, this engine
executes each ``TickPlan`` with compiled programs drawn from a FINITE
shape set (the no-recompile invariant):

- one **prefill** program per bucketed prompt width — the batched
  training forward captured into the request's pages, emitting the
  first generated token;
- one **decode** program per (batch bucket, block-table width) pair —
  the shared ragged decode step with sampling FUSED into the program
  (greedy argmax / temperature categorical selected per sequence on
  device), so per-token logits never round-trip to the host;
- the paged cache buffers are DONATED to each call (off-CPU), so a
  step updates the pool in place instead of copying every page per
  emitted token — the contiguous path's scan-carry aliasing,
  reproduced for the step-at-a-time serving shape.

Phases are annotated with the ``prefill`` / ``decode`` / ``sampling``
trace scopes (obs/buckets.NAMED_SCOPES), so profiler captures
attribute device time to the serving phases the same way training
traces name ``ln``/``moe_*``/``pp_comm``.

Thread model: ``submit()`` may be called from any thread (the
``/generate`` HTTP handlers); ``step()`` — or the ``start()``-ed
background loop — executes ticks under the engine lock.  Completion
is signaled per request via an Event; ``stats()`` exposes the
request-latency percentiles the Prometheus endpoint exports.
"""

from __future__ import annotations

import collections
import math
import sys
import threading
import time
import traceback
from typing import Dict, List, Optional, Tuple

import numpy as np

from . import scheduler as sched_lib
from .admission import BrownoutPolicy, ShedError, retry_after_hint
from .faults import InjectedFault
from .scheduler import SCRATCH_PAGE

# rolling window for the latency percentiles stats() reports (the
# Prometheus gauges are point-in-time reads; an all-history scan would
# grow every scrape O(N log N) under the engine lock)
STATS_WINDOW = 2048
# brownout burn-rate recompute cadence, in tick boundaries: the SLO
# fold over the span ring is O(ring), too heavy for every tick
BURN_EVERY = 32
# supervised-restart backoff (resilience/restart.backoff_s shape):
# base doubles per consecutive crash up to the cap, resets on the
# first healthy tick
RESTART_BACKOFF_BASE_S = 0.05
RESTART_BACKOFF_MAX_S = 2.0
# completed requests retained for result() pickup before the oldest
# are evicted — bounds a long-running dtx-serve's memory under
# fire-and-forget clients
RETAIN_FINISHED = 4096


def _percentile(vals: List[float], q: float) -> Optional[float]:
    # np.percentile (linear interpolation) — the SAME definition the
    # gated bench_serving row uses, so the dtx_generate_* gauges and
    # serving_p99_ms agree on identical data
    if not vals:
        return None
    return float(np.percentile(vals, q * 100.0))


class _Result:
    __slots__ = ("event", "prompt", "tokens", "arrival_t", "first_t",
                 "finish_t", "error", "status", "attempts")

    def __init__(self, prompt, arrival_t: float):
        self.event = threading.Event()
        self.prompt = prompt
        self.tokens: List[int] = []
        self.arrival_t = arrival_t
        self.first_t: Optional[float] = None
        self.finish_t: Optional[float] = None
        self.error: Optional[str] = None
        # retry-budget accounting on a typed "failed" terminal — the
        # fleet router carries it onto the next replica
        self.attempts: Optional[int] = None
        # terminal type once the event is set: "result" | "timeout" |
        # "failed" (shed requests never get a _Result — they are
        # refused at submit with a typed ShedError)
        self.status: Optional[str] = None


class DecodeEngine:
    """Continuous-batching decode over a paged KV cache.

    ``num_pages=0`` sizes the pool for ``max_batch`` concurrent
    worst-case (``max_len``) sequences plus the scratch page;
    ``max_len`` (prompt + generated) defaults to — and may never
    exceed — ``spec.seq_len`` (the positional table's reach).
    ``donate=None`` resolves by backend (CPU implements no buffer
    donation and warns per call).

    Fail-open knobs (all off by default — the default path is
    bitwise-identical to the unsupervised engine):

    - ``max_queue`` bounds the pending queue; a submit past the bound
      raises a typed ``ShedError`` (503 + Retry-After at the HTTP
      door) instead of growing memory without limit;
    - ``deadline_ms`` is the default per-request deadline (0 = none;
      a request's own ``deadline_ms`` overrides) — expiry retires it
      at the next tick boundary with a typed ``timeout`` terminal and
      frees its pages;
    - ``brownout`` (admission.BrownoutPolicy) clamps new admissions'
      token budgets and admission width while page occupancy or the
      fast-window SLO burn rate is over threshold;
    - ``engine_retries`` > 0 arms SUPERVISION: a crashed engine loop
      restarts with bounded backoff, in-flight requests are re-queued
      (pages freed, prefill re-run) at most ``engine_retries`` times
      each before a typed ``failed`` terminal — instead of today's
      fail-closed "every pending request errors, submits refuse";
    - ``faults`` (faults.FaultPlan) is the deterministic chaos
      switchboard the above are tested against;
    - ``slos``/``restart_narrator``: the brownout burn-rate specs
      (obs/slo.SLOSpec list; None = defaults) and an optional
      resilience RestartNarrator that lands every supervised restart
      on the restarts.jsonl timeline."""

    def __init__(self, spec, params, page_size: int = 16,
                 num_pages: int = 0, max_batch: int = 8,
                 max_len: int = 0, donate: Optional[bool] = None,
                 seed: int = 0, kv_quant: str = "", recorder=None,
                 max_queue: int = 0, deadline_ms: float = 0.0,
                 engine_retries: int = 0,
                 brownout: Optional[BrownoutPolicy] = None,
                 faults=None, slos=None, restart_narrator=None):
        import jax

        from . import kv_cache as kvc

        if spec.objective != "lm":
            raise ValueError("the decode engine serves the lm "
                             "objective only")
        self.spec = spec
        self.params = params
        self.page_size = int(page_size)
        self.kv_quant = str(kv_quant or "")
        self.max_len = int(max_len) or spec.seq_len
        if self.max_len > spec.seq_len:
            raise ValueError(
                f"max_len={self.max_len} exceeds the positional "
                f"table's seq_len={spec.seq_len}")
        pages_per_seq = max(1, math.ceil((self.max_len - 1)
                                         / self.page_size))
        self.num_pages = int(num_pages) or 1 + max_batch * pages_per_seq
        # ONE span recorder (obs/spans.SpanRecorder or None) threads
        # both layers: the scheduler narrates admission decisions, the
        # engine adds the execution milestones (prefill / first_token /
        # error).  Host-side appends only — greedy outputs are
        # token-identical with tracing on or off.
        self.recorder = recorder
        self.faults = faults
        self.max_queue = int(max_queue)
        self.deadline_ms = float(deadline_ms)
        self.engine_retries = int(engine_retries)
        if self.max_queue < 0 or self.deadline_ms < 0 \
                or self.engine_retries < 0:
            raise ValueError("max_queue, deadline_ms and "
                             "engine_retries must be >= 0")
        self.brownout = brownout
        self.slos = slos
        self.restart_narrator = restart_narrator
        self.max_batch = int(max_batch)
        self.sched = sched_lib.ContinuousScheduler(
            self.num_pages, self.page_size, max_batch,
            recorder=recorder, faults=faults)
        self.prompt_buckets = sched_lib.shape_buckets(
            max(1, self.max_len - 1))
        self._heads = kvc.local_heads(spec, params)
        self.cache = kvc.init_paged_cache(
            spec, self.num_pages, self.page_size, heads=self._heads,
            quant=self.kv_quant)
        self._kvc = kvc
        self._jax = jax
        if donate is None:
            donate = jax.default_backend() != "cpu"
        self._donate = (1,) if donate else ()
        self._decode_fns: Dict[Tuple[int, int], object] = {}
        self._prefill_fns: Dict[int, object] = {}
        self._base_key = jax.random.PRNGKey(seed)
        self._lock = threading.RLock()
        self._results: Dict[int, _Result] = {}
        self._temps: Dict[int, float] = {}
        self._last_tok: Dict[int, int] = {}
        self._finished_order: collections.deque = collections.deque()
        self._lat_ms: collections.deque = collections.deque(
            maxlen=STATS_WINDOW)
        self._ttft_ms: collections.deque = collections.deque(
            maxlen=STATS_WINDOW)
        self._completed = 0
        self._failure: Optional[str] = None
        # rid -> (trace_id, parent_id): the W3C trace context every
        # accepted request carries (trimmed with _results retention)
        self._traces: Dict[int, tuple] = {}
        # rid -> the attempts count seeded by submit(attempts=): the
        # local retry budget bounds crashes THIS engine absorbs, so
        # the budget check offsets by the carried-in base while spans
        # keep the cumulative fleet-wide count
        self._attempt_base: Dict[int, int] = {}
        self._next_rid = 0
        self._accepted = 0
        self._tick = 0
        self._prefills = 0
        self._tokens_out = 0
        # fail-open accounting (stats()/dtx_generate_* surface)
        self._shed = 0
        self._timeouts = 0
        self._failed = 0
        self._requeued = 0
        self._restarts = 0
        self._queue_peak = 0
        self._brownout_active = False
        self._brownout_clamped = 0
        self._consec_crashes = 0
        # monotonic tick-boundary counter — the FaultPlan clock for
        # crash/stall/delay.  Deliberately NOT the scheduler's tick
        # counter: a supervised restart rebuilds the scheduler (ticks
        # reset to 0), and a crash plan must not re-fire at the same
        # indices forever
        self._boundaries = 0
        self._burn_cache: Tuple[int, Optional[float]] = (-BURN_EVERY,
                                                         None)
        self._started_t: Optional[float] = None
        self._busy_s = 0.0
        self.shapes_used: set = set()
        self._thread: Optional[threading.Thread] = None
        self._running = False
        self._work = threading.Condition()

    # ---- request surface ----
    def submit(self, prompt, max_new_tokens: int,
               temperature: float = 0.0,
               deadline_ms: Optional[float] = None,
               traceparent: Optional[str] = None,
               attempts: int = 0,
               fingerprint: Optional[list] = None) -> int:
        """Queue a request (``prompt``: iterable of int token ids);
        returns its rid.  Thread-safe; the background loop (or the
        next ``step()``) picks it up.  ``deadline_ms`` bounds the
        request's total time in the system (None = the engine's
        ``deadline_ms`` default; 0 = explicitly none); past it, the
        scheduler retires the request with a typed ``timeout``
        terminal and frees its pages.  Raises ``ShedError`` when the
        bounded pending queue (``max_queue``) is full — the typed
        503-with-Retry-After rejection.

        ``traceparent`` is an optional W3C trace-context header value
        from the caller: its trace_id/parent_id ride every span this
        request emits (a malformed header degrades to a fresh trace,
        never to a rejection).  Without one, the engine mints a fresh
        trace_id — every request is traceable either way; look it up
        with ``trace_context(rid)``.

        ``attempts`` seeds the supervision retry ledger (0 = a fresh
        request): a fleet router failing a request over from another
        engine passes the count the old engine burned, so the PR 15
        ``attempts`` accounting stays cumulative ACROSS engines —
        ``engine_retries`` then bounds the *additional* crashes this
        engine will absorb before the typed ``failed`` terminal."""
        from ..obs import spans as spans_lib

        ctx = spans_lib.parse_traceparent(traceparent)
        if ctx is not None:
            trace_id, parent_id = ctx
        else:
            trace_id, parent_id = spans_lib.new_trace_id(), None
        prompt = [int(t) for t in prompt]
        if not prompt:
            raise ValueError("empty prompt")
        if any(not 0 <= t < self.spec.vocab_size for t in prompt):
            raise ValueError("prompt token outside the vocabulary")
        if len(prompt) + int(max_new_tokens) > self.max_len:
            raise ValueError(
                f"prompt ({len(prompt)}) + max_new_tokens "
                f"({max_new_tokens}) exceeds max_len={self.max_len}")
        now = time.monotonic()
        with self._lock:
            if self._failure is not None:
                raise RuntimeError(
                    f"decode engine failed: {self._failure}")
            if self.max_queue and len(self.sched.waiting) >= self.max_queue:
                # typed load shedding: the queue bound is the memory
                # bound.  The shed rid is consumed (span-stream rids
                # stay unique) but requests_total counts ACCEPTED only
                rid = self._next_rid
                self._next_rid += 1
                self._shed += 1
                retry_s = self._retry_after_s()
                if self.recorder is not None:
                    extra = {"trace_id": trace_id}
                    if parent_id is not None:
                        extra["parent_id"] = parent_id
                    self.recorder.emit(
                        "shed", rid=rid, reason="queue",
                        tick=self.sched.ticks,
                        queued=len(self.sched.waiting), **extra)
                raise ShedError(
                    f"queue full ({len(self.sched.waiting)} waiting, "
                    f"max_queue={self.max_queue})",
                    retry_after_s=retry_s, rid=rid)
            dl_ms = self.deadline_ms if deadline_ms is None \
                else float(deadline_ms)
            deadline = now + dl_ms / 1e3 if dl_ms > 0 else None
            rid = self._next_rid
            # the scheduler may reject (page need > pool): allocate the
            # rid only on acceptance so requests_total counts accepted
            # requests, not attempts
            # the prompt-block fingerprint (v10) rides the submit span
            # so workload capture preserves shared-prefix structure
            # without storing content; a replay passes the RECORDED
            # fingerprint through verbatim (its stand-in tokens would
            # hash differently), keeping capture→replay→capture
            # idempotent
            if fingerprint is None and self.recorder is not None:
                from ..obs.workload import prompt_fingerprint

                fingerprint = prompt_fingerprint(prompt)
            self.sched.submit(rid, len(prompt), int(max_new_tokens),
                              arrival=now, deadline=deadline,
                              trace_id=trace_id, parent_id=parent_id,
                              fingerprint=fingerprint)
            if attempts:
                # a failed-over request arrives mid-ledger: the seq
                # carries the cumulative count (requeue/failed spans
                # stay fleet-truthful), the base offsets the local
                # budget check in _recover
                self.sched.waiting[-1].attempts = int(attempts)
                self._attempt_base[rid] = int(attempts)
            self._next_rid += 1
            self._accepted += 1
            self._queue_peak = max(self._queue_peak,
                                   len(self.sched.waiting))
            self._results[rid] = _Result(prompt, now)
            self._temps[rid] = float(temperature)
            self._traces[rid] = (trace_id, parent_id)
        with self._work:
            self._work.notify()
        return rid

    def trace_context(self, rid: int) -> Optional[tuple]:
        """``(trace_id, parent_id)`` for an accepted rid (None for an
        unknown/shed one) — the serving edge reads this to stamp the
        response traceparent."""
        with self._lock:
            return self._traces.get(int(rid))

    def _retry_after_s(self) -> float:
        """The Retry-After hint on a shed: admission.retry_after_hint
        over the rolling p50 (the ONE home of the heuristic — the
        /generate 503 header and the fleet router consume the same
        number)."""
        return retry_after_hint(_percentile(list(self._lat_ms), 0.50))

    def waiting_rids(self) -> List[int]:
        """Rids still WAITING for admission (no pages held, no tokens
        earned) — the fleet router's drain path typed-cancels exactly
        these; in-flight requests finish."""
        with self._lock:
            return [s.rid for s in self.sched.waiting]

    def fast_burn(self) -> Optional[float]:
        """The cached fast-window SLO burn rate (None without a
        recorder or before the first fold) — the router's health
        probe reads this from any thread."""
        with self._lock:
            return self._fast_burn()

    def cancel(self, rid: int) -> bool:
        """Client-side cancellation: mark ``rid`` for retirement at
        the next tick boundary (pages freed through the same path a
        deadline expiry uses; the result terminal is ``timeout`` with
        reason "cancel").  Returns False when the rid is unknown or
        already terminal."""
        with self._lock:
            res = self._results.get(rid)
            if res is None or res.event.is_set():
                return False
            ok = self.sched.cancel(rid)
        with self._work:
            self._work.notify()
        return ok

    def result(self, rid: int, timeout: Optional[float] = None):
        """Block until rid completes; returns
        ``{"rid", "status": "result", "prompt", "tokens",
        "latency_ms", "ttft_ms"}`` on success, ``{"rid", "status",
        "error"}`` for a typed non-result terminal (``status`` is
        "timeout" — deadline expiry or cancellation — or "failed" —
        the engine loop died with the retry budget spent), or None
        when ``timeout`` elapsed first (the request is still in
        flight).  Results stay retrievable until the engine has
        finished ``RETAIN_FINISHED`` newer requests (KeyError after
        eviction — bounded memory for fire-and-forget clients)."""
        res = self._results[rid]
        if not res.event.wait(timeout):
            return None
        trace = self._traces.get(rid)
        extra = {"trace_id": trace[0]} if trace else {}
        if res.error is not None:
            out = {"rid": rid, "status": res.status or "failed",
                   "error": res.error, **extra}
            if res.attempts is not None:
                # the spent retry ledger rides the typed failed
                # terminal — a fleet router seeds the next engine's
                # submit(attempts=) with it
                out["attempts"] = res.attempts
            return out
        return {
            "rid": rid,
            "status": "result",
            "prompt": list(res.prompt),
            "tokens": list(res.tokens),
            "latency_ms": round((res.finish_t - res.arrival_t) * 1e3, 3),
            "ttft_ms": round((res.first_t - res.arrival_t) * 1e3, 3),
            **extra,
        }

    # ---- execution ----
    def step(self) -> bool:
        """Execute one scheduler tick (admissions' prefills + the
        shared decode step).  Returns False when there was nothing to
        do.  The fail-open order of business at each boundary:
        brownout verdict -> plan (which expires deadlines/cancels
        first) -> finalize the expirations' results -> injected
        crash/stall (FaultPlan) -> execute."""
        with self._lock:
            t0 = time.monotonic()
            if self._started_t is None:
                self._started_t = t0
            self._update_brownout()
            plan = self.sched.plan_tick(now=t0)
            self._finalize_expired(self.sched.take_expired(), t0)
            # the engine keeps its own counters; the scheduler's
            # finished map is the simulate() surface and would grow
            # per request forever in a long-running server
            self.sched.finished.clear()
            if plan is None:
                return False
            boundary = self._boundaries
            self._boundaries += 1
            if self.faults is not None:
                if self.faults.crash(boundary):
                    raise InjectedFault(
                        f"injected crash at tick boundary {boundary}")
                stall = (self.faults.stall(boundary)
                         + self.faults.delay_s)
                if stall > 0:
                    # a wedged/slow tick: deadlines keep running while
                    # the engine holds its lock (submits block too —
                    # exactly what a stalled worker looks like)
                    time.sleep(stall)
            exec_t0 = time.monotonic()
            for rid in plan.prefills:
                self._run_prefill(rid)
            decodes = [r for r in plan.decodes
                       if not self.sched._seq(r).done]
            if decodes:
                self._run_decode(decodes, plan)
            if self.recorder is not None:
                # close the tick the scheduler's tick row opened:
                # dur_ms is EXECUTION wall only (prefill + decode),
                # so (tick_done.t - tick.t) - dur_ms isolates the
                # boundary's stall — injected sleeps land between the
                # tick row and exec_t0 and show up as stall, which is
                # exactly the decode_stall segment the per-request
                # waterfall (obs/waterfall.py) attributes
                self.recorder.emit(
                    "tick_done", tick=self.sched.ticks - 1,
                    dur_ms=round((time.monotonic() - exec_t0) * 1e3, 3))
            self._consec_crashes = 0
            self._busy_s += time.monotonic() - t0
            return True

    def _update_brownout(self) -> None:
        """One hysteresis transition of the brownout policy, applied
        as this boundary's scheduler verdict (admission.BrownoutPolicy
        decides; the scheduler clamps)."""
        if self.brownout is None:
            return
        occ = self.sched.alloc.in_use / self.sched.alloc.usable
        self._brownout_active = self.brownout.update(
            self._brownout_active, occ, self._fast_burn())
        self.sched.brownout = (
            (self.brownout.clamp_new_tokens,
             self.brownout.admit_per_tick)
            if self._brownout_active else None)
        self._brownout_clamped = self.sched.brownout_clamped

    def _fast_burn(self) -> Optional[float]:
        """Max fast-window SLO burn rate over the recorder ring (None
        without a recorder), recomputed every ``BURN_EVERY``
        boundaries — the SLO fold is O(ring) and must not run per
        tick."""
        if self.recorder is None:
            return None
        at, val = self._burn_cache
        if self._boundaries - at < BURN_EVERY:
            return val
        from ..obs import slo as slo_lib

        doc = slo_lib.evaluate(
            slo_lib.records_from_spans(self.recorder.snapshot()),
            specs=self.slos)
        burns = [(d.get("windows") or {}).get("fast", {}).get("burn_rate")
                 for d in doc.get("slos") or []]
        burns = [b for b in burns if isinstance(b, (int, float))]
        val = max(burns) if burns else None
        self._burn_cache = (self._boundaries, val)
        return val

    def _finalize_expired(self, pairs, now: float) -> None:
        """Set the typed ``timeout`` terminal on every result the
        scheduler just expired (deadline or cancel)."""
        for rid, reason in pairs:
            res = self._results.get(rid)
            self._timeouts += 1
            if res is None or res.event.is_set():
                continue
            res.status = "timeout"
            res.error = ("cancelled by client" if reason == "cancel"
                         else "deadline exceeded")
            res.finish_t = now
            self._seal(rid, res)

    def run_until_idle(self) -> int:
        """Drive ticks until every submitted request completed;
        returns the number of executed ticks (the bench's measured
        loop).  Supervision applies here exactly as in the background
        loop: a crashed tick recovers (requeue/restart) when
        ``engine_retries`` > 0, else propagates."""
        n = 0
        while True:
            try:
                did = self.step()
            except Exception as e:  # noqa: BLE001 — supervised driver
                if self.engine_retries > 0 and self._recover(e):
                    continue
                raise
            if not did:
                with self._lock:
                    if self.sched.idle:
                        return n
                time.sleep(0.001)
                continue
            n += 1

    def _run_prefill(self, rid: int) -> None:
        jnp = self._jax.numpy
        seq = self.sched._seq(rid)
        res = self._results[rid]
        p = len(res.prompt)
        pb = sched_lib.bucket_for(p, self.prompt_buckets)
        wp = max(1, math.ceil(pb / self.page_size))
        self.shapes_used.add(("prefill", pb, wp))
        if self.recorder is not None:
            self.recorder.emit("prefill", rid=rid, bucket=pb,
                           pages_width=wp)
        bt = np.full((1, wp), SCRATCH_PAGE, np.int32)
        own = seq.pages[:wp]
        bt[0, :len(own)] = own
        toks = np.zeros((1, pb), np.int32)
        toks[0, :p] = res.prompt
        fn = self._prefill_fn(pb, wp)
        # even/odd split keeps prefill and decode key domains disjoint
        key = self._jax.random.fold_in(self._base_key, 2 * rid)
        nxt, self.cache = fn(
            self.params, self.cache, jnp.asarray(bt),
            jnp.asarray(toks), jnp.asarray([p], jnp.int32), key,
            jnp.asarray([self._temps[rid]], jnp.float32))
        tok = int(np.asarray(nxt)[0])
        now = time.monotonic()
        res.tokens.append(tok)
        res.first_t = now
        self._last_tok[rid] = tok
        self._prefills += 1
        self._tokens_out += 1
        if self.recorder is not None:
            self.recorder.emit("first_token", rid=rid, ttft_ms=round(
                (now - res.arrival_t) * 1e3, 3))
        self.sched.record_prefill(rid, now=now)
        if seq.done:
            self._finish(rid, now)

    def _run_decode(self, rids: List[int], plan) -> None:
        jnp = self._jax.numpy
        b, w = plan.batch_bucket, plan.kv_pages
        self.shapes_used.add(("decode", b, w))
        bt = np.full((b, w), SCRATCH_PAGE, np.int32)
        tok = np.zeros((b,), np.int32)
        pos = np.zeros((b,), np.int32)
        temp = np.zeros((b,), np.float32)
        for i, rid in enumerate(rids):
            seq = self.sched._seq(rid)
            own = seq.pages[:w]
            bt[i, :len(own)] = own
            tok[i] = self._last_tok[rid]
            pos[i] = seq.length - 1
            temp[i] = self._temps[rid]
        fn = self._decode_fn(b, w)
        self._tick += 1
        key = self._jax.random.fold_in(self._base_key,
                                       2 * self._tick + 1)
        nxt, self.cache = fn(
            self.params, self.cache, jnp.asarray(bt),
            jnp.asarray(tok), jnp.asarray(pos), key,
            jnp.asarray(temp))
        out = np.asarray(nxt)
        now = time.monotonic()
        for i, rid in enumerate(rids):
            t = int(out[i])
            self._results[rid].tokens.append(t)
            self._last_tok[rid] = t
            self._tokens_out += 1
        self.sched.record_decode(rids, now=now)
        for rid in rids:
            if self.sched._seq(rid).done:
                self._finish(rid, now)

    def _finish(self, rid: int, now: float) -> None:
        res = self._results[rid]
        res.finish_t = now
        res.status = "result"
        self._completed += 1
        self._lat_ms.append((now - res.arrival_t) * 1e3)
        if res.first_t is not None:
            self._ttft_ms.append((res.first_t - res.arrival_t) * 1e3)
        self._seal(rid, res)

    def _seal(self, rid: int, res: "_Result") -> None:
        """The one terminal-sealing path (caller holds the lock):
        per-rid decode state dies, the result stays for pickup under
        the bounded retention, and the waiter wakes — every terminal
        (result/timeout/failed) funnels through here so the retention
        discipline cannot drift between them."""
        self._temps.pop(rid, None)
        self._last_tok.pop(rid, None)
        self._finished_order.append(rid)
        while len(self._finished_order) > RETAIN_FINISHED:
            evicted = self._finished_order.popleft()
            self._results.pop(evicted, None)
            self._traces.pop(evicted, None)
            self._attempt_base.pop(evicted, None)
        res.event.set()

    # ---- compiled-program caches (one per shape bucket) ----
    def _prefill_fn(self, pb: int, wp: int):
        fn = self._prefill_fns.get(pb)
        if fn is None:
            jax, kvc, spec = self._jax, self._kvc, self.spec

            def prefill(params, cache, bt, toks, lengths, key, temp):
                with jax.named_scope("prefill"):
                    logits, cache = kvc.prefill_into_pages(
                        spec, params, cache, bt, toks, lengths)
                with jax.named_scope("sampling"):
                    nxt = kvc.sample_tokens(logits, key, temp)
                return nxt, cache

            fn = jax.jit(prefill, donate_argnums=self._donate)
            self._prefill_fns[pb] = fn
        return fn

    def _decode_fn(self, b: int, w: int):
        fn = self._decode_fns.get((b, w))
        if fn is None:
            jax, kvc, spec = self._jax, self._kvc, self.spec

            def decode(params, cache, bt, tok, pos, key, temp):
                with jax.named_scope("decode"):
                    logits, cache = kvc.paged_decode_step(
                        spec, params, cache, bt, tok, pos)
                with jax.named_scope("sampling"):
                    nxt = kvc.sample_tokens(logits, key, temp)
                return nxt, cache

            fn = jax.jit(decode, donate_argnums=self._donate)
            self._decode_fns[(b, w)] = fn
        return fn

    # ---- background loop (the HTTP front door's worker) ----
    def start(self) -> None:
        with self._work:
            if self._running:
                return
            self._running = True
        self._thread = threading.Thread(target=self._loop,
                                        name="dtx-decode-engine",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        with self._work:
            self._running = False
            self._work.notify()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None

    def _loop(self) -> None:
        while True:
            with self._work:
                if not self._running:
                    return
            try:
                did = self.step()
            except Exception as e:   # noqa: BLE001 — the one thread
                # every request depends on must not die silently
                if self.engine_retries > 0 and self._recover(e):
                    continue          # supervised: loop resumes
                self._fail(e)
                return
            if not did:
                with self._work:
                    if self._running:
                        self._work.wait(timeout=0.02)

    # ---- supervision (engine_retries > 0) ----
    def _recover(self, e: BaseException) -> bool:
        """A tick crashed under supervision: restart the engine
        in place instead of failing closed.  Correctness over
        cleverness — every admitted-but-unfinished request is torn
        down to its prompt (pages freed with the dead scheduler,
        generated tokens discarded, prefill re-run on re-admission)
        and re-queued unless its ``engine_retries`` budget is spent,
        in which case it gets the typed ``failed`` terminal.  The KV
        cache is re-initialized (a crash mid-dispatch can leave
        donated buffers in limbo); compiled programs are kept — they
        are pure.  Every restart lands on the span stream
        (``engine_restart``/``requeue``/``failed``) and, when a
        narrator is attached, on the restarts.jsonl timeline.
        Returns True (the loop resumes after a bounded backoff)."""
        from ..resilience.restart import backoff_s

        msg = f"{type(e).__name__}: {e}"
        now = time.monotonic()
        with self._lock:
            self._restarts += 1
            self._consec_crashes += 1
            old = self.sched
            inflight = list(old.live)
            waiting = list(old.waiting)
            if self.recorder is not None:
                self.recorder.emit(
                    "engine_restart", restart=self._restarts,
                    reason=msg, rids=[s.rid for s in inflight],
                    tick=old.ticks)
            if self.restart_narrator is not None:
                self.restart_narrator.emit(
                    "engine_restart", restart=self._restarts,
                    reason=msg, inflight=len(inflight),
                    queued=len(waiting))
            sys.stderr.write(
                f"dtx-serve: engine loop crashed ({msg}); supervised "
                f"restart {self._restarts} with {len(inflight)} "
                f"in-flight re-queued\n")
            # rebuild the execution state: fresh scheduler/allocator
            # (the dead one may hold a half-planned boundary) and a
            # fresh cache (donation can leave the old buffers invalid)
            self.sched = sched_lib.ContinuousScheduler(
                self.num_pages, self.page_size, self.max_batch,
                recorder=self.recorder, faults=self.faults)
            # the FaultPlan's alloc-call clock survives the restart —
            # a deterministic plan must not re-fire
            self.sched.alloc.alloc_calls = old.alloc.alloc_calls
            self.sched.alloc.injected_fails = old.alloc.injected_fails
            self.sched.brownout_clamped = old.brownout_clamped
            # the span stream's tick index stays MONOTONIC across the
            # restart: the SLO windows and reconstruct slide over it,
            # and a reset would strand every post-restart terminal
            # outside windows anchored at the pre-crash maximum
            self.sched.ticks = old.ticks
            # pending cancellations and already-expired-but-undrained
            # rids survive the rebuild: a client that cancelled just
            # before the crash must still get its typed timeout, not
            # a silent re-decode (the new scheduler's first boundary
            # expires the carried markers)
            self.sched._cancelled = set(old._cancelled)
            self._finalize_expired(old.take_expired(), now)
            self.cache = self._kvc.init_paged_cache(
                self.spec, self.num_pages, self.page_size,
                heads=self._heads, quant=self.kv_quant)
            # in-flight requests burned one attempt; waiters did not
            # (the crash consumed none of their work)
            survivors = []
            for s in inflight:
                s.pages = []          # freed with the dead allocator
                s.attempts += 1
                res = self._results.get(s.rid)
                if res is None or res.event.is_set():
                    continue
                if s.attempts > self.engine_retries \
                        + self._attempt_base.get(s.rid, 0):
                    self._finalize_failed(
                        s.rid, f"engine crashed {s.attempts} times "
                               f"on this request "
                               f"(engine_retries={self.engine_retries}"
                               f"): {msg}",
                        attempts=s.attempts, now=now)
                    continue
                res.tokens.clear()
                res.first_t = None
                self._last_tok.pop(s.rid, None)
                self._requeued += 1
                if self.recorder is not None:
                    # the requeue keeps the request's trace_id — the
                    # chain across a supervised restart stays unbroken
                    extra = ({"trace_id": s.trace_id}
                             if s.trace_id else {})
                    self.recorder.emit("requeue", rid=s.rid,
                                       attempt=s.attempts,
                                       tick=self.sched.ticks, **extra)
                survivors.append(s)
            # FIFO by arrival across survivors + untouched waiters
            # (waiters hold no pages and no generated tokens already)
            for s in sorted(survivors + waiting,
                            key=lambda st: (st.arrival, st.rid)):
                self.sched.requeue(s)
            # markers for rids that did NOT survive (failed terminal)
            # would never match a waiting/live seq again — prune them
            self.sched._cancelled &= {s.rid for s in self.sched.waiting}
            wait_s = backoff_s(self._consec_crashes - 1,
                               base_s=RESTART_BACKOFF_BASE_S,
                               cap_s=RESTART_BACKOFF_MAX_S)
        if wait_s > 0:
            time.sleep(wait_s)
        with self._work:
            self._work.notify()
        return True

    def _finalize_failed(self, rid: int, msg: str, attempts: int,
                         now: float) -> None:
        """The typed ``failed`` terminal: retry budget spent (caller
        holds the engine lock)."""
        res = self._results.get(rid)
        if res is None or res.event.is_set():
            return
        self._failed += 1
        res.status = "failed"
        res.error = msg
        res.attempts = int(attempts)
        res.finish_t = now
        if self.recorder is not None:
            trace = self._traces.get(rid)
            extra = {"trace_id": trace[0]} if trace else {}
            self.recorder.emit("failed", rid=rid, reason=msg,
                               attempts=int(attempts), **extra)
        self._seal(rid, res)

    def _fail(self, e: BaseException) -> None:
        """A tick raised: record the failure, refuse new submits, and
        fail every pending request NOW — blocked ``result()`` /
        ``/generate`` callers get an error immediately instead of
        hanging until their timeout against a dead worker."""
        msg = f"{type(e).__name__}: {e}"
        sys.stderr.write(f"dtx-serve: decode engine loop died: {msg}\n"
                         f"{traceback.format_exc()}")
        with self._lock:
            self._failure = msg
            for rid, res in self._results.items():
                if res.finish_t is None and res.error is None:
                    res.error = msg
                    res.status = "failed"
                    self._failed += 1
                    if self.recorder is not None:
                        # no retire will follow: mark the lifecycle
                        # failed so reconstruction doesn't read these
                        # as silently dropped requests
                        trace = self._traces.get(rid)
                        extra = {"trace_id": trace[0]} if trace else {}
                        self.recorder.emit("error", rid=rid,
                                           reason=msg, **extra)
                    res.event.set()
        with self._work:
            self._running = False

    # ---- observability ----
    def stats(self) -> dict:
        """Point-in-time serving counters + request-latency
        percentiles (the obs/schema.SERVING_STATS contract; the
        Prometheus ``dtx_generate_*`` gauges read these).  Percentiles
        cover the last ``STATS_WINDOW`` completions — a rolling
        window, so scrape cost stays O(window) under the engine lock
        however long the server has been up."""
        with self._lock:
            lats = list(self._lat_ms)
            ttfts = list(self._ttft_ms)
            wall = (time.monotonic() - self._started_t
                    if self._started_t is not None else 0.0)
            toks = self._tokens_out
            occ = self.sched.alloc.in_use / self.sched.alloc.usable
            return {
                "requests_total": self._accepted,
                "completed_total": self._completed,
                "inflight": len(self.sched.live),
                "queued": len(self.sched.waiting),
                "latency_p50_ms": _percentile(lats, 0.50),
                "latency_p99_ms": _percentile(lats, 0.99),
                "ttft_p50_ms": _percentile(ttfts, 0.50),
                "ttft_p99_ms": _percentile(ttfts, 0.99),
                "tokens_generated_total": toks,
                "tokens_per_sec": (toks / wall if wall > 0 and toks
                                   else None),
                "page_occupancy_frac": round(occ, 6),
                "decode_ticks_total": self._tick,
                "prefills_total": self._prefills,
                # fail-open accounting (PR 15): typed terminals +
                # admission-control and supervision counters
                "shed_total": self._shed,
                "timeout_total": self._timeouts,
                "failed_total": self._failed,
                "requeued_total": self._requeued,
                "engine_restarts_total": self._restarts,
                "queue_limit": self.max_queue,
                "queue_peak": self._queue_peak,
                "brownout_active": int(self._brownout_active),
                "brownout_clamped_total": self._brownout_clamped,
            }
