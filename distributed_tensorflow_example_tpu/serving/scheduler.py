"""Continuous-batching scheduler — pure Python, no jax import.

Like parallel/pp_schedule.py, the control plane is derived entirely
off-device: the scheduler decides, tick by tick, WHICH ragged requests
occupy the shared decode batch and which pages they own; the engine
(serving/engine.py) merely executes the resulting ``TickPlan`` with
one compiled program per shape bucket.  Keeping it jax-free makes
iteration-level scheduling (Orca) and block allocation (vLLM)
unit-testable in tier-1 on any environment, and lets bench.py count
decode ticks analytically — the deterministic half of the serving
bench's evidence.

Semantics:

- **admission** (FIFO, arrival-gated): a waiting request joins the
  live batch when a slot inside the largest batch bucket AND its full
  conservative page reservation (``ceil((prompt+max_new-1)/page)``)
  are both available — no mid-flight OOM, no preemption needed;
- **retirement**: a sequence that produced its last token frees its
  pages at the NEXT tick boundary, BEFORE that tick's admissions —
  finished sequences release capacity immediately and the freed
  pages/slot are reusable in the same tick;
- **bucketed shapes** (the no-recompile invariant): the decode batch
  is padded to the smallest ``batch_bucket`` >= live count, and the
  block-table width to the smallest power-of-two page count covering
  the longest live sequence — every (batch, width) pair the engine
  can see comes from a finite, precomputed set, so membership churn
  never recompiles or repads live state.

``simulate`` replays a request set through a scheduler counting
decode ticks (prefill cost is identical across policies for the same
set), which is how the bench proves continuous batching strictly
beats static batching on ragged lengths: a static batch decodes
``max(len)`` ticks per group while continuous backfills retired slots
the very tick they free.

**Span emission**: when constructed with a ``recorder`` (anything
with ``.emit(event, **fields)`` — obs/spans.SpanRecorder in the real
engine), the scheduler narrates every admission decision into the
request-lifecycle span stream: ``submit`` on accept, ``blocked`` with
its reason (``pages``/``slots``) once per tick a waiter stays out,
``admit`` with the pages granted, one ``tick`` row per planned step
(members, bucket shape, pool occupancy) and ``retire`` when the pages
free.  The recorder is *injected* so this module stays jax- and
obs-free; ``recorder=None`` (the default) emits nothing.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, List, Optional, Tuple

SCRATCH_PAGE = 0


def shape_buckets(max_value: int, floor: int = 1) -> Tuple[int, ...]:
    """Power-of-two bucket ladder ``(floor, 2*floor, ...)`` capped at
    (and always containing) ``max_value`` — the finite shape set both
    the batch and the block-table width draw from."""
    if max_value < 1:
        raise ValueError(f"max_value={max_value} must be >= 1")
    out: List[int] = []
    b = max(1, floor)
    while b < max_value:
        out.append(b)
        b *= 2
    out.append(max_value)
    return tuple(out)


def bucket_for(n: int, buckets: Tuple[int, ...]) -> int:
    """Smallest bucket >= n (buckets sorted ascending)."""
    for b in buckets:
        if b >= n:
            return b
    raise ValueError(f"{n} exceeds the largest bucket {buckets[-1]}")


class BlockAllocator:
    """Free-list page allocator over a pool of ``num_pages``. Page 0
    is reserved as the SCRATCH page (dead batch slots write there), so
    ``usable`` = num_pages - 1.  LIFO reuse keeps the hot pages hot.

    ``faults``: an optional serving/faults.FaultPlan — allocation
    calls are numbered 0, 1, 2, ... and a call the plan names fails
    (returns None, indistinguishable from pool exhaustion to the
    caller).  None (the default) injects nothing and costs one
    attribute check."""

    def __init__(self, num_pages: int, page_size: int, faults=None):
        if num_pages < 2:
            raise ValueError(f"num_pages={num_pages} must be >= 2 "
                             f"(page 0 is the reserved scratch page)")
        if page_size < 1:
            raise ValueError(f"page_size={page_size} must be >= 1")
        self.num_pages = num_pages
        self.page_size = page_size
        self.faults = faults
        self.alloc_calls = 0
        self.injected_fails = 0
        self._free: List[int] = list(range(num_pages - 1, SCRATCH_PAGE,
                                           -1))

    @property
    def usable(self) -> int:
        return self.num_pages - 1

    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def in_use(self) -> int:
        return self.usable - self.free_count

    def alloc(self, n: int) -> Optional[List[int]]:
        """``n`` pages or None (all-or-nothing: a partial grant would
        deadlock admission)."""
        call = self.alloc_calls
        self.alloc_calls += 1
        if self.faults is not None and self.faults.fail_alloc(call):
            self.injected_fails += 1
            return None
        if n > len(self._free):
            return None
        got = [self._free.pop() for _ in range(n)]
        return got

    def free(self, pages: List[int]) -> None:
        seen = set(self._free)
        for p in pages:
            if not (SCRATCH_PAGE < p < self.num_pages):
                raise ValueError(f"freed page {p} outside the pool")
            if p in seen:
                raise ValueError(f"double free of page {p}")
            seen.add(p)
        self._free.extend(reversed(pages))


@dataclasses.dataclass
class SeqState:
    """One request's scheduler-side state. Lengths only — the token
    arrays live in the engine."""

    rid: int
    prompt_len: int
    max_new_tokens: int
    arrival: float = 0.0
    pages: List[int] = dataclasses.field(default_factory=list)
    generated: int = 0
    finish_t: Optional[float] = None
    # absolute deadline on the scheduler's ``now`` clock (tick count
    # in simulation, wall clock live); None = no deadline
    deadline: Optional[float] = None
    # engine-supervision retry count (how many crashes this request
    # already survived via requeue)
    attempts: int = 0
    # W3C trace context (PR 16): the 32-hex trace id this request
    # carries on every span it emits, stable across requeue (a
    # supervised restart keeps the chain unbroken); parent_id is the
    # caller's 16-hex span id when a traceparent arrived at the edge
    trace_id: Optional[str] = None
    parent_id: Optional[str] = None

    @property
    def length(self) -> int:
        """Tokens known so far (prompt + generated)."""
        return self.prompt_len + self.generated

    @property
    def done(self) -> bool:
        return self.generated >= self.max_new_tokens


@dataclasses.dataclass(frozen=True)
class TickPlan:
    """What the engine executes this tick: ``prefills`` are the rids
    admitted at this boundary (one batched-forward prefill each),
    ``decodes`` the rids taking a decode step, padded to
    ``batch_bucket`` slots with the block table ``kv_pages`` pages
    wide.  Either list may be empty (a pure-prefill or pure-decode
    tick)."""

    prefills: Tuple[int, ...]
    decodes: Tuple[int, ...]
    batch_bucket: int
    kv_pages: int


class ContinuousScheduler:
    """Iteration-level (Orca-style) scheduler: every tick boundary
    retires, then admits, then plans one shared decode step over the
    live ragged batch."""

    def __init__(self, num_pages: int, page_size: int, max_batch: int,
                 recorder=None, faults=None):
        if max_batch < 1:
            raise ValueError(f"max_batch={max_batch} must be >= 1")
        self.alloc = BlockAllocator(num_pages, page_size, faults=faults)
        self.page_size = page_size
        self.max_batch = max_batch
        self.batch_buckets = shape_buckets(max_batch)
        # widest table a sequence can need: every usable page
        self.kv_page_buckets = shape_buckets(self.alloc.usable)
        self.waiting: List[SeqState] = []
        self.live: List[SeqState] = []
        self.finished: Dict[int, SeqState] = {}
        self.ticks = 0
        self.decode_slots = 0       # slot-ticks executed (live work)
        self.occupancy_samples: List[float] = []
        # request-lifecycle span emission (obs/spans.SpanRecorder, or
        # anything with .emit(event, **fields)) — INJECTED so the
        # scheduler module itself stays jax- and obs-free; None = off
        self.recorder = recorder
        # deadline/cancel machinery: rids marked for cancellation are
        # retired at the next tick boundary exactly like an expired
        # deadline (same page-freeing path, reason "cancel"); the
        # boundary's typed expirations accumulate in _expired until
        # the engine drains them via take_expired()
        self._cancelled: set = set()
        self._expired: List[Tuple[int, str]] = []
        self.timeouts = 0
        # brownout verdict for THIS boundary, set by the engine before
        # plan_tick: (clamp_new_tokens, admit_per_tick) or None.  The
        # scheduler only applies it — the policy (thresholds,
        # hysteresis) lives in serving/admission.py
        self.brownout: Optional[Tuple[int, int]] = None
        self.brownout_clamped = 0

    def _emit(self, event: str, **fields) -> None:
        if self.recorder is not None:
            self.recorder.emit(event, **fields)

    # ---- request surface ----
    def submit(self, rid: int, prompt_len: int, max_new_tokens: int,
               arrival: float = 0.0,
               deadline: Optional[float] = None,
               trace_id: Optional[str] = None,
               parent_id: Optional[str] = None,
               fingerprint: Optional[List[str]] = None) -> None:
        if prompt_len < 1 or max_new_tokens < 1:
            raise ValueError("prompt_len and max_new_tokens must be "
                             ">= 1")
        need = self._pages_for(prompt_len, max_new_tokens)
        if need > self.alloc.usable:
            raise ValueError(
                f"request {rid} needs {need} pages; the pool only has "
                f"{self.alloc.usable} usable")
        self.waiting.append(SeqState(rid, prompt_len, max_new_tokens,
                                     arrival=arrival,
                                     deadline=deadline,
                                     trace_id=trace_id,
                                     parent_id=parent_id))
        # emitted on ACCEPT only (validation above raises first), so
        # the span stream's submit events mirror requests_total
        extra = ({"deadline": float(deadline)}
                 if deadline is not None else {})
        if trace_id is not None:
            extra["trace_id"] = str(trace_id)
        if parent_id is not None:
            extra["parent_id"] = str(parent_id)
        if fingerprint:
            # prompt-block hashes (v10): workload capture reads these
            # off the submit span — the scheduler stays content-free
            extra["fingerprint"] = [str(f) for f in fingerprint]
        self._emit("submit", rid=rid, prompt_len=int(prompt_len),
                   max_new_tokens=int(max_new_tokens),
                   arrival=float(arrival), **extra)

    def requeue(self, s: SeqState) -> None:
        """Put a previously-admitted request back on the waiting
        queue with its work discarded (pages must already be freed by
        the caller's teardown; generated tokens are re-earned by a
        fresh prefill).  Engine supervision's re-admission path — no
        ``submit`` span is emitted (the rid already has one; the
        engine narrates the ``requeue`` event itself)."""
        if s.pages:
            raise ValueError(f"requeue of rid {s.rid} still holding "
                             f"pages {s.pages}")
        s.generated = 0
        s.finish_t = None
        self.waiting.append(s)

    def cancel(self, rid: int) -> bool:
        """Mark ``rid`` for cancellation: the next tick boundary
        retires it through the deadline path (pages freed, typed
        ``timeout`` terminal with reason "cancel").  Returns False for
        a rid that is not waiting or live (already terminal)."""
        known = any(s.rid == rid for s in self.waiting) \
            or any(s.rid == rid for s in self.live)
        if known:
            self._cancelled.add(rid)
        return known

    def take_expired(self) -> List[Tuple[int, str]]:
        """Drain the (rid, reason) pairs retired by deadline expiry or
        cancellation since the last call — the engine finalizes their
        results from this list right after each ``plan_tick``."""
        out, self._expired = self._expired, []
        return out

    def _expire(self, now: float, tick: int) -> None:
        """Retire every waiting/live request whose deadline has passed
        or that was cancelled — pages freed BEFORE retirement and
        admission look at the pool, one typed ``timeout`` span each."""
        for s in list(self.waiting):
            reason = self._expiry_reason(s, now)
            if reason is None:
                continue
            self.waiting.remove(s)
            self._retire_expired(s, reason, tick, waited=True)
        for s in list(self.live):
            if s.done:
                # finished last boundary, awaiting retirement: its
                # tokens were delivered IN time — the deadline race
                # resolves in favor of completed work
                continue
            reason = self._expiry_reason(s, now)
            if reason is None:
                continue
            self.live.remove(s)
            self.alloc.free(s.pages)
            s.pages = []
            self._retire_expired(s, reason, tick, waited=False)

    def _expiry_reason(self, s: SeqState, now: float) -> Optional[str]:
        if s.rid in self._cancelled:
            return "cancel"
        if s.deadline is not None and now > s.deadline:
            return "deadline"
        return None

    def _retire_expired(self, s: SeqState, reason: str, tick: int,
                        waited: bool) -> None:
        self._cancelled.discard(s.rid)
        self._expired.append((s.rid, reason))
        self.timeouts += 1
        self._emit("timeout", rid=s.rid, reason=reason, tick=tick,
                   generated=int(s.generated), queued=bool(waited))

    def _pages_for(self, prompt_len: int, max_new: int) -> int:
        # rows written run 0 .. prompt+max_new-2: the final token is
        # emitted by writing row total-2, so it never needs its own row
        return max(1, math.ceil((prompt_len + max_new - 1)
                                / self.page_size))

    # ---- tick boundary ----
    def plan_tick(self, now: float = float("inf")) -> Optional[TickPlan]:
        """Retire finished sequences (freeing their pages), admit
        arrived waiters while slots and pages last, and return the
        tick's plan — None when nothing is live or admissible (the
        engine idles).  ``now``: admission considers requests with
        ``arrival <= now`` only (tick-count clock in simulation, wall
        clock live)."""
        # 0-based boundary index every span event at this boundary
        # shares (the step-index the SLO windows slide over)
        tick = self.ticks
        # 0) expire: deadlines/cancellations free their pages first —
        # a request past its deadline must not hold capacity that
        # could admit a request that can still make its own
        self._expire(now, tick)
        # 1) retire: pages return BEFORE admission looks at the pool
        for s in [s for s in self.live if s.done]:
            self.live.remove(s)
            self.alloc.free(s.pages)
            s.pages = []
            self.finished[s.rid] = s
            # a cancel that lost the race to completion must not
            # leak its marker for the scheduler's lifetime
            self._cancelled.discard(s.rid)
            self._emit("retire", rid=s.rid, generated=s.generated,
                       finish_t=float(s.finish_t or 0.0), tick=tick)
        # 2) admit FIFO among the arrived (under the boundary's
        # brownout verdict, when the engine set one: admission width
        # capped, new admissions' token budgets clamped)
        clamp = admit_cap = None
        if self.brownout is not None:
            clamp, admit_cap = self.brownout
        prefills: List[int] = []
        for s in list(self.waiting):
            if s.arrival > now:
                continue                  # not arrived ≠ blocked
            if admit_cap is not None and len(prefills) >= admit_cap:
                # brownout admission-width cap: the queue drains at a
                # bounded rate until the pressure signal clears
                self._emit("blocked", rid=s.rid, reason="brownout",
                           tick=tick)
                break
            if len(self.live) >= self.max_batch:
                self._emit("blocked", rid=s.rid, reason="slots",
                           tick=tick)
                continue
            # degrade, don't refuse: a clamped answer reserves fewer
            # pages and frees its slot sooner.  The budget mutation,
            # counter and admit tag land ONLY on a successful
            # admission — a clamped-then-blocked request must keep
            # its submitted budget (or its retire would contradict
            # the submit span with no clamped tag to exempt it)
            eff_new = s.max_new_tokens
            if clamp is not None and eff_new > clamp:
                eff_new = clamp
            pages = self.alloc.alloc(
                self._pages_for(s.prompt_len, eff_new))
            if pages is None:
                # head-of-line blocks on pages: smaller requests behind
                # it must not starve it forever — stop admitting
                self._emit("blocked", rid=s.rid, reason="pages",
                           tick=tick)
                break
            clamped = eff_new < s.max_new_tokens
            if clamped:
                s.max_new_tokens = eff_new
                self.brownout_clamped += 1
            s.pages = pages
            self.waiting.remove(s)
            self.live.append(s)
            prefills.append(s.rid)
            extra = {"clamped": True} if clamped else {}
            self._emit("admit", rid=s.rid, pages_held=len(pages),
                       tick=tick, **extra)
        if not self.live:
            return None
        decodes = [s.rid for s in self.live if not s.done]
        # block-table width covers only the rows this tick can touch
        # (decode at pos = projected_length - 1): LIVE blocks, not the
        # full reservation — the paged gather's whole point.  A
        # max_new_tokens=1 prefill finishes WITHOUT a same-tick decode
        # (the engine filters done rids), so it projects no extra row —
        # the +1 would otherwise overflow the reservation (and the
        # width ladder) when the prompt fills its last page
        prefset = set(prefills)
        rows = max(s.length
                   + (1 if s.rid in prefset and s.max_new_tokens > 1
                      else 0)
                   for s in self.live)
        width = max(1, math.ceil(rows / self.page_size))
        plan = TickPlan(
            prefills=tuple(prefills),
            decodes=tuple(decodes),
            batch_bucket=bucket_for(len(decodes) or 1,
                                    self.batch_buckets),
            kv_pages=bucket_for(width, self.kv_page_buckets),
        )
        self.ticks += 1
        self.decode_slots += len(decodes)
        occ = self.alloc.in_use / self.alloc.usable
        self.occupancy_samples.append(occ)
        self._emit("tick", tick=tick, rids=list(decodes),
                   batch=len(decodes), batch_bucket=plan.batch_bucket,
                   kv_pages=plan.kv_pages, occupancy=round(occ, 6))
        return plan

    def record_prefill(self, rid: int, now: float = 0.0) -> None:
        """A prefill produced the request's FIRST generated token."""
        self._seq(rid).generated += 1
        self._maybe_finish(rid, now)

    def record_decode(self, rids, now: float = 0.0) -> None:
        """One decode tick produced one token for each rid."""
        for rid in rids:
            self._seq(rid).generated += 1
            self._maybe_finish(rid, now)

    def _maybe_finish(self, rid: int, now: float) -> None:
        s = self._seq(rid)
        if s.done and s.finish_t is None:
            s.finish_t = now

    def _seq(self, rid: int) -> SeqState:
        for s in self.live:
            if s.rid == rid:
                return s
        raise KeyError(f"rid {rid} is not live")

    @property
    def idle(self) -> bool:
        return not self.live and not self.waiting

    def occupancy(self) -> float:
        """Mean cache-page occupancy over the ticks planned so far."""
        if not self.occupancy_samples:
            return 0.0
        return sum(self.occupancy_samples) / len(self.occupancy_samples)


class StaticBatchScheduler(ContinuousScheduler):
    """The baseline policy: admit in groups of up to ``max_batch`` and
    hold the group until EVERY member finishes (classic offline
    batching — what ``generate_dp`` does today).  Same allocator, same
    plan surface, so ``simulate`` compares the two policies on the
    identical request set."""

    def plan_tick(self, now: float = float("inf")) -> Optional[TickPlan]:
        tick = self.ticks
        # deadlines/cancellations expire identically under both
        # policies (the same typed-terminal contract)
        self._expire(now, tick)
        # retire pages as sequences finish (memory is freed either
        # way; the STATIC restriction is about slots, not pages)
        for s in [s for s in self.live if s.done and s.pages]:
            self.alloc.free(s.pages)
            s.pages = []
        if self.live and all(s.done for s in self.live):
            for s in self.live:
                self.finished[s.rid] = s
                self._cancelled.discard(s.rid)
                self._emit("retire", rid=s.rid, generated=s.generated,
                           finish_t=float(s.finish_t or 0.0),
                           tick=tick)
            self.live = []
        prefills: List[int] = []
        if not self.live:
            # next group: fill up to max_batch from the arrived queue
            for s in list(self.waiting):
                if s.arrival > now:
                    continue
                if len(self.live) >= self.max_batch:
                    self._emit("blocked", rid=s.rid, reason="slots",
                               tick=tick)
                    continue
                pages = self.alloc.alloc(
                    self._pages_for(s.prompt_len, s.max_new_tokens))
                if pages is None:
                    self._emit("blocked", rid=s.rid, reason="pages",
                               tick=tick)
                    break
                s.pages = pages
                self.waiting.remove(s)
                self.live.append(s)
                prefills.append(s.rid)
                self._emit("admit", rid=s.rid,
                           pages_held=len(pages), tick=tick)
        if not self.live:
            return None
        decodes = [s.rid for s in self.live if not s.done]
        if not decodes and not prefills:
            return None
        prefset = set(prefills)
        rows = max(s.length
                   + (1 if s.rid in prefset and s.max_new_tokens > 1
                      else 0)
                   for s in self.live if not s.done)
        width = max(1, math.ceil(rows / self.page_size))
        plan = TickPlan(
            prefills=tuple(prefills), decodes=tuple(decodes),
            # static batching pads every tick to the FULL group bucket:
            # finished members keep their slot until the group retires
            batch_bucket=bucket_for(max(len(self.live), 1),
                                    self.batch_buckets),
            kv_pages=bucket_for(max(width, 1), self.kv_page_buckets),
        )
        self.ticks += 1
        self.decode_slots += len(decodes)
        occ = self.alloc.in_use / self.alloc.usable
        self.occupancy_samples.append(occ)
        self._emit("tick", tick=tick, rids=list(decodes),
                   batch=len(decodes), batch_bucket=plan.batch_bucket,
                   kv_pages=plan.kv_pages, occupancy=round(occ, 6))
        return plan


@dataclasses.dataclass(frozen=True)
class SimResult:
    """Deterministic tick-count accounting for one policy over one
    request set (latencies in TICKS — the analytic, gateable number;
    the engine measures wall-clock on top)."""

    decode_ticks: int
    total_ticks: int
    finish_ticks: Dict[int, float]
    latency_ticks: Dict[int, float]
    occupancy: float
    shapes: Tuple[Tuple[int, int], ...]   # (batch_bucket, kv_pages) seen


def simulate(scheduler: ContinuousScheduler,
             requests) -> SimResult:
    """Drive ``scheduler`` over ``requests`` (iterable of
    ``(rid, prompt_len, max_new_tokens[, arrival])``) counting ticks:
    each planned tick costs 1 (its prefills + the shared decode step),
    matching the engine's execution shape.  Pure Python — the bench's
    continuous-vs-static comparison and the tier-1 scheduler tests
    run this without jax."""
    for req in requests:
        scheduler.submit(*req)
    t = 0.0
    shapes = set()
    guard = 0
    while not scheduler.idle:
        plan = scheduler.plan_tick(now=t)
        t += 1.0
        if plan is None:
            continue
        shapes.add((plan.batch_bucket, plan.kv_pages))
        for rid in plan.prefills:
            scheduler.record_prefill(rid, now=t)
        scheduler.record_decode(
            [r for r in plan.decodes
             if not scheduler._seq(r).done], now=t)
        guard += 1
        if guard > 10_000_000:
            raise RuntimeError("simulation did not converge")
    finish = {rid: s.finish_t for rid, s in scheduler.finished.items()}
    latency = {rid: s.finish_t - s.arrival
               for rid, s in scheduler.finished.items()}
    return SimResult(
        decode_ticks=scheduler.ticks, total_ticks=int(t),
        finish_ticks=finish, latency_ticks=latency,
        occupancy=scheduler.occupancy(), shapes=tuple(sorted(shapes)))
