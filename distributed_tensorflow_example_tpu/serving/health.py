"""Per-replica health scoring and circuit breakers — pure Python.

PR 15 made ONE engine fail open (typed terminals, bounded queue,
supervised restarts) and PR 16 made a fleet observable (trace
propagation, federated SLO).  The router (serving/router.py) needs a
*decision* layer on top of those signals: "is this replica a good
place for the next request, and when do we stop asking a sick one?"
This module holds both policies as closed-form decision tables — no
jax, no threads, no wall-clock reads except through an injectable
clock — so tier-1 pins every transition exactly:

- **health score** (``health_score``): one scalar in [0, 1] per
  replica, derived from the signals the stack already exports —
  queue depth against its bound (``SERVING_STATS``), the typed
  failure fraction since the last probe (shed/timeout/failed/
  engine_restart counter deltas), the SLO fast-window burn rate
  (obs/slo.py) and heartbeat-style staleness of the stats snapshot
  itself.  ``HealthMonitor`` tracks the counter deltas between
  probes;
- **circuit breaker** (``CircuitBreaker``): closed → open on
  ``failures`` consecutive typed failures OR health collapse under
  ``health_floor``; open → half-open after a seeded-jitter
  exponential backoff; half-open admits exactly ONE probe request —
  success closes the breaker, failure re-opens it with the next
  backoff step.  The jitter is drawn from ``random.Random(seed)`` so
  the whole backoff sequence is deterministic and test-pinned.

``parse_breaker`` is the ``--breaker`` flag DSL (the parse_brownout
pattern).
"""

from __future__ import annotations

import dataclasses
import random
import time
from typing import Any, Dict, Optional

# health-score weights: the penalty budget each signal can spend.
# They sum to 1.0 so a replica maxing every signal scores exactly 0.
W_QUEUE = 0.25      # pending queue depth / its bound
W_BURN = 0.25       # SLO fast-window burn rate / BURN_SCALE
W_FAILURE = 0.30    # typed-failure fraction of terminals since probe
W_STALE = 0.20      # stats-snapshot staleness / STALE_SCALE_S
BURN_SCALE = 2.0    # burn rate at which the burn penalty saturates
STALE_SCALE_S = 10.0  # staleness at which the stale penalty saturates


def _unit(x: float) -> float:
    return min(1.0, max(0.0, float(x)))


def health_score(queued: int = 0, queue_limit: int = 0,
                 failure_delta: int = 0, ok_delta: int = 0,
                 burn_rate: Optional[float] = None,
                 staleness_s: float = 0.0) -> float:
    """One replica's health in [0, 1] — 1.0 = idle and clean, 0.0 =
    every signal saturated.  Closed form (docs/serving.md documents
    the formula):

        score = 1 - W_QUEUE   * min(1, queued / queue_limit)
                  - W_BURN    * min(1, burn_rate / BURN_SCALE)
                  - W_FAILURE * failure_delta / max(1, failure_delta
                                                       + ok_delta)
                  - W_STALE   * min(1, staleness_s / STALE_SCALE_S)

    ``queue_limit`` 0 (unbounded) contributes no queue penalty — an
    unbounded queue has no fullness fraction; ``burn_rate`` None (no
    SLO data yet) contributes no burn penalty.  ``failure_delta`` /
    ``ok_delta`` are counter DELTAS since the last probe: sheds,
    timeouts, faileds and engine restarts vs completions."""
    score = 1.0
    if queue_limit > 0:
        score -= W_QUEUE * _unit(queued / queue_limit)
    if burn_rate is not None:
        score -= W_BURN * _unit(burn_rate / BURN_SCALE)
    total = failure_delta + ok_delta
    if failure_delta > 0:
        score -= W_FAILURE * _unit(failure_delta / max(1, total))
    if staleness_s > 0:
        score -= W_STALE * _unit(staleness_s / STALE_SCALE_S)
    return round(_unit(score), 6)


class HealthMonitor:
    """Turns a stream of ``DecodeEngine.stats()`` snapshots into
    health scores by tracking the typed-failure counter deltas
    between probes (the counters are lifetime totals; health is about
    what happened RECENTLY)."""

    _FAIL_KEYS = ("shed_total", "timeout_total", "failed_total",
                  "engine_restarts_total")

    def __init__(self, clock=time.monotonic):
        self._clock = clock
        self._prev: Dict[str, int] = {}
        self._prev_t: Optional[float] = None
        self.score = 1.0

    def update(self, stats: Dict[str, Any],
               burn_rate: Optional[float] = None,
               now: Optional[float] = None) -> float:
        """Fold one stats snapshot; returns the new score.  ``now``
        overrides the clock (tests drive it deterministically)."""
        if now is None:
            now = self._clock()
        fails = sum(int(stats.get(k) or 0) for k in self._FAIL_KEYS)
        oks = int(stats.get("completed_total") or 0)
        d_fail = fails - self._prev.get("fail", 0)
        d_ok = oks - self._prev.get("ok", 0)
        stale = (now - self._prev_t) if self._prev_t is not None else 0.0
        self._prev = {"fail": fails, "ok": oks}
        self._prev_t = now
        self.score = health_score(
            queued=int(stats.get("queued") or 0),
            queue_limit=int(stats.get("queue_limit") or 0),
            failure_delta=max(0, d_fail), ok_delta=max(0, d_ok),
            burn_rate=burn_rate,
            staleness_s=max(0.0, stale) if self._prev_t else 0.0)
        return self.score


@dataclasses.dataclass(frozen=True)
class BreakerPolicy:
    """Circuit-breaker knobs.  ``failures`` consecutive typed
    failures (or a health score under ``health_floor``) trip the
    breaker; trip ``n`` (1-based) backs off
    ``min(cap_s, base_s * 2**(n-1)) * (1 + jitter * u_n)`` with
    ``u_n`` the n-th draw of ``random.Random(seed)`` — seeded, so
    the sequence is exact in tests and de-synchronized across
    replicas in production (each replica's breaker gets its own
    seed)."""

    failures: int = 3
    base_s: float = 0.2
    cap_s: float = 5.0
    jitter: float = 0.1
    health_floor: float = 0.2
    seed: int = 0

    def __post_init__(self):
        if self.failures < 1:
            raise ValueError(
                f"failures={self.failures} must be >= 1")
        if self.base_s <= 0:
            raise ValueError(f"base_s={self.base_s} must be > 0")
        if self.cap_s < self.base_s:
            raise ValueError(
                f"cap_s={self.cap_s} must be >= base_s={self.base_s}")
        if not 0.0 <= self.jitter <= 1.0:
            raise ValueError(
                f"jitter={self.jitter} must be in [0, 1]")
        if not 0.0 <= self.health_floor < 1.0:
            raise ValueError(
                f"health_floor={self.health_floor} must be in [0, 1)")


def parse_breaker(text: str) -> BreakerPolicy:
    """Parse the ``--breaker`` DSL: empty or ``on`` = the documented
    defaults; otherwise comma-separated ``key=value`` over failures /
    base / cap / jitter / floor / seed (e.g.
    ``failures=5,base=0.5,cap=10``).  Raises ValueError on an unknown
    key or malformed value, naming the offending part (the
    parse_brownout contract)."""
    text = (text or "").strip()
    if not text or text == "on":
        return BreakerPolicy()
    names = {"failures": ("failures", int),
             "base": ("base_s", float),
             "cap": ("cap_s", float),
             "jitter": ("jitter", float),
             "floor": ("health_floor", float),
             "seed": ("seed", int)}
    kw = {}
    for part in text.split(","):
        part = part.strip()
        if not part:
            continue
        key, sep, val = part.partition("=")
        key = key.strip()
        if not sep or key not in names:
            raise ValueError(
                f"bad --breaker part {part!r} (want key=value with "
                f"key one of {sorted(names)}, or 'on', or empty)")
        field, typ = names[key]
        try:
            kw[field] = typ(val)
        except ValueError:
            raise ValueError(f"bad --breaker value in {part!r}")
    return BreakerPolicy(**kw)


class CircuitBreaker:
    """closed → open → half-open → closed, deterministically.

    - **closed**: requests flow; ``record_failure`` counts
      consecutive typed failures — at ``policy.failures`` (or when
      ``note_health`` reports a score under ``health_floor``) the
      breaker OPENS and arms the trip's backoff;
    - **open**: ``allow()`` is False until the backoff elapses, then
      the breaker moves to half-open;
    - **half-open**: ``allow()`` grants exactly ONE probe (further
      calls are refused while it is outstanding);
      ``record_success`` closes the breaker and resets the trip
      ordinal, ``record_failure`` re-opens it with the NEXT backoff
      step.

    The clock is injected (``time.monotonic`` by default) so the
    state machine is test-drivable without sleeping."""

    def __init__(self, policy: Optional[BreakerPolicy] = None,
                 clock=time.monotonic):
        self.policy = policy or BreakerPolicy()
        self._clock = clock
        self._rng = random.Random(self.policy.seed)
        self.state = "closed"
        self.consecutive_failures = 0
        self.trips = 0              # lifetime trip count (stats)
        self._trip_ordinal = 0      # resets on close: backoff restarts
        self._retry_at: Optional[float] = None
        self._probe_out = False
        self.last_reason: Optional[str] = None

    def backoff_s(self) -> float:
        """The CURRENT trip's backoff: exponential in the trip
        ordinal, capped, with one seeded jitter draw per trip."""
        p = self.policy
        base = min(p.cap_s, p.base_s * (2 ** (self._trip_ordinal - 1)))
        return round(base * (1.0 + p.jitter * self._rng.random()), 6)

    def _open(self, reason: str, now: Optional[float] = None) -> None:
        self._trip_ordinal += 1
        self.trips += 1
        self.state = "open"
        self.last_reason = reason
        self._probe_out = False
        self._retry_at = (self._clock() if now is None else now) \
            + self.backoff_s()

    def allow(self, now: Optional[float] = None) -> bool:
        """May a request be routed here now?  Transitions open →
        half-open as a side effect once the backoff has elapsed; in
        half-open, True exactly once (the single probe)."""
        if self.state == "closed":
            return True
        if now is None:
            now = self._clock()
        if self.state == "open":
            if self._retry_at is not None and now >= self._retry_at:
                self.state = "half_open"
                self._probe_out = True
                return True
            return False
        # half-open: one probe outstanding
        if not self._probe_out:
            self._probe_out = True
            return True
        return False

    def would_allow(self, now: Optional[float] = None) -> bool:
        """A NON-consuming admittability peek: placement ranks
        replicas with this; only the actual dispatch calls ``allow()``
        (which consumes the half-open probe).  No state transitions —
        an open breaker whose backoff has elapsed reads True here but
        moves to half-open only when ``allow()`` grants the probe."""
        if self.state == "closed":
            return True
        if now is None:
            now = self._clock()
        if self.state == "open":
            return self._retry_at is not None and now >= self._retry_at
        return not self._probe_out

    def abort_probe(self) -> None:
        """The granted half-open probe was never actually issued (the
        replica shed it at the door, so nothing will succeed or fail):
        hand the slot back, or the breaker waits forever on a probe
        that does not exist."""
        if self.state == "half_open":
            self._probe_out = False

    def record_success(self) -> None:
        """A routed request reached a clean terminal: close (from any
        state) and reset both the consecutive-failure count and the
        backoff ladder."""
        self.state = "closed"
        self.consecutive_failures = 0
        self._trip_ordinal = 0
        self._retry_at = None
        self._probe_out = False

    def record_failure(self, reason: str = "typed failure",
                       now: Optional[float] = None) -> None:
        """A routed request hit a typed failed terminal (or the
        replica refused as dead).  In half-open this re-opens
        immediately; closed opens at the consecutive threshold."""
        if self.state == "half_open":
            self._open(reason, now=now)
            return
        if self.state == "open":
            return
        self.consecutive_failures += 1
        if self.consecutive_failures >= self.policy.failures:
            self._open(reason, now=now)

    def note_health(self, score: float,
                    now: Optional[float] = None) -> None:
        """Health collapse trips a CLOSED breaker without waiting for
        ``failures`` individual requests to burn."""
        if self.state == "closed" and score < self.policy.health_floor:
            self._open(f"health collapse ({score:g} < "
                       f"{self.policy.health_floor:g})", now=now)

    def describe(self) -> Dict[str, Any]:
        return {
            "state": self.state,
            "consecutive_failures": self.consecutive_failures,
            "trips": self.trips,
            "retry_at": self._retry_at,
            "last_reason": self.last_reason,
        }
