"""Fleet router: health-checked placement + cross-engine failover.

PR 15 made ONE DecodeEngine fail open (typed terminals, bounded
queue, supervised restarts) and PR 16 made a fleet observable (trace
propagation, merged timelines, federated SLO).  This module is the
layer ROADMAP item 1 said was still missing: N replicas behind one
door, where a dead or sick replica costs retries — never answers.

- **placement** is least-loaded over health: each submit ranks the
  replicas by ``(load + 1) / health_score`` (serving/health.py folds
  queue depth, typed-failure deltas, SLO fast-window burn and stats
  staleness into the score) and dispatches to the best one whose
  circuit breaker admits it;
- **circuit breakers** (serving/health.CircuitBreaker, one per
  replica): consecutive typed failures or health collapse open the
  breaker; a seeded-jitter exponential backoff gates the half-open
  single probe; success closes it.  Placement peeks with the
  non-consuming ``would_allow`` — only the dispatch itself consumes
  the probe, and a dispatch the replica sheds hands the probe back;
- **failover**: a request whose replica fails it (``--engine_retries``
  budget spent, or the engine refusing as dead) re-submits to another
  replica carrying the SAME trace_id (PR 16 propagation) and the
  accumulated PR 15 ``attempts`` count (``engine.submit(attempts=)``
  seeds the new engine's retry ledger), bounded by a fleet-level
  ``fleet_retries`` hop budget.  Every accepted request still ends in
  exactly one typed terminal ``{result, timeout, shed, failed}``
  fleet-wide: each hop's lifecycle closes in ITS replica's span
  stream (intermediate hops as ``failed``), and obs/collector.py
  joins the hops by trace_id into one fleet verdict;
- **narration**: with a recorder attached the router appends
  ``route`` / ``failover`` spans (fleet rid, replica name, attempt,
  trace_id) to its own stream — the fleet timeline shows WHERE each
  request went, while the lifecycle truth stays in the replica
  streams (obs/spans.reconstruct treats these rows as narration, not
  lifecycles).

``RouterServer`` is the stdlib HTTP front door (the obs/serve.py
idiom): ``POST /generate`` proxied across the in-process replicas
(503 + Retry-After via admission.retry_after_header when every
replica sheds or the router drains), ``/status`` with a per-replica
section, ``/metrics`` with the ``dtx_router_*`` gauges, and SIGTERM
draining — stop admitting, finish in-flight, typed-cancel the queued
(their replica streams close with typed timeout/cancel terminals;
the router's client surface reports them shed with a Retry-After).

Pure Python like the scheduler: no jax anywhere in this module, so
the whole fleet decision layer is subprocess-provable and drives the
bench's analytic half over fake replicas.
"""

from __future__ import annotations

import json
import signal
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Set

from ..obs import spans as spans_lib
from .admission import ShedError, retry_after_header
from .health import BreakerPolicy, CircuitBreaker, HealthMonitor

# the Retry-After hint a router-level refusal carries when no replica
# offered one (all breakers open): at least this, or the earliest
# breaker re-probe, whichever is later
ROUTER_RETRY_AFTER_S = 1.0
# health-score floor in the placement ratio: a zero score must rank
# the replica last, not divide by zero
_SCORE_EPS = 1e-6


class _Replica:
    """One replica's routing state: the engine handle plus its health
    monitor, circuit breaker and dispatch accounting."""

    __slots__ = ("index", "name", "engine", "monitor", "breaker",
                 "dispatched", "load")

    def __init__(self, index: int, engine, policy: BreakerPolicy,
                 clock) -> None:
        self.index = index
        self.name = f"replica{index}"
        self.engine = engine
        self.monitor = HealthMonitor(clock=clock)
        # each replica's breaker draws its own jitter stream: same
        # policy, seed offset by the index (de-synchronized re-probes
        # in production, still fully deterministic in tests)
        self.breaker = CircuitBreaker(
            BreakerPolicy(**{**_policy_kw(policy),
                             "seed": policy.seed + index}),
            clock=clock)
        self.dispatched = 0
        self.load = 0


def _policy_kw(p: BreakerPolicy) -> Dict[str, Any]:
    return {"failures": p.failures, "base_s": p.base_s,
            "cap_s": p.cap_s, "jitter": p.jitter,
            "health_floor": p.health_floor, "seed": p.seed}


class _FleetRequest:
    """The router's ledger entry for one accepted request: where it
    currently lives, everything needed to re-submit it, and the
    failover accounting."""

    __slots__ = ("rid", "replica_index", "replica_rid", "trace_id",
                 "parent_id", "prompt", "max_new_tokens",
                 "temperature", "deadline_abs", "deadline_ms",
                 "attempts", "hops", "drained", "done")

    def __init__(self, rid: int) -> None:
        self.rid = rid
        self.replica_index = -1
        self.replica_rid = -1
        self.trace_id: Optional[str] = None
        self.parent_id: Optional[str] = None
        self.prompt: List[int] = []
        self.max_new_tokens = 0
        self.temperature = 0.0
        self.deadline_abs: Optional[float] = None
        self.deadline_ms: Optional[float] = None
        self.attempts = 0
        self.hops = 0
        self.drained = False
        self.done = False


class Router:
    """Health-checked least-loaded routing over N in-process replicas
    with circuit breakers and bounded cross-engine failover.

    ``replicas``: engine-like objects (serving/engine.DecodeEngine or
    any object with ``submit`` / ``result`` / ``cancel`` / ``stats``;
    ``waiting_rids`` and ``fast_burn`` are consumed when present).
    ``fleet_retries`` bounds the FAILOVER hops per request (on top of
    each engine's own ``engine_retries`` budget); ``breaker`` is the
    per-replica BreakerPolicy (each replica's breaker gets
    ``seed + index``).  ``recorder``: an obs/spans.SpanRecorder for
    the router's own route/failover narration stream.  The clock is
    injectable (tests drive the breakers without sleeping)."""

    def __init__(self, replicas: Sequence[Any], fleet_retries: int = 2,
                 breaker: Optional[BreakerPolicy] = None,
                 recorder=None, clock=time.monotonic):
        if not replicas:
            raise ValueError("Router needs at least one replica")
        if fleet_retries < 0:
            raise ValueError(
                f"fleet_retries={fleet_retries} must be >= 0")
        policy = breaker or BreakerPolicy()
        self.fleet_retries = int(fleet_retries)
        self.recorder = recorder
        self._clock = clock
        self._replicas = [_Replica(i, e, policy, clock)
                          for i, e in enumerate(replicas)]
        self._lock = threading.Lock()
        self._requests: Dict[int, _FleetRequest] = {}
        self._by_replica: Dict[tuple, int] = {}
        self._next_rid = 0
        self._draining = False
        # fleet accounting (stats()/dtx_router_* surface)
        self._accepted = 0
        self._completed = 0
        self._failovers = 0
        self._exhausted = 0
        self._shed = 0
        self._drain_cancelled = 0

    # ---- placement ----
    def _probe(self, r: _Replica) -> None:
        """Refresh one replica's health from its live stats (and its
        fast-window burn when the engine exposes one); a health
        collapse trips the breaker here, before placement ranks."""
        try:
            stats = r.engine.stats()
        except Exception:  # noqa: BLE001 — a dead stats() is sick, not fatal
            r.breaker.note_health(0.0, now=self._clock())
            return
        burn_of = getattr(r.engine, "fast_burn", None)
        burn = burn_of() if callable(burn_of) else None
        score = r.monitor.update(stats, burn_rate=burn,
                                 now=self._clock())
        r.load = int(stats.get("queued") or 0) \
            + int(stats.get("inflight") or 0)
        r.breaker.note_health(score, now=self._clock())

    def _placement(self,
                   exclude: Optional[Set[int]] = None) -> List[_Replica]:
        """Candidate replicas in dispatch order: breaker-admittable
        (non-consuming peek), ranked least-loaded-per-health —
        ``(load + 1) / score`` ascending, index as the deterministic
        tie-break."""
        now = self._clock()
        ranked = []
        for r in self._replicas:
            if exclude and r.index in exclude:
                continue
            self._probe(r)
            if not r.breaker.would_allow(now=now):
                continue
            score = max(r.monitor.score, _SCORE_EPS)
            ranked.append(((r.load + 1) / score, r.index, r))
        return [r for _, _, r in sorted(ranked, key=lambda t: t[:2])]

    def _dispatch(self, req: _FleetRequest, order: List[_Replica],
                  first: bool) -> Optional[_Replica]:
        """Try each candidate in order; returns the replica that
        accepted (ledger updated, narration emitted) or None.  Shed
        hints are folded into ``req``-independent state by the
        caller via the raised ShedError on the first hop."""
        hints: List[float] = []
        for r in order:
            if not r.breaker.allow(now=self._clock()):
                continue
            header = spans_lib.format_traceparent(
                req.trace_id, req.parent_id or spans_lib.new_span_id())
            kw: Dict[str, Any] = {"temperature": req.temperature,
                                  "deadline_ms": self._remaining_ms(req),
                                  "traceparent": header}
            if req.attempts:
                # PR 15 accounting carries ACROSS engines: the new
                # replica's retry ledger starts where the old stopped
                kw["attempts"] = req.attempts
            try:
                rrid = r.engine.submit(list(req.prompt),
                                       req.max_new_tokens, **kw)
            except ShedError as e:
                hints.append(float(e.retry_after_s))
                r.breaker.abort_probe()   # nothing was probed
                continue
            except RuntimeError as e:
                # the engine refused as dead — a typed failure for
                # the breaker, and placement moves on
                r.breaker.record_failure(f"submit refused: {e}",
                                         now=self._clock())
                continue
            with self._lock:
                req.replica_index = r.index
                req.replica_rid = int(rrid)
                self._by_replica[(r.index, int(rrid))] = req.rid
                r.dispatched += 1
            if self.recorder is not None:
                event = "route" if first else "failover"
                extra: Dict[str, Any] = {}
                if req.trace_id:
                    extra["trace_id"] = req.trace_id
                if not first:
                    extra["reason"] = "replica failed"
                self.recorder.emit(event, rid=req.rid, replica=r.name,
                                   attempt=req.attempts, **extra)
            return r
        if hints:
            raise ShedError(
                "every admittable replica shed (queues full)",
                retry_after_s=min(hints))
        return None

    def _remaining_ms(self, req: _FleetRequest) -> Optional[float]:
        """The deadline a (re-)submit carries: the ORIGINAL absolute
        deadline re-expressed as remaining milliseconds — a failover
        must not restart the client's clock.  Floored at 1ms so a
        past-deadline re-submit is accepted and immediately retired
        with the typed timeout terminal (the lifecycle closes in a
        replica stream either way)."""
        if req.deadline_abs is None:
            return req.deadline_ms
        return max(1.0, (req.deadline_abs - self._clock()) * 1e3)

    def _breaker_wait_s(self) -> float:
        """Retry-After when every breaker refused: the earliest
        re-probe across replicas, floored at ROUTER_RETRY_AFTER_S."""
        now = self._clock()
        waits = [r.breaker._retry_at - now for r in self._replicas
                 if r.breaker._retry_at is not None]
        wait = min((w for w in waits if w > 0), default=0.0)
        return round(max(ROUTER_RETRY_AFTER_S, wait), 3)

    # ---- request surface ----
    def submit(self, prompt, max_new_tokens: int,
               temperature: float = 0.0,
               deadline_ms: Optional[float] = None,
               traceparent: Optional[str] = None) -> int:
        """Place one request on the best admittable replica; returns
        the FLEET rid (the router's own namespace — replica rids are
        internal).  Raises ShedError when draining, when every
        admittable replica shed, or when every breaker is open
        (Retry-After = the earliest re-probe)."""
        with self._lock:
            if self._draining:
                self._shed += 1
                raise ShedError("router draining",
                                retry_after_s=ROUTER_RETRY_AFTER_S)
            rid = self._next_rid
            self._next_rid += 1
        ctx = spans_lib.parse_traceparent(traceparent)
        req = _FleetRequest(rid)
        req.trace_id, req.parent_id = ctx if ctx is not None else (
            spans_lib.new_trace_id(), None)
        req.prompt = [int(t) for t in prompt]
        req.max_new_tokens = int(max_new_tokens)
        req.temperature = float(temperature)
        req.deadline_ms = deadline_ms
        if deadline_ms is not None and float(deadline_ms) > 0:
            req.deadline_abs = self._clock() + float(deadline_ms) / 1e3
        try:
            placed = self._dispatch(req, self._placement(), first=True)
        except ShedError:
            with self._lock:
                self._shed += 1
            raise
        if placed is None:
            with self._lock:
                self._shed += 1
            raise ShedError("no admittable replica (circuit breakers "
                            "open)", retry_after_s=self._breaker_wait_s())
        with self._lock:
            self._requests[rid] = req
            self._accepted += 1
        return rid

    def trace_context(self, rid: int) -> Optional[tuple]:
        """``(trace_id, parent_id)`` for an accepted fleet rid — the
        serving edge stamps the response traceparent from this (the
        DecodeEngine surface, fleet-scoped)."""
        with self._lock:
            req = self._requests.get(int(rid))
        return (req.trace_id, req.parent_id) if req is not None else None

    def cancel(self, rid: int) -> bool:
        """Client-side cancellation, routed to the request's current
        replica (typed timeout terminal with reason "cancel" there)."""
        with self._lock:
            req = self._requests.get(int(rid))
        if req is None or req.done:
            return False
        r = self._replicas[req.replica_index]
        return bool(r.engine.cancel(req.replica_rid))

    def result(self, rid: int, timeout: Optional[float] = None):
        """Block until the fleet terminal: the replica result with
        ``rid`` rewritten to the fleet rid (plus ``failovers`` when
        hops happened).  A typed ``failed`` from the current replica
        triggers failover while the ``fleet_retries`` hop budget
        lasts; a drain-cancelled queued request comes back as status
        "shed" with a ``retry_after_s`` (the replica stream holds its
        typed timeout/cancel terminal; the CLIENT contract is "try
        again elsewhere", not "you timed out").  None = ``timeout``
        elapsed with the request still in flight."""
        deadline = None if timeout is None \
            else self._clock() + float(timeout)
        with self._lock:
            req = self._requests[int(rid)]
        while True:
            r = self._replicas[req.replica_index]
            remaining = None if deadline is None \
                else max(0.0, deadline - self._clock())
            res = r.engine.result(req.replica_rid, timeout=remaining)
            if res is None:
                return None
            status = res.get("status")
            if status == "result":
                r.breaker.record_success()
                with self._lock:
                    self._completed += 1
                    req.done = True
                return self._rewrite(res, req)
            if status == "timeout":
                if req.drained:
                    with self._lock:
                        self._drain_cancelled += 1
                        req.done = True
                    out = {"rid": req.rid, "status": "shed",
                           "error": "router draining: cancelled "
                                    "before completion",
                           "retry_after_s": ROUTER_RETRY_AFTER_S}
                    if req.trace_id:
                        out["trace_id"] = req.trace_id
                    return out
                # a deadline/cancel terminal is the CLIENT's contract
                # playing out, not the replica's fault: no breaker
                # penalty, no failover
                with self._lock:
                    req.done = True
                return self._rewrite(res, req)
            # typed "failed" (or the engine died mid-request): the
            # failover path
            reason = str(res.get("error") or "typed failed terminal")
            r.breaker.record_failure(reason, now=self._clock())
            req.attempts = int(res.get("attempts")
                               or req.attempts + 1)
            if req.hops >= self.fleet_retries or self._draining:
                with self._lock:
                    self._exhausted += 1
                    req.done = True
                out = self._rewrite(res, req)
                out["attempts"] = req.attempts
                out["error"] = (f"{reason} (fleet retry budget spent: "
                                f"{req.hops} failovers, fleet_retries="
                                f"{self.fleet_retries})")
                return out
            try:
                placed = self._dispatch(
                    self._mark_hop(req),
                    self._placement(exclude={req.replica_index}
                                    if len(self._replicas) > 1
                                    else None),
                    first=False)
            except ShedError:
                # every failover candidate shed: same terminal as "no
                # admittable replica" — the request already HAS its
                # typed failed terminal in the old replica's stream
                placed = None
            if placed is None:
                with self._lock:
                    self._exhausted += 1
                    req.hops -= 1
                    self._failovers -= 1
                    req.done = True
                out = self._rewrite(res, req)
                out["attempts"] = req.attempts
                out["error"] = (f"{reason} (no admittable replica for "
                                f"failover)")
                return out

    def _mark_hop(self, req: _FleetRequest) -> _FleetRequest:
        with self._lock:
            req.hops += 1
            self._failovers += 1
        return req

    def _rewrite(self, res: Dict[str, Any],
                 req: _FleetRequest) -> Dict[str, Any]:
        out = dict(res)
        out["rid"] = req.rid
        if req.hops:
            out["failovers"] = req.hops
        return out

    # ---- drain ----
    def drain(self) -> int:
        """SIGTERM semantics: stop admitting (new submits shed),
        typed-cancel every router-owned request still WAITING on its
        replica (its stream closes with the typed timeout/cancel
        terminal; its client gets the shed remap), let in-flight
        decodes finish.  Returns the number of cancelled requests;
        idempotent."""
        with self._lock:
            if self._draining:
                return 0
            self._draining = True
            by_replica = dict(self._by_replica)
        cancelled = 0
        for r in self._replicas:
            waiting_of = getattr(r.engine, "waiting_rids", None)
            if not callable(waiting_of):
                continue
            for rrid in waiting_of():
                frid = by_replica.get((r.index, int(rrid)))
                if frid is None:
                    continue
                with self._lock:
                    req = self._requests.get(frid)
                    if req is None or req.done:
                        continue
                    req.drained = True
                if r.engine.cancel(int(rrid)):
                    cancelled += 1
        return cancelled

    @property
    def draining(self) -> bool:
        return self._draining

    # ---- observability ----
    def stats(self) -> Dict[str, Any]:
        """Point-in-time fleet counters + a per-replica section (the
        dtx_router_* gauges and the RouterServer /status read this)."""
        per_replica = []
        healthy = 0
        for r in self._replicas:
            self._probe(r)
            desc = r.breaker.describe()
            if desc["state"] == "closed":
                healthy += 1
            per_replica.append({
                "name": r.name,
                "health": r.monitor.score,
                "load": r.load,
                "dispatched": r.dispatched,
                "breaker": desc,
            })
        with self._lock:
            return {
                "replicas": len(self._replicas),
                "replicas_healthy": healthy,
                "draining": int(self._draining),
                "fleet_retries": self.fleet_retries,
                "requests_total": self._accepted,
                "completed_total": self._completed,
                "failovers_total": self._failovers,
                "fleet_failed_total": self._exhausted,
                "shed_total": self._shed,
                "drain_cancelled_total": self._drain_cancelled,
                "per_replica": per_replica,
            }


class RouterServer:
    """The fleet's stdlib HTTP front door (the obs/serve.StatusServer
    idiom): ``POST /generate`` proxied through the router (503 +
    integer-ceil Retry-After on shed — admission.retry_after_header —
    whether the hint came from a replica's bounded queue or the
    router's own drain/breaker refusals), ``GET /status`` with the
    per-replica health/breaker section, ``GET /metrics`` with the
    ``dtx_router_*`` gauges.  ``install_sigterm()`` arms the drain
    handler (main thread only — signal module rules)."""

    def __init__(self, router: Router):
        self.router = router
        self.port: Optional[int] = None
        self._httpd = None
        self._thread: Optional[threading.Thread] = None
        self._prev_sigterm = None

    def install_sigterm(self) -> None:
        prev = signal.getsignal(signal.SIGTERM)

        def handler(signum, frame):
            self.router.drain()
            if callable(prev):
                prev(signum, frame)

        self._prev_sigterm = prev
        signal.signal(signal.SIGTERM, handler)

    def start(self, port: int, host: str = "") -> Optional[int]:
        from http.server import (
            BaseHTTPRequestHandler,
            ThreadingHTTPServer,
        )

        from ..obs.serve import (
            GENERATE_DEADLINE_GRACE_S,
            GENERATE_TIMEOUT_S,
            prometheus_text,
        )

        router = self.router

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # stdout belongs to the fleet
                pass

            def _send(self, code: int, body: bytes,
                      ctype: str = "application/json",
                      headers: Optional[Dict[str, str]] = None) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def _shed(self, msg: str, retry_after_s: float,
                      headers: Optional[Dict[str, str]] = None) -> None:
                hdrs = dict(headers or {})
                hdrs["Retry-After"] = str(
                    retry_after_header(retry_after_s))
                self._send(503, json.dumps(
                    {"error": msg, "status": "shed",
                     "retry_after_s": retry_after_s}).encode(),
                    headers=hdrs)

            def do_GET(self):
                path = self.path.split("?", 1)[0].rstrip("/") or "/"
                try:
                    if path in ("/", "/status"):
                        doc = {"live": not router.draining,
                               "router": router.stats()}
                        self._send(200, json.dumps(doc).encode())
                    elif path == "/metrics":
                        text = prometheus_text(
                            {"live": not router.draining},
                            router=router.stats())
                        self._send(200, text.encode(),
                                   "text/plain; version=0.0.4")
                    else:
                        self._send(404, json.dumps(
                            {"error": f"unknown path {path!r}",
                             "endpoints": ["/status", "/metrics",
                                           "/generate"]}).encode())
                except Exception as e:  # a bad read must not kill serving
                    self._send(500, json.dumps(
                        {"error": f"{type(e).__name__}: {e}"}).encode())

            def do_POST(self):
                path = self.path.split("?", 1)[0].rstrip("/") or "/"
                if path != "/generate":
                    self._send(404, json.dumps(
                        {"error": f"unknown POST path {path!r}"}
                    ).encode())
                    return
                try:
                    n = int(self.headers.get("Content-Length") or 0)
                    req = json.loads(self.rfile.read(n) or b"{}")
                    prompt = req.get("prompt")
                    if not isinstance(prompt, list):
                        raise ValueError(
                            "'prompt' must be a list of token ids")
                    deadline_ms = req.get("deadline_ms")
                    if deadline_ms is not None:
                        deadline_ms = float(deadline_ms)
                        if deadline_ms < 0:
                            raise ValueError("'deadline_ms' must be "
                                             ">= 0")
                    rid = router.submit(
                        prompt,
                        int(req.get("max_new_tokens", 16)),
                        temperature=float(req.get("temperature", 0.0)),
                        deadline_ms=deadline_ms,
                        traceparent=self.headers.get("traceparent"))
                except ShedError as e:
                    # a replica 503's Retry-After hint is HONORED: the
                    # router propagates the smallest replica hint (or
                    # its own drain/breaker wait) into the header
                    self._shed(str(e), e.retry_after_s)
                    return
                except (ValueError, TypeError, KeyError) as e:
                    self._send(400, json.dumps(
                        {"error": f"{type(e).__name__}: {e}"}).encode())
                    return
                resp_headers: Optional[Dict[str, str]] = None
                ctx = router.trace_context(rid)
                if ctx is not None:
                    resp_headers = {
                        "traceparent": spans_lib.format_traceparent(
                            ctx[0], spans_lib.new_span_id())}
                wait_s = GENERATE_TIMEOUT_S
                if deadline_ms and deadline_ms > 0:
                    wait_s = min(wait_s, deadline_ms / 1e3
                                 + GENERATE_DEADLINE_GRACE_S)
                try:
                    res = router.result(rid, timeout=wait_s)
                    if res is None:
                        router.cancel(rid)
                        self._send(504, json.dumps(
                            {"error": "generation timed out",
                             "status": "timeout", "rid": rid}).encode(),
                            headers=resp_headers)
                        return
                    if res.get("status") == "shed":
                        # the drain remap: typed-shed, try elsewhere
                        self._shed(str(res.get("error")),
                                   float(res.get("retry_after_s")
                                         or ROUTER_RETRY_AFTER_S),
                                   headers=resp_headers)
                        return
                    if res.get("status") == "timeout":
                        self._send(504, json.dumps(res).encode(),
                                   headers=resp_headers)
                        return
                    if "error" in res:
                        self._send(500, json.dumps(res).encode(),
                                   headers=resp_headers)
                        return
                    self._send(200, json.dumps(res).encode(),
                               headers=resp_headers)
                except Exception as e:
                    self._send(500, json.dumps(
                        {"error": f"{type(e).__name__}: {e}"}).encode())

        try:
            self._httpd = ThreadingHTTPServer((host, int(port)), Handler)
        except OSError as e:
            print(f"NOTE: router server failed to bind port {port}: {e}")
            return None
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="dtx-router",
            daemon=True)
        self._thread.start()
        return self.port

    def close(self) -> None:
        httpd, self._httpd = self._httpd, None
        if httpd is not None:
            httpd.shutdown()
            httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        if self._prev_sigterm is not None:
            signal.signal(signal.SIGTERM, self._prev_sigterm)
            self._prev_sigterm = None
