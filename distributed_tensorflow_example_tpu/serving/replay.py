"""Deterministic open-loop replay of a captured workload.

The write-back half of observability (ISSUE 19): ``obs/workload.py``
distills a span dir into a WORKLOAD document; this module feeds that
document back through the serving stack at the recorded (or
``--speed``-scaled) arrival offsets, so a production traffic shape
becomes a reproducible benchmark.  Two paths share one schedule:

- **scheduler-only fast path** (``replay_sim``): pure Python through
  the REAL ``ContinuousScheduler.simulate`` (reused, not forked) on
  the ticks-as-seconds clock — one tick boundary per workload second
  at speed 1, so arrival offsets and relative deadlines round-trip
  through capture unchanged.  No jax, no wall clock: the
  capture→replay→capture idempotence property is provable in tier-1
  on any backend;
- **real-engine path** (``replay_engine``): an open-loop driver over
  a live ``DecodeEngine`` (or the r18 router fleet — anything with
  ``submit``/``result``).  Prompts are regenerated from the recorded
  fingerprints (``obs/workload.synth_prompt``: same hash -> same
  block, so shared prefixes stay shared and two replays submit
  IDENTICAL prompts), submits fire at ``arrival_s / speed`` on an
  injectable clock (the serving/faults.py discipline: tests drive
  virtual time, production sleeps), and relative deadlines scale
  with speed.  With greedy decode (the default) the engine's seeded
  keys make two replays of one workload produce identical typed
  terminals, token counts and span shapes — timestamps aside —
  which ``identity()`` verifies and ``bench_workload_replay`` gates.

Span attribution: build the engine's recorder with
``replay_recorder(...)`` and every row the replay writes carries
``replay_of: <workload_id>`` (schema v10), so ``dtx-obs tail/explain
--workload`` can compare waterfalls A/B across replays.
"""

from __future__ import annotations

import hashlib
import time
from typing import Any, Callable, Dict, List, Optional

from ..obs import spans as spans_lib
from ..obs.workload import synth_prompt
from . import scheduler as sched_lib
from .admission import ShedError

# how long ``replay_engine`` waits on each straggler result after the
# last submit before declaring the replay wedged
RESULT_TIMEOUT_S = 120.0

# polling granularity of the open-loop wait (real clock only; a
# virtual clock's sleep() advances time instead)
_WAIT_SLICE_S = 0.02


def replay_recorder(logs_path: str, workload_id: str,
                    process_index: int = 0,
                    **kw) -> spans_lib.SpanRecorder:
    """A SpanRecorder whose every row is stamped ``replay_of`` — give
    this to the engine/router under replay so the whole stream is
    attributable to its source workload."""
    return spans_lib.SpanRecorder(
        logs_path, process_index=process_index,
        extra={"replay_of": str(workload_id)}, **kw)


def _schedule(doc: Dict[str, Any], speed: float) -> List[Dict[str, Any]]:
    if speed <= 0:
        raise ValueError(f"speed={speed} must be > 0")
    return sorted(doc["requests"], key=lambda r: (float(r["arrival_s"]),
                                                  int(r["rid"])))


def replay_sim(doc: Dict[str, Any], num_pages: int = 65,
               page_size: int = 16, max_batch: int = 8,
               speed: float = 1.0,
               recorder=None) -> Dict[str, Any]:
    """Scheduler-only replay: the workload schedule through the real
    ``ContinuousScheduler`` + ``simulate`` on the ticks-as-seconds
    clock (arrival tick = ``arrival_s / speed``; a relative deadline
    becomes an absolute tick the same way).  Deterministic by
    construction — same workload, same pool shape => identical
    SimResult — and with a recorder attached the emitted span stream
    re-captures to the SAME workload (fingerprints pass through
    verbatim), which is the round-trip property the tests pin."""
    sched = sched_lib.ContinuousScheduler(
        num_pages=num_pages, page_size=page_size, max_batch=max_batch,
        recorder=recorder)
    requests = []
    for r in _schedule(doc, speed):
        arrival = float(r["arrival_s"]) / speed
        if r.get("deadline_ms") is not None:
            deadline = arrival + float(r["deadline_ms"]) / 1e3 / speed
            requests.append((int(r["rid"]), int(r["prompt_len"]),
                             int(r["max_new_tokens"]), arrival,
                             deadline))
        else:
            requests.append((int(r["rid"]), int(r["prompt_len"]),
                             int(r["max_new_tokens"]), arrival))
    # fingerprints ride the submit spans verbatim (content-free
    # idempotence): simulate() calls scheduler.submit(*req), which
    # takes fingerprint as its trailing keyword — append it only when
    # the entry recorded one
    with_fp = []
    by_rid = {int(r["rid"]): r for r in doc["requests"]}
    for req in requests:
        fp = by_rid[req[0]].get("fingerprint") or None
        if fp and len(req) == 4:
            req = req + (None,)       # explicit no-deadline slot
        with_fp.append(req + (None, None, fp) if fp else req)
    sim = sched_lib.simulate(sched, with_fp)
    per_request = []
    terminals: Dict[str, int] = {}
    for r in doc["requests"]:
        rid = int(r["rid"])
        if rid in sim.finish_ticks:
            term, toks = "result", int(r["max_new_tokens"])
        else:
            term, toks = "timeout", None
        terminals[term] = terminals.get(term, 0) + 1
        per_request.append({"rid": rid, "terminal": term,
                            "tokens": toks, "token_sig": None,
                            "latency": sim.latency_ticks.get(rid)})
    return {
        "kind": "replay_report",
        "mode": "sim",
        "workload_id": doc["workload_id"],
        "speed": float(speed),
        "n_requests": int(doc["n_requests"]),
        "terminals": terminals,
        "completed": terminals.get("result", 0),
        "decode_ticks": sim.decode_ticks,
        "total_ticks": sim.total_ticks,
        "occupancy": round(sim.occupancy, 6),
        "shapes": [list(s) for s in sim.shapes],
        "per_request": per_request,
    }


def _submit(target, prompt: List[int], max_new: int,
            temperature: float, deadline_ms: Optional[float],
            fingerprint: Optional[List[str]]) -> int:
    """Submit to an engine OR a router: the engine takes the recorded
    fingerprint through; the router's surface doesn't (its replicas'
    engines re-derive one from the regenerated prompt)."""
    kw: Dict[str, Any] = {"temperature": temperature}
    if deadline_ms is not None:
        kw["deadline_ms"] = deadline_ms
    try:
        return target.submit(prompt, max_new, fingerprint=fingerprint,
                             **kw)
    except TypeError:
        return target.submit(prompt, max_new, **kw)


def replay_engine(target, doc: Dict[str, Any], vocab_size: int,
                  speed: float = 1.0, temperature: float = 0.0,
                  seed: int = 0,
                  clock: Callable[[], float] = time.monotonic,
                  sleep: Callable[[float], None] = time.sleep,
                  result_timeout_s: float = RESULT_TIMEOUT_S
                  ) -> Dict[str, Any]:
    """Open-loop replay through a live engine/router ``target`` (its
    background loop must be running).  Submits fire at
    ``arrival_s / speed`` on the injectable ``clock`` (virtual-time
    tests pass a fake clock whose ``sleep`` advances it — the
    serving/faults.py discipline); deadlines scale by ``1/speed``.
    Returns the replay report: typed-terminal multiset, per-request
    token counts + content signatures, wall/throughput accounting."""
    entries = _schedule(doc, speed)
    start = clock()
    rids: Dict[int, int] = {}
    shed: Dict[int, str] = {}
    for r in entries:
        due = float(r["arrival_s"]) / speed
        while True:
            now = clock() - start
            if now >= due:
                break
            sleep(min(due - now, _WAIT_SLICE_S))
        prompt = synth_prompt(int(r["prompt_len"]),
                              r.get("fingerprint"), vocab_size,
                              seed=seed, rid=int(r["rid"]))
        deadline_ms = (float(r["deadline_ms"]) / speed
                       if r.get("deadline_ms") is not None else None)
        try:
            rids[int(r["rid"])] = _submit(
                target, prompt, int(r["max_new_tokens"]), temperature,
                deadline_ms, r.get("fingerprint") or None)
        except ShedError as e:
            shed[int(r["rid"])] = str(e)
    per_request = []
    terminals: Dict[str, int] = {}
    tokens_total = 0
    for r in doc["requests"]:
        rid = int(r["rid"])
        if rid in shed:
            entry = {"rid": rid, "terminal": "shed", "tokens": None,
                     "token_sig": None}
        else:
            res = target.result(rids[rid], timeout=result_timeout_s)
            if res is None:
                entry = {"rid": rid, "terminal": "wedged",
                         "tokens": None, "token_sig": None}
            else:
                status = res.get("status")
                term = {"result": "result", "timeout": "timeout",
                        "shed": "shed"}.get(status, "failed")
                toks = res.get("tokens")
                sig = None
                if toks is not None:
                    sig = hashlib.sha1(
                        ",".join(str(t) for t in toks).encode()
                    ).hexdigest()[:16]
                    tokens_total += len(toks)
                entry = {"rid": rid, "terminal": term,
                         "tokens": (len(toks) if toks is not None
                                    else None),
                         "token_sig": sig}
        terminals[entry["terminal"]] = \
            terminals.get(entry["terminal"], 0) + 1
        per_request.append(entry)
    wall_s = max(clock() - start, 1e-9)
    dur = max(float(doc.get("duration_s") or 0.0) / speed, 1e-9)
    return {
        "kind": "replay_report",
        "mode": "engine",
        "workload_id": doc["workload_id"],
        "speed": float(speed),
        "n_requests": int(doc["n_requests"]),
        "terminals": terminals,
        "completed": terminals.get("result", 0),
        "tokens_total": tokens_total,
        "wall_s": round(wall_s, 6),
        "qps_offered": round(doc["n_requests"] / dur, 6),
        "qps_completed": round(terminals.get("result", 0) / wall_s, 6),
        "per_request": per_request,
    }


def identity(a: Dict[str, Any], b: Dict[str, Any]) -> Dict[str, Any]:
    """The determinism verdict over two replay reports of the SAME
    workload: per-request typed terminals and token content must
    match pairwise.  ``determinism_frac`` is the matching fraction —
    the ``replay_determinism_frac`` gate metric; ``identical`` also
    requires the terminal MULTISETS to agree (a swap that preserves
    counts per-request would already fail pairwise, so this is the
    belt to that suspender)."""
    if a.get("workload_id") != b.get("workload_id"):
        raise ValueError(
            f"replay reports of different workloads: "
            f"{a.get('workload_id')} vs {b.get('workload_id')}")
    pa = {r["rid"]: r for r in a.get("per_request", [])}
    pb = {r["rid"]: r for r in b.get("per_request", [])}
    rids = sorted(set(pa) | set(pb))
    mismatches = []
    matched = 0
    for rid in rids:
        ra, rb = pa.get(rid), pb.get(rid)
        if (ra is not None and rb is not None
                and ra["terminal"] == rb["terminal"]
                and ra.get("tokens") == rb.get("tokens")
                and ra.get("token_sig") == rb.get("token_sig")):
            matched += 1
        else:
            mismatches.append({"rid": rid, "a": ra, "b": rb})
    frac = matched / max(len(rids), 1)
    return {
        "identical": (not mismatches
                      and a.get("terminals") == b.get("terminals")),
        "determinism_frac": round(frac, 6),
        "n_requests": len(rids),
        "mismatches": mismatches[:10],
    }
