"""Capacity forecasting: workload shape + service model -> QPS.

The forward-looking half of the queueing analytics (ISSUE 19): where
``obs/queueing.py`` explains a PAST stream (utilization, Little's
law), this module answers the planning question — given a captured
WORKLOAD's traffic shape, how many requests per second can one
replica sustain, and how many replicas does the offered load need?

The model is utilization-first and deliberately closed-form
(auditable, drift-gateable):

- a replica's decode budget is ``service_tok_s`` generated tokens per
  second — either MEASURED (an unloaded ``serving/replay.py`` run's
  ``tokens_total / wall_s``, the only honest base off-TPU where
  ``chip_peak_hbm_bytes`` is None) or the ROOFLINE bound
  (``roofline_decode_tok_s``: peak HBM bytes/s over
  ``obs/flops.decode_bytes_per_step``, the bench's gated decode
  ceiling);
- one request costs its mean decode tokens (prefill is amortized into
  the measured rate; the forecast is decode-bound by the same
  argument the roofline makes), so
  ``sustainable_qps = service_tok_s * utilization_target /
  mean_new_tokens`` — Little's law rearranged from time-per-request
  to requests-per-time at the target utilization;
- ``required_replicas = ceil(offered_qps / sustainable_qps)``.

Validation closes the loop: ``measured_knee`` finds the saturation
knee by replaying the SAME workload at increasing ``--speed`` (the
highest completed-throughput the system sustained without dropping
requests), and ``verdict`` compares it to the forecast —
``capacity_forecast_rel_err`` is gated at 25% and ``dtx-obs
capacity`` exits 3 when measurement falls short of forecast beyond
tolerance (the drift-detection exit-code idiom).
"""

from __future__ import annotations

import math
import time
from typing import Any, Dict, List, Optional

from . import flops as flops_lib
from .schema import SCHEMA_VERSION

# default fraction of the service budget a forecast plans to (run a
# queue at 100% and Little's law says the backlog diverges)
UTILIZATION_TARGET = 0.8

# a speed point "sustains" when at least this fraction of requests
# reached the result terminal
SUSTAINED_COMPLETED_FRAC = 0.99

DEFAULT_TOLERANCE = 0.25


def workload_shape(doc: Dict[str, Any]) -> Dict[str, float]:
    """The forecast's inputs off a WORKLOAD document: offered rate and
    mean request shape."""
    reqs = doc["requests"]
    n = max(len(reqs), 1)
    dur = max(float(doc.get("duration_s") or 0.0), 1e-9)
    return {
        "offered_qps": round(len(reqs) / dur, 6),
        "mean_prompt_len": round(
            sum(int(r["prompt_len"]) for r in reqs) / n, 3),
        "mean_new_tokens": round(
            sum(int(r["max_new_tokens"]) for r in reqs) / n, 3),
    }


def roofline_decode_tok_s(spec, batch: int, kv_len: float,
                          device=None,
                          kv_dtype_bytes: Optional[float] = None
                          ) -> Optional[float]:
    """The decode-token ceiling one replica's HBM allows: peak bytes/s
    over the analytic bytes/step, times the batch one step serves.
    None off-TPU (the peak is unknown — callers fall back to a
    measured rate, never a fabricated one)."""
    peak = flops_lib.chip_peak_hbm_bytes(device)
    if peak is None:
        return None
    bytes_per_step = flops_lib.decode_bytes_per_step(
        spec, batch, kv_len, kv_dtype_bytes=kv_dtype_bytes)
    if bytes_per_step <= 0:
        return None
    return peak / bytes_per_step * max(int(batch), 1)


def forecast(doc: Dict[str, Any], service_tok_s: float,
             utilization_target: float = UTILIZATION_TARGET
             ) -> Dict[str, Any]:
    """The closed-form capacity document for one workload against one
    replica's service rate.  Exact by construction: a synthetic
    fixture whose service rate and token counts are chosen by hand
    reproduces ``sustainable_qps`` to float precision (the test's
    exactness hook)."""
    if service_tok_s <= 0:
        raise ValueError(
            f"service_tok_s={service_tok_s} must be > 0")
    if not 0 < utilization_target <= 1:
        raise ValueError(f"utilization_target={utilization_target} "
                         f"must be in (0, 1]")
    shape = workload_shape(doc)
    sustainable = (service_tok_s * utilization_target
                   / max(shape["mean_new_tokens"], 1e-9))
    rho = shape["offered_qps"] / max(sustainable, 1e-9)
    return {
        "v": SCHEMA_VERSION,
        "kind": "capacity",
        "generated_t": time.time(),
        "workload_id": doc["workload_id"],
        "n_requests": int(doc["n_requests"]),
        "offered_qps": shape["offered_qps"],
        "mean_prompt_len": shape["mean_prompt_len"],
        "mean_new_tokens": shape["mean_new_tokens"],
        "service_tok_s": round(float(service_tok_s), 6),
        "utilization_target": float(utilization_target),
        "sustainable_qps": round(sustainable, 6),
        "utilization": round(rho * utilization_target, 6),
        "required_replicas": int(math.ceil(
            shape["offered_qps"] / max(sustainable, 1e-9))),
    }


def measured_knee(points: List[Dict[str, Any]],
                  min_completed_frac: float = SUSTAINED_COMPLETED_FRAC
                  ) -> Dict[str, Any]:
    """The saturation knee over replay reports of ONE workload at
    increasing speeds: each point offers ``qps_offered`` and completes
    ``qps_completed``; the measured capacity is the highest completed
    throughput among points that still completed (essentially) every
    request — past the knee, sheds/timeouts appear and completed
    throughput plateaus.  ``points`` entries need ``speed``,
    ``qps_offered``, ``qps_completed``, ``n_requests`` and
    ``completed`` (the ``replay_engine`` report surface)."""
    if not points:
        raise ValueError("measured_knee needs at least one point")
    rows = []
    for p in sorted(points, key=lambda p: float(p.get("speed") or 0)):
        frac = p["completed"] / max(int(p["n_requests"]), 1)
        rows.append({"speed": float(p["speed"]),
                     "qps_offered": float(p["qps_offered"]),
                     "qps_completed": float(p["qps_completed"]),
                     "completed_frac": round(frac, 6),
                     "sustained": frac >= min_completed_frac})
    sustained = [r for r in rows if r["sustained"]]
    base = sustained if sustained else rows
    best = max(base, key=lambda r: r["qps_completed"])
    return {
        "points": rows,
        "measured_qps": round(best["qps_completed"], 6),
        "knee_speed": best["speed"],
        "saturated": any(not r["sustained"] for r in rows),
    }


def verdict(forecast_qps: float, measured_qps: float,
            tolerance: float = DEFAULT_TOLERANCE) -> Dict[str, Any]:
    """Forecast vs measurement: ``rel_err`` is the gated
    ``capacity_forecast_rel_err``; ``ok`` is False exactly when the
    measured capacity falls SHORT of the forecast beyond tolerance
    (beating the forecast is headroom, not a failure — but it still
    counts toward rel_err, so a wildly conservative model drifts the
    gate)."""
    if forecast_qps <= 0:
        raise ValueError(f"forecast_qps={forecast_qps} must be > 0")
    rel_err = abs(measured_qps - forecast_qps) / forecast_qps
    return {
        "forecast_qps": round(float(forecast_qps), 6),
        "measured_qps": round(float(measured_qps), 6),
        "rel_err": round(rel_err, 6),
        "tolerance": float(tolerance),
        "ok": measured_qps >= forecast_qps * (1.0 - tolerance),
    }
