"""A/B run comparison + the regression gate.

``compare(base, cand)`` diffs two run documents and emits a
machine-readable verdict::

    {"metrics": {name: {"base": x, "cand": y, "rel_change": r,
                        "threshold": t, "verdict": "ok|regression|
                        improvement|missing"}},
     "regressions": [name, ...], "improvements": [...], "ok": bool}

Accepted document shapes (``extract_metrics`` normalizes; mixing
shapes is fine — a fresh run report can gate against last month's
BENCH row):

- an ``obs/aggregate.py`` run report (``kind: "run_report"``);
- a ``bench.py`` per-config row (``wall_clock_20ep_s``, ...);
- the ``bench.py`` final summary line (``metric``/``value``);
- ``BASELINE.json`` (its ``measured`` anchors);
- a ``BENCH_*.json`` driver capture (``{"tail": "..."}`` — the last
  JSON line of the captured stdout is the bench final summary);
- an ``obs/history.py`` record (``kind: "bench_history"``) or the
  rolling-median baseline (``kind: "history_baseline"``) that
  ``bench.py --gate-rolling`` builds over the last N history entries.

Thresholds are RELATIVE and one-sided: wall/step-time may grow, or
throughput/MFU/accuracy/goodput shrink, by up to the threshold before
a metric counts as a regression. ``bench.py --gate FILE`` wires this
into the bench driver (exit code 3 on regression, after every row is
written); ``dtx-obs compare`` is the standalone CLI.
"""

from __future__ import annotations

import json
from typing import Any, Dict, Optional

# metric -> (direction, default relative threshold); "lower" = smaller
# is better (wall), "higher" = bigger is better (throughput)
GATE_METRICS: Dict[str, tuple] = {
    "wall_s": ("lower", 0.05),
    "examples_per_sec": ("higher", 0.05),
    "tokens_per_sec": ("higher", 0.05),
    "mfu": ("higher", 0.05),
    "step_time_p50_ms": ("lower", 0.05),
    "goodput_frac": ("higher", 0.05),
    "test_accuracy": ("higher", 0.02),
    # the bench input-pipeline row (bench_input_pipeline): per-step
    # wall with the H2D commit on vs off the critical path, and their
    # ratio — gating these holds the device-prefetch win over time.
    # Wider default thresholds than the steady-state metrics: these
    # are medians of short interleaved A/B runs, noisier by nature
    # (tighten per-deployment via --thresholds when the host is quiet)
    "blocking_step_ms": ("lower", 0.15),
    "prefetch_step_ms": ("lower", 0.15),
    "overlap_ratio": ("higher", 0.15),
    # the fused-kernel MFU line (ISSUE 6): the per-row headline MFUs
    # that carry the TPU targets (transformer_wide >= 0.60, wide_long
    # >= 0.52, moe_wide >= 0.35) and the moe_wide dispatch-vs-expert
    # breakdown. The breakdown medians come from short standalone
    # component programs — wider 15% default like the input-pipeline
    # A/B keys above.
    "transformer_wide_mfu": ("higher", 0.05),
    "transformer_wide_long_mfu": ("higher", 0.05),
    "moe_wide_mfu": ("higher", 0.05),
    "moe_dispatch_ms": ("lower", 0.15),
    "moe_expert_ms": ("lower", 0.15),
    # pipeline bubble fractions (ISSUE 8): analytic tick-table
    # accounting from parallel/pp_schedule — deterministic on every
    # backend, so ANY upward move is a schedule regression (the tight
    # threshold is deliberate; these only change when the schedule
    # derivation itself changes)
    "pp_bubble_frac_gpipe": ("lower", 0.01),
    "pp_bubble_frac_1f1b": ("lower", 0.01),
    "pp_bubble_frac_interleaved_v2": ("lower", 0.01),
    "pp_bubble_frac_interleaved_v4": ("lower", 0.01),
    # the serving rows (ISSUE 9): request-latency p99 + aggregate
    # decode throughput from bench_serving's offered-load sweep (short
    # CPU-measured loops — wide thresholds like the other A/B rows),
    # and the decode roofline fraction (achieved vs analytic
    # weights+KV HBM bytes/step) from bench_decode — the
    # hardware-limited number VERDICT r5 #7 asked the decode row for
    "serving_p99_ms": ("lower", 0.25),
    "serving_tok_s": ("higher", 0.25),
    "decode_hbm_frac": ("higher", 0.05),
    # the multi-site local-SGD row (ISSUE 10): comm bytes per trained
    # token at H=8 is ANALYTIC (obs/flops.py closed form — like the
    # bubble fractions, any upward move is an algorithm regression,
    # hence the tight 1%); the final cost is a short measured CPU A/B
    # run, wide like the serving latencies
    "local_sgd_comm_bytes_per_token": ("lower", 0.01),
    "local_sgd_final_cost": ("lower", 0.25),
    # the quantization keys (ISSUE 11) — ALL analytic closed forms
    # (obs/flops.py), deterministic on every backend, tight 1% like
    # the bubble fractions: the int8 KV pool's bytes/step must stay
    # half the bf16 pool's, and the int8+error-feedback outer sync
    # must stay >= 3.5x below the f32 form
    "decode_kv_bytes_per_step_int8": ("lower", 0.01),
    "decode_kv_reduction_int8": ("higher", 0.01),
    "local_sgd_outer_quant_bytes_per_token": ("lower", 0.01),
    "local_sgd_outer_quant_reduction": ("higher", 0.01),
    # the async-checkpoint keys (ISSUE 13): bench_checkpoint's A/B of
    # the same numpy loop with the write-behind writer on vs off.
    # ckpt_stall_ms is the per-snapshot submit wall (a host memcpy +
    # handoff — short interleaved medians) and the overhead ratio is
    # with/without step time; both share a crowded host with the
    # writer thread's hashing, so the wide 25% A/B default applies
    # (tighten per-deployment via --thresholds when the host is quiet)
    "ckpt_stall_ms": ("lower", 0.25),
    "ckpt_overhead_ratio": ("lower", 0.25),
    # the fail-open serving keys (ISSUE 15): the completed fraction
    # of the deterministic degraded workload (deadlines + bounded
    # queue through the pure scheduler sim — a closed form like the
    # bubble fractions, tight 1%: any downward move is an
    # admission/deadline regression) and the supervised engine's p99
    # under the injected-crash plan (short CPU loops with restarts
    # baked in — the wide A/B default)
    "serving_degraded_completed_frac": ("higher", 0.01),
    "serving_degraded_p99_ms": ("lower", 0.25),
    # the span-emission overhead key (ISSUE 16): bench_trace_overhead
    # replays the SAME saturated request set through the real engine
    # with the recorder on vs off, interleaved, and the key is the
    # median of per-round on/off tok/s RATIOS — a ratio of interleaved
    # same-process arms, so host drift divides out.  Tight 1%: the
    # fleet-observability claim is that tracing costs <= 1% tok/s,
    # and the retained fraction sits at ~1.0 by construction
    "trace_retained_tok_frac": ("higher", 0.01),
    # the latency-attribution keys (ISSUE 17): both are ratios that
    # sit at ~1.0 BY CONSTRUCTION, so the tight 1% gate is an absolute
    # claim, not a noisy relative one.  waterfall_sum_to_wall_frac is
    # the MINIMUM over the chaos run's requests of (segment sum /
    # submit->terminal wall) — the waterfall partition is exact, so
    # any dip below 1 - 1e-6 means a segment went missing;
    # attribution_retained_tok_frac is tok/s with the waterfall
    # derivation running against tok/s without (the trace-overhead
    # pattern: interleaved same-process arms, host drift divides out)
    "waterfall_sum_to_wall_frac": ("higher", 0.01),
    "attribution_retained_tok_frac": ("higher", 0.01),
    # the fleet-failover keys (ISSUE 18): bench_fleet_failover drives
    # a 3-replica router fleet with one engine crashed past its retry
    # budget.  fleet_completed_frac is the completed fraction of the
    # deterministic analytic fleet (pure router over scripted
    # replicas — a closed form at 1.0, tight 1%: any dip means the
    # failover path dropped or double-delivered a request);
    # fleet_failover_p99_ms is the measured failed-over request p99
    # under the injected-crash plan (short CPU loops with restarts
    # and re-prefill baked in — the wide 25% A/B default)
    "fleet_completed_frac": ("higher", 0.01),
    "fleet_failover_p99_ms": ("lower", 0.25),
    # the workload-replay keys (ISSUE 19): bench_workload_replay
    # captures a seeded run and replays it twice through the real
    # engine.  replay_determinism_frac is the fraction of requests
    # whose typed terminal + token content matched pairwise across
    # the two replays — deterministic by construction (seeded keys,
    # greedy decode), so 1.0 with the tight 1% gate: any dip means
    # replay lost its determinism; capacity_forecast_rel_err is the
    # closed-form sustainable-QPS forecast (obs/capacity.py) against
    # the measured saturation knee from replaying at increasing
    # speed — a model-vs-measurement gap, gated at the wide 25%
    "replay_determinism_frac": ("higher", 0.01),
    "capacity_forecast_rel_err": ("lower", 0.25),
}


def _json_lines_reversed(text: str):
    for line in reversed(text.strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                yield json.loads(line)
            except ValueError:
                continue


def _last_json_line(text: str) -> Optional[Dict[str, Any]]:
    return next(_json_lines_reversed(text), None)


def extract_metrics(doc: Dict[str, Any]) -> Dict[str, float]:
    """Normalize any accepted document shape to {gate metric: value}.
    Absent metrics are simply omitted — compare() only diffs the
    intersection."""
    out: Dict[str, float] = {}

    def put(name, val):
        if isinstance(val, (int, float)) and not isinstance(val, bool):
            out[name] = float(val)

    if not isinstance(doc, dict):
        return out
    if isinstance(doc.get("tail"), str):        # BENCH_*.json capture
        # scan back past non-metric trailing lines (a --gate run's
        # verdict prints AFTER the final summary) to the newest line
        # that actually yields gate metrics
        for inner in _json_lines_reversed(doc["tail"]):
            m = extract_metrics(inner)
            if m:
                return m
        return out
    if doc.get("kind") in ("bench_history", "history_baseline"):
        # obs/history.py shapes: one recorded round, or the rolling-
        # median baseline --gate-rolling builds — the metrics dict IS
        # the already-extracted gate mapping (filtered numeric here so
        # a doctored file cannot smuggle strings into compare())
        for name, val in (doc.get("metrics") or {}).items():
            if name in GATE_METRICS:
                put(name, val)
        return out
    if doc.get("kind") == "run_report":         # aggregate.py report
        put("wall_s", doc.get("wall_s"))
        put("test_accuracy", doc.get("test_accuracy"))
        g = doc.get("goodput") or {}
        put("goodput_frac", g.get("goodput_frac"))
        st = doc.get("step_time") or {}
        put("step_time_p50_ms", st.get("p50_ms"))
        tp = doc.get("throughput") or {}
        put("examples_per_sec", tp.get("examples_per_sec_mean"))
        put("tokens_per_sec", tp.get("tokens_per_sec_last"))
        put("mfu", tp.get("mfu_mean"))
        return out
    if "measured" in doc and isinstance(doc["measured"], dict):
        # BASELINE.json: the recorded CPU anchors
        m = doc["measured"]
        put("wall_s", m.get("cpu_baseline_wall_clock_20ep_s"))
        put("test_accuracy", m.get("cpu_baseline_test_accuracy"))
        return out
    if "prefetch_step_ms" in doc:               # bench input-pipeline row
        put("prefetch_step_ms", doc.get("prefetch_step_ms"))
        put("blocking_step_ms", doc.get("blocking_step_ms"))
        put("overlap_ratio", doc.get("overlap_ratio"))
        put("test_accuracy", doc.get("test_accuracy"))
        return out
    if "1f1b_bubble_fraction" in doc:           # bench pp_memory row
        for name in ("gpipe", "1f1b", "interleaved_v2",
                     "interleaved_v4"):
            put(f"pp_bubble_frac_{name}",
                doc.get(f"{name}_bubble_fraction"))
        return out
    # bench local-SGD row — keyed on sync_comm_bytes_per_token, a
    # row-only key (the final summary carries the two GATE keys too
    # and must fall through to its own branch — the serving lesson)
    if "sync_comm_bytes_per_token" in doc:
        put("local_sgd_comm_bytes_per_token",
            doc.get("local_sgd_comm_bytes_per_token"))
        put("local_sgd_final_cost", doc.get("local_sgd_final_cost"))
        put("local_sgd_outer_quant_bytes_per_token",
            doc.get("local_sgd_outer_quant_bytes_per_token"))
        put("local_sgd_outer_quant_reduction",
            doc.get("local_sgd_outer_quant_reduction"))
        return out
    # bench decode row — keyed on decode_step_ms, a row-only key (the
    # final summary carries decode_hbm_frac too and must fall through
    # to its own branch — the serving lesson)
    if "decode_step_ms" in doc:
        put("decode_hbm_frac", doc.get("decode_hbm_frac"))
        put("tokens_per_sec", doc.get("tokens_per_sec"))
        put("wall_s", doc.get("wall_s"))
        return out
    # bench kv-quant row (every backend) — keyed on the scale-plane
    # term, a row-only key (the final summary carries the two gate
    # keys too and must fall through — the serving lesson)
    if "decode_kv_scale_bytes_per_step" in doc:
        put("decode_kv_bytes_per_step_int8",
            doc.get("decode_kv_bytes_per_step_int8"))
        put("decode_kv_reduction_int8",
            doc.get("decode_kv_reduction_int8"))
        return out
    # bench checkpoint row — keyed on ckpt_write_ms, a row-only key
    # (the final summary carries ckpt_stall_ms/ckpt_overhead_ratio
    # too and must fall through — the serving lesson)
    if "ckpt_write_ms" in doc:
        put("ckpt_stall_ms", doc.get("ckpt_stall_ms"))
        put("ckpt_overhead_ratio", doc.get("ckpt_overhead_ratio"))
        return out
    # bench serving row — keyed on continuous_ticks, NOT serving_tok_s:
    # the final summary carries serving_tok_s too, and must fall
    # through to its own branch below to keep wall_s/mfu/...
    if "continuous_ticks" in doc:
        put("serving_p99_ms", doc.get("serving_p99_ms"))
        put("serving_tok_s", doc.get("serving_tok_s"))
        put("decode_hbm_frac", doc.get("decode_hbm_frac"))
        return out
    # bench trace-overhead row — keyed on trace_on_tok_s, a row-only
    # key (the final summary carries trace_retained_tok_frac too and
    # must fall through to its own branch — the serving lesson)
    if "trace_on_tok_s" in doc:
        put("trace_retained_tok_frac",
            doc.get("trace_retained_tok_frac"))
        return out
    # bench latency-attribution row — keyed on waterfall_requests, a
    # row-only key (the final summary carries both gate keys too and
    # must fall through to its own branch — the serving lesson)
    if "waterfall_requests" in doc:
        put("waterfall_sum_to_wall_frac",
            doc.get("waterfall_sum_to_wall_frac"))
        put("attribution_retained_tok_frac",
            doc.get("attribution_retained_tok_frac"))
        return out
    # bench degraded-serving row — keyed on degraded_sim_ticks, a
    # row-only key (the final summary carries both gate keys too and
    # must fall through to its own branch — the serving lesson)
    if "degraded_sim_ticks" in doc:
        put("serving_degraded_completed_frac",
            doc.get("serving_degraded_completed_frac"))
        put("serving_degraded_p99_ms",
            doc.get("serving_degraded_p99_ms"))
        return out
    # bench fleet-failover row — keyed on fleet_failover_requests, a
    # row-only key (the final summary carries both gate keys too and
    # must fall through to its own branch — the serving lesson)
    if "fleet_failover_requests" in doc:
        put("fleet_completed_frac", doc.get("fleet_completed_frac"))
        put("fleet_failover_p99_ms",
            doc.get("fleet_failover_p99_ms"))
        return out
    # bench workload-replay row — keyed on workload_replay_requests,
    # a row-only key (the final summary carries both gate keys too
    # and must fall through to its own branch — the serving lesson)
    if "workload_replay_requests" in doc:
        put("replay_determinism_frac",
            doc.get("replay_determinism_frac"))
        put("capacity_forecast_rel_err",
            doc.get("capacity_forecast_rel_err"))
        return out
    if "wall_clock_20ep_s" in doc:              # bench per-config row
        put("wall_s", doc.get("wall_clock_20ep_s"))
        put("examples_per_sec", doc.get("examples_per_sec"))
        put("mfu", doc.get("mfu"))
        put("test_accuracy", doc.get("test_accuracy"))
        g = doc.get("goodput_summary") or {}
        put("goodput_frac", g.get("goodput_frac"))
        return out
    if "metric" in doc and "value" in doc:      # bench final summary
        put("wall_s", doc.get("value"))
        put("mfu", doc.get("mfu"))
        put("test_accuracy", doc.get("learning_accuracy"))
        # the input-pipeline keys ride the final line (input_pipeline_*
        # prefix there), so --gate holds the prefetch win too
        put("blocking_step_ms", doc.get("input_pipeline_blocking_step_ms"))
        put("prefetch_step_ms", doc.get("input_pipeline_prefetch_step_ms"))
        put("overlap_ratio", doc.get("input_pipeline_overlap_ratio"))
        # the fused-kernel MFU keys + the moe_wide breakdown carry
        # their final-line names verbatim
        for k in ("transformer_wide_mfu", "transformer_wide_long_mfu",
                  "moe_wide_mfu", "moe_dispatch_ms", "moe_expert_ms",
                  "pp_bubble_frac_gpipe", "pp_bubble_frac_1f1b",
                  "pp_bubble_frac_interleaved_v2",
                  "pp_bubble_frac_interleaved_v4",
                  # the serving/decode-roofline keys (ISSUE 9) ride
                  # the final line under their gate names verbatim
                  "serving_p99_ms", "serving_tok_s",
                  "decode_hbm_frac",
                  # the multi-site local-SGD keys (ISSUE 10) likewise
                  "local_sgd_comm_bytes_per_token",
                  "local_sgd_final_cost",
                  # the quantization closed forms (ISSUE 11): int8 KV
                  # pool bytes/step + the compressed outer sync
                  "decode_kv_bytes_per_step_int8",
                  "decode_kv_reduction_int8",
                  "local_sgd_outer_quant_bytes_per_token",
                  "local_sgd_outer_quant_reduction",
                  # the async-checkpoint overhead keys (ISSUE 13)
                  "ckpt_stall_ms", "ckpt_overhead_ratio",
                  # the fail-open serving keys (ISSUE 15): degraded
                  # goodput closed form + supervised crash-plan p99
                  "serving_degraded_completed_frac",
                  "serving_degraded_p99_ms",
                  # the span-emission overhead key (ISSUE 16)
                  "trace_retained_tok_frac",
                  # the latency-attribution keys (ISSUE 17): the
                  # chaos run's sum-to-wall minimum + the waterfall-
                  # derivation overhead ratio
                  "waterfall_sum_to_wall_frac",
                  "attribution_retained_tok_frac",
                  # the fleet-failover keys (ISSUE 18): analytic
                  # fleet completed fraction + measured failover p99
                  "fleet_completed_frac",
                  "fleet_failover_p99_ms",
                  # the workload-replay keys (ISSUE 19): two-replay
                  # determinism + capacity forecast vs measured knee
                  "replay_determinism_frac",
                  "capacity_forecast_rel_err"):
            put(k, doc.get(k))
        return out
    # last resort: any directly-named gate metrics
    for name in GATE_METRICS:
        put(name, doc.get(name))
    return out


def compare(base: Dict[str, Any], cand: Dict[str, Any],
            thresholds: Optional[Dict[str, float]] = None,
            default_threshold: Optional[float] = None) -> Dict[str, Any]:
    """Diff two documents (any accepted shape). ``thresholds``
    overrides per metric; ``default_threshold`` overrides every
    metric's default at once."""
    b, c = extract_metrics(base), extract_metrics(cand)
    metrics: Dict[str, Any] = {}
    regressions, improvements = [], []
    for name, (direction, thr) in GATE_METRICS.items():
        if default_threshold is not None:
            thr = default_threshold
        if thresholds and name in thresholds:
            thr = thresholds[name]
        if name not in b or name not in c:
            if name in b or name in c:
                metrics[name] = {"base": b.get(name), "cand": c.get(name),
                                 "verdict": "missing"}
            continue
        bv, cv = b[name], c[name]
        if bv == 0 and cv != 0:
            # no finite relative change exists against a zero
            # baseline (a broken/aborted baseline run) — report it
            # without fabricating Infinity (non-strict JSON) and
            # without gating on it
            metrics[name] = {"base": bv, "cand": cv,
                             "rel_change": None, "threshold": thr,
                             "direction": direction,
                             "verdict": "incomparable"}
            continue
        rel = (cv - bv) / abs(bv) if bv else 0.0
        worse = rel > thr if direction == "lower" else rel < -thr
        better = rel < -thr if direction == "lower" else rel > thr
        verdict = ("regression" if worse
                   else "improvement" if better else "ok")
        metrics[name] = {"base": bv, "cand": cv,
                         "rel_change": round(rel, 6),
                         "threshold": thr, "direction": direction,
                         "verdict": verdict}
        if worse:
            regressions.append(name)
        elif better:
            improvements.append(name)
    return {
        "metrics": metrics,
        "compared": sorted(k for k, v in metrics.items()
                           if v.get("verdict") != "missing"),
        "regressions": regressions,
        "improvements": improvements,
        "ok": not regressions,
    }


def load_doc(path: str) -> Dict[str, Any]:
    """Read a comparison document from disk: JSON file, or a logs
    directory (aggregated on the fly)."""
    import os

    if os.path.isdir(path):
        from .aggregate import aggregate

        return aggregate(path)
    with open(path) as f:
        text = f.read()
    try:
        return json.loads(text)
    except ValueError:
        # a captured stdout file: hand it over capture-shaped so
        # extract_metrics scans back to the newest metric-bearing
        # JSON line (skipping e.g. a trailing --gate verdict)
        if _last_json_line(text) is None:
            raise ValueError(f"{path}: neither JSON nor a text capture "
                             f"with a JSON tail line")
        return {"tail": text}
