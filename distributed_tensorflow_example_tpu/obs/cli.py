"""``dtx-obs`` — the operator CLI over the obs/ telemetry.

Subcommands (``dtx-obs <cmd> --help`` for flags):

- ``report LOGS``   — aggregate a run's logs into the run report
  (obs/aggregate.py): goodput decomposition, step-time percentiles,
  throughput/MFU, anomaly timeline. ``--summary`` prints the one-line
  form instead of JSON;
- ``compare BASE CAND`` — A/B two runs/reports/bench rows
  (obs/compare.py); exit 3 on regression — usable directly as a CI
  gate;
- ``tail LOGS``     — one line per metrics window (plus anomaly/
  run_end events), ``-f`` to follow a live run;
- ``serve LOGS``    — (re-)serve a run directory over HTTP: /status,
  /metrics (Prometheus), /report, /slo, /trace (obs/serve.py). Works
  identically on a finished run and alongside a live one;
- ``validate PATH...`` — run the obs/schema.py validators over
  metrics/span/history JSONL files / flight dumps / run reports /
  whole logs dirs; exit 1 on drift, 2 on unreadable input, with the
  precise schema-version diagnosis for old-format logs;
- ``slo LOGS``      — evaluate the obs/slo.py specs over the serving
  span stream; exit 3 on breach (the compare regression convention);
- ``trace LOGS RID`` — one request's reconstructed lifecycle from the
  span stream (submit → blocked/admit → prefill → first_token →
  decode ticks → retire), with the raw events;
- ``history FILE``  — the rolling bench history (obs/history.py):
  trend table by default, ``--import`` backfills from committed
  BENCH captures, ``--append`` records any comparison document.

Exit codes: 0 ok; 1 validation failure; 2 bad input (missing files,
no metrics stream); 3 regression/SLO-breach verdict (compare, slo).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import time
from typing import Any, Dict, List, Optional

from . import aggregate as agg_lib
from . import compare as cmp_lib
from . import schema as schema_lib
from . import serve as serve_lib


def _fmt(v, nd=4) -> str:
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:.{nd}g}"
    return str(v)


def format_row(row: Dict[str, Any]) -> Optional[str]:
    """One terminal line per window row; anomaly/stragglers/run_end
    events and serving span rows ride along; other rows (compile
    etc.) map to None."""
    kind = row.get("kind")
    proc = row.get("proc", "?")
    if kind == "span":
        ev = row.get("event")
        if ev == "tick":
            return (f"[p{proc}] tick {_fmt(row.get('tick'))} "
                    f"batch {_fmt(row.get('batch'))}/"
                    f"{_fmt(row.get('batch_bucket'))} "
                    f"kv_pages {_fmt(row.get('kv_pages'))} "
                    f"occ {_fmt(row.get('occupancy'))}")
        if ev == "engine_restart":
            # batch-shaped like tick: no single rid
            return (f"[p{proc}] ENGINE RESTART "
                    f"{_fmt(row.get('restart'))} "
                    f"inflight {len(row.get('rids') or ())} "
                    f"({_fmt(row.get('reason'))})")
        bits = [f"[p{proc}] rid {_fmt(row.get('rid'))} {ev}"]
        for key, label in (("reason", ""), ("pages_held", "pages="),
                           ("bucket", "bucket="),
                           ("ttft_ms", "ttft_ms="),
                           ("generated", "generated="),
                           ("attempt", "attempt="),
                           ("attempts", "attempts="),
                           ("queued", "queued="),
                           ("tick", "tick=")):
            if row.get(key) is not None:
                bits.append(f"{label}{_fmt(row[key])}")
        return " ".join(bits)
    if kind == "window":
        return (f"[p{proc}] step {_fmt(row.get('step'))} "
                f"ep {_fmt(row.get('epoch'))} "
                f"cost {_fmt(row.get('cost'))} "
                f"p50 {_fmt(row.get('step_time_p50_ms'))}ms "
                f"p95 {_fmt(row.get('step_time_p95_ms'))}ms "
                f"ex/s {_fmt(row.get('examples_per_sec'))} "
                f"mfu {_fmt(row.get('mfu'))}")
    if kind == "event":
        ev = row.get("event")
        if ev == "anomaly":
            return (f"[p{proc}] ANOMALY step {_fmt(row.get('step'))} "
                    f"{','.join(row.get('reasons') or [])} "
                    f"policy={row.get('policy')}")
        if ev == "stragglers":
            return (f"[p{proc}] stragglers: lag "
                    f"{_fmt(row.get('max_step_lag'))} steps "
                    f"(slowest p{_fmt(row.get('slowest_proc'))})")
        if ev == "run_end":
            return (f"[p{proc}] run_end: steps {_fmt(row.get('steps'))} "
                    f"wall {_fmt(row.get('total_time_s'))}s "
                    f"acc {_fmt(row.get('test_accuracy'))}")
    return None


def _metrics_files(logs_path: str) -> List[str]:
    return [path for _pid, path in agg_lib.metrics_files(logs_path)]


def _stream_files(logs_path: str) -> List[str]:
    """Every JSONL stream tail/validate watch: the metrics streams
    plus the serving span streams (same whole-line discipline)."""
    from . import spans as spans_lib

    return _metrics_files(logs_path) + [
        path for _pid, path in spans_lib.span_files(logs_path)]


def cmd_report(args) -> int:
    try:
        report = agg_lib.aggregate(args.logs_path)
    except FileNotFoundError as e:
        print(f"dtx-obs report: {e}", file=sys.stderr)
        return 2
    if args.out:
        with open(args.out, "w") as f:
            json.dump(report, f, indent=1)
            f.write("\n")
    if args.summary:
        print(agg_lib.summary_line(report))
    elif not args.out:
        print(json.dumps(report, indent=None if args.compact else 1))
    if report["schema_error_count"] and not args.summary:
        print(f"NOTE: {report['schema_error_count']} schema error(s) — "
              f"see report['schema_errors']", file=sys.stderr)
    return 0


def cmd_compare(args) -> int:
    try:
        base = cmp_lib.load_doc(args.base)
        cand = cmp_lib.load_doc(args.cand)
    except (OSError, ValueError) as e:
        print(f"dtx-obs compare: {e}", file=sys.stderr)
        return 2
    thresholds = {}
    for spec in (args.thresholds or "").split(","):
        if not spec.strip():
            continue
        name, _, val = spec.partition("=")
        if name.strip() not in cmp_lib.GATE_METRICS:
            print(f"dtx-obs compare: unknown metric {name.strip()!r} "
                  f"(known: {sorted(cmp_lib.GATE_METRICS)})",
                  file=sys.stderr)
            return 2
        try:
            thresholds[name.strip()] = float(val)
        except ValueError:
            print(f"dtx-obs compare: bad threshold {spec.strip()!r} "
                  f"(want NAME=REL, e.g. wall_s=0.1)", file=sys.stderr)
            return 2
    verdict = cmp_lib.compare(base, cand, thresholds=thresholds or None,
                              default_threshold=args.threshold)
    print(json.dumps(verdict, indent=None if args.compact else 1))
    if not verdict["compared"]:
        print("dtx-obs compare: no overlapping metrics between the two "
              "documents", file=sys.stderr)
        return 2
    return 0 if verdict["ok"] else 3


def cmd_tail(args) -> int:
    files = _stream_files(args.logs_path)
    if not files and not args.follow:
        print(f"dtx-obs tail: no metrics.<proc>.jsonl or "
              f"spans.<proc>.jsonl under {args.logs_path!r}",
              file=sys.stderr)
        return 2
    # print the last -n formatted lines across streams, then follow
    offsets: Dict[str, int] = {}
    backlog: List[tuple] = []
    for path in files:
        rows = serve_lib.tail_rows(path)
        offsets[path] = os.path.getsize(path)
        for r in rows:
            line = format_row(r)
            if line is not None:
                backlog.append((r.get("t") or 0.0, line))
    backlog.sort()
    for _, line in backlog[-args.lines:]:
        print(line)
    if not args.follow:
        return 0
    try:
        while True:
            time.sleep(args.interval)
            for path in _stream_files(args.logs_path):
                off = offsets.get(path, 0)
                try:
                    size = os.path.getsize(path)
                    if size <= off:
                        continue
                    with open(path, "rb") as f:
                        f.seek(off)
                        data = f.read()
                    # consume only whole lines: a poll landing mid-
                    # append must leave the torn tail for next time,
                    # not split it into two unparseable halves
                    nl = data.rfind(b"\n")
                    if nl < 0:
                        continue
                    chunk = data[:nl + 1].decode("utf-8",
                                                 errors="replace")
                    offsets[path] = off + nl + 1
                except OSError:
                    continue
                for ln in chunk.splitlines():
                    try:
                        line = format_row(json.loads(ln))
                    except ValueError:
                        continue
                    if line is not None:
                        print(line, flush=True)
    except KeyboardInterrupt:
        return 0


def cmd_serve(args) -> int:
    srv = serve_lib.StatusServer(args.logs_path)
    port = srv.start(args.port, host=args.host)
    if port is None:
        return 2
    print(f"dtx-obs serve: http://{args.host or 'localhost'}:{port}"
          f"  (/status /metrics /report)  logs={args.logs_path}")
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        return 0
    finally:
        srv.close()


def _validate_one(path: str) -> List[str]:
    """Route one file to the right obs/schema.py validator by shape."""
    base = os.path.basename(path)
    if base.endswith(".jsonl"):
        if base.startswith("spans."):
            return schema_lib.validate_span_file(path)
        if base.startswith("metrics."):
            return schema_lib.validate_metrics_file(path)
        if base.startswith("restarts"):
            return schema_lib.validate_restart_file(path)
        # an unnamed JSONL: route by its first WELL-FORMED row's kind
        # (history files travel under arbitrary names; a torn first
        # line — a crashed writer — must not misroute the rest)
        kind = None
        try:
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        row = json.loads(line)
                    except ValueError:
                        continue
                    if isinstance(row, dict):
                        kind = row.get("kind")
                        break
        except OSError as e:
            return [f"{path}: unreadable ({e})"]
        if kind == "span":
            return schema_lib.validate_span_file(path)
        if kind == "bench_history":
            return schema_lib.validate_history_file(path)
        if kind == "restart":
            return schema_lib.validate_restart_file(path)
        return schema_lib.validate_metrics_file(path)
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        return [f"{path}: unreadable ({e})"]
    if isinstance(doc, dict) and doc.get("kind") == "run_report":
        return schema_lib.validate_run_report(doc, where=path)
    if base == "report.json":
        # the chief's collate() post-mortem, not a per-proc dump: it
        # has its own (version-stamped) shape — check the version only
        return schema_lib.validate_version(doc, "version", where=path)
    return schema_lib.validate_flight_dump(doc, where=path)


def cmd_validate(args) -> int:
    targets: List[str] = []
    for path in args.paths:
        if os.path.isdir(path):
            targets += _stream_files(path)
            restarts = os.path.join(path, "restarts.jsonl")
            if os.path.isfile(restarts):
                targets.append(restarts)
            targets += sorted(glob.glob(os.path.join(path, "flight",
                                                     "*.json")))
        elif os.path.exists(path):
            targets.append(path)
        else:
            print(f"dtx-obs validate: {path}: no such file",
                  file=sys.stderr)
            return 2
    if not targets:
        print("dtx-obs validate: nothing to validate", file=sys.stderr)
        return 2
    failed = 0
    for path in targets:
        errs = _validate_one(path)
        if errs:
            failed += 1
            print(f"FAIL {path}")
            for e in errs[:args.max_errors]:
                print(f"  {e}")
            if len(errs) > args.max_errors:
                print(f"  ... {len(errs) - args.max_errors} more")
        else:
            print(f"OK   {path}")
    return 1 if failed else 0


def cmd_slo(args) -> int:
    from . import slo as slo_lib
    from . import spans as spans_lib

    try:
        specs = slo_lib.parse_specs(args.spec)
    except ValueError as e:
        print(f"dtx-obs slo: {e}", file=sys.stderr)
        return 2
    rows = spans_lib.load_spans(args.logs_path)
    if not rows:
        print(f"dtx-obs slo: no spans.<proc>.jsonl under "
              f"{args.logs_path!r} — was the engine started with "
              f"--trace_spans?", file=sys.stderr)
        return 2
    doc = slo_lib.evaluate(slo_lib.records_from_spans(rows),
                           specs=specs)
    print(json.dumps(doc, indent=None if args.compact else 1))
    if doc["breaches"]:
        print(f"dtx-obs slo: BREACH {','.join(doc['breaches'])}",
              file=sys.stderr)
        return 3
    return 0


def cmd_trace(args) -> int:
    from . import spans as spans_lib

    rows = spans_lib.load_spans(args.logs_path)
    if not rows:
        print(f"dtx-obs trace: no spans.<proc>.jsonl under "
              f"{args.logs_path!r}", file=sys.stderr)
        return 2
    doc = spans_lib.trace_record(rows, args.rid)
    if doc is None:
        print(f"dtx-obs trace: rid {args.rid} not in the span stream",
              file=sys.stderr)
        return 2
    print(json.dumps(doc, indent=None if args.compact else 1))
    return 0


def cmd_history(args) -> int:
    from . import history as hist_lib

    rc = 0
    if args.imports:
        appended, skipped = hist_lib.import_captures(args.history,
                                                     args.imports)
        print(f"dtx-obs history: imported {appended} capture(s), "
              f"skipped {len(skipped)}", file=sys.stderr)
        for msg in skipped:
            print(f"  {msg}", file=sys.stderr)
    if args.append:
        try:
            doc = cmp_lib.load_doc(args.append)
        except (OSError, ValueError) as e:
            print(f"dtx-obs history: {e}", file=sys.stderr)
            return 2
        entry = hist_lib.append_entry(
            args.history, doc,
            label=os.path.splitext(os.path.basename(args.append))[0],
            source=args.append)
        if not entry["metrics"]:
            print(f"dtx-obs history: {args.append}: no gate metrics "
                  f"extractable (recorded an empty entry)",
                  file=sys.stderr)
            rc = 1
    entries = hist_lib.read_history(args.history)
    if not entries:
        print(f"dtx-obs history: no entries in {args.history!r}",
              file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps(entries, indent=1))
    else:
        metrics = ([m.strip() for m in args.metrics.split(",")
                    if m.strip()] if args.metrics else None)
        print(hist_lib.trend_table(entries, metrics=metrics,
                                   last=args.last))
    return rc


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="dtx-obs",
        description="run analytics over the obs/ telemetry: goodput "
                    "reports, A/B regression gating, live tail/serve, "
                    "schema validation")
    sub = p.add_subparsers(dest="cmd", required=True)

    r = sub.add_parser("report", help="aggregate a run into the run "
                                      "report (goodput decomposition)")
    r.add_argument("logs_path")
    r.add_argument("--summary", action="store_true",
                   help="one-line summary instead of JSON")
    r.add_argument("--compact", action="store_true",
                   help="single-line JSON")
    r.add_argument("-o", "--out", default="",
                   help="also write the JSON report to this file")
    r.set_defaults(fn=cmd_report)

    c = sub.add_parser("compare", help="A/B two runs; exit 3 on "
                                       "regression")
    c.add_argument("base", help="baseline: logs dir, run report JSON, "
                                "bench row/summary, BASELINE.json or "
                                "BENCH_*.json capture")
    c.add_argument("cand", help="candidate (same shapes)")
    c.add_argument("--threshold", type=float, default=None,
                   help="relative threshold for EVERY metric "
                        "(default: per-metric, 0.05 perf / 0.02 "
                        "accuracy)")
    c.add_argument("--thresholds", default="",
                   metavar="NAME=REL,...",
                   help="per-metric overrides, e.g. wall_s=0.1,mfu=0.02")
    c.add_argument("--compact", action="store_true")
    c.set_defaults(fn=cmd_compare)

    t = sub.add_parser("tail", help="one line per metrics window")
    t.add_argument("logs_path")
    t.add_argument("-n", "--lines", type=int, default=20)
    t.add_argument("-f", "--follow", action="store_true",
                   help="keep following a live run")
    t.add_argument("--interval", type=float, default=2.0,
                   help="follow poll interval seconds")
    t.set_defaults(fn=cmd_tail)

    s = sub.add_parser("serve", help="serve /status /metrics /report "
                                     "over HTTP (works on finished "
                                     "runs)")
    s.add_argument("logs_path")
    s.add_argument("--port", type=int, default=8321)
    s.add_argument("--host", default="",
                   help="bind address (default: all interfaces)")
    s.set_defaults(fn=cmd_serve)

    v = sub.add_parser("validate", help="schema-validate metrics/"
                                        "spans/history/flight/report "
                                        "files or a whole logs dir")
    v.add_argument("paths", nargs="+")
    v.add_argument("--max-errors", type=int, default=10,
                   help="errors printed per file")
    v.set_defaults(fn=cmd_validate)

    o = sub.add_parser("slo", help="evaluate the serving SLOs over "
                                   "the span stream; exit 3 on "
                                   "breach")
    o.add_argument("logs_path")
    o.add_argument("--spec", default="",
                   metavar="NAME<=VALUE,...",
                   help="SLO specs (ttft_p99_ms<=MS, "
                        "latency_p99_ms<=MS, error_rate<=FRAC); "
                        "empty = the obs/slo.py defaults")
    o.add_argument("--compact", action="store_true")
    o.set_defaults(fn=cmd_slo)

    tr = sub.add_parser("trace", help="one request's reconstructed "
                                      "lifecycle from the span "
                                      "stream")
    tr.add_argument("logs_path")
    tr.add_argument("rid", type=int)
    tr.add_argument("--compact", action="store_true")
    tr.set_defaults(fn=cmd_trace)

    h = sub.add_parser("history", help="rolling bench history: trend "
                                       "table, --import backfill, "
                                       "--append recording")
    h.add_argument("history", help="the history.jsonl file")
    h.add_argument("--import", dest="imports", nargs="+", default=[],
                   metavar="CAPTURE",
                   help="backfill from BENCH_*.json captures (or any "
                        "comparison document); idempotent per label")
    h.add_argument("--append", default="",
                   metavar="DOC",
                   help="record one comparison document (bench "
                        "summary / run report / capture) as a new "
                        "entry")
    h.add_argument("--last", type=int, default=0,
                   help="show only the newest N entries")
    h.add_argument("--metrics", default="",
                   metavar="NAME,...",
                   help="trend-table columns (default: the headline "
                        "set present in the file)")
    h.add_argument("--json", action="store_true",
                   help="dump the raw entries instead of the table")
    h.set_defaults(fn=cmd_history)
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
