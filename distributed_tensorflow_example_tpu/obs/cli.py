"""``dtx-obs`` — the operator CLI over the obs/ telemetry.

Subcommands (``dtx-obs <cmd> --help`` for flags):

- ``report LOGS``   — aggregate a run's logs into the run report
  (obs/aggregate.py): goodput decomposition, step-time percentiles,
  throughput/MFU, anomaly timeline. ``--summary`` prints the one-line
  form instead of JSON;
- ``compare BASE CAND`` — A/B two runs/reports/bench rows
  (obs/compare.py); exit 3 on regression — usable directly as a CI
  gate;
- ``tail LOGS``     — one line per metrics window (plus anomaly/
  run_end events), ``-f`` to follow a live run;
- ``serve LOGS``    — (re-)serve a run directory over HTTP: /status,
  /metrics (Prometheus), /report, /slo, /trace (obs/serve.py). Works
  identically on a finished run and alongside a live one;
- ``validate PATH...`` — run the obs/schema.py validators over
  metrics/span/history JSONL files / flight dumps / run reports /
  whole logs dirs; exit 1 on drift, 2 on unreadable input, with the
  precise schema-version diagnosis for old-format logs;
- ``slo LOGS``      — evaluate the obs/slo.py specs over the serving
  span stream; exit 3 on breach (the compare regression convention);
- ``trace LOGS RID`` — one request's reconstructed lifecycle from the
  span stream (submit → blocked/admit → prefill → first_token →
  decode ticks → retire), with the raw events; ``--export chrome``
  renders the WHOLE merged timeline as Chrome trace-event JSON
  (openable in ui.perfetto.dev) instead — RID optional there;
- ``collect PATH...`` — merge N run dirs' span/metrics/restart
  streams into one causally-ordered fleet timeline
  (obs/collector.py): skew-aligned, ``source``-stamped, printed as
  tail lines (or ``--json`` rows);
- ``fleet PATH...``  — the fleet report over merged streams:
  per-source accounting, the fleet-wide exactly-once verdict and the
  federated SLO evaluation; exit 3 on an SLO breach, a federated-
  identity violation or an exactly-once violation;
- ``history FILE``  — the rolling bench history (obs/history.py):
  trend table by default, ``--import`` backfills from committed
  BENCH captures, ``--append`` records any comparison document;
- ``explain LOGS`` — per-request latency waterfalls (obs/
  waterfall.py): disjoint segments that provably sum to submit ->
  terminal wall, ``--rid N`` / ``--trace ID`` to focus one request,
  ``--fleet`` for the queueing analytics (arrival rate, per-bucket
  service time, Little's-law check) instead;
- ``drift HISTORY`` — change-point detection over the bench history
  (obs/drift.py): names the metric, the window and the FIRST
  offending row; ``--capture`` joins the roofline closed forms; exit
  3 on confirmed drift (the compare regression convention);
- ``capture RUN`` — distill a run's span stream (single engine or a
  fleet parent dir) into a portable WORKLOAD document
  (obs/workload.py, schema v10): per-request arrival offsets, token
  counts, deadlines and prompt fingerprints — the input to ``dtx-serve
  --replay`` and ``capacity``;
- ``capacity WORKLOAD`` — closed-form capacity forecast
  (obs/capacity.py): sustainable QPS per replica and required
  replicas off the workload shape and a service rate;
  ``--measured-qps`` joins a replayed saturation knee and exits 3
  when measurement falls short of forecast beyond tolerance.

``tail``/``explain`` take ``--workload WID`` to isolate rows a replay
stamped with ``replay_of: WID``.

Exit codes: 0 ok; 1 validation failure; 2 bad input (missing files,
no metrics stream); 3 regression/SLO-breach/fleet-invariant/drift/
capacity verdict (compare, slo, fleet, drift, capacity).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import time
from typing import Any, Dict, List, Optional

from . import aggregate as agg_lib
from . import compare as cmp_lib
from . import schema as schema_lib
from . import serve as serve_lib


def _fmt(v, nd=4) -> str:
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:.{nd}g}"
    return str(v)


def format_row(row: Dict[str, Any]) -> Optional[str]:
    """One terminal line per window row; anomaly/stragglers/run_end
    events and serving span rows ride along; other rows (compile
    etc.) map to None."""
    kind = row.get("kind")
    proc = row.get("proc", "?")
    if kind == "span":
        ev = row.get("event")
        if ev == "tick":
            return (f"[p{proc}] tick {_fmt(row.get('tick'))} "
                    f"batch {_fmt(row.get('batch'))}/"
                    f"{_fmt(row.get('batch_bucket'))} "
                    f"kv_pages {_fmt(row.get('kv_pages'))} "
                    f"occ {_fmt(row.get('occupancy'))}")
        if ev == "engine_restart":
            # batch-shaped like tick: no single rid
            return (f"[p{proc}] ENGINE RESTART "
                    f"{_fmt(row.get('restart'))} "
                    f"inflight {len(row.get('rids') or ())} "
                    f"({_fmt(row.get('reason'))})")
        if ev == "phase":
            # the training-side span: no rid, a registered phase name
            return (f"[p{proc}] phase {row.get('phase')} "
                    f"dur {_fmt(row.get('dur_ms'))}ms")
        if ev == "tick_done":
            # batch-shaped like tick: no rid, the execute duration
            return (f"[p{proc}] tick_done {_fmt(row.get('tick'))} "
                    f"dur {_fmt(row.get('dur_ms'))}ms")
        bits = [f"[p{proc}] rid {_fmt(row.get('rid'))} {ev}"]
        for key, label in (("reason", ""), ("pages_held", "pages="),
                           ("replay_of", "replay_of="),
                           ("bucket", "bucket="),
                           ("ttft_ms", "ttft_ms="),
                           ("generated", "generated="),
                           ("attempt", "attempt="),
                           ("attempts", "attempts="),
                           ("queued", "queued="),
                           ("tick", "tick=")):
            if row.get(key) is not None:
                bits.append(f"{label}{_fmt(row[key])}")
        return " ".join(bits)
    if kind == "window":
        return (f"[p{proc}] step {_fmt(row.get('step'))} "
                f"ep {_fmt(row.get('epoch'))} "
                f"cost {_fmt(row.get('cost'))} "
                f"p50 {_fmt(row.get('step_time_p50_ms'))}ms "
                f"p95 {_fmt(row.get('step_time_p95_ms'))}ms "
                f"ex/s {_fmt(row.get('examples_per_sec'))} "
                f"mfu {_fmt(row.get('mfu'))}")
    if kind == "event":
        ev = row.get("event")
        if ev == "anomaly":
            return (f"[p{proc}] ANOMALY step {_fmt(row.get('step'))} "
                    f"{','.join(row.get('reasons') or [])} "
                    f"policy={row.get('policy')}")
        if ev == "stragglers":
            return (f"[p{proc}] stragglers: lag "
                    f"{_fmt(row.get('max_step_lag'))} steps "
                    f"(slowest p{_fmt(row.get('slowest_proc'))})")
        if ev == "run_end":
            return (f"[p{proc}] run_end: steps {_fmt(row.get('steps'))} "
                    f"wall {_fmt(row.get('total_time_s'))}s "
                    f"acc {_fmt(row.get('test_accuracy'))}")
    return None


def _metrics_files(logs_path: str) -> List[str]:
    return [path for _pid, path in agg_lib.metrics_files(logs_path)]


def _stream_files(logs_path: str) -> List[str]:
    """Every JSONL stream tail/validate watch: the metrics streams
    plus the serving span streams (same whole-line discipline)."""
    from . import spans as spans_lib

    return _metrics_files(logs_path) + [
        path for _pid, path in spans_lib.span_files(logs_path)]


def cmd_report(args) -> int:
    try:
        report = agg_lib.aggregate(args.logs_path)
    except FileNotFoundError as e:
        print(f"dtx-obs report: {e}", file=sys.stderr)
        return 2
    if args.out:
        with open(args.out, "w") as f:
            json.dump(report, f, indent=1)
            f.write("\n")
    if args.summary:
        print(agg_lib.summary_line(report))
    elif not args.out:
        print(json.dumps(report, indent=None if args.compact else 1))
    if report["schema_error_count"] and not args.summary:
        print(f"NOTE: {report['schema_error_count']} schema error(s) — "
              f"see report['schema_errors']", file=sys.stderr)
    return 0


def cmd_compare(args) -> int:
    try:
        base = cmp_lib.load_doc(args.base)
        cand = cmp_lib.load_doc(args.cand)
    except (OSError, ValueError) as e:
        print(f"dtx-obs compare: {e}", file=sys.stderr)
        return 2
    thresholds = {}
    for spec in (args.thresholds or "").split(","):
        if not spec.strip():
            continue
        name, _, val = spec.partition("=")
        if name.strip() not in cmp_lib.GATE_METRICS:
            print(f"dtx-obs compare: unknown metric {name.strip()!r} "
                  f"(known: {sorted(cmp_lib.GATE_METRICS)})",
                  file=sys.stderr)
            return 2
        try:
            thresholds[name.strip()] = float(val)
        except ValueError:
            print(f"dtx-obs compare: bad threshold {spec.strip()!r} "
                  f"(want NAME=REL, e.g. wall_s=0.1)", file=sys.stderr)
            return 2
    verdict = cmp_lib.compare(base, cand, thresholds=thresholds or None,
                              default_threshold=args.threshold)
    print(json.dumps(verdict, indent=None if args.compact else 1))
    if not verdict["compared"]:
        print("dtx-obs compare: no overlapping metrics between the two "
              "documents", file=sys.stderr)
        return 2
    return 0 if verdict["ok"] else 3


def poll_new_lines(path: str, state: Dict[str, tuple]) -> List[str]:
    """One follow-poll over ``path``: the newly appended WHOLE lines
    since the recorded position.  ``state`` maps path -> (inode,
    offset) and is updated in place.

    The rotation/truncation fix (PR 16): a live stream that rotates
    (the file we were offset into got renamed away and a fresh one
    took its name — new inode) or truncates (size < our offset) used
    to make the follow loop silently go quiet forever, because the
    stale offset never passed the ``size > offset`` check again.  Both
    regressions now RESET the offset to 0 and re-read the replacement
    from its start.  Only whole lines are consumed: a poll landing
    mid-append leaves the torn tail for next time, not split into two
    unparseable halves."""
    ino, off = state.get(path, (None, 0))
    try:
        st = os.stat(path)
        if ino is not None and (st.st_ino != ino or st.st_size < off):
            off = 0
        if st.st_size <= off:
            state[path] = (st.st_ino, off)
            return []
        with open(path, "rb") as f:
            f.seek(off)
            data = f.read()
    except OSError:
        return []
    nl = data.rfind(b"\n")
    if nl < 0:
        state[path] = (st.st_ino, off)
        return []
    state[path] = (st.st_ino, off + nl + 1)
    return data[:nl + 1].decode("utf-8",
                                errors="replace").splitlines()


def _tail_match(row: Dict[str, Any], rid: Optional[int],
                trace: Optional[str],
                workload: Optional[str] = None) -> bool:
    """The ``tail --rid/--trace/--workload`` filter: span rows about
    the request (directly, or as a member of a batch row's ``rids``),
    or — for ``--workload`` — rows a replay stamped with
    ``replay_of``.  With no filter every row passes; with one,
    non-span rows are noise."""
    if rid is None and trace is None and workload is None:
        return True
    if row.get("kind") != "span":
        return False
    if rid is not None and row.get("rid") != rid \
            and rid not in (row.get("rids") or ()):
        return False
    if trace is not None and row.get("trace_id") != trace:
        return False
    if workload is not None and row.get("replay_of") != workload:
        return False
    return True


def cmd_tail(args) -> int:
    files = _stream_files(args.logs_path)
    if not files and not args.follow:
        print(f"dtx-obs tail: no metrics.<proc>.jsonl or "
              f"spans.<proc>.jsonl under {args.logs_path!r}",
              file=sys.stderr)
        return 2
    # print the last -n formatted lines across streams, then follow
    from . import spans as spans_lib

    state: Dict[str, tuple] = {}
    backlog: List[tuple] = []
    for path in files:
        # a span stream's backlog spans its rotation boundary: the
        # rotated-away segments (oldest-first) feed the same sorted
        # backlog the live file does — only the live file is followed
        rows = []
        for seg in spans_lib.rotated_files(path)[:-1]:
            rows.extend(serve_lib.tail_rows(seg))
        rows.extend(serve_lib.tail_rows(path))
        try:
            st = os.stat(path)
            state[path] = (st.st_ino, st.st_size)
        except OSError:
            pass
        for r in rows:
            if not _tail_match(r, args.rid, args.trace or None,
                               args.workload or None):
                continue
            line = format_row(r)
            if line is not None:
                backlog.append((r.get("t") or 0.0, line))
    backlog.sort()
    for _, line in backlog[-args.lines:]:
        print(line)
    if not args.follow:
        return 0
    try:
        while True:
            time.sleep(args.interval)
            for path in _stream_files(args.logs_path):
                for ln in poll_new_lines(path, state):
                    try:
                        row = json.loads(ln)
                    except ValueError:
                        continue
                    if not isinstance(row, dict) or not _tail_match(
                            row, args.rid, args.trace or None,
                            args.workload or None):
                        continue
                    line = format_row(row)
                    if line is not None:
                        print(line, flush=True)
    except KeyboardInterrupt:
        return 0


def cmd_serve(args) -> int:
    srv = serve_lib.StatusServer(args.logs_path,
                                 cache_ttl_s=args.cache_s)
    port = srv.start(args.port, host=args.host)
    if port is None:
        return 2
    print(f"dtx-obs serve: http://{args.host or 'localhost'}:{port}"
          f"  (/status /metrics /report)  logs={args.logs_path}")
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        return 0
    finally:
        srv.close()


def _validate_one(path: str) -> List[str]:
    """Route one file to the right obs/schema.py validator by shape."""
    base = os.path.basename(path)
    if base.endswith(".jsonl"):
        if base.startswith("spans."):
            return schema_lib.validate_span_file(path)
        if base.startswith("metrics."):
            return schema_lib.validate_metrics_file(path)
        if base.startswith("restarts"):
            return schema_lib.validate_restart_file(path)
        # an unnamed JSONL: route by its first WELL-FORMED row's kind
        # (history files travel under arbitrary names; a torn first
        # line — a crashed writer — must not misroute the rest)
        kind = None
        try:
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        row = json.loads(line)
                    except ValueError:
                        continue
                    if isinstance(row, dict):
                        kind = row.get("kind")
                        break
        except OSError as e:
            return [f"{path}: unreadable ({e})"]
        if kind == "span":
            return schema_lib.validate_span_file(path)
        if kind == "bench_history":
            return schema_lib.validate_history_file(path)
        if kind == "restart":
            return schema_lib.validate_restart_file(path)
        return schema_lib.validate_metrics_file(path)
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        return [f"{path}: unreadable ({e})"]
    if isinstance(doc, dict) and doc.get("kind") == "workload":
        # a dtx-obs capture document (schema v10) under any name
        return schema_lib.validate_workload(doc, where=path)
    if isinstance(doc, dict) and doc.get("kind") == "run_report":
        return schema_lib.validate_run_report(doc, where=path)
    if base == "report.json":
        # the chief's collate() post-mortem, not a per-proc dump: it
        # has its own (version-stamped) shape — check the version only
        return schema_lib.validate_version(doc, "version", where=path)
    return schema_lib.validate_flight_dump(doc, where=path)


def cmd_validate(args) -> int:
    targets: List[str] = []
    for path in args.paths:
        if os.path.isdir(path):
            targets += _stream_files(path)
            restarts = os.path.join(path, "restarts.jsonl")
            if os.path.isfile(restarts):
                targets.append(restarts)
            targets += sorted(glob.glob(os.path.join(path, "flight",
                                                     "*.json")))
        elif os.path.exists(path):
            targets.append(path)
        else:
            print(f"dtx-obs validate: {path}: no such file",
                  file=sys.stderr)
            return 2
    if not targets:
        print("dtx-obs validate: nothing to validate", file=sys.stderr)
        return 2
    failed = 0
    for path in targets:
        errs = _validate_one(path)
        if errs:
            failed += 1
            print(f"FAIL {path}")
            for e in errs[:args.max_errors]:
                print(f"  {e}")
            if len(errs) > args.max_errors:
                print(f"  ... {len(errs) - args.max_errors} more")
        else:
            print(f"OK   {path}")
    return 1 if failed else 0


def cmd_slo(args) -> int:
    from . import slo as slo_lib
    from . import spans as spans_lib

    try:
        specs = slo_lib.parse_specs(args.spec)
    except ValueError as e:
        print(f"dtx-obs slo: {e}", file=sys.stderr)
        return 2
    rows = spans_lib.load_spans(args.logs_path)
    if not rows:
        print(f"dtx-obs slo: no spans.<proc>.jsonl under "
              f"{args.logs_path!r} — was the engine started with "
              f"--trace_spans?", file=sys.stderr)
        return 2
    doc = slo_lib.evaluate(slo_lib.records_from_spans(rows),
                           specs=specs)
    print(json.dumps(doc, indent=None if args.compact else 1))
    if doc["breaches"]:
        print(f"dtx-obs slo: BREACH {','.join(doc['breaches'])}",
              file=sys.stderr)
        return 3
    return 0


def cmd_trace(args) -> int:
    from . import spans as spans_lib

    if args.export:
        # whole-timeline export (RID optional): run dirs AND fleet
        # parents both work, via the collector's discovery/merge
        from . import collector as col_lib

        try:
            col = col_lib.collect([args.logs_path])
        except FileNotFoundError as e:
            print(f"dtx-obs trace: {e}", file=sys.stderr)
            return 2
        rows = col["rows"]
        if args.rid is not None:
            rows = [r for r in rows
                    if r.get("kind") != "span"
                    or r.get("rid") == args.rid
                    or args.rid in (r.get("rids") or ())]
        doc = col_lib.chrome_trace(rows)
        if not any(e["ph"] != "M" for e in doc["traceEvents"]):
            print(f"dtx-obs trace: nothing to export under "
                  f"{args.logs_path!r}"
                  + (f" for rid {args.rid}" if args.rid is not None
                     else ""), file=sys.stderr)
            return 2
        out = json.dumps(doc, indent=None if args.compact else 1)
        if args.out:
            with open(args.out, "w") as f:
                f.write(out + "\n")
            print(f"dtx-obs trace: wrote "
                  f"{len(doc['traceEvents'])} events to {args.out} "
                  f"(open in ui.perfetto.dev)", file=sys.stderr)
        else:
            print(out)
        return 0
    if args.rid is None:
        print("dtx-obs trace: RID is required without --export",
              file=sys.stderr)
        return 2
    rows = spans_lib.load_spans(args.logs_path)
    if not rows:
        print(f"dtx-obs trace: no spans.<proc>.jsonl under "
              f"{args.logs_path!r}", file=sys.stderr)
        return 2
    doc = spans_lib.trace_record(rows, args.rid)
    if doc is None:
        print(f"dtx-obs trace: rid {args.rid} not in the span stream",
              file=sys.stderr)
        return 2
    print(json.dumps(doc, indent=None if args.compact else 1))
    return 0


def cmd_collect(args) -> int:
    from . import collector as col_lib

    try:
        col = col_lib.collect(args.paths, align=not args.no_align)
    except FileNotFoundError as e:
        print(f"dtx-obs collect: {e}", file=sys.stderr)
        return 2
    for s in col["sources"]:
        print(f"source {s['source']}: {s['rows']} rows, "
              f"{s['procs']} proc(s), skew {s['skew_s']:+.3f}s",
              file=sys.stderr)
    rows = col["rows"]
    if args.lines > 0:
        rows = rows[-args.lines:]
    if args.out:
        with open(args.out, "w") as f:
            for r in col["rows"]:
                f.write(json.dumps(r) + "\n")
        print(f"dtx-obs collect: wrote {len(col['rows'])} merged "
              f"rows to {args.out}", file=sys.stderr)
        return 0
    for r in rows:
        if args.json:
            print(json.dumps(r))
        else:
            line = format_row(r)
            if line is not None:
                print(f"[{r.get('source')}]{line}")
    return 0


def cmd_fleet(args) -> int:
    from . import collector as col_lib
    from . import slo as slo_lib

    try:
        specs = slo_lib.parse_specs(args.spec)
    except ValueError as e:
        print(f"dtx-obs fleet: {e}", file=sys.stderr)
        return 2
    try:
        report = col_lib.fleet_report(args.paths, specs=specs,
                                      align=not args.no_align)
    except FileNotFoundError as e:
        print(f"dtx-obs fleet: {e}", file=sys.stderr)
        return 2
    print(json.dumps(report, indent=None if args.compact else 1))
    bad = []
    if not report["exactly_once"]:
        bad.append("exactly-once violation")
    slo_doc = report.get("slo")
    if slo_doc is not None:
        if not slo_doc["identity"]["holds"]:
            bad.append("federated-identity violation")
        if slo_doc["breaches"]:
            bad.append(f"SLO breach {','.join(slo_doc['breaches'])}")
    if bad:
        print(f"dtx-obs fleet: {'; '.join(bad)}", file=sys.stderr)
        return 3
    return 0


def cmd_history(args) -> int:
    from . import history as hist_lib

    rc = 0
    if args.imports:
        appended, skipped = hist_lib.import_captures(args.history,
                                                     args.imports)
        print(f"dtx-obs history: imported {appended} capture(s), "
              f"skipped {len(skipped)}", file=sys.stderr)
        for msg in skipped:
            print(f"  {msg}", file=sys.stderr)
    if args.append:
        try:
            doc = cmp_lib.load_doc(args.append)
        except (OSError, ValueError) as e:
            print(f"dtx-obs history: {e}", file=sys.stderr)
            return 2
        entry = hist_lib.append_entry(
            args.history, doc,
            label=os.path.splitext(os.path.basename(args.append))[0],
            source=args.append)
        if not entry["metrics"]:
            print(f"dtx-obs history: {args.append}: no gate metrics "
                  f"extractable (recorded an empty entry)",
                  file=sys.stderr)
            rc = 1
    entries = hist_lib.read_history(args.history)
    if not entries:
        print(f"dtx-obs history: no entries in {args.history!r}",
              file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps(entries, indent=1))
    else:
        metrics = ([m.strip() for m in args.metrics.split(",")
                    if m.strip()] if args.metrics else None)
        print(hist_lib.trend_table(entries, metrics=metrics,
                                   last=args.last))
    return rc


def cmd_explain(args) -> int:
    from . import collector as col_lib
    from . import waterfall as wf_lib
    from .queueing import queueing_report

    try:
        col = col_lib.collect([args.logs_path])
    except FileNotFoundError as e:
        print(f"dtx-obs explain: {e}", file=sys.stderr)
        return 2
    span_rows = [r for r in col["rows"] if r.get("kind") == "span"]
    if args.workload:
        # only rows a replay stamped with this source workload id —
        # the A/B surface across replays of one capture
        span_rows = [r for r in span_rows
                     if r.get("replay_of") == args.workload]
    if args.fleet:
        q = queueing_report(span_rows)
        if q is None:
            print(f"dtx-obs explain: no request submits in the span "
                  f"stream under {args.logs_path!r}", file=sys.stderr)
            return 2
        print(json.dumps(q, indent=None if args.compact else 1))
        ll = q["littles_law"]
        if not ll["holds"]:
            print(f"dtx-obs explain: Little's law gap "
                  f"{ll['rel_err']:.1%} — {ll['violations']} "
                  f"in-flight/untracked request(s)", file=sys.stderr)
        return 0
    docs = wf_lib.waterfalls(span_rows, rid=args.rid,
                             trace_id=args.trace or None)
    if not docs:
        where = (f" for rid {args.rid}" if args.rid is not None
                 else f" for trace {args.trace!r}" if args.trace
                 else "")
        print(f"dtx-obs explain: no request lifecycles in the span "
              f"stream under {args.logs_path!r}{where}",
              file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps({"summary": wf_lib.summarize(docs),
                          "waterfalls": docs},
                         indent=None if args.compact else 1))
        return 0
    for d in docs:
        head = (f"[p{d['proc']}] rid {d['rid']} -> "
                f"{d['terminal'] or 'IN FLIGHT'}  "
                f"wall {d['wall_ms']:.1f}ms")
        if d.get("trace_id"):
            head += f"  trace {d['trace_id']}"
        if d["requeues"]:
            head += f"  requeues {d['requeues']}"
        print(head)
        for seg in wf_lib.WATERFALL_SEGMENTS:
            ms = d["segments"].get(seg, 0.0)
            if ms <= 0.0:
                continue
            frac = ms / d["wall_ms"] if d["wall_ms"] > 0 else 0.0
            print(f"  {seg:<20} {ms:>10.2f}ms  {frac:>6.1%}")
        print(f"  {'sum':<20} {d['segment_sum_ms']:>10.2f}ms  "
              f"(residual {d['residual_ms']:+.3f}ms)")
    summ = wf_lib.summarize(docs)
    print(f"{summ['requests']} request(s), {summ['complete']} "
          f"complete; wall p50 {_fmt(summ['wall_p50_ms'])}ms "
          f"p99 {_fmt(summ['wall_p99_ms'])}ms; "
          f"sum-to-wall {'OK' if summ['sum_to_wall_ok'] else 'GAP'} "
          f"(max residual {summ['max_residual_frac']:.2%})")
    return 0


def cmd_drift(args) -> int:
    from . import drift as drift_lib

    if not os.path.isfile(args.history):
        print(f"dtx-obs drift: {args.history}: no such file",
              file=sys.stderr)
        return 2
    metrics = [m.strip() for m in args.metrics.split(",")
               if m.strip()] or None
    try:
        doc = drift_lib.drift_report(
            args.history, window=args.window,
            tolerance=args.tolerance, metrics=metrics,
            capture=args.capture or None)
    except (OSError, ValueError) as e:
        print(f"dtx-obs drift: {e}", file=sys.stderr)
        return 2
    if doc["entries"] < drift_lib.MIN_ENTRIES:
        print(f"dtx-obs drift: only {doc['entries']} history "
              f"entr{'y' if doc['entries'] == 1 else 'ies'} in "
              f"{args.history!r} — change-point detection needs "
              f">= {drift_lib.MIN_ENTRIES}", file=sys.stderr)
        return 2
    print(json.dumps(doc, indent=None if args.compact else 1))
    for d in doc["drifts"]:
        print(f"dtx-obs drift: CONFIRMED {d['metric']} shifted "
              f"{d['shift_frac']:+.1%} (tolerance "
              f"{d['tolerance']:.1%}) — first offending row "
              f"{d['first_offending']!r} (entry "
              f"{d['first_offending_index']})", file=sys.stderr)
    return 0 if doc["ok"] else 3


def cmd_capture(args) -> int:
    from . import workload as wl_lib

    try:
        doc = wl_lib.capture(args.run_dir, align=not args.no_align)
    except (FileNotFoundError, ValueError) as e:
        print(f"dtx-obs capture: {e}", file=sys.stderr)
        return 2
    if args.out:
        wl_lib.write_workload(doc, args.out)
        print(f"dtx-obs capture: {doc['workload_id']} "
              f"({doc['n_requests']} requests over "
              f"{doc['duration_s']:g}s) -> {args.out}")
    else:
        print(json.dumps(doc, indent=None if args.compact else 1,
                         sort_keys=True))
    return 0


def cmd_capacity(args) -> int:
    from . import capacity as cap_lib
    from . import workload as wl_lib

    try:
        doc = wl_lib.load_workload(args.workload)
    except (OSError, ValueError) as e:
        print(f"dtx-obs capacity: {e}", file=sys.stderr)
        return 2
    util = (args.utilization if args.utilization is not None
            else cap_lib.UTILIZATION_TARGET)
    tol = (args.tolerance if args.tolerance is not None
           else cap_lib.DEFAULT_TOLERANCE)
    try:
        fc = cap_lib.forecast(
            doc, service_tok_s=args.service_tok_s,
            utilization_target=util)
    except ValueError as e:
        print(f"dtx-obs capacity: {e}", file=sys.stderr)
        return 2
    out = dict(fc)
    rc = 0
    if args.measured_qps is not None:
        # the validation loop: a measured saturation knee (replaying
        # the same workload at increasing --replay_speed) against the
        # closed-form forecast — exit 3 on the drift convention when
        # measurement falls short beyond tolerance
        out["verdict"] = cap_lib.verdict(
            fc["sustainable_qps"], args.measured_qps,
            tolerance=tol)
        if not out["verdict"]["ok"]:
            rc = 3
    print(json.dumps(out, indent=None if args.compact else 1,
                     sort_keys=True))
    if rc:
        v = out["verdict"]
        print(f"dtx-obs capacity: measured {v['measured_qps']:g} qps "
              f"falls short of forecast {v['forecast_qps']:g} qps "
              f"beyond tolerance {v['tolerance']:.0%} "
              f"(rel_err {v['rel_err']:.1%})", file=sys.stderr)
    return rc


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="dtx-obs",
        description="run analytics over the obs/ telemetry: goodput "
                    "reports, A/B regression gating, live tail/serve, "
                    "schema validation")
    sub = p.add_subparsers(dest="cmd", required=True)

    r = sub.add_parser("report", help="aggregate a run into the run "
                                      "report (goodput decomposition)")
    r.add_argument("logs_path")
    r.add_argument("--summary", action="store_true",
                   help="one-line summary instead of JSON")
    r.add_argument("--compact", action="store_true",
                   help="single-line JSON")
    r.add_argument("-o", "--out", default="",
                   help="also write the JSON report to this file")
    r.set_defaults(fn=cmd_report)

    c = sub.add_parser("compare", help="A/B two runs; exit 3 on "
                                       "regression")
    c.add_argument("base", help="baseline: logs dir, run report JSON, "
                                "bench row/summary, BASELINE.json or "
                                "BENCH_*.json capture")
    c.add_argument("cand", help="candidate (same shapes)")
    c.add_argument("--threshold", type=float, default=None,
                   help="relative threshold for EVERY metric "
                        "(default: per-metric, 0.05 perf / 0.02 "
                        "accuracy)")
    c.add_argument("--thresholds", default="",
                   metavar="NAME=REL,...",
                   help="per-metric overrides, e.g. wall_s=0.1,mfu=0.02")
    c.add_argument("--compact", action="store_true")
    c.set_defaults(fn=cmd_compare)

    t = sub.add_parser("tail", help="one line per metrics window")
    t.add_argument("logs_path")
    t.add_argument("-n", "--lines", type=int, default=20)
    t.add_argument("-f", "--follow", action="store_true",
                   help="keep following a live run")
    t.add_argument("--interval", type=float, default=2.0,
                   help="follow poll interval seconds")
    t.add_argument("--rid", type=int, default=None,
                   help="only span rows about this request id "
                        "(directly or as a batch member)")
    t.add_argument("--workload", default="",
                   metavar="WID",
                   help="only span rows a replay stamped with "
                        "replay_of WID (dtx-serve --replay)")
    t.add_argument("--trace", default="",
                   metavar="ID",
                   help="only span rows stamped with this trace id")
    t.set_defaults(fn=cmd_tail)

    s = sub.add_parser("serve", help="serve /status /metrics /report "
                                     "over HTTP (works on finished "
                                     "runs)")
    s.add_argument("logs_path")
    s.add_argument("--port", type=int, default=8321)
    s.add_argument("--host", default="",
                   help="bind address (default: all interfaces)")
    s.add_argument("--cache_s", type=float, default=None,
                   help="response cache TTL in seconds — /report, "
                        "/fleet and /explain share one TTL cache "
                        "(default 15; 0 = recompute every request)")
    s.set_defaults(fn=cmd_serve)

    v = sub.add_parser("validate", help="schema-validate metrics/"
                                        "spans/history/flight/report "
                                        "files or a whole logs dir")
    v.add_argument("paths", nargs="+")
    v.add_argument("--max-errors", type=int, default=10,
                   help="errors printed per file")
    v.set_defaults(fn=cmd_validate)

    o = sub.add_parser("slo", help="evaluate the serving SLOs over "
                                   "the span stream; exit 3 on "
                                   "breach")
    o.add_argument("logs_path")
    o.add_argument("--spec", default="",
                   metavar="NAME<=VALUE,...",
                   help="SLO specs (ttft_p99_ms<=MS, "
                        "latency_p99_ms<=MS, error_rate<=FRAC); "
                        "empty = the obs/slo.py defaults")
    o.add_argument("--compact", action="store_true")
    o.set_defaults(fn=cmd_slo)

    tr = sub.add_parser("trace", help="one request's reconstructed "
                                      "lifecycle from the span "
                                      "stream; --export chrome for "
                                      "the Perfetto timeline")
    tr.add_argument("logs_path")
    tr.add_argument("rid", type=int, nargs="?", default=None,
                    help="request id (optional with --export: the "
                         "whole timeline exports by default)")
    tr.add_argument("--export", choices=("chrome",), default="",
                    help="render as Chrome trace-event JSON "
                         "(ui.perfetto.dev) instead of the lifecycle "
                         "record")
    tr.add_argument("-o", "--out", default="",
                    help="write the export to this file instead of "
                         "stdout")
    tr.add_argument("--compact", action="store_true")
    tr.set_defaults(fn=cmd_trace)

    co = sub.add_parser("collect", help="merge N run dirs into one "
                                        "causally-ordered fleet "
                                        "timeline")
    co.add_argument("paths", nargs="+",
                    help="run dirs (or parents of run dirs)")
    co.add_argument("-n", "--lines", type=int, default=0,
                    help="print only the newest N merged rows")
    co.add_argument("--json", action="store_true",
                    help="raw merged rows instead of tail lines")
    co.add_argument("--no-align", action="store_true",
                    help="skip per-source clock-skew alignment")
    co.add_argument("-o", "--out", default="",
                    help="write the merged rows (JSONL) to this file")
    co.set_defaults(fn=cmd_collect)

    fl = sub.add_parser("fleet", help="fleet report over merged "
                                      "streams: exactly-once verdict "
                                      "+ federated SLO; exit 3 on "
                                      "breach/violation")
    fl.add_argument("paths", nargs="+",
                    help="run dirs (or parents of run dirs)")
    fl.add_argument("--spec", default="",
                    metavar="NAME<=VALUE,...",
                    help="SLO specs (the dtx-obs slo DSL); empty = "
                         "the obs/slo.py defaults")
    fl.add_argument("--no-align", action="store_true",
                    help="skip per-source clock-skew alignment")
    fl.add_argument("--compact", action="store_true")
    fl.set_defaults(fn=cmd_fleet)

    h = sub.add_parser("history", help="rolling bench history: trend "
                                       "table, --import backfill, "
                                       "--append recording")
    h.add_argument("history", help="the history.jsonl file")
    h.add_argument("--import", dest="imports", nargs="+", default=[],
                   metavar="CAPTURE",
                   help="backfill from BENCH_*.json captures (or any "
                        "comparison document); idempotent per label")
    h.add_argument("--append", default="",
                   metavar="DOC",
                   help="record one comparison document (bench "
                        "summary / run report / capture) as a new "
                        "entry")
    h.add_argument("--last", type=int, default=0,
                   help="show only the newest N entries")
    h.add_argument("--metrics", default="",
                   metavar="NAME,...",
                   help="trend-table columns (default: the headline "
                        "set present in the file)")
    h.add_argument("--json", action="store_true",
                   help="dump the raw entries instead of the table")
    h.set_defaults(fn=cmd_history)

    ex = sub.add_parser("explain",
                        help="per-request latency waterfalls: where "
                             "every millisecond between submit and "
                             "terminal went; --fleet for queueing "
                             "analytics")
    ex.add_argument("logs_path",
                    help="run dir (or parent of run dirs)")
    ex.add_argument("--rid", type=int, default=None,
                    help="only this request id")
    ex.add_argument("--trace", default="",
                    metavar="ID",
                    help="only requests stamped with this trace id")
    ex.add_argument("--workload", default="",
                    metavar="WID",
                    help="only requests a replay stamped with "
                         "replay_of WID (dtx-serve --replay)")
    ex.add_argument("--fleet", action="store_true",
                    help="queueing analytics (arrival rate, service "
                         "time by bucket, Little's-law check) "
                         "instead of per-request waterfalls")
    ex.add_argument("--json", action="store_true",
                    help="raw waterfall documents instead of tables")
    ex.add_argument("--compact", action="store_true")
    ex.set_defaults(fn=cmd_explain)

    dr = sub.add_parser("drift",
                        help="change-point detection over the bench "
                             "history; exit 3 on confirmed drift")
    dr.add_argument("history", help="the history.jsonl file")
    dr.add_argument("--window", type=int, default=0,
                    help="only the newest N entries (0 = all)")
    dr.add_argument("--tolerance", type=float, default=None,
                    help="relative shift tolerance for EVERY metric "
                         "(default: per-metric, 2x the gate "
                         "threshold, floor 0.05)")
    dr.add_argument("--metrics", default="",
                    metavar="NAME,...",
                    help="only these metrics (default: every metric "
                         "present in enough entries)")
    dr.add_argument("--capture", default="",
                    metavar="BENCH.json",
                    help="join this capture's measured decode "
                         "throughput against the roofline closed "
                         "forms")
    dr.add_argument("--compact", action="store_true")
    dr.set_defaults(fn=cmd_drift)

    ca = sub.add_parser("capture",
                        help="distill a run's span stream into a "
                             "portable WORKLOAD document — the input "
                             "to dtx-serve --replay and capacity")
    ca.add_argument("run_dir",
                    help="run dir (or fleet parent of run dirs)")
    ca.add_argument("-o", "--out", default="",
                    help="write the workload json here instead of "
                         "stdout")
    ca.add_argument("--no-align", action="store_true",
                    help="skip cross-source clock alignment")
    ca.add_argument("--compact", action="store_true")
    ca.set_defaults(fn=cmd_capture)

    cp = sub.add_parser("capacity",
                        help="closed-form capacity forecast off a "
                             "captured workload; exit 3 when a "
                             "--measured-qps knee falls short of "
                             "forecast beyond tolerance")
    cp.add_argument("workload", help="a dtx-obs capture json")
    cp.add_argument("--service-tok-s", type=float, required=True,
                    dest="service_tok_s",
                    help="one replica's decode budget in generated "
                         "tokens/s (a measured unloaded replay rate, "
                         "or the obs/capacity.py roofline on TPU)")
    cp.add_argument("--utilization", type=float,
                    default=None,
                    help="target utilization the forecast plans to "
                         "(default 0.8)")
    cp.add_argument("--measured-qps", type=float, default=None,
                    dest="measured_qps",
                    help="the measured saturation knee (replaying at "
                         "increasing --replay_speed); joins a "
                         "verdict and arms exit 3")
    cp.add_argument("--tolerance", type=float, default=None,
                    help="verdict tolerance (default 0.25)")
    cp.add_argument("--compact", action="store_true")
    cp.set_defaults(fn=cmd_capacity)
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
