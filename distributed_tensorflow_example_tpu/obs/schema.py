"""Telemetry/forensics format contracts + validators.

The metrics JSONL rows (obs/metrics.py) and flight dumps
(obs/flight.py) are consumed by tooling that is NOT in this repo
(dashboards, the bench driver, post-mortem scripts). A silently
renamed field breaks those consumers long after the commit that did
it. This module is the single written-down contract — field names and
types for every row kind — plus validators that bench.py runs on its
own capture and tier-1 tests pin, so format drift fails loudly at the
commit that causes it.

Validators return a list of error strings (empty = valid) rather than
raising: callers decide whether drift is fatal (tests) or a logged
warning (bench).
"""

from __future__ import annotations

import json
from typing import Any, Dict, List

from .buckets import HOST_BUCKET, WINDOW_BUCKETS

_NUM = (int, float)

# The one version number for everything obs/ writes: stamped as "v"
# into every metrics row (obs/metrics.MetricsLogger) and as "version"
# into every flight dump (obs/flight.FlightRecorder imports it), and
# checked FIRST by the validators — so dtx-obs on an old-format log
# says "written by schema v1" instead of cascading field-missing
# errors. Bump it whenever a field is renamed/retyped/removed.
# History: v1 = PR 1/2 (unstamped metrics rows, flight "version": 1);
# v2 = the stamp itself + the run_end goodput fields
# (compile_s/eval_s/sample_s); v3 = the h2d_s window bucket (the
# batch device-commit wall) + the matching h2d goodput bucket;
# v4 = the serving request-lifecycle span stream (spans.<proc>.jsonl,
# SPAN_* contracts below), the bench history records
# (HISTORY_ENTRY) and the ttft_p99_ms serving-stats field;
# v5 = the resilience subsystem: the ckpt_s window/goodput bucket
# (async-checkpoint submit stall), the restart-timeline stream
# (restarts.jsonl, RESTART_EVENT below) and the run report's
# "restarts" section;
# v6 = fail-open serving: the typed-terminal span events
# (timeout/shed/failed) + the supervision records
# (requeue/engine_restart) in SPAN_FIELDS/SPAN_REQUIRED, the
# "engine_restart" restart-timeline event, and the SERVING_STATS
# shed/timeout/failed/requeue/restart/queue/brownout counters;
# v7 = fleet observability: trace-context propagation
# (trace_id/parent_id on every span, W3C traceparent at the serving
# edge), the training-side "phase" span event (phase/dur_ms), the
# collector's "source" stamp on merged rows, and the FLEET_REPORT
# document (obs/collector.py fleet timeline + federated SLO);
# v8 = latency attribution: the "tick_done" span event (the engine
# closes each tick with its execution-only dur_ms so stall time is
# separable), the WATERFALL document (obs/waterfall.py per-request
# segment decomposition), the DRIFT_REPORT document (obs/drift.py
# model-vs-measured change-point detection), and the FLEET_REPORT's
# optional "queueing" section (obs/queueing.py Little's-law
# analytics);
# v9 = fleet serving: the router narration span events
# ("route"/"failover" with the per-replica "replica" field in
# SPAN_FIELDS/SPAN_REQUIRED — placement and cross-engine failover
# records that join replica-stream lifecycles by trace_id), and the
# FLEET_REPORT's "failover" section (per-trace hop chains: every
# intermediate hop a typed "failed", the last hop the fleet
# terminal, intermediates excluded from the federated SLO so a
# failed-over request counts once);
# v10 = workload capture/replay: the WORKLOAD document (obs/workload.py
# distills a span dir into a portable request schedule — arrival
# offsets, token counts, deadlines, prompt fingerprints), the
# "fingerprint" submit-span payload (chained prompt-block hashes
# preserving shared-prefix structure, the prefix-cache input) and the
# "replay_of" stamp (every row a serving/replay.py run writes names
# the source workload id, so waterfalls compare A/B across replays).
SCHEMA_VERSION = 10


# field -> allowed types; a tuple including type(None) marks nullable
METRICS_COMMON = {
    "kind": (str,),
    "t": _NUM,
    "proc": (int,),
    "v": (int,),
}

# kind == "window": the per---log_every training telemetry row. Both
# the host and fast paths emit every field below (metrics_row +
# log_window in train/loop.py + obs/metrics.py).
METRICS_WINDOW = {
    "step": (int,),
    "epoch": (int,),
    "cost": _NUM + (str,),  # non-finite costs stringify (strict JSON)
    "path": (str,),
    "steps": (int,),
    "window_wall_s": _NUM,
    "step_time_p50_ms": _NUM,
    "step_time_p95_ms": _NUM,
    "step_time_max_ms": _NUM,
    "data_wait_s": _NUM,
    "h2d_s": _NUM,
    "dispatch_s": _NUM,
    "device_wait_s": _NUM,
    "ckpt_s": _NUM,
    "host_s": _NUM,
    "examples_per_sec": _NUM + (type(None),),
    "tokens_per_sec": _NUM + (type(None),),
    "model_flops_per_step": _NUM,
    "tflops_per_sec": _NUM + (type(None),),
    "mfu": _NUM + (type(None),),
    "rss_bytes": (int, type(None)),
    "device_memory": (dict, type(None)),
}

# The per-bucket timing fields above are the shared bucket registry
# (obs/buckets.py) spelled out — a contract stays explicit — and this
# import-time check keeps the two from drifting: adding a WindowTimer
# bucket without its schema field (or vice versa) fails the first
# import, not a consumer months later. dtx-lint's scope-registry rule
# checks the same statically.
_BUCKET_FIELDS = {f"{b}_s" for b in WINDOW_BUCKETS + (HOST_BUCKET,)}
_SCHEMA_BUCKET_FIELDS = {k for k in METRICS_WINDOW
                         if k.endswith("_s") and k != "window_wall_s"}
if _SCHEMA_BUCKET_FIELDS != _BUCKET_FIELDS:
    raise AssertionError(
        f"METRICS_WINDOW bucket fields {sorted(_SCHEMA_BUCKET_FIELDS)} "
        f"out of sync with obs/buckets.py WINDOW_BUCKETS "
        f"{sorted(_BUCKET_FIELDS)}; update both (and bump "
        f"SCHEMA_VERSION)")

# kind == "event": point events; free-form payload beyond these.
METRICS_EVENT = {
    "event": (str,),
}

FLIGHT_DUMP = {
    "version": (int,),
    "proc": (int,),
    "reason": (str,),
    "t": _NUM,
    "last_step": (int, type(None)),
    "steps": (list,),
    "windows": (list,),
    "anomalies": (list,),
    "env": (dict,),
}

FLIGHT_STEP_RECORD = {
    "step": (int,),
    "t": _NUM,
}

FLIGHT_ANOMALY_RECORD = {
    "step": (int,),
    "t": _NUM,
    "reasons": (list,),
    "policy": (str,),
}

# The serving engine's point-in-time counters
# (serving/engine.DecodeEngine.stats): the /status "serving" section
# and the dtx_generate_* Prometheus gauges read exactly these fields,
# so dashboards scrape a pinned surface.  Percentiles/throughput are
# nullable — absent before the first completion, never fabricated.
SERVING_STATS = {
    "requests_total": (int,),
    "completed_total": (int,),
    "inflight": (int,),
    "queued": (int,),
    "latency_p50_ms": _NUM + (type(None),),
    "latency_p99_ms": _NUM + (type(None),),
    "ttft_p50_ms": _NUM + (type(None),),
    "ttft_p99_ms": _NUM + (type(None),),
    "tokens_generated_total": (int,),
    "tokens_per_sec": _NUM + (type(None),),
    "page_occupancy_frac": _NUM,
    "decode_ticks_total": (int,),
    "prefills_total": (int,),
    # fail-open serving (PR 15): typed-terminal counters + the
    # admission-control/supervision surface.  requests_total counts
    # ACCEPTED requests only; shed requests consume a rid (span-stream
    # uniqueness) but land here instead.  brownout_active is 0/1 (a
    # gauge, not a bool — Prometheus has no bool).
    "shed_total": (int,),
    "timeout_total": (int,),
    "failed_total": (int,),
    "requeued_total": (int,),
    "engine_restarts_total": (int,),
    "queue_limit": (int,),
    "queue_peak": (int,),
    "brownout_active": (int,),
    "brownout_clamped_total": (int,),
}


def validate_serving_stats(doc: Dict[str, Any],
                           where: str = "serving") -> List[str]:
    """Validate a DecodeEngine.stats() document (no version stamp —
    it is an in-process snapshot, never written to disk by obs/)."""
    return _check(doc, SERVING_STATS, where)


# The serving request-lifecycle span stream (obs/spans.py writes
# spans.<proc>.jsonl; serving/scheduler.py + serving/engine.py emit
# through an injected SpanRecorder).  SPAN_COMMON is every row's
# envelope; SPAN_FIELDS types every per-event payload field a span
# row may carry; SPAN_REQUIRED maps each event (the obs/buckets.py
# SPAN_EVENTS vocabulary) to the fields it must carry — together the
# written contract the validator and dtx-obs validate enforce.
SPAN_COMMON = {
    "kind": (str,),          # "span"
    "v": (int,),
    "t": _NUM,
    "proc": (int,),
    "event": (str,),
}

SPAN_FIELDS = {
    "rid": (int,),
    "prompt_len": (int,),
    "max_new_tokens": (int,),
    "arrival": _NUM,
    "reason": (str,),
    "tick": (int,),
    "pages_held": (int,),
    "bucket": (int,),
    "pages_width": (int,),
    "ttft_ms": _NUM,
    "rids": (list,),
    "batch": (int,),
    "batch_bucket": (int,),
    "kv_pages": (int,),
    "occupancy": _NUM,
    "generated": (int,),
    "finish_t": _NUM,
    # fail-open payloads (v6): deadline rides submit (optional),
    # queued the shed/timeout context, attempt(s) the supervision
    # retry accounting, restart the engine-restart ordinal, clamped
    # the brownout admit marker
    "deadline": _NUM,
    "queued": (bool, int),
    "attempt": (int,),
    "attempts": (int,),
    "restart": (int,),
    "clamped": (bool,),
    # fleet observability (v7): trace_id is the 32-hex W3C trace id a
    # request (or training round) carries through its whole lifecycle
    # — requeue/engine_restart survivors keep theirs; parent_id is the
    # 16-hex span id of the caller's traceparent when one arrived at
    # the serving edge; source is stamped by the fleet collector on
    # merged rows (never by a writer); phase/dur_ms are the
    # training-side "phase" span payload (obs/buckets.PHASE_SCOPES).
    "trace_id": (str,),
    "parent_id": (str,),
    "source": (str,),
    "phase": (str,),
    "dur_ms": _NUM,
    # fleet serving (v9): the router's route/failover narration names
    # the replica a request was placed on
    "replica": (str,),
    # workload capture/replay (v10): fingerprint is the chained
    # prompt-block hash list riding submit (optional — pure-scheduler
    # streams omit it); replay_of stamps every row a replay run writes
    # with the source workload id (recorder-level, so the whole
    # stream is attributable to its workload for A/B waterfalls)
    "fingerprint": (list,),
    "replay_of": (str,),
}

SPAN_REQUIRED = {
    "submit": ("rid", "prompt_len", "max_new_tokens", "arrival"),
    "blocked": ("rid", "reason", "tick"),
    "admit": ("rid", "pages_held", "tick"),
    "prefill": ("rid", "bucket", "pages_width"),
    "first_token": ("rid", "ttft_ms"),
    "tick": ("tick", "rids", "batch", "batch_bucket", "kv_pages",
             "occupancy"),
    # the tick-closing timestamp (v8): emitted by the engine after a
    # tick's prefill+decode execution, dur_ms = execution wall only —
    # (tick_done.t - tick.t) - dur_ms is the tick's stall, the number
    # obs/waterfall.py splits decode time on.  Batch-shaped like tick
    # (no rid): reconstruct() skips it, the waterfall consumes it.
    "tick_done": ("tick", "dur_ms"),
    "retire": ("rid", "generated", "finish_t", "tick"),
    "error": ("rid", "reason"),
    # the typed terminals + supervision records (v6): timeout carries
    # its reason ("deadline"/"cancel") and how much work was lost;
    # shed is the only terminal without a submit (never accepted);
    # requeue marks a supervised re-admission (attempt = crashes this
    # request survived); engine_restart is batch-shaped like tick
    # (rids = the in-flight set torn down); failed closes the retry
    # budget.
    "timeout": ("rid", "reason", "tick", "generated"),
    "shed": ("rid", "reason", "tick", "queued"),
    "requeue": ("rid", "attempt", "tick"),
    "engine_restart": ("restart", "reason", "rids", "tick"),
    "failed": ("rid", "reason", "attempts"),
    # the training-side phase span (v7): one row per completed
    # train-loop phase, carrying its registered name, the round's
    # trace id, and the measured wall.  trace_id/parent_id stay
    # OPTIONAL on every serving event (old fixtures remain valid);
    # only the phase row requires one.
    "phase": ("phase", "trace_id", "dur_ms"),
    # the fleet router's narration rows (v9): rid is the FLEET rid
    # (the router's own namespace), replica the placement target,
    # attempt the cumulative PR 15 retry count carried across
    # engines; failover adds why the request moved.  Lifecycle events
    # for the request live in the REPLICA's stream — reconstruct()
    # treats narration-only records as non-lifecycles.
    "route": ("rid", "replica", "attempt"),
    "failover": ("rid", "replica", "attempt", "reason"),
}


def validate_span_row(row: Dict[str, Any], where: str = "row") -> List[str]:
    """Validate one spans.<proc>.jsonl row: version first, then the
    envelope, then the event's required payload fields."""
    if not isinstance(row, dict):
        return [f"{where}: not an object"]
    verrs = _version_errs(row, "v", where)
    if verrs:
        return verrs
    errs = _check(row, SPAN_COMMON, where)
    if row.get("kind") not in (None, "span"):
        errs.append(f"{where}: kind is {row.get('kind')!r}, expected "
                    f"'span'")
    event = row.get("event")
    if event is not None:
        required = SPAN_REQUIRED.get(event)
        if required is None:
            errs.append(f"{where}: unknown span event {event!r} "
                        f"(known: {sorted(SPAN_REQUIRED)})")
        else:
            errs += _check(row, {f: SPAN_FIELDS[f] for f in required},
                           where)
        if event == "phase" and isinstance(row.get("phase"), str):
            from .buckets import PHASE_SCOPES

            if row["phase"] not in PHASE_SCOPES:
                errs.append(f"{where}: unknown phase "
                            f"{row['phase']!r} (known: "
                            f"{sorted(PHASE_SCOPES)})")
    # the optional trace-context payload (v7) and the capture/replay
    # payloads (v10) are typed whenever present
    for f in ("trace_id", "parent_id", "source", "fingerprint",
              "replay_of"):
        if f in row:
            errs += _check(row, {f: SPAN_FIELDS[f]}, where)
    return errs


def validate_span_file(path: str) -> List[str]:
    """Validate every line of a spans.<proc>.jsonl file."""
    errs: List[str] = []
    with open(path) as f:
        for i, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                row = json.loads(line)
            except ValueError as e:
                errs.append(f"line {i}: not JSON ({e})")
                continue
            errs += validate_span_row(row, where=f"line {i}")
    return errs


# One restart-timeline row (resilience/restart.py RestartNarrator
# appends these to <logs_path>/restarts.jsonl; the event vocabulary
# is obs/buckets.py RESTART_EVENTS and the payload beyond this
# envelope is free-form — decisions carry reason/wait_s/dp/dead,
# snapshots carry step/objects written, the preempt row its signal).
RESTART_EVENT = {
    "kind": (str,),          # "restart"
    "v": (int,),
    "t": _NUM,
    "proc": (int,),
    "event": (str,),
}


def validate_restart_row(row: Dict[str, Any],
                         where: str = "row") -> List[str]:
    """Validate one restarts.jsonl row: version first, then the
    envelope, then the event vocabulary."""
    if not isinstance(row, dict):
        return [f"{where}: not an object"]
    verrs = _version_errs(row, "v", where)
    if verrs:
        return verrs
    errs = _check(row, RESTART_EVENT, where)
    if row.get("kind") != "restart":
        errs.append(f"{where}: kind is {row.get('kind')!r}, expected "
                    f"'restart'")
    event = row.get("event")
    if isinstance(event, str):
        from .buckets import RESTART_EVENTS

        if event not in RESTART_EVENTS:
            errs.append(f"{where}: unknown restart event {event!r} "
                        f"(known: {sorted(RESTART_EVENTS)})")
    return errs


def validate_restart_file(path: str) -> List[str]:
    """Validate every line of a restarts.jsonl file."""
    errs: List[str] = []
    with open(path) as f:
        for i, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                row = json.loads(line)
            except ValueError as e:
                errs.append(f"line {i}: not JSON ({e})")
                continue
            errs += validate_restart_row(row, where=f"line {i}")
    return errs


# One bench-history record (obs/history.py appends these to the
# rolling history.jsonl: the final bench summary / run-report summary
# reduced to its gate metrics, so --gate-rolling and the dtx-obs
# history trend table read a pinned shape).
HISTORY_ENTRY = {
    "v": (int,),
    "kind": (str,),          # "bench_history"
    "t": _NUM,
    "label": (str,),
    "source": (str,),
    "metrics": (dict,),
}


def validate_history_entry(row: Dict[str, Any],
                           where: str = "row") -> List[str]:
    """Validate one history.jsonl record."""
    if not isinstance(row, dict):
        return [f"{where}: not an object"]
    verrs = _version_errs(row, "v", where)
    if verrs:
        return verrs
    errs = _check(row, HISTORY_ENTRY, where)
    if row.get("kind") != "bench_history":
        errs.append(f"{where}: kind is {row.get('kind')!r}, expected "
                    f"'bench_history'")
    return errs


def validate_history_file(path: str) -> List[str]:
    """Validate every line of a history.jsonl file."""
    errs: List[str] = []
    with open(path) as f:
        for i, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                row = json.loads(line)
            except ValueError as e:
                errs.append(f"line {i}: not JSON ({e})")
                continue
            errs += validate_history_entry(row, where=f"line {i}")
    return errs


# The run report obs/aggregate.py produces (dtx-obs report emits it,
# obs/compare.py diffs it). Top-level contract only — the nested
# goodput bucket names are pinned by aggregate.BUCKETS.
RUN_REPORT = {
    "v": (int,),
    "kind": (str,),          # "run_report"
    "logs_path": (str,),
    "generated_t": _NUM,
    "partial": (bool,),
    "procs": (int,),
    "steps": (int, type(None)),
    "wall_s": _NUM,
    "test_accuracy": _NUM + (type(None),),
    "goodput": (dict,),
    "step_time": (dict,),
    "throughput": (dict,),
    "trajectory": (list,),
    "stragglers": (dict,),
    "anomalies": (dict,),
    "restarts": (dict,),
    "timeline": (list,),
    "schema_errors": (list,),
}


# The fleet report obs/collector.py produces (dtx-obs fleet emits it,
# the StatusServer /fleet endpoint + dtx_fleet_* gauges read it): N
# source dirs' span/metrics/restart streams merged into one
# causally-ordered timeline.  "sources" is one entry per discovered
# run dir (name, rows, skew_s, procs); "requests" counts reconstructed
# request lifecycles fleet-wide; "exactly_once" is the PR 15
# terminates-typed invariant held across sources (every accepted
# request exactly one typed terminal, no duplicate milestones);
# "slo" is the federated evaluation (obs/slo.fleet_evaluate): the
# merged-stream burn plus per-source burns and the closed-form
# identity section.
FLEET_REPORT = {
    "v": (int,),
    "kind": (str,),          # "fleet_report"
    "generated_t": _NUM,
    "sources": (list,),
    "rows": (int,),
    "requests": (int,),
    "exactly_once": (bool,),
    "errors": (list,),
    "restarts": (int,),
    "slo": (dict, type(None)),
    # queueing analytics (v8, obs/queueing.py): arrival rate,
    # per-bucket service time, utilization and the Little's-law
    # consistency check over the merged stream; None when the stream
    # has no completed requests to measure.
    "queueing": (dict, type(None)),
    # cross-engine failover accounting (v9, the router join): hop
    # chains grouped by trace_id across sources — chains/hops counts,
    # the chain-shape verdict (every intermediate hop a typed
    # "failed", exactly one fleet terminal at the end) and the
    # per-chain terminals; None when no request spans >1 lifecycle.
    "failover": (dict, type(None)),
}


def validate_fleet_report(doc: Dict[str, Any],
                          where: str = "fleet") -> List[str]:
    """Validate a collector fleet report (top-level contract + the
    per-source entry shape)."""
    if not isinstance(doc, dict):
        return [f"{where}: not an object"]
    verrs = _version_errs(doc, "v", where)
    if verrs:
        return verrs
    errs = _check(doc, FLEET_REPORT, where)
    if doc.get("kind") != "fleet_report":
        errs.append(f"{where}: kind is {doc.get('kind')!r}, expected "
                    f"'fleet_report'")
    for i, src in enumerate(doc.get("sources") or []):
        errs += _check(src, {"source": (str,), "rows": (int,),
                             "skew_s": _NUM, "procs": (int,)},
                       f"{where}.sources[{i}]")
    return errs


# One per-request latency waterfall (obs/waterfall.py derives it from
# the span stream; dtx-obs explain and the /explain endpoint emit it).
# "segments" maps obs/buckets.WATERFALL_SEGMENTS names to
# milliseconds; the segments are computed as an exact partition of
# [submit_t, terminal_t], so segment_sum_ms matches wall_ms up to
# float rounding — residual_ms is the honesty field, and "complete"
# says whether the stream held a typed terminal for this request.
# "intervals" carries the absolute (t0, t1, segment) triples the
# Chrome-trace export renders as nested slices.
WATERFALL = {
    "v": (int,),
    "kind": (str,),          # "waterfall"
    "proc": (int,),
    "rid": (int,),
    "terminal": (str, type(None)),
    "submit_t": _NUM,
    "terminal_t": _NUM,
    "wall_ms": _NUM,
    "segments": (dict,),
    "segment_sum_ms": _NUM,
    "residual_ms": _NUM,
    "decode_ticks": (int,),
    "requeues": (int,),
    "complete": (bool,),
    "intervals": (list,),
}


def validate_waterfall(doc: Dict[str, Any],
                       where: str = "waterfall") -> List[str]:
    """Validate one per-request waterfall document (top-level contract
    + the segment names against the obs/buckets.py registry)."""
    if not isinstance(doc, dict):
        return [f"{where}: not an object"]
    verrs = _version_errs(doc, "v", where)
    if verrs:
        return verrs
    errs = _check(doc, WATERFALL, where)
    if doc.get("kind") != "waterfall":
        errs.append(f"{where}: kind is {doc.get('kind')!r}, expected "
                    f"'waterfall'")
    segs = doc.get("segments")
    if isinstance(segs, dict):
        from .buckets import WATERFALL_SEGMENTS

        unknown = [s for s in segs if s not in WATERFALL_SEGMENTS]
        if unknown:
            errs.append(f"{where}: unknown segments {sorted(unknown)} "
                        f"(known: {list(WATERFALL_SEGMENTS)})")
        missing = [s for s in WATERFALL_SEGMENTS if s not in segs]
        if missing:
            errs.append(f"{where}: segments missing {missing}")
    return errs


# The drift report obs/drift.py produces (dtx-obs drift emits it,
# exit 3 when "ok" is False): measured bench trajectory vs the
# analytic closed forms, change-point detection over the history
# window.  Each "drifts" entry names the metric, the window, the
# split point and the FIRST offending row label — the three facts a
# regression hunt needs.  "roofline" is the decode model-vs-measured
# join (None where the chip peak is unknown, e.g. CPU).
DRIFT_REPORT = {
    "v": (int,),
    "kind": (str,),          # "drift_report"
    "generated_t": _NUM,
    "history_path": (str,),
    "entries": (int,),
    "window": (int,),
    "metrics": (list,),
    "drifts": (list,),
    "roofline": (dict, type(None)),
    "ok": (bool,),
}


def validate_drift_report(doc: Dict[str, Any],
                          where: str = "drift") -> List[str]:
    """Validate an obs/drift.py report (top-level contract + the
    per-drift entry shape)."""
    if not isinstance(doc, dict):
        return [f"{where}: not an object"]
    verrs = _version_errs(doc, "v", where)
    if verrs:
        return verrs
    errs = _check(doc, DRIFT_REPORT, where)
    if doc.get("kind") != "drift_report":
        errs.append(f"{where}: kind is {doc.get('kind')!r}, expected "
                    f"'drift_report'")
    for i, d in enumerate(doc.get("drifts") or []):
        errs += _check(d, {"metric": (str,), "first_offending": (str,),
                           "shift_frac": _NUM}, f"{where}.drifts[{i}]")
    return errs


# The portable workload document obs/workload.py distills from a span
# dir (dtx-obs capture emits it; serving/replay.py consumes it): the
# request schedule of a recorded run, re-playable against any engine
# or fleet.  "requests" entries are WORKLOAD_REQUEST-shaped; arrivals
# are OFFSETS from the run's first submit (seconds), deadlines are
# RELATIVE milliseconds (a replay must not inherit the recording's
# wall clock); "fingerprint" is the chained prompt-block hash list
# (same prefix ⇔ same leading hashes — the shared-prefix structure
# ROADMAP item 1's prefix cache keys on); "workload_id" is a content
# hash over the request schedule, so two captures of identical
# traffic collide and a replay stream's replay_of stamp is stable.
WORKLOAD = {
    "v": (int,),
    "kind": (str,),          # "workload"
    "workload_id": (str,),
    "source": (str,),
    "generated_t": _NUM,
    "n_requests": (int,),
    "duration_s": _NUM,
    "requests": (list,),
}

WORKLOAD_REQUEST = {
    "rid": (int,),
    "arrival_s": _NUM,
    "prompt_len": (int,),
    "max_new_tokens": (int,),
    "output_tokens": (int, type(None)),
    "deadline_ms": _NUM + (type(None),),
    "trace_id": (str, type(None)),
    "terminal": (str, type(None)),
    "fingerprint": (list,),
}


def validate_workload(doc: Dict[str, Any],
                      where: str = "workload") -> List[str]:
    """Validate a captured workload document (top-level contract +
    every request entry's shape + the schedule invariants a replay
    relies on: rids dense from 0 in arrival order, offsets
    non-negative and non-decreasing)."""
    if not isinstance(doc, dict):
        return [f"{where}: not an object"]
    verrs = _version_errs(doc, "v", where)
    if verrs:
        return verrs
    errs = _check(doc, WORKLOAD, where)
    if doc.get("kind") != "workload":
        errs.append(f"{where}: kind is {doc.get('kind')!r}, expected "
                    f"'workload'")
    reqs = doc.get("requests")
    if isinstance(reqs, list):
        if isinstance(doc.get("n_requests"), int) \
                and doc["n_requests"] != len(reqs):
            errs.append(f"{where}: n_requests {doc['n_requests']} != "
                        f"len(requests) {len(reqs)}")
        prev = 0.0
        for i, req in enumerate(reqs):
            w = f"{where}.requests[{i}]"
            sub = _check(req, WORKLOAD_REQUEST, w)
            errs += sub
            if sub or not isinstance(req, dict):
                continue
            if req["rid"] != i:
                errs.append(f"{w}: rid {req['rid']} != index {i} "
                            f"(rids are dense in arrival order)")
            if req["arrival_s"] < prev:
                errs.append(f"{w}: arrival_s {req['arrival_s']} "
                            f"decreases (schedule must be sorted)")
            prev = float(req["arrival_s"])
            if req["prompt_len"] < 1 or req["max_new_tokens"] < 1:
                errs.append(f"{w}: prompt_len/max_new_tokens must be "
                            f">= 1")
    return errs


def validate_workload_file(path: str) -> List[str]:
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        return [f"{path}: unreadable ({e})"]
    return validate_workload(doc, where=path)


def _check(doc: Dict[str, Any], spec: Dict[str, tuple],
           where: str) -> List[str]:
    errs = []
    if not isinstance(doc, dict):
        return [f"{where}: not an object"]
    for field, types in spec.items():
        if field not in doc:
            errs.append(f"{where}: missing field {field!r}")
        elif not isinstance(doc[field], tuple(types)):
            # bool is an int subclass: reject bool where int expected
            errs.append(f"{where}: field {field!r} has type "
                        f"{type(doc[field]).__name__}, expected "
                        f"{'/'.join(t.__name__ for t in types)}")
        elif isinstance(doc[field], bool) and bool not in types:
            errs.append(f"{where}: field {field!r} is bool, expected "
                        f"{'/'.join(t.__name__ for t in types)}")
    return errs


def _version_errs(doc: Dict[str, Any], field: str, where: str) -> List[str]:
    """Precise old-format diagnosis, checked before any field check: a
    v1 log fed to a v2 tool must say so, not cascade missing-field
    errors."""
    v = doc.get(field)
    if v is None:
        return [f"{where}: no {field!r} stamp — written by a "
                f"pre-versioned build (schema v1); this tool reads "
                f"schema v{SCHEMA_VERSION}"]
    if isinstance(v, bool) or not isinstance(v, int):
        return [f"{where}: {field!r} is {type(v).__name__}, expected int"]
    if v != SCHEMA_VERSION:
        return [f"{where}: written by schema v{v}; this tool reads "
                f"schema v{SCHEMA_VERSION}"]
    return []


def validate_metrics_row(row: Dict[str, Any], where: str = "row") -> List[str]:
    """Validate one metrics JSONL row (window or event)."""
    if not isinstance(row, dict):
        return [f"{where}: not an object"]
    verrs = _version_errs(row, "v", where)
    if verrs:
        return verrs
    errs = _check(row, METRICS_COMMON, where)
    kind = row.get("kind") if isinstance(row, dict) else None
    if kind == "window":
        errs += _check(row, METRICS_WINDOW, where)
    elif kind == "event":
        errs += _check(row, METRICS_EVENT, where)
    elif kind is not None:
        errs.append(f"{where}: unknown kind {kind!r}")
    return errs


def validate_metrics_file(path: str) -> List[str]:
    """Validate every line of a metrics.<proc>.jsonl file."""
    errs: List[str] = []
    with open(path) as f:
        for i, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                row = json.loads(line)
            except ValueError as e:
                errs.append(f"line {i}: not JSON ({e})")
                continue
            errs += validate_metrics_row(row, where=f"line {i}")
    return errs


def validate_flight_dump(doc: Dict[str, Any],
                         where: str = "dump") -> List[str]:
    """Validate a flight/<proc>.json document, including every step
    and anomaly record inside it."""
    if not isinstance(doc, dict):
        return [f"{where}: not an object"]
    verrs = _version_errs(doc, "version", where)
    if verrs:
        return verrs
    errs = _check(doc, FLIGHT_DUMP, where)
    if isinstance(doc, dict):
        for i, rec in enumerate(doc.get("steps") or []):
            errs += _check(rec, FLIGHT_STEP_RECORD, f"{where}.steps[{i}]")
        for i, rec in enumerate(doc.get("windows") or []):
            errs += _check(rec, FLIGHT_STEP_RECORD,
                           f"{where}.windows[{i}]")
        for i, rec in enumerate(doc.get("anomalies") or []):
            errs += _check(rec, FLIGHT_ANOMALY_RECORD,
                           f"{where}.anomalies[{i}]")
        exc = doc.get("exception")
        if exc is not None and not isinstance(exc, dict):
            errs.append(f"{where}: exception must be an object")
    return errs


def validate_version(doc: Dict[str, Any], field: str = "v",
                     where: str = "doc") -> List[str]:
    """Public version-only check, for documents whose body has no
    field spec here (e.g. the chief's flight/report.json collate):
    precise old-format diagnosis, nothing else."""
    if not isinstance(doc, dict):
        return [f"{where}: not an object"]
    return _version_errs(doc, field, where)


def validate_run_report(doc: Dict[str, Any],
                        where: str = "report") -> List[str]:
    """Validate an aggregate.py run report (its top-level contract +
    the goodput bucket names)."""
    if not isinstance(doc, dict):
        return [f"{where}: not an object"]
    verrs = _version_errs(doc, "v", where)
    if verrs:
        return verrs
    errs = _check(doc, RUN_REPORT, where)
    if doc.get("kind") != "run_report":
        errs.append(f"{where}: kind is {doc.get('kind')!r}, expected "
                    f"'run_report'")
    buckets = (doc.get("goodput") or {}).get("buckets")
    if isinstance(buckets, dict):
        from .aggregate import BUCKETS

        missing = [b for b in BUCKETS if b not in buckets]
        if missing:
            errs.append(f"{where}: goodput.buckets missing {missing}")
    return errs


def validate_flight_file(path: str) -> List[str]:
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        return [f"{path}: unreadable ({e})"]
    return validate_flight_dump(doc, where=path)
