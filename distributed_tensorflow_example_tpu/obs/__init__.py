"""Training telemetry (observability beyond the TB event file).

The reference's only observability is the per-step cost/accuracy
scalars in its TensorBoard event log (/root/reference/example.py:
124-128, 163) plus a Step/Epoch/Cost stdout line every 100 steps
(example.py:166-174) — reproduced by utils/summary.py and
train/loop.py. This package adds the telemetry layer production
training systems rely on for throughput accounting and straggler
diagnosis (MegaScale, arXiv:2402.15627):

    flops       analytic per-model FLOPs + chip peaks — the ONE
                MFU accounting shared by the train loop, bench.py
                and the tests
    metrics     MetricsLogger: one JSON object per logging window
                appended to <logs_path>/metrics.<proc>.jsonl
                (step-time percentiles, data-wait/dispatch/device
                split, examples/sec, MFU, RSS, device memory)
    heartbeat   per-process heartbeat files at window boundaries +
                the chief's straggler report

Enabled by ``--metrics`` (with ``--log_every`` windows); grad/param
norm histograms ride the event file via ``--histograms``
(utils/summary.py's HistogramProto support). See
docs/observability.md.
"""

from .flops import (  # noqa: F401
    PEAK_BF16_FLOPS,
    attention_flops,
    chip_peak_flops,
    mfu,
    mlp_flops_per_step,
    model_flops_per_step,
    tokens_per_example,
)
from .heartbeat import Heartbeat, read_heartbeats, straggler_report  # noqa: F401
from .metrics import MetricsLogger, WindowTimer, read_metrics  # noqa: F401
