"""Training telemetry (observability beyond the TB event file).

The reference's only observability is the per-step cost/accuracy
scalars in its TensorBoard event log (/root/reference/example.py:
124-128, 163) plus a Step/Epoch/Cost stdout line every 100 steps
(example.py:166-174) — reproduced by utils/summary.py and
train/loop.py. This package adds the telemetry layer production
training systems rely on for throughput accounting and straggler
diagnosis (MegaScale, arXiv:2402.15627):

    flops       analytic per-model FLOPs + chip peaks — the ONE
                MFU accounting shared by the train loop, bench.py
                and the tests
    metrics     MetricsLogger: one JSON object per logging window
                appended to <logs_path>/metrics.<proc>.jsonl
                (step-time percentiles, data-wait/dispatch/device
                split, examples/sec, MFU, RSS, device memory)
    heartbeat   per-process heartbeat files at window boundaries +
                the chief's straggler report

and the failure-forensics layer (the run explains its own failures):

    tracer      WindowedTracer: programmatic --profile_steps
                START:COUNT profiler capture around exact steps,
                trace scopes named after the metrics buckets, the
                exception-safe whole-run --profile mode and the
                --profile_port on-demand profiler server
    anomaly     LossWatchdog (loss-EMA divergence) + AnomalyPolicy
                (--on_anomaly={halt,dump,skip} with skipped-step
                accounting and per-leaf blame); the compiled
                non-finite flags live in parallel/step.py
    flight      FlightRecorder: ring buffer of the last K step
                records + env snapshot, dumped to
                <logs_path>/flight/<proc>.json on crash, anomaly or
                SIGUSR1; chief-side collate() post-mortem report
    schema      the written-down metrics/flight/report format
                contract + validators (bench.py and tier-1 pin it),
                SCHEMA_VERSION stamped into every row

and the read side that consumes all of the above (PR 4):

    aggregate   fold one run's metrics/heartbeats/flight dumps into
                the run report — goodput/badput wall-time
                decomposition, cross-process step-time percentiles,
                MFU trajectory, anomaly/restart timeline
    compare     A/B two runs (or a run vs a BASELINE/BENCH row) with
                relative thresholds -> machine-readable regression
                verdict; bench.py --gate wires it into CI
    serve       stdlib-only live status server: /status JSON,
                /metrics Prometheus text, /report — started on the
                chief via --status_port, or offline re-serving
    cli         the ``dtx-obs`` console script: report / compare /
                tail / serve / validate / slo / trace / history

and the serving request-lifecycle layer (PR 12):

    spans       SpanRecorder: strict-JSON span stream
                (spans.<proc>.jsonl) narrating every accepted
                request's lifecycle through the decode engine
                (submit/blocked/admit/prefill/first_token/tick/
                retire), plus reconstruct() — the exactly-once
                per-request record /trace and dtx-obs trace serve
    slo         declarative SLO specs (ttft/latency/error-rate) with
                multi-window burn-rate evaluation over the span
                stream's tick index: /slo, the dtx_slo_* gauges and
                dtx-obs slo (exit 3 on breach)
    history     append-only bench history (history.jsonl): final
                summaries reduced to gate metrics, the rolling-median
                baseline behind bench.py --gate-rolling, and the
                dtx-obs history trend table / --import backfill

Enabled by ``--metrics`` (with ``--log_every`` windows); grad/param
norm histograms ride the event file via ``--histograms``
(utils/summary.py's HistogramProto support). See
docs/observability.md.
"""

# NOTE: the aggregate()/compare() FUNCTIONS are deliberately not
# re-exported at package level — they share their module's name, and
# rebinding ``obs.aggregate`` to a function would shadow the submodule
# (use ``obs.aggregate.aggregate`` / ``from ...obs.aggregate import
# aggregate``).
from .aggregate import BUCKETS, load_run, metrics_files, summary_line  # noqa: F401
from .anomaly import AnomalyError, AnomalyPolicy, LossWatchdog  # noqa: F401
from .compare import GATE_METRICS, extract_metrics  # noqa: F401
from .flight import FlightRecorder, collate, env_snapshot, read_flight  # noqa: F401
from .flops import (  # noqa: F401
    PEAK_BF16_FLOPS,
    attention_flops,
    chip_peak_flops,
    mfu,
    mlp_flops_per_step,
    model_flops_per_step,
    tokens_per_example,
)
from .heartbeat import (  # noqa: F401
    Heartbeat,
    clear_stale_signals,
    read_heartbeats,
    straggler_report,
)
from .metrics import MetricsLogger, WindowTimer, read_metrics  # noqa: F401
from .schema import (  # noqa: F401
    SCHEMA_VERSION,
    validate_flight_dump,
    validate_flight_file,
    validate_history_entry,
    validate_history_file,
    validate_metrics_file,
    validate_metrics_row,
    validate_run_report,
    validate_span_file,
    validate_span_row,
    validate_version,
)
from .serve import StatusServer, collect_status, prometheus_text  # noqa: F401
from .slo import DEFAULT_SLOS, SLOSpec, parse_specs  # noqa: F401
from .spans import SpanRecorder, read_spans, reconstruct, span_files  # noqa: F401
from .tracer import WindowedTracer, parse_profile_steps  # noqa: F401
