"""Training telemetry (observability beyond the TB event file).

The reference's only observability is the per-step cost/accuracy
scalars in its TensorBoard event log (/root/reference/example.py:
124-128, 163) plus a Step/Epoch/Cost stdout line every 100 steps
(example.py:166-174) — reproduced by utils/summary.py and
train/loop.py. This package adds the telemetry layer production
training systems rely on for throughput accounting and straggler
diagnosis (MegaScale, arXiv:2402.15627):

    flops       analytic per-model FLOPs + chip peaks — the ONE
                MFU accounting shared by the train loop, bench.py
                and the tests
    metrics     MetricsLogger: one JSON object per logging window
                appended to <logs_path>/metrics.<proc>.jsonl
                (step-time percentiles, data-wait/dispatch/device
                split, examples/sec, MFU, RSS, device memory)
    heartbeat   per-process heartbeat files at window boundaries +
                the chief's straggler report

and the failure-forensics layer (the run explains its own failures):

    tracer      WindowedTracer: programmatic --profile_steps
                START:COUNT profiler capture around exact steps,
                trace scopes named after the metrics buckets, the
                exception-safe whole-run --profile mode and the
                --profile_port on-demand profiler server
    anomaly     LossWatchdog (loss-EMA divergence) + AnomalyPolicy
                (--on_anomaly={halt,dump,skip} with skipped-step
                accounting and per-leaf blame); the compiled
                non-finite flags live in parallel/step.py
    flight      FlightRecorder: ring buffer of the last K step
                records + env snapshot, dumped to
                <logs_path>/flight/<proc>.json on crash, anomaly or
                SIGUSR1; chief-side collate() post-mortem report
    schema      the written-down metrics/flight format contract +
                validators (bench.py and tier-1 pin it)

Enabled by ``--metrics`` (with ``--log_every`` windows); grad/param
norm histograms ride the event file via ``--histograms``
(utils/summary.py's HistogramProto support). See
docs/observability.md.
"""

from .anomaly import AnomalyError, AnomalyPolicy, LossWatchdog  # noqa: F401
from .flight import FlightRecorder, collate, env_snapshot, read_flight  # noqa: F401
from .flops import (  # noqa: F401
    PEAK_BF16_FLOPS,
    attention_flops,
    chip_peak_flops,
    mfu,
    mlp_flops_per_step,
    model_flops_per_step,
    tokens_per_example,
)
from .heartbeat import Heartbeat, read_heartbeats, straggler_report  # noqa: F401
from .metrics import MetricsLogger, WindowTimer, read_metrics  # noqa: F401
from .schema import (  # noqa: F401
    validate_flight_dump,
    validate_flight_file,
    validate_metrics_file,
    validate_metrics_row,
)
from .tracer import WindowedTracer, parse_profile_steps  # noqa: F401
