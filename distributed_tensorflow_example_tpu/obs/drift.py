"""Model-vs-measured drift detection over the bench trajectory.

The rolling gate (``bench.py --gate-rolling``, PR 12) answers "is
THIS run worse than the recent median?"; this module answers the
post-mortem question the gate can't: "WHEN did the trajectory move,
and which round moved it?"  It runs change-point detection over the
``history.jsonl`` window — for every metric, every split point's
pre/post medians are compared and the split with the largest shift
in the metric's REGRESSION direction (obs/compare.GATE_METRICS knows
which way is worse) wins; a confirmed drift names the metric, the
window, the split, and the FIRST offending row label, which is
exactly what a bisect needs.  Medians on both sides make one noisy
round invisible — a confirmed drift is a level shift, not a spike.

The roofline join closes the loop with the analytic cost models: the
measured decode throughput's achieved HBM bytes/s
(``decode_achieved_gbps``, from ``decode_bytes_per_step`` /
``obs/flops.py``) against the chip's peak.  Off-TPU the peak is
unknown (``chip_peak_hbm_bytes`` -> None), so the join is
INFORMATIONAL there and never confirms a drift by itself — the
history trajectory of ``decode_hbm_frac`` is the gated signal.

``dtx-obs drift HISTORY`` prints the DRIFT_REPORT document (schema
v8) and exits 3 on confirmed drift, 0 clean, 2 on unusable input.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

from . import history as hist_lib
from .compare import GATE_METRICS
from .schema import SCHEMA_VERSION

# a change-point needs >= 2 entries on each side of the split
MIN_ENTRIES = 4

# default tolerance floor: twice the metric's gate threshold (a drift
# is a SUSTAINED move, so it must clear the per-run gate band), never
# below 5% (medians of short benches wobble)
TOL_FLOOR = 0.05


def _median(vals: List[float]) -> float:
    s = sorted(vals)
    n = len(s)
    return s[n // 2] if n % 2 else 0.5 * (s[n // 2 - 1] + s[n // 2])


def _tolerance(metric: str, override: Optional[float]) -> float:
    if override is not None:
        return override
    thr = GATE_METRICS.get(metric, (None, 0.0))[1]
    return max(2.0 * thr, TOL_FLOOR)


def detect(labels: List[str], values: List[float], metric: str,
           tolerance: Optional[float] = None) -> Optional[dict]:
    """Change-point detection on one metric's series: the split
    whose pre/post medians shift most in the metric's regression
    direction; a shift beyond tolerance is a confirmed drift naming
    the first offending row.  None = no confirmed drift."""
    n = len(values)
    if n < MIN_ENTRIES:
        return None
    direction = GATE_METRICS.get(metric, ("any",))[0]
    tol = _tolerance(metric, tolerance)
    best = None  # (score, split, pre_med, post_med)
    for k in range(2, n - 1):
        pre = _median(values[:k])
        post = _median(values[k:])
        if pre == 0:
            continue
        shift = (post - pre) / abs(pre)
        # score only the regression direction: "lower"-is-better
        # metrics drift UP, "higher"-is-better drift DOWN; metrics
        # without a gate direction drift either way
        if direction == "lower":
            score = shift
        elif direction == "higher":
            score = -shift
        else:
            score = abs(shift)
        if score > (best[0] if best else 0.0):
            best = (score, k, pre, post)
    if best is None or best[0] <= tol:
        return None
    score, k, pre, post = best
    # the first row at/after the split already beyond the pre-median
    # by the tolerance, in the regression direction — the row a
    # bisect starts from (the split itself is the fallback)
    first = k
    for i in range(k, n):
        v = values[i]
        if direction == "lower" and v > pre * (1.0 + tol):
            first = i
            break
        if direction == "higher" and v < pre * (1.0 - tol):
            first = i
            break
        if direction not in ("lower", "higher") \
                and abs(v - pre) / abs(pre) > tol:
            first = i
            break
    return {
        "metric": metric,
        "direction": direction,
        "n": n,
        "split": k,
        "pre_median": round(pre, 6),
        "post_median": round(post, 6),
        "shift_frac": round((post - pre) / abs(pre), 6),
        "tolerance": round(tol, 6),
        "first_offending": labels[first],
        "first_offending_index": first,
        "first_offending_value": values[first],
    }


def _roofline(capture_path: str) -> dict:
    """Join a bench capture's measured decode throughput against the
    analytic HBM closed forms: achieved bytes/s vs the chip peak.
    Off-TPU the peak is unknown — the join reports what it measured
    and says so, instead of fabricating a fraction."""
    from . import compare as cmp_lib
    from . import flops as flops_lib

    doc = cmp_lib.load_doc(capture_path)
    metrics = cmp_lib.extract_metrics(doc)
    peak = flops_lib.chip_peak_hbm_bytes()
    out: dict = {
        "capture": capture_path,
        "decode_hbm_frac": metrics.get("decode_hbm_frac"),
        "chip_peak_hbm_gbps": (round(peak / 1e9, 1)
                               if peak is not None else None),
    }
    if peak is None:
        out["note"] = ("chip HBM peak unknown on this backend — "
                       "informational only; the decode_hbm_frac "
                       "history trajectory is the gated signal")
    return out


def drift_report(history_path: str, window: int = 0,
                 tolerance: Optional[float] = None,
                 metrics: Optional[List[str]] = None,
                 capture: Optional[str] = None) -> dict:
    """The DRIFT_REPORT document (schema v8): change-point detection
    over the last ``window`` history entries (0 = all) for every
    numeric metric present in >= MIN_ENTRIES of them (or the explicit
    ``metrics`` list), plus the optional roofline join."""
    entries = hist_lib.read_history(history_path)
    if window > 0:
        entries = entries[-window:]
    labels = [str(e.get("label")) for e in entries]
    series: Dict[str, List[tuple]] = {}
    for i, e in enumerate(entries):
        for name, v in (e.get("metrics") or {}).items():
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                series.setdefault(name, []).append((i, float(v)))
    names = (metrics if metrics
             else sorted(n for n, s in series.items()
                         if len(s) >= MIN_ENTRIES))
    drifts = []
    for name in names:
        pts = series.get(name) or []
        if len(pts) < MIN_ENTRIES:
            continue
        d = detect([labels[i] for i, _v in pts],
                   [v for _i, v in pts], name, tolerance)
        if d is not None:
            drifts.append(d)
    doc = {
        "v": SCHEMA_VERSION,
        "kind": "drift_report",
        "generated_t": time.time(),
        "history_path": history_path,
        "entries": len(entries),
        "window": window,
        "metrics": names,
        "drifts": drifts,
        "roofline": _roofline(capture) if capture else None,
        "ok": not drifts,
    }
    return doc
