"""The shared trace-scope / goodput-bucket name registry.

Before this module the same bucket strings lived in four places —
``WindowTimer``'s charge sites (obs/metrics.py), the tracer scope
names (obs/tracer.py docstring + call sites), ``aggregate.BUCKETS``
and the ``*_s`` fields of ``schema.METRICS_WINDOW`` — and nothing but
review discipline kept them in lockstep.  A renamed bucket would
silently split one cost across two names (charged under the new one,
aggregated/validated under the old).  This module is the ONE source
of truth; the other four point at it, ``dtx-lint``'s
``scope-registry`` rule checks every literal call site against it,
and ``schema.py`` asserts its contract matches at import time.

Names are grouped by the surface they name:

- ``WINDOW_BUCKETS`` — the host-loop wall buckets ``WindowTimer``
  charges per window; each becomes the ``<name>_s`` field of a
  metrics window row.  ``host`` is NOT here: it is the computed
  residual (wall minus every charged bucket), never charged directly.
- ``TRACE_SCOPES`` — valid ``WindowedTracer.annotate`` names: the
  window buckets plus the non-step phases (``eval``, ``checkpoint``)
  that annotate host work outside the step window.
- ``NAMED_SCOPES`` — ``jax.named_scope`` regions inside the compiled
  forward (models/transformer.py) that attribute device time in a
  captured trace to the bench breakdowns.
- ``GOODPUT_BUCKETS`` — the run-level wall-time decomposition
  ``aggregate.aggregate`` reports (presentation order; sums to wall).
"""

from __future__ import annotations

# host-loop per-window charge buckets (field "<name>_s" in every
# metrics window row; "host" is the residual field computed from
# them). "ckpt" is the async-checkpoint submit stall (the host wall
# of handing a snapshot to the write-behind thread,
# resilience/writer.py) — the bucket whose staying-near-zero IS the
# async-checkpointing claim, gated via bench_checkpoint.
WINDOW_BUCKETS = ("data_wait", "h2d", "dispatch", "device_wait",
                  "ckpt")

# the residual bucket name (field "host_s"): wall not charged above
HOST_BUCKET = "host"

# valid WindowedTracer.annotate scope names: the charge buckets plus
# the out-of-step-window host phases
TRACE_SCOPES = WINDOW_BUCKETS + ("eval", "checkpoint")

# jax.named_scope regions inside the compiled step (transformer
# forward): device-timeline attribution for the bench breakdowns.
# "pp_comm" names the pipeline stage-hop collectives (the async
# ppermute start/done pairs in transformer._hop_start) so a profiler
# capture shows the transfer overlapping the opposite direction's
# compute instead of folding it into anonymous collective time.
# "prefill"/"decode"/"sampling" name the serving engine's phases
# (serving/engine.py compiled programs): a capture of the decode
# engine splits prompt ingestion, the paged decode step, and the
# fused on-device sampling.
# "outer_sync" names the multi-site round's one cross-site collective
# (parallel/local_sgd.py: the pseudo-gradient psum + outer optimizer
# update), so a profiler capture shows exactly how much of a round
# the slow-axis sync costs.
# "quant" names the quantize/dequantize edges (ops/quant.py callers:
# the int8 KV-page adapter in serving/kv_cache.py, the fp8 operand
# rounding in ops/pallas_fused.py, the compressed outer sync in
# parallel/local_sgd.py) so a capture attributes the low-precision
# conversion cost separately from the compute it feeds.
NAMED_SCOPES = ("ln", "moe_dispatch", "moe_expert", "pp_comm",
                "prefill", "decode", "sampling", "outer_sync",
                "quant")

# run-level goodput/badput decomposition, in presentation order
# ("train" is the goodput bucket, "eval"/"sample" auxiliary useful
# work, the rest badput — "ckpt" is the checkpoint submit stall,
# kept near zero by the write-behind writer); aggregate.BUCKETS
# re-exports this
GOODPUT_BUCKETS = ("train", "compile", "data_wait", "h2d", "ckpt",
                   "host", "eval", "sample", "anomaly_skipped",
                   "straggler_idle", "untracked")

# serving request-lifecycle span events (obs/spans.py): the ONE
# vocabulary for the spans.<proc>.jsonl stream.  The exactly-once
# milestones (submit/admit/prefill/first_token/retire) plus the
# repeatable records (blocked — once per tick a waiter stays blocked,
# with its reason; tick — one per shared decode step, carrying batch
# occupancy; error — the engine loop died with the request in
# flight).  SpanRecorder.emit validates against this tuple (the
# WindowTimer.charge discipline) and obs/schema.py pins the per-event
# field contract, so a drifted event name fails at the emit site, not
# in a consumer months later.
#
# The fail-open terminals and supervision records (PR 15): every
# accepted request ends in EXACTLY ONE of retire ("result") /
# "timeout" (deadline expiry or client cancel — reason says which) /
# "shed" (bounded-queue rejection, the only terminal without a
# submit: the request was never accepted) / "failed" (the supervised
# engine's per-request retry budget spent, or — via the legacy
# "error" event — an unsupervised loop death).  "requeue" marks a
# supervised re-admission (its admit/prefill/first_token milestones
# reset), "engine_restart" one supervised loop restart (carries the
# in-flight rids, like a tick row).  obs/spans.reconstruct() is
# closed over this set and classifies each record's ``terminal``.
# "phase" (PR 16) is the TRAINING-side span: one row per completed
# train-loop phase (a multi-site round, the outer_sync collective, a
# checkpoint submit) carrying ``phase``/``trace_id``/``dur_ms`` so the
# fleet collector can interleave training rounds with serving request
# lifecycles on one timeline.  Valid phase names live in PHASE_SCOPES.
# "tick_done" (PR 17) closes the tick the scheduler's tick row opened:
# the engine emits it after the boundary's prefill+decode execution
# with the execution-only ``dur_ms``, so the waterfall can split a
# decode interval into active compute vs stall (fault-injected sleeps,
# host scheduling gaps) — the tick-boundary timestamp pair the
# per-request latency attribution (obs/waterfall.py) segments on.
# "route"/"failover" (PR 18) are the fleet ROUTER's narration: one
# "route" per placement (fleet rid, replica name, carried attempt
# count) and one "failover" per cross-engine re-submit (plus the
# reason) — they describe WHERE a request went, while the lifecycle
# truth stays in the replica streams; obs/spans.reconstruct() treats
# a record holding only these rows as narration, not a lifecycle.
# v10 (ISSUE 19) adds no NEW events — workload capture/replay rides
# the existing vocabulary: "submit" rows gain the optional
# ``fingerprint`` chain (schema.SPAN_FIELDS) and replayed runs stamp
# every row with ``replay_of`` via serving/replay.replay_recorder.
SPAN_EVENTS = ("submit", "blocked", "admit", "prefill", "first_token",
               "tick", "tick_done", "retire", "error", "timeout",
               "shed", "requeue", "engine_restart", "failed", "phase",
               "route", "failover")

# per-request latency waterfall segments (obs/waterfall.py), in
# presentation order — the goodput-buckets discipline applied to ONE
# request: disjoint intervals that partition submit→terminal wall.
# "queue_wait" = submitted but not admitted (slot/page waits),
# "brownout_clamp_delay" = blocked specifically by the brownout
# governor, "prefill" = admit→first_token, "decode_active" = decode
# execution, "decode_stall" = tick gaps not covered by execution
# (injected stalls, host scheduling), "requeue" = engine-restart
# recovery until re-admission, "finalize" = last tick end→terminal
# bookkeeping, "untracked" = defensive residual (should be 0).
WATERFALL_SEGMENTS = ("queue_wait", "brownout_clamp_delay", "prefill",
                      "decode_active", "decode_stall", "requeue",
                      "finalize", "untracked")

# valid "phase" span names (train/loop.py emit sites): "round" is one
# multi-site dispatch (site_mode), "outer_sync" the cross-site
# pseudo-gradient exchange, "ckpt" the checkpoint snapshot submit.
# The scope-registry discipline applies: emit sites pass these
# literals and obs/schema.py requires the field on every phase row.
PHASE_SCOPES = ("round", "outer_sync", "ckpt")

# restart-timeline events (resilience/restart.py RestartNarrator
# appends them to restarts.jsonl; obs/aggregate.py folds them into
# the run-report timeline): the preemption/recovery lifecycle
# ("preempt" = a SIGTERM/SIGINT landed, "snapshot" = the write-behind
# writer persisted one, "resumed" = --resume=auto picked the run back
# up) plus the chief-side elastic decisions ("dead_proc" detection,
# Supervisor "attempt_start"/"attempt_exit", the policy verdicts
# "retry"/"reform"/"give_up"). RestartNarrator.emit validates against
# this tuple (the SpanRecorder discipline) and obs/schema.py pins the
# row envelope.  "engine_restart" is the SERVING supervisor's entry
# (serving/engine.py _recover): the decode-engine loop died and was
# restarted in place with its in-flight requests re-queued — the
# restarts.jsonl timeline spans training preemptions and serving
# loop deaths alike, and dtx-obs report folds both.
RESTART_EVENTS = ("preempt", "snapshot", "resumed", "dead_proc",
                  "attempt_start", "attempt_exit", "retry", "reform",
                  "give_up", "engine_restart")
