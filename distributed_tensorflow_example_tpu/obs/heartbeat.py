"""Multi-process heartbeats + the chief's straggler report.

Each process touches ``<logs_path>/heartbeat.<proc>`` at window
boundaries with its current step and wall time (atomic
write-then-rename, so a reader never sees a torn file). The chief
reads every peer's file at epoch end and folds a straggler summary —
max step lag, the slowest process, the oldest heartbeat age — into
its metrics stream (obs/metrics.MetricsLogger), which is how
production systems localize slow hosts without a profiler attach
(MegaScale-style; the reference has no multi-worker health signal at
all beyond the Supervisor's internal ready-polling)."""

from __future__ import annotations

import glob
import json
import os
import time
from typing import Dict, Optional, Tuple


class Heartbeat:
    """Writer side: ``touch(step)`` at window boundaries."""

    def __init__(self, logs_path: str, process_index: int = 0):
        os.makedirs(logs_path, exist_ok=True)
        self.process_index = int(process_index)
        self.path = os.path.join(logs_path,
                                 f"heartbeat.{self.process_index}")
        # a dead run's file for THIS index must not leak into the new
        # run's report (each process clears only its own file — no
        # cross-process race); peers from a previous wider run are
        # excluded by straggler_report's `since` filter
        try:
            os.remove(self.path)
        except OSError:
            pass

    def touch(self, step: int) -> None:
        # best-effort like the metrics stream: a full volume must not
        # kill the run the heartbeat is monitoring
        try:
            tmp = self.path + ".tmp"
            with open(tmp, "w") as f:
                json.dump({"proc": self.process_index, "step": int(step),
                           "t": time.time()}, f)
            os.replace(tmp, self.path)  # atomic on POSIX
        except OSError:
            pass


# flight-dump reasons a RESUMING run must keep: a preemption dump is
# the restart's forensic evidence — clearing it at relaunch would
# erase the very event the restart timeline exists to show
_PRESERVED_FLIGHT_REASONS = ("sigterm", "preempt")


def clear_stale_signals(logs_path: str, resuming: bool = False) -> int:
    """Run-start hygiene, chief-only: remove a previous run's leftover
    per-process signal files from a reused ``logs_path`` — every
    ``heartbeat.*`` (a dead run's peers would otherwise fabricate
    stragglers beyond what ``straggler_report(since=...)`` fences) and
    every ``flight/*.json`` incl. ``report.json`` (a stale dump would
    collate into THIS run's post-mortem and dtx-obs report would mix
    runs). The metrics jsonl streams are append-only history and stay,
    as does the restart timeline (``restarts.jsonl``) — its whole
    point is spanning restarts.

    ``resuming`` (a ``--resume`` relaunch continuing the SAME run):
    the cleanup must not assume a fresh run — it spares every
    ``heartbeat.*`` (the chief's dead-process detection needs the
    preempted attempt's beats to tell a dead peer from a
    never-started one; this run's straggler stats still fence them
    out via ``since``) and every flight dump whose recorded reason is
    a preemption (``sigterm``/``preempt`` — the restart's evidence;
    crash/anomaly dumps from older runs still clear).

    Best-effort (a locked file must not kill the run); returns the
    number of files removed. A live peer's heartbeat written in the
    start-up race is re-touched at its next window boundary, so a
    spurious removal only delays that beat one window."""
    removed = 0
    if not resuming:
        for path in glob.glob(os.path.join(logs_path, "heartbeat.*")):
            try:
                os.remove(path)
                removed += 1
            except OSError:
                pass
    for path in glob.glob(os.path.join(logs_path, "flight", "*.json")):
        if resuming:
            try:
                with open(path) as f:
                    reason = json.load(f).get("reason")
            except (OSError, ValueError):
                reason = None  # torn dump: clear it
            if reason in _PRESERVED_FLIGHT_REASONS:
                continue
        try:
            os.remove(path)
            removed += 1
        except OSError:
            pass
    return removed


def read_heartbeats(logs_path: str) -> Dict[int, Tuple[int, float]]:
    """{proc: (step, wall_time)} for every heartbeat file present.
    A torn/absent file is skipped (its process simply looks stale)."""
    out: Dict[int, Tuple[int, float]] = {}
    for path in glob.glob(os.path.join(logs_path, "heartbeat.*")):
        if path.endswith(".tmp"):
            continue
        try:
            with open(path) as f:
                row = json.load(f)
            out[int(row["proc"])] = (int(row["step"]), float(row["t"]))
        except (OSError, ValueError, KeyError):
            continue
    return out


def straggler_report(logs_path: str,
                     now: Optional[float] = None,
                     since: Optional[float] = None) -> Dict[str, object]:
    """Fold the heartbeat files into the chief's straggler summary:
    ``max_step_lag`` (front-runner step minus laggard step),
    ``slowest_proc`` (the laggard; ties break to the lowest index),
    ``oldest_heartbeat_age_s`` and the participating process count.
    ``since`` drops beats written before this run started (stale
    files from a previous, wider run sharing the logs_path would
    otherwise fabricate phantom stragglers)."""
    beats = read_heartbeats(logs_path)
    if since is not None:
        beats = {p: (s, t) for p, (s, t) in beats.items() if t >= since}
    if not beats:
        return {"procs": 0, "max_step_lag": None, "slowest_proc": None,
                "oldest_heartbeat_age_s": None}
    now = time.time() if now is None else now
    steps = {p: s for p, (s, _t) in beats.items()}
    lead = max(steps.values())
    slowest = min(sorted(steps), key=lambda p: steps[p])
    oldest = min(t for _s, t in beats.values())
    return {
        "procs": len(beats),
        "max_step_lag": lead - steps[slowest],
        "slowest_proc": slowest,
        "oldest_heartbeat_age_s": round(max(0.0, now - oldest), 3),
    }
