"""Fleet span collector: N run dirs -> one causally-ordered timeline.

Every observability surface before this module is single-run: one
spans dir, one /slo, one report per process tree.  The paper's whole
premise is a multi-process cluster, and the multi-engine router
(ROADMAP item 1) needs to follow a request or a training round across
engines.  This module is that substrate:

- **discovery** mirrors ``aggregate.metrics_files``: each *source* is
  a run dir (identified by containing at least one
  ``spans.*.jsonl`` / ``metrics.*.jsonl`` / ``restarts.jsonl``
  stream); a path argument may be a run dir itself or a parent whose
  immediate children are run dirs — ``dtx-obs collect logs/*`` just
  works;
- **merge** stitches every source's span stream (across rotation
  boundaries — ``read_spans`` handles the ``.1``…``.K`` segments),
  restart timeline and metrics events into ONE time-ordered list.
  Each merged row gains a ``source`` stamp and a REWRITTEN globally
  unique ``proc`` (one per (source, original proc) pair) — engines
  all number rids from 0, and ``reconstruct()`` keys records on
  ``(proc, rid)``, so the rewrite is exactly what makes the PR 15
  terminates-typed invariant checkable fleet-wide with the same fold
  that checks it per-engine;
- **clock-skew alignment**: sources stamp rows with their own
  ``time.time()``; hosts drift.  Aligning each source's first row to
  the fleet's earliest first row (a per-source constant offset —
  monotonic within each source, so intra-source ordering is
  preserved) puts concurrently-started runs on one axis; the applied
  offset is reported per source, never silently;
- **Perfetto/Chrome export** (``chrome_trace``): the merged timeline
  as Chrome trace-event JSON — one process track per source, one
  thread track per request with the lifecycle phases (queued /
  prefill / decode) nested inside the request span, training phase
  spans on their own track, restart/anomaly instants — openable
  directly in ui.perfetto.dev;
- **fleet report** (``fleet_report``): the ``FLEET_REPORT`` schema
  document — per-source row/skew accounting, the fleet-wide
  exactly-once verdict from ``reconstruct()`` over the merged stream,
  and the federated SLO evaluation (``slo.fleet_evaluate``) whose
  closed-form identity cross-checks the merge itself.
"""

from __future__ import annotations

import glob
import json
import os
import time
from typing import Any, Dict, Iterable, List, Optional, Tuple

from . import slo as slo_lib
from . import waterfall as wf_lib
from .aggregate import has_streams as _has_streams
from .aggregate import metrics_files
from .queueing import queueing_report
from .schema import SCHEMA_VERSION
from .spans import read_spans, reconstruct, span_files

# cap on the errors list a fleet report carries (the load_run
# max_errors discipline): a corrupt fleet should diagnose, not flood
MAX_REPORT_ERRORS = 50


def discover_sources(paths: Iterable[str]) -> List[Tuple[str, str]]:
    """``[(name, dir)]`` for every run dir reachable from ``paths``
    (each entry a run dir itself, or a parent whose immediate children
    are run dirs), sorted by name.  The name is the dir basename,
    suffixed with ``#N`` on collision — a source label must be unique
    because the federated SLO groups on it."""
    dirs: List[str] = []
    for p in paths:
        p = os.path.normpath(p)
        if os.path.isdir(p) and _has_streams(p):
            dirs.append(p)
            continue
        if os.path.isdir(p):
            for child in sorted(glob.glob(os.path.join(p, "*"))):
                if os.path.isdir(child) and _has_streams(child):
                    dirs.append(child)
    out: List[Tuple[str, str]] = []
    seen: Dict[str, int] = {}
    for d in sorted(dict.fromkeys(dirs),
                    key=lambda d: os.path.basename(d)):
        name = os.path.basename(d) or d
        n = seen.get(name, 0)
        seen[name] = n + 1
        out.append((name if n == 0 else f"{name}#{n}", d))
    return out


def _read_jsonl(path: str) -> List[Dict[str, Any]]:
    rows = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rows.append(json.loads(line))
                except ValueError:
                    continue
    except OSError:
        pass
    return rows


def collect(paths: Iterable[str],
            align: bool = True) -> Dict[str, Any]:
    """Merge every discovered source's streams into one timeline.

    Returns ``{"rows", "sources"}``: ``rows`` time-ordered across
    sources, each stamped with ``source`` and a globally unique
    ``proc``; ``sources`` the per-source accounting (name, dir, row
    count, proc count, applied ``skew_s``).  Raises FileNotFoundError
    when no source has any stream — same contract as
    ``aggregate.load_run`` on an empty dir."""
    found = discover_sources(paths)
    if not found:
        raise FileNotFoundError(
            f"no span/metrics/restart streams under {list(paths)}")
    per_src: List[Dict[str, Any]] = []
    for name, d in found:
        rows: List[Dict[str, Any]] = []
        for _pid, path in span_files(d):
            rows.extend(read_spans(path))   # stitches rotations
        rows.extend(_read_jsonl(os.path.join(d, "restarts.jsonl")))
        for _pid, path in metrics_files(d):
            # metrics "event" rows (run_start/run_end/...) are point
            # markers worth a place on the fleet timeline; window
            # rows are per-window aggregates, not events — skipped
            rows.extend(r for r in _read_jsonl(path)
                        if r.get("kind") == "event")
        rows.sort(key=lambda r: (r.get("t") or 0.0))
        per_src.append({"source": name, "dir": d, "raw": rows})

    # per-source monotonic skew alignment: shift every source by a
    # constant so its first row lands on the fleet's earliest first
    # row.  Constant per source => intra-source order is untouched.
    starts = [src["raw"][0].get("t") or 0.0
              for src in per_src if src["raw"]]
    ref0 = min(starts) if starts else 0.0
    merged: List[Dict[str, Any]] = []
    sources: List[Dict[str, Any]] = []
    proc_map: Dict[Tuple[str, int], int] = {}
    for src in per_src:
        raw = src["raw"]
        skew = ((raw[0].get("t") or 0.0) - ref0) if raw else 0.0
        offset = -skew if align else 0.0
        procs = set()
        for r in raw:
            row = dict(r)
            orig_proc = int(row.get("proc") or 0)
            procs.add(orig_proc)
            key = (src["source"], orig_proc)
            if key not in proc_map:
                proc_map[key] = len(proc_map)
            row["proc"] = proc_map[key]
            row["source"] = src["source"]
            if offset and row.get("t") is not None:
                row["t"] = row["t"] + offset
            merged.append(row)
        sources.append({
            "source": src["source"], "dir": src["dir"],
            "rows": len(raw), "procs": len(procs),
            "skew_s": round(skew if align else 0.0, 6),
        })
    merged.sort(key=lambda r: (r.get("t") or 0.0))
    return {"rows": merged, "sources": sources}


def fleet_report(paths: Iterable[str],
                 specs: Optional[List[slo_lib.SLOSpec]] = None,
                 align: bool = True) -> Dict[str, Any]:
    """The ``FLEET_REPORT`` document over merged streams: per-source
    accounting, the fleet-wide exactly-once verdict (every request
    reconstructed from the merged stream carries exactly one typed
    terminal and a clean errors list), restart count and the
    federated SLO evaluation."""
    col = collect(paths, align=align)
    span_rows = [r for r in col["rows"] if r.get("kind") == "span"]
    recs = reconstruct(span_rows)
    errors: List[str] = []
    exactly_once = True
    # router narration records (route/failover rows only) describe
    # placements, not lifecycles: they neither count as requests nor
    # enter the SLO fold
    lifecycles = {k: rec for k, rec in recs.items()
                  if not rec.get("narration")}
    for (proc, rid), rec in sorted(recs.items()):
        # a terminal-free record with a clean errors list is simply
        # still in flight — not a violation; anything in errors
        # (duplicate milestone, multiple terminals, broken trace
        # chain, …) breaks the fleet-wide exactly-once verdict
        if rec["errors"]:
            exactly_once = False
            src = rec.get("source") or f"proc{proc}"
            for e in rec["errors"]:
                errors.append(f"{src} rid {rid}: {e}")
    # cross-engine failover join (v9): a request the router moved
    # spans one lifecycle PER HOP, tied together by its stable
    # trace_id.  Fleet-wide exactly-once then means: every
    # intermediate hop closed with a typed "failed" (the replica's
    # budget verdict) or "shed" (refused at the door, placed
    # elsewhere), and exactly the LAST hop carries the
    # client-delivered terminal.  An intermediate "result"/"timeout"
    # would be a double answer — flagged.
    by_trace: Dict[str, List[tuple]] = {}
    for key, rec in lifecycles.items():
        tid = rec.get("trace_id")
        if isinstance(tid, str):
            by_trace.setdefault(tid, []).append((key, rec))
    chains = 0
    hops = 0
    chain_terminals: Dict[str, int] = {}
    intermediate: set = set()
    clean = True
    for tid, members in sorted(by_trace.items()):
        if len(members) < 2:
            continue
        members.sort(key=lambda kr: (
            kr[1].get("submit_t") or kr[1].get("shed_t") or 0.0))
        chains += 1
        hops += len(members) - 1
        for key, rec in members[:-1]:
            intermediate.add(key)
            term = rec.get("terminal")
            if term in ("result", "timeout"):
                clean = False
                exactly_once = False
                src = rec.get("source") or f"proc{key[0]}"
                errors.append(
                    f"{src} rid {key[1]}: intermediate failover hop "
                    f"ended {term!r} (trace {tid}) — double-delivered")
        last = members[-1][1].get("terminal")
        if last is not None:
            chain_terminals[last] = chain_terminals.get(last, 0) + 1
    failover_doc = ({"chains": chains, "hops": hops, "clean": clean,
                     "terminals": chain_terminals}
                    if chains else None)
    restarts = sum(1 for r in col["rows"]
                   if r.get("event") == "engine_restart")
    # the federated SLO counts a failed-over request ONCE, with its
    # final terminal: intermediate hops (and router narration) are
    # carved out of the record stream before the fold
    excluded = intermediate | {k for k in recs if k not in lifecycles}
    slo_rows = [r for r in span_rows
                if r.get("rid") is None
                or (int(r.get("proc") or 0),
                    int(r["rid"])) not in excluded]
    slo_records = slo_lib.records_from_spans(slo_rows)
    slo_doc = (slo_lib.fleet_evaluate(slo_records, specs)
               if slo_records else None)
    return {
        "v": SCHEMA_VERSION,
        "kind": "fleet_report",
        "generated_t": time.time(),
        "sources": [{k: v for k, v in s.items() if k != "dir"}
                    for s in col["sources"]],
        "rows": len(col["rows"]),
        "requests": len(lifecycles),
        "exactly_once": exactly_once,
        "errors": errors[:MAX_REPORT_ERRORS],
        "restarts": restarts,
        "slo": slo_doc,
        # queueing analytics (v8, obs/queueing.py): arrival rate,
        # per-bucket service, utilization + the Little's-law identity
        # over the merged stream — None when nothing was submitted
        "queueing": queueing_report(span_rows),
        # cross-engine failover accounting (v9): the per-trace hop
        # chains the router produced — None when no request spanned
        # more than one lifecycle
        "failover": failover_doc,
    }


def _us(t: Optional[float]) -> float:
    return round((t or 0.0) * 1e6, 1)


def chrome_trace(rows: List[Dict[str, Any]]) -> Dict[str, Any]:
    """The merged timeline as Chrome trace-event JSON (the Perfetto
    import format): one process track per source, one thread per
    request (the request's lifecycle phases nested inside its span —
    same tid + contained intervals is the format's nesting rule),
    training phase spans on a dedicated thread, restart rows and
    legacy error spans as instant events.  Timestamps are the merged
    (skew-aligned) ``t`` in microseconds."""
    sources: List[str] = []
    src_pid: Dict[str, int] = {}
    events: List[Dict[str, Any]] = []

    def pid_for(row: Dict[str, Any]) -> int:
        src = str(row.get("source") or f"proc{row.get('proc', 0)}")
        if src not in src_pid:
            src_pid[src] = len(src_pid)
            sources.append(src)
            events.append({"ph": "M", "pid": src_pid[src], "tid": 0,
                           "name": "process_name",
                           "args": {"name": src}})
        return src_pid[src]

    span_rows = [r for r in rows if r.get("kind") == "span"]
    recs = reconstruct(span_rows)
    # per-request waterfall segments (PR 17): the exact attribution
    # partition nests under the coarse lifecycle slices
    falls = {(d["proc"], d["rid"]): d
             for d in wf_lib.waterfalls(span_rows)}
    # stable tid per request within its source track (rid collisions
    # across sources are fine — they live on different pids)
    for (proc, rid), rec in sorted(recs.items()):
        probe = {"source": rec.get("source"), "proc": proc}
        pid = pid_for(probe)
        tid = rid + 1                      # tid 0 = the phase track
        t0 = rec.get("submit_t")
        t1 = (rec.get("retire_t") or rec.get("timeout_t")
              or rec.get("failed_t") or rec.get("shed_t"))
        if t0 is None:
            t0 = t1
        if t0 is None:
            continue
        args = {k: rec[k] for k in ("trace_id", "parent_id",
                                    "terminal", "generated",
                                    "ttft_ms", "latency_ms",
                                    "attempts")
                if rec.get(k) is not None}
        events.append({
            "ph": "X", "pid": pid, "tid": tid,
            "name": f"request {rid}",
            "cat": "request", "ts": _us(t0),
            "dur": max(1.0, _us(t1) - _us(t0)) if t1 else 1.0,
            "args": args,
        })
        # nested lifecycle phases (same tid, contained intervals)
        for name, a, b in (
                ("queued", rec.get("submit_t"), rec.get("admit_t")),
                ("prefill", rec.get("admit_t"),
                 rec.get("first_token_t")),
                ("decode", rec.get("first_token_t"),
                 rec.get("retire_t"))):
            if a is not None and b is not None and b >= a:
                events.append({
                    "ph": "X", "pid": pid, "tid": tid, "name": name,
                    "cat": "lifecycle", "ts": _us(a),
                    "dur": max(1.0, _us(b) - _us(a)),
                })
        # the waterfall's exact segment intervals (obs/waterfall.py):
        # finer than the lifecycle slices — decode splits into
        # active/stall, restarts show as requeue — skipping the
        # zero-width and defensive-untracked pieces
        fall = falls.get((proc, rid))
        for a, b, seg in (fall or {}).get("intervals", ()):
            if seg == "untracked" or b <= a:
                continue
            events.append({
                "ph": "X", "pid": pid, "tid": tid, "name": seg,
                "cat": "waterfall", "ts": _us(a),
                "dur": max(1.0, _us(b) - _us(a)),
            })
    for r in rows:
        kind, event = r.get("kind"), r.get("event")
        if kind == "span" and event == "phase":
            pid = pid_for(r)
            dur_ms = float(r.get("dur_ms") or 0.0)
            ts = _us(r.get("t")) - round(dur_ms * 1e3, 1)
            args = {k: r[k] for k in ("phase", "trace_id", "step",
                                      "round")
                    if r.get(k) is not None}
            events.append({"ph": "X", "pid": pid, "tid": 0,
                           "name": str(r.get("phase")),
                           "cat": "train", "ts": ts,
                           "dur": max(1.0, round(dur_ms * 1e3, 1)),
                           "args": args})
        elif kind == "span" and event in ("engine_restart", "error"):
            pid = pid_for(r)
            events.append({"ph": "i", "pid": pid, "tid": 0,
                           "name": str(event), "cat": "anomaly",
                           "ts": _us(r.get("t")), "s": "p",
                           "args": {"reason": str(r.get("reason"))}})
        elif kind == "restart":
            pid = pid_for(r)
            events.append({"ph": "i", "pid": pid, "tid": 0,
                           "name": f"restart:{r.get('event')}",
                           "cat": "restart", "ts": _us(r.get("t")),
                           "s": "p"})
    events.sort(key=lambda e: (e.get("ts") or 0.0,
                               0 if e["ph"] == "M" else 1))
    return {"traceEvents": events, "displayTimeUnit": "ms",
            "otherData": {"schema": f"dtx v{SCHEMA_VERSION}",
                          "sources": sources}}
