"""In-step anomaly detection: the host half of ``--on_anomaly``.

The device half lives in parallel/step.py: ``build_train_step(...,
with_anomaly=True)`` returns, alongside cost/acc, a compiled
``{"flag": bool, "counts": [n_leaves] i32}`` — one global "this step
produced a non-finite loss or gradient" bit plus per-leaf non-finite
element counts (exact under TP/PP/EP sharding, mirroring the
``with_norms`` vectors). Under ``--on_anomaly=skip`` the compiled
step also masks the update itself (params/opt keep their old value on
a flagged step), so a single NaN batch cannot poison the run even
before the host notices.

This module is the host side:

- ``LossWatchdog`` — a rolling loss-EMA divergence detector: flags a
  non-finite loss immediately, and (after a warmup) a loss more than
  ``factor``x the EMA — the "diverging but not yet NaN" case a
  non-finite check misses;
- ``AnomalyPolicy`` — the ``--on_anomaly={halt,dump,skip}`` policy
  with skipped-step accounting and per-leaf blame. Every anomaly is
  recorded into the flight recorder (obs/flight.py) and the metrics
  stream; ``halt`` then raises ``AnomalyError`` (the crash path dumps
  the flight record with full context — this is what supersedes the
  context-free global ``--debug_nans``), ``dump`` writes a flight
  dump and continues, ``skip`` counts on the device-masked step.

The host checks ride fetches the loop already performs (the bounded
dispatch-queue drain and window boundaries), so detection lags by at
most the dispatch-window depth and the feature costs nothing when
off.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Sequence

POLICIES = ("", "halt", "dump", "skip")


class AnomalyError(RuntimeError):
    """--on_anomaly=halt: raised after the anomaly is recorded; the
    train loop's crash path turns it into a flight dump."""


def _finite(x) -> bool:
    try:
        return math.isfinite(float(x))
    except (TypeError, ValueError):
        return False


class LossWatchdog:
    """Rolling loss-EMA divergence detector.

    ``observe(step, loss)`` -> reason string or None. A non-finite
    loss flags immediately ("nonfinite_loss"); once ``warmup`` finite
    losses have seeded the EMA, a loss exceeding ``factor * ema``
    (with an absolute floor ``min_ema`` so a near-zero EMA cannot
    flag noise) flags "divergence". The EMA only absorbs NON-flagged
    losses, so a blowup cannot drag its own baseline up.
    """

    def __init__(self, factor: float = 10.0, beta: float = 0.98,
                 warmup: int = 20, min_ema: float = 1e-3):
        if factor <= 1.0:
            raise ValueError(f"factor={factor} must be > 1")
        self.factor = float(factor)
        self.beta = float(beta)
        self.warmup = int(warmup)
        self.min_ema = float(min_ema)
        self.ema: Optional[float] = None
        self.seen = 0

    def observe(self, step: int, loss) -> Optional[str]:
        if loss is None:
            return None
        if not _finite(loss):
            return "nonfinite_loss"
        loss = float(loss)
        if self.ema is not None and self.seen >= self.warmup:
            if loss > self.factor * max(self.ema, self.min_ema):
                return "divergence"
        self.ema = (loss if self.ema is None
                    else self.beta * self.ema + (1.0 - self.beta) * loss)
        self.seen += 1
        return None


class AnomalyPolicy:
    """--on_anomaly bookkeeping + reaction.

    ``on_step`` consumes one step's fetched signals (host-side loss
    and, on the sync path, the compiled flag/counts); ``on_epoch``
    consumes a fast-path epoch's already-returned cost array
    post-hoc. Both record every anomaly (flight + metrics event) and
    then apply the policy.
    """

    def __init__(self, mode: str, leaf_names: Optional[Sequence[str]] = None,
                 flight=None, mlogger=None,
                 watchdog: Optional[LossWatchdog] = None,
                 max_dump_writes: int = 8, max_event_logs: int = 64):
        if mode not in POLICIES or not mode:
            raise ValueError(
                f"on_anomaly={mode!r}: expected one of "
                f"{[p for p in POLICIES if p]}")
        self.mode = mode
        self.leaf_names = list(leaf_names) if leaf_names else None
        self.flight = flight
        self.mlogger = mlogger
        self.watchdog = watchdog
        self.anomalies = 0
        self.skipped_steps = 0
        self._dump_writes = 0
        self._max_dump_writes = int(max_dump_writes)
        self._max_event_logs = int(max_event_logs)

    # -- blame -------------------------------------------------------------

    def blame(self, counts) -> Dict[str, int]:
        """{leaf_name: non-finite element count} for flagged leaves."""
        if counts is None:
            return {}
        out: Dict[str, int] = {}
        for i, c in enumerate(counts):
            c = int(c)
            if c:
                name = (self.leaf_names[i]
                        if self.leaf_names and i < len(self.leaf_names)
                        else f"leaf[{i}]")
                out[name] = c
        return out

    # -- reaction ----------------------------------------------------------

    def _react(self, step: int, reasons: List[str], loss,
               blame: Dict[str, int], skipped: int = 0) -> None:
        self.anomalies += 1
        self.skipped_steps += skipped
        if loss is not None:
            # strict-JSON-safe: the record lands in the metrics jsonl
            # (whose consumers are standards parsers) as well as the
            # flight dump — a bare NaN literal would break the former
            loss = float(loss)
            if not math.isfinite(loss):
                loss = repr(loss)
        record = {
            "step": int(step),
            "reasons": reasons,
            "loss": loss,
            "blame": blame,
            "policy": self.mode,
            "skipped_steps_total": self.skipped_steps,
        }
        if self.flight is not None:
            self.flight.record_anomaly(**record)
        if self.mlogger is not None and self.anomalies <= self._max_event_logs:
            # bounded: a skip-mode run limping through a long NaN tail
            # must not flood the metrics stream (the flight ring and
            # the counters keep the full accounting)
            self.mlogger.log_event("anomaly", **record)
        if self.mode == "dump" and self.flight is not None:
            # bounded: a long NaN tail must not turn into an I/O storm
            if self._dump_writes < self._max_dump_writes:
                self._dump_writes += 1
                self.flight.dump("anomaly")
        if self.mode == "halt":
            raise AnomalyError(
                f"anomaly at step {step}: {', '.join(reasons)} "
                f"(loss={loss}, blame={blame or 'n/a'}); halted by "
                f"--on_anomaly=halt")

    def on_step(self, step: int, loss=None, flagged: Optional[bool] = None,
                counts=None) -> bool:
        """One host-visible step; True if it was anomalous. ``flagged``
        /``counts`` are the compiled step's outputs when available."""
        reasons: List[str] = []
        blame: Dict[str, int] = {}
        if flagged:
            blame = self.blame(counts)
            reasons.append("nonfinite_grads" if blame else "nonfinite_loss")
        if self.watchdog is not None:
            r = self.watchdog.observe(step, loss)
            if r and r not in reasons:
                # a device-flagged nonfinite loss is already reason'd
                if not (r == "nonfinite_loss" and flagged):
                    reasons.append(r)
        if not reasons:
            return False
        self._react(step, reasons, loss, blame,
                    skipped=(1 if self.mode == "skip" and flagged else 0))
        return True

    def on_epoch(self, epoch: int, costs, base_step: int = 0) -> int:
        """Fast-path post-hoc check over one epoch's returned per-step
        cost array; returns the number of anomalous steps. Under
        ``skip`` the compiled step already masked those updates — the
        non-finite cost entries are the skipped-step accounting.

        Known limit: the scan paths return only costs, so a step whose
        GRADIENTS went non-finite while its loss stayed finite is
        masked on-device but invisible here (uncounted, and halt/dump
        don't fire). The host loop fetches the compiled flag and has
        exact accounting — use it when that distinction matters."""
        import numpy as np

        costs = np.asarray(costs)
        bad_idx = np.nonzero(~np.isfinite(costs))[0]
        for i in bad_idx:
            self._react(base_step + int(i) + 1, ["nonfinite_loss"],
                        float(costs[i]) if costs[i] == costs[i] else None,
                        {}, skipped=(1 if self.mode == "skip" else 0))
        if self.watchdog is not None:
            for i in np.nonzero(np.isfinite(costs))[0]:
                r = self.watchdog.observe(base_step + int(i) + 1,
                                          float(costs[i]))
                if r:
                    self._react(base_step + int(i) + 1, [r],
                                float(costs[i]), {})
        return int(bad_idx.size)

    def summary(self) -> Dict[str, int]:
        return {"anomalies": self.anomalies,
                "skipped_steps": self.skipped_steps}
