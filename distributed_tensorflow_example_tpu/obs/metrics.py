"""Structured metrics sink: one JSON object per logging window.

``MetricsLogger`` appends newline-delimited JSON to
``<logs_path>/metrics.<proc>.jsonl`` (``<proc>`` = jax process index;
one file per process so multi-process runs never interleave writes).
Two row kinds:

- ``window``: the per-``--log_every``-steps training telemetry —
  step-time p50/p95/max over the window, the host loop's
  data-wait / dispatch / device-wait split, examples/sec, tokens/sec,
  analytic MFU (obs/flops.py), process RSS and device memory stats;
- ``event``: point events (compile times, straggler reports, run end).

``WindowTimer`` is the host-loop accumulator behind the window rows:
the loop charges each step's phases into named buckets (``data_wait``
= blocking on the prefetcher, ``h2d`` = committing batches to their
device layout — at dispatch time on the blocking path, ahead of
consumption under ``--device_prefetch``, ``dispatch`` = the jit'd
step call, ``device_wait`` = blocking fetches: the bounded-queue
drain and the window-boundary metric fetch) and records per-step wall
times for the percentiles. Everything not charged is the ``host``
residual. The timer adds NO device traffic — it only wraps host-side
waits the loop already performs, so the dispatch-queue depth is
unchanged.

``read_metrics`` parses a file back (tests, tooling).
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, List

from .buckets import HOST_BUCKET, WINDOW_BUCKETS
from .schema import SCHEMA_VERSION


def rss_bytes():
    """Resident set size of this process via /proc (no psutil
    dependency); None where /proc is unavailable."""
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1]) * 1024
    except (OSError, ValueError, IndexError):
        pass
    return None


def device_memory_stats(device=None):
    """``device.memory_stats()`` where the backend provides it (TPU;
    returns None on CPU), reduced to the portable byte counters."""
    try:
        if device is None:
            import jax

            device = jax.local_devices()[0]
        stats = device.memory_stats()
    except Exception:
        return None
    if not stats:
        return None
    keep = ("bytes_in_use", "peak_bytes_in_use", "bytes_limit",
            "largest_alloc_size")
    return {k: int(stats[k]) for k in keep if k in stats}


def _percentile(sorted_vals: List[float], q: float) -> float:
    """Nearest-rank percentile on a pre-sorted list."""
    if not sorted_vals:
        return float("nan")
    idx = min(len(sorted_vals) - 1, max(0, int(round(
        q / 100.0 * (len(sorted_vals) - 1)))))
    return sorted_vals[idx]


class WindowTimer:
    """Accumulates one logging window's per-step host-loop timing."""

    def __init__(self):
        self.reset()

    def reset(self) -> None:
        self.step_times: List[float] = []
        self.buckets: Dict[str, float] = {}
        self._t_start = time.perf_counter()
        self._t_last = self._t_start

    @property
    def steps(self) -> int:
        return len(self.step_times)

    def charge(self, bucket: str, seconds: float) -> None:
        if bucket not in WINDOW_BUCKETS:
            # one registry (obs/buckets.py) names every bucket; an
            # unknown name would silently vanish from the window row
            raise ValueError(f"unknown window bucket {bucket!r}: "
                             f"expected one of {WINDOW_BUCKETS}")
        self.buckets[bucket] = self.buckets.get(bucket, 0.0) + seconds

    def step_done(self) -> None:
        now = time.perf_counter()
        self.step_times.append(now - self._t_last)
        self._t_last = now

    def window_row(self) -> Dict[str, Any]:
        """Timing fields for the closing window; caller adds identity
        (step/epoch/cost) and throughput fields then resets."""
        wall = time.perf_counter() - self._t_start
        st = sorted(self.step_times)
        row = {
            "steps": len(st),
            "window_wall_s": round(wall, 6),
            "step_time_p50_ms": round(_percentile(st, 50) * 1e3, 4),
            "step_time_p95_ms": round(_percentile(st, 95) * 1e3, 4),
            "step_time_max_ms": round((st[-1] if st else float("nan"))
                                      * 1e3, 4),
        }
        # bucket fields from the shared registry (obs/buckets.py) —
        # the "<bucket>_s" naming here, the schema contract and the
        # aggregate decomposition all walk the same tuple
        charged = 0.0
        for bucket in WINDOW_BUCKETS:
            v = self.buckets.get(bucket, 0.0)
            charged += v
            row[f"{bucket}_s"] = round(v, 6)
        row[f"{HOST_BUCKET}_s"] = round(max(0.0, wall - charged), 6)
        return row


def _scrub_nonfinite(row):
    """Strict-JSON-safe copy: NaN/Inf floats stringify ("nan"/"inf"),
    unknown types fall back to repr. The stream's consumers are
    standards parsers (dashboards, jq) and a NaN cost is routine
    under --on_anomaly=skip — a bare ``NaN`` literal in the jsonl
    would break them (obs/schema.py documents this contract). ONE
    sanitizer for the whole obs package: this is flight.py's
    _jsonable, shared so the two streams cannot drift."""
    from .flight import _jsonable

    return _jsonable(row)


class MetricsLogger:
    """Append-only JSONL metrics stream, one file per process."""

    def __init__(self, logs_path: str, process_index: int = 0):
        os.makedirs(logs_path, exist_ok=True)
        self.process_index = int(process_index)
        self.path = os.path.join(logs_path,
                                 f"metrics.{self.process_index}.jsonl")
        self._f = open(self.path, "a", buffering=1)  # line-buffered

    def _emit(self, row: Dict[str, Any]) -> None:
        # telemetry must degrade, never kill the run it observes: a
        # bad fd / full volume disables the stream instead of raising
        # into the training loop
        if self._f is None:
            return
        try:
            self._f.write(json.dumps(_scrub_nonfinite(row),
                                     allow_nan=False) + "\n")
        except (OSError, ValueError):
            try:
                self._f.close()
            except Exception:
                pass
            self._f = None

    def log_window(self, **fields) -> None:
        self._emit({"kind": "window", "v": SCHEMA_VERSION,
                    "t": time.time(),
                    "proc": self.process_index, **fields,
                    "rss_bytes": rss_bytes(),
                    "device_memory": device_memory_stats()})

    def log_event(self, event: str, **fields) -> None:
        self._emit({"kind": "event", "v": SCHEMA_VERSION,
                    "event": event, "t": time.time(),
                    "proc": self.process_index, **fields})

    def flush(self) -> None:
        if self._f is not None:
            self._f.flush()

    def close(self) -> None:
        if self._f is None:
            return
        try:
            self._f.flush()
        finally:
            self._f.close()
            self._f = None


def read_metrics(path: str) -> List[Dict[str, Any]]:
    """Parse a metrics.<proc>.jsonl back into row dicts."""
    rows = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                rows.append(json.loads(line))
    return rows
