"""Run-level analytics: the read side of the obs/ telemetry.

PR 1/2 made every run *emit* per-window metrics JSONL, heartbeats and
flight dumps; this module *consumes* them. ``aggregate(logs_path)``
loads every ``metrics.<proc>.jsonl`` (schema-validated against
obs/schema.py), joins heartbeats and flight dumps, and folds the run
into one report:

- **goodput accounting** — wall time decomposed into the buckets
  production fleet reports use (the goodput/badput decomposition of
  Google's large-fleet training reports, MegaScale-style straggler
  attribution): productive ``train`` time vs ``compile``,
  ``data_wait``, ``h2d`` (batch device-commit wall),
  host overhead, ``anomaly_skipped`` step time,
  ``straggler_idle`` (derived from per-proc step lag) and the
  ``untracked`` residual, plus the non-train-but-useful ``eval`` /
  ``sample`` phases. The run_end event carries the cumulative
  compile/eval/sample seconds (train/loop.py) so the buckets sum to
  wall time;
- **step-time percentiles across processes**, an MFU/throughput
  summary and a (subsampled) per-window trajectory;
- an **anomaly/restart timeline** merging metrics anomaly events,
  compile events (a mid-run compile is a restart signal) and flight
  dumps.

Everything here is a pure function over files — no jax, safe to run
on a laptop against rsync'd logs. ``obs/compare.py`` diffs two of
these reports; ``dtx-obs report`` is the CLI wrapper.
"""

from __future__ import annotations

import glob
import json
import os
import re
import time
from typing import Any, Dict, List, Optional

from . import heartbeat as hb_lib
from . import schema as schema_lib
from .buckets import GOODPUT_BUCKETS

# bucket names, in presentation order; "train" is the goodput bucket,
# "eval"/"sample" are auxiliary useful work, the rest is badput
# ("h2d" = the host wall spent committing batches to their device
# layout — overlapped ahead of dispatch under --device_prefetch).
# The names live in the shared registry (obs/buckets.py).
BUCKETS = GOODPUT_BUCKETS

_METRICS_RE = re.compile(r"metrics\.(\d+)\.jsonl$")


def metrics_files(logs_path: str) -> List[tuple]:
    """[(proc_index, path)] for every metrics stream in a run dir —
    the ONE place the stream naming/discovery convention lives
    (obs/serve.py and the CLI reuse it)."""
    out = []
    for path in sorted(glob.glob(os.path.join(logs_path,
                                              "metrics.*.jsonl"))):
        m = _METRICS_RE.search(os.path.basename(path))
        if m:
            out.append((int(m.group(1)), path))
    return out


def has_streams(logs_path: str) -> bool:
    """True when ``logs_path`` looks like a run dir — it holds at
    least one metrics/span stream or a restart timeline.  The fleet
    collector (obs/collector.py) keys source discovery on this, so
    the definition of "a run dir" stays next to ``metrics_files``."""
    from .spans import span_files

    return bool(metrics_files(logs_path) or span_files(logs_path)
                or os.path.exists(os.path.join(logs_path,
                                               "restarts.jsonl")))


def _median(vals: List[float]) -> Optional[float]:
    if not vals:
        return None
    s = sorted(vals)
    n = len(s)
    return s[n // 2] if n % 2 else 0.5 * (s[n // 2 - 1] + s[n // 2])


def load_run(logs_path: str, max_errors: int = 20) -> Dict[str, Any]:
    """Load one run's signals: per-process metrics rows (validated),
    heartbeats and flight dumps. Raises FileNotFoundError when there
    is no metrics stream at all; schema drift is collected into
    ``schema_errors`` (capped), not raised — a report over a slightly
    torn log beats no report."""
    procs: Dict[int, List[Dict[str, Any]]] = {}
    errors: List[str] = []
    n_errors = 0
    for pid, path in metrics_files(logs_path):
        rows: List[Dict[str, Any]] = []
        with open(path) as f:
            for i, line in enumerate(f, 1):
                line = line.strip()
                if not line:
                    continue
                where = f"{os.path.basename(path)}:{i}"
                try:
                    row = json.loads(line)
                except ValueError as e:
                    n_errors += 1
                    if len(errors) < max_errors:
                        errors.append(f"{where}: not JSON ({e})")
                    continue
                errs = schema_lib.validate_metrics_row(row, where=where)
                if errs:
                    n_errors += len(errs)
                    errors.extend(errs[:max(0, max_errors - len(errors))])
                rows.append(row)
        procs[pid] = rows
    if not procs:
        raise FileNotFoundError(
            f"no metrics.<proc>.jsonl under {logs_path!r} — was the run "
            f"started with --metrics (or DTX_METRICS=1)?")
    flights = []
    fdir = os.path.join(logs_path, "flight")
    for path in sorted(glob.glob(os.path.join(fdir, "*.json"))):
        if os.path.basename(path) == "report.json":
            continue
        try:
            with open(path) as f:
                flights.append(json.load(f))
        except (OSError, ValueError):
            n_errors += 1
            if len(errors) < max_errors:
                errors.append(f"{path}: unreadable flight dump")
    # restart timeline (resilience/restart.py RestartNarrator):
    # validated like the metrics rows, folded into the report timeline
    restarts = []
    from ..resilience.restart import read_restarts

    for i, row in enumerate(read_restarts(logs_path), 1):
        errs = schema_lib.validate_restart_row(
            row, where=f"restarts.jsonl:{i}")
        if errs:
            n_errors += len(errs)
            errors.extend(errs[:max(0, max_errors - len(errors))])
        restarts.append(row)
    return {
        "procs": procs,
        "heartbeats": hb_lib.read_heartbeats(logs_path),
        "flights": flights,
        "restarts": restarts,
        "schema_errors": errors,
        "schema_error_count": n_errors,
    }


def _goodput(windows: List[Dict[str, Any]], run_end: Optional[Dict],
             wall: float, lag_steps: int) -> Dict[str, Any]:
    """The decomposition. ``windows`` are the chief's window rows
    (their timing buckets are disjoint by construction: the
    WindowTimer charges waits the loop performs exactly once, and the
    loop excludes compile/eval from window walls)."""
    def wsum(key):
        return sum(float(w.get(key) or 0.0) for w in windows)

    data_wait = wsum("data_wait_s")
    h2d = wsum("h2d_s")
    ckpt = wsum("ckpt_s")
    train = wsum("dispatch_s") + wsum("device_wait_s")
    host = wsum("host_s")
    steps_obs = int(wsum("steps"))
    window_wall = wsum("window_wall_s")
    mean_step_s = (window_wall / steps_obs) if steps_obs else 0.0
    run_end = run_end or {}
    compile_s = float(run_end.get("compile_s") or 0.0)
    eval_s = float(run_end.get("eval_s") or 0.0)
    sample_s = float(run_end.get("sample_s") or 0.0)
    skipped = int(run_end.get("skipped_steps") or 0)
    # carve-outs: skipped steps and straggler idle are train time that
    # did NOT advance training — reclassified out of the train bucket
    anomaly_skipped = min(train, skipped * mean_step_s)
    train -= anomaly_skipped
    straggler_idle = min(train, max(0, lag_steps) * mean_step_s)
    train -= straggler_idle
    known = (train + compile_s + data_wait + h2d + ckpt + host + eval_s
             + sample_s + anomaly_skipped + straggler_idle)
    untracked = max(0.0, wall - known)
    buckets = {
        "train": train,
        "compile": compile_s,
        "data_wait": data_wait,
        "h2d": h2d,
        "ckpt": ckpt,
        "host": host,
        "eval": eval_s,
        "sample": sample_s,
        "anomaly_skipped": anomaly_skipped,
        "straggler_idle": straggler_idle,
        "untracked": untracked,
    }
    buckets = {k: round(v, 6) for k, v in buckets.items()}
    badput = (compile_s + data_wait + h2d + ckpt + host
              + anomaly_skipped + straggler_idle + untracked)
    out = {
        "wall_s": round(wall, 6),
        "buckets": buckets,
        "bucket_sum_s": round(sum(buckets.values()), 6),
        # a negative residual means double-counted buckets — surfaced,
        # never hidden (untracked is clamped at 0)
        "residual_s": round(wall - known, 6),
        "goodput_s": round(train, 6),
        "mean_step_s": round(mean_step_s, 6),
    }
    if wall > 0:
        out["goodput_frac"] = round(train / wall, 6)
        out["aux_frac"] = round((eval_s + sample_s) / wall, 6)
        out["badput_frac"] = round(badput / wall, 6)
    return out


def aggregate(logs_path: str, max_trajectory: int = 200,
              now: Optional[float] = None) -> Dict[str, Any]:
    """Fold one run's signals into the run report (see the module
    docstring for the shape; obs/schema.py RUN_REPORT pins the
    top-level contract)."""
    data = load_run(logs_path)
    procs = data["procs"]
    chief = min(procs)
    chief_rows = procs[chief]
    windows = [r for r in chief_rows if r.get("kind") == "window"]
    events = [r for r in chief_rows if r.get("kind") == "event"]
    run_end = next((r for r in reversed(events)
                    if r.get("event") == "run_end"), None)
    compile_events = [r for r in events if r.get("event") == "compile"]
    straggler_events = [r for r in events
                        if r.get("event") == "stragglers"]

    all_rows_t = [float(r["t"]) for rows in procs.values() for r in rows
                  if isinstance(r.get("t"), (int, float))]
    if run_end is not None and run_end.get("total_time_s") is not None:
        wall = float(run_end["total_time_s"])
        partial = False
    else:
        # live/crashed run: span of the observed rows
        wall = (max(all_rows_t) - min(all_rows_t)) if all_rows_t else 0.0
        partial = True
    # decomposition inputs: run_end when present; a pre-v2 or partial
    # (live/crashed) stream falls back to the compile events
    eff_end = dict(run_end or {})
    if eff_end.get("compile_s") is None:
        eff_end["compile_s"] = sum(
            float(r.get("dispatch_wall_s") or 0.0)
            for r in compile_events)

    # straggler idle: the chief's recorded per-epoch step lag (mean
    # over epochs — each epoch's laggard stalls the collectives for
    # ~lag steps), falling back to the final per-proc window spread
    lags = [int(r["max_step_lag"]) for r in straggler_events
            if isinstance(r.get("max_step_lag"), int)]
    if not lags and len(procs) > 1:
        last_steps = [int(s[-1].get("step") or 0) for s in (
            [r for r in rows if r.get("kind") == "window"]
            for rows in procs.values()) if s]
        if len(last_steps) > 1:
            lags = [int(max(last_steps) - min(last_steps))]
    lag_mean = int(round(sum(lags) / len(lags))) if lags else 0

    goodput = _goodput(windows, eff_end, wall, lag_mean)

    # step-time percentiles across every process's windows
    all_windows = [r for rows in procs.values() for r in rows
                   if r.get("kind") == "window"]

    def col(key):
        return [float(r[key]) for r in all_windows
                if isinstance(r.get(key), (int, float))]

    step_time = {
        "p50_ms": _median(col("step_time_p50_ms")),
        "p95_ms": max(col("step_time_p95_ms"), default=None),
        "max_ms": max(col("step_time_max_ms"), default=None),
        "windows": len(all_windows),
    }

    mfus = col("mfu")
    eps = col("examples_per_sec")
    throughput = {
        "examples_per_sec_mean": round(sum(eps) / len(eps), 3) if eps
        else None,
        "examples_per_sec_last": eps[-1] if eps else None,
        "mfu_mean": round(sum(mfus) / len(mfus), 6) if mfus else None,
        "mfu_best": max(mfus, default=None),
        "tokens_per_sec_last": (col("tokens_per_sec") or [None])[-1],
    }

    stride = max(1, -(-len(windows) // max_trajectory))  # ceil: cap holds
    trajectory = [{
        "step": w.get("step"), "t": w.get("t"), "cost": w.get("cost"),
        "examples_per_sec": w.get("examples_per_sec"),
        "mfu": w.get("mfu"),
        "step_time_p50_ms": w.get("step_time_p50_ms"),
    } for w in windows[::stride]]

    # anomaly/restart timeline: anomaly events + compile events (a
    # recompile mid-run marks a restart) + flight dumps, in time order
    timeline: List[Dict[str, Any]] = []
    for rows in procs.values():
        for r in rows:
            if r.get("kind") != "event":
                continue
            if r.get("event") == "anomaly":
                timeline.append({
                    "t": r.get("t"), "kind": "anomaly",
                    "proc": r.get("proc"), "step": r.get("step"),
                    "reasons": r.get("reasons"),
                    "policy": r.get("policy")})
            elif r.get("event") == "compile":
                timeline.append({
                    "t": r.get("t"), "kind": "compile",
                    "proc": r.get("proc"), "what": r.get("what"),
                    "dispatch_wall_s": r.get("dispatch_wall_s")})
    for d in data["flights"]:
        timeline.append({
            "t": d.get("t"), "kind": "flight_dump",
            "proc": d.get("proc"), "reason": d.get("reason"),
            "last_step": d.get("last_step"),
            "exception": (d.get("exception") or {}).get("type")})
    # the restart timeline (resilience narration): every preemption,
    # snapshot-on-signal, resume and chief-side retry/reform decision
    for r in data["restarts"]:
        entry = {"t": r.get("t"), "kind": "restart",
                 "proc": r.get("proc"), "event": r.get("event")}
        for k in ("step", "signal", "reason", "dp", "wait_s",
                  "attempt", "exit_code", "dead"):
            if r.get(k) is not None:
                entry[k] = r.get(k)
        timeline.append(entry)
    timeline.sort(key=lambda e: (e.get("t") or 0.0))

    rk = [r.get("event") for r in data["restarts"]]
    restarts_summary = {
        "events": len(rk),
        "preemptions": rk.count("preempt"),
        "snapshots": rk.count("snapshot"),
        "resumes": rk.count("resumed"),
        "dead_procs": rk.count("dead_proc"),
        "retries": rk.count("retry"),
        "reforms": rk.count("reform"),
        "gave_up": rk.count("give_up"),
        # the serving supervisor's entries (PR 15): decode-engine
        # loop deaths restarted in place with in-flight re-queued
        "engine_restarts": rk.count("engine_restart"),
    }

    now = time.time() if now is None else now
    proc_summary = {}
    for pid, rows in procs.items():
        pw = [r for r in rows if r.get("kind") == "window"]
        hb = data["heartbeats"].get(pid)
        proc_summary[str(pid)] = {
            "windows": len(pw),
            "last_step": pw[-1].get("step") if pw else None,
            "heartbeat_step": hb[0] if hb else None,
            "heartbeat_age_s": (round(max(0.0, now - hb[1]), 3)
                                if hb else None),
        }

    report = {
        "v": schema_lib.SCHEMA_VERSION,
        "kind": "run_report",
        "logs_path": os.path.abspath(logs_path),
        "generated_t": now,
        "partial": partial,
        "procs": len(procs),
        "proc_summary": proc_summary,
        "steps": (int(run_end["steps"]) if run_end
                  and run_end.get("steps") is not None
                  else (windows[-1].get("step") if windows else None)),
        "wall_s": round(wall, 6),
        "test_accuracy": (run_end or {}).get("test_accuracy"),
        "goodput": goodput,
        "step_time": step_time,
        "throughput": throughput,
        "trajectory": trajectory,
        "stragglers": {
            "max_step_lag": (max(lags) if lags else None),
            "mean_step_lag": (lag_mean if lags else None),
            "reports": len(straggler_events),
        },
        "anomalies": {
            "count": int((run_end or {}).get("anomalies") or 0) or len(
                [e for e in timeline if e["kind"] == "anomaly"]),
            "skipped_steps": int((run_end or {}).get("skipped_steps")
                                 or 0),
            "flight_dumps": len(data["flights"]),
        },
        "restarts": restarts_summary,
        "timeline": timeline,
        "schema_errors": data["schema_errors"],
        "schema_error_count": data["schema_error_count"],
    }
    return report


def summary_line(report: Dict[str, Any]) -> str:
    """One human-scannable line (dtx-obs report default output; bench
    appends it next to each row JSON)."""
    g = report.get("goodput") or {}
    frac = g.get("goodput_frac")
    tp = report.get("throughput") or {}
    bits = [
        f"steps={report.get('steps')}",
        f"wall={report.get('wall_s')}s",
        f"goodput={frac * 100:.1f}%" if frac is not None else "goodput=?",
        f"compile={g.get('buckets', {}).get('compile', 0):.3g}s",
        f"data_wait={g.get('buckets', {}).get('data_wait', 0):.3g}s",
    ]
    if g.get("buckets", {}).get("h2d"):
        bits.append(f"h2d={g['buckets']['h2d']:.3g}s")
    if tp.get("mfu_mean") is not None:
        bits.append(f"mfu={tp['mfu_mean']}")
    if tp.get("examples_per_sec_last") is not None:
        bits.append(f"ex/s={tp['examples_per_sec_last']}")
    an = report.get("anomalies") or {}
    if an.get("count"):
        bits.append(f"anomalies={an['count']}"
                    + (f" skipped={an['skipped_steps']}"
                       if an.get("skipped_steps") else ""))
    rs = report.get("restarts") or {}
    if rs.get("events"):
        bits.append(
            f"restarts[preempt={rs.get('preemptions', 0)} "
            f"resume={rs.get('resumes', 0)} "
            f"reform={rs.get('reforms', 0)}]")
    if report.get("partial"):
        bits.append("PARTIAL")
    if report.get("schema_error_count"):
        bits.append(f"schema_errors={report['schema_error_count']}")
    return " ".join(bits)
