"""Analytic FLOPs and MFU accounting — the single source of truth.

Hoisted from bench.py (round 6) so the train loop's metrics rows, the
bench rows, and the tests all compute MFU from ONE implementation: a
drift between the loop's number and the bench's number would make the
committed perf record unauditable. bench.py now imports these.

Conventions (unchanged from the bench's original accounting):

- matmul FLOPs are 6*MACs per training step (forward 2*MACs, backward
  4*MACs — dW and dx each cost one matmul per layer);
- attention is 4*B*H*S^2*Dh forward (QK^T and P@V at 2 FLOPs/MAC),
  halved under causal masking, and 3.5x forward for fwd+bwd (the
  backward's ~5 matmuls: p recompute, dp, dq, dk, dv);
- MFU divides by the chip's bf16 peak (the MXU's native input width);
  for f32 runs this is conservative.
"""

from __future__ import annotations

# bf16 peak matmul throughput per chip, by jax device_kind.
PEAK_BF16_FLOPS = {
    "TPU v5 lite": 197e12,   # v5e
    "TPU v5e": 197e12,
    "TPU v5": 459e12,        # v5p
    "TPU v4": 275e12,
    "TPU v6 lite": 918e12,   # v6e / Trillium
}


def chip_peak_flops(device=None):
    """Per-chip bf16 peak for ``device`` (default: jax.devices()[0]);
    None off-TPU or for an unknown device_kind — MFU is then
    undefined (reported as null, never fabricated)."""
    if device is None:
        import jax

        device = jax.devices()[0]
    if device.platform != "tpu":
        return None
    return PEAK_BF16_FLOPS.get(device.device_kind)


def mlp_flops_per_step(hidden_sizes, batch, input_size=784, num_classes=10):
    """Analytic fwd+bwd matmul FLOPs for the MLP family: 2*MACs fwd,
    4*MACs bwd (dW and dx each cost one matmul per layer) = 6*MACs
    total, per example."""
    sizes = (input_size, *hidden_sizes, num_classes)
    macs = sum(sizes[i] * sizes[i + 1] for i in range(len(sizes) - 1))
    return 6.0 * batch * macs


def attention_flops(b: int, s: int, h: int, d: int, causal: bool,
                    grad: bool = False) -> float:
    """Analytic attention FLOPs: forward = 4*B*H*S^2*D (QK^T and P@V,
    2 FLOPs per MAC), halved under causal masking; a value+grad call
    adds the backward's ~5 matmuls (p recompute, dp, dq, dk, dv) for
    ~2.5x forward on top."""
    f = 4.0 * b * h * float(s) * s * d * (0.5 if causal else 1.0)
    return f * 3.5 if grad else f


def model_flops_per_step(spec, batch: int) -> float:
    """Fwd+bwd FLOPs per training step for any model spec the train
    loop builds (make_spec): dispatches to the family's accounting."""
    from ..models import mlp

    if isinstance(spec, mlp.MLPSpec):
        return mlp_flops_per_step(tuple(spec.hidden_sizes), batch,
                                  input_size=spec.input_size,
                                  num_classes=spec.num_classes)
    from ..models import transformer

    if isinstance(spec, transformer.TransformerSpec):
        # transformer.flops_per_step uses the same 6*MACs + 3.5x-fwd
        # attention conventions as this module (cross-pinned by
        # tests/test_obs.py)
        return transformer.flops_per_step(spec, batch)
    raise TypeError(f"no FLOPs accounting for spec type {type(spec)!r}")


def tokens_per_example(spec):
    """Tokens one example contributes per step (for tokens/sec rows);
    None for families without a token axis (the MLP)."""
    seq = getattr(spec, "seq_len", None)
    return int(seq) if seq else None


def mfu(flops_per_step: float, steps_per_sec: float, peak,
        n_devices: int = 1):
    """Model FLOPs utilization vs the fleet's aggregate bf16 peak;
    None when the peak is unknown (non-TPU backends)."""
    if not peak:
        return None
    return flops_per_step * steps_per_sec / (peak * max(n_devices, 1))


# ---- decode roofline (round 9) ----
#
# Autoregressive decode at serving batch sizes is BANDWIDTH-bound,
# not FLOPs-bound: every step streams the full weight set plus the
# live KV cache through HBM to produce one token per sequence, so the
# honest utilization number is achieved bytes/s against the chip's
# HBM bandwidth ("hbm_frac"), not MFU.  VERDICT r5 #7 flagged the
# decode bench's naked tok/s; these functions provide the analytic
# denominator, and bench_decode reports achieved-vs-analytic as
# ``decode_hbm_frac`` (gated — obs/compare.GATE_METRICS).

# HBM bandwidth per chip (bytes/s), by jax device_kind — the decode
# roofline's denominator, as PEAK_BF16_FLOPS is the MFU's.
PEAK_HBM_BYTES = {
    "TPU v5 lite": 819e9,    # v5e
    "TPU v5e": 819e9,
    "TPU v5": 2765e9,        # v5p
    "TPU v4": 1228e9,
    "TPU v6 lite": 1640e9,   # v6e / Trillium
}


def chip_peak_hbm_bytes(device=None):
    """Per-chip HBM bandwidth for ``device`` (default:
    jax.devices()[0]); None off-TPU or for an unknown device_kind —
    hbm_frac is then undefined (reported as null, never fabricated)."""
    if device is None:
        import jax

        device = jax.devices()[0]
    if device.platform != "tpu":
        return None
    return PEAK_HBM_BYTES.get(device.device_kind)


def decode_weight_bytes(spec) -> float:
    """Bytes of parameters one decode step streams from HBM: every
    weight is read once per token (batch-invariant — the term
    batching amortizes)."""
    from ..models import transformer

    if not isinstance(spec, transformer.TransformerSpec):
        raise TypeError(f"no decode accounting for spec type "
                        f"{type(spec)!r}")
    import numpy as np

    itemsize = np.dtype(spec.param_dtype).itemsize
    return float(transformer.num_params(spec)) * itemsize


def decode_kv_bytes_per_step(spec, batch: int, kv_len: float,
                             heads: int | None = None,
                             kv_dtype_bytes: float | None = None) -> float:
    """KV-cache traffic of one decode step at ``kv_len`` cached
    positions per sequence: every block READS its [kv_len, H, Dh] k
    and v per sequence and WRITES one new row of each, at
    ``kv_dtype_bytes`` per element — default: the compute dtype's
    itemsize (what the unquantized cache stores).  ``kv_dtype_bytes=1``
    is the ``--kv_quant=int8`` pool (exactly half of bf16 — the gated
    ISSUE-11 claim; the per-row/per-head f32 scale planes are a
    separate ``decode_kv_scale_bytes_per_step`` term, 4/Dh of the
    payload, kept out of this closed form so the halving is exact and
    auditable).  ``kv_len`` may be fractional (a mean over a decode's
    positions)."""
    import numpy as np

    h = heads or spec.n_heads
    if kv_dtype_bytes is None:
        kv_dtype_bytes = np.dtype(spec.compute_dtype).itemsize
    row = h * spec.d_head * float(kv_dtype_bytes)
    return 2.0 * spec.num_blocks * batch * (kv_len + 1.0) * row


def decode_kv_scale_bytes_per_step(spec, batch: int, kv_len: float,
                                   heads: int | None = None) -> float:
    """The int8 pools' scale-plane traffic per decode step: one f32
    per cached (row, head) on each of the k/v planes — ``4 / Dh`` of
    the int8 payload (3% at Dh=128)."""
    h = heads or spec.n_heads
    return 2.0 * spec.num_blocks * batch * (kv_len + 1.0) * h * 4.0


def decode_bytes_per_step(spec, batch: int, kv_len: float,
                          heads: int | None = None,
                          kv_dtype_bytes: float | None = None) -> float:
    """Analytic HBM bytes per decode step: weights (read once) + KV
    read/write — the roofline's numerator.  Activations are excluded
    (O(B*d) per block, negligible against both terms at decode
    shapes)."""
    return decode_weight_bytes(spec) \
        + decode_kv_bytes_per_step(spec, batch, kv_len, heads=heads,
                                   kv_dtype_bytes=kv_dtype_bytes)


def hbm_frac(bytes_per_step: float, step_time_s: float, peak,
             n_devices: int = 1):
    """Achieved HBM bandwidth as a fraction of the fleet's peak —
    decode's utilization number; None when the peak is unknown
    (non-TPU backends)."""
    if not peak or step_time_s <= 0:
        return None
    return bytes_per_step / step_time_s / (peak * max(n_devices, 1))


# ---- cross-replica communication volume (round 10) ----
#
# The multi-site local-SGD claim is a COMMUNICATION claim: H inner
# steps per outer sync cut the bytes crossing the slow inter-site
# link ~H-fold vs per-step synchronous DP.  These helpers are the
# analytic accounting behind it — per-replica all-reduce traffic for
# the sync-DP gradient psum vs the local-SGD outer pseudo-gradient
# psum, amortized per trained token — surfaced by bench_local_sgd as
# ``local_sgd_comm_bytes_per_token`` and gated via obs/compare.
# Deterministic closed forms (like the pp bubble fractions): they
# hold on every backend and change only when the algorithm changes.

def num_params(spec) -> int:
    """Parameter count for any model spec the train loop builds
    (make_spec): dispatches to the family's own accounting."""
    from ..models import mlp

    if isinstance(spec, mlp.MLPSpec):
        return mlp.num_params(spec)
    from ..models import transformer

    if isinstance(spec, transformer.TransformerSpec):
        return transformer.num_params(spec)
    raise TypeError(f"no parameter accounting for spec type "
                    f"{type(spec)!r}")


def allreduce_bytes_per_replica(payload_bytes: float, n: int) -> float:
    """Bytes one replica moves (send + receive) in a bandwidth-optimal
    ring all-reduce of ``payload_bytes`` across ``n`` replicas:
    ``2 * (n-1)/n * payload`` (reduce-scatter + all-gather, each
    (n-1)/n of the payload). 0 for n <= 1 — nothing crosses a link."""
    if n <= 1:
        return 0.0
    return 2.0 * (n - 1) / n * float(payload_bytes)


def sync_dp_comm_bytes_per_step(spec, dp: int,
                                itemsize: int | None = None) -> float:
    """Per-replica bytes the synchronous-DP gradient psum moves every
    step: one all-reduce of the full gradient set (param-shaped, in
    the param dtype unless ``itemsize`` overrides)."""
    import numpy as np

    if itemsize is None:
        itemsize = np.dtype(getattr(spec, "param_dtype",
                                    np.float32)).itemsize
    return allreduce_bytes_per_replica(num_params(spec) * itemsize, dp)


def local_sgd_comm_bytes_per_round(spec, sites: int) -> float:
    """Per-site bytes one multi-site outer sync moves: the f32
    pseudo-gradient psum across 'site' (parallel/local_sgd.py
    extracts deltas in f32 regardless of param dtype; inner optimizer
    slots stay per-site and never cross the axis). Amortize over
    ``inner_steps`` for a per-inner-step figure."""
    return allreduce_bytes_per_replica(num_params(spec) * 4, sites)


def num_param_leaves(spec) -> int:
    """Leaf count of the model's parameter tree (the per-leaf scale
    overhead term of the compressed outer sync)."""
    from ..models import mlp

    if isinstance(spec, mlp.MLPSpec):
        # W1..WL + b1..bL
        return 2 * (len(spec.layer_sizes) - 1)
    from ..models import transformer

    if isinstance(spec, transformer.TransformerSpec):
        return len(transformer.param_shapes(spec))
    raise TypeError(f"no parameter accounting for spec type "
                    f"{type(spec)!r}")


def local_sgd_outer_quant_bytes_per_round(spec, sites: int) -> float:
    """Per-site bytes of the ``--outer_quant=int8`` outer sync: the
    pseudo-gradient crosses 'site' as int8 wire values (1 byte/param)
    plus one f32 scale per parameter leaf (symmetric per-leaf
    quantization, ops/quant.py — the error-feedback residual stays
    per-site and never crosses the axis).  ~4x below the f32 form
    above; the exact ratio is ``4N / (N + 4*leaves)``, which the
    bench row gates >= 3.5x."""
    payload = num_params(spec) * 1 + num_param_leaves(spec) * 4
    return allreduce_bytes_per_replica(payload, sites)


def comm_bytes_per_token(bytes_per_step: float, batch: int,
                         tokens_each: int | None) -> float:
    """Collective bytes amortized per trained token (``tokens_each``
    from tokens_per_example; token-less families count one "token"
    per example)."""
    return bytes_per_step / (batch * (tokens_each or 1))
