"""Declarative serving SLOs with multi-window burn-rate evaluation.

``DecodeEngine.stats()`` reports what the p99 *is*; nothing in the
stack says whether that is *acceptable* — the verdict a router,
autoscaler or pager acts on.  This module closes that gap with the
standard SRE construction:

- an **SLO spec** promises that an ``objective`` fraction of requests
  (default 99%) is *good* — a latency-type metric (``ttft_ms`` /
  ``latency_ms``) under its per-request ``threshold_ms``, or simply
  non-erroring for the ``error`` metric.  ``ttft_p99_ms <= T`` and
  "99% of requests have ttft <= T" are the same statement;
- **burn rate** over a window = observed bad fraction / the error
  budget (``1 - objective``): 1.0 burns the budget exactly as fast as
  allowed, 2.0 twice as fast;
- a **breach** requires the burn rate over BOTH a fast and a slow
  sliding window to reach ``burn_threshold`` — the multi-window
  construction (Google SRE workbook ch. 5) that pages neither on a
  single bad tick (fast-only) nor hours after recovery (slow-only).

Windows slide over the scheduler's **tick index** (the span stream's
step counter), not wall time — deterministic, so the closed-form
tier-1 tests pin exact burn rates.  Request records come from the
span stream (``records_from_spans``); ``evaluate`` is a pure function
over them.  Surfaces: the ``/slo`` endpoint + ``dtx_slo_*``
Prometheus gauges (obs/serve.py) and ``dtx-obs slo`` (exit 3 on
breach, the compare regression convention).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Iterable, List, Optional

from .schema import SCHEMA_VERSION

# sliding-window defaults, in scheduler ticks; burn_threshold 1.0 =
# breach when the budget burns at (or above) exactly its sustainable
# rate on both windows
FAST_WINDOW = 64
SLOW_WINDOW = 512
BURN_THRESHOLD = 1.0

# spec-DSL metric name -> the per-request record field it bounds
_METRIC_FIELDS = {
    "ttft_p99_ms": "ttft_ms",
    "latency_p99_ms": "latency_ms",
    "error_rate": "error",
}


@dataclasses.dataclass(frozen=True)
class SLOSpec:
    """One service-level objective.  ``metric`` is the per-request
    field (``ttft_ms``/``latency_ms``/``error``); latency metrics
    bound each request by ``threshold_ms``, ``error`` counts engine
    failures.  ``objective`` is the promised good fraction."""

    name: str
    metric: str                     # ttft_ms | latency_ms | error
    threshold_ms: Optional[float]   # None for the error metric
    objective: float = 0.99
    fast_window: int = FAST_WINDOW
    slow_window: int = SLOW_WINDOW
    burn_threshold: float = BURN_THRESHOLD

    def bad(self, rec: Dict[str, Any]) -> bool:
        """Does this request burn budget under this SLO?  An errored
        request is bad under every SLO (it delivered nothing)."""
        if rec.get("error"):
            return True
        if self.metric == "error":
            return False
        v = rec.get(self.metric)
        if v is None:
            # retired without the measurement (torn stream): count it
            # bad — absence of evidence must not look like health
            return True
        return float(v) > float(self.threshold_ms)


DEFAULT_SLOS = (
    SLOSpec("ttft_p99_ms", "ttft_ms", 500.0),
    SLOSpec("latency_p99_ms", "latency_ms", 5000.0),
    SLOSpec("error_rate", "error", None, objective=0.99),
)


def parse_specs(text: str) -> List[SLOSpec]:
    """Parse the ``--slo`` DSL: comma-separated ``NAME<=VALUE`` with
    NAME one of ttft_p99_ms / latency_p99_ms / error_rate (VALUE: ms
    for the latency pair, the max bad fraction for error_rate).
    Empty input yields DEFAULT_SLOS.  Raises ValueError with the
    offending spec on malformed input."""
    text = (text or "").strip()
    if not text:
        return list(DEFAULT_SLOS)
    out: List[SLOSpec] = []
    for part in text.split(","):
        part = part.strip()
        if not part:
            continue
        name, sep, val = part.partition("<=")
        name = name.strip()
        if not sep or name not in _METRIC_FIELDS:
            raise ValueError(
                f"bad SLO spec {part!r} (want NAME<=VALUE with NAME "
                f"one of {sorted(_METRIC_FIELDS)})")
        try:
            v = float(val)
        except ValueError:
            raise ValueError(f"bad SLO value in {part!r}")
        if name == "error_rate":
            if not 0.0 < v < 1.0:
                raise ValueError(
                    f"error_rate bound {v} must be in (0, 1)")
            out.append(SLOSpec(name, "error", None, objective=1.0 - v))
        else:
            if v <= 0:
                raise ValueError(f"threshold in {part!r} must be > 0")
            out.append(SLOSpec(name, _METRIC_FIELDS[name], v))
    return out


def records_from_spans(rows: Iterable[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Per-request SLO records from a span stream: one dict per
    request that REACHED a terminal state, carrying ``retire_tick``,
    ``ttft_ms``, ``latency_ms``, ``error`` and its typed
    ``terminal`` (result / timeout / shed / failed).  A ``timeout``
    or ``failed`` terminal is an errored request — it delivered
    nothing within its contract — so it burns budget under every SLO;
    ``shed`` records ride along for ``evaluate``'s separate shed rate
    but are EXCLUDED from the SLO windows (a typed 503 is the
    admission policy working, not the service breaking its latency
    promise).  In-flight requests are excluded — they haven't
    consumed budget yet.  So are non-shed records with no ``submit``
    event: the /slo surface reads bounded TAILS, and a long-running
    server's oldest lifecycle heads scroll out — a retire whose
    submit was truncated away is missing its measurements by
    TRUNCATION, not by failure, and must not read as bad (events are
    time-ordered, so submit-in-tail implies the rest of the lifecycle
    is too)."""
    from .spans import reconstruct

    out = []
    for (proc, rid), rec in sorted(reconstruct(rows).items()):
        err = rec.get("error")
        terminal = rec.get("terminal")
        if terminal is None and not err:
            continue
        if "submit_t" not in rec and terminal != "shed":
            continue
        rt = rec.get("retire_tick")
        if rt is None:
            rt = rec.get("timeout_tick")
        if rt is None:
            rt = rec.get("shed_tick")
        if rt is None:
            # an errored request may never have retired; pin it to the
            # last tick it touched (or 0) so windows include it
            ticks = rec.get("ticks") or []
            rt = ticks[-1] if ticks else 0
        out.append({
            "proc": proc,
            "rid": rid,
            # the fleet collector's source stamp (None on a
            # single-engine stream) — fleet_evaluate groups on it
            "source": rec.get("source"),
            "terminal": terminal or "failed",
            "retire_tick": int(rt),
            "ttft_ms": rec.get("ttft_ms"),
            "latency_ms": rec.get("latency_ms"),
            # timeout/failed burn budget under every SLO (the typed
            # non-delivery terminals); shed is handled separately
            "error": bool(err) or terminal in ("timeout", "failed"),
        })
    return out


def _percentile(vals: List[float], q: float) -> Optional[float]:
    # np.percentile (linear interpolation) — the SAME definition
    # serving/engine.stats() and the gated bench rows use, so
    # dtx_slo_observed_p99_ms and dtx_generate_ttft_p99_ms agree on
    # identical data
    if not vals:
        return None
    import numpy as np

    return float(np.percentile(vals, q * 100.0))


def evaluate(records: List[Dict[str, Any]],
             specs: Optional[Iterable[SLOSpec]] = None,
             now_tick: Optional[int] = None) -> Dict[str, Any]:
    """Evaluate every spec over the records' sliding tick windows.

    Pure and closed-form: given the same records and ``now_tick`` the
    verdict is bit-identical (the tier-1 tests pin exact burn rates).
    ``now_tick`` defaults to the newest ``retire_tick`` observed.

    Shed requests (terminal "shed") are carved out before the SLO
    windows slide: a typed 503 is admission control doing its job,
    not a latency/error-budget burn — they get their OWN rate in the
    returned ``shed`` section (count + shed fraction of all terminals
    per window), surfaced as the ``dtx_slo_shed_rate`` gauge."""
    specs = list(DEFAULT_SLOS if specs is None else specs)
    if now_tick is None:
        now_tick = max((r["retire_tick"] for r in records), default=0)
    shed_records = [r for r in records
                    if r.get("terminal") == "shed"]
    records = [r for r in records if r.get("terminal") != "shed"]
    slos: List[Dict[str, Any]] = []
    breaches: List[str] = []
    for spec in specs:
        windows: Dict[str, Dict[str, Any]] = {}
        burning = []
        for label, w in (("fast", spec.fast_window),
                         ("slow", spec.slow_window)):
            inside = [r for r in records
                      if r["retire_tick"] > now_tick - w]
            bad = sum(1 for r in inside if spec.bad(r))
            n = len(inside)
            bad_frac = (bad / n) if n else 0.0
            budget = 1.0 - spec.objective
            # rounded ONCE and compared rounded: the displayed burn
            # rate and the breach decision must agree (1 - 0.99 is
            # not exactly 0.01 in floats)
            burn = round(bad_frac / budget, 6) if budget > 0 else 0.0
            windows[label] = {
                "window_ticks": w, "requests": n, "bad": bad,
                "bad_frac": round(bad_frac, 6),
                "burn_rate": burn,
            }
            burning.append(n > 0 and burn >= spec.burn_threshold)
        doc: Dict[str, Any] = {
            "name": spec.name, "metric": spec.metric,
            "threshold_ms": spec.threshold_ms,
            "objective": spec.objective,
            "burn_threshold": spec.burn_threshold,
            "windows": windows,
            # both windows must burn: the multi-window AND
            "breach": all(burning),
        }
        if spec.metric != "error":
            slow = [float(r[spec.metric]) for r in records
                    if r["retire_tick"] > now_tick - spec.slow_window
                    and isinstance(r.get(spec.metric), (int, float))]
            doc["observed_p99_ms"] = _percentile(slow, 0.99)
        if doc["breach"]:
            breaches.append(spec.name)
        slos.append(doc)
    # shed's own rate over the slow window: shed / (shed + served)
    # among terminals inside the window — the load-shedding pressure
    # signal, deliberately NOT an SLO breach input
    w = max((s.slow_window for s in specs), default=SLOW_WINDOW)
    shed_in = sum(1 for r in shed_records
                  if r["retire_tick"] > now_tick - w)
    served_in = sum(1 for r in records
                    if r["retire_tick"] > now_tick - w)
    shed_doc = {
        "window_ticks": w,
        "shed": shed_in,
        "terminals": shed_in + served_in,
        "rate": (round(shed_in / (shed_in + served_in), 6)
                 if shed_in + served_in else 0.0),
    }
    return {
        "v": SCHEMA_VERSION,
        "kind": "slo_report",
        "now_tick": int(now_tick),
        "requests": len(records),
        "shed": shed_doc,
        "slos": slos,
        "breaches": breaches,
        "ok": not breaches,
    }


def _source_of(rec: Dict[str, Any]) -> str:
    """A record's fleet-source label: the collector's ``source`` stamp
    when present, else the process index (a single-dir multi-proc run
    federates per process)."""
    src = rec.get("source")
    return str(src) if src else f"proc{rec.get('proc', 0)}"


def fleet_evaluate(records: List[Dict[str, Any]],
                   specs: Optional[Iterable[SLOSpec]] = None,
                   now_tick: Optional[int] = None) -> Dict[str, Any]:
    """Federated SLO evaluation over a merged multi-source stream.

    Evaluates the fleet (the union of every source's records) and each
    source separately, all against ONE shared ``now_tick`` (the newest
    retire_tick fleet-wide) — the alignment that makes the closed-form
    identity exact: because the per-source record sets PARTITION the
    fleet set inside every window, the fleet's bad/request counts are
    the integer sums of the per-source counts, and the fleet burn rate
    is exactly ``round((Σ bad_s / Σ n_s) / budget, 6)`` — the
    request-weighted combination of the per-source bad fractions.
    The ``identity`` section re-derives the fleet burn from the
    per-source window counts and checks the equalities exactly (no
    tolerance); a violation means the merge double-counted or dropped
    a record, which is precisely what it is there to catch.

    Returns ``{"kind": "fleet_slo_report", fleet, per_source,
    identity, ...}``; ``ok`` requires the fleet verdict AND the
    identity to hold."""
    specs = list(DEFAULT_SLOS if specs is None else specs)
    records = list(records)
    if now_tick is None:
        now_tick = max((r["retire_tick"] for r in records), default=0)
    sources = sorted({_source_of(r) for r in records})
    fleet = evaluate(records, specs, now_tick=now_tick)
    per_source = {
        s: evaluate([r for r in records if _source_of(r) == s],
                    specs, now_tick=now_tick)
        for s in sources
    }
    checks: List[Dict[str, Any]] = []
    holds = True
    for i, spec in enumerate(specs):
        budget = 1.0 - spec.objective
        for label in ("fast", "slow"):
            fw = fleet["slos"][i]["windows"][label]
            sum_bad = sum(
                per_source[s]["slos"][i]["windows"][label]["bad"]
                for s in sources)
            sum_n = sum(
                per_source[s]["slos"][i]["windows"][label]["requests"]
                for s in sources)
            recombined = (round((sum_bad / sum_n) / budget, 6)
                          if sum_n and budget > 0 else 0.0)
            ok = (fw["bad"] == sum_bad
                  and fw["requests"] == sum_n
                  and fw["burn_rate"] == recombined)
            holds = holds and ok
            checks.append({
                "slo": spec.name, "window": label,
                "fleet_bad": fw["bad"],
                "sum_source_bad": sum_bad,
                "fleet_requests": fw["requests"],
                "sum_source_requests": sum_n,
                "fleet_burn": fw["burn_rate"],
                "recombined_burn": recombined,
                "holds": ok,
            })
    return {
        "v": SCHEMA_VERSION,
        "kind": "fleet_slo_report",
        "now_tick": int(now_tick),
        "sources": sources,
        "fleet": fleet,
        "per_source": per_source,
        "identity": {"holds": holds, "checks": checks},
        "breaches": fleet["breaches"],
        "ok": fleet["ok"] and holds,
    }
