"""Request-lifecycle span recorder for the serving stack.

PR 8's serving stack reports only aggregate counters
(``DecodeEngine.stats()``); a router, an autoscaler or a human
debugging one slow request needs the *per-request* record: when it
was submitted, how long admission blocked it (and on what), when its
prefill ran, when the first token came back, which decode ticks it
shared and with how full a batch, and when it retired.  This module
is that record:

- ``SpanRecorder`` appends one strict-JSON row per lifecycle event to
  ``<logs_path>/spans.<proc>.jsonl`` (the metrics-stream discipline:
  one file per process, line-buffered, non-finite floats stringified
  via flight.py's ``_jsonable``, a bad fd degrades the stream instead
  of killing the engine) and keeps a bounded in-memory ring so the
  live ``/trace?rid=N`` endpoint never re-reads the file;
- the event vocabulary is pinned in ``obs/buckets.py SPAN_EVENTS``
  and the per-event field contract in ``obs/schema.py``
  (``SPAN_COMMON``/``SPAN_FIELDS``/``SPAN_REQUIRED``), so a drifted
  name fails at the emit site or in ``dtx-obs validate``, never in a
  consumer months later;
- ``reconstruct(rows)`` folds a span stream back into per-request
  lifecycle records — the exactly-once invariant (each milestone
  event at most once per rid, every accepted rid retiring) is
  *checked* during reconstruction and violations surface in each
  record's ``errors`` list.

The scheduler (serving/scheduler.py) stays jax-free by emitting
through an *injected* recorder — it never imports this module; the
engine (serving/engine.py) threads one recorder through both layers.
Tracing is host-side appends only: greedy decode outputs are
token-identical with tracing on or off (pinned in
tests/test_serving.py).

Lifecycle (one accepted request)::

    submit ── blocked(reason)* ── admit ── prefill ── first_token
           ── [tick]* ── retire | timeout | failed

``blocked`` repeats once per tick the request stays unadmitted (with
``reason`` "pages", "slots" or "brownout" — the admission-accounting
signal); ``tick`` rows are per decode step, shared across the batch
(``rids`` lists the members, ``occupancy`` the KV-pool fill);
``error`` marks requests failed by an engine-loop death (no retire
follows).

Fail-open extensions (PR 15): ``timeout`` ends a request whose
deadline expired or that the client cancelled (pages freed, reason
says which); ``shed`` is a bounded-queue rejection — the ONE terminal
without a submit, since the request was never accepted; under engine
supervision a crash emits ``engine_restart`` (batch-shaped, the torn-
down in-flight rids) and each surviving request a ``requeue`` (its
admit/prefill/first_token milestones legitimately repeat — the
exactly-once fold resets them), with ``failed`` closing a request
whose retry budget is spent.  ``reconstruct`` classifies every
record's ``terminal`` ∈ {result, timeout, shed, failed} and flags a
record carrying more than one — the terminates-exactly-once invariant
the chaos suite asserts.

Fleet extensions (PR 16): every request carries a stable W3C
``trace_id`` (accepted/minted at the serving edge, threaded through
``engine.submit`` → scheduler → every span it emits, preserved across
supervised restarts) so a lifecycle can be followed across processes;
``reconstruct`` carries it onto the record and flags a mid-lifecycle
change.  The stream itself is bounded by size-based rotation
(``spans.<proc>.jsonl.1`` … keep-K, newest rotation = ``.1``);
``read_spans`` stitches the rotated segments back together so
``reconstruct``/``load_spans``/the fleet collector see one unbroken
stream.
"""

from __future__ import annotations

import collections
import glob
import json
import os
import re
import time
from typing import Any, Dict, Iterable, List, Optional, Tuple

from .buckets import SPAN_EVENTS
from .flight import _jsonable
from .schema import SCHEMA_VERSION

# in-memory ring default: enough for the /trace view of a busy tail
# without growing per request forever
RING_CAPACITY = 8192

# the exactly-once milestones (per rid); blocked/tick/error repeat.
# admit/prefill/first_token RESET on a requeue event (a supervised
# engine restart re-runs them legitimately); the terminals never do.
MILESTONES = ("submit", "admit", "prefill", "first_token", "retire",
              "timeout", "shed", "failed")

# the typed terminal states (PR 15): every accepted request reaches
# exactly one — "result" (a retire event), "timeout" (deadline or
# cancel), "shed" (bounded-queue rejection; the one terminal with no
# submit), "failed" (retry budget spent, or a legacy "error" row).
# reconstruct() classifies each record's ``terminal`` from these.
TERMINALS = ("result", "timeout", "shed", "failed")

_SPANS_RE = re.compile(r"spans\.(\d+)\.jsonl$")

# a W3C trace-context header: version-trace_id-parent_id-flags
# (https://www.w3.org/TR/trace-context/).  We accept any version byte
# but reject the all-zero ids the spec marks invalid.
_TRACEPARENT_RE = re.compile(
    r"^([0-9a-f]{2})-([0-9a-f]{32})-([0-9a-f]{16})-([0-9a-f]{2})$")


def new_trace_id() -> str:
    """A fresh 32-hex (128-bit) W3C trace id."""
    return os.urandom(16).hex()


def new_span_id() -> str:
    """A fresh 16-hex (64-bit) W3C span id (the serving edge's own id,
    returned to the caller in the response traceparent)."""
    return os.urandom(8).hex()


def parse_traceparent(header: Any) -> Optional[Tuple[str, str]]:
    """``(trace_id, parent_id)`` from a ``traceparent`` header value,
    or None when absent/malformed/all-zero — a bad header degrades to
    a fresh trace, never to a rejected request."""
    if not isinstance(header, str):
        return None
    m = _TRACEPARENT_RE.match(header.strip().lower())
    if not m:
        return None
    _ver, trace_id, parent_id, _flags = m.groups()
    if trace_id == "0" * 32 or parent_id == "0" * 16:
        return None
    return trace_id, parent_id


def format_traceparent(trace_id: str, span_id: str) -> str:
    """The response-header form: version 00, sampled flag set."""
    return f"00-{trace_id}-{span_id}-01"


def span_files(logs_path: str) -> List[Tuple[int, str]]:
    """[(proc_index, path)] for every span stream in a run dir — the
    one place the naming/discovery convention lives (the CLI, the
    status server and the SLO evaluator all reuse it)."""
    out = []
    for path in sorted(glob.glob(os.path.join(logs_path,
                                              "spans.*.jsonl"))):
        m = _SPANS_RE.search(os.path.basename(path))
        if m:
            out.append((int(m.group(1)), path))
    return out


class SpanRecorder:
    """Append-only span stream + bounded in-memory ring.

    ``emit`` validates the event name against the obs/buckets.py
    registry (the WindowTimer.charge discipline), stamps the schema
    version and writes one strict-JSON line.  Telemetry must degrade,
    never kill the engine it observes: a bad fd / full volume closes
    the stream and emission becomes ring-only.

    ``rotate_bytes`` > 0 bounds the stream on disk (the bounded-queue
    lesson from PR 15, applied to the file that previously grew
    without limit on a long-lived engine): when the live file would
    exceed the limit it cascades to ``spans.<proc>.jsonl.1`` …
    ``.<keep>`` (newest rotation = ``.1``, oldest dropped) and a fresh
    live file is opened.  ``read_spans`` stitches the segments back
    together."""

    def __init__(self, logs_path: str, process_index: int = 0,
                 ring: int = RING_CAPACITY, rotate_bytes: int = 0,
                 keep: int = 3,
                 extra: Optional[Dict[str, Any]] = None):
        import threading

        os.makedirs(logs_path, exist_ok=True)
        # constant fields stamped onto EVERY emitted row (event fields
        # win on collision): serving/replay.py attributes a whole
        # replay stream to its workload with extra={"replay_of": id}
        self.extra = dict(extra or {})
        self.process_index = int(process_index)
        self.rotate_bytes = int(rotate_bytes)
        self.keep = max(1, int(keep))
        self.path = os.path.join(
            logs_path, f"spans.{self.process_index}.jsonl")
        self._f = open(self.path, "a", buffering=1)  # line-buffered
        self._written = os.path.getsize(self.path)
        self.ring: collections.deque = collections.deque(maxlen=ring)
        # the engine emits under ITS lock, but /trace /slo readers are
        # HTTP handler threads: snapshot() must not race an append
        self._ring_lock = threading.Lock()

    def emit(self, event: str, **fields) -> None:
        if event not in SPAN_EVENTS:
            # one registry (obs/buckets.py) names every span event; an
            # unknown name would silently vanish from reconstruction
            raise ValueError(f"unknown span event {event!r}: expected "
                             f"one of {SPAN_EVENTS}")
        row = {"kind": "span", "v": SCHEMA_VERSION, "t": time.time(),
               "proc": self.process_index, "event": event,
               **self.extra, **_jsonable(fields)}
        with self._ring_lock:
            self.ring.append(row)
        if self._f is None:
            return
        try:
            line = json.dumps(row, allow_nan=False) + "\n"
            if (self.rotate_bytes > 0 and self._written > 0
                    and self._written + len(line) > self.rotate_bytes):
                self._rotate()
                if self._f is None:
                    return
            self._f.write(line)
            self._written += len(line)
        except (OSError, ValueError):
            try:
                self._f.close()
            except Exception:
                pass
            self._f = None

    def _rotate(self) -> None:
        """Cascade the live file to ``.1`` (``.keep`` dropped) and
        reopen.  A rotation failure degrades to ring-only, the same
        contract as a bad fd."""
        try:
            self._f.close()
        except Exception:
            pass
        try:
            last = f"{self.path}.{self.keep}"
            if os.path.exists(last):
                os.remove(last)
            for i in range(self.keep - 1, 0, -1):
                src = f"{self.path}.{i}"
                if os.path.exists(src):
                    os.replace(src, f"{self.path}.{i + 1}")
            os.replace(self.path, f"{self.path}.1")
            self._f = open(self.path, "a", buffering=1)
            self._written = 0
        except OSError:
            self._f = None

    def snapshot(self) -> List[Dict[str, Any]]:
        """A consistent copy of the ring (the live /trace and /slo
        data source — no file re-read while the engine is attached)."""
        with self._ring_lock:
            return list(self.ring)

    def rows_for(self, rid: int) -> List[Dict[str, Any]]:
        """Every ring row touching ``rid`` — its own events plus the
        shared decode ticks it was a member of (the /trace view)."""
        rid = int(rid)
        return [r for r in self.snapshot()
                if r.get("rid") == rid or rid in (r.get("rids") or ())]

    def flush(self) -> None:
        if self._f is not None:
            self._f.flush()

    def close(self) -> None:
        if self._f is None:
            return
        try:
            self._f.flush()
        finally:
            self._f.close()
            self._f = None


def rotated_files(path: str) -> List[str]:
    """Every on-disk segment of one span stream, oldest first:
    ``<path>.<keep>`` … ``<path>.1`` then the live ``<path>`` (the
    SpanRecorder rotation convention).  A never-rotated stream is just
    ``[path]``."""
    segs = []
    for p in glob.glob(glob.escape(path) + ".*"):
        suffix = p[len(path) + 1:]
        if suffix.isdigit():
            segs.append((int(suffix), p))
    segs.sort(reverse=True)
    files = [p for _n, p in segs]
    if os.path.exists(path) or not files:
        files.append(path)
    return files


def read_spans(path: str,
               include_rotated: bool = True) -> List[Dict[str, Any]]:
    """Parse a spans.<proc>.jsonl back into rows (whole lines only —
    a torn trailing append is skipped, not half-parsed).  Rotated
    segments (``<path>.K`` … ``.1``) are stitched in front of the live
    file by default, so a bounded stream reconstructs identically to
    an unbounded one."""
    rows = []
    for p in (rotated_files(path) if include_rotated else [path]):
        with open(p) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rows.append(json.loads(line))
                except ValueError:
                    continue
    return rows


def load_spans(logs_path: str) -> List[Dict[str, Any]]:
    """All span rows under a run dir, time-ordered across processes."""
    rows: List[Dict[str, Any]] = []
    for _pid, path in span_files(logs_path):
        rows.extend(read_spans(path))
    rows.sort(key=lambda r: (r.get("t") or 0.0))
    return rows


def reconstruct(
        rows: Iterable[Dict[str, Any]]) -> Dict[tuple, Dict[str, Any]]:
    """Fold a span stream into per-request lifecycle records.

    Returns ``{(proc, rid): record}`` — keyed by the PAIR because
    every engine numbers its rids from 0, so streams merged across
    processes (``load_spans``) would otherwise conflate distinct
    requests into one corrupted record.  Each record carries the
    milestone timestamps/payloads, the blocked-reason counts, the
    decode-tick attribution and a ``complete`` verdict.  The
    exactly-once invariant is CHECKED here: a duplicate milestone, a
    milestone for a never-submitted rid, or a retire whose
    ``generated`` disagrees with ``max_new_tokens`` lands in that
    record's ``errors`` list — reconstruction never raises on a torn
    stream."""
    recs: Dict[tuple, Dict[str, Any]] = {}

    def rec_for(proc: int, rid: int) -> Dict[str, Any]:
        r = recs.get((proc, rid))
        if r is None:
            r = recs[(proc, rid)] = {
                "proc": proc, "rid": rid, "blocked": {},
                "decode_ticks": 0, "ticks": [], "errors": [],
            }
        return r

    for row in rows:
        event = row.get("event")
        proc = int(row.get("proc") or 0)
        if event in ("tick", "engine_restart"):
            # batch-shaped rows: attributed to every member rid
            for rid in (row.get("rids") or ()):
                r = rec_for(proc, int(rid))
                if event == "tick":
                    r["decode_ticks"] += 1
                    r["ticks"].append(row.get("tick"))
                else:
                    r["engine_restarts"] = \
                        r.get("engine_restarts", 0) + 1
            continue
        rid = row.get("rid")
        if rid is None:
            continue
        r = rec_for(proc, int(rid))
        # trace-context carry (v7): the id must be STABLE across the
        # whole lifecycle — a supervised restart requeues the request
        # under the same trace_id, and a change mid-stream means two
        # requests were conflated (or propagation broke).
        tid = row.get("trace_id")
        if isinstance(tid, str):
            if "trace_id" not in r:
                r["trace_id"] = tid
            elif r["trace_id"] != tid:
                r["errors"].append(
                    f"trace_id changed mid-lifecycle: "
                    f"{r['trace_id']} -> {tid}")
        if "parent_id" not in r and isinstance(row.get("parent_id"),
                                               str):
            r["parent_id"] = row["parent_id"]
        if "source" not in r and isinstance(row.get("source"), str):
            r["source"] = row["source"]
        if "replay_of" not in r and isinstance(row.get("replay_of"),
                                               str):
            r["replay_of"] = row["replay_of"]
        if event in MILESTONES:
            key = f"{event}_t"
            if key in r:
                r["errors"].append(f"duplicate {event}")
                continue
            r[key] = row.get("t")
        if event == "submit":
            r["prompt_len"] = row.get("prompt_len")
            r["max_new_tokens"] = row.get("max_new_tokens")
            r["arrival"] = row.get("arrival")
            if row.get("deadline") is not None:
                r["deadline"] = row.get("deadline")
            if row.get("fingerprint") is not None:
                # the v10 prompt-block hashes workload capture reads
                r["fingerprint"] = row.get("fingerprint")
        elif event == "blocked":
            reason = str(row.get("reason"))
            r["blocked"][reason] = r["blocked"].get(reason, 0) + 1
        elif event == "admit":
            r["pages_held"] = row.get("pages_held")
            r["admit_tick"] = row.get("tick")
            if row.get("clamped"):
                r["brownout_clamped"] = True
        elif event == "prefill":
            r["prefill_bucket"] = row.get("bucket")
        elif event == "first_token":
            r["ttft_ms"] = row.get("ttft_ms")
        elif event == "retire":
            r["generated"] = row.get("generated")
            r["finish_t"] = row.get("finish_t")
            r["retire_tick"] = row.get("tick")
        elif event == "error":
            r["error"] = str(row.get("reason"))
        elif event == "timeout":
            r["timeout_reason"] = str(row.get("reason"))
            r["timeout_tick"] = row.get("tick")
            r["generated"] = row.get("generated")
        elif event == "shed":
            r["shed_reason"] = str(row.get("reason"))
            r["shed_tick"] = row.get("tick")
        elif event == "failed":
            r["failed_reason"] = str(row.get("reason"))
            r["attempts"] = row.get("attempts")
        elif event in ("route", "failover"):
            # fleet-router narration (v9): WHERE the request went.
            # The lifecycle itself lives in a REPLICA's stream (under
            # that stream's own rid), so these rows create no
            # milestone expectations — a record holding only them is
            # narration, not a truncated lifecycle.
            key = "routes" if event == "route" else "failovers"
            r[key] = r.get(key, 0) + 1
            r["replica"] = row.get("replica")
            if row.get("attempt") is not None:
                r["attempt"] = row.get("attempt")
        elif event == "requeue":
            # a supervised re-admission legitimately re-runs the
            # admission/prefill milestones: reset their exactly-once
            # slate (the terminals stay armed) and count the retry.
            # The aborted attempt's measurements go too — a stale
            # ttft from discarded tokens must not feed the SLO fold
            # if the retry never produces a new first_token
            # (brownout_clamped stays sticky: the budget mutation
            # survives the requeue).
            r["requeues"] = r.get("requeues", 0) + 1
            r["attempt"] = row.get("attempt")
            for k in ("admit", "prefill", "first_token"):
                r.pop(f"{k}_t", None)
            for k in ("ttft_ms", "prefill_bucket", "pages_held",
                      "admit_tick"):
                r.pop(k, None)

    for _key, r in recs.items():
        # terminal classification: exactly one of the typed ends.
        # "error" (unsupervised loop death) types as failed too.
        ends = [t for t, k in (("result", "retire_t"),
                               ("timeout", "timeout_t"),
                               ("shed", "shed_t"),
                               ("failed", "failed_t"))
                if k in r]
        if "error" in r and not ends:
            ends = ["failed"]
        r["terminal"] = ends[0] if len(ends) == 1 else None
        if len(ends) > 1:
            r["errors"].append(
                f"multiple terminals: {'+'.join(ends)}")
        # router narration streams hold route/failover rows (and
        # nothing else) per fleet rid: mark them so consumers can
        # separate narration from lifecycles, and exempt them from
        # the lifecycle checks below
        r["narration"] = bool(
            (r.get("routes") or r.get("failovers"))
            and "submit_t" not in r and "shed_t" not in r
            and r.get("error") is None)
        # shed is the one terminal without a submit: the request was
        # never accepted, so the no-submit check exempts it (router
        # narration describes a lifecycle that lives elsewhere)
        if "submit_t" not in r and "shed_t" not in r \
                and not r["narration"]:
            r["errors"].append("no submit event")
        if "shed_t" in r and "submit_t" in r:
            r["errors"].append("shed after submit (shed requests are "
                               "never accepted)")
        for a, b in (("admit", "submit"), ("retire", "admit")):
            if f"{a}_t" in r and f"{b}_t" not in r:
                r["errors"].append(f"{a} without {b}")
        if ("retire_t" in r and "generated" in r
                and r.get("max_new_tokens") is not None
                and not r.get("brownout_clamped")
                and r["generated"] != r["max_new_tokens"]):
            # (a brownout-clamped admit legitimately retires short of
            # the submitted budget — the clamp IS the degradation)
            r["errors"].append(
                f"generated {r['generated']} != max_new_tokens "
                f"{r['max_new_tokens']}")
        if (r.get("arrival") is not None and r.get("finish_t")
                is not None):
            r["latency_ms"] = round(
                (r["finish_t"] - r["arrival"]) * 1e3, 3)
        # complete = reached exactly one TYPED terminal cleanly.  A
        # legacy "error" row (unsupervised loop death) types the
        # terminal as failed but stays incomplete: it marks a
        # truncated lifecycle, not a closed one.
        r["complete"] = (not r["errors"] and (
            (r["terminal"] == "result" and "admit_t" in r)
            or r["terminal"] in ("timeout", "shed")
            or (r["terminal"] == "failed" and "failed_t" in r)))
    return recs


def trace_record(rows: Iterable[Dict[str, Any]], rid: int,
                 proc: Optional[int] = None) -> Optional[Dict[str, Any]]:
    """The /trace?rid=N payload: the reconstructed record plus the
    raw events touching ``rid`` (its own + shared ticks).  ``proc``
    disambiguates merged multi-process streams (every engine numbers
    rids from 0); unset, the lowest matching proc wins and the other
    candidates are listed in ``ambiguous_procs``."""
    rid = int(rid)
    rows = list(rows)
    recs = reconstruct(rows)
    procs = sorted(p for p, r in recs if r == rid
                   and (proc is None or p == proc))
    if not procs:
        return None
    pick = procs[0]
    events = [r for r in rows
              if int(r.get("proc") or 0) == pick
              and (r.get("rid") == rid or rid in (r.get("rids") or ()))]
    doc = {"rid": rid, "proc": pick,
           "record": recs[(pick, rid)], "events": events}
    if len(procs) > 1:
        doc["ambiguous_procs"] = procs
    return doc
