"""Fleet queueing analytics over the span stream.

The waterfall (obs/waterfall.py) explains ONE request; this module
explains the QUEUE: arrival rate, per-prompt-bucket service time,
decode utilization, and a Little's-law consistency check.  The law
(L = lambda * W) is an accounting identity, not a model: over a
window where every arrival also terminates,

    integral of N(t) dt  =  sum of per-request sojourn times,

so L (the time-average number in system) must equal the arrival rate
times the mean sojourn EXACTLY.  A relative error beyond tolerance is
therefore EVIDENCE OF UNTRACKED TIME — requests whose terminal never
made it into the stream (torn tail, crashed writer, dropped rows) —
the same "buckets must sum to wall" honesty discipline, applied to
the whole fleet.  ``violations`` counts the in-flight/torn requests
that explain a gap.

``queueing_report()`` feeds the FLEET_REPORT's optional "queueing"
section (obs/collector.py, schema v8), ``dtx-obs explain --fleet``
and the ``/fleet`` endpoint.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

# a request is "in system" from submit to its typed terminal
_TERMINALS = ("retire", "timeout", "shed", "failed", "error")


def queueing_report(rows: List[dict],
                    tolerance: float = 0.05) -> Optional[dict]:
    """Queueing analytics over a span stream (one proc's file or a
    collector-merged fleet stream); None when the stream carries no
    submits to measure."""
    submits: Dict[Tuple[int, int], float] = {}
    terminals: Dict[Tuple[int, int], float] = {}
    admits: Dict[Tuple[int, int], float] = {}
    bucket_of: Dict[Tuple[int, int], int] = {}
    occupancies: List[float] = []
    for row in rows:
        ev = row.get("event")
        proc = row.get("proc")
        rid = row.get("rid")
        t = row.get("t")
        if ev == "tick" and isinstance(row.get("occupancy"),
                                       (int, float)):
            occupancies.append(float(row["occupancy"]))
            continue
        if not (isinstance(proc, int) and isinstance(rid, int)
                and isinstance(t, (int, float))):
            continue
        key = (proc, rid)
        if ev == "submit":
            submits.setdefault(key, t)
        elif ev == "admit":
            admits.setdefault(key, t)
        elif ev == "prefill" and isinstance(row.get("bucket"), int):
            bucket_of.setdefault(key, row["bucket"])
        elif ev in _TERMINALS:
            terminals.setdefault(key, t)
    if not submits:
        return None

    t_lo = min(submits.values())
    t_hi = max(list(terminals.values()) + list(submits.values()))
    window_s = max(t_hi - t_lo, 1e-9)
    arrivals = len(submits)
    completed = [k for k in submits if k in terminals]
    in_flight = [k for k in submits if k not in terminals]

    # per-prompt-bucket service time: admit -> terminal (the time the
    # request actually held engine resources)
    per_bucket: Dict[str, List[float]] = {}
    for k in completed:
        if k in admits:
            ms = (terminals[k] - admits[k]) * 1e3
            per_bucket.setdefault(str(bucket_of.get(k, 0)),
                                  []).append(ms)
    service = {
        b: {"n": len(v),
            "mean_ms": round(sum(v) / len(v), 3),
            "max_ms": round(max(v), 3)}
        for b, v in sorted(per_bucket.items())
    }

    # Little's law as an identity: L from the integral of the
    # in-system count (= sum of in-window sojourns / window), lambda
    # from arrivals, W from the completed sojourns.  Exact when every
    # arrival terminates in-window; in-flight/torn requests are the
    # violations that explain any gap.
    sojourn_total = sum(
        (terminals.get(k, t_hi) - submits[k]) for k in submits)
    big_l = sojourn_total / window_s
    lam = arrivals / window_s
    w_s = (sum(terminals[k] - submits[k] for k in completed)
           / len(completed)) if completed else 0.0
    lam_w = lam * w_s
    rel_err = (abs(big_l - lam_w) / big_l) if big_l > 0 else 0.0
    return {
        "window_s": round(window_s, 6),
        "arrivals": arrivals,
        "arrival_rate_per_s": round(lam, 4),
        "completed": len(completed),
        "in_flight": len(in_flight),
        "utilization": (round(sum(occupancies) / len(occupancies), 4)
                        if occupancies else None),
        "service_ms_by_bucket": service,
        "littles_law": {
            "L": round(big_l, 6),
            "lambda_per_s": round(lam, 6),
            "W_ms": round(w_s * 1e3, 3),
            "lambda_W": round(lam_w, 6),
            "rel_err": round(rel_err, 6),
            "holds": rel_err <= tolerance,
            "violations": len(in_flight),
        },
    }
