"""Rolling bench history: the trajectory ``--gate`` never had.

``bench.py --gate FILE`` compares against ONE hand-picked baseline —
a point, not a trend: a slow creep (1% per round) passes every
pairwise gate while losing 10% over ten rounds, and the committed
``BENCH_r0*.json`` captures were never machine-readable as a series.
This module is the append-only memory:

- ``append_entry`` reduces any comparison document (bench final
  summary, run report, BENCH capture — obs/compare.extract_metrics
  normalizes) to its gate metrics and appends ONE strict-JSON record
  to a ``history.jsonl`` (shape pinned by obs/schema.HISTORY_ENTRY);
- ``rolling_baseline`` folds the last N entries into a per-metric
  **median** baseline — robust to one noisy round, unlike a
  last-run-wins gate — in the ``history_baseline`` shape
  obs/compare understands, so ``bench.py --gate-rolling N`` reuses
  the exact thresholds and verdict machinery ``--gate`` has;
- ``import_captures`` backfills from the committed ``BENCH_r0*.json``
  driver captures (idempotent on the label), so the trajectory starts
  non-empty instead of waiting N rounds to gate;
- ``trend_table`` renders the ``dtx-obs history`` one-line-per-round
  view.

Everything here is pure file I/O over strict JSON — no jax, laptop-
safe against an rsync'd history.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, Iterable, List, Optional, Tuple

from . import compare as cmp_lib
from .schema import SCHEMA_VERSION, validate_history_file

# trend-table default columns, in priority order; --metrics overrides
TREND_METRICS = ("wall_s", "mfu", "test_accuracy", "goodput_frac",
                 "serving_p99_ms", "serving_tok_s")


def append_entry(path: str, doc: Dict[str, Any], label: str = "",
                 source: str = "", t: Optional[float] = None) -> Dict[str, Any]:
    """Reduce ``doc`` (any obs/compare shape) to its gate metrics and
    append one history record; returns the record (metrics may be
    empty — the caller decides whether that is an error)."""
    entry = {
        "v": SCHEMA_VERSION,
        "kind": "bench_history",
        "t": float(time.time() if t is None else t),
        "label": str(label),
        "source": str(source),
        "metrics": cmp_lib.extract_metrics(doc),
    }
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "a") as f:
        f.write(json.dumps(entry, allow_nan=False) + "\n")
    return entry


def read_history(path: str) -> List[Dict[str, Any]]:
    """Every well-formed history record in the file, in append order.
    Torn/foreign lines are skipped (an append-only log must survive a
    crashed writer); ``dtx-obs validate`` is the strict check."""
    out: List[Dict[str, Any]] = []
    try:
        f = open(path)
    except OSError:
        return out
    with f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                row = json.loads(line)
            except ValueError:
                continue
            if isinstance(row, dict) and row.get("kind") == "bench_history":
                out.append(row)
    return out


def _median(vals: List[float]) -> float:
    s = sorted(vals)
    n = len(s)
    return s[n // 2] if n % 2 else 0.5 * (s[n // 2 - 1] + s[n // 2])


def rolling_baseline(entries: Iterable[Dict[str, Any]],
                     n: int) -> Dict[str, Any]:
    """Per-metric median over the last ``n`` entries, as a
    ``history_baseline`` document obs/compare.extract_metrics reads
    directly — the rolling gate's BASE side.  A metric contributes
    wherever present, so a round that skipped one bench row doesn't
    void the whole baseline."""
    tail = list(entries)[-max(1, int(n)):]
    cols: Dict[str, List[float]] = {}
    for e in tail:
        for name, v in (e.get("metrics") or {}).items():
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                cols.setdefault(name, []).append(float(v))
    return {
        "kind": "history_baseline",
        "entries": len(tail),
        "metrics": {name: _median(vals)
                    for name, vals in sorted(cols.items())},
    }


def import_captures(path: str,
                    capture_paths: Iterable[str]) -> Tuple[int, List[str]]:
    """Backfill the history from committed BENCH captures (or any
    obs/compare-loadable documents).  Idempotent: a capture whose
    label (basename sans extension) already appears is skipped, so
    re-seeding never duplicates rounds.  Returns (appended, skipped
    messages)."""
    have = {e.get("label") for e in read_history(path)}
    appended, skipped = 0, []
    for cap in capture_paths:
        label = os.path.splitext(os.path.basename(cap))[0]
        if label in have:
            skipped.append(f"{cap}: label {label!r} already present")
            continue
        try:
            doc = cmp_lib.load_doc(cap)
        except (OSError, ValueError) as e:
            skipped.append(f"{cap}: unreadable ({e})")
            continue
        metrics = cmp_lib.extract_metrics(doc)
        if not metrics:
            skipped.append(f"{cap}: no gate metrics extractable")
            continue
        # stamp the capture's own mtime so the trend stays in recorded
        # order even when the import happens years later
        try:
            t = os.path.getmtime(cap)
        except OSError:
            t = None
        append_entry(path, doc, label=label, source="import", t=t)
        have.add(label)
        appended += 1
    return appended, skipped


# strict per-line validation: ONE implementation (obs/schema.py, the
# copy dtx-obs validate routes to) — re-exported so history callers
# and the schema hook can never drift apart
validate_file = validate_history_file


def trend_table(entries: List[Dict[str, Any]],
                metrics: Optional[Iterable[str]] = None,
                last: int = 0) -> str:
    """One line per history entry (label, age-ordered) with the
    selected metric columns — the ``dtx-obs history`` view."""
    if last:
        entries = entries[-last:]
    if metrics is None:
        present = set()
        for e in entries:
            present |= set(e.get("metrics") or {})
        metrics = [m for m in TREND_METRICS if m in present] or \
            sorted(present)[:len(TREND_METRICS)]
    metrics = list(metrics)
    wl = max([len("label")] + [len(str(e.get("label"))) for e in entries])

    def fmt(v) -> str:
        if v is None:
            return "-"
        if isinstance(v, float):
            return f"{v:.4g}"
        return str(v)

    head = "label".ljust(wl) + "  " + "  ".join(
        m.rjust(max(len(m), 8)) for m in metrics)
    lines = [head]
    for e in entries:
        m = e.get("metrics") or {}
        lines.append(
            str(e.get("label")).ljust(wl) + "  " + "  ".join(
                fmt(m.get(name)).rjust(max(len(name), 8))
                for name in metrics))
    return "\n".join(lines)
