"""Crash flight recorder: the last K steps survive the crash.

A mid-run failure today leaves a stack trace and nothing else — no
record of what the run was doing when it died (round-5 VERDICT: the
bench crash voided half a round's evidence exactly this way). The
flight recorder is the aviation answer: a bounded ring of the last
``K`` step records (step id, loss, norms, timing, batch index —
whatever the loop had on hand, all host-side, no device traffic)
plus an environment snapshot, dumped to
``<logs_path>/flight/<proc>.json``:

- on **crash** — ``sys.excepthook`` chaining AND the train loop's
  own try/except (pytest and embedded callers never reach the
  excepthook);
- on **anomaly** — the ``--on_anomaly=dump`` policy (obs/anomaly.py);
- on **SIGUSR1** — on-demand from a live run (``kill -USR1 <pid>``),
  with a ``faulthandler`` all-thread stack dump beside it
  (``flight/<proc>.stacks.txt``) — the "is it hung or slow?" probe.

Dumps are atomic (write-then-rename), best-effort (a full volume
must never mask the original failure) and strict-JSON (non-finite
floats are stringified). ``collate`` is the chief-side post-mortem:
it folds every process's dump into ``flight/report.json`` — last
step per process, the step spread (the blast-radius signal: the
laggard is usually the culprit), and all anomalies merged.
"""

from __future__ import annotations

import collections
import faulthandler
import json
import math
import os
import signal
import sys
import time
import traceback
from typing import Any, Dict, List, Optional

from .schema import SCHEMA_VERSION

# the dump's "version" IS the obs schema version (one number for the
# whole package — obs/schema.py documents the history)
FORMAT_VERSION = SCHEMA_VERSION


def _jsonable(x):
    """Strict-JSON-safe copy: NaN/Inf -> strings, unknown types ->
    repr. A forensics dump that a standards-compliant parser rejects
    is a forensics dump that gets lost."""
    if isinstance(x, dict):
        return {str(k): _jsonable(v) for k, v in x.items()}
    if isinstance(x, (list, tuple)):
        return [_jsonable(v) for v in x]
    if isinstance(x, bool) or x is None or isinstance(x, (int, str)):
        return x
    if isinstance(x, float):
        return x if math.isfinite(x) else repr(x)
    try:  # numpy scalars
        import numpy as np

        if isinstance(x, np.integer):
            return int(x)
        if isinstance(x, np.floating):
            return _jsonable(float(x))
        if isinstance(x, np.ndarray):
            return _jsonable(x.tolist())
    except Exception:
        pass
    return repr(x)


def env_snapshot(config: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """One-time environment capture: versions, topology, the JAX/TPU
    env vars and (when given) the full run config — everything a
    post-mortem needs to reproduce the context."""
    import platform
    import socket

    snap: Dict[str, Any] = {
        "python": sys.version.split()[0],
        "platform": platform.platform(),
        "host": socket.gethostname(),
        "pid": os.getpid(),
        "argv": list(sys.argv),
        "env": {k: v for k, v in os.environ.items()
                if k.startswith(("JAX_", "DTX_", "XLA_", "TPU_"))},
    }
    try:
        import jax

        snap["jax"] = jax.__version__
        snap["backend"] = jax.default_backend()
        snap["device_count"] = jax.device_count()
        snap["process_index"] = jax.process_index()
        snap["process_count"] = jax.process_count()
    except Exception:
        pass
    try:
        from .metrics import rss_bytes

        snap["rss_bytes"] = rss_bytes()
    except Exception:
        pass
    if config is not None:
        snap["config"] = _jsonable(config)
    return snap


def install_chained(signum, handler):
    """Install ``handler`` for ``signum``, returning the previous
    handler (to chain to and to restore later) — or None when this is
    not the main thread / the platform lacks the signal. The ONE
    signal-plumbing helper the forensics hooks here (SIGUSR1) and the
    resilience preemption handler (SIGTERM/SIGINT,
    resilience/signals.py) share."""
    try:
        return signal.signal(signum, handler)
    except (ValueError, OSError, AttributeError):
        return None


def restore_handler(signum, prev) -> None:
    """Undo install_chained (best-effort; SIG_DFL when the previous
    handler is unknown)."""
    try:
        signal.signal(signum, prev or signal.SIG_DFL)
    except (ValueError, OSError, AttributeError):
        pass


class FlightRecorder:
    """Bounded ring of step records + dump-on-demand."""

    def __init__(self, logs_path: str, process_index: int = 0,
                 capacity: int = 64, config: Optional[Dict[str, Any]] = None,
                 anomaly_capacity: int = 32, window_capacity: int = 16):
        if capacity < 1:
            raise ValueError(f"capacity={capacity} must be >= 1")
        self.process_index = int(process_index)
        self.dir = os.path.join(logs_path, "flight")
        self.path = os.path.join(self.dir, f"{self.process_index}.json")
        self.stacks_path = os.path.join(
            self.dir, f"{self.process_index}.stacks.txt")
        self.capacity = int(capacity)
        self.records: collections.deque = collections.deque(maxlen=capacity)
        # enriched window records (loss/timing/norms) live in their OWN
        # ring: the bare per-step appends must not evict the few
        # records that actually carry post-mortem signal
        self.windows: collections.deque = collections.deque(
            maxlen=window_capacity)
        self.anomalies: collections.deque = collections.deque(
            maxlen=anomaly_capacity)
        self.env = env_snapshot(config)
        self.dumps = 0
        self.last_reason: Optional[str] = None
        self._prev_excepthook = None
        self._prev_sigusr1 = None
        self._installed = False

    # -- recording (hot path: one deque append, no I/O) --------------------

    def record_step(self, step: int, **fields) -> None:
        self.records.append({"step": int(step), "t": time.time(), **fields})

    def record_window(self, step: int, **fields) -> None:
        """One enriched record per logging window (loss, timing split,
        norms) — its own ring, never evicted by per-step appends."""
        self.windows.append({"step": int(step), "t": time.time(),
                             **fields})

    def attach_loss(self, step: int, loss) -> None:
        """Backfill the fetched loss onto an already-appended step
        record (the anomaly drain learns the loss a few steps after
        dispatch). Right-to-left scan of a <=capacity-long deque —
        cheap, and only runs when --on_anomaly is fetching anyway."""
        for rec in reversed(self.records):
            if rec["step"] == step:
                rec["loss"] = loss
                return
            if rec["step"] < step:
                return

    def record_anomaly(self, step: int, **fields) -> None:
        self.anomalies.append({"step": int(step), "t": time.time(),
                               **fields})

    # -- dumping -----------------------------------------------------------

    def dump(self, reason: str, exc: Optional[BaseException] = None) -> Optional[str]:
        """Write the dump atomically; returns the path, or None on
        failure. NEVER raises — the recorder must not mask the
        failure it is recording."""
        try:
            doc = {
                "version": FORMAT_VERSION,
                "proc": self.process_index,
                "reason": str(reason),
                "t": time.time(),
                "last_step": (self.records[-1]["step"]
                              if self.records else None),
                "steps": _jsonable(list(self.records)),
                "windows": _jsonable(list(self.windows)),
                "anomalies": _jsonable(list(self.anomalies)),
                "env": _jsonable(self.env),
            }
            if exc is not None:
                doc["exception"] = {
                    "type": type(exc).__name__,
                    "message": str(exc)[:2000],
                    "traceback": traceback.format_exception(
                        type(exc), exc, exc.__traceback__)[-30:],
                }
            os.makedirs(self.dir, exist_ok=True)
            tmp = self.path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(doc, f, allow_nan=False, indent=1)
            os.replace(tmp, self.path)  # atomic on POSIX
            self.dumps += 1
            self.last_reason = str(reason)
            return self.path
        except Exception as e:
            try:
                print(f"NOTE: flight dump failed: {e}")
            except Exception:
                pass
            return None

    def dump_stacks(self) -> Optional[str]:
        """faulthandler all-thread stack dump next to the flight dump
        (the SIGUSR1 'where is it stuck?' answer)."""
        try:
            os.makedirs(self.dir, exist_ok=True)
            with open(self.stacks_path, "w") as f:
                f.write(f"# proc {self.process_index} stacks @ "
                        f"{time.time()}\n")
                faulthandler.dump_traceback(file=f, all_threads=True)
            return self.stacks_path
        except Exception:
            return None

    # -- hooks -------------------------------------------------------------

    def install(self) -> None:
        """Chain into sys.excepthook and (main thread only) SIGUSR1.
        The train loop ALSO dumps from its own except clause — callers
        that swallow exceptions (pytest, embedding) bypass the
        excepthook entirely."""
        if self._installed:
            return
        self._prev_excepthook = sys.excepthook

        def _hook(tp, val, tb, _prev=sys.excepthook):
            self.dump("crash", exc=val)
            _prev(tp, val, tb)

        sys.excepthook = _hook

        def _on_sigusr1(signum, frame):
            self.dump("sigusr1")
            self.dump_stacks()
            if callable(self._prev_sigusr1):
                self._prev_sigusr1(signum, frame)

        # non-main thread, or a platform without SIGUSR1 -> None
        self._prev_sigusr1 = install_chained(signal.SIGUSR1, _on_sigusr1)
        self._installed = True

    def uninstall(self) -> None:
        if not self._installed:
            return
        if self._prev_excepthook is not None:
            sys.excepthook = self._prev_excepthook
            self._prev_excepthook = None
        restore_handler(signal.SIGUSR1, self._prev_sigusr1)
        self._prev_sigusr1 = None
        self._installed = False


# -- post-mortem ------------------------------------------------------------


def read_flight(path: str) -> Dict[str, Any]:
    with open(path) as f:
        return json.load(f)


def collate(logs_path: str, write: bool = True) -> Dict[str, Any]:
    """Chief-side collator: fold every ``flight/<proc>.json`` into one
    post-mortem report (written to ``flight/report.json``). The step
    spread across processes is the blast-radius signal — the process
    whose last step trails the fleet is where to look first."""
    fdir = os.path.join(logs_path, "flight")
    dumps: List[Dict[str, Any]] = []
    try:
        names = sorted(os.listdir(fdir))
    except OSError:
        names = []
    for name in names:
        if not name.endswith(".json") or name == "report.json":
            continue
        try:
            dumps.append(read_flight(os.path.join(fdir, name)))
        except (OSError, ValueError):
            continue  # a torn dump still leaves the others readable
    procs = {}
    anomalies: List[Dict[str, Any]] = []
    for d in dumps:
        procs[str(d.get("proc"))] = {
            "reason": d.get("reason"),
            "last_step": d.get("last_step"),
            "t": d.get("t"),
            "exception": (d.get("exception") or {}).get("type"),
        }
        anomalies.extend(d.get("anomalies") or [])
    steps = [p["last_step"] for p in procs.values()
             if p["last_step"] is not None]
    anomalies.sort(key=lambda a: (a.get("step") or 0))
    report = {
        "version": FORMAT_VERSION,
        "t": time.time(),
        "procs": procs,
        "proc_count": len(procs),
        "min_last_step": (min(steps) if steps else None),
        "max_last_step": (max(steps) if steps else None),
        "step_spread": (max(steps) - min(steps) if steps else None),
        "slowest_proc": (min(
            (p for p in procs if procs[p]["last_step"] is not None),
            key=lambda p: procs[p]["last_step"], default=None)
            if steps else None),
        "anomalies": anomalies,
    }
    if write and dumps:
        try:
            tmp = os.path.join(fdir, "report.json.tmp")
            with open(tmp, "w") as f:
                json.dump(_jsonable(report), f, allow_nan=False, indent=1)
            os.replace(tmp, os.path.join(fdir, "report.json"))
        except OSError:
            pass
    return report


if __name__ == "__main__":  # post-mortem CLI: python -m ...obs.flight LOGS
    print(json.dumps(_jsonable(
        collate(sys.argv[1] if len(sys.argv) > 1 else ".")), indent=1))
