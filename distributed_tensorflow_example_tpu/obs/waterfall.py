"""Per-request latency waterfalls from the span stream.

The goodput report (obs/aggregate.py) decomposes a RUN's wall into
disjoint buckets that sum to wall; this module applies the same
discipline to ONE request: its submit→terminal wall is partitioned
into disjoint segments — the obs/buckets.WATERFALL_SEGMENTS registry
— that sum to the wall BY CONSTRUCTION (the segments are the gaps
between consecutive lifecycle boundaries, each labeled by the state
the request was in when the gap opened, so they tile the interval
exactly; ``residual_ms`` is the honesty field and stays ~0).

The state machine rides the span vocabulary (obs/buckets.SPAN_EVENTS):

- ``submit`` opens ``queue_wait``; a ``blocked`` row re-labels the
  wait by its reason (``brownout`` → ``brownout_clamp_delay``, the
  slot/page reasons stay ``queue_wait``) — EXCEPT while the request
  is in post-restart ``requeue``, whose wait is restart overhead, not
  ordinary queueing.
- ``admit`` opens ``prefill`` (admit→first_token: prompt ingestion +
  the first sampled token), ``first_token`` opens ``decode_active``.
- decode time splits on the v8 tick-boundary pair: the scheduler's
  ``tick`` row opens the boundary, the engine's ``tick_done`` closes
  it carrying the execution-only ``dur_ms``.  The execution window
  [done_t - dur, done_t] is ``decode_active``; everything else
  between member ticks is ``decode_stall`` (injected stalls, host
  scheduling, lock waits).  Streams without ``tick_done`` (older
  schema, the pure tick simulator) degrade gracefully: decode time
  stays ``decode_active``.
- ``requeue`` / a member ``engine_restart`` opens ``requeue`` until
  the next ``admit`` — supervised-restart overhead, attributed to the
  requests that paid it.
- the typed terminal (retire/timeout/shed/failed, legacy ``error``)
  closes the waterfall; a trailing post-execution stall before a
  terminal re-labels to ``finalize`` (the retire/timeout narration
  lands at the NEXT scheduler boundary, so the gap is bookkeeping,
  not decode).

``waterfalls()`` derives one document per request, ``summarize()``
the aggregate (per-segment p50/p99 + the sum-to-wall verdict) the
``/explain`` endpoint, the ``dtx_waterfall_*`` gauges and the
``bench_latency_attribution`` row read.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from .buckets import WATERFALL_SEGMENTS
from .schema import SCHEMA_VERSION

# lifecycle events that carry a single "rid" payload
_OWN_EVENTS = ("submit", "blocked", "admit", "prefill", "first_token",
               "retire", "error", "timeout", "shed", "requeue",
               "failed")

_TERMINAL_NAME = {"retire": "result", "timeout": "timeout",
                  "shed": "shed", "failed": "failed",
                  "error": "failed"}

# tie-break priorities mirroring real emission order at one boundary:
# blocked/admit narration, then the tick row, then execution
# (exec_start < prefill < first_token < exec_end), then supervision,
# then the terminal (retires land at the NEXT boundary, strictly
# after that tick's narration)
_PRIO = {"submit": 0, "blocked": 1, "admit": 2, "tick": 3,
         "exec_start": 4, "prefill": 5, "first_token": 6,
         "exec_end": 7, "engine_restart": 8, "requeue": 9,
         "terminal": 10}


def _tick_table(rows: List[dict]) -> Dict[Tuple[int, int], dict]:
    """(proc, tick) -> {"t", "done_t", "dur_s"}: the scheduler's tick
    row joined with the engine's tick_done close.  Tick indices stay
    monotonic across supervised restarts (serving/engine._recover
    rebuilds the scheduler at the old count), so the key is unique."""
    table: Dict[Tuple[int, int], dict] = {}
    for row in rows:
        ev = row.get("event")
        if ev not in ("tick", "tick_done"):
            continue
        proc = row.get("proc")
        tick = row.get("tick")
        if not isinstance(proc, int) or not isinstance(tick, int):
            continue
        ent = table.setdefault((proc, tick), {})
        if ev == "tick":
            ent["t"] = row["t"]
            ent["rids"] = tuple(row.get("rids") or ())
        else:
            ent["done_t"] = row["t"]
            ent["dur_s"] = float(row.get("dur_ms") or 0.0) / 1e3
    return table


def _boundaries(own: List[dict], ticks: List[Tuple[float, dict]],
                restarts: List[dict]) -> List[Tuple[float, int, str, dict]]:
    """Every labeled time boundary of one request, sorted by (t,
    emission priority): its own lifecycle rows, its member tick
    boundaries (with the synthetic exec_start/exec_end pair when the
    tick carries a tick_done close), and member engine restarts."""
    out: List[Tuple[float, int, str, dict]] = []
    for row in own:
        ev = row["event"]
        kind = "terminal" if ev in _TERMINAL_NAME else ev
        out.append((row["t"], _PRIO.get(kind, 5), kind, row))
    for t, ent in ticks:
        out.append((t, _PRIO["tick"], "tick", ent))
        done_t = ent.get("done_t")
        if done_t is not None:
            # the execution window: dur_ms is execution-only wall, so
            # it ends at done_t and starts dur before it — clamped to
            # the tick row (wall t's vs a monotonic duration can
            # disagree by clock granularity)
            start = max(t, done_t - ent.get("dur_s", 0.0))
            out.append((start, _PRIO["exec_start"], "exec_start", ent))
            out.append((done_t, _PRIO["exec_end"], "exec_end", ent))
    for row in restarts:
        out.append((row["t"], _PRIO["engine_restart"], "engine_restart",
                    row))
    out.sort(key=lambda b: (b[0], b[1]))
    return out


def _one(proc: int, rid: int, own: List[dict],
         ticks: List[Tuple[float, dict]],
         restarts: List[dict]) -> Optional[dict]:
    """The waterfall document for one request, or None when the
    stream holds nothing usable for it."""
    if not own:
        return None
    bounds = _boundaries(own, ticks, restarts)
    submit_t = bounds[0][0]
    terminal = None
    terminal_t = bounds[-1][0]
    for t, _p, kind, row in bounds:
        if kind == "terminal":
            terminal = _TERMINAL_NAME[row["event"]]
            terminal_t = t
            break
    complete = terminal is not None
    trace_id = next((r["trace_id"] for r in own
                     if isinstance(r.get("trace_id"), str)), None)

    # walk the boundaries, labeling each gap with the state entered
    # at its start — the gaps tile [submit_t, terminal_t] exactly
    intervals: List[Tuple[float, float, str]] = []
    state = "untracked"
    stall_via_exec = False
    cur_t = submit_t
    decode_ticks = 0
    requeues = 0

    def close(t: float, next_state: str) -> None:
        nonlocal cur_t, state
        t = min(max(t, cur_t), terminal_t)
        if t > cur_t:
            intervals.append((cur_t, t, state))
        cur_t = max(cur_t, t)
        state = next_state

    for t, _p, kind, row in bounds:
        if t > terminal_t:
            break
        if kind == "submit":
            close(t, "queue_wait")
        elif kind == "blocked":
            if state == "requeue":
                continue  # post-restart waiting IS restart overhead
            seg = ("brownout_clamp_delay"
                   if row.get("reason") == "brownout" else "queue_wait")
            close(t, seg)
        elif kind == "admit":
            close(t, "prefill")
        elif kind == "first_token":
            close(t, "decode_active")
            stall_via_exec = False
        elif kind == "tick":
            decode_ticks += 1
            # only a tick with a tick_done close can separate stall
            # from execution; without one (older stream, crash tick)
            # the state is left alone and the restart/terminal decides
            if row.get("done_t") is not None and state in (
                    "decode_active", "decode_stall"):
                close(t, "decode_stall")
                stall_via_exec = False
        elif kind == "exec_start":
            if state in ("decode_active", "decode_stall"):
                close(t, "decode_active")
        elif kind == "exec_end":
            if state == "decode_active":
                close(t, "decode_stall")
                stall_via_exec = True
        elif kind in ("engine_restart", "requeue"):
            if kind == "requeue":
                requeues += 1
            close(t, "requeue")
        elif kind == "terminal":
            # a trailing post-execution stall is retire/timeout
            # bookkeeping at the next scheduler boundary, not decode
            if state == "decode_stall" and stall_via_exec:
                state = "finalize"
            close(t, "done")
            break
    if not complete and cur_t < terminal_t:
        close(terminal_t, "done")

    segs = {name: 0.0 for name in WATERFALL_SEGMENTS}
    for t0, t1, seg in intervals:
        segs[seg] += t1 - t0
    wall_s = terminal_t - submit_t
    sum_s = sum(segs.values())
    doc = {
        "v": SCHEMA_VERSION,
        "kind": "waterfall",
        "proc": proc,
        "rid": rid,
        "terminal": terminal,
        "submit_t": submit_t,
        "terminal_t": terminal_t,
        "wall_ms": round(wall_s * 1e3, 3),
        "segments": {k: round(v * 1e3, 3) for k, v in segs.items()},
        "segment_sum_ms": round(sum_s * 1e3, 3),
        "residual_ms": round((wall_s - sum_s) * 1e3, 6),
        "decode_ticks": decode_ticks,
        "requeues": requeues,
        "complete": complete,
        "intervals": [[t0, t1, seg] for t0, t1, seg in intervals],
    }
    if trace_id is not None:
        doc["trace_id"] = trace_id
    return doc


def waterfalls(rows: List[dict], rid: Optional[int] = None,
               trace_id: Optional[str] = None,
               proc: Optional[int] = None) -> List[dict]:
    """Derive the per-request waterfall documents from a span stream
    (any order; one proc's file or a collector-merged fleet stream),
    optionally filtered to one rid / trace id / proc."""
    table = _tick_table(rows)
    own: Dict[Tuple[int, int], List[dict]] = {}
    for row in rows:
        if row.get("event") in _OWN_EVENTS and isinstance(
                row.get("rid"), int) and isinstance(row.get("proc"), int):
            own.setdefault((row["proc"], row["rid"]), []).append(row)
    member_ticks: Dict[Tuple[int, int], List[Tuple[float, dict]]] = {}
    for (p, _tick), ent in sorted(table.items()):
        if "t" not in ent:
            continue  # tick_done without its tick row (torn tail)
        for r in ent.get("rids", ()):
            if isinstance(r, int):
                member_ticks.setdefault((p, r), []).append(
                    (ent["t"], ent))
    restarts: Dict[Tuple[int, int], List[dict]] = {}
    for row in rows:
        if row.get("event") != "engine_restart":
            continue
        p = row.get("proc")
        for r in (row.get("rids") or ()):
            if isinstance(r, int) and isinstance(p, int):
                restarts.setdefault((p, r), []).append(row)

    out: List[dict] = []
    for (p, r), events in sorted(own.items()):
        if rid is not None and r != rid:
            continue
        if proc is not None and p != proc:
            continue
        doc = _one(p, r, sorted(events, key=lambda e: e["t"]),
                   member_ticks.get((p, r), []),
                   restarts.get((p, r), []))
        if doc is None:
            continue
        if trace_id is not None and doc.get("trace_id") != trace_id:
            continue
        out.append(doc)
    return out


def _pct(vals: List[float], q: float) -> float:
    """Nearest-rank percentile (no numpy: obs/ stays import-light)."""
    if not vals:
        return 0.0
    s = sorted(vals)
    i = min(len(s) - 1, max(0, int(round(q / 100.0 * (len(s) - 1)))))
    return s[i]


def summarize(docs: List[dict]) -> dict:
    """Aggregate a set of waterfalls: per-segment p50/p99/mean, the
    wall percentiles, and the sum-to-wall verdict the attribution
    gate (bench_latency_attribution) holds at <= 1% residual."""
    complete = [d for d in docs if d.get("complete")]
    terminals: Dict[str, int] = {}
    for d in complete:
        terminals[d["terminal"]] = terminals.get(d["terminal"], 0) + 1
    seg_stats = {}
    for name in WATERFALL_SEGMENTS:
        vals = [d["segments"].get(name, 0.0) for d in complete]
        seg_stats[name] = {
            "p50_ms": round(_pct(vals, 50), 3),
            "p99_ms": round(_pct(vals, 99), 3),
            "mean_ms": round(sum(vals) / len(vals), 3) if vals else 0.0,
        }
    fracs = [d["segment_sum_ms"] / d["wall_ms"]
             for d in complete if d["wall_ms"] > 0]
    resid = [abs(d["residual_ms"]) / d["wall_ms"]
             for d in complete if d["wall_ms"] > 0]
    walls = [d["wall_ms"] for d in complete]
    max_resid = max(resid) if resid else 0.0
    return {
        "requests": len(docs),
        "complete": len(complete),
        "terminals": terminals,
        "wall_p50_ms": round(_pct(walls, 50), 3),
        "wall_p99_ms": round(_pct(walls, 99), 3),
        "segments": seg_stats,
        "min_sum_to_wall_frac": round(min(fracs), 6) if fracs else 1.0,
        "max_residual_frac": round(max_resid, 6),
        "sum_to_wall_ok": max_resid <= 0.01,
    }
