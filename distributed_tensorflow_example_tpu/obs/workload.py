"""Workload capture: span streams -> a portable, replayable schedule.

Every observability layer before this module is read-only after the
fact: spans narrate what happened, the collector joins it, waterfalls
and queueing explain it.  This module closes the loop — it distills
any span dir (a single engine run or a v9 fleet run) into the
WORKLOAD document (obs/schema.py, v10): per-request arrival offsets,
prompt/output token counts, deadlines, trace ids and a prompt-content
fingerprint.  ``serving/replay.py`` feeds that document back through
the real engine (or the scheduler-only fast path) deterministically,
so a production incident becomes a reproducible benchmark and
``obs/capacity.py`` can forecast from recorded traffic shapes.

Fingerprints, not tokens: the span stream never carries prompt
content (and a portable workload should not either).  The engine
hashes each FINGERPRINT_BLOCK-token block of the prompt CHAINED on
the previous block's hash (``prompt_fingerprint``), so two prompts
share a fingerprint prefix exactly when they share a token prefix —
the shared-prefix group structure ROADMAP item 1's prefix cache
keys on survives the round trip.  ``synth_prompt`` regenerates a
deterministic stand-in prompt from the fingerprint (same hash ->
same block), so replayed traffic preserves lengths AND sharing
without ever storing user content.

Clock discipline: arrival offsets come from the submit span's
``arrival`` field (the engine's monotonic clock — exact within a
source) calibrated across fleet sources by the collector's
skew-aligned wall timestamps; deadlines are stored RELATIVE
(milliseconds from arrival), so a replay never inherits the
recording's wall clock.
"""

from __future__ import annotations

import hashlib
import json
import random
import time
from typing import Any, Dict, Iterable, List, Optional

from . import collector as collector_lib
from .schema import SCHEMA_VERSION, validate_workload
from .spans import reconstruct

# prompt tokens per fingerprint block: matches the default KV page
# size, so one fingerprint entry corresponds to one shareable page
FINGERPRINT_BLOCK = 16

# hex digits per fingerprint entry (48 bits — collision-safe for any
# plausible prefix-group population, small enough to ship thousands)
_FP_HEX = 12


def prompt_fingerprint(tokens: Iterable[int],
                       block: int = FINGERPRINT_BLOCK) -> List[str]:
    """Chained per-block prompt hash: entry ``i`` digests block ``i``'s
    tokens AND entry ``i-1``, so fingerprints share a PREFIX exactly
    when the prompts share a token prefix (equal later blocks after a
    divergence do not collide back together)."""
    toks = [int(t) for t in tokens]
    if block < 1:
        raise ValueError(f"block={block} must be >= 1")
    out: List[str] = []
    prev = b""
    for i in range(0, len(toks), block):
        h = hashlib.sha1()
        h.update(prev)
        h.update(",".join(str(t) for t in toks[i:i + block]).encode())
        digest = h.hexdigest()[:_FP_HEX]
        out.append(digest)
        prev = digest.encode()
    return out


def synth_prompt(prompt_len: int, fingerprint: Optional[List[str]],
                 vocab_size: int, seed: int = 0,
                 rid: int = 0) -> List[int]:
    """A deterministic stand-in prompt for one workload entry: each
    fingerprint entry seeds its block's tokens, so equal fingerprint
    prefixes regenerate equal token prefixes (sharing preserved) and
    two replays of the same workload submit identical prompts.  A
    missing fingerprint (pure-scheduler captures) degrades to a
    (seed, rid)-keyed stream — still replay-deterministic, just
    without cross-request sharing.  Tokens land in [1, vocab_size)."""
    if prompt_len < 1:
        raise ValueError(f"prompt_len={prompt_len} must be >= 1")
    if vocab_size < 2:
        raise ValueError(f"vocab_size={vocab_size} must be >= 2")
    fps = [str(f) for f in (fingerprint or [])]
    tokens: List[int] = []
    for b in range(0, prompt_len, FINGERPRINT_BLOCK):
        i = b // FINGERPRINT_BLOCK
        if i < len(fps) and fps[i]:
            key = int(fps[i][:_FP_HEX], 16)
        else:
            h = hashlib.sha1(f"{seed}:{rid}:{i}".encode()).hexdigest()
            key = int(h[:_FP_HEX], 16)
        rng = random.Random(key)
        n = min(FINGERPRINT_BLOCK, prompt_len - b)
        tokens.extend(1 + rng.randrange(vocab_size - 1)
                      for _ in range(n))
    return tokens


def workload_id(requests: List[Dict[str, Any]]) -> str:
    """Content hash over the request SCHEDULE (arrivals, shapes,
    deadlines, fingerprints — not trace ids or outcomes), so two
    captures of identical traffic collide and the replay stream's
    ``replay_of`` stamp is stable across re-captures."""
    canon = [[round(float(r["arrival_s"]), 6), int(r["prompt_len"]),
              int(r["max_new_tokens"]),
              (round(float(r["deadline_ms"]), 3)
               if r.get("deadline_ms") is not None else None),
              list(r.get("fingerprint") or [])]
             for r in requests]
    h = hashlib.sha1(json.dumps(canon,
                                separators=(",", ":")).encode())
    return f"wl-{h.hexdigest()[:12]}"


def _finish(requests: List[Dict[str, Any]], source: str,
            t: Optional[float] = None) -> Dict[str, Any]:
    """Assemble + self-validate the WORKLOAD document from raw request
    entries (sorted, rids renumbered dense in arrival order)."""
    requests = sorted(requests,
                      key=lambda r: (float(r["arrival_s"]),
                                     int(r.get("rid", 0))))
    base = min((float(r["arrival_s"]) for r in requests),
               default=0.0)
    for i, r in enumerate(requests):
        r["rid"] = i
        r["arrival_s"] = round(float(r["arrival_s"]) - base, 6)
    doc = {
        "v": SCHEMA_VERSION,
        "kind": "workload",
        "workload_id": workload_id(requests),
        "source": source,
        "generated_t": time.time() if t is None else t,
        "n_requests": len(requests),
        "duration_s": (round(float(requests[-1]["arrival_s"]), 6)
                       if requests else 0.0),
        "requests": requests,
    }
    errs = validate_workload(doc)
    if errs:
        raise ValueError(f"capture produced an invalid workload: "
                         f"{errs[:5]}")
    return doc


def capture(run_dir: str, align: bool = True) -> Dict[str, Any]:
    """Distill one run dir's span streams into a WORKLOAD document.

    Accepts a single-engine run dir or a fleet layout (a parent whose
    children are ``replica<i>``/``router`` run dirs — the collector's
    discovery).  Failover chains are joined by trace_id: the chain's
    FIRST hop contributes the arrival/prompt shape (the client's
    request, submitted once) and the chain's terminal hop the
    outcome, so a failed-over request captures as ONE entry.  Shed
    and router-narration records are skipped — a workload is the
    ACCEPTED schedule.  Raises ValueError when the streams hold no
    replayable request."""
    res = collector_lib.collect([run_dir], align=align)
    recs = reconstruct(res["rows"])
    lifecycles = [r for r in recs.values()
                  if r.get("submit_t") is not None
                  and not r.get("narration")]
    if not lifecycles:
        raise ValueError(f"no accepted request lifecycles under "
                         f"{run_dir!r}")
    # failover join: one entry per trace chain (untraced records are
    # their own chain)
    chains: Dict[Any, List[Dict[str, Any]]] = {}
    for i, r in enumerate(sorted(lifecycles,
                                 key=lambda r: r["submit_t"])):
        key = r.get("trace_id") or ("", r.get("source"), r["proc"],
                                    r["rid"], i)
        chains.setdefault(key, []).append(r)
    # per-source arrival calibration: the engine's monotonic
    # ``arrival`` field is exact WITHIN a source; across sources the
    # collector's skew-aligned submit_t wall clock places each
    # source's earliest submit on the fleet axis
    per_src: Dict[str, List[Dict[str, Any]]] = {}
    for chain in chains.values():
        first = chain[0]
        per_src.setdefault(str(first.get("source") or ""),
                           []).append(first)
    src_offset: Dict[str, float] = {}
    global_t0 = min(r["submit_t"] for r in lifecycles)
    for src, firsts in per_src.items():
        if all(r.get("arrival") is not None for r in firsts):
            src_offset[src] = (min(r["submit_t"] for r in firsts)
                               - global_t0
                               - min(float(r["arrival"])
                                     for r in firsts))
        else:
            src_offset[src] = None  # fall back to wall submit_t
    requests: List[Dict[str, Any]] = []
    for chain in chains.values():
        first = chain[0]
        last = chain[-1]
        terminal = next((r["terminal"] for r in chain
                         if r.get("terminal")
                         and r["terminal"] != "failed"),
                        last.get("terminal"))
        done = next((r for r in reversed(chain)
                     if r.get("generated") is not None), last)
        src = str(first.get("source") or "")
        off = src_offset[src]
        if off is not None and first.get("arrival") is not None:
            arrival_s = float(first["arrival"]) + off
        else:
            arrival_s = float(first["submit_t"]) - global_t0
        deadline_ms = None
        if first.get("deadline") is not None \
                and first.get("arrival") is not None:
            deadline_ms = max(
                0.0, round((float(first["deadline"])
                            - float(first["arrival"])) * 1e3, 3))
        if not first.get("prompt_len") \
                or not first.get("max_new_tokens"):
            continue
        requests.append({
            "rid": 0,  # renumbered by _finish
            "arrival_s": arrival_s,
            "prompt_len": int(first["prompt_len"]),
            "max_new_tokens": int(first["max_new_tokens"]),
            "output_tokens": (int(done["generated"])
                              if done.get("generated") is not None
                              else None),
            "deadline_ms": deadline_ms,
            "trace_id": first.get("trace_id"),
            "terminal": terminal,
            "fingerprint": list(first.get("fingerprint") or []),
        })
    if not requests:
        raise ValueError(f"no replayable requests under {run_dir!r}")
    return _finish(requests, source=run_dir)


def synthetic_workload(n: int, seed: int = 0, qps: float = 50.0,
                       mean_prompt: int = 24, mean_new: int = 12,
                       vocab_size: int = 64,
                       shared_prefix_frac: float = 0.5,
                       prefix_len: int = FINGERPRINT_BLOCK,
                       deadline_ms: Optional[float] = None
                       ) -> Dict[str, Any]:
    """A seeded synthetic WORKLOAD (the bench's analytic input and the
    round-trip tests' fixture): Poisson-ish arrivals at ``qps``,
    geometric-ish lengths around the means, and a
    ``shared_prefix_frac`` fraction of requests opening with the SAME
    ``prefix_len``-token system prompt — the prefix-group structure a
    capture must preserve."""
    if n < 1:
        raise ValueError(f"n={n} must be >= 1")
    rng = random.Random(seed)
    prefix = [1 + rng.randrange(vocab_size - 1)
              for _ in range(prefix_len)]
    t = 0.0
    requests: List[Dict[str, Any]] = []
    for i in range(n):
        t += rng.expovariate(qps)
        p = max(1, min(4 * mean_prompt,
                       int(rng.expovariate(1.0 / mean_prompt)) + 1))
        m = max(1, min(4 * mean_new,
                       int(rng.expovariate(1.0 / mean_new)) + 1))
        if rng.random() < shared_prefix_frac:
            body = [1 + rng.randrange(vocab_size - 1)
                    for _ in range(max(1, p))]
            tokens = prefix + body
        else:
            tokens = [1 + rng.randrange(vocab_size - 1)
                      for _ in range(p)]
        requests.append({
            "rid": i,
            "arrival_s": round(t, 6),
            "prompt_len": len(tokens),
            "max_new_tokens": m,
            "output_tokens": m,
            "deadline_ms": deadline_ms,
            "trace_id": None,
            "terminal": None,
            "fingerprint": prompt_fingerprint(tokens),
        })
    return _finish(requests, source=f"synthetic:seed={seed}", t=0.0)


def load_workload(path: str) -> Dict[str, Any]:
    """Read + validate a workload file; raises ValueError on schema
    drift (the replay driver and the CLI both refuse bad input loudly
    instead of replaying garbage)."""
    with open(path) as f:
        doc = json.load(f)
    errs = validate_workload(doc, where=path)
    if errs:
        raise ValueError("; ".join(errs[:5]))
    return doc


def write_workload(doc: Dict[str, Any], path: str) -> None:
    with open(path, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")
