"""Live run status over HTTP — stdlib only (http.server, no deps).

Production fleets watch training runs from outside the process; the
chief therefore exposes (``--status_port P``, wired in train/loop.py,
or offline re-serving via ``dtx-obs serve``):

- ``/status``  — JSON assembled from the metrics JSONL *tails* plus
  heartbeat freshness: per-process step/cost/throughput, the chief's
  last window, liveness, run_end when finished;
- ``/metrics`` — the same signals in Prometheus text exposition
  format (``dtx_*`` gauges), scrapeable by any Prometheus/VictoriaM/
  Grafana-agent stack;
- ``/report``  — the full obs/aggregate.py run report, cached by the
  input files' (path, mtime, size) signature so a dashboard poller
  hammering the endpoint recomputes only when the run actually wrote
  something new;
- ``/slo``     — the obs/slo.py multi-window burn-rate verdict over
  the serving span stream (``spans.<proc>.jsonl`` tails), plus
  ``dtx_slo_*`` gauges on ``/metrics`` — the machine-actionable
  "is the service healthy" answer;
- ``/trace?rid=N`` — one request's reconstructed lifecycle (obs/spans
  reconstruct) with its raw span events: submit → blocked/admit →
  prefill → first_token → shared decode ticks → retire;
- ``/fleet``   — the obs/collector.py fleet report over this server's
  ``logs_path`` (a run dir is a one-source fleet; a parent of run
  dirs federates its children): per-source accounting, the fleet-wide
  exactly-once verdict and the federated SLO evaluation, plus
  ``dtx_fleet_*`` gauges on ``/metrics`` (TTL-cached — a scrape never
  re-merges an unchanged fleet).

``POST /generate`` speaks W3C trace context: an incoming
``traceparent`` header's trace id rides every span the request emits,
and the response carries a ``traceparent`` (plus ``trace_id`` in the
body) either way — callers can stitch the serving edge into their own
traces, and ``dtx-obs trace --export chrome`` shows the full chain.

With a decode engine attached (``StatusServer(logs_path, engine=...)``
— the ``dtx-serve`` front door, serving/cli.py) the same server also
exposes:

- ``POST /generate`` — ``{"prompt": [token ids], "max_new_tokens": N,
  "temperature": t}`` -> ``{"tokens": [...], "latency_ms": ...}``;
  the handler thread submits into the engine's continuous-batching
  scheduler and blocks on ITS request only, so concurrent requests
  share decode steps;
- request-level latency percentiles as ``dtx_generate_*`` gauges on
  ``/metrics`` (p50/p99 latency, p50/p99 time-to-first-token,
  inflight/queue depth, tok/s, KV page occupancy — the
  obs/schema.SERVING_STATS surface).

The reader side only ever *reads* files the run appends to, so the
server adds zero overhead to the training loop and the identical code
serves a finished run's directory offline. Tail reads are bounded
(the last ``TAIL_BYTES`` of each stream), so /status stays O(1) as
the run grows.
"""

from __future__ import annotations

import json
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional

from . import heartbeat as hb_lib

TAIL_BYTES = 256 * 1024
# a heartbeat older than this marks the process (and the run) stale
STALE_HEARTBEAT_S = 120.0
# /report cache lifetime: long enough to shrug off a hammering
# poller, short enough that wall-clock fields (heartbeat_age_s) keep
# aging visibly for a HUNG run whose files stopped changing
REPORT_CACHE_TTL_S = 15.0


class TTLCache:
    """The ONE cache for the recompute-heavy endpoints (/report,
    /fleet, /explain — each was growing its own lock + timestamp +
    signature triple).  ``get(compute)`` returns the cached value
    while it is younger than ``ttl_s``; pass ``sig`` (any comparable
    snapshot of the inputs, e.g. file stat triples) to ALSO
    invalidate the moment the inputs change — the /report semantics.
    ``None`` is a legitimate cached value (a fleet with no streams),
    so freshness is tracked explicitly, not by value."""

    def __init__(self, ttl_s: float = REPORT_CACHE_TTL_S):
        self.ttl_s = float(ttl_s)
        self._lock = threading.Lock()
        self._sig: Any = None
        self._value: Any = None
        self._t = -1e18
        self._filled = False

    def get(self, compute, sig: Any = None) -> Any:
        now = time.monotonic()
        with self._lock:
            if (self._filled and now - self._t < self.ttl_s
                    and (sig is None or sig == self._sig)):
                return self._value
        value = compute()
        with self._lock:
            self._sig = sig
            self._value = value
            self._t = now
            self._filled = True
        return value


def tail_rows(path: str, max_bytes: int = TAIL_BYTES) -> List[Dict[str, Any]]:
    """Parse the last ``max_bytes`` of a JSONL file. When the read
    starts mid-file the first (possibly torn) line is dropped."""
    try:
        size = os.path.getsize(path)
        with open(path, "rb") as f:
            if size > max_bytes:
                f.seek(size - max_bytes)
            chunk = f.read().decode("utf-8", errors="replace")
    except OSError:
        return []
    lines = chunk.splitlines()
    if size > max_bytes and lines:
        lines = lines[1:]
    rows = []
    for line in lines:
        line = line.strip()
        if not line:
            continue
        try:
            rows.append(json.loads(line))
        except ValueError:
            continue
    return rows


def collect_status(logs_path: str,
                   now: Optional[float] = None) -> Dict[str, Any]:
    """The /status document: metrics tails + heartbeat freshness."""
    from .aggregate import metrics_files

    now = time.time() if now is None else now
    beats = hb_lib.read_heartbeats(logs_path)
    procs: Dict[str, Dict[str, Any]] = {}
    run_end = None
    last_window = None
    anomalies = 0
    chief: Optional[int] = None
    for pid, path in metrics_files(logs_path):
        rows = tail_rows(path)
        windows = [r for r in rows if r.get("kind") == "window"]
        events = [r for r in rows if r.get("kind") == "event"]
        anomalies += sum(1 for r in events if r.get("event") == "anomaly")
        w = windows[-1] if windows else {}
        hb = beats.get(pid)
        procs[str(pid)] = {
            "step": w.get("step"),
            "epoch": w.get("epoch"),
            "cost": w.get("cost"),
            "examples_per_sec": w.get("examples_per_sec"),
            "tokens_per_sec": w.get("tokens_per_sec"),
            "mfu": w.get("mfu"),
            "step_time_p50_ms": w.get("step_time_p50_ms"),
            "rss_bytes": w.get("rss_bytes"),
            "t": w.get("t"),
            "heartbeat_step": hb[0] if hb else None,
            "heartbeat_age_s": (round(max(0.0, now - hb[1]), 3)
                                if hb else None),
        }
        if chief is None or pid < chief:
            chief = pid
            last_window = w or None
            run_end = next((r for r in reversed(events)
                            if r.get("event") == "run_end"), None)
    ages = [p["heartbeat_age_s"] for p in procs.values()
            if p["heartbeat_age_s"] is not None]
    complete = run_end is not None
    return {
        "t": now,
        "logs_path": os.path.abspath(logs_path),
        "procs": procs,
        "proc_count": len(procs),
        "last_window": last_window,
        "run_end": run_end,
        "run_complete": complete,
        "live": bool(procs) and not complete
        and (min(ages) < STALE_HEARTBEAT_S if ages else True),
        "anomalies": anomalies,
        "flight_dumps": len([
            n for n in (os.listdir(os.path.join(logs_path, "flight"))
                        if os.path.isdir(os.path.join(logs_path,
                                                      "flight")) else [])
            if n.endswith(".json") and n != "report.json"]),
    }


def prometheus_text(status: Dict[str, Any],
                    serving: Optional[Dict[str, Any]] = None,
                    slo: Optional[Dict[str, Any]] = None,
                    fleet: Optional[Dict[str, Any]] = None,
                    waterfall: Optional[Dict[str, Any]] = None,
                    router: Optional[Dict[str, Any]] = None) -> str:
    """Render a /status document in Prometheus text exposition format
    (version 0.0.4). Gauges only — everything here is a point-in-time
    read of the run's own counters. ``serving``: a
    DecodeEngine.stats() document (schema.SERVING_STATS) appended as
    the ``dtx_generate_*`` request-latency gauges.  ``slo``: an
    obs/slo.evaluate document appended as the ``dtx_slo_*`` burn-rate
    gauges (per-SLO per-window burn rate, breach flags, observed
    p99).  ``fleet``: an obs/collector.fleet_report document appended
    as the ``dtx_fleet_*`` gauges (merged-timeline accounting, the
    exactly-once and federated-identity verdicts, per-source skew and
    burn).  ``waterfall``: an obs/waterfall.summarize document
    appended as the ``dtx_waterfall_*`` latency-attribution gauges
    (per-segment p50/p99 and the sum-to-wall residual).  ``router``:
    a serving/router.Router.stats() document appended as the
    ``dtx_router_*`` fleet gauges (fleet counters plus per-replica
    health / breaker / load, labelled ``replica``)."""
    out: List[str] = []

    def fmt(v) -> str:
        return format(float(v), ".10g")

    def gauge(name, help_text, samples):
        """samples: [(label_dict_or_None, value)] — None values are
        skipped (absent ≠ zero)."""
        kept = [(lb, v) for lb, v in samples
                if isinstance(v, (int, float))
                and not isinstance(v, bool)]
        if not kept:
            return
        out.append(f"# HELP {name} {help_text}")
        out.append(f"# TYPE {name} gauge")
        for labels, v in kept:
            if labels:
                lab = ",".join(f'{k}="{val}"'
                               for k, val in sorted(labels.items()))
                out.append(f"{name}{{{lab}}} {fmt(v)}")
            else:
                out.append(f"{name} {fmt(v)}")

    procs = status.get("procs") or {}

    def per_proc(key):
        return [({"proc": pid}, p.get(key))
                for pid, p in sorted(procs.items(), key=lambda kv:
                                     int(kv[0]))]

    gauge("dtx_up", "1 while the run looks live (fresh heartbeat, no "
          "run_end)", [(None, 1 if status.get("live") else 0)])
    gauge("dtx_run_complete", "1 once the run_end event was written",
          [(None, 1 if status.get("run_complete") else 0)])
    gauge("dtx_procs", "processes with a metrics stream",
          [(None, status.get("proc_count"))])
    gauge("dtx_step", "latest window step per process",
          per_proc("step"))
    gauge("dtx_cost", "latest window cost per process",
          per_proc("cost"))
    gauge("dtx_examples_per_sec", "latest window throughput",
          per_proc("examples_per_sec"))
    gauge("dtx_tokens_per_sec", "latest window token throughput",
          per_proc("tokens_per_sec"))
    gauge("dtx_mfu", "latest window model FLOPs utilization",
          per_proc("mfu"))
    gauge("dtx_step_time_p50_ms", "latest window median step time",
          per_proc("step_time_p50_ms"))
    gauge("dtx_rss_bytes", "latest resident set size per process",
          per_proc("rss_bytes"))
    gauge("dtx_heartbeat_age_seconds", "seconds since each process's "
          "last heartbeat", per_proc("heartbeat_age_s"))
    gauge("dtx_anomalies_total", "anomaly events in the metrics tails",
          [(None, status.get("anomalies"))])
    gauge("dtx_flight_dumps_total", "flight dumps on disk",
          [(None, status.get("flight_dumps"))])
    run_end = status.get("run_end") or {}
    gauge("dtx_total_time_seconds", "final run wall time (run_end)",
          [(None, run_end.get("total_time_s"))])
    gauge("dtx_test_accuracy", "final test accuracy (run_end)",
          [(None, run_end.get("test_accuracy"))])
    if serving:
        gauge("dtx_generate_requests_total", "requests accepted by "
              "the decode engine", [(None, serving.get("requests_total"))])
        gauge("dtx_generate_completed_total", "requests completed",
              [(None, serving.get("completed_total"))])
        gauge("dtx_generate_inflight", "requests in the live decode "
              "batch", [(None, serving.get("inflight"))])
        gauge("dtx_generate_queued", "requests waiting for admission",
              [(None, serving.get("queued"))])
        gauge("dtx_generate_latency_p50_ms", "median request latency",
              [(None, serving.get("latency_p50_ms"))])
        gauge("dtx_generate_latency_p99_ms", "p99 request latency",
              [(None, serving.get("latency_p99_ms"))])
        gauge("dtx_generate_ttft_p50_ms", "median time to first token",
              [(None, serving.get("ttft_p50_ms"))])
        gauge("dtx_generate_ttft_p99_ms", "p99 time to first token",
              [(None, serving.get("ttft_p99_ms"))])
        gauge("dtx_generate_tokens_total", "tokens generated",
              [(None, serving.get("tokens_generated_total"))])
        gauge("dtx_generate_tokens_per_sec", "aggregate decode "
              "throughput", [(None, serving.get("tokens_per_sec"))])
        gauge("dtx_generate_page_occupancy", "KV cache page occupancy "
              "fraction", [(None, serving.get("page_occupancy_frac"))])
        gauge("dtx_generate_decode_ticks_total", "decode engine ticks "
              "executed", [(None, serving.get("decode_ticks_total"))])
        # fail-open serving (PR 15): typed terminals + admission
        # control + supervision counters
        gauge("dtx_generate_shed_total", "requests refused by the "
              "bounded queue (typed 503)",
              [(None, serving.get("shed_total"))])
        gauge("dtx_generate_timeout_total", "requests retired by "
              "deadline expiry or client cancel (typed timeout)",
              [(None, serving.get("timeout_total"))])
        gauge("dtx_generate_failed_total", "requests failed after the "
              "supervised retry budget (typed failed)",
              [(None, serving.get("failed_total"))])
        gauge("dtx_generate_requeued_total", "requests re-queued by a "
              "supervised engine restart",
              [(None, serving.get("requeued_total"))])
        gauge("dtx_generate_engine_restarts_total", "supervised "
              "engine-loop restarts",
              [(None, serving.get("engine_restarts_total"))])
        gauge("dtx_generate_queue_peak", "peak pending-queue depth "
              "observed (bound: queue_limit, 0 = unbounded)",
              [(None, serving.get("queue_peak"))])
        gauge("dtx_generate_brownout_active", "1 while the brownout "
              "admission clamp is active",
              [(None, serving.get("brownout_active"))])
        gauge("dtx_generate_brownout_clamped_total", "admissions with "
              "a brownout-clamped token budget",
              [(None, serving.get("brownout_clamped_total"))])
    if slo:
        gauge("dtx_slo_requests", "terminal requests the SLO windows "
              "slide over", [(None, slo.get("requests"))])
        docs = slo.get("slos") or []
        gauge("dtx_slo_burn_rate", "error-budget burn rate per SLO "
              "and window (1.0 = burning exactly at budget)",
              [({"slo": d.get("name"), "window": label},
                (d.get("windows") or {}).get(label, {}).get("burn_rate"))
               for d in docs for label in ("fast", "slow")])
        gauge("dtx_slo_breach", "1 while the SLO burns past its "
              "threshold on BOTH windows",
              [({"slo": d.get("name")}, 1 if d.get("breach") else 0)
               for d in docs])
        gauge("dtx_slo_observed_p99_ms", "observed p99 of the SLO's "
              "metric over its slow window",
              [({"slo": d.get("name")}, d.get("observed_p99_ms"))
               for d in docs])
        gauge("dtx_slo_shed_rate", "shed fraction of terminal "
              "requests over the slow window (load-shedding "
              "pressure; deliberately not an SLO breach input)",
              [(None, (slo.get("shed") or {}).get("rate"))])
    if fleet:
        sources = fleet.get("sources") or []
        gauge("dtx_fleet_sources", "run dirs merged into the fleet "
              "timeline", [(None, len(sources))])
        gauge("dtx_fleet_rows", "rows on the merged fleet timeline",
              [(None, fleet.get("rows"))])
        gauge("dtx_fleet_requests", "request lifecycles reconstructed "
              "fleet-wide", [(None, fleet.get("requests"))])
        gauge("dtx_fleet_exactly_once", "1 while every fleet request "
              "has exactly one typed terminal",
              [(None, 1 if fleet.get("exactly_once") else 0)])
        gauge("dtx_fleet_restarts_total", "engine restarts on the "
              "merged timeline", [(None, fleet.get("restarts"))])
        gauge("dtx_fleet_source_skew_seconds", "clock-skew offset the "
              "collector aligned away per source",
              [({"source": s.get("source")}, s.get("skew_s"))
               for s in sources])
        fslo = fleet.get("slo") or {}
        if fslo:
            gauge("dtx_fleet_identity_holds", "1 while the federated "
                  "burn identity (fleet == request-weighted per-source "
                  "combination) holds exactly",
                  [(None, 1 if (fslo.get("identity") or {}).get("holds")
                    else 0)])
            fdocs = (fslo.get("fleet") or {}).get("slos") or []
            gauge("dtx_fleet_burn_rate", "fleet-wide error-budget burn "
                  "rate per SLO and window",
                  [({"slo": d.get("name"), "window": label},
                    (d.get("windows") or {}).get(label, {})
                    .get("burn_rate"))
                   for d in fdocs for label in ("fast", "slow")])
            gauge("dtx_fleet_source_burn_rate", "per-source slow-window "
                  "burn rate per SLO",
                  [({"source": src, "slo": d.get("name")},
                    (d.get("windows") or {}).get("slow", {})
                    .get("burn_rate"))
                   for src, ps in sorted(
                       (fslo.get("per_source") or {}).items())
                   for d in (ps.get("slos") or [])])
    if waterfall:
        segs = waterfall.get("segments") or {}
        gauge("dtx_waterfall_requests", "requests with a derived "
              "latency waterfall",
              [(None, waterfall.get("requests"))])
        gauge("dtx_waterfall_segment_p50_ms", "median per-request "
              "time in each waterfall segment",
              [({"segment": name}, st.get("p50_ms"))
               for name, st in sorted(segs.items())])
        gauge("dtx_waterfall_segment_p99_ms", "p99 per-request time "
              "in each waterfall segment",
              [({"segment": name}, st.get("p99_ms"))
               for name, st in sorted(segs.items())])
        gauge("dtx_waterfall_residual_frac_max", "largest |wall - "
              "segment sum| fraction across requests (the sum-to-wall "
              "honesty bound; ~0 by construction)",
              [(None, waterfall.get("max_residual_frac"))])
    if router:
        # fleet router (PR 18, serving/router.Router.stats())
        per_replica = router.get("per_replica") or []
        gauge("dtx_router_replicas", "replicas behind the fleet "
              "router", [(None, router.get("replicas"))])
        gauge("dtx_router_replicas_healthy", "replicas whose circuit "
              "breaker is closed",
              [(None, router.get("replicas_healthy"))])
        gauge("dtx_router_draining", "1 while the router is draining "
              "(SIGTERM: no new admissions)",
              [(None, router.get("draining"))])
        gauge("dtx_router_requests_total", "requests the router "
              "accepted and placed",
              [(None, router.get("requests_total"))])
        gauge("dtx_router_completed_total", "requests that reached a "
              "clean result through the router",
              [(None, router.get("completed_total"))])
        gauge("dtx_router_failovers_total", "cross-engine failover "
              "hops (a request re-submitted to another replica)",
              [(None, router.get("failovers_total"))])
        gauge("dtx_router_fleet_failed_total", "requests failed after "
              "the fleet-level retry budget (typed failed fleet-wide)",
              [(None, router.get("fleet_failed_total"))])
        gauge("dtx_router_shed_total", "requests the router refused "
              "(draining, every replica shed, or breakers open)",
              [(None, router.get("shed_total"))])
        gauge("dtx_router_drain_cancelled_total", "queued requests "
              "typed-cancelled by a drain",
              [(None, router.get("drain_cancelled_total"))])
        gauge("dtx_router_replica_health", "per-replica health score "
              "in [0, 1] (serving/health.health_score)",
              [({"replica": r.get("name")}, r.get("health"))
               for r in per_replica])
        gauge("dtx_router_replica_load", "per-replica queued + "
              "in-flight load at the last probe",
              [({"replica": r.get("name")}, r.get("load"))
               for r in per_replica])
        gauge("dtx_router_breaker_open", "1 while the replica's "
              "circuit breaker is not closed (open or half-open)",
              [({"replica": r.get("name")},
                0 if (r.get("breaker") or {}).get("state") == "closed"
                else 1) for r in per_replica])
        gauge("dtx_router_breaker_trips_total", "lifetime circuit-"
              "breaker trips per replica",
              [({"replica": r.get("name")},
                (r.get("breaker") or {}).get("trips"))
               for r in per_replica])
    return "\n".join(out) + "\n"


# the /generate handler's ceiling wait; a request carrying its own
# deadline waits only deadline + grace (the engine retires it with a
# typed timeout terminal AT the deadline — the 504 is engine-truth,
# not just the client giving up).  A handler-side expiry with no
# engine deadline cancels the request so engine-side state frees.
GENERATE_TIMEOUT_S = 600.0
GENERATE_DEADLINE_GRACE_S = 5.0


class StatusServer:
    """Threaded HTTP status server over a ``logs_path``. ``start()``
    binds and serves from a daemon thread (port 0 = ephemeral;
    ``.port`` is the bound port); ``close()`` shuts down cleanly —
    the train loop calls it from its ``finally``, so a crash never
    leaks the socket. Never raises out of start(): a taken port logs
    a NOTE and the run proceeds unobserved (the server must not kill
    the run it reports on).

    ``engine``: a serving/engine.DecodeEngine (or any object with
    ``submit``/``result``/``stats``) — enables ``POST /generate`` and
    the ``dtx_generate_*`` gauges (the dtx-serve front door).

    ``slos``: obs/slo.SLOSpec list evaluated by ``/slo`` and the
    ``dtx_slo_*`` gauges (None = obs/slo.DEFAULT_SLOS)."""

    def __init__(self, logs_path: str, engine=None, slos=None,
                 cache_ttl_s: Optional[float] = None):
        self.logs_path = logs_path
        self.engine = engine
        self.slos = slos
        self.port: Optional[int] = None
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        ttl = (REPORT_CACHE_TTL_S if cache_ttl_s is None
               else float(cache_ttl_s))
        # /report cache keyed by the input files' stat signature: the
        # aggregate is recomputed only when the run wrote something
        # new, so a dashboard poller cannot stall the chief.  A short
        # TTL rides along because the report carries WALL-CLOCK-derived
        # fields (heartbeat_age_s): a HUNG run stops touching its
        # files, and a signature-only cache would pin the ages at
        # their last fresh-looking values forever — the exact stall
        # signal the field exists to expose.
        self._report_cache = TTLCache(ttl)
        # /fleet and /explain caches: the collector re-reads every
        # span stream end to end (rotated segments included) and the
        # waterfall derivation walks every request's boundaries, so a
        # scrape must not recompute an unchanged fleet.  TTL-only —
        # neither has wall-clock fields, and a stat signature across
        # N run dirs would cost nearly as much as the work it guards.
        self._fleet_cache = TTLCache(ttl)
        self._explain_cache = TTLCache(ttl)

    def _report_signature(self) -> tuple:
        """(path, mtime_ns, size) for every file /report reads —
        metrics streams, heartbeats, flight dumps and the restart
        timeline.  Size rides along so an append inside one mtime
        granule still misses."""
        import glob as glob_lib

        sig = []
        for pattern in ("metrics.*.jsonl", "heartbeat.*",
                        "restarts.jsonl",
                        os.path.join("flight", "*.json")):
            for path in glob_lib.glob(os.path.join(self.logs_path,
                                                   pattern)):
                try:
                    st = os.stat(path)
                except OSError:
                    continue
                sig.append((path, st.st_mtime_ns, st.st_size))
        return tuple(sorted(sig))

    def report_json(self) -> bytes:
        """The /report payload, recomputed when the signature of the
        underlying files changed OR the cached copy aged past the
        cache TTL (heartbeat ages must keep growing for a hung
        run)."""
        from . import aggregate as agg_lib

        return self._report_cache.get(
            lambda: json.dumps(agg_lib.aggregate(self.logs_path))
            .encode(),
            sig=self._report_signature())

    def _span_rows(self):
        """The /slo and /trace data source.  With a live engine whose
        recorder is attached (dtx-serve --trace_spans) this is the
        recorder's in-memory ring — no file re-read per request;
        offline it is the bounded span-stream tails across processes,
        time-ordered (same O(tail) discipline as /status)."""
        rec = getattr(self.engine, "recorder", None) \
            if self.engine is not None else None
        if rec is not None:
            return rec.snapshot()
        from .spans import span_files

        rows = []
        for _pid, path in span_files(self.logs_path):
            rows.extend(r for r in tail_rows(path)
                        if r.get("kind") == "span")
        rows.sort(key=lambda r: (r.get("t") or 0.0))
        return rows

    def slo_doc(self, rows=None) -> Dict[str, Any]:
        from . import slo as slo_lib

        if rows is None:
            rows = self._span_rows()
        return slo_lib.evaluate(slo_lib.records_from_spans(rows),
                                specs=self.slos)

    def fleet_doc(self) -> Optional[Dict[str, Any]]:
        """The /fleet payload: obs/collector.fleet_report over this
        server's ``logs_path`` (a run dir is a one-source fleet; a
        parent of run dirs federates its children).  None when no
        span/metrics streams exist underneath.  TTL-cached."""
        from . import collector as col_lib

        def compute() -> Optional[Dict[str, Any]]:
            if col_lib.discover_sources([self.logs_path]):
                return col_lib.fleet_report([self.logs_path],
                                            specs=self.slos)
            return None

        return self._fleet_cache.get(compute)

    def explain_docs(self) -> List[Dict[str, Any]]:
        """The /explain data: every reconstructible per-request
        waterfall over the current span rows (engine ring when live,
        span tails offline).  TTL-cached unfiltered; the rid/trace
        query filters are applied per request — filtering is cheap,
        the derivation is not."""
        from . import waterfall as wf_lib

        return self._explain_cache.get(
            lambda: wf_lib.waterfalls(self._span_rows()))

    def start(self, port: int, host: str = "") -> Optional[int]:
        logs_path = self.logs_path
        engine = self.engine
        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # stdout belongs to the run
                pass

            def _send(self, code: int, body: bytes,
                      ctype: str = "application/json",
                      headers: Optional[Dict[str, str]] = None) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                path, _, query = self.path.partition("?")
                path = path.rstrip("/") or "/"
                try:
                    if path in ("/", "/status"):
                        doc = collect_status(logs_path)
                        if engine is not None:
                            doc["serving"] = engine.stats()
                        self._send(200, json.dumps(doc).encode())
                    elif path == "/metrics":
                        from . import waterfall as wf_lib

                        spans = server._span_rows()
                        falls = server.explain_docs()
                        text = prometheus_text(
                            collect_status(logs_path),
                            serving=(engine.stats()
                                     if engine is not None else None),
                            slo=(server.slo_doc(spans) if spans
                                 else None),
                            fleet=server.fleet_doc(),
                            waterfall=(wf_lib.summarize(falls)
                                       if falls else None))
                        self._send(200, text.encode(),
                                   "text/plain; version=0.0.4")
                    elif path == "/report":
                        self._send(200, server.report_json())
                    elif path == "/slo":
                        self._send(200, json.dumps(
                            server.slo_doc()).encode())
                    elif path == "/trace":
                        from urllib.parse import parse_qs

                        from .spans import trace_record

                        rid = (parse_qs(query).get("rid")
                               or [None])[0]
                        try:
                            rid = int(rid)
                        except (TypeError, ValueError):
                            self._send(400, json.dumps(
                                {"error": "/trace needs ?rid=N (an "
                                          "integer request id)"}).encode())
                            return
                        doc = trace_record(server._span_rows(), rid)
                        if doc is None:
                            self._send(404, json.dumps(
                                {"error": f"rid {rid} not in the span "
                                          f"stream tails"}).encode())
                            return
                        self._send(200, json.dumps(doc).encode())
                    elif path == "/fleet":
                        doc = server.fleet_doc()
                        if doc is None:
                            self._send(404, json.dumps(
                                {"error": "no span/metrics streams "
                                          "under this logs_path"}
                            ).encode())
                            return
                        self._send(200, json.dumps(doc).encode())
                    elif path == "/explain":
                        from urllib.parse import parse_qs

                        from . import waterfall as wf_lib

                        q = parse_qs(query)
                        docs = server.explain_docs()
                        rid_q = (q.get("rid") or [None])[0]
                        if rid_q is not None:
                            try:
                                rid_q = int(rid_q)
                            except ValueError:
                                self._send(400, json.dumps(
                                    {"error": "?rid=N must be an "
                                              "integer"}).encode())
                                return
                            docs = [d for d in docs
                                    if d["rid"] == rid_q]
                        trace_q = (q.get("trace") or [None])[0]
                        if trace_q is not None:
                            docs = [d for d in docs
                                    if d.get("trace_id") == trace_q]
                        self._send(200, json.dumps(
                            {"summary": wf_lib.summarize(docs),
                             "waterfalls": docs}).encode())
                    else:
                        self._send(404, json.dumps(
                            {"error": f"unknown path {path!r}",
                             "endpoints": ["/status", "/metrics",
                                           "/report", "/slo", "/trace",
                                           "/fleet", "/explain"]
                             + (["/generate"] if engine is not None
                                else [])}).encode())
                except Exception as e:  # a bad read must not kill serving
                    self._send(500, json.dumps(
                        {"error": f"{type(e).__name__}: {e}"}).encode())

            def do_POST(self):
                path = self.path.split("?", 1)[0].rstrip("/") or "/"
                if path != "/generate":
                    self._send(404, json.dumps(
                        {"error": f"unknown POST path {path!r}"}).encode())
                    return
                if engine is None:
                    self._send(503, json.dumps(
                        {"error": "no decode engine attached (start "
                                  "via dtx-serve)"}).encode())
                    return
                from ..serving.admission import ShedError

                try:
                    n = int(self.headers.get("Content-Length") or 0)
                    req = json.loads(self.rfile.read(n) or b"{}")
                    prompt = req.get("prompt")
                    if not isinstance(prompt, list):
                        raise ValueError(
                            "'prompt' must be a list of token ids")
                    deadline_ms = req.get("deadline_ms")
                    if deadline_ms is not None:
                        deadline_ms = float(deadline_ms)
                        if deadline_ms < 0:
                            raise ValueError("'deadline_ms' must be "
                                             ">= 0")
                    # W3C trace context: a malformed header degrades
                    # to a fresh trace inside submit, never a 400
                    traceparent = self.headers.get("traceparent")
                    rid = engine.submit(
                        prompt,
                        int(req.get("max_new_tokens", 16)),
                        temperature=float(req.get("temperature", 0.0)),
                        deadline_ms=deadline_ms,
                        traceparent=traceparent)
                except ShedError as e:
                    # typed load shedding: the bounded queue is full —
                    # overloaded, not broken; Retry-After tells the
                    # client when one queue slot should have drained
                    # (integer-seconds CEIL via the one shared helper
                    # — rounding DOWN invited the retry back early)
                    from ..serving.admission import retry_after_header

                    self.send_response(503)
                    body = json.dumps(
                        {"error": str(e), "status": "shed",
                         "retry_after_s": e.retry_after_s}).encode()
                    self.send_header(
                        "Retry-After",
                        str(retry_after_header(e.retry_after_s)))
                    self.send_header("Content-Type",
                                     "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                except (ValueError, TypeError, KeyError) as e:
                    self._send(400, json.dumps(
                        {"error": f"{type(e).__name__}: {e}"}).encode())
                    return
                except RuntimeError as e:
                    # the engine loop died (submit refuses after a
                    # failure): the server is up, generation is not
                    self._send(503, json.dumps(
                        {"error": f"{type(e).__name__}: {e}"}).encode())
                    return
                # the response traceparent: the request's trace id
                # (propagated or freshly minted by submit) with a new
                # span id naming the serving edge — read BEFORE the
                # wait, while the engine still holds the rid's context
                resp_headers: Optional[Dict[str, str]] = None
                ctx_of = getattr(engine, "trace_context", None)
                ctx = ctx_of(rid) if ctx_of is not None else None
                if ctx is not None:
                    from .spans import format_traceparent, new_span_id

                    resp_headers = {"traceparent": format_traceparent(
                        ctx[0], new_span_id())}
                # the handler wait honors the REQUEST's deadline (its
                # own field, or the engine default): the engine
                # retires it at the deadline with a typed timeout
                # terminal, so the wait only needs a grace window on
                # top — never the full 600s ceiling against a request
                # that contracted to finish in two seconds
                if deadline_ms is None:
                    deadline_ms = float(getattr(engine, "deadline_ms",
                                                0.0) or 0.0)
                wait_s = GENERATE_TIMEOUT_S
                if deadline_ms and deadline_ms > 0:
                    wait_s = min(wait_s, deadline_ms / 1e3
                                 + GENERATE_DEADLINE_GRACE_S)
                try:
                    res = engine.result(rid, timeout=wait_s)
                    if res is None:
                        # handler-side expiry with no engine-side
                        # terminal yet: cancel so engine state frees
                        # (pages, queue slot) instead of decoding for
                        # a client that already got its 504
                        cancel = getattr(engine, "cancel", None)
                        if cancel is not None:
                            cancel(rid)
                        self._send(504, json.dumps(
                            {"error": "generation timed out",
                             "status": "timeout",
                             "rid": rid}).encode(),
                            headers=resp_headers)
                        return
                    if res.get("status") == "timeout":
                        # the engine's typed deadline/cancel terminal
                        self._send(504, json.dumps(res).encode(),
                                   headers=resp_headers)
                        return
                    if "error" in res:
                        # typed "failed" (retry budget spent) or the
                        # engine loop died while THIS request was in
                        # flight
                        self._send(500, json.dumps(res).encode(),
                                   headers=resp_headers)
                        return
                    self._send(200, json.dumps(res).encode(),
                               headers=resp_headers)
                except Exception as e:
                    self._send(500, json.dumps(
                        {"error": f"{type(e).__name__}: {e}"}).encode())

        try:
            self._httpd = ThreadingHTTPServer((host, int(port)), Handler)
        except OSError as e:
            print(f"NOTE: status server failed to bind port {port}: {e}")
            return None
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="dtx-status",
            daemon=True)
        self._thread.start()
        return self.port

    def close(self) -> None:
        httpd, self._httpd = self._httpd, None
        if httpd is not None:
            httpd.shutdown()
            httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
