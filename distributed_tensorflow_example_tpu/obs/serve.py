"""Live run status over HTTP — stdlib only (http.server, no deps).

Production fleets watch training runs from outside the process; the
chief therefore exposes (``--status_port P``, wired in train/loop.py,
or offline re-serving via ``dtx-obs serve``):

- ``/status``  — JSON assembled from the metrics JSONL *tails* plus
  heartbeat freshness: per-process step/cost/throughput, the chief's
  last window, liveness, run_end when finished;
- ``/metrics`` — the same signals in Prometheus text exposition
  format (``dtx_*`` gauges), scrapeable by any Prometheus/VictoriaM/
  Grafana-agent stack;
- ``/report``  — the full obs/aggregate.py run report (computed per
  request — cheap at these log sizes, and always current).

With a decode engine attached (``StatusServer(logs_path, engine=...)``
— the ``dtx-serve`` front door, serving/cli.py) the same server also
exposes:

- ``POST /generate`` — ``{"prompt": [token ids], "max_new_tokens": N,
  "temperature": t}`` -> ``{"tokens": [...], "latency_ms": ...}``;
  the handler thread submits into the engine's continuous-batching
  scheduler and blocks on ITS request only, so concurrent requests
  share decode steps;
- request-level latency percentiles as ``dtx_generate_*`` gauges on
  ``/metrics`` (p50/p99 latency, time-to-first-token, inflight/queue
  depth, tok/s, KV page occupancy — the obs/schema.SERVING_STATS
  surface).

The reader side only ever *reads* files the run appends to, so the
server adds zero overhead to the training loop and the identical code
serves a finished run's directory offline. Tail reads are bounded
(the last ``TAIL_BYTES`` of each stream), so /status stays O(1) as
the run grows.
"""

from __future__ import annotations

import json
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional

from . import heartbeat as hb_lib

TAIL_BYTES = 256 * 1024
# a heartbeat older than this marks the process (and the run) stale
STALE_HEARTBEAT_S = 120.0


def tail_rows(path: str, max_bytes: int = TAIL_BYTES) -> List[Dict[str, Any]]:
    """Parse the last ``max_bytes`` of a JSONL file. When the read
    starts mid-file the first (possibly torn) line is dropped."""
    try:
        size = os.path.getsize(path)
        with open(path, "rb") as f:
            if size > max_bytes:
                f.seek(size - max_bytes)
            chunk = f.read().decode("utf-8", errors="replace")
    except OSError:
        return []
    lines = chunk.splitlines()
    if size > max_bytes and lines:
        lines = lines[1:]
    rows = []
    for line in lines:
        line = line.strip()
        if not line:
            continue
        try:
            rows.append(json.loads(line))
        except ValueError:
            continue
    return rows


def collect_status(logs_path: str,
                   now: Optional[float] = None) -> Dict[str, Any]:
    """The /status document: metrics tails + heartbeat freshness."""
    from .aggregate import metrics_files

    now = time.time() if now is None else now
    beats = hb_lib.read_heartbeats(logs_path)
    procs: Dict[str, Dict[str, Any]] = {}
    run_end = None
    last_window = None
    anomalies = 0
    chief: Optional[int] = None
    for pid, path in metrics_files(logs_path):
        rows = tail_rows(path)
        windows = [r for r in rows if r.get("kind") == "window"]
        events = [r for r in rows if r.get("kind") == "event"]
        anomalies += sum(1 for r in events if r.get("event") == "anomaly")
        w = windows[-1] if windows else {}
        hb = beats.get(pid)
        procs[str(pid)] = {
            "step": w.get("step"),
            "epoch": w.get("epoch"),
            "cost": w.get("cost"),
            "examples_per_sec": w.get("examples_per_sec"),
            "tokens_per_sec": w.get("tokens_per_sec"),
            "mfu": w.get("mfu"),
            "step_time_p50_ms": w.get("step_time_p50_ms"),
            "rss_bytes": w.get("rss_bytes"),
            "t": w.get("t"),
            "heartbeat_step": hb[0] if hb else None,
            "heartbeat_age_s": (round(max(0.0, now - hb[1]), 3)
                                if hb else None),
        }
        if chief is None or pid < chief:
            chief = pid
            last_window = w or None
            run_end = next((r for r in reversed(events)
                            if r.get("event") == "run_end"), None)
    ages = [p["heartbeat_age_s"] for p in procs.values()
            if p["heartbeat_age_s"] is not None]
    complete = run_end is not None
    return {
        "t": now,
        "logs_path": os.path.abspath(logs_path),
        "procs": procs,
        "proc_count": len(procs),
        "last_window": last_window,
        "run_end": run_end,
        "run_complete": complete,
        "live": bool(procs) and not complete
        and (min(ages) < STALE_HEARTBEAT_S if ages else True),
        "anomalies": anomalies,
        "flight_dumps": len([
            n for n in (os.listdir(os.path.join(logs_path, "flight"))
                        if os.path.isdir(os.path.join(logs_path,
                                                      "flight")) else [])
            if n.endswith(".json") and n != "report.json"]),
    }


def prometheus_text(status: Dict[str, Any],
                    serving: Optional[Dict[str, Any]] = None) -> str:
    """Render a /status document in Prometheus text exposition format
    (version 0.0.4). Gauges only — everything here is a point-in-time
    read of the run's own counters. ``serving``: a
    DecodeEngine.stats() document (schema.SERVING_STATS) appended as
    the ``dtx_generate_*`` request-latency gauges."""
    out: List[str] = []

    def fmt(v) -> str:
        return format(float(v), ".10g")

    def gauge(name, help_text, samples):
        """samples: [(label_dict_or_None, value)] — None values are
        skipped (absent ≠ zero)."""
        kept = [(lb, v) for lb, v in samples
                if isinstance(v, (int, float))
                and not isinstance(v, bool)]
        if not kept:
            return
        out.append(f"# HELP {name} {help_text}")
        out.append(f"# TYPE {name} gauge")
        for labels, v in kept:
            if labels:
                lab = ",".join(f'{k}="{val}"'
                               for k, val in sorted(labels.items()))
                out.append(f"{name}{{{lab}}} {fmt(v)}")
            else:
                out.append(f"{name} {fmt(v)}")

    procs = status.get("procs") or {}

    def per_proc(key):
        return [({"proc": pid}, p.get(key))
                for pid, p in sorted(procs.items(), key=lambda kv:
                                     int(kv[0]))]

    gauge("dtx_up", "1 while the run looks live (fresh heartbeat, no "
          "run_end)", [(None, 1 if status.get("live") else 0)])
    gauge("dtx_run_complete", "1 once the run_end event was written",
          [(None, 1 if status.get("run_complete") else 0)])
    gauge("dtx_procs", "processes with a metrics stream",
          [(None, status.get("proc_count"))])
    gauge("dtx_step", "latest window step per process",
          per_proc("step"))
    gauge("dtx_cost", "latest window cost per process",
          per_proc("cost"))
    gauge("dtx_examples_per_sec", "latest window throughput",
          per_proc("examples_per_sec"))
    gauge("dtx_tokens_per_sec", "latest window token throughput",
          per_proc("tokens_per_sec"))
    gauge("dtx_mfu", "latest window model FLOPs utilization",
          per_proc("mfu"))
    gauge("dtx_step_time_p50_ms", "latest window median step time",
          per_proc("step_time_p50_ms"))
    gauge("dtx_rss_bytes", "latest resident set size per process",
          per_proc("rss_bytes"))
    gauge("dtx_heartbeat_age_seconds", "seconds since each process's "
          "last heartbeat", per_proc("heartbeat_age_s"))
    gauge("dtx_anomalies_total", "anomaly events in the metrics tails",
          [(None, status.get("anomalies"))])
    gauge("dtx_flight_dumps_total", "flight dumps on disk",
          [(None, status.get("flight_dumps"))])
    run_end = status.get("run_end") or {}
    gauge("dtx_total_time_seconds", "final run wall time (run_end)",
          [(None, run_end.get("total_time_s"))])
    gauge("dtx_test_accuracy", "final test accuracy (run_end)",
          [(None, run_end.get("test_accuracy"))])
    if serving:
        gauge("dtx_generate_requests_total", "requests accepted by "
              "the decode engine", [(None, serving.get("requests_total"))])
        gauge("dtx_generate_completed_total", "requests completed",
              [(None, serving.get("completed_total"))])
        gauge("dtx_generate_inflight", "requests in the live decode "
              "batch", [(None, serving.get("inflight"))])
        gauge("dtx_generate_queued", "requests waiting for admission",
              [(None, serving.get("queued"))])
        gauge("dtx_generate_latency_p50_ms", "median request latency",
              [(None, serving.get("latency_p50_ms"))])
        gauge("dtx_generate_latency_p99_ms", "p99 request latency",
              [(None, serving.get("latency_p99_ms"))])
        gauge("dtx_generate_ttft_p50_ms", "median time to first token",
              [(None, serving.get("ttft_p50_ms"))])
        gauge("dtx_generate_tokens_total", "tokens generated",
              [(None, serving.get("tokens_generated_total"))])
        gauge("dtx_generate_tokens_per_sec", "aggregate decode "
              "throughput", [(None, serving.get("tokens_per_sec"))])
        gauge("dtx_generate_page_occupancy", "KV cache page occupancy "
              "fraction", [(None, serving.get("page_occupancy_frac"))])
        gauge("dtx_generate_decode_ticks_total", "decode engine ticks "
              "executed", [(None, serving.get("decode_ticks_total"))])
    return "\n".join(out) + "\n"


# a /generate request that cannot finish in this window is reported
# as a 504 timeout (the engine keeps decoding it; the CLIENT gave up)
GENERATE_TIMEOUT_S = 600.0


class StatusServer:
    """Threaded HTTP status server over a ``logs_path``. ``start()``
    binds and serves from a daemon thread (port 0 = ephemeral;
    ``.port`` is the bound port); ``close()`` shuts down cleanly —
    the train loop calls it from its ``finally``, so a crash never
    leaks the socket. Never raises out of start(): a taken port logs
    a NOTE and the run proceeds unobserved (the server must not kill
    the run it reports on).

    ``engine``: a serving/engine.DecodeEngine (or any object with
    ``submit``/``result``/``stats``) — enables ``POST /generate`` and
    the ``dtx_generate_*`` gauges (the dtx-serve front door)."""

    def __init__(self, logs_path: str, engine=None):
        self.logs_path = logs_path
        self.engine = engine
        self.port: Optional[int] = None
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    def start(self, port: int, host: str = "") -> Optional[int]:
        logs_path = self.logs_path
        engine = self.engine

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # stdout belongs to the run
                pass

            def _send(self, code: int, body: bytes,
                      ctype: str = "application/json") -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                path = self.path.split("?", 1)[0].rstrip("/") or "/"
                try:
                    if path in ("/", "/status"):
                        doc = collect_status(logs_path)
                        if engine is not None:
                            doc["serving"] = engine.stats()
                        self._send(200, json.dumps(doc).encode())
                    elif path == "/metrics":
                        text = prometheus_text(
                            collect_status(logs_path),
                            serving=(engine.stats()
                                     if engine is not None else None))
                        self._send(200, text.encode(),
                                   "text/plain; version=0.0.4")
                    elif path == "/report":
                        from .aggregate import aggregate

                        self._send(200, json.dumps(
                            aggregate(logs_path)).encode())
                    else:
                        self._send(404, json.dumps(
                            {"error": f"unknown path {path!r}",
                             "endpoints": ["/status", "/metrics",
                                           "/report"]
                             + (["/generate"] if engine is not None
                                else [])}).encode())
                except Exception as e:  # a bad read must not kill serving
                    self._send(500, json.dumps(
                        {"error": f"{type(e).__name__}: {e}"}).encode())

            def do_POST(self):
                path = self.path.split("?", 1)[0].rstrip("/") or "/"
                if path != "/generate":
                    self._send(404, json.dumps(
                        {"error": f"unknown POST path {path!r}"}).encode())
                    return
                if engine is None:
                    self._send(503, json.dumps(
                        {"error": "no decode engine attached (start "
                                  "via dtx-serve)"}).encode())
                    return
                try:
                    n = int(self.headers.get("Content-Length") or 0)
                    req = json.loads(self.rfile.read(n) or b"{}")
                    prompt = req.get("prompt")
                    if not isinstance(prompt, list):
                        raise ValueError(
                            "'prompt' must be a list of token ids")
                    rid = engine.submit(
                        prompt,
                        int(req.get("max_new_tokens", 16)),
                        temperature=float(req.get("temperature", 0.0)))
                except (ValueError, TypeError, KeyError) as e:
                    self._send(400, json.dumps(
                        {"error": f"{type(e).__name__}: {e}"}).encode())
                    return
                except RuntimeError as e:
                    # the engine loop died (submit refuses after a
                    # failure): the server is up, generation is not
                    self._send(503, json.dumps(
                        {"error": f"{type(e).__name__}: {e}"}).encode())
                    return
                try:
                    res = engine.result(rid, timeout=GENERATE_TIMEOUT_S)
                    if res is None:
                        self._send(504, json.dumps(
                            {"error": "generation timed out",
                             "rid": rid}).encode())
                        return
                    if "error" in res:
                        # the engine loop died while THIS request was
                        # in flight; its event was failed immediately
                        self._send(500, json.dumps(res).encode())
                        return
                    self._send(200, json.dumps(res).encode())
                except Exception as e:
                    self._send(500, json.dumps(
                        {"error": f"{type(e).__name__}: {e}"}).encode())

        try:
            self._httpd = ThreadingHTTPServer((host, int(port)), Handler)
        except OSError as e:
            print(f"NOTE: status server failed to bind port {port}: {e}")
            return None
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="dtx-status",
            daemon=True)
        self._thread.start()
        return self.port

    def close(self) -> None:
        httpd, self._httpd = self._httpd, None
        if httpd is not None:
            httpd.shutdown()
            httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
