"""Windowed profiler capture (`--profile_steps`) + trace scopes.

The whole-run ``--profile`` trace has two production problems: it
skews the numbers it reports (the profiler's own overhead rides every
step) and a multi-hour run produces a trace too large to open. The
MegaScale-style answer (arXiv:2402.15627) is *programmatic windowed
capture*: start the trace right before an exact step, stop it a fixed
number of steps later, and annotate the phases inside so the timeline
lines up with the metrics split (obs/metrics.py buckets).

``WindowedTracer`` owns the whole lifecycle:

- ``--profile_steps START:COUNT`` (``parse_profile_steps``) captures
  exactly the steps ``[START, START+COUNT)`` on the host path; the
  fast path traces at its program granularity (``on_range``) — the
  epochs/run overlapping the window;
- the legacy whole-run ``--profile`` mode rides the same object
  (``begin_run``), which is what makes it exception-safe: the loop's
  ``finally`` calls ``stop()``, so a mid-run crash always terminates
  the trace instead of leaving a corrupt/unterminated capture;
- ``step_annotation``/``annotate`` wrap ``jax.profiler``'s
  ``StepTraceAnnotation``/``TraceAnnotation`` with the SAME scope
  names as the metrics buckets (``data_wait``, ``h2d``, ``dispatch``,
  ``device_wait``, ``eval``, ``checkpoint``) and collapse to
  ``nullcontext`` when tracing is off — zero steady-state cost;
- INSIDE the compiled step the transformer forward carries
  ``jax.named_scope`` regions — ``ln`` (every LayerNorm, fused or
  reference), ``moe_dispatch`` (router + scatter/gather slotting +
  combine) and ``moe_expert`` (the grouped expert matmuls) — which
  land in the op metadata of the device timeline, so a captured
  window attributes device time to the exact ops the moe_wide bench
  breakdown (``moe_dispatch_ms``/``moe_expert_ms``) times standalone;
- ``--profile_port`` starts the on-demand profiler server
  (``jax.profiler.start_server``) so TensorBoard/perfetto can attach
  to a live run without any flag planned in advance.

The profiler module is injected (``profiler=``, default
``jax.profiler``) so the windowing contract — start/stop called
exactly once per window, annotations nest — is testable without a
real trace backend (tests/test_forensics.py).
"""

from __future__ import annotations

import contextlib
import os
from typing import Optional, Tuple

from .buckets import TRACE_SCOPES


def parse_profile_steps(s: str) -> Optional[Tuple[int, int]]:
    """``"START:COUNT"`` -> ``(start, count)``; ``""``/None -> None.
    start is the 0-based global step index of the first traced step."""
    if not s:
        return None
    parts = str(s).split(":")
    if len(parts) != 2:
        raise ValueError(
            f"profile_steps={s!r}: expected 'START:COUNT' (e.g. '500:20')")
    try:
        start, count = int(parts[0]), int(parts[1])
    except ValueError:
        raise ValueError(
            f"profile_steps={s!r}: START and COUNT must be integers")
    if start < 0:
        raise ValueError(f"profile_steps={s!r}: START must be >= 0")
    if count < 1:
        raise ValueError(f"profile_steps={s!r}: COUNT must be >= 1")
    return start, count


class WindowedTracer:
    """Programmatic jax.profiler capture around exact steps.

    One instance per process; ``enabled=False`` (non-chief, or no
    profiling flag) makes every method a no-op returning
    ``nullcontext`` — the off-path costs one attribute check.
    """

    def __init__(self, logs_path: str, window: Optional[Tuple[int, int]] = None,
                 whole_run: bool = False, enabled: bool = True,
                 profiler=None):
        self.trace_dir = os.path.join(logs_path, "profile")
        self.window = window
        self.whole_run = bool(whole_run) and window is None
        self.enabled = bool(enabled) and (window is not None or whole_run)
        self._profiler = profiler
        self._active = False
        self._finished = False
        self._server = None
        self.windows_captured = 0

    def _prof(self):
        if self._profiler is None:
            import jax.profiler

            self._profiler = jax.profiler
        return self._profiler

    # -- capture lifecycle ------------------------------------------------

    def _start(self) -> None:
        try:
            os.makedirs(self.trace_dir, exist_ok=True)
            self._prof().start_trace(self.trace_dir)
            self._active = True
        except Exception as e:  # tracing must never take down training
            print(f"NOTE: profiler start_trace failed: {e}")
            self.enabled = False

    def _stop(self) -> None:
        try:
            self._prof().stop_trace()
            self.windows_captured += 1
        except Exception as e:
            print(f"NOTE: profiler stop_trace failed: {e}")
        self._active = False

    def begin_run(self) -> None:
        """Whole-run (--profile) mode: start now. Windowed mode waits
        for its step."""
        if self.enabled and self.whole_run and not self._active:
            self._start()

    def boundary(self, step: int) -> bool:
        """True when ``on_step(step)`` will open or close the window.
        A host loop running an async dispatch queue MUST drain it
        before crossing a boundary (block on the newest in-flight
        result): the host runs up to the queue depth ahead of the
        device, so an unaligned start/stop would capture the device
        execution of EARLIER steps, not the requested window. Two
        syncs per run, only at the window edges — zero cost
        otherwise."""
        if (not self.enabled or self._finished or self.whole_run
                or self.window is None):
            return False
        start, count = self.window
        if self._active:
            return step >= start + count
        return start <= step < start + count

    def on_step(self, step: int) -> None:
        """Host-path hook, called once per step (0-based global id of
        the step ABOUT to run): opens the window at START, closes it
        before step START+COUNT dispatches — exactly COUNT steps."""
        if not self.enabled or self._finished or self.whole_run:
            return
        start, count = self.window
        if self._active:
            if step >= start + count:
                self._stop()
                self._finished = True
        elif start <= step < start + count:
            self._start()

    def on_range(self, lo: int, hi: int) -> None:
        """Fast-path hook: the program about to dispatch covers steps
        ``[lo, hi)``. The scan paths compile whole epochs/runs into one
        executable, so capture is at that granularity: start when the
        program overlaps the window, stop once past it."""
        if not self.enabled or self._finished or self.whole_run:
            return
        start, count = self.window
        end = start + count
        if self._active:
            if lo >= end:
                self._stop()
                self._finished = True
        elif lo < end and hi > start:
            self._start()

    def stop(self) -> None:
        """Final stop: idempotent and exception-safe — the loop's
        ``finally`` calls this so a crash can never leave an
        unterminated trace behind."""
        if self._active:
            self._stop()
        self._finished = True

    @property
    def active(self) -> bool:
        return self._active

    # -- annotations ------------------------------------------------------

    def step_annotation(self, step: int):
        """``StepTraceAnnotation`` scope for one train step (the unit
        TensorBoard's trace viewer groups by). Only while a capture is
        OPEN — a 50k-step run with a 20-step window must not pay the
        TraceMe construct/enter/exit on the other 49 980 steps."""
        if not self._active:
            return contextlib.nullcontext()
        return self._prof().StepTraceAnnotation("train", step_num=step)

    def annotate(self, name: str):
        """Named ``TraceAnnotation`` scope; names come from the shared
        registry (obs/buckets.py TRACE_SCOPES = the metrics buckets +
        eval/checkpoint) so the trace timeline and the JSONL split
        agree. nullcontext whenever no capture is open (see
        step_annotation)."""
        if name not in TRACE_SCOPES:
            # validated BEFORE the active check so a drifted scope
            # name fails in any test run, not only under --profile
            raise ValueError(f"unknown trace scope {name!r}: expected "
                             f"one of {TRACE_SCOPES}")
        if not self._active:
            return contextlib.nullcontext()
        return self._prof().TraceAnnotation(name)

    # -- on-demand server -------------------------------------------------

    def start_server(self, port: int):
        """``--profile_port``: profiler server for on-demand capture
        (TensorBoard 'Capture profile' / `jax.profiler.trace` attach).
        Independent of windowed/whole-run capture."""
        if not port:
            return None
        try:
            self._server = self._prof().start_server(int(port))
        except Exception as e:
            print(f"NOTE: profiler server on port {port} failed: {e}")
        return self._server
