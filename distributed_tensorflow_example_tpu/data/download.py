"""MNIST IDX download with mirror fallback and SHA-256 verification.

Reference parity: ``input_data.read_data_sets('MNIST_data', one_hot=True)``
(/root/reference/example.py:47-48) downloads the four canonical IDX
files into ``MNIST_data/`` when absent. This module is the equivalent
capability, hardened the way a modern loader should be:

- a **mirror list** (the original yann.lecun.com host frequently 403s;
  the S3/GCS mirrors are the de-facto canonical sources now), tried in
  order per file;
- **SHA-256 verification** of every downloaded archive against the
  published digests — a truncated or tampered file is discarded and the
  next mirror is tried;
- **resume-safe writes**: downloads land in a same-directory temp file
  and are atomically ``os.replace``d into place only after the digest
  checks out, so a killed process never leaves a corrupt file where the
  loader would trust it.

Offline behavior: every failure path raises ``DownloadError`` listing
what was tried; callers (data.mnist.load_datasets) surface that next to
the drop-the-files-in-place instructions. Tests drive this module
against a local ``http.server`` fixture (tests/test_download.py), so
the capability is fully exercised without network egress.
"""

from __future__ import annotations

import hashlib
import os
import urllib.error
import urllib.request

# Canonical gzip archives and their published SHA-256 digests.
MNIST_FILES = {
    "train-images-idx3-ubyte.gz":
        "440fcabf73cc546fa21475e81ea370265605f56be210a4024d2ca8f203523609",
    "train-labels-idx1-ubyte.gz":
        "3552534a0a558bbed6aed32b30c495cca23d567ec52cac8be1a0730e8010255c",
    "t10k-images-idx3-ubyte.gz":
        "8d422c7b0a1c1c79245a5bcf07fe86e33eeafee792b84584aec276f5a2dbc4e6",
    "t10k-labels-idx1-ubyte.gz":
        "f7ae60f92e00ec6debd23a6088c31dbd2371eca3ffa0defaefb259924204aec6",
}

MIRRORS = (
    "https://ossci-datasets.s3.amazonaws.com/mnist/",
    "https://storage.googleapis.com/cvdf-datasets/mnist/",
    "http://yann.lecun.com/exdb/mnist/",
)

_CHUNK = 1 << 16


class DownloadError(RuntimeError):
    pass


def _reap_stale_temps(dest: str, max_age_s: float = 3600.0) -> None:
    """Remove abandoned ``<dest>.tmp-<pid>`` files from killed runs.
    Age-gated so a concurrent process's in-flight download (writing its
    own pid-suffixed temp right now) is left alone."""
    import glob
    import time

    for tmp in glob.glob(dest + ".tmp-*"):
        try:
            if time.time() - os.path.getmtime(tmp) > max_age_s:
                os.remove(tmp)
        except OSError:
            pass  # raced with its owner; harmless


def sha256_file(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        while True:
            chunk = f.read(_CHUNK)
            if not chunk:
                break
            h.update(chunk)
    return h.hexdigest()


def _fetch_one(url: str, dest: str, sha256: str | None, timeout: float) -> None:
    """Stream url -> dest via a same-directory temp file; verify digest
    before the atomic rename. Raises on any failure, leaving no partial
    file at ``dest``."""
    tmp = f"{dest}.tmp-{os.getpid()}"
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp, \
                open(tmp, "wb") as out:
            while True:
                chunk = resp.read(_CHUNK)
                if not chunk:
                    break
                out.write(chunk)
        if sha256 is not None:
            got = sha256_file(tmp)
            if got != sha256:
                raise DownloadError(
                    f"{url}: SHA-256 mismatch (got {got}, want {sha256})"
                )
        os.replace(tmp, dest)  # atomic: readers never see a partial file
    finally:
        if os.path.exists(tmp):
            os.remove(tmp)


def download_file(
    name: str,
    data_dir: str,
    mirrors=None,
    sha256: str | None = None,
    timeout: float = 30.0,
) -> str:
    """Fetch ``name`` into ``data_dir``, trying each mirror in order.
    Returns the local path; no-op if a file with the right digest is
    already in place."""
    if mirrors is None:
        mirrors = MIRRORS  # resolved at call time (tests patch the module)
    os.makedirs(data_dir, exist_ok=True)
    dest = os.path.join(data_dir, name)
    _reap_stale_temps(dest)  # before the early-return: a completed file
    # can coexist with another process's abandoned temp
    if os.path.exists(dest) and (sha256 is None or sha256_file(dest) == sha256):
        return dest
    errors = []
    for base in mirrors:
        url = base.rstrip("/") + "/" + name  # tolerate no trailing slash
        # visible per-attempt line: on silently-dropping networks each
        # attempt can run to its timeout, and this must not look like a
        # hang (read_data_sets printed progress too)
        print(f"Downloading {url} ...", flush=True)
        try:
            _fetch_one(url, dest, sha256, timeout)
            return dest
        except (urllib.error.URLError, OSError, DownloadError) as e:
            errors.append(f"  {url}: {e}")
    raise DownloadError(
        f"could not download {name!r}; tried:\n" + "\n".join(errors)
    )


def download_mnist(
    data_dir: str = "MNIST_data", mirrors=None, timeout: float = 10.0
) -> None:
    """Fetch all four MNIST archives (the read_data_sets behavior,
    example.py:47-48), verifying each against its published SHA-256."""
    for name, digest in MNIST_FILES.items():  # module global: patchable
        download_file(name, data_dir, mirrors=mirrors, sha256=digest,
                      timeout=timeout)
