from .mnist import Dataset, DataSplit, load_datasets, EpochIterator

__all__ = ["Dataset", "DataSplit", "load_datasets", "EpochIterator"]
