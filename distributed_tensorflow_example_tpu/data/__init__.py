from .mnist import Dataset, DataSplit, load_datasets, EpochIterator
from .prefetch import DevicePrefetcher, EpochPrefetcher, Prefetcher

__all__ = ["Dataset", "DataSplit", "load_datasets", "EpochIterator",
           "Prefetcher", "EpochPrefetcher", "DevicePrefetcher"]
