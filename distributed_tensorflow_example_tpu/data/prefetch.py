"""Background batch prefetcher for the host-fed loop.

Reference parity: the reference's input pipeline is fully synchronous —
``next_batch`` gathers on the host, then ``sess.run`` blocks
(/root/reference/example.py:157-162); batch prep and training never
overlap.

Here a daemon thread runs one epoch ahead of the consumer through a
small bounded queue. The actual gather runs in native C++ via ctypes
(``native.gather_batch``), which releases the GIL — so prefetch
genuinely overlaps with the train loop's dispatch work. Used by the
host path (async local-SGD mode, multi-process); the default fast path
keeps the whole dataset in HBM and needs no host feeding at all.
"""

from __future__ import annotations

import queue
import threading
from typing import Iterator, Tuple

import numpy as np

_END = object()


class Prefetcher:
    """Wraps an iterable of batches; yields the same batches, produced
    by a background thread with ``depth`` batches of lookahead."""

    def __init__(self, iterable, depth: int = 2):
        self._q: "queue.Queue" = queue.Queue(maxsize=max(1, depth))
        self._err: list = []
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._produce, args=(iterable,), daemon=True
        )
        self._thread.start()

    def _produce(self, iterable) -> None:
        try:
            for item in iterable:
                # bounded put that notices close(): never blocks forever
                # holding the iterator's buffers if the consumer bails out
                while not self._stop.is_set():
                    try:
                        self._q.put(item, timeout=0.1)
                        break
                    except queue.Full:
                        continue
                if self._stop.is_set():
                    return
        except BaseException as e:  # surface producer errors to the consumer
            self._err.append(e)
        finally:
            # deliver the sentinel unless closed (a Full queue must not
            # lose it, or the consumer would block forever)
            while not self._stop.is_set():
                try:
                    self._q.put(_END, timeout=0.1)
                    break
                except queue.Full:
                    continue

    def close(self) -> None:
        """Stop the producer and release its buffers (safe to call
        multiple times; called by consumers on early exit)."""
        self._stop.set()
        try:
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass

    def __iter__(self) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
        try:
            while True:
                item = self._q.get()
                if item is _END:
                    if self._err:
                        raise self._err[0]
                    return
                yield item
        finally:
            self.close()
