"""Background batch prefetchers for the host-fed loop.

Reference parity: the reference's input pipeline is fully synchronous —
``next_batch`` gathers on the host, then ``sess.run`` blocks
(/root/reference/example.py:157-162); batch prep, host-to-device
transfer and training never overlap.

Three stages, composable (the host path uses all three; the default
fast path keeps the whole dataset in HBM and needs no host feeding):

- ``Prefetcher``: a daemon thread runs ahead of the consumer through a
  small bounded queue. The actual gather runs in native C++ via ctypes
  (``native.gather_batch``), which releases the GIL — so prefetch
  genuinely overlaps with the train loop's dispatch work.
- ``EpochPrefetcher``: the persistent epoch-aware variant — ONE
  producer thread spans every epoch of the run (epoch-keyed rewind via
  :meth:`EpochPrefetcher.epoch`), so epoch boundaries pay no cold
  thread/queue spin-up and the next epoch's gather overlaps the
  between-epoch host work (validation eval, checkpoints).
- ``DevicePrefetcher``: the device-side stage (``--device_prefetch``)
  — commits upcoming host batches to their step layout (sharded jax
  Arrays) up to ``depth`` batches ahead of consumption. jax transfers
  are asynchronous, so the H2D copy of batch N+k overlaps the device
  execution of batch N instead of blocking dispatch — the
  ``flax.jax_utils.prefetch_to_device`` lineage every production JAX
  input stack uses.
"""

from __future__ import annotations

import collections
import queue
import threading
from typing import Callable, Iterator, Optional, Tuple

import numpy as np

_END = object()


class _EpochEnd:
    """Queue marker: the producer finished epoch ``epoch``."""

    __slots__ = ("epoch",)

    def __init__(self, epoch: int):
        self.epoch = epoch


class Prefetcher:
    """Wraps an iterable of batches; yields the same batches, produced
    by a background thread with ``depth`` batches of lookahead."""

    def __init__(self, iterable, depth: int = 2):
        self._q: "queue.Queue" = queue.Queue(maxsize=max(1, depth))
        self._err: list = []
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._produce, args=(iterable,), daemon=True
        )
        self._thread.start()

    def _produce(self, iterable) -> None:
        try:
            for item in iterable:
                # bounded put that notices close(): never blocks forever
                # holding the iterator's buffers if the consumer bails out
                while not self._stop.is_set():
                    try:
                        self._q.put(item, timeout=0.1)
                        break
                    except queue.Full:
                        continue
                if self._stop.is_set():
                    return
        except BaseException as e:  # surface producer errors to the consumer
            self._err.append(e)
        finally:
            # deliver the sentinel unless closed (a Full queue must not
            # lose it, or the consumer would block forever)
            while not self._stop.is_set():
                try:
                    self._q.put(_END, timeout=0.1)
                    break
                except queue.Full:
                    continue

    def close(self) -> None:
        """Stop the producer and release its buffers (safe to call
        multiple times; called by consumers on early exit)."""
        self._stop.set()
        try:
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass

    @property
    def closed(self) -> bool:
        return self._stop.is_set()

    def _check_open(self) -> None:
        """A closed prefetcher has no producer and a drained queue (no
        sentinel left): iterating it would block forever on a ``get``
        that can never complete — fail fast instead."""
        if self._stop.is_set():
            raise RuntimeError(
                f"{type(self).__name__} is closed; create a new one "
                f"instead of iterating a closed prefetcher")

    def _get(self):
        """Blocking queue read that keeps noticing ``close()``: the
        sentinel may already be gone by the time the consumer blocks."""
        while True:
            self._check_open()
            try:
                return self._q.get(timeout=0.1)
            except queue.Empty:
                continue

    def __iter__(self) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
        # eager check: iter() on a closed prefetcher raises at the
        # call, not at the first next() (generators run lazily)
        self._check_open()
        return self._iter()

    def _iter(self) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
        try:
            while True:
                item = self._get()
                if item is _END:
                    if self._err:
                        raise self._err[0]
                    return
                yield item
        finally:
            self.close()


class EpochPrefetcher(Prefetcher):
    """One persistent producer across every epoch of a run.

    ``epoch_fn(e)`` must return epoch ``e``'s batch iterator (e.g.
    ``EpochIterator.epoch``). The single producer thread runs the
    epochs of ``epoch_indices`` back to back, separated by epoch-end
    markers — while the consumer evaluates/checkpoints between epochs
    the producer is already gathering the next epoch's batches, and no
    epoch pays a cold thread/queue spin-up.

    :meth:`epoch` is the epoch-keyed rewind: it yields exactly epoch
    ``e``'s batches, first dropping whatever the consumer left behind
    of earlier epochs. The stream is forward-only — epochs can only be
    consumed in the order produced (re-requesting a finished epoch
    raises), which is all the train loop needs and what keeps this one
    bounded queue instead of a cache.
    """

    def __init__(self, epoch_fn: Callable[[int], Iterator],
                 epoch_indices, depth: int = 2):
        self._indices = list(epoch_indices)
        self._pos = 0   # consumer cursor into _indices (the epoch at
                        # the queue head, barring in-flight markers)
        self._next_allowed = 0  # hand-out cursor: epochs at earlier
                                # indices were already handed to a
                                # consumer (possibly partially drained)
        super().__init__(self._chain(epoch_fn, self._indices), depth)

    def __iter__(self):
        raise TypeError(
            "EpochPrefetcher is consumed per epoch — use .epoch(e); "
            "direct iteration would interleave internal epoch markers "
            "with batches")

    @staticmethod
    def _chain(epoch_fn, indices):
        for e in indices:
            yield from epoch_fn(e)
            yield _EpochEnd(e)

    def _advance(self, finished_epoch: int) -> None:
        self._pos = self._indices.index(finished_epoch) + 1

    def epoch(self, e: int) -> Iterator:
        """Yield epoch ``e``'s batches (epoch-keyed rewind)."""
        if e not in self._indices:
            raise RuntimeError(
                f"epoch {e} is not in this prefetcher's sequence "
                f"{self._indices!r}")
        # forward-only against the HAND-OUT cursor, not just the queue
        # position: re-requesting an epoch that was already handed out
        # (even if only partially drained) would silently yield a
        # truncated epoch, never 'exactly epoch e's batches'
        if self._indices.index(e) < self._next_allowed:
            raise RuntimeError(
                f"epoch {e} was already consumed (or started) — the "
                f"prefetch stream is forward-only")
        self._next_allowed = self._indices.index(e) + 1
        return self._epoch_iter(e)

    def _epoch_iter(self, e: int) -> Iterator:
        # fast-forward: drop earlier epochs' leftovers (a consumer that
        # abandoned an epoch mid-way rewinds to the next epoch's start)
        while self._pos < len(self._indices) and self._indices[self._pos] != e:
            item = self._get()
            if item is _END:
                if self._err:
                    raise self._err[0]
                raise RuntimeError(f"stream ended before epoch {e}")
            if isinstance(item, _EpochEnd):
                self._advance(item.epoch)
        while True:
            item = self._get()
            if item is _END:
                if self._err:
                    raise self._err[0]
                raise RuntimeError(f"stream ended inside epoch {e}")
            if isinstance(item, _EpochEnd):
                self._advance(item.epoch)
                return
            yield item


class DevicePrefetcher:
    """Bounded depth-K device-commit pipeline — the H2D overlap stage.

    Pulls host batches from a source iterator and immediately commits
    each to its step layout via ``commit(x, y) -> (x_dev, y_dev)``
    (``jax.device_put`` / ``make_array_from_process_local_data`` /
    ``make_array_from_callback`` with the sharding from
    ``parallel.step.batch_layout``), keeping up to ``depth`` committed
    batches buffered ahead of the consumer. jax transfers are
    asynchronous — ``commit`` returns as soon as the copies are
    enqueued — so the H2D transfer of batch N+k proceeds while the
    device executes batch N, and the train loop dispatches on arrays
    that are already (becoming) device-resident instead of paying the
    copy on the critical path.

    Pure python, no thread of its own: the commit call is cheap host
    work (the transfer engine does the copying), and running it inline
    on the consumer thread commits batches in exactly the order the
    source yields them — which is what keeps the device-prefetched
    path bit-exact with the synchronous-commit path.

    One instance persists across epochs: :meth:`rewind` re-arms the
    same object on the next epoch's source, dropping any buffered
    batches from the old source (the arrays just release) and clearing
    a pending source error. :meth:`close` releases the buffer and
    makes further iteration raise — early-exit safe. A source error
    surfaces after the already-committed batches, mirroring
    ``Prefetcher``'s ordering.
    """

    def __init__(self, commit: Callable, depth: int = 2, source=None):
        if depth < 1:
            raise ValueError(f"depth={depth} must be >= 1")
        self._commit = commit
        self._depth = depth
        self._buf: collections.deque = collections.deque()
        self._it = iter(source) if source is not None else None
        self._err: Optional[BaseException] = None
        self._done = source is None
        self._closed = False

    @property
    def depth(self) -> int:
        return self._depth

    def rewind(self, source) -> "DevicePrefetcher":
        """Re-arm on a new source (the next epoch); returns self."""
        if self._closed:
            raise RuntimeError("DevicePrefetcher is closed")
        self._buf.clear()
        self._it = iter(source)
        self._err = None
        self._done = False
        return self

    def close(self) -> None:
        """Drop buffered device batches and refuse further iteration
        (idempotent; called by consumers on early exit)."""
        self._closed = True
        self._buf.clear()
        self._it = None
        self._done = True

    @property
    def closed(self) -> bool:
        return self._closed

    def _fill(self) -> None:
        while not self._done and len(self._buf) < self._depth:
            try:
                item = next(self._it)
            except StopIteration:
                self._done = True
                return
            except Exception as e:  # surfaced after buffered items.
                # NOT BaseException: _fill runs on the consumer thread
                # (unlike Prefetcher._produce), so a KeyboardInterrupt
                # must stop the run now, not resurface `depth` steps
                # later disguised as a data-pipeline failure
                self._err = e
                self._done = True
                return
            self._buf.append(self._commit(*item))

    def __iter__(self) -> Iterator:
        # eager check, like Prefetcher: iter() on a closed instance
        # raises at the call, not at the first next()
        if self._closed:
            raise RuntimeError("DevicePrefetcher is closed")
        return self._iter()

    def _iter(self) -> Iterator:
        self._fill()
        while True:
            if self._closed:
                raise RuntimeError("DevicePrefetcher is closed")
            if not self._buf:
                if self._err is not None:
                    err, self._err = self._err, None
                    raise err
                return
            item = self._buf.popleft()
            yield item
            self._fill()
