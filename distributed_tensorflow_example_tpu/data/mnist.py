"""MNIST input pipeline.

Reference parity: the reference calls
``input_data.read_data_sets('MNIST_data', one_hot=True)``
(/root/reference/example.py:47-48) from the long-gone
``tensorflow.examples.tutorials.mnist`` package, then iterates
``mnist.train.next_batch(batch_size)`` (example.py:157) over
``mnist.train.num_examples`` (= 55 000; example.py:153) and evaluates on
``mnist.test.images/labels`` (10 000 examples; example.py:177).

This module is a from-scratch replacement:

- **IDX parser** for the four standard MNIST files (``*-images-idx3-ubyte``
  / ``*-labels-idx1-ubyte``, optionally ``.gz``), validated against the
  IDX magic numbers (0x00000803 images / 0x00000801 labels);
- the TF tutorial's exact split semantics: the 60 000-example train file
  becomes 55 000 train + 5 000 validation;
- a **deterministic synthetic MNIST** fallback for air-gapped machines
  (no network egress): procedurally rendered digit glyphs with jitter and
  noise, same shapes/dtypes/split sizes, so every code path (train, eval,
  bench) runs end-to-end offline;
- an **epoch iterator** mirroring ``next_batch`` (shuffled each epoch,
  seeded) with optional per-process sharding. Note the reference does
  *not* shard: each of its 3 async workers consumes all 20 full epochs
  (example.py:150-157); ``shard=False`` reproduces that, ``shard=True``
  is the sync-DP equivalent (SURVEY.md §7 hard part 3).

Native path: when the compiled helper library is available
(``native/libdtx.so``), IDX decoding and batch gather run in C++
(see ``distributed_tensorflow_example_tpu.native``).
"""

from __future__ import annotations

import dataclasses
import gzip
import os
import struct
from typing import Iterator, Tuple

import numpy as np

IMAGE_MAGIC = 0x00000803
LABEL_MAGIC = 0x00000801

TRAIN_IMAGES = "train-images-idx3-ubyte"
TRAIN_LABELS = "train-labels-idx1-ubyte"
TEST_IMAGES = "t10k-images-idx3-ubyte"
TEST_LABELS = "t10k-labels-idx1-ubyte"

VALIDATION_SIZE = 5000  # TF tutorial split: 60k -> 55k train + 5k validation


def _open_maybe_gz(path: str):
    if os.path.exists(path + ".gz"):
        return gzip.open(path + ".gz", "rb")
    return open(path, "rb")


def parse_idx_images(data: bytes) -> np.ndarray:
    """Parse an IDX3 image file into uint8 [N, rows, cols]."""
    if len(data) < 16:
        raise ValueError(f"IDX image file too short ({len(data)} bytes); bad magic/header")
    magic, n, rows, cols = struct.unpack(">IIII", data[:16])
    if magic != IMAGE_MAGIC:
        raise ValueError(f"bad IDX image magic 0x{magic:08x}, want 0x{IMAGE_MAGIC:08x}")
    arr = np.frombuffer(data, dtype=np.uint8, count=n * rows * cols, offset=16)
    return arr.reshape(n, rows, cols)


def parse_idx_labels(data: bytes) -> np.ndarray:
    """Parse an IDX1 label file into uint8 [N]."""
    if len(data) < 8:
        raise ValueError(f"IDX label file too short ({len(data)} bytes); bad magic/header")
    magic, n = struct.unpack(">II", data[:8])
    if magic != LABEL_MAGIC:
        raise ValueError(f"bad IDX label magic 0x{magic:08x}, want 0x{LABEL_MAGIC:08x}")
    return np.frombuffer(data, dtype=np.uint8, count=n, offset=8)


def one_hot(labels: np.ndarray, num_classes: int = 10) -> np.ndarray:
    out = np.zeros((labels.shape[0], num_classes), dtype=np.float32)
    out[np.arange(labels.shape[0]), labels] = 1.0
    return out


@dataclasses.dataclass
class DataSplit:
    """One split: flattened float32 images in [0,1] and one-hot labels.

    Mirrors the ``mnist.train`` / ``mnist.test`` objects the reference
    uses (example.py:153, 157, 177).
    """

    images: np.ndarray  # [N, 784] float32 in [0, 1]
    labels: np.ndarray  # [N, 10] float32 one-hot

    @property
    def num_examples(self) -> int:
        return self.images.shape[0]


@dataclasses.dataclass
class Dataset:
    train: DataSplit
    validation: DataSplit
    test: DataSplit
    source: str  # "mnist" or "synthetic"


# ---------------------------------------------------------------------------
# Synthetic fallback (offline-deterministic)
# ---------------------------------------------------------------------------

# 5x7 bitmap glyphs for digits 0-9 (classic dot-matrix font), row-major.
_GLYPHS = {
    0: ["01110", "10001", "10011", "10101", "11001", "10001", "01110"],
    1: ["00100", "01100", "00100", "00100", "00100", "00100", "01110"],
    2: ["01110", "10001", "00001", "00010", "00100", "01000", "11111"],
    3: ["11111", "00010", "00100", "00010", "00001", "10001", "01110"],
    4: ["00010", "00110", "01010", "10010", "11111", "00010", "00010"],
    5: ["11111", "10000", "11110", "00001", "00001", "10001", "01110"],
    6: ["00110", "01000", "10000", "11110", "10001", "10001", "01110"],
    7: ["11111", "00001", "00010", "00100", "01000", "01000", "01000"],
    8: ["01110", "10001", "10001", "01110", "10001", "10001", "01110"],
    9: ["01110", "10001", "10001", "01111", "00001", "00010", "01100"],
}


def _glyph_array(digit: int) -> np.ndarray:
    g = _GLYPHS[digit]
    return np.array([[int(c) for c in row] for row in g], dtype=np.float32)


def synthesize_split(n: int, seed: int, input_size: int = 784) -> DataSplit:
    """Deterministic MNIST-like data: upscaled glyphs + jitter + noise.

    Learnable by the reference MLP to high accuracy, which is what the
    end-to-end and bench paths need; statistically it is NOT MNIST and
    accuracy numbers on it are labelled as synthetic (Dataset.source).

    ``input_size != 784`` tiles (or truncates) each flattened 28x28
    glyph image to the requested feature width, keeping the labels
    learnable — this is what lets non-MNIST-shaped configs (e.g. the
    long-sequence transformer, ``--input_size=1024 --seq_len=256``)
    run through the same end-to-end driver.
    """
    rng = np.random.RandomState(seed)
    labels = rng.randint(0, 10, size=n).astype(np.uint8)
    images = np.zeros((n, 28, 28), dtype=np.float32)
    # Upscale 5x7 -> 15x21 (3x), place with +-3 px jitter around center.
    glyphs = {d: np.kron(_glyph_array(d), np.ones((3, 3), np.float32)) for d in range(10)}
    gh, gw = 21, 15
    for i in range(n):
        gy = 3 + rng.randint(-3, 4)
        gx = 6 + rng.randint(-3, 4)
        intensity = 0.6 + 0.4 * rng.rand()
        images[i, gy : gy + gh, gx : gx + gw] = glyphs[labels[i]] * intensity
    images += rng.normal(0.0, 0.08, size=images.shape).astype(np.float32)
    np.clip(images, 0.0, 1.0, out=images)
    # Quantize to the 8-bit pixel grid (k/255), exactly like real MNIST
    # pixels: the device-resident fast path can then store the split as
    # uint8 (4x less HBM + host->device transfer) with bit-exact
    # reconstruction (parallel/epoch._pack_images).
    images = np.round(images * 255.0).astype(np.float32) / np.float32(255.0)
    flat = images.reshape(n, 784)
    if input_size != 784:
        flat = np.ascontiguousarray(
            np.tile(flat, (1, -(-input_size // 784)))[:, :input_size])
    return DataSplit(images=flat, labels=one_hot(labels))


def synthesize_dataset(
    seed: int = 0, train_size: int = 55000, test_size: int = 10000,
    input_size: int = 784,
) -> Dataset:
    return Dataset(
        train=synthesize_split(train_size, seed=seed + 1,
                               input_size=input_size),
        validation=synthesize_split(max(train_size // 11, 10), seed=seed + 2,
                                    input_size=input_size),
        test=synthesize_split(test_size, seed=seed + 3,
                              input_size=input_size),
        source="synthetic",
    )


# ---------------------------------------------------------------------------
# Real MNIST from IDX files on disk
# ---------------------------------------------------------------------------


def load_idx_dataset(data_dir: str) -> Dataset:
    def read(name: str) -> bytes:
        with _open_maybe_gz(os.path.join(data_dir, name)) as f:
            return f.read()

    train_images = parse_idx_images(read(TRAIN_IMAGES))
    train_labels = parse_idx_labels(read(TRAIN_LABELS))
    test_images = parse_idx_images(read(TEST_IMAGES))
    test_labels = parse_idx_labels(read(TEST_LABELS))

    def to_split(imgs: np.ndarray, lbls: np.ndarray) -> DataSplit:
        flat = imgs.reshape(imgs.shape[0], -1).astype(np.float32) / 255.0
        return DataSplit(images=flat, labels=one_hot(lbls))

    # TF tutorial split semantics: first VALIDATION_SIZE examples held out.
    return Dataset(
        train=to_split(train_images[VALIDATION_SIZE:], train_labels[VALIDATION_SIZE:]),
        validation=to_split(train_images[:VALIDATION_SIZE], train_labels[:VALIDATION_SIZE]),
        test=to_split(test_images, test_labels),
        source="mnist",
    )


def _process_index() -> int:
    try:
        import jax

        return jax.process_index()
    except Exception:
        return 0  # jax not initialized: single-process semantics


def _process_count() -> int:
    try:
        import jax

        return jax.process_count()
    except Exception:
        return 1


def _download_barrier() -> None:
    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices("mnist_download")


def idx_files_present(data_dir: str) -> bool:
    return all(
        os.path.exists(os.path.join(data_dir, n))
        or os.path.exists(os.path.join(data_dir, n + ".gz"))
        for n in (TRAIN_IMAGES, TRAIN_LABELS, TEST_IMAGES, TEST_LABELS)
    )


def load_datasets(
    data_dir: str = "MNIST_data",
    dataset: str = "auto",
    seed: int = 0,
    synthetic_train_size: int = 55000,
    synthetic_test_size: int = 10000,
    mirrors=None,
    input_size: int = 784,
) -> Dataset:
    """Replacement for ``input_data.read_data_sets`` (example.py:47-48).

    ``mnist`` uses real IDX files from ``data_dir``, downloading the
    four canonical archives (mirror list + SHA-256 verification,
    data.download) when absent — the reference's read_data_sets
    behavior. ``auto`` uses real files when already present, otherwise
    the deterministic synthetic fallback — never touching the network
    (the right default for air-gapped machines).

    ``input_size != 784`` (non-MNIST-shaped configs, e.g. the
    long-sequence transformer) requires ``--dataset=synthetic``: real
    MNIST bytes are inherently 784-dim.
    """
    if input_size != 784:
        if dataset == "mnist" or (dataset == "auto"
                                  and idx_files_present(data_dir)):
            raise ValueError(
                f"input_size={input_size}: real MNIST IDX data is 784-dim; "
                "use --dataset=synthetic for non-MNIST-shaped configs")
        dataset = "synthetic"  # auto resolves to the only shape that fits
    if dataset in ("mnist", "auto") and idx_files_present(data_dir):
        if dataset == "mnist" and _process_count() > 1:
            # Join the barrier even on the files-present path: a peer
            # that raced ahead (e.g. the chief finishing its download)
            # is waiting in it, and every process passes through exactly
            # one of the two mnist branches.
            _download_barrier()
        return load_idx_dataset(data_dir)
    if dataset == "mnist":
        from .download import DownloadError, download_mnist

        # Multi-process: only the chief downloads (data_dir is commonly
        # shared); everyone barriers, then re-checks the files. A bare
        # per-process download would hit the mirrors N times over.
        err: Exception | None = None
        if _process_index() == 0:
            try:
                download_mnist(data_dir, mirrors=mirrors or None)
            except Exception as e:  # noqa: BLE001 — ANY chief failure
                # must still reach the barrier below, or every other
                # process hangs in the collective (e.g. PermissionError
                # from makedirs is not a DownloadError)
                err = e
        if _process_count() > 1:
            _download_barrier()
        if not idx_files_present(data_dir):
            raise FileNotFoundError(
                f"MNIST IDX files not found in {data_dir!r} and download "
                f"failed:\n{err}\nDrop {TRAIN_IMAGES}, {TRAIN_LABELS}, "
                f"{TEST_IMAGES}, {TEST_LABELS} (optionally .gz) into "
                f"{data_dir!r} to train on real MNIST offline."
            ) from err
        return load_idx_dataset(data_dir)
    return synthesize_dataset(
        seed=seed, train_size=synthetic_train_size,
        test_size=synthetic_test_size, input_size=input_size,
    )


# ---------------------------------------------------------------------------
# Epoch iterator (next_batch equivalent)
# ---------------------------------------------------------------------------


class EpochIterator:
    """Shuffled mini-batch iterator, the ``next_batch`` analog.

    The reference's ``mnist.train.next_batch(100)`` (example.py:157)
    shuffles once per epoch and walks the permutation. This iterator does
    the same, seeded for determinism, with optional per-process sharding:
    process ``p`` of ``P`` sees the permutation's slice ``p::P`` so one
    "epoch" across all processes is exactly one global pass (SURVEY.md §7
    hard part 3). With ``shard=False`` every process walks the full
    permutation — the reference's actual (unsharded) behavior.

    Batches are gathered through the native C++ helper when available
    (index-gather is host-side memcpy work, off the interpreter).
    """

    def __init__(
        self,
        split: DataSplit,
        batch_size: int,
        seed: int = 1,
        shard: bool = True,
        process_index: int = 0,
        process_count: int = 1,
        drop_remainder: bool = True,
    ):
        if batch_size <= 0:
            raise ValueError("batch_size must be positive")
        self.split = split
        self.batch_size = batch_size
        self.shard = shard
        self.process_index = process_index
        self.process_count = process_count
        self.drop_remainder = drop_remainder
        self._seed = seed
        self._epoch = 0

    def _local_examples(self) -> int:
        """Per-process example count. When sharded, every process gets
        exactly floor(N / P): unequal shards would give processes
        different batches_per_epoch, and under SPMD an extra step on one
        process is a collective the others never join (deadlock). The
        remainder (< P examples) is dropped each epoch."""
        n = self.split.num_examples
        if self.shard:
            n = n // self.process_count
        return n

    @property
    def batches_per_epoch(self) -> int:
        """Reference: ``int(mnist.train.num_examples / batch_size)`` (example.py:153)."""
        n = self._local_examples()
        if self.drop_remainder:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size

    def epoch(
        self, epoch_index: int | None = None
    ) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
        """One shuffled pass. The permutation is keyed by ``(seed,
        epoch_index)`` — not by a stateful RNG stream — so a run resumed
        at epoch E replays exactly the shuffles an uninterrupted run
        would have used (the host-path analog of the device path's
        ``fold_in(epoch)`` keying). ``epoch_index`` defaults to an
        internal counter for sequential use."""
        # Eager body: the permutation and counter update happen at the
        # epoch() call, not at first next() — two un-consumed epoch()
        # calls must not key the same permutation.
        if epoch_index is None:
            epoch_index = self._epoch
        rng = np.random.RandomState([self._seed & 0x7FFFFFFF, epoch_index])
        perm = rng.permutation(self.split.num_examples)
        self._epoch = epoch_index + 1
        if self.shard and self.process_count > 1:
            # strided slice, truncated to the common per-process length
            # so every process runs the same number of (collective) steps
            perm = perm[self.process_index :: self.process_count]
            perm = perm[: self._local_examples()]

        def _batches():
            from ..native import gather_batch  # lazy: avoids import cycle

            for b in range(self.batches_per_epoch):
                idx = perm[b * self.batch_size : (b + 1) * self.batch_size]
                yield gather_batch(self.split.images, self.split.labels, idx)

        return _batches()
