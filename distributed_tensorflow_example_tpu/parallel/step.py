"""SPMD train/eval steps.

Reference parity: one reference training step is
``sess.run([train_op, cross_entropy, summary_op, global_step])``
(/root/reference/example.py:160-162) — the TF graph executor pulls all
parameters from the ps, runs fwd/bwd on the worker, pushes gradients
back, and the ps applies SGD without locking (async, example.py:101,
111) or behind the SyncReplicasOptimizer barrier (sync, commented,
example.py:102-110). Three gRPC crossings and a full parameter copy
each way, every step (SURVEY.md §3.3).

TPU-native design (SURVEY.md §7): both reference paths compile to ONE
XLA executable per step — forward, backward, cross-replica gradient
reduction, and the optimizer update fused, with the reduction riding
the ICI as a single psum. Two flavors:

- **sync** (`build_train_step`): the SyncReplicasOptimizer semantics.
  Per-shard fwd/bwd on the local batch slice; gradients of the (data-)
  replicated params are automatically psum'd across the 'data' axis by
  shard_map's transpose; ``grad_reduce='mean'`` rescales by 1/dp so an
  N-device batch-B step is bitwise the 1-device batch-B step (the §4
  psum-equivalence guarantee), while ``'sum'`` keeps the summed-replica
  gradient — the effective-LR analog of N async workers each applying
  their local gradient (SURVEY.md §7 hard part 1).

- **async analog** (`build_local_train_step` + `build_param_sync`):
  the reference's HOGWILD-style path (example.py:101,111) has no shared
  mutable server under SPMD; its TPU-native equivalent is **local SGD**:
  every data shard keeps a *divergent* copy of the params (stacked along
  a leading mesh-sharded axis) and applies its own gradients locally,
  reconciled by parameter averaging every ``--sync_period`` steps.
  K=1 collapses to sync; growing K dials in the gradient staleness the
  async reference exhibits.

Tensor parallelism (absent in the reference, SURVEY.md §2c) composes
orthogonally: layers marked 'col'/'row' by mesh.layer_styles shard the
hidden dim Megatron-style with one psum after each row-split matmul.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..models import mlp
from ..ops import losses, metrics
from ..train.state import TrainState
from . import mesh as mesh_lib
from .mesh import DATA_AXIS, MODEL_AXIS


def forward_local(spec, params, x, styles, use_pallas: bool = False,
                  seq_axis: str | None = None,
                  expert_axis: str | None = None,
                  pipeline: tuple | None = None,
                  model_axis: str | None = None,
                  with_aux: bool = False, aux_axes: tuple = (),
                  dropout_rng=None, slot_remat: bool = False):
    """Per-shard forward to (replicated) logits; TP-aware (example.py:87-89).

    Model-family dispatch: TransformerSpec routes to the transformer
    forward (its Pallas path is the flash-attention backend, selected
    on the spec itself). For the MLP, the fused Pallas kernel handles
    the pure data-parallel case for activations whose VJP is
    expressible from the saved activation
    (pallas_fused.SUPPORTED_ACTIVATIONS); TP shards the hidden dim and
    gelu's VJP needs the pre-activation, so those fall to the XLA path.
    """
    from ..models import transformer

    if isinstance(spec, transformer.TransformerSpec):
        if pipeline is not None:
            stage_axis, n_stages, microbatches, virtual = pipeline
            if getattr(spec, "objective", "classify") == "lm":
                # next-token loss statistics computed ON the last
                # stage: two numbers per example ride the collective,
                # never the [mb, S, V] logits (count is the static
                # S-1). Returns [B, 2] = (nll_sum, correct_sum).
                # Under PP x SP the tokens arrive seq-sharded;
                # _lm_stats handles the shard-boundary target ppermute
                # and psums the sums over 'seq', so the collected
                # per-example numbers are already GLOBAL.
                mb = x.shape[0] // microbatches
                micro_t = transformer.tokenize(spec, x).reshape(
                    microbatches, mb, -1)

                def lm_head(params_, h, m):
                    hl = transformer._ln(
                        spec, h, params_["lnf_g"], params_["lnf_b"])
                    logits = transformer._mm(
                        params_, hl, "W_head", "b_head",
                        spec.compute_dtype).astype(jnp.float32)
                    tok = jax.lax.dynamic_index_in_dim(
                        micro_t, m, 0, keepdims=False)
                    nll, correct, _cnt = _lm_stats(spec, logits, tok,
                                                   seq_axis)
                    return jnp.stack([nll, correct], axis=-1)

                return transformer.apply_pipeline(
                    spec, params, x, stage_axis, n_stages, microbatches,
                    model_axis=model_axis, virtual=virtual,
                    head_fn=lm_head, head_width=2, seq_axis=seq_axis,
                    expert_axis=expert_axis, with_aux=with_aux,
                    aux_axes=aux_axes, dropout_rng=dropout_rng,
                    slot_remat=slot_remat)
            return transformer.apply_pipeline(
                spec, params, x, stage_axis, n_stages, microbatches,
                model_axis=model_axis, virtual=virtual,
                seq_axis=seq_axis, expert_axis=expert_axis,
                with_aux=with_aux, aux_axes=aux_axes,
                dropout_rng=dropout_rng, slot_remat=slot_remat)
        return transformer.apply(spec, params, x, seq_axis=seq_axis,
                                 expert_axis=expert_axis,
                                 model_axis=model_axis,
                                 with_aux=with_aux, aux_axes=aux_axes,
                                 dropout_rng=dropout_rng)
    if use_pallas and all(s == "rep" for s in styles):
        from ..ops import pallas_fused

        if spec.activation in pallas_fused.SUPPORTED_ACTIVATIONS:
            return pallas_fused.mlp_forward(spec, params, x)
    return mlp.apply(spec, params, x, styles=styles, model_axis=MODEL_AXIS)


def _lm_stats(spec, logits, tokens, seq_axis):
    """Per-example next-token sums from per-position vocab logits:
    ``(nll_sum [B], correct_sum [B], count [B])`` over the S-1 valid
    positions (position t predicts token t+1; the global last position
    has no target).

    Under sequence parallelism each shard holds a contiguous token
    block: its last position's target is the NEXT shard's first token
    — fetched with one tiny ppermute — and the per-example sums are
    psum'd over the seq axis, so every shard returns the GLOBAL
    statistics and N-shard training/eval matches one device exactly.
    """
    b, sl, _ = logits.shape
    logp = jax.nn.log_softmax(logits, axis=-1)
    if seq_axis is None:
        preds, targets = logp[:, :-1], tokens[:, 1:]
        nll = -jnp.take_along_axis(preds, targets[..., None], -1)[..., 0]
        correct = (jnp.argmax(logits[:, :-1], -1) == targets)
        count = jnp.full((b,), nll.shape[1], jnp.float32)
        return (jnp.sum(nll, 1), jnp.sum(correct, 1).astype(jnp.float32),
                count)
    n = jax.lax.psum(1, seq_axis)
    idx = jax.lax.axis_index(seq_axis)
    # boundary target: shard i receives shard i+1's first token
    nxt = jax.lax.ppermute(tokens[:, 0], seq_axis,
                           [(i + 1, i) for i in range(n - 1)])
    targets = jnp.concatenate([tokens[:, 1:], nxt[:, None]], axis=1)
    nll = -jnp.take_along_axis(logp, targets[..., None], -1)[..., 0]
    correct = (jnp.argmax(logits, -1) == targets).astype(jnp.float32)
    mask = jnp.ones((b, sl), jnp.float32)
    mask = mask.at[:, -1].multiply(
        jnp.where(jnp.equal(idx, n - 1), 0.0, 1.0))
    return (jax.lax.psum(jnp.sum(nll * mask, 1), seq_axis),
            jax.lax.psum(jnp.sum(correct * mask, 1), seq_axis),
            jax.lax.psum(jnp.sum(mask, 1), seq_axis))


def _loss_and_acc(spec, params, x, y, styles, naive, use_pallas, remat=False,
                  seq_axis=None, expert_axis=None, pipeline=None,
                  model_axis=None, aux_axes=(), label_smoothing=0.0,
                  dropout_rng=None):
    """-> (objective, (reported_cost, accuracy)): the objective is what
    gradients flow from (CE plus, for a MoE spec with
    ``aux_loss_weight``, the weighted load-balance loss); the reported
    cost stays plain CE so the reference's printed metric is
    unchanged. ``aux_axes``: mesh axes the tokens shard over — the
    balance loss pmean's its statistics across them so N-shard
    training optimizes the same global objective as one device."""
    aux_w = float(getattr(spec, "aux_loss_weight", 0.0))
    want_aux = aux_w > 0.0

    # under a pipeline, --remat means PER-SLOT remat inside the tick
    # loop (apply_pipeline's chunk_fn): backward saves only each
    # slot's input, the strictly better granularity — a whole-forward
    # checkpoint would re-run the full tick loop and hold every
    # recomputed residual at once
    pipe_remat = bool(remat and pipeline is not None)

    def fwd(p, xx):
        if want_aux:
            return forward_local(spec, p, xx, styles, use_pallas,
                                 seq_axis, expert_axis, pipeline,
                                 model_axis, with_aux=True,
                                 aux_axes=aux_axes,
                                 dropout_rng=dropout_rng,
                                 slot_remat=pipe_remat)
        return forward_local(spec, p, xx, styles, use_pallas,
                             seq_axis, expert_axis, pipeline,
                             model_axis,
                             dropout_rng=dropout_rng,
                             slot_remat=pipe_remat), jnp.float32(0.0)

    if remat and not pipe_remat:
        # jax.checkpoint: recompute activations in the backward pass
        # instead of saving them — trades MXU FLOPs for HBM, the
        # standard lever once hidden sizes grow (SURVEY has no analog:
        # TF 1.2 always stored every activation).
        fwd = jax.checkpoint(fwd)
    logits, aux = fwd(params, x)
    if getattr(spec, "objective", "classify") == "lm":
        # self-supervised: y is unused; loss = mean next-token CE
        from ..models import transformer

        if pipeline is not None:
            # the pipeline forward already reduced the last stage's
            # logits to per-example (nll_sum, correct_sum) [B, 2];
            # every example counts its S-1 valid positions
            count = jnp.float32(x.shape[0] * (spec.seq_len - 1))
            cost = jnp.sum(logits[:, 0]) / count
            acc = jnp.sum(logits[:, 1]) / count
            return cost + aux_w * aux, (cost, acc)
        tokens = transformer.tokenize(spec, x)
        nll, correct, count = _lm_stats(spec, logits, tokens, seq_axis)
        cost = jnp.sum(nll) / jnp.sum(count)
        acc = jnp.sum(correct) / jnp.sum(count)
        return cost + aux_w * aux, (cost, acc)
    cost = losses.cross_entropy(logits, y, naive=naive,
                                label_smoothing=label_smoothing)
    acc = metrics.accuracy(logits, y)
    return cost + aux_w * aux, (cost, acc)


def _pspec_axes(sp) -> tuple:
    """The mesh axes a leaf's PartitionSpec shards over (flattened,
    deduped, sorted) — the axes its square-sum must psum across for an
    exact global reduction."""
    axes = []
    for part in (sp or ()):
        if part is None:
            continue
        axes.extend(part if isinstance(part, tuple) else (part,))
    return tuple(sorted(set(axes)))


def _leaf_reduce(tree, param_pspecs, leaf_fn):
    """Shared per-leaf global-reduction scaffolding for the telemetry
    vectors: ``leaf_fn`` maps each leaf to a scalar local partial,
    which is psum'd over exactly the mesh axes the leaf's
    PartitionSpec mentions (its shards partition the full leaf, so
    the result is the GLOBAL value on every shard, as _clip_sharded
    computes). Returns the list of per-leaf scalars in tree_leaves
    order."""
    leaves = jax.tree_util.tree_leaves(tree)
    if param_pspecs is None:
        spec_leaves = [None] * len(leaves)
    else:
        spec_leaves = jax.tree_util.tree_leaves(
            param_pspecs, is_leaf=lambda x: isinstance(x, P))
    out = []
    for g, sp in zip(leaves, spec_leaves):
        v = leaf_fn(g)
        axes = _pspec_axes(sp)
        if axes:
            v = jax.lax.psum(v, axes)
        out.append(v)
    return out


def _leaf_norms(tree, param_pspecs):
    """Per-leaf global L2 norms as one [n_leaves] f32 vector, exact
    under parameter sharding (_leaf_reduce). The telemetry source for
    the --histograms grad/param-norm summaries — a handful of scalars
    per step, so keeping the latest device value and fetching it once
    per logging window adds no per-step host traffic."""
    sq = _leaf_reduce(tree, param_pspecs,
                      lambda g: jnp.sum(jnp.square(g.astype(jnp.float32))))
    return jnp.sqrt(jnp.stack(sq))


def _leaf_nonfinite(tree, param_pspecs):
    """Per-leaf GLOBAL non-finite element counts as one [n_leaves] i32
    vector — the --on_anomaly blame signal, sharding-exact via the
    same _leaf_reduce scaffolding as the norms. A couple of
    reductions per leaf — noise next to the matmuls."""
    return jnp.stack(_leaf_reduce(
        tree, param_pspecs,
        lambda g: jnp.sum(~jnp.isfinite(g.astype(jnp.float32)))
        .astype(jnp.int32)))


def _clip_sharded(grads, param_pspecs, max_norm: float):
    """Global-norm clip that is exact under PARAMETER sharding: a
    leaf's square-sum is psum'd over exactly the mesh axes its
    PartitionSpec mentions (its shards partition the full leaf), while
    replicated leaves contribute once — so TP/PP/EP shards all compute
    the SAME global norm and replicated params cannot drift apart
    under a binding clip. Leaves are grouped by their axis set to
    batch the psums."""
    g_leaves = jax.tree_util.tree_leaves(grads)
    s_leaves = jax.tree_util.tree_leaves(
        param_pspecs, is_leaf=lambda x: isinstance(x, P))
    groups: dict = {}
    for g, sp in zip(g_leaves, s_leaves):
        key = _pspec_axes(sp)
        groups.setdefault(key, []).append(
            jnp.sum(jnp.square(g.astype(jnp.float32))))
    sq = jnp.float32(0.0)
    for axes, sqs in groups.items():
        part_sum = sum(sqs)
        if axes:
            part_sum = jax.lax.psum(part_sum, axes)
        sq = sq + part_sum
    norm = jnp.sqrt(sq)
    scale = jnp.minimum(1.0, max_norm / jnp.maximum(norm, 1e-12))
    return jax.tree.map(lambda g: (g * scale).astype(g.dtype), grads)


def make_step_rng(cfg, spec, axes):
    """Deterministic per-step dropout rng factory: seed x step, folded
    by each token-sharding axis index so every batch/token shard draws
    its own masks while TP shards (replicated activations) share
    theirs. Resume-stable: the step count determines the stream.
    Shared by the sync and FSDP step bodies so FSDP-with-dropout is
    bitwise the sync step's masks."""
    dropping = getattr(spec, "dropout_rate", 0.0) > 0

    def step_rng(state):
        if not dropping:
            return None
        rng = jax.random.fold_in(
            jax.random.PRNGKey(cfg.seed ^ 0xD0C0), state.step)
        for axis in axes:
            rng = jax.random.fold_in(rng, jax.lax.axis_index(axis))
        return rng

    return step_rng


def make_sync_step_body(cfg, spec: mlp.MLPSpec, styles, dp: int, optimizer,
                        seq_axis: str | None = None,
                        expert_axis: str | None = None,
                        pipeline: tuple | None = None,
                        model_axis: str | None = None,
                        batch_axes: tuple = (DATA_AXIS,),
                        param_pspecs=None,
                        zero_dp: int = 0,
                        with_norms: bool = False,
                        with_anomaly: bool = False) -> Callable:
    """The per-shard synchronous step body (state, x, y) -> (state, cost,
    acc) — shared by the host-fed step (build_train_step) and the
    device-resident scan paths (parallel/epoch.py) so both train with
    identical semantics. ``dp`` is the total number of batch shards
    (the product of the ``batch_axes`` sizes — more than one axis under
    sparse-dispatch expert parallelism, where tokens shard over
    'expert' too). ``zero_dp`` > 0 swaps the optimizer apply for the
    ZeRO-1 chunked update (parallel/zero.py): slots arrive as flat
    1/zero_dp shards over 'data' and the updated params all-gather."""

    # token-sharding axes for the MoE balance loss: the batch axes
    # plus the sequence axis when the token dim itself is sharded
    aux_axes = tuple(batch_axes) + ((seq_axis,) if seq_axis else ())
    dropping = getattr(spec, "dropout_rate", 0.0) > 0
    # --on_anomaly: 'skip' masks the update on-device (a NaN batch
    # cannot poison params even before the host notices); any mode
    # needs the flag when the caller asks for the compiled outputs.
    anomaly_mode = getattr(cfg, "on_anomaly", "") or ""
    detect_anomaly = with_anomaly or anomaly_mode == "skip"
    # every mesh axis the step runs over: the scalar flag must psum
    # across ALL of them so every shard takes the same skip/keep
    # branch (replicated leaves would otherwise drift apart)
    all_axes = tuple(dict.fromkeys(
        tuple(batch_axes)
        + tuple(a for a in (seq_axis, expert_axis, model_axis) if a)
        + ((pipeline[0],) if pipeline else ())))

    def grad_of(params, x, y, rng=None):
        def loss_fn(p):
            return _loss_and_acc(
                spec, p, x, y, styles, cfg.naive_ce, cfg.pallas, cfg.remat,
                seq_axis, expert_axis, pipeline, model_axis, aux_axes,
                cfg.label_smoothing, rng,
            )

        return jax.value_and_grad(loss_fn, has_aux=True)(params)

    use_1f1b = (pipeline is not None
                and getattr(cfg, "pp_schedule", "gpipe") == "1f1b")

    def grad_1f1b(params, x, y, rng=None):
        """(cost, acc), grads via the fused-tick 1F1B schedule family
        (transformer.pipeline_value_and_grad_1f1b; virtual > 1 runs
        the interleaved refinement) — live microbatch activations cap
        at O(p·v) instead of jax.grad's M. Objective plumbing mirrors
        _loss_and_acc's pipeline branch exactly."""
        from ..models import transformer

        stage_axis, n_stages, microbatches, virt = pipeline
        mbs = x.shape[0] // microbatches
        if getattr(spec, "objective", "classify") == "lm":
            micro_t = transformer.tokenize(spec, x).reshape(
                microbatches, mbs, -1)

            def head(prm, h, m):
                hl = transformer._ln(spec, h, prm["lnf_g"],
                                     prm["lnf_b"])
                logits = transformer._mm(
                    prm, hl, "W_head", "b_head",
                    spec.compute_dtype).astype(jnp.float32)
                tok = jax.lax.dynamic_index_in_dim(micro_t, m, 0,
                                                   keepdims=False)
                nll, correct, _cnt = _lm_stats(spec, logits, tok, None)
                return jnp.stack([nll, correct], axis=-1)

            count = jnp.float32(x.shape[0] * (spec.seq_len - 1))

            def loss_of(vals, m):
                return jnp.sum(vals[:, 0]) / count

            (loss, stats), grads = transformer.pipeline_value_and_grad_1f1b(
                spec, params, x, stage_axis, n_stages, microbatches,
                loss_of, head_fn=head, head_width=2,
                model_axis=model_axis, dropout_rng=rng,
                batch_axes=batch_axes, virtual=virt)
            cost = jnp.sum(stats[:, 0]) / count
            acc = jnp.sum(stats[:, 1]) / count
            return (cost, acc), grads

        ys = y.reshape(microbatches, mbs, *y.shape[1:])

        def loss_of(vals, m):
            y_m = jax.lax.dynamic_index_in_dim(ys, m, 0, keepdims=False)
            return losses.cross_entropy(
                vals, y_m, naive=cfg.naive_ce,
                label_smoothing=cfg.label_smoothing) / microbatches

        (loss, stats), grads = transformer.pipeline_value_and_grad_1f1b(
            spec, params, x, stage_axis, n_stages, microbatches,
            loss_of, model_axis=model_axis, dropout_rng=rng,
            batch_axes=batch_axes, virtual=virt)
        cost = losses.cross_entropy(stats, y, naive=cfg.naive_ce,
                                    label_smoothing=cfg.label_smoothing)
        acc = metrics.accuracy(stats, y)
        return (cost, acc), grads

    step_rng = make_step_rng(cfg, spec, aux_axes)

    def body(state: TrainState, x, y):
        n = cfg.grad_accum
        if n > 1:
            # accumulate over n microbatches inside the compiled step:
            # mean of the chunk gradients == the full-batch gradient
            # (equal chunks, mean-CE), at 1/n the activation memory
            if x.shape[0] % n:
                raise ValueError(
                    f"per-shard batch {x.shape[0]} must divide into "
                    f"grad_accum={n} microbatches")
            xs = x.reshape(n, x.shape[0] // n, *x.shape[1:])
            ys = y.reshape(n, y.shape[0] // n, *y.shape[1:])
            rng0 = step_rng(state)

            def mb_rng(i):
                # distinct dropout masks per microbatch
                return (jax.random.fold_in(rng0, i) if dropping else None)

            def accum(carry, xy_i):
                g_acc, c_acc, a_acc = carry
                xc, yc, i = xy_i
                (_t, (c, a)), g = grad_of(state.params, xc, yc, mb_rng(i))
                return (jax.tree.map(jnp.add, g_acc, g),
                        c_acc + c, a_acc + a), None

            # seed the carry with microbatch 0 (a plain zero init would
            # be device-invariant while the accumulated values vary
            # over the batch axes — scan requires matching types)
            (_t0, (c0, a0)), g0 = grad_of(state.params, xs[0], ys[0],
                                          mb_rng(0))
            (g_sum, c_sum, a_sum), _ = jax.lax.scan(
                accum, (g0, c0, a0),
                (xs[1:], ys[1:], jnp.arange(1, n)))
            grads = jax.tree.map(lambda g: g / n, g_sum)
            cost, acc = c_sum / n, a_sum / n
        elif use_1f1b:
            (cost, acc), grads = grad_1f1b(state.params, x, y,
                                           step_rng(state))
        else:
            (_total, (cost, acc)), grads = grad_of(state.params, x, y,
                                                   step_rng(state))
        # shard_map's transpose has already psum'd grads over the batch
        # axes (params are batch-unvarying); rescale for mean semantics.
        if cfg.grad_reduce == "mean" and dp > 1:
            grads = jax.tree.map(lambda g: g / dp, grads)
        # telemetry norms ride the step PRE-clip (the raw gradient
        # scale is the debugging signal a clip would mask)
        grad_norms = _leaf_norms(grads, param_pspecs) if with_norms else None
        bad_counts = bad_flag = None
        if detect_anomaly:
            # pre-clip, like the norms: a clip of NaN stays NaN but
            # the RAW gradient is the forensic signal. The flag folds
            # in the local objective too (psum over every axis makes
            # it identical on all shards).
            bad_counts = _leaf_nonfinite(grads, param_pspecs)
            loss_bad = (~jnp.isfinite(cost)).astype(jnp.int32)
            if all_axes:
                loss_bad = jax.lax.psum(loss_bad, all_axes)
            bad_flag = jnp.any(bad_counts > 0) | (loss_bad > 0)
        if cfg.grad_clip > 0:
            if param_pspecs is not None:
                grads = _clip_sharded(grads, param_pspecs, cfg.grad_clip)
            else:
                from ..train.optim import clip_by_global_norm

                grads, _ = clip_by_global_norm(grads, cfg.grad_clip)
        if zero_dp:
            from .zero import zero_update

            new_params, new_opt = zero_update(
                optimizer, grads, state.opt_state, state.params, zero_dp)
        else:
            new_params, new_opt = optimizer.update(
                grads, state.opt_state, state.params)
        if anomaly_mode == "skip" and bad_flag is not None:
            # masked update: a flagged step keeps the old params/opt
            # (step still advances — it counts steps ATTEMPTED; the
            # host's skipped-step accounting rides the flag/the
            # non-finite cost). bad_flag is globally consistent, so
            # every shard keeps or applies together.
            keep_old = bad_flag
            new_params = jax.tree.map(
                lambda n, o: jnp.where(keep_old, o, n),
                new_params, state.params)
            new_opt = jax.tree.map(
                lambda n, o: jnp.where(keep_old, o, n),
                new_opt, state.opt_state)
        cost = jax.lax.pmean(cost, batch_axes)
        acc = jax.lax.pmean(acc, batch_axes)
        new_state = TrainState(state.step + 1, new_params, new_opt)
        extras = ()
        if with_norms:
            extras += ({"grad": grad_norms,
                        "param": _leaf_norms(new_params, param_pspecs)},)
        if with_anomaly:
            extras += ({"flag": bad_flag, "counts": bad_counts},)
        if extras:
            return (new_state, cost, acc) + extras
        return new_state, cost, acc

    return body


def eval_chunk_cap(spec, eval_batch_size: int) -> int:
    """Examples per eval chunk: the caller's batch size, capped for
    transformers so one chunk's forward stays within a ~2 GB
    activation budget. Two per-example terms: (1) the O(S) per-token
    activations every backend materializes — counted at the TPU's
    128-lane tile, because a head dim below 128 pads each [B, S, H,
    Dh] tensor up to [.., 128] in HBM (measured 4x expansion at
    Dh=32, the allocation that OOM'd the whole-test-set flash eval) —
    plus the FFN hidden and, for the lm objective, the [S, vocab]
    logits; (2) dense attention adds its [B, H, S, S] score tensor.
    For small models the budget quotient exceeds any realistic test
    set, so the cap never binds."""
    from ..models import transformer

    cap = eval_batch_size
    if isinstance(spec, transformer.TransformerSpec):
        budget = 2 * 1024 ** 3
        dh_pad = max(spec.d_head, 128)
        # ~8 live f32 [S, H, dh_pad] tensors (qkv, q/k/v, att, two
        # residual streams) + the two FFN hiddens, per example
        per_example = 4 * spec.seq_len * (
            8 * spec.n_heads * dh_pad + 2 * spec.d_ff)
        if spec.objective == "lm":
            per_example += 4 * spec.seq_len * spec.vocab_size
        if spec.attention == "dense":
            per_example += 8 * spec.n_heads * spec.seq_len ** 2  # f32, ~2x
        cap = min(cap, max(1, budget // per_example))
    return cap


def _eval_correct(spec, logits, x, y, seq_axis=None):
    """Per-example 'correct' value for eval: the 0/1 classification
    hit, or — lm objective — the example's mean next-token accuracy.
    Shared by the host eval step and the fast device-resident eval so
    the two paths cannot drift."""
    if getattr(spec, "objective", "classify") == "lm":
        from ..models import transformer

        tokens = transformer.tokenize(spec, x)
        _nll, c, cnt = _lm_stats(spec, logits, tokens, seq_axis)
        return c / cnt
    return (jnp.argmax(logits, -1) == jnp.argmax(y, -1)).astype(jnp.float32)


def sparse_ep_mode(mesh, spec) -> bool:
    """True when sparse-dispatch expert parallelism is active: tokens
    then shard over BOTH ('data','expert') — the GShard layout where
    the all_to_all exchange carries real (distinct-token) traffic and
    expert FLOPs split 1/ep per shard — instead of replicating the
    batch over the expert axis as the dense dispatch does."""
    from ..models import transformer

    return (mesh_lib.axis_if_present(mesh, mesh_lib.EXPERT_AXIS) is not None
            and isinstance(spec, transformer.TransformerSpec)
            and spec.num_experts > 0 and spec.moe_dispatch == "alltoall")


def batch_layout(mesh, spec):
    """(batch_axes, total_batch_shards, x_pspec, y_pspec) for the mesh —
    the one source of truth for how the global batch maps onto it."""
    dp = mesh.shape[DATA_AXIS]
    site_axis = mesh_lib.axis_if_present(mesh, mesh_lib.SITE_AXIS)
    if site_axis:
        # multi-site local SGD (parallel/local_sgd.py): every site
        # trains on its own slice, so the batch shards over BOTH the
        # site and the within-site data axis
        axes = (site_axis, DATA_AXIS)
        return axes, mesh.shape[site_axis] * dp, P(axes), P(axes)
    seq_axis = mesh_lib.axis_if_present(mesh, mesh_lib.SEQ_AXIS)
    if sparse_ep_mode(mesh, spec):
        ep = mesh.shape[mesh_lib.EXPERT_AXIS]
        axes = (DATA_AXIS, mesh_lib.EXPERT_AXIS)
        return axes, dp * ep, P(axes), P(axes)
    x_spec = P(DATA_AXIS, mesh_lib.SEQ_AXIS) if seq_axis else P(DATA_AXIS)
    return (DATA_AXIS,), dp, x_spec, P(DATA_AXIS)


def _pipeline_info(mesh, cfg, spec, optimizer=None):
    """(pipeline_tuple, param_or_state_pspecs) for a possibly-staged
    mesh — the one source of truth build_train_step and build_eval_step
    share. With ``optimizer`` returns state pspecs, else param pspecs.
    On a ('data','stage','model') mesh the stacked leaves also carry
    their Megatron inner-axis sharding (PPxTP)."""
    stage_axis = mesh_lib.axis_if_present(mesh, mesh_lib.STAGE_AXIS)
    if not stage_axis:
        return None, None
    model_axis = mesh_lib.tp_axis(spec, mesh.shape.get(MODEL_AXIS, 1))
    expert_axis = mesh_lib.axis_if_present(mesh, mesh_lib.EXPERT_AXIS)
    pipeline = (stage_axis, mesh.shape[stage_axis], cfg.microbatches,
                cfg.virtual_stages)
    if optimizer is not None:
        return pipeline, mesh_lib.pipeline_state_pspecs(
            spec, optimizer, stage_axis, model_axis, expert_axis)
    from ..models import transformer

    return pipeline, transformer.pipeline_param_pspecs(
        spec, stage_axis, model_axis, expert_axis)


def build_train_step(cfg, mesh, spec: mlp.MLPSpec, optimizer,
                     with_norms: bool = False,
                     with_anomaly: bool = False) -> Callable:
    """Synchronous SPMD step: (state, x, y) -> (state, cost, acc).

    The returned callable is jit'd with the state donated — params never
    leave the devices (the inverse of the reference's per-step parameter
    round-trip, SURVEY.md §3.3).

    ``with_norms=True`` (the --histograms telemetry) appends an
    output: {'grad': [n_leaves], 'param': [n_leaves]} per-leaf global
    L2 norms, computed inside the same compiled step (exact under
    parameter sharding) — the host keeps the latest device value and
    fetches it once per logging window.

    ``with_anomaly=True`` (--on_anomaly forensics) appends a LAST
    output {'flag': bool, 'counts': [n_leaves] i32}: one globally
    consistent "non-finite loss or gradient this step" bit plus the
    per-leaf non-finite element counts (the blame vector) — fetched
    lazily by the host (obs/anomaly.py), never a per-step sync. When
    ``cfg.on_anomaly == 'skip'`` the compiled update is additionally
    masked on the flag (here AND in the scan paths, which share this
    body), so a poisoned batch leaves params untouched.
    """
    mp = mesh.shape.get(MODEL_AXIS, 1)
    seq_axis = mesh_lib.axis_if_present(mesh, mesh_lib.SEQ_AXIS)
    expert_axis = mesh_lib.axis_if_present(mesh, mesh_lib.EXPERT_AXIS)
    pipeline, pp_specs = _pipeline_info(mesh, cfg, spec, optimizer)
    styles = mesh_lib.layer_styles(spec, mp)
    model_axis = mesh_lib.tp_axis(spec, mp)
    sspecs = (pp_specs if pipeline
              else mesh_lib.state_pspecs(spec, optimizer, mp, expert_axis))
    # batch layout: x splits over 'data' (plus 'seq' for the token
    # axis under sequence parallelism, plus 'expert' under
    # sparse-dispatch EP where tokens shard over the expert axis too)
    batch_axes, shards, x_spec, y_spec = batch_layout(mesh, spec)
    zero_dp = 0
    if getattr(cfg, "zero_opt", False):
        from ..train.state import TrainState as TS
        from .zero import zero_state_pspecs

        zero_dp = mesh.shape[DATA_AXIS]
        sspecs = TS(step=P(), params=sspecs.params,
                    opt_state=zero_state_pspecs(optimizer, sspecs.params))
    shard_step = make_sync_step_body(cfg, spec, styles, shards, optimizer,
                                     seq_axis, expert_axis, pipeline,
                                     model_axis, batch_axes,
                                     param_pspecs=sspecs.params,
                                     zero_dp=zero_dp,
                                     with_norms=with_norms,
                                     with_anomaly=with_anomaly)
    out_specs = (sspecs, P(), P())
    if with_norms:
        out_specs = out_specs + ({"grad": P(), "param": P()},)
    if with_anomaly:
        out_specs = out_specs + ({"flag": P(), "counts": P()},)
    fn = jax.shard_map(
        shard_step,
        mesh=mesh,
        in_specs=(sspecs, x_spec, y_spec),
        out_specs=out_specs,
    )
    return jax.jit(fn, donate_argnums=0)


def build_eval_step(cfg, mesh, spec: mlp.MLPSpec) -> Callable:
    """(params, x, y, mask) -> correct-prediction count (example.py:118-121).

    Masked so the eval set can be zero-padded to a multiple of the data
    axis; chunked callers sum counts exactly.
    """
    mp = mesh.shape.get(MODEL_AXIS, 1)
    seq_axis = mesh_lib.axis_if_present(mesh, mesh_lib.SEQ_AXIS)
    expert_axis = mesh_lib.axis_if_present(mesh, mesh_lib.EXPERT_AXIS)
    pipeline, pp_specs = _pipeline_info(mesh, cfg, spec)
    styles = mesh_lib.layer_styles(spec, mp)
    model_axis = mesh_lib.tp_axis(spec, mp)
    pp = pp_specs if pipeline else mesh_lib.param_pspecs(spec, mp, expert_axis)
    batch_axes, _, x_spec, y_spec = batch_layout(mesh, spec)

    def shard_eval(params, x, y, mask):
        out = forward_local(spec, params, x, styles, cfg.pallas,
                            seq_axis, expert_axis, pipeline,
                            model_axis)
        if (pipeline is not None
                and getattr(spec, "objective", "classify") == "lm"):
            # out = per-example (nll_sum, correct_sum): the example's
            # mean next-token accuracy over its S-1 positions
            correct = out[:, 1] / jnp.float32(spec.seq_len - 1)
        else:
            correct = _eval_correct(spec, out, x, y, seq_axis)
        return jax.lax.psum(jnp.sum(correct * mask), batch_axes)

    fn = jax.shard_map(
        shard_eval,
        mesh=mesh,
        in_specs=(pp, x_spec, y_spec, y_spec),
        out_specs=P(),
    )
    return jax.jit(fn)


# ---------------------------------------------------------------------------
# Async analog: local SGD over divergent per-shard replicas
# ---------------------------------------------------------------------------


def stack_state(state: TrainState, dp: int) -> TrainState:
    """Replicate params/opt into a [dp, ...] leading axis (one divergent
    copy per data shard — the analog of each async worker's view)."""
    stack = lambda a: jnp.repeat(jnp.asarray(a)[None], dp, axis=0)
    return TrainState(
        step=state.step,
        params=jax.tree.map(stack, state.params),
        opt_state=jax.tree.map(stack, state.opt_state),
    )


def _stacked_specs(state: TrainState) -> TrainState:
    """Spec tree for a stacked state: every array leaf P('data'), step P()."""
    return TrainState(
        step=P(),
        params=jax.tree.map(lambda _: P(DATA_AXIS), state.params),
        opt_state=jax.tree.map(lambda _: P(DATA_AXIS), state.opt_state),
    )


def build_local_train_step(cfg, mesh, spec: mlp.MLPSpec, optimizer, state_template):
    """Async-analog step: each data shard updates its own param copy.

    No cross-shard collective at all — the reference's unlocked
    ps-apply (example.py:101, 111) with staleness made explicit.
    Requires model_parallel == 1 (the reference has no TP to compose
    with its async path either). This is the legacy parameter-
    averaging analog; the first-class multi-site path — H inner steps
    per site, an outer Nesterov over pseudo-gradients on a 'site'
    mesh axis — is --sites (parallel/local_sgd.py), which --sync_period
    K>1 with outer SGD(lr=1, momentum=0) exactly reproduces.
    """
    if mesh.shape[MODEL_AXIS] != 1:
        raise ValueError(
            "local SGD (--sync_period K>1, the async analog) requires "
            "model_parallel=1 — as does the first-class multi-site "
            "path, --sites with a ('site','data') mesh "
            "(parallel/local_sgd.py)")
    styles = mesh_lib.layer_styles(spec, 1)
    sspecs = _stacked_specs(state_template)

    def shard_step(state: TrainState, x, y):
        local_p = jax.tree.map(lambda a: a[0], state.params)
        local_o = jax.tree.map(lambda a: a[0], state.opt_state)

        def loss_fn(p):
            return _loss_and_acc(
                spec, p, x, y, styles, cfg.naive_ce, cfg.pallas, cfg.remat,
                label_smoothing=cfg.label_smoothing,
            )

        (_total, (cost, acc)), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(local_p)
        if cfg.grad_clip > 0:
            from ..train.optim import clip_by_global_norm

            grads, _ = clip_by_global_norm(grads, cfg.grad_clip)
        new_p, new_o = optimizer.update(grads, local_o, local_p)
        cost = jax.lax.pmean(cost, DATA_AXIS)
        acc = jax.lax.pmean(acc, DATA_AXIS)
        return (
            TrainState(
                state.step + 1,
                jax.tree.map(lambda a: a[None], new_p),
                jax.tree.map(lambda a: a[None], new_o),
            ),
            cost,
            acc,
        )

    fn = jax.shard_map(
        shard_step,
        mesh=mesh,
        in_specs=(sspecs, P(DATA_AXIS), P(DATA_AXIS)),
        out_specs=(sspecs, P(), P()),
    )
    return jax.jit(fn, donate_argnums=0)


def build_param_sync(mesh, state_template) -> Callable:
    """Average divergent replicas — the --sync_period reconciliation.

    Float leaves are averaged across the data axis (the model-averaging
    step of local SGD); integer leaves (e.g. Adam's count) are identical
    across shards by construction and pass through.
    """
    sspecs = _stacked_specs(state_template)

    def avg(a):
        if jnp.issubdtype(a.dtype, jnp.integer):
            return a
        return jax.lax.pmean(a, DATA_AXIS)

    def shard_sync(state: TrainState):
        return TrainState(
            step=state.step,
            params=jax.tree.map(avg, state.params),
            opt_state=jax.tree.map(avg, state.opt_state),
        )

    fn = jax.shard_map(shard_sync, mesh=mesh, in_specs=(sspecs,), out_specs=sspecs)
    return jax.jit(fn, donate_argnums=0)


def build_unstack_params(mesh, state_template) -> Callable:
    """Consensus (mean) params from a stacked state, replicated — for
    eval and checkpointing in async mode."""
    sspecs = _stacked_specs(state_template)
    pspecs_out = jax.tree.map(lambda _: P(), state_template.params)

    def shard_mean(state: TrainState):
        return jax.tree.map(
            lambda a: jax.lax.pmean(a[0], DATA_AXIS), state.params
        )

    fn = jax.shard_map(shard_mean, mesh=mesh, in_specs=(sspecs,), out_specs=pspecs_out)
    return jax.jit(fn)


def unstack_params(mesh, state: TrainState):
    return build_unstack_params(mesh, state)(state)
