"""Pure-Python pipeline tick tables — the ONE schedule derivation.

Pipeline schedules here are lockstep SPMD programs: every stage
executes the same sequence of ticks, each tick holding at most one
forward sub-slot and one backward sub-slot of CHUNK-granular work
(a chunk = the stage's ``num_blocks/(p*v)`` consecutive blocks; at
``virtual == 1`` the chunk IS the stage's whole slice).  This module
derives, with no jax import, exactly which (stage, tick) runs which
(direction, virtual-chunk, microbatch) — and the kernel loop
(models/transformer.pipeline_value_and_grad_1f1b), the golden tests
(tests/test_pp_schedule.py) and the bubble bench (bench.py
bench_pp_memory) all consume THIS table, so schedule correctness is
checkable without a mesh and the bench's tick accounting cannot drift
from what the kernel actually emits.

Schedule family (``p`` stages, ``v`` virtual chunks per stage, ``m``
microbatches; work units per stage per direction = ``v*m``):

- **forward wavefront** (shared by gpipe and 1f1b): stage ``s`` runs
  its ``ts``-th forward unit at tick ``t = s + ts`` where round
  ``g = ts // p`` and offset ``r = ts % p`` select chunk ``g % v`` of
  microbatch ``(g // v) * p + r`` — groups of p microbatches cycle
  through the v chunks in execution order (Megatron's interleaved
  pattern; at v == 1 it degenerates to GPipe's ``m = t - s``).
- **1f1b backward wavefront**: stage ``s`` runs its ``ts``-th backward
  unit at tick ``t = (p - 1 - s) + ts + delay`` with
  ``delay = p*v - 1``, the reverse traversal: round ``g`` selects
  chunk ``v - 1 - g % v`` of microbatch ``(g // v) * p + r``.  The
  delay is exact: the LAST stage's LAST chunk backwards a microbatch
  in the very tick its forward completed, every hop dependency
  (activations ``s -> s+1``; the chunk wrap ``p-1 -> 0``; gradients
  reversed) lands exactly one tick before its consumer, and at
  ``v == 1`` the tick count collapses to the classic
  ``m + 2(p - 1)`` fused-1F1B schedule.

Tick specialization is what realizes the interleaved bubble shrink in
a lockstep realization: ticks before the first live backward
(``p*v - 1`` of them) are emitted FORWARD-ONLY and the trailing
``p*v - 1`` ticks BACKWARD-ONLY, so warmup/drain cost one sub-slot
each instead of a dead fwd+bwd pair.  In full-stage fwd+bwd work
units the 1f1b family then measures ``(v*m + p - 1)/v`` against the
ideal ``m`` — bubble fraction ``(p-1)/(v*m + p - 1)``, the ~v-fold
shrink over plain 1F1B (Narayanan et al.; GPipe's jax.grad schedule
measures the same fraction at its own v).

Stash liveness: a forward unit's input must survive until its
backward sub-slot.  ``stash_cap = min(v*m, 2*p*v - 1)`` — at v == 1
the familiar ``min(m, 2p-1)`` — is the RING the kernel's
``ts % stash_cap`` slot addressing needs: a chunk-0 unit's backward
sits ``(v-1)*p`` units later in the reverse traversal, so modulo
reuse demands the full ``2pv - 1`` even though peak simultaneous
liveness is only ``min(v*m, p*(v+1) - 1)`` (the two coincide at
v == 1).  Reuse safety — a slot's next write lands strictly after
the evicted unit's backward read — is verified structurally by
``check_table``.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Tuple


@dataclasses.dataclass(frozen=True)
class SubSlot:
    """One stage's share of a tick's forward or backward sub-slot.

    ``live`` False = this stage idles the sub-slot (the kernel runs it
    on clipped garbage and masks the writes — ``chunk``/``microbatch``
    are then safe placeholder indices, always in range).  ``unit`` is
    the unit's FORWARD work-slot index ``ts`` (backward rows carry the
    fwd ``ts`` of the unit they retire, i.e. the stash slot to read =
    ``unit % stash_cap``).  ``head`` marks the loss-bearing unit: last
    stage, last virtual chunk."""

    live: bool
    chunk: int
    microbatch: int
    unit: int
    head: bool


@dataclasses.dataclass(frozen=True)
class TickTable:
    """The full schedule: ``fwd[t]``/``bwd[t]`` are per-stage SubSlot
    rows, or None when NO stage has that direction at tick ``t`` (the
    kernel then omits the sub-slot from the compiled program — the
    warmup/drain specialization)."""

    schedule: str
    n_stages: int
    virtual: int
    microbatches: int
    ticks: int
    stash_cap: int
    fwd: List[Optional[List[SubSlot]]]
    bwd: List[Optional[List[SubSlot]]]

    @property
    def total_units(self) -> int:
        return self.virtual * self.microbatches


def _validate(p: int, v: int, m: int) -> None:
    if p < 1:
        raise ValueError(f"n_stages={p} must be >= 1")
    if v < 1:
        raise ValueError(f"virtual={v} must be >= 1")
    if m < 1:
        raise ValueError(f"microbatches={m} must be >= 1")
    if v > 1 and p < 2:
        raise ValueError(
            f"virtual={v} needs n_stages >= 2 (nothing to interleave "
            f"on one stage)")
    if v > 1 and m % p:
        raise ValueError(
            f"interleaved stages need microbatches ({m}) divisible "
            f"by n_stages ({p})")


def fwd_unit(ts: int, p: int, v: int) -> Tuple[int, int]:
    """Forward work-slot index -> (chunk, microbatch)."""
    g, r = divmod(ts, p)
    return g % v, (g // v) * p + r


def bwd_unit(ts: int, p: int, v: int) -> Tuple[int, int]:
    """Backward work-slot index -> (chunk, microbatch): the reverse
    chunk traversal of the same round structure."""
    g, r = divmod(ts, p)
    return v - 1 - g % v, (g // v) * p + r


def fwd_ts(chunk: int, microbatch: int, p: int, v: int) -> int:
    """Inverse of fwd_unit: the forward work-slot index of a unit."""
    return ((microbatch // p) * v + chunk) * p + microbatch % p


def stash_cap(p: int, v: int, m: int) -> int:
    """Input-stash buffers a stage needs under the 1f1b family:
    ``min(v*m, 2*p*v - 1)`` — M-independent once m is large enough."""
    return min(v * m, 2 * p * v - 1)


def _fwd_rows(p: int, v: int, m: int, ticks: int,
              ) -> List[Optional[List[SubSlot]]]:
    total = v * m
    last = p - 1
    rows: List[Optional[List[SubSlot]]] = []
    for t in range(ticks):
        if not any(0 <= t - s < total for s in range(p)):
            rows.append(None)
            continue
        row = []
        for s in range(p):
            ts = t - s
            if 0 <= ts < total:
                c, mb = fwd_unit(ts, p, v)
                row.append(SubSlot(True, c, mb, ts,
                                   s == last and c == v - 1))
            else:
                row.append(SubSlot(False, 0, 0, 0, False))
        rows.append(row)
    return rows


def gpipe_table(p: int, v: int, m: int) -> TickTable:
    """The GPipe/interleaved forward wavefront (apply_pipeline's tick
    loop; the backward is jax.grad's transpose of the same loop, so
    the table carries forward rows only and the cost accounting
    doubles them)."""
    _validate(p, v, m)
    ticks = v * m + p - 1
    return TickTable("gpipe", p, v, m, ticks, v * m,
                     _fwd_rows(p, v, m, ticks), [None] * ticks)


def interleaved_1f1b_table(p: int, v: int, m: int) -> TickTable:
    """The fused-tick 1f1b family: v == 1 is the classic 1F1B
    (m + 2(p-1) ticks), v > 1 the Megatron interleaved refinement
    (v*m + p(v+1) - 2 chunk-granular ticks)."""
    _validate(p, v, m)
    if p < 2:
        raise ValueError(
            f"1f1b needs n_stages >= 2 (no schedule to fuse on one "
            f"stage), got {p}")
    total = v * m
    delay = p * v - 1
    ticks = total + delay + (p - 1)
    cap = stash_cap(p, v, m)
    fwd = _fwd_rows(p, v, m, ticks)
    bwd: List[Optional[List[SubSlot]]] = []
    last = p - 1
    for t in range(ticks):
        if not any(0 <= t - (last - s) - delay < total for s in range(p)):
            bwd.append(None)
            continue
        row = []
        for s in range(p):
            ts = t - (last - s) - delay
            if 0 <= ts < total:
                c, mb = bwd_unit(ts, p, v)
                row.append(SubSlot(True, c, mb, fwd_ts(c, mb, p, v),
                                   s == last and c == v - 1))
            else:
                row.append(SubSlot(False, 0, 0, 0, False))
        bwd.append(row)
    return TickTable("1f1b", p, v, m, ticks, cap, fwd, bwd)


def schedule_table(schedule: str, p: int, v: int, m: int) -> TickTable:
    """``schedule`` in {'gpipe', '1f1b'} (each at any v >= 1; v > 1 is
    the interleaved refinement of either)."""
    if schedule == "gpipe":
        return gpipe_table(p, v, m)
    if schedule == "1f1b":
        return interleaved_1f1b_table(p, v, m)
    raise ValueError(
        f"unknown schedule {schedule!r}: expected 'gpipe' or '1f1b'")


def tick_counts(table: TickTable) -> dict:
    """Raw sub-slot structure: total ticks, fwd-only / bwd-only /
    combined tick counts, and live work units per direction."""
    fwd_only = sum(1 for f, b in zip(table.fwd, table.bwd)
                   if f is not None and b is None)
    bwd_only = sum(1 for f, b in zip(table.fwd, table.bwd)
                   if f is None and b is not None)
    both = sum(1 for f, b in zip(table.fwd, table.bwd)
               if f is not None and b is not None)
    return {"ticks": table.ticks, "fwd_only_ticks": fwd_only,
            "bwd_only_ticks": bwd_only, "combined_ticks": both,
            "units_per_direction": table.total_units}


def bubble_fraction(table: TickTable, fwd_cost: float = 1.0,
                    bwd_cost: float = 2.0) -> dict:
    """Measured vs ideal work-time for the schedule, in full-stage
    forward-cost units (one chunk sub-slot costs ``cost/v``; a gpipe
    table's jax.grad transpose replays every forward tick backward, so
    its ticks each cost ``(fwd+bwd)/v``).  ``ideal`` is the zero-bubble
    bound: m microbatches of full-stage fwd+bwd work per stage.
    ``bubble_fraction = 1 - ideal/measured`` — the fraction of the
    step the hardware idles (or, lockstep, computes masked garbage)."""
    v = table.virtual
    if table.schedule == "gpipe":
        measured = table.ticks * (fwd_cost + bwd_cost) / v
    else:
        measured = sum(
            (fwd_cost if f is not None else 0.0)
            + (bwd_cost if b is not None else 0.0)
            for f, b in zip(table.fwd, table.bwd)) / v
    ideal = table.microbatches * (fwd_cost + bwd_cost)
    return {
        "measured_ticks": round(measured, 4),
        "ideal_ticks": round(ideal, 4),
        "bubble_fraction": round(1.0 - ideal / measured, 4),
        **tick_counts(table),
    }


def check_table(table: TickTable) -> None:
    """Structural invariants — raises AssertionError on any violation.
    The golden tests call this over a (p, v, m) matrix; the kernel's
    correctness argument leans on exactly these properties:

    1. every (stage, chunk, microbatch) unit appears exactly once
       forward and (1f1b) exactly once backward;
    2. every consumer's producer ran exactly one tick earlier
       (activations ``s-1 -> s``; chunk wrap ``p-1 -> 0``; gradients
       reversed), and a unit's backward never precedes its forward;
    3. stash discipline: live stashed inputs never exceed
       ``stash_cap`` and a slot's rewrite lands strictly after the
       evicted unit's backward read.
    """
    p, v, m = table.n_stages, table.virtual, table.microbatches
    fwd_at = {}
    bwd_at = {}
    for t in range(table.ticks):
        for kind, rows, seen in (("fwd", table.fwd, fwd_at),
                                 ("bwd", table.bwd, bwd_at)):
            row = rows[t]
            if row is None:
                continue
            assert len(row) == p, f"{kind} row width at tick {t}"
            assert any(e.live for e in row), \
                f"tick {t}: emitted {kind} sub-slot with no live stage"
            for s, e in enumerate(row):
                assert 0 <= e.chunk < v and 0 <= e.microbatch < m, \
                    f"tick {t} stage {s}: {kind} indices out of range"
                if not e.live:
                    continue
                key = (s, e.chunk, e.microbatch)
                assert key not in seen, f"duplicate {kind} unit {key}"
                seen[key] = t
                assert e.head == (s == p - 1 and e.chunk == v - 1), \
                    f"tick {t} stage {s}: head flag wrong"
    units = {(s, c, mb) for s in range(p) for c in range(v)
             for mb in range(m)}
    assert set(fwd_at) == units, "forward coverage incomplete"
    if table.schedule == "1f1b":
        assert set(bwd_at) == units, "backward coverage incomplete"
    for (s, c, mb), t in fwd_at.items():
        if s > 0:
            assert fwd_at[(s - 1, c, mb)] == t - 1, \
                f"fwd hop into {(s, c, mb)} not one tick earlier"
        elif c > 0:
            assert fwd_at[(p - 1, c - 1, mb)] == t - 1, \
                f"fwd wrap into {(s, c, mb)} not one tick earlier"
    for (s, c, mb), t in bwd_at.items():
        assert t >= fwd_at[(s, c, mb)], \
            f"backward of {(s, c, mb)} precedes its forward"
        if s < p - 1:
            assert bwd_at[(s + 1, c, mb)] == t - 1, \
                f"grad hop into {(s, c, mb)} not one tick earlier"
        elif c < v - 1:
            assert bwd_at[(0, c + 1, mb)] == t - 1, \
                f"grad wrap into {(s, c, mb)} not one tick earlier"
    if table.schedule != "1f1b":
        return
    cap = table.stash_cap
    for s in range(p):
        slots: dict = {}
        live = 0
        peak = 0
        for t in range(table.ticks):
            frow, brow = table.fwd[t], table.bwd[t]
            if frow is not None and frow[s].live:
                e = frow[s]
                sl = e.unit % cap
                assert sl not in slots, \
                    f"stage {s} tick {t}: slot {sl} rewritten before " \
                    f"its backward read"
                slots[sl] = (e.chunk, e.microbatch)
                live += 1
                peak = max(peak, live)
            if brow is not None and brow[s].live:
                e = brow[s]
                sl = e.unit % cap
                assert slots.get(sl) == (e.chunk, e.microbatch), \
                    f"stage {s} tick {t}: backward reads slot {sl} " \
                    f"holding {slots.get(sl)}, wanted " \
                    f"{(e.chunk, e.microbatch)}"
                del slots[sl]
                live -= 1
        assert not slots, f"stage {s}: units never retired: {slots}"
        assert peak <= cap, f"stage {s}: {peak} live stashes > cap {cap}"
