"""Low-communication multi-site training: local SGD with an outer
optimizer (DiLoCo-style) over a ``site`` mesh axis.

Reference parity: the reference's async path let each worker apply
divergent updates between reconciliations (/root/reference/example.py:
101-111). The first TPU-native rendering of that idea here was the
``--sync_period`` parameter-averaging analog (parallel/step.py:
build_local_train_step — divergent replicas over 'data', averaged
every K steps). This module promotes it to the form that actually
*saves* something on real fleets (Stich 2019; Douillard et al. 2023,
DiLoCo): clusters joined by slow DCN links train as independent
sync-DP **sites** — H inner optimizer steps per site with NO
cross-site traffic, then ONE outer synchronization:

- **pseudo-gradient**: ``params_at_round_start − params_after_H_steps``
  per site, psum-averaged across 'site' — the only parameter-sized
  collective crossing the slow axis, cutting synced bytes ~H-fold vs
  per-step sync DP (obs/flops.py quantifies; bench_local_sgd gates);
- **outer optimizer**: SGD or Nesterov momentum applied to the
  averaged pseudo-gradient from the round-start params, with its
  state replicated (outer SGD at lr=1, momentum=0 degenerates to
  plain parameter averaging — the old ``--sync_period`` semantics,
  and at H=1 to synchronous DP itself: the equivalence tests pin
  both);
- **inner optimizer state** stays PER-SITE across rounds (the
  DiLoCo recipe): it rides the site-stacked state layout and never
  crosses the 'site' axis.

State layout mirrors the proven ``stack_state`` pattern: every
params / inner-slot leaf carries a leading ``[sites]`` axis sharded
``P('site')`` (one copy per site — same per-device memory as
replication), the outer state and step are replicated. Between
rounds all sites hold identical params (each round ends with the
outer update); the divergence exists only inside the compiled round
program, whose ``lax.scan`` runs the H inner steps the way the
grad-accum scan runs its microbatches.

This module imports the mesh layer lazily so the pure outer-optimizer
math (oracle-tested with numpy, no mesh) stays importable on
environments whose jax predates the repo's sharding API.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Tuple

import jax
import jax.numpy as jnp

from ..train.state import TrainState

# the multi-site mesh axis; mirrors parallel/mesh.py's SITE_AXIS
# registry entry (mesh.py is imported lazily here — see module
# docstring; tests pin the two constants equal)
SITE_AXIS = "site"

# valid --outer_optimizer values ("sgd" is nesterov with momentum
# pinned to 0 — one code path, two names)
OUTER_OPTIMIZERS = ("sgd", "nesterov")


@dataclasses.dataclass(frozen=True)
class OuterOptimizer:
    """The outer (cross-site) optimizer: a pure ``(init, update)``
    pair over pseudo-gradients. ``update(delta, state, params)``
    steps ``params`` (the round-start weights every site shares) by
    the averaged pseudo-gradient ``delta`` and returns the new
    replicated weights + outer state."""

    name: str
    lr: float
    momentum: float
    init: Callable[[Any], Any]
    update: Callable[[Any, Any, Any], Tuple[Any, Any]]


def make_outer_optimizer(name: str, lr: float,
                         momentum: float = 0.0) -> OuterOptimizer:
    """SGD / Nesterov-momentum over pseudo-gradients (the DiLoCo
    outer step).  PyTorch-convention Nesterov: ``m ← μ·m + Δ``,
    applied step ``Δ + μ·m`` (plain momentum applies ``m``; μ=0
    collapses both to SGD).  At lr=1, μ=0 the update is exactly
    parameter averaging: ``p − 1·Δ = mean_site(p_after)``."""
    if name not in OUTER_OPTIMIZERS:
        raise ValueError(
            f"outer_optimizer={name!r}: expected one of "
            f"{list(OUTER_OPTIMIZERS)}")
    mu = 0.0 if name == "sgd" else float(momentum)
    nesterov = name == "nesterov"

    def init(params):
        if mu == 0.0:
            return ()          # stateless: plain outer SGD
        # f32, matching the pseudo-gradient pipeline (build_local_sgd_
        # step extracts deltas in f32): param-dtype zeros would flip
        # to f32 after the first update, retracing the donated round
        # program and degrading checkpoint resume under low-precision
        # params
        return {"m": jax.tree.map(
            lambda p: jnp.zeros(jnp.shape(p), jnp.float32), params)}

    def update(delta, state, params):
        if mu == 0.0:
            step_tree = delta
            new_state = state
        else:
            m = jax.tree.map(lambda m_, d: mu * m_ + d,
                             state["m"], delta)
            step_tree = (jax.tree.map(lambda d, m_: d + mu * m_,
                                      delta, m)
                         if nesterov else m)
            new_state = {"m": m}
        new_params = jax.tree.map(
            lambda p, s: (p - lr * s).astype(p.dtype),
            params, step_tree)
        return new_params, new_state

    return OuterOptimizer(name, float(lr), mu, init, update)


def outer_optimizer_from_config(cfg) -> OuterOptimizer:
    return make_outer_optimizer(cfg.outer_optimizer, cfg.outer_lr,
                                cfg.outer_momentum)


def site_state(state: TrainState, sites: int, outer: OuterOptimizer,
               outer_quant: str = "") -> TrainState:
    """Lay a fresh TrainState out for multi-site training: params and
    inner optimizer slots replicated into a leading ``[sites]`` axis
    (one divergent copy per site — stack_state's pattern), the outer
    state replicated alongside under ``opt_state['outer']``.
    ``outer_quant='int8'`` adds the per-site error-feedback residual
    (``opt_state['ef']``, f32 param-shaped, site-stacked like the
    inner slots — each site carries ITS OWN compression error across
    rounds)."""
    stack = lambda a: jnp.repeat(jnp.asarray(a)[None], sites, axis=0)
    opt_state = {
        "inner": jax.tree.map(stack, state.opt_state),
        "outer": outer.init(state.params),
    }
    if outer_quant:
        if outer_quant != "int8":
            raise ValueError(f"outer_quant={outer_quant!r}: expected "
                             f"'' or 'int8'")
        opt_state["ef"] = jax.tree.map(
            lambda p: stack(jnp.zeros(jnp.shape(p), jnp.float32)),
            state.params)
    return TrainState(
        step=state.step,
        params=jax.tree.map(stack, state.params),
        opt_state=opt_state,
    )


def site_specs(state_template: TrainState) -> TrainState:
    """Spec tree for a site-stacked state: site-stacked leaves
    P('site') — params, inner slots and the error-feedback residual
    when present — outer state + step replicated P()."""
    from jax.sharding import PartitionSpec as P

    opt_specs = {
        "inner": jax.tree.map(lambda _: P(SITE_AXIS),
                              state_template.opt_state["inner"]),
        "outer": jax.tree.map(lambda _: P(),
                              state_template.opt_state["outer"]),
    }
    if "ef" in state_template.opt_state:
        opt_specs["ef"] = jax.tree.map(
            lambda _: P(SITE_AXIS), state_template.opt_state["ef"])
    return TrainState(
        step=P(),
        params=jax.tree.map(lambda _: P(SITE_AXIS),
                            state_template.params),
        opt_state=opt_specs,
    )


def build_local_sgd_step(cfg, mesh, spec, optimizer,
                         outer: OuterOptimizer,
                         state_template: TrainState) -> Callable:
    """One compiled multi-site ROUND: ``(state, x, y) -> (state, cost,
    acc)``.

    The batch ``x`` (sharded over ('site','data')) splits into
    ``cfg.inner_steps`` equal chunks; a ``lax.scan`` applies H inner
    steps of the ordinary synchronous step body (gradients psum'd
    over each site's 'data' axis only — parallel/step.py
    make_sync_step_body, so grad-accum/clip/mean-rescale semantics
    are shared, not reimplemented), then the pseudo-gradient
    ``params_before − params_after`` is pmean'd across 'site' (the
    ONE parameter-sized collective on the slow axis, under the
    ``outer_sync`` trace scope) and the outer optimizer steps the
    shared round-start params. Inner optimizer slots stay per-site.
    Returned cost/acc are the round's LAST inner step, site-averaged
    (the printed-cost analog of the reference's latest-step print).
    """
    from jax.sharding import PartitionSpec as P

    from . import mesh as mesh_lib
    from .step import make_sync_step_body

    if mesh_lib.SITE_AXIS not in mesh.shape:
        raise ValueError("build_local_sgd_step needs a ('site','data') "
                         "mesh (mesh_lib.build_site_mesh)")
    if mesh.shape.get(mesh_lib.MODEL_AXIS, 1) != 1:
        raise ValueError("multi-site local SGD composes with data "
                         "parallelism inside each site only "
                         "(model_parallel=1)")
    H = int(cfg.inner_steps)
    site_dp = mesh.shape[mesh_lib.DATA_AXIS]
    styles = mesh_lib.layer_styles(spec, 1)
    inner_body = make_sync_step_body(
        cfg, spec, styles, site_dp, optimizer,
        batch_axes=(mesh_lib.DATA_AXIS,), param_pspecs=None)
    sspecs = site_specs(state_template)

    quantize = getattr(cfg, "outer_quant", "") == "int8"

    def shard_round(state: TrainState, x, y):
        if x.shape[0] % H:
            raise ValueError(
                f"per-device batch {x.shape[0]} must divide into "
                f"inner_steps={H} chunks")
        params0 = jax.tree.map(lambda a: a[0], state.params)
        inner0 = jax.tree.map(lambda a: a[0],
                              state.opt_state["inner"])
        xs = x.reshape(H, x.shape[0] // H, *x.shape[1:])
        ys = y.reshape(H, y.shape[0] // H, *y.shape[1:])

        def inner(st, xy):
            xc, yc = xy
            st, cost, acc = inner_body(st, xc, yc)
            return st, (cost, acc)

        st_end, (costs, accs) = jax.lax.scan(
            inner, TrainState(state.step, params0, inner0), (xs, ys))
        # pseudo-gradient: what this site's H local steps moved the
        # shared round-start weights by (f32 so tiny per-step deltas
        # on low-precision params accumulate exactly in the average)
        delta = jax.tree.map(
            lambda p0, p1: p0.astype(jnp.float32)
            - p1.astype(jnp.float32), params0, st_end.params)
        new_opt = {"inner": None, "outer": None}
        if quantize:
            # --outer_quant=int8: each site compresses (delta + its
            # carried residual) to symmetric per-leaf int8 and keeps
            # the new residual; error feedback keeps the compression
            # unbiased over rounds (ops/quant.ef_compress_int8).
            # NUMERICS here are exactly the compressed recipe's; the
            # TRANSPORT is emulated — this SPMD program pmeans the
            # dequantized f32 values, while a real DCN deployment
            # moves the int8 wire format (reduce-scatter/all-gather
            # on the quantized domain).  The ~4x byte claim is the
            # analytic closed form of that transport (obs/flops.
            # local_sgd_outer_quant_bytes_per_round, gated), not a
            # property of this mesh — docs/quantization.md spells
            # out the measurement-honesty split
            from ..ops import quant as quant_lib

            ef0 = jax.tree.map(lambda a: a[0], state.opt_state["ef"])
            with jax.named_scope("quant"):
                pairs = jax.tree.map(quant_lib.ef_compress_int8,
                                     delta, ef0)
                delta = jax.tree.map(lambda _, p: p[0], ef0, pairs)
                new_ef = jax.tree.map(lambda _, p: p[1], ef0, pairs)
            new_opt["ef"] = jax.tree.map(lambda a: a[None], new_ef)
        with jax.named_scope("outer_sync"):
            # THE one parameter-sized collective crossing 'site'
            delta = jax.tree.map(
                lambda d: jax.lax.pmean(d, SITE_AXIS), delta)
            new_params, new_outer = outer.update(
                delta, state.opt_state["outer"], params0)
        cost = jax.lax.pmean(costs[-1], SITE_AXIS)
        acc = jax.lax.pmean(accs[-1], SITE_AXIS)
        new_opt["inner"] = jax.tree.map(lambda a: a[None],
                                        st_end.opt_state)
        new_opt["outer"] = new_outer
        return (
            TrainState(
                st_end.step,
                jax.tree.map(lambda a: a[None], new_params),
                new_opt,
            ),
            cost,
            acc,
        )

    fn = jax.shard_map(
        shard_round,
        mesh=mesh,
        in_specs=(sspecs, P((SITE_AXIS, mesh_lib.DATA_AXIS)),
                  P((SITE_AXIS, mesh_lib.DATA_AXIS))),
        out_specs=(sspecs, P(), P()),
    )
    return jax.jit(fn, donate_argnums=0)


def build_site_unstack_params(mesh, state_template: TrainState) -> Callable:
    """Replicated consensus params from a site-stacked state, for eval
    / sampling / portable checkpoints. Every round ends with the
    outer update, so the sites are identical at any host-visible
    point — the pmean is a safety net, not a reconciliation."""
    from jax.sharding import PartitionSpec as P

    sspecs = site_specs(state_template)
    pspecs_out = jax.tree.map(lambda _: P(), state_template.params)

    def shard_mean(state: TrainState):
        return jax.tree.map(
            lambda a: jax.lax.pmean(a[0], SITE_AXIS), state.params)

    fn = jax.shard_map(shard_mean, mesh=mesh, in_specs=(sspecs,),
                       out_specs=pspecs_out)
    return jax.jit(fn)
