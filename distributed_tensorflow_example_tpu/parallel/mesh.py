"""Device mesh and sharding policy.

Reference parity: the reference's placement policy is
``tf.train.replica_device_setter(worker_device=..., cluster=cluster)``
(/root/reference/example.py:55-57) — between-graph replication that pins
every ``tf.Variable`` to the parameter server and compute to the local
worker, making each training step a param-pull/grad-push over gRPC
(SURVEY.md §3.3: three network crossings per step).

TPU-native design (SURVEY.md L2): a named ``jax.sharding.Mesh`` over
the chips with axes ``('data', 'model')`` replaces the cluster spec's
job/task topology. Placement becomes declarative ``PartitionSpec``s:

- pure data parallelism (the reference's one real strategy, SURVEY.md
  §2c): params replicated ``P()``, batch split ``P('data')`` — gradient
  exchange compiles to one psum allreduce over ICI;
- optional Megatron-style tensor parallelism over the MLP hidden dim
  (``--model_parallel > 1``): odd layers column-split ``P(None,
  'model')``, even layers row-split ``P('model', None)`` with a psum
  after the row-split matmul. Absent from the reference (SURVEY.md §2c)
  but a config change here, not a rewrite — the mesh layer is built so
  absent strategies have a natural slot.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
from jax.sharding import AxisType, Mesh, NamedSharding, PartitionSpec as P

from ..models.mlp import MLPSpec

DATA_AXIS = "data"
MODEL_AXIS = "model"
SEQ_AXIS = "seq"
EXPERT_AXIS = "expert"
STAGE_AXIS = "stage"
SITE_AXIS = "site"      # multi-site local-SGD/DiLoCo: the slow (DCN)
                        # inter-cluster axis parallel/local_sgd.py's
                        # outer sync crosses once per H inner steps


def build_mesh(data_parallel: int = -1, model_parallel: int = 1, devices=None) -> Mesh:
    """Build the ('data', 'model') mesh; replaces ClusterSpec (example.py:22-27).

    ``data_parallel == -1`` takes every device not used by the model
    axis. Axis order puts 'model' innermost so TP collectives ride the
    fastest ICI links on real slices.
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if model_parallel < 1 or n % model_parallel:
        raise ValueError(f"model_parallel={model_parallel} must divide device count {n}")
    dp = n // model_parallel if data_parallel == -1 else data_parallel
    if dp * model_parallel > n:
        raise ValueError(
            f"mesh {dp}x{model_parallel} needs {dp * model_parallel} devices, have {n}"
        )
    devices = devices[: dp * model_parallel]
    import numpy as np

    dev_array = np.array(devices).reshape(dp, model_parallel)
    return Mesh(
        dev_array, (DATA_AXIS, MODEL_AXIS), axis_types=(AxisType.Auto, AxisType.Auto)
    )


def _build_2d_mesh(data_parallel: int, n: int, axis_name: str,
                   devices=None, model_parallel: int = 1,
                   inner_axis: str = None, inner: int = 1) -> Mesh:
    """('data', axis_name[, inner_axis]) mesh shared by the sequence-,
    expert- and stage-parallel layouts; validates sizes against the
    device pool. ``model_parallel > 1`` appends a third (innermost —
    fastest ICI links on real slices, where the per-block TP psums
    live) Megatron axis; a generic ``inner_axis``/``inner`` pair
    expresses the other three-axis layouts (e.g. PP x SP's inner
    'seq')."""
    if model_parallel > 1:
        inner_axis, inner = MODEL_AXIS, model_parallel
    axes = {DATA_AXIS: data_parallel, axis_name: n}
    if inner > 1:
        axes[inner_axis] = inner
    return build_nd_mesh(axes, devices)


def build_stage_mesh(data_parallel: int, pipeline_parallel: int,
                     devices=None, model_parallel: int = 1,
                     sequence_parallel: int = 1,
                     expert_parallel: int = 1) -> Mesh:
    """('data', 'stage'[, 'seq' | 'expert'][, 'model']) mesh for
    pipeline-parallel transformer training: each stage holds a
    contiguous slice of the encoder blocks; activations hop
    stage->stage+1 via ppermute on the GPipe microbatch schedule
    (models/transformer.apply_pipeline).

    Inner axes compose (r5 — the standard 3D/4D recipes): with
    ``sequence_parallel`` each microbatch's token axis shards over an
    inner 'seq' axis and attention runs the ring/Ulysses layout INSIDE
    every pipeline chunk; with ``expert_parallel`` the stacked expert
    leaves shard over an inner 'expert' axis; ``model_parallel``
    additionally Megatron-shards each stage's blocks over the
    INNERMOST 'model' axis (fastest ICI links on real slices, where
    the two per-block psums live) — DP x PP x SP x TP in one mesh.
    'seq' and 'expert' stay mutually exclusive (token-sharded sparse
    MoE capacity pools are not defined here)."""
    if sequence_parallel > 1 and expert_parallel > 1:
        raise ValueError(
            "pipeline parallelism composes with EITHER sequence_parallel "
            "OR expert_parallel (token-sharded expert capacity pools "
            "are not defined), not both")
    axes = {DATA_AXIS: data_parallel, STAGE_AXIS: pipeline_parallel}
    if sequence_parallel > 1:
        axes[SEQ_AXIS] = sequence_parallel
    if expert_parallel > 1:
        axes[EXPERT_AXIS] = expert_parallel
    if model_parallel > 1:
        axes[MODEL_AXIS] = model_parallel
    return build_nd_mesh(axes, devices)


def build_site_mesh(sites: int, data_parallel: int,
                    devices=None) -> Mesh:
    """('site', 'data') mesh for low-communication multi-site training
    (parallel/local_sgd.py): each site is a self-contained sync-DP
    group of ``data_parallel`` devices; the ONLY parameter-sized
    collective crossing 'site' is the outer pseudo-gradient psum, once
    per ``--inner_steps`` local steps. 'site' is OUTERMOST — on real
    fleets those are the DCN links between pods, the slowest hops —
    while the per-step gradient psum stays inside each site's 'data'
    axis (ICI)."""
    if sites < 1:
        raise ValueError(f"sites={sites} must be >= 1")
    return build_nd_mesh({SITE_AXIS: sites, DATA_AXIS: data_parallel},
                         devices)


def build_nd_mesh(axes: Dict[str, int], devices=None) -> Mesh:
    """Mesh over the ordered ``{axis: size}`` dict (sizes >= 1; listed
    order = device-array order, so the LAST axis gets the
    fastest-varying device stride — put the chattiest collectives
    there on real slices)."""
    devices = list(devices if devices is not None else jax.devices())
    if any(v < 1 for v in axes.values()):
        raise ValueError(f"mesh axes must be >= 1, got {axes}")
    import numpy as np

    sizes = tuple(axes.values())
    need = int(np.prod(sizes))
    if need > len(devices):
        raise ValueError(
            f"mesh {'x'.join(map(str, sizes))} over {tuple(axes)} needs "
            f"{need} devices, have {len(devices)}")
    dev_array = np.array(devices[:need]).reshape(sizes)
    return Mesh(dev_array, tuple(axes),
                axis_types=(AxisType.Auto,) * len(axes))


def pipeline_state_pspecs(spec, optimizer, stage_axis: str,
                          model_axis: str | None = None,
                          expert_axis: str | None = None):
    """Spec tree for the PP-stacked TrainState layout (PPxTP when
    ``model_axis`` is set; PPxEP when ``expert_axis`` is)."""
    from ..models import transformer
    from ..train.state import TrainState

    pp = transformer.pipeline_param_pspecs(spec, stage_axis, model_axis,
                                           expert_axis)
    return TrainState(step=P(), params=pp,
                      opt_state=optimizer.state_pspecs(pp))


def tp_axis(spec, model_parallel: int) -> str | None:
    """MODEL_AXIS when the transformer family runs Megatron TP (the
    MLP's TP goes through layer_styles instead)."""
    from ..models.transformer import TransformerSpec

    return (MODEL_AXIS if model_parallel > 1
            and isinstance(spec, TransformerSpec) else None)


def axis_if_present(mesh: Mesh, name: str) -> str | None:
    """``name`` if the mesh has that axis, else None — the step/loop
    probe for optional mesh flavors (seq/expert)."""
    return name if name in mesh.shape else None


def build_seq_mesh(data_parallel: int, sequence_parallel: int,
                   devices=None, model_parallel: int = 1) -> Mesh:
    """('data', 'seq'[, 'model']) mesh for sequence-parallel
    transformer training: the batch splits over 'data', each example's
    token axis splits over 'seq' (ring attention moves k/v blocks
    between the seq shards via ppermute — neighbor ICI traffic on real
    slices). With ``model_parallel`` the attention heads / FFN hidden
    additionally Megatron-shard over the inner 'model' axis."""
    return _build_2d_mesh(data_parallel, sequence_parallel, SEQ_AXIS,
                          devices, model_parallel)


def build_expert_mesh(data_parallel: int, expert_parallel: int,
                      devices=None, model_parallel: int = 1) -> Mesh:
    """('data', 'expert'[, 'model']) mesh for expert-parallel MoE
    training: the batch splits over 'data', each MoE layer's expert
    stack splits over 'expert' (models/transformer._moe_ffn combines
    the per-shard partial outputs with one psum). With
    ``model_parallel`` the attention side of every block additionally
    Megatron-shards over the inner 'model' axis (the expert FFNs stay
    expert-sharded — within-expert width sharding is not a thing
    here)."""
    return _build_2d_mesh(data_parallel, expert_parallel, EXPERT_AXIS,
                          devices, model_parallel)


def layer_styles(spec, model_parallel: int) -> list[str]:
    """Per-layer TP style: 'col' (column-split), 'row' (row-split + psum),
    or 'rep' (replicated). Layers alternate col/row so activations only
    need one psum per pair; the final layer stays replicated when the
    alternation would leave the logits sharded."""
    from ..models import transformer
    from ..models.transformer import TransformerSpec

    if isinstance(spec, TransformerSpec):
        # transformer TP shards heads/hidden via param_pspecs, not
        # per-layer styles; validate the degree and return a no-op
        # style list for the callers that iterate it
        transformer.check_tp(spec, model_parallel)
        return ["rep"]
    styles = []
    for i in range(1, spec.num_layers + 1):
        if model_parallel == 1:
            styles.append("rep")
        elif i % 2 == 1:
            # Column-split shards the layer's output dim; keep logits replicated.
            styles.append("rep" if i == spec.num_layers else "col")
        else:
            styles.append("row")
    # validate divisibility for the sharded dims
    sizes = spec.layer_sizes
    for i, st in enumerate(styles, start=1):
        if st == "col" and sizes[i] % model_parallel:
            raise ValueError(
                f"layer {i} output dim {sizes[i]} not divisible by model_parallel={model_parallel}"
            )
        if st == "row" and sizes[i - 1] % model_parallel:
            raise ValueError(
                f"layer {i} input dim {sizes[i - 1]} not divisible by model_parallel={model_parallel}"
            )
    return styles


def param_pspecs(spec, model_parallel: int = 1,
                 expert_axis: str | None = None) -> Dict[str, P]:
    """PartitionSpecs for the param pytree — the replica_device_setter analog."""
    from ..models import transformer

    if isinstance(spec, transformer.TransformerSpec):
        layer_styles(spec, model_parallel)  # TP validation
        return transformer.param_pspecs(
            spec, expert_axis, model_axis=tp_axis(spec, model_parallel))
    out: Dict[str, P] = {}
    for i, st in enumerate(layer_styles(spec, model_parallel), start=1):
        if st == "col":
            out[f"W{i}"] = P(None, MODEL_AXIS)
            out[f"b{i}"] = P(MODEL_AXIS)
        elif st == "row":
            out[f"W{i}"] = P(MODEL_AXIS, None)
            out[f"b{i}"] = P()
        else:
            out[f"W{i}"] = P()
            out[f"b{i}"] = P()
    return out


def state_pspecs(spec, optimizer, model_parallel: int = 1,
                 expert_axis: str | None = None):
    """Spec tree matching a TrainState pytree."""
    from ..train.state import TrainState

    pp = param_pspecs(spec, model_parallel, expert_axis)
    return TrainState(step=P(), params=pp, opt_state=optimizer.state_pspecs(pp))


def shardings_for(mesh: Mesh, pspec_tree: Any):
    return jax.tree.map(
        lambda s: NamedSharding(mesh, s),
        pspec_tree,
        is_leaf=lambda x: isinstance(x, P),
    )


def place_state(state, mesh: Mesh, pspec_tree):
    """Put the state on the mesh with its shardings (one-time, at init;
    afterwards the donated jit'd step keeps buffers in place)."""
    return jax.device_put(state, shardings_for(mesh, pspec_tree))
