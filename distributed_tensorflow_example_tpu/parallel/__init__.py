from .mesh import build_mesh, param_pspecs, state_pspecs, place_state
from .step import (
    build_train_step,
    build_eval_step,
    build_local_train_step,
    build_param_sync,
    stack_state,
    unstack_params,
)

__all__ = [
    "build_mesh",
    "param_pspecs",
    "state_pspecs",
    "place_state",
    "build_train_step",
    "build_eval_step",
    "build_local_train_step",
    "build_param_sync",
    "stack_state",
    "unstack_params",
]
