"""Parallelism package: mesh building, SPMD steps, schedules.

Re-exports resolve lazily (PEP 562): importing the package does NOT
pull in jax, so the pure-Python members (``pp_schedule`` — the
pipeline tick tables the golden tests consume) stay importable on
environments whose jax predates the repo's mesh/step API.  Touching
any re-exported name still imports its (jax-dependent) home module
with the same error surface as the old eager imports.
"""

_EXPORTS = {
    "build_mesh": "mesh",
    "param_pspecs": "mesh",
    "state_pspecs": "mesh",
    "place_state": "mesh",
    "build_train_step": "step",
    "build_eval_step": "step",
    "build_local_train_step": "step",
    "build_param_sync": "step",
    "stack_state": "step",
    "unstack_params": "step",
}

__all__ = list(_EXPORTS)


def __getattr__(name):
    if name in _EXPORTS:
        import importlib

        mod = importlib.import_module(f".{_EXPORTS[name]}", __name__)
        return getattr(mod, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
