"""ZeRO-3 / FSDP-style fully-sharded data parallelism.

Absent from the reference (SURVEY.md §2c: its ~79.5k params fit
anywhere — /root/reference/example.py:76-82), but the mesh/sharding
core leaves it a natural slot, and it is the TPU-native answer the
moment parameters outgrow one chip's HBM. Where the reference's
parameter server *centralizes* shared state on one host
(example.py:55-57), FSDP *partitions* it across all of them.

Layout: every floating-point array leaf of the train state (params AND
optimizer slots) is flattened, zero-padded to a multiple of the
data-axis size ``dp``, and stored as ``[dp, chunk]`` sharded
``P('data')`` — each device holds 1/dp of the model + optimizer memory
(the ZeRO-3 partitioning). Integer scalars (global step, Adam's count)
stay replicated.

Per step (the scaling-book recipe):
  1. all-gather the param shards over ICI -> full params (transient),
  2. local fwd/bwd on this shard's batch slice,
  3. reduce-scatter (``psum_scatter``) the gradients -> a 1/dp shard,
  4. optimizer update on the 1/dp shard only.
The gathered params live only inside the compiled step, so peak HBM is
state/dp + one transient full copy; the per-step collective bytes equal
sync DP's single allreduce (an allreduce *is* reduce-scatter +
all-gather). Elementwise optimizers (SGD/momentum/Adam) commute with
the flat partitioning, so the update each shard applies is exactly the
full update restricted to its slice — verified against the 1-device
step in tests/test_fsdp.py.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ..models import mlp
from ..train.state import TrainState
from . import mesh as mesh_lib
from .mesh import DATA_AXIS, MODEL_AXIS
from .step import _loss_and_acc


def _is_sharded_leaf(a) -> bool:
    """Float arrays are sharded; integer scalars/counters replicate.
    Inspects dtype without materializing (host leaves must not be
    device-transferred just to be classified)."""
    return np.ndim(a) >= 1 and jnp.issubdtype(jnp.result_type(a), jnp.floating)


def shard_state_host(state: TrainState, dp: int) -> TrainState:
    """Flatten + zero-pad + reshape every float leaf to [dp, chunk]."""

    def conv(a):
        if not _is_sharded_leaf(a):
            return a
        flat = np.asarray(a).reshape(-1)
        chunk = -(-flat.size // dp)
        pad = chunk * dp - flat.size
        if pad:
            flat = np.concatenate([flat, np.zeros(pad, flat.dtype)])
        return flat.reshape(dp, chunk)

    return jax.tree.map(conv, state)


def unshard_state_host(state, template: TrainState) -> TrainState:
    """Inverse of shard_state_host (host-side; used for checkpoints so
    the on-disk layout stays the portable unsharded one)."""
    state = jax.device_get(state)

    def conv(s, t):
        if not _is_sharded_leaf(t):
            return np.asarray(s)
        t = np.asarray(t)
        return np.asarray(s).reshape(-1)[: t.size].reshape(t.shape)

    return jax.tree.map(conv, state, template)


def fsdp_specs(template: TrainState) -> TrainState:
    """PartitionSpec tree for the state: P('data') on the leading
    [dp, chunk] dim of every float leaf, replicated otherwise. The
    predicate depends only on dtype/ndim-class, so the template may be
    in either layout (full or sharded) — no copy is made."""
    return jax.tree.map(
        lambda a: P(DATA_AXIS) if _is_sharded_leaf(a) else P(), template
    )


def _gather_full(leaf2d, shape):
    """Inside shard_map: [1, chunk] local shard -> full [shape] params."""
    flat = jax.lax.all_gather(leaf2d[0], DATA_AXIS, tiled=True)
    size = int(np.prod(shape))
    return flat[:size].reshape(shape)


def _scatter_grad(g, chunk: int, dp: int):
    """Inside shard_map: full grad -> summed 1/dp shard [chunk]."""
    flat = g.reshape(-1)
    pad = chunk * dp - flat.size
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    return jax.lax.psum_scatter(flat, DATA_AXIS, scatter_dimension=0, tiled=True)


def _unwrap(a):
    """[1, chunk] local block -> [chunk] flat shard (pass ints through)."""
    return a[0] if _is_sharded_leaf(a) else a


def _rewrap(a):
    return a[None] if _is_sharded_leaf(a) else a


def make_fsdp_step_body(
    cfg, spec: mlp.MLPSpec, dp: int, optimizer, full_template: TrainState
) -> Callable:
    """The per-shard FSDP step body (state, x, y) -> (state, cost, acc)
    — shared by the host-fed step (build_fsdp_train_step) and the
    device-resident scan runner (parallel/epoch.py) so both train with
    identical semantics. State leaves arrive as [1, chunk] local blocks."""
    styles = mesh_lib.layer_styles(spec, 1)
    shapes = {k: tuple(np.shape(v)) for k, v in full_template.params.items()}

    def shard_step(state: TrainState, x, y):
        params_full = {
            k: _gather_full(state.params[k], shapes[k]) for k in state.params
        }

        def loss_fn(p):
            from .mesh import DATA_AXIS

            return _loss_and_acc(
                spec, p, x, y, styles, cfg.naive_ce, cfg.pallas, cfg.remat,
                aux_axes=(DATA_AXIS,),
                label_smoothing=cfg.label_smoothing,
            )

        (_total, (cost, acc)), grads_full = jax.value_and_grad(
            loss_fn, has_aux=True)(params_full)
        grads = {
            k: _scatter_grad(grads_full[k], state.params[k].shape[1], dp)
            for k in grads_full
        }
        if cfg.grad_reduce == "mean" and dp > 1:
            grads = jax.tree.map(lambda g: g / dp, grads)
        if cfg.grad_clip > 0:
            # each shard holds a 1/dp chunk of every (reduced) grad:
            # psum the square-sums for the global norm
            from ..train.optim import clip_by_global_norm

            grads, _ = clip_by_global_norm(grads, cfg.grad_clip,
                                           (DATA_AXIS,))
        local_p = jax.tree.map(_unwrap, state.params)
        local_o = jax.tree.map(_unwrap, state.opt_state)
        new_p, new_o = optimizer.update(grads, local_o, local_p)
        cost = jax.lax.pmean(cost, DATA_AXIS)
        acc = jax.lax.pmean(acc, DATA_AXIS)
        return (
            TrainState(
                state.step + 1,
                jax.tree.map(_rewrap, new_p),
                jax.tree.map(_rewrap, new_o),
            ),
            cost,
            acc,
        )

    return shard_step


def build_fsdp_train_step(
    cfg, mesh, spec: mlp.MLPSpec, optimizer, full_template: TrainState
) -> Callable:
    """FSDP step: (sharded_state, x, y) -> (sharded_state, cost, acc).

    ``full_template`` supplies the unsharded leaf shapes (host arrays or
    ShapeDtypeStructs). State is donated; params never materialize
    outside the step.
    """
    if mesh.shape[MODEL_AXIS] != 1:
        raise ValueError("FSDP composes over the data axis; set model_parallel=1")
    dp = mesh.shape[DATA_AXIS]
    sspecs = fsdp_specs(full_template)
    shard_step = make_fsdp_step_body(cfg, spec, dp, optimizer, full_template)

    fn = jax.shard_map(
        shard_step,
        mesh=mesh,
        in_specs=(sspecs, P(DATA_AXIS), P(DATA_AXIS)),
        out_specs=(sspecs, P(), P()),
    )
    return jax.jit(fn, donate_argnums=0)


def build_gather_params(mesh, full_template: TrainState) -> Callable:
    """jit'd (sharded_state) -> full replicated param pytree — one
    all-gather per leaf; used for eval and checkpointing."""
    shapes = {k: tuple(np.shape(v)) for k, v in full_template.params.items()}
    sspecs = fsdp_specs(full_template)
    out_specs = {k: P() for k in shapes}

    def shard_gather(state: TrainState):
        return {k: _gather_full(state.params[k], shapes[k]) for k in state.params}

    # all_gather output is bitwise-identical on every shard, but the
    # varying-manual-axes checker cannot prove replication — disable it
    # for this collective-only function.
    fn = jax.shard_map(
        shard_gather, mesh=mesh, in_specs=(sspecs,), out_specs=out_specs,
        check_vma=False,
    )
    return jax.jit(fn)
